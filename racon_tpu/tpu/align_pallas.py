"""Single-dispatch batched pairwise alignment: Pallas TPU kernel.

Replaces the lax.scan wavefront kernels (racon_tpu/tpu/aligner.py) on
real TPU backends.  The scan kernels pay per-step XLA overhead over
``lq+lt`` anti-diagonals and one host round-trip per (bucket, chunk);
on the tunneled-TPU deployment target those transfers cost ~100 ms of
latency each.  This kernel aligns EVERY queued pair in one
``pallas_call`` and emits a compact 2-bit move tape.

Design notes:

* **8 pairs per grid program, stacked on the sublane axis** (a full
  8-sublane vreg): the banded row DP's critical path is the in-row
  prefix-min chain (log2(wb) serial vector steps, latency-bound
  regardless of width), so eight independent pairs share ONE chain
  per row group -- measured 0.57-0.96 us/row vs ~2 us single-pair.
  Callers sort pairs by length so group partners finish together;
* the row loop bound is the group's longest REAL query, so mixing
  short and long pairs in one shape bucket costs padding memory, not
  padded compute -- no per-length bucket dispatch loop (the
  cudaaligner analog queues per-batch, src/cuda/cudaaligner.cpp:52-86);
* the band follows a per-pair CENTER TABLE: piecewise-linear knots
  (one per ``_CTR_BLK`` rows, scalar-prefetched) give the expected
  target column at each query row, quantized to 128 columns so the
  per-row target slice and the previous-row realignment are
  lane-aligned (TPU dynamic lane offsets must be 128-multiples).  The
  default knots reproduce the proportional diagonal ``i*tl/ql``, for
  which an alignment of cost c deviates at most ``(c + |tl-ql|)/2``
  columns, so a tape satisfying ``cost + |tl-ql| <= wb - 512`` is
  exact (Ukkonen) and callers escalate the rest to a wider band.
  Retry pairs instead follow MEASURED knots from a strided k-mer
  pre-pass (``estimate_center_knots``), so a band of the same width
  can hold alignments with large indel drift; those results are
  accepted on the empirical criterion that the recovered path keeps
  >= one 128-column quantum of margin to both band edges
  (``path_center_margin``), not the Ukkonen certificate;
* no direction tape is materialised in HBM: the forward pass keeps
  one score-row checkpoint every ``_CKPT`` rows in VMEM, and the
  traceback re-derives each 128-row block's directions from its
  checkpoint on demand, walking all stacked pairs' segments through a
  block before moving down (one recompute per block, not per pair);
* the kernel emits 2-bit moves (diag/up/left) packed 16-per-int32;
  the host reconstructs =/X from the sequences vectorised, then RLEs
  to a CIGAR (the reference also finishes CIGARs on the host,
  src/cuda/cudaaligner.cpp:89-103).
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from racon_tpu.obs import decision as _decision
from racon_tpu.obs import devutil as obs_devutil
from racon_tpu.obs import trace as obs_trace

# the sanctioned clock (racon_tpu/obs): watcher spans feed only the
# trace and the device_s reporting counters, never control flow
_mono = obs_trace.now

_BIG = 1 << 20
_CKPT = 128                  # rows between score checkpoints
                             # (halved for wide bands: VMEM dirs block)


def _ckrows(wb: int) -> int:
    """Rows per checkpoint block, shrunk for wide bands so the dirs
    scratch (ckrows x 8 x wb i32) stays inside the ~16 MB VMEM scope."""
    if wb >= 8192:
        return 32
    return 64 if wb >= 4096 else _CKPT
_N_SHIFT = 3                 # band start advances <= 2 quanta per row
_S = 8                       # pairs stacked per grid program
_MV_DIAG, _MV_UP, _MV_LEFT = 0, 1, 2

# center-table knot spacing (rows); 16384-cap rows -> <= 18 knots/pair
_CTR_BLK = 1024
_CTR_LOG = 10


def _n_ctr(lq: int) -> int:
    """Knots per pair for a query bucket (row i reads knots i>>10 and
    (i>>10)+1, so one knot past the last full block plus one)."""
    return lq // _CTR_BLK + 2


# per-row center advance cap: the kernel realigns the previous row by
# at most _N_SHIFT-1 = 2 quanta (256 columns), so a knot segment may
# advance at most 255 columns per row
_CTR_SLOPE_MAX = 255


def proportional_knots(ql: int, tl: int, lq: int) -> np.ndarray:
    """Default center table: the proportional diagonal ``i*tl/ql``
    sampled at the knot rows.  Knot values PAST the query length
    keep the slope (they may exceed tl -- rows stop at ql, and the
    kernel clips band starts): clipping them to tl would flatten the
    interpolated center across the final block and mis-place the
    band at the end of every pair shorter than its knot grid."""
    ks = np.arange(_n_ctr(lq), dtype=np.int64) * _CTR_BLK
    vals = (ks * tl) // max(ql, 1)
    return np.minimum(vals,
                      ks * _CTR_SLOPE_MAX + tl).astype(np.int32)


def smooth_knots(knots: np.ndarray, tl: int) -> np.ndarray:
    """Clamp a measured center path into kernel-legal knots: monotone
    non-decreasing with each segment advancing at most
    ``_CTR_SLOPE_MAX`` columns per row (the kernel's 2-quanta
    realignment window), values bounded but NOT clipped to tl (see
    proportional_knots)."""
    k = np.maximum.accumulate(np.clip(
        knots, 0, tl + _CTR_SLOPE_MAX * _CTR_BLK).astype(np.int64))
    d = np.clip(np.diff(k), 0, _CTR_SLOPE_MAX * _CTR_BLK)
    return np.concatenate(
        ([k[0]], k[0] + np.cumsum(d))).astype(np.int32)


def estimate_center_knots(query: bytes, target: bytes,
                          lq: int) -> np.ndarray:
    """Cheap strided pre-pass estimating the pair's REAL diagonal
    path: at every knot row an exact query 16-mer is looked up in a
    rolling-hash index of the target and the hit nearest the previous
    knot's extrapolation wins; missing knots interpolate.  The result
    (smoothed monotone) replaces the proportional diagonal for retry
    pairs whose indel drift pushed the true path out of a
    proportionally-centered band — measured centers let the SAME band
    width hold the alignment instead of escalating rungs."""
    k = 16
    ql, tl = len(query), len(target)
    prop = proportional_knots(ql, tl, lq)
    if ql < 4 * k or tl < 4 * k:
        return prop
    qa = np.frombuffer(query, np.uint8).astype(np.uint64)
    ta = np.frombuffer(target, np.uint8).astype(np.uint64)
    mul = np.uint64(1099511628211)      # FNV-ish rolling base

    def hashes(a):
        h = np.zeros(len(a) - k + 1, np.uint64)
        for p in range(k):
            h = h * mul + a[p:p + len(h)]
        return h
    hq, ht = hashes(qa), hashes(ta)
    n_ctr = _n_ctr(lq)
    knots = np.full(n_ctr, -1, np.int64)
    knots[0] = 0
    slope = tl / max(ql, 1)
    prev_row, prev_col = 0, 0
    for ki in range(1, n_ctr):
        row = ki * _CTR_BLK
        if row >= ql - k:
            break
        cand = np.flatnonzero(ht == hq[row])
        if cand.size:
            expect = prev_col + (row - prev_row) * slope
            j = int(cand[np.argmin(np.abs(cand - expect))])
            knots[ki] = j
            prev_row, prev_col = row, j
    # tail + gaps: extend/interpolate along the proportional slope
    last = -1
    for ki in range(n_ctr):
        if knots[ki] >= 0:
            last = ki
    for ki in range(n_ctr):
        if knots[ki] < 0:
            knots[ki] = (knots[last] + (ki - last) * _CTR_BLK * slope
                         if last >= 0 and ki > last else prop[ki])
    return smooth_knots(knots, tl)


def path_center_margin(moves_row: np.ndarray, length: int,
                       knots: np.ndarray, wb: int) -> int:
    """Smallest distance (columns) from the decoded path to either
    edge of the knot-centered band — the empirical acceptance
    criterion for re-centered rungs (a path that never comes within a
    quantum of the band edge would not change under widening)."""
    mv = moves_row[:length][::-1]
    di = np.cumsum((mv != _MV_LEFT).astype(np.int64))      # i after op
    dj = np.cumsum((mv != _MV_UP).astype(np.int64))        # j after op
    kk = di >> _CTR_LOG
    kn = knots.astype(np.int64)
    c0 = kn[np.minimum(kk, len(kn) - 1)]
    c1 = kn[np.minimum(kk + 1, len(kn) - 1)]
    ctr = c0 + (((c1 - c0) * (di & (_CTR_BLK - 1))) >> _CTR_LOG)
    dev = int(np.max(np.abs(dj - ctr))) if len(mv) else 0
    return wb // 2 - dev


def available() -> bool:
    """Default on real TPU backends (RACON_TPU_PALLAS_ALIGN=0 falls
    back to the scan-ladder kernels): with 8 pairs sharing each row
    group the kernel measures 0.57-0.96 us/row including the
    traceback pass, in ONE dispatch per band rung."""
    if os.environ.get("RACON_TPU_NO_PALLAS"):
        return False
    if os.environ.get("RACON_TPU_PALLAS_ALIGN", "1") == "0":
        return False
    if os.environ.get("RACON_TPU_PALLAS_INTERPRET") == "1":
        return True
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _kernel(ql_ref, tl_ref, ctr_ref, q_ref, t_ref, tape_ref, dist_ref,
            ckpt_hbm, ckstage, dirs, taperow, dsem, regs_s, *,
            lq: int, lt: int, wb: int, ckrows: int):
    g0 = pl.program_id(0) * _S
    nck8 = (lq // ckrows + 1) * 8
    ck0 = pl.program_id(0) * nck8      # this program's HBM region
    q = 128
    n_ctr = _n_ctr(lq)
    tape_w = (lq + lt) // 16 + 1
    tape_rows = (tape_w + 127) // 128
    big = jnp.int32(_BIG)
    cols = lax.broadcasted_iota(jnp.int32, (1, wb), 1)
    cols_s = lax.broadcasted_iota(jnp.int32, (_S, wb), 1)
    rows_s = lax.broadcasted_iota(jnp.int32, (_S, wb), 0)
    iota_c = lax.broadcasted_iota(jnp.int32, (1, 128), 1)

    qls = [ql_ref[g0 + s] for s in range(_S)]
    tls = [tl_ref[g0 + s] for s in range(_S)]
    smaxs = [(jnp.maximum(tls[s] + 1 - wb, 0) + q - 1) // q
             for s in range(_S)]

    def sqq(s, i):
        """Quantized band start for pair s, row i: centered on the
        pair's knot-interpolated center table (symmetric margins
        >= wb/2 - 128).  The knots are host-built: the proportional
        diagonal by default, a measured diagonal path for re-centered
        retry rungs (estimate_center_knots).  Host smoothing bounds
        the knot slope so consecutive-row starts move <= 1 quantum,
        inside the _N_SHIFT realignment window.  Cost per call: two
        SMEM loads + one multiply/shift, on par with the fixed-point
        slope multiply this replaces."""
        k = i >> _CTR_LOG
        c0 = ctr_ref[(g0 + s) * n_ctr + k]
        c1 = ctr_ref[(g0 + s) * n_ctr + k + 1]
        ctr_i = c0 + (((c1 - c0) * (i - (k << _CTR_LOG))) >> _CTR_LOG)
        return jnp.clip((ctr_i - (wb // 2)) >> 7, 0, smaxs[s])

    def stackv(vals, dtype=jnp.int32):
        """[_S] scalars -> [_S, 1] column vector."""
        out = jnp.full((_S, 1), 0, dtype)
        ri = lax.broadcasted_iota(jnp.int32, (_S, 1), 0)
        for s, v in enumerate(vals):
            out = jnp.where(ri == s, jnp.asarray(v, dtype), out)
        return out

    # tl as a broadcastable column; per-pair big mask rows beyond tl
    tl_col = stackv(tls)

    def t_band(starts):
        """Stacked [S, wb] target chars at each pair's band start."""
        rows = [t_ref[s, :, pl.ds(pl.multiple_of(starts[s], q), wb)]
                for s in range(_S)]
        return jnp.concatenate(rows, axis=0)

    def row_dp(i, pvp, qchars, i0):
        """One stacked DP row group.  pvp: [S, wb + shift headroom] of
        D[i-1][s_{i-1} + c]; qchars: [S, _CKPT] of this block's query
        chars.  Returns (row_u [S, wb], dirs_row [S, wb])."""
        sq_i = [sqq(s, i) for s in range(_S)]
        s_i = stackv([x * q for x in sq_i])
        dq = stackv([sq_i[s] - sqq(s, i - 1) for s in range(_S)])
        pu = pvp[:, 0:wb]
        for mm in range(1, _N_SHIFT):
            pu = jnp.where(dq == mm, pvp[:, mm * q: mm * q + wb], pu)
        qc = jnp.sum(jnp.where(iota_c == (i - 1 - i0), qchars, 0),
                     axis=1, keepdims=True)           # [S, 1]
        tb = t_band([x * q for x in sq_i])
        j_u = s_i + cols_s
        sub_u = jnp.where(tb == qc, 0, 1)
        du = pu + sub_u
        vu = pu + 1
        t_u = jnp.minimum(jnp.pad(du, ((0, 0), (1, 0)),
                                  constant_values=big)[:, :wb], vu)
        t_u2 = jnp.where(j_u == 0, i, t_u)
        t_u2 = jnp.where(j_u > tl_col, big, t_u2)
        x = t_u2 - j_u
        sh = 1
        while sh < wb:
            x = jnp.minimum(
                x, jnp.pad(x, ((0, 0), (sh, 0)),
                           constant_values=big)[:, :wb])
            sh <<= 1
        row = jnp.minimum(x + j_u, big)
        dshift = jnp.pad(du, ((0, 0), (1, 0)),
                         constant_values=big)[:, :wb]
        dr = jnp.where(
            row == dshift, _MV_DIAG,
            jnp.where(row == vu, _MV_UP, _MV_LEFT)).astype(jnp.int32)
        dr = jnp.where(j_u == 0, _MV_UP, dr)
        return row, dr

    def pad_row(row):
        return jnp.pad(row, ((0, 0), (0, _N_SHIFT * q)),
                       constant_values=big)

    # ---- pass 1: forward scores, checkpoints every _CKPT rows -------
    def ck_save(slot, rows):
        # tiled HBM slices must be 8-row aligned AND 8 rows long --
        # exactly one _S=8 row group per checkpoint slot
        ckstage[0:_S, :] = rows
        cp = pltpu.make_async_copy(
            ckstage,
            ckpt_hbm.at[pl.ds(pl.multiple_of(ck0 + slot * 8, 8),
                              8), :],
            dsem)
        cp.start()
        cp.wait()

    def ck_load(slot):
        cp = pltpu.make_async_copy(
            ckpt_hbm.at[pl.ds(pl.multiple_of(ck0 + slot * 8, 8),
                              8), :],
            ckstage, dsem)
        cp.start()
        cp.wait()
        return ckstage[0:_S, :]

    init = jnp.where(cols_s > tl_col, big, cols_s)   # D[0][j] = j
    ck_save(0, init)
    max_ql = qls[0]
    for s in range(1, _S):
        max_ql = jnp.maximum(max_ql, qls[s])

    def qchars_blk(i0):
        # char window anchored to 128 lanes (ckrows may be 64)
        i0b = (i0 // 128) * 128
        rows = [q_ref[s, :, pl.ds(pl.multiple_of(i0b, 128), 128)]
                for s in range(_S)]
        return jnp.concatenate(rows, axis=0), i0b     # [S, 128]

    ql_col1 = stackv(qls)

    def blk_fwd(bk, pv):
        i0 = bk * ckrows
        qchars, i0b = qchars_blk(i0)

        def row_step(i, pv):
            row, _ = row_dp(i, pv, qchars, i0b)
            # a pair whose query ended keeps its final row frozen so
            # the end score survives to the loop exit
            row = jnp.where(ql_col1 < i, pv[:, 0:wb], row)
            return pad_row(row)

        top = jnp.minimum((bk + 1) * ckrows, max_ql)
        pv = lax.fori_loop(i0 + 1, top + 1, row_step, pv)

        @pl.when(top == (bk + 1) * ckrows)
        def _():
            ck_save(bk + 1, pv[:, 0:wb])
        return pv

    nblk = (max_ql + ckrows - 1) // ckrows
    pv = lax.fori_loop(0, nblk, blk_fwd, pad_row(init))

    # NOTE on the freeze: once i passes ql_s, pair s's row stops
    # updating, so its band start must also stop moving -- sqq(s, i)
    # with i > ql_s would drift.  The freeze keeps the row contents of
    # row ql_s, whose band start is sqq(s, ql_s); the end-score read
    # below uses exactly that start, so they agree.
    for s in range(_S):
        c_end = tls[s] - sqq(s, qls[s]) * q
        dval = jnp.sum(jnp.where((rows_s == s) &
                                 (cols_s == jnp.clip(c_end, 0,
                                                     wb - 1)),
                                 pv[:, 0:wb], 0))
        dval = jnp.where((c_end < 0) | (c_end >= wb), big, dval)
        dist_ref[s, 0:1, 0:1] = jnp.full((1, 1), dval, jnp.int32)

    # ---- pass 2: checkpointed traceback, all pairs per block --------
    for s in range(_S):
        tape_ref[s, :, :] = jnp.zeros((tape_rows, 128), jnp.int32)
    # regs per pair s at base s*8: 0 word, 1 word count, 2 bit count,
    # 3 i, 4 j
    for s in range(_S):
        regs_s[s * 8 + 0] = jnp.int32(0)
        regs_s[s * 8 + 1] = jnp.int32(0)
        regs_s[s * 8 + 2] = jnp.int32(0)
        regs_s[s * 8 + 3] = qls[s]
        regs_s[s * 8 + 4] = tls[s]

    def put_word(s, w):
        """Append one finished 16-move word: accumulate into the
        pair's 128-lane row register and flush whole rows -- the tape
        output packs 128 words per sublane row, so nothing is stored
        through the ~800ns dynamic-scalar path and the block is not
        lane-padded 128x in VMEM."""
        wcnt = regs_s[s * 8 + 1]
        lane = wcnt % 128
        taperow[s:s + 1, :] = jnp.where(iota_c == lane, w,
                                        taperow[s:s + 1, :])

        @pl.when(lane == 127)
        def _():
            tape_ref[s, pl.ds(wcnt // 128, 1), :] = taperow[s:s + 1, :]
        regs_s[s * 8 + 1] = wcnt + 1

    def emit(s, mv):
        w = regs_s[s * 8] | (mv << (regs_s[s * 8 + 2] * 2))
        nb = regs_s[s * 8 + 2] + 1
        full = nb == 16

        @pl.when(full)
        def _():
            put_word(s, w)
            regs_s[s * 8] = jnp.int32(0)
            regs_s[s * 8 + 2] = jnp.int32(0)

        @pl.when(jnp.logical_not(full))
        def _():
            regs_s[s * 8] = w
            regs_s[s * 8 + 2] = nb

    def blk_bwd(bkr, _):
        bk = nblk - 1 - bkr
        i0 = bk * ckrows
        any_here = regs_s[3] > i0
        for s in range(1, _S):
            any_here = any_here | (regs_s[s * 8 + 3] > i0)

        @pl.when(any_here)
        def _():
            # rebuild this block's direction rows from its checkpoint
            qchars, i0b = qchars_blk(i0)

            def row_step(i, pv):
                row, dr = row_dp(i, pv, qchars, i0b)
                dirs[pl.ds(pl.multiple_of((i - 1 - i0) * 8, 8),
                           _S), :] = dr
                row = jnp.where(ql_col1 < i, pv[:, 0:wb], row)
                return pad_row(row)

            top = jnp.minimum(i0 + ckrows, max_ql)
            pv0 = pad_row(ck_load(bk))
            lax.fori_loop(i0 + 1, top + 1, row_step, pv0)

            for s in range(_S):
                def w_cond(c):
                    i, j = c
                    return (i > i0) | ((i0 == 0) &
                                       ((i > 0) | (j > 0)))

                def w_body(c, s=s):
                    i, j = c

                    @pl.when(i == 0)
                    def _():
                        emit(s, jnp.int32(_MV_LEFT))

                    @pl.when(i > 0)
                    def _():
                        s_i = sqq(s, i) * q
                        cc = jnp.clip(j - s_i, 0, wb - 1)
                        drow = dirs[pl.ds((i - 1 - i0) * 8 + s,
                                          1), :]
                        mv = jnp.sum(jnp.where(cols == cc, drow, 0))
                        mv = jnp.where(j <= 0, _MV_UP, mv)
                        emit(s, mv)
                        regs_s[s * 8 + 3] = jnp.where(mv != _MV_LEFT,
                                                      i - 1, i)
                        regs_s[s * 8 + 4] = jnp.where(mv != _MV_UP,
                                                      j - 1, j)
                    ni = jnp.where(i == 0, i, regs_s[s * 8 + 3])
                    nj = jnp.where(i == 0, j - 1, regs_s[s * 8 + 4])
                    regs_s[s * 8 + 3] = ni
                    regs_s[s * 8 + 4] = nj
                    return ni, nj

                ii, jj = lax.while_loop(
                    w_cond, w_body,
                    (regs_s[s * 8 + 3], regs_s[s * 8 + 4]))
                regs_s[s * 8 + 3] = ii
                regs_s[s * 8 + 4] = jj
        return 0

    lax.fori_loop(0, nblk, blk_bwd, 0)
    for s in range(_S):
        @pl.when(regs_s[s * 8 + 2] > 0)
        def _(s=s):
            put_word(s, regs_s[s * 8])

        # flush the partial final row (garbage tail lanes are beyond
        # the move count the host slices by)
        @pl.when(regs_s[s * 8 + 1] % 128 > 0)
        def _(s=s):
            tape_ref[s, pl.ds(regs_s[s * 8 + 1] // 128, 1), :] = \
                taperow[s:s + 1, :]
        dist_ref[s, 1:2, 0:1] = jnp.full(
            (1, 1),
            regs_s[s * 8 + 1] * 16 - jnp.where(
                regs_s[s * 8 + 2] > 0, 16 - regs_s[s * 8 + 2], 0),
            jnp.int32)


@functools.partial(jax.jit, static_argnums=(5, 6, 7, 8))
def _align(q, t, ql, tl, ctr, lq: int, lt: int, wb: int,
           interpret: bool = False):
    b = q.shape[0]
    tape_w = (lq + lt) // 16 + 1
    tape_rows = (tape_w + 127) // 128
    q_i = q.astype(jnp.int32)[:, None, :]
    t_i = jnp.pad(t.astype(jnp.int32), ((0, 0), (0, wb + 128)),
                  constant_values=-1)[:, None, :]
    ckrows = _ckrows(wb)
    kern = functools.partial(_kernel, lq=lq, lt=lt, wb=wb,
                             ckrows=ckrows)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b // _S,),
        in_specs=[
            pl.BlockSpec((_S, 1, lq), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_S, 1, lt + wb + 128),
                         lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((_S, tape_rows, 128), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_S, 8, 1), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),      # ckpt HBM buffer
        ),
        scratch_shapes=[
            pltpu.VMEM((8, wb), jnp.int32),                    # stage
            pltpu.VMEM((ckrows * 8, wb), jnp.int32),           # dirs
            pltpu.VMEM((8, 128), jnp.int32),                   # taperow
            pltpu.SemaphoreType.DMA(()),
            pltpu.SMEM((8 * _S,), jnp.int32),                  # regs
        ],
    )
    nck8 = (lq // ckrows + 1) * 8
    tape, meta, _ = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct((b, tape_rows, 128),
                                        jnp.int32),
                   jax.ShapeDtypeStruct((b, 8, 1), jnp.int32),
                   jax.ShapeDtypeStruct((b // _S * nck8, wb),
                                        jnp.int32)),
        interpret=interpret,
    )(ql, tl, ctr.reshape(-1), q_i, t_i)
    return tape, meta


def per_pair_bytes(bd: int, wb: int) -> int:
    """Device bytes one queued pair costs at band ``wb``: the
    checkpoint HBM region plus q/t/tape buffers (shared by the
    dispatch chunking and the shape-prediction prewarm)."""
    return (bd // _ckrows(wb) + 1) * wb * 4 + 6 * bd


def pipeline_depth() -> int:
    """In-flight chunks per device dispatch loop (RACON_TPU_PIPE_DEPTH,
    clamped to [1, 4]).  Depth 2 is the classic double buffer: chunk
    k+1 is packed on the host and enqueued while k executes and k-1's
    tapes decode; deeper keeps more chunks in flight at proportionally
    smaller per-chunk memory budgets (callers divide their HBM chunk
    cap by this depth)."""
    try:
        d = int(os.environ.get("RACON_TPU_PIPE_DEPTH", "2"))
    except ValueError:
        d = 2
    return max(1, min(d, 4))


def run_pipelined(chunks, dispatch, consume, depth: int = None) -> None:
    """Drive ``dispatch(chunk) -> collect`` over ``chunks`` keeping up
    to ``depth`` dispatches in flight, consuming strictly in FIFO
    order (``consume(chunk, collect)``).  JAX dispatch is async, so
    the host packs and enqueues chunk k+1 while the device still
    executes chunk k -- the shared loop body of the WFA rung, the
    banded rung and the POA megabatch dispatchers."""
    if depth is None:
        depth = pipeline_depth()
    from collections import deque

    inflight = deque()
    for sub in chunks:
        inflight.append((sub, dispatch(sub)))
        if len(inflight) >= max(1, depth):
            sub0, coll = inflight.popleft()
            consume(sub0, coll)
    while inflight:
        sub0, coll = inflight.popleft()
        consume(sub0, coll)


def pad_pairs(n: int, n_dev: int = 1) -> int:
    """Batch padding rule: power of two (floor 32), a multiple of the
    stacking factor and of the mesh size.  The floor keeps the
    compiled-variant set small enough for the prebuild manifest to
    cover it: a final-rung straggler batch of 8 pairs would otherwise
    mint its own kernel variant whose first-contact compile costs far
    more than 24 empty lanes ever will (empty pairs cost ~nothing --
    the row loops follow real lengths)."""
    from racon_tpu.utils.tuning import pow2_at_least

    n_pad = pow2_at_least(max(n, 32), _S)
    return n_pad + (-n_pad) % (_S * n_dev)


def prewarm(n: int, lq: int, lt: int, wb: int, mesh=None) -> None:
    """Populate the jit dispatch cache for one (batch, dims, band)
    variant with an all-empty batch through THE SAME entry production
    dispatch uses (sharded when the mesh has more than one device);
    run from a background thread so later band rungs are already
    traced+compiled when the first rung finishes."""
    from racon_tpu.parallel.mesh_utils import interpret_mode

    n_dev = len(mesh.devices) if mesh is not None else 1
    if n_dev > 1:
        interp = interpret_mode()
        q = jnp.zeros((n, lq), jnp.uint8)
        t = jnp.zeros((n, lt), jnp.uint8)
        zl = jnp.zeros((n,), jnp.int32)
        zc = jnp.zeros((n, _n_ctr(lq)), jnp.int32)
        out = _align_sharded(q, t, zl, zl, zc, mesh=mesh, lq=lq,
                             lt=lt, wb=wb, interpret=interp)
        jax.block_until_ready(out)
    else:
        # route through align_batch so the AOT-shelf callable the
        # production dispatch will use is the one warmed here
        align_batch([b""] * n, [b""] * n, lq, lt, wb, mesh=None)


@functools.partial(jax.jit,
                   static_argnames=("mesh", "lq", "lt", "wb",
                                    "interpret"))
def _align_sharded(q, t, ql, tl, ctr, *, mesh, lq: int, lt: int,
                   wb: int, interpret: bool):
    """The stacked kernel sharded over the mesh batch axis (one grid
    of programs per device, no collectives — the analog of the
    reference's per-device aligner queues, cudapolisher.cpp:170-188)."""
    from racon_tpu.parallel.mesh_utils import shard_batch_map

    def shard_fn(q, t, ql, tl, ctr):
        return _align(q, t, ql, tl, ctr, lq, lt, wb, interpret)

    return shard_batch_map(shard_fn, mesh, 5, 2)(q, t, ql, tl, ctr)


def align_dispatch(queries, targets, lq: int, lt: int, wb: int,
                   mesh=None, centers=None):
    """Enqueue one aligner batch and return a zero-arg collect
    closure producing (moves, lens, dists) -- the async half of
    ``align_batch``.  A caller can dispatch chunk k+1 (and run host
    decode for chunk k) while chunk k computes, hiding the tunnel's
    per-transfer latency behind device time (the POA megabatch
    pipeline's analog, racon_tpu/tpu/polisher.py).

    ``centers`` optionally carries one knot array per pair
    (estimate_center_knots) for band re-centering; None falls back to
    the proportional diagonal for every pair."""
    from racon_tpu.tpu.aligner import encode_batch, _QPAD, _TPAD

    import threading

    n_real = len(queries)
    n_dev = len(mesh.devices) if mesh is not None else 1
    # pad the pair count to a power of two so grid sizes (and thus
    # compiled variants) stay bucketed; empty pairs cost ~nothing
    n_pad = pad_pairs(n_real, n_dev)
    queries = list(queries) + [b""] * (n_pad - n_real)
    targets = list(targets) + [b""] * (n_pad - n_real)
    q = encode_batch(queries, lq, _QPAD)
    t = encode_batch(targets, lt, _TPAD)
    ql = np.array([len(s) for s in queries], np.int32)
    tl = np.array([len(s) for s in targets], np.int32)
    ctr = np.zeros((n_pad, _n_ctr(lq)), np.int32)
    for i in range(n_pad):
        if centers is not None and i < n_real \
                and centers[i] is not None:
            ctr[i] = centers[i]
        else:
            ctr[i] = proportional_knots(int(ql[i]), int(tl[i]), lq)
    from racon_tpu.parallel.mesh_utils import interpret_mode

    interp = interpret_mode()
    t_disp = _mono()
    if n_dev > 1:
        tape, meta = _align_sharded(q, t, ql, tl, ctr, mesh=mesh,
                                    lq=lq, lt=lt, wb=wb,
                                    interpret=interp)
    else:
        from racon_tpu.utils import aot_shelf

        def build(qq, tt, qql, ttl, cc):
            return _align(qq, tt, qql, ttl, cc, lq, lt, wb, interp)

        tape, meta = aot_shelf.call(
            ("align", n_pad, lq, lt, wb, interp), __file__, build,
            (q, t, ql, tl, ctr))
    tape.copy_to_host_async()
    meta.copy_to_host_async()

    # host-independent per-dispatch device time: the watcher blocks
    # on the outputs from dispatch-enqueue on, so host work between
    # dispatch and collect (decoding the previous chunk under the
    # two-deep pipeline) never inflates the span -- the bench's
    # align_device_s (VERDICT r5 #8)
    span = {}

    def _watch():
        try:
            jax.block_until_ready((tape, meta))
            t_end = _mono()
            span["s"] = t_end - t_disp
            # device-lane trace span: dispatch-enqueue -> outputs
            # ready, free of host work between dispatch and collect
            obs_trace.TRACER.add_span(
                f"device.align_band{wb}", t_disp, t_end, cat="device",
                lane="device", args={"n": n_real})
            obs_devutil.DEVICE_UTIL.record("align_band", t_disp, t_end)
            # decision-plane exemplar (r16): the pure device interval
            # for this dispatch, free of host packing/decode time
            _decision.DECISIONS.record(
                "align_device", engine="band", rung=int(wb),
                n=int(n_real), device_s=round(t_end - t_disp, 6))
        except Exception:
            pass  # dispatch errors surface at collect()

    watcher = threading.Thread(target=_watch, daemon=True,
                               name="racon-align-devtime")
    watcher.start()

    def collect():
        tp = np.asarray(tape)[:n_real].reshape(n_real, -1) \
            .astype(np.uint32)
        mt = np.asarray(meta)[:n_real, :, 0]
        watcher.join()
        n = tp.shape[1] * 16
        moves = np.zeros((tp.shape[0], n), np.uint8)
        for sh in range(16):
            moves[:, sh::16] = (tp >> (2 * sh)) & 3
        return moves, mt[:, 1], mt[:, 0]

    collect.device_s = lambda: span.get("s", 0.0)
    return collect


def align_batch(queries, targets, lq: int, lt: int, wb: int,
                mesh=None, centers=None):
    """Align padded pair batches; returns (moves, lens, dists).

    moves: [B, n] uint8 of 2-bit codes in traceback (reversed) order,
    lens: [B] number of valid moves, dists: [B] band edit distance
    (_BIG when the endpoint fell outside the band)."""
    return align_dispatch(queries, targets, lq, lt, wb, mesh=mesh,
                          centers=centers)()


# ---------------------------------------------------------------------------
# Device WFA (wavefront) kernel: align cost scales with DISTANCE, not band^2
# ---------------------------------------------------------------------------
#
# The banded kernel above does wb x lq work per pair no matter how
# similar the sequences are, serialized by its per-row prefix-min
# chain; the CPU engine (native/align.cpp) is the O(N + D^2)
# unit-cost wavefront algorithm, which is why divergence used to hand
# the align stage back to the host.  This kernel is the device-shaped
# wavefront: wavefront e has a statically bounded diagonal extent
# (lane c <-> diagonal d = c - emax, 8 pairs stacked on sublanes), so
# every e-step is a fixed-width vector body and the serial chain is
# ~DISTANCE steps long instead of lq rows.  The furthest-reaching
# extension is a vectorized LCP over precomputed match-bit words
# (one XLA elementwise+gather pre-pass builds, per diagonal, the
# 32-chars-per-int32 match bits; the kernel slides via a
# trailing-ones popcount on each lane's cached word and DMA-refills
# exhausted words from an 8-row window anchored at the neediest
# lane).  The wavefront history lands in HBM; an in-kernel lockstep
# traceback re-derives each step's predecessor with EXACTLY the
# native engine's candidate and preference rules, so the emitted
# (slide, op) tape decodes to byte-identical CIGARs with the CPU WFA
# -- and the compact tape (<= emax+2 int32 entries per pair) is all
# that travels device->host.
#
# Failure contract: a pair whose distance exceeds ``emax`` (or whose
# length difference already does) reports _BIG and keeps no tape; the
# polisher escalates it to the re-centered banded rung (reject code
# "wfa<emax>" in align_retry_counts).

_WFA_NEG = -(1 << 20)        # inactive-diagonal sentinel
_WFA_NEG_H = -(1 << 19)      # activity threshold (> any real deficit)
_W_SUB, _W_INS, _W_DEL = 1, 2, 3   # tape op codes (0 = final slide)


def _wfa_wd(emax: int) -> int:
    """Diagonal extent (lanes): covers d in [-emax, emax], 128-padded."""
    return ((2 * emax + 2) + 127) // 128 * 128


def _wfa_nwords(lq: int) -> int:
    """Match-bit words per diagonal (8-row aligned for DMA windows)."""
    return ((lq // 32 + 2) + 7) // 8 * 8


def _wfa_tape_rows(emax: int) -> int:
    return (emax + 2 + 127) // 128


def wfa_available() -> bool:
    """Device WFA rung gate: RACON_TPU_WFA=0 keeps the banded-only
    ladder (the pre-WFA behavior; the TPU CI golden configs pin this
    until their committed bytes are regenerated)."""
    if os.environ.get("RACON_TPU_WFA", "1") == "0":
        return False
    return available()


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _wfa_match_words(q, t, lq: int, emax: int, wd: int):
    """Per-diagonal match bits, packed 32 query rows per int32.

    Word r of diagonal c holds bit k = (q[i] == t[i + c - emax]) for
    i = 32r + k; out-of-range positions compare pads (q pad 5, t pad
    6, shift sentinel 7) and are always 0, so the kernel's slide
    stops at sequence boundaries with no extra masking.  One fused
    elementwise pass at memory bandwidth -- the O(wd x lq) element
    count looks like the banded DP's, but these are independent byte
    compares, not a serialized scoring recurrence.  Returns
    [B * nwords, wd] (2-D so the kernel's refill DMA windows are
    plain 8-row slices)."""
    b = q.shape[0]
    nwords = _wfa_nwords(lq)
    li = nwords * 32
    from racon_tpu.tpu.aligner import _QPAD

    qq = jnp.pad(q, ((0, 0), (0, li - lq)), constant_values=_QPAD)
    tp = jnp.full((b, li + wd), 7, jnp.uint8)
    tp = lax.dynamic_update_slice(tp, t, (0, emax))

    def one_diag(c):
        return lax.dynamic_slice_in_dim(tp, c, li, axis=1)

    tsh = jax.vmap(one_diag, out_axes=1)(jnp.arange(wd))  # [b, wd, li]
    eqw = (qq[:, None, :] == tsh).reshape(b, wd, nwords, 32)
    word = jnp.zeros((b, wd, nwords), jnp.uint32)
    for k in range(32):
        word = word | (eqw[..., k].astype(jnp.uint32)
                       << np.uint32(k))
    word = lax.bitcast_convert_type(word, jnp.int32)
    return jnp.transpose(word, (0, 2, 1)).reshape(b * nwords, wd)


def _wfa_kernel(ql_ref, tl_ref, mw_hbm, tape_ref, meta_ref, hist_hbm,
                F, W, BW, win, taperow, dsems, hsem, regs_s, *,
                lq: int, emax: int, wd: int, nwords: int):
    g0 = pl.program_id(0) * _S
    h0 = pl.program_id(0) * (emax + 1) * 8
    big = jnp.int32(_BIG)
    neg = jnp.int32(_WFA_NEG)
    negh = jnp.int32(_WFA_NEG_H)
    tape_rows = _wfa_tape_rows(emax)
    cols_s = lax.broadcasted_iota(jnp.int32, (_S, wd), 1)
    rows_s = lax.broadcasted_iota(jnp.int32, (_S, wd), 0)
    wrow8 = lax.broadcasted_iota(jnp.int32, (8, wd), 0)
    riota = lax.broadcasted_iota(jnp.int32, (_S, 1), 0)
    iota_c = lax.broadcasted_iota(jnp.int32, (1, 128), 1)

    qls = [ql_ref[g0 + s] for s in range(_S)]
    tls = [tl_ref[g0 + s] for s in range(_S)]
    valids = [(qls[s] > 0) & (tls[s] > 0)
              & (jnp.abs(tls[s] - qls[s]) <= emax)
              for s in range(_S)]

    def stackv(vals, dtype=jnp.int32):
        out = jnp.full((_S, 1), 0, dtype)
        for s, v in enumerate(vals):
            out = jnp.where(riota == s, jnp.asarray(v, dtype), out)
        return out

    ql_col = stackv(qls)
    tl_col = stackv(tls)
    valid_col = stackv([jnp.where(v, 1, 0) for v in valids]) > 0
    fin_col = tl_col - ql_col + emax       # lane of the final diagonal
    d_col = cols_s - emax                  # each lane's diagonal

    # regs per pair s at base s*8: 0 dist (-1 pending / e / _BIG
    # failed), 1 cur_i, 2 cur_d, 3 tape entry count
    for s in range(_S):
        regs_s[s * 8 + 0] = jnp.where(valids[s], -1, big)
        regs_s[s * 8 + 3] = jnp.int32(0)

    def dist_done_col():
        dist_col = stackv([regs_s[s * 8] for s in range(_S)])
        return dist_col, dist_col != -1

    def extend():
        """LCP extension to the furthest-reaching points: slide every
        lane along its cached match word (trailing-ones popcount),
        refilling exhausted words from an 8-row DMA window anchored
        at each pair's neediest lane.  Loops until no active lane
        awaits a word; each round serves at least the minimum-index
        needy lane, so it terminates."""
        _, done_col = dist_done_col()

        def body(_):
            Fv = F[0:_S, :]
            active = (Fv > negh) & ~done_col & (Fv < ql_col)
            needy = active & ((Fv >> 5) != BW[0:_S, :])
            widx = jnp.where(needy, Fv >> 5, jnp.int32(1 << 24))
            cps, rlos = [], []
            for s in range(_S):
                rlo = jnp.min(jnp.where(rows_s == s, widx,
                                        jnp.int32(1 << 24)))
                rlo8 = jnp.clip((rlo >> 3) << 3, 0, nwords - 8)
                cp = pltpu.make_async_copy(
                    mw_hbm.at[pl.ds(pl.multiple_of(
                        (g0 + s) * nwords + rlo8, 8), 8), :],
                    win.at[pl.ds(pl.multiple_of(s * 8, 8), 8), :],
                    dsems.at[s])
                cp.start()
                cps.append(cp)
                rlos.append(rlo8)
            for cp in cps:
                cp.wait()
            f5 = Fv >> 5
            for s in range(_S):
                wnd = win[s * 8:(s + 1) * 8, :]
                f5s = f5[s:s + 1, :]
                served = needy[s:s + 1, :] & (f5s >= rlos[s]) \
                    & (f5s < rlos[s] + 8)
                sel = jnp.sum(
                    jnp.where(wrow8[0:8, :] + rlos[s] == f5s, wnd, 0),
                    axis=0, keepdims=True)
                W[s:s + 1, :] = jnp.where(served, sel, W[s:s + 1, :])
                BW[s:s + 1, :] = jnp.where(served, f5s,
                                           BW[s:s + 1, :])
            have = active & ((Fv >> 5) == BW[0:_S, :])
            x = lax.shift_right_logical(W[0:_S, :], Fv & 31)
            y = ~x
            lsb = y & (-y)
            tr = lax.population_count(lsb - 1)
            Fn = jnp.where(have, Fv + tr, Fv)
            F[0:_S, :] = Fn
            needy2 = (Fn > negh) & ~done_col & (Fn < ql_col) \
                & ((Fn >> 5) != BW[0:_S, :])
            return jnp.sum(needy2.astype(jnp.int32)) > 0

        lax.while_loop(lambda c: c, body, jnp.bool_(True))

    def estep():
        """One wavefront advance: candidates exactly as the native
        wf_candidate (del keeps i from d-1; sub/ins advance i from
        d/d+1), furthest = max, boundary masks identical -- the
        wavefront VALUES must equal the CPU engine's for the
        traceback tapes to agree byte-for-byte."""
        _, done_col = dist_done_col()
        Fv = F[0:_S, :]
        nl = jnp.pad(Fv, ((0, 0), (1, 0)),
                     constant_values=_WFA_NEG)[:, :wd]
        nr = jnp.pad(Fv, ((0, 0), (0, 1)),
                     constant_values=_WFA_NEG)[:, 1:]
        vdel = jnp.where((nl > negh) & (nl + d_col <= tl_col),
                         nl, neg)
        vsub = jnp.where((Fv > negh) & (Fv + 1 <= ql_col)
                         & (Fv + 1 + d_col <= tl_col), Fv + 1, neg)
        vins = jnp.where((nr > negh) & (nr + 1 <= ql_col),
                         nr + 1, neg)
        cand = jnp.maximum(jnp.maximum(vdel, vsub), vins)
        F[0:_S, :] = jnp.where(done_col, Fv, cand)

    def hist_write(e):
        cp = pltpu.make_async_copy(
            F, hist_hbm.at[pl.ds(pl.multiple_of(h0 + e * 8, 8),
                                 8), :], hsem)
        cp.start()
        cp.wait()

    def check_done(e):
        Fv = F[0:_S, :]
        sel = jnp.max(jnp.where(cols_s == fin_col, Fv, neg),
                      axis=1, keepdims=True)
        newly = (sel >= ql_col) & valid_col
        for s in range(_S):
            ns = jnp.sum(jnp.where(riota == s,
                                   newly.astype(jnp.int32), 0)) > 0

            @pl.when(ns & (regs_s[s * 8] == -1))
            def _(s=s):
                regs_s[s * 8] = jnp.asarray(e, jnp.int32)

    # ---- forward: wavefronts until every pair finishes or e > emax
    F[0:_S, :] = jnp.where((cols_s == emax) & valid_col, 0, neg)
    W[0:_S, :] = jnp.zeros((_S, wd), jnp.int32)
    BW[0:_S, :] = jnp.full((_S, wd), -1, jnp.int32)
    for s in range(_S):
        tape_ref[s, :, :] = jnp.zeros((tape_rows, 128), jnp.int32)
    taperow[0:8, :] = jnp.zeros((8, 128), jnp.int32)
    extend()
    hist_write(0)
    check_done(0)

    def n_done():
        nd = jnp.int32(0)
        for s in range(_S):
            nd = nd + jnp.where(regs_s[s * 8] != -1, 1, 0)
        return nd

    def fbody(c):
        e, _ = c
        estep()
        extend()
        hist_write(e)
        check_done(e)
        return e + 1, n_done()

    lax.while_loop(lambda c: (c[0] <= emax) & (c[1] < _S), fbody,
                   (jnp.int32(1), n_done()))
    for s in range(_S):
        @pl.when(regs_s[s * 8] == -1)
        def _(s=s):
            regs_s[s * 8] = big                # ran past emax: reject

    # ---- traceback: lockstep walk from each pair's distance to 0,
    # re-deriving predecessors from the HBM history with the native
    # engine's preference order (ins > sub > del)
    for s in range(_S):
        regs_s[s * 8 + 1] = qls[s]
        regs_s[s * 8 + 2] = tls[s] - qls[s]
    e_top = jnp.int32(0)
    for s in range(_S):
        e_top = jnp.maximum(
            e_top, jnp.where(regs_s[s * 8] < big, regs_s[s * 8], 0))

    def put_entry(s, val):
        n = regs_s[s * 8 + 3]
        lane = n % 128
        taperow[s:s + 1, :] = jnp.where(iota_c == lane, val,
                                        taperow[s:s + 1, :])

        @pl.when(lane == 127)
        def _():
            tape_ref[s, pl.ds(n // 128, 1), :] = taperow[s:s + 1, :]
        regs_s[s * 8 + 3] = n + 1

    def tbody(e):
        cp = pltpu.make_async_copy(
            hist_hbm.at[pl.ds(pl.multiple_of(h0 + (e - 1) * 8, 8),
                              8), :], F, hsem)
        cp.start()
        cp.wait()
        prev = F[0:_S, :]
        dist_col, _ = dist_done_col()
        i_col = stackv([regs_s[s * 8 + 1] for s in range(_S)])
        dcur = stackv([regs_s[s * 8 + 2] for s in range(_S)])
        active_col = (dist_col < big) & (e <= dist_col)
        c_col = dcur + emax

        def pick(delta):
            return jnp.max(
                jnp.where(cols_s == c_col + delta, prev, neg),
                axis=1, keepdims=True)

        vm1, v0, vp1 = pick(-1), pick(0), pick(1)
        del_c = jnp.where((vm1 > negh) & (vm1 + dcur <= tl_col),
                          vm1, neg)
        sub_c = jnp.where((v0 > negh) & (v0 + 1 <= ql_col)
                          & (v0 + 1 + dcur <= tl_col), v0 + 1, neg)
        ins_c = jnp.where((vp1 > negh) & (vp1 + 1 <= ql_col),
                          vp1 + 1, neg)
        i0 = jnp.maximum(jnp.maximum(del_c, sub_c), ins_c)
        is_ins = (ins_c > negh) & (ins_c == i0)
        is_sub = ~is_ins & (sub_c > negh) & (sub_c == i0)
        entry = (i_col - i0) * 4 + jnp.where(
            is_ins, _W_INS, jnp.where(is_sub, _W_SUB, _W_DEL))
        ni = jnp.where(is_ins | is_sub, i0 - 1, i0)
        nd2 = jnp.where(is_ins, dcur + 1,
                        jnp.where(is_sub, dcur, dcur - 1))
        for s in range(_S):
            act = jnp.sum(jnp.where(
                riota == s, active_col.astype(jnp.int32), 0)) > 0

            @pl.when(act)
            def _(s=s):
                put_entry(s, jnp.sum(jnp.where(riota == s, entry,
                                               0)))
                regs_s[s * 8 + 1] = jnp.sum(
                    jnp.where(riota == s, ni, 0))
                regs_s[s * 8 + 2] = jnp.sum(
                    jnp.where(riota == s, nd2, 0))
        return e - 1

    lax.while_loop(lambda e: e > 0, tbody, e_top)
    for s in range(_S):
        @pl.when(regs_s[s * 8] < big)
        def _(s=s):
            put_entry(s, regs_s[s * 8 + 1] * 4)   # e == 0 slide

        @pl.when(regs_s[s * 8 + 3] % 128 > 0)
        def _(s=s):
            tape_ref[s, pl.ds(regs_s[s * 8 + 3] // 128, 1), :] = \
                taperow[s:s + 1, :]
        meta_ref[s, 0:1, 0:1] = jnp.full((1, 1), regs_s[s * 8],
                                         jnp.int32)
        meta_ref[s, 1:2, 0:1] = jnp.full((1, 1), regs_s[s * 8 + 3],
                                         jnp.int32)


@functools.partial(jax.jit, static_argnums=(4, 5, 6))
def _wfa_call(q, t, ql, tl, lq: int, emax: int,
              interpret: bool = False):
    b = q.shape[0]
    wd = _wfa_wd(emax)
    nwords = _wfa_nwords(lq)
    mw = _wfa_match_words(q, t, lq, emax, wd)
    tape_rows = _wfa_tape_rows(emax)
    kern = functools.partial(_wfa_kernel, lq=lq, emax=emax, wd=wd,
                             nwords=nwords)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b // _S,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],   # match words
        out_specs=(
            pl.BlockSpec((_S, tape_rows, 128), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_S, 8, 1), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),          # history HBM
        ),
        scratch_shapes=[
            pltpu.VMEM((_S, wd), jnp.int32),            # wavefront F
            pltpu.VMEM((_S, wd), jnp.int32),            # cached words
            pltpu.VMEM((_S, wd), jnp.int32),            # word indices
            pltpu.VMEM((_S * 8, wd), jnp.int32),        # refill window
            pltpu.VMEM((8, 128), jnp.int32),            # taperow
            pltpu.SemaphoreType.DMA((_S,)),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SMEM((8 * _S,), jnp.int32),
        ],
    )
    tape, meta, _ = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct((b, tape_rows, 128),
                                        jnp.int32),
                   jax.ShapeDtypeStruct((b, 8, 1), jnp.int32),
                   jax.ShapeDtypeStruct((b // _S * (emax + 1) * 8,
                                         wd), jnp.int32)),
        interpret=interpret,
    )(ql, tl, mw)
    return tape, meta


@functools.partial(jax.jit,
                   static_argnames=("mesh", "lq", "emax", "interpret"))
def _wfa_sharded(q, t, ql, tl, *, mesh, lq: int, emax: int,
                 interpret: bool):
    from racon_tpu.parallel.mesh_utils import shard_batch_map

    def shard_fn(q, t, ql, tl):
        return _wfa_call(q, t, ql, tl, lq, emax, interpret)

    return shard_batch_map(shard_fn, mesh, 4, 2)(q, t, ql, tl)


def wfa_per_pair_bytes(lq: int, emax: int) -> int:
    """Device bytes one queued pair costs at max e-step ``emax``: the
    HBM wavefront history dominates ((emax+1) x wd int32 rows), plus
    the match-word pre-pass buffer and the q/t/tape buffers."""
    wd = _wfa_wd(emax)
    return (emax + 1) * wd * 4 + _wfa_nwords(lq) * wd * 4 + 8 * lq


def wfa_dispatch(queries, targets, lq: int, emax: int, mesh=None):
    """Enqueue one WFA batch; returns a zero-arg collect closure
    producing (tapes, n_entries, dists) -- dists are EXACT edit
    distances (<= emax) or _BIG for rejected pairs.  Same two-deep
    pipeline contract as ``align_dispatch``."""
    from racon_tpu.tpu.aligner import encode_batch, _QPAD, _TPAD

    import threading

    n_real = len(queries)
    n_dev = len(mesh.devices) if mesh is not None else 1
    n_pad = pad_pairs(n_real, n_dev)
    queries = list(queries) + [b""] * (n_pad - n_real)
    targets = list(targets) + [b""] * (n_pad - n_real)
    q = encode_batch(queries, lq, _QPAD)
    t = encode_batch(targets, lq, _TPAD)
    ql = np.array([len(s) for s in queries], np.int32)
    tl = np.array([len(s) for s in targets], np.int32)
    from racon_tpu.parallel.mesh_utils import interpret_mode

    interp = interpret_mode()
    t_disp = _mono()
    if n_dev > 1:
        tape, meta = _wfa_sharded(q, t, ql, tl, mesh=mesh, lq=lq,
                                  emax=emax, interpret=interp)
    else:
        from racon_tpu.utils import aot_shelf

        def build(qq, tt, qql, ttl):
            return _wfa_call(qq, tt, qql, ttl, lq, emax, interp)

        tape, meta = aot_shelf.call(
            ("align_wfa", n_pad, lq, emax, interp), __file__, build,
            (q, t, ql, tl))
    tape.copy_to_host_async()
    meta.copy_to_host_async()
    span = {}

    def _watch():
        try:
            jax.block_until_ready((tape, meta))
            t_end = _mono()
            span["s"] = t_end - t_disp
            obs_trace.TRACER.add_span(
                f"device.align_wfa{emax}", t_disp, t_end,
                cat="device", lane="device", args={"n": n_real})
            obs_devutil.DEVICE_UTIL.record("align_wfa", t_disp, t_end)
            _decision.DECISIONS.record(
                "align_device", engine="wfa", rung=int(emax),
                n=int(n_real), device_s=round(t_end - t_disp, 6))
        except Exception:
            pass  # dispatch errors surface at collect()

    watcher = threading.Thread(target=_watch, daemon=True,
                               name="racon-wfa-devtime")
    watcher.start()

    def collect():
        tp = np.asarray(tape)[:n_real].reshape(n_real, -1) \
            .astype(np.int64)
        mt = np.asarray(meta)[:n_real, :, 0]
        watcher.join()
        return tp, mt[:, 1], mt[:, 0]

    collect.device_s = lambda: span.get("s", 0.0)
    return collect


def wfa_batch(queries, targets, lq: int, emax: int, mesh=None):
    """Synchronous wrapper over ``wfa_dispatch``."""
    return wfa_dispatch(queries, targets, lq, emax, mesh=mesh)()


def wfa_prewarm(n: int, lq: int, emax: int, mesh=None) -> None:
    """Populate the jit/AOT caches for one WFA variant through the
    same entry production dispatch uses (see ``prewarm``)."""
    from racon_tpu.parallel.mesh_utils import interpret_mode

    n_dev = len(mesh.devices) if mesh is not None else 1
    if n_dev > 1:
        interp = interpret_mode()
        q = jnp.zeros((n, lq), jnp.uint8)
        t = jnp.zeros((n, lq), jnp.uint8)
        zl = jnp.zeros((n,), jnp.int32)
        out = _wfa_sharded(q, t, zl, zl, mesh=mesh, lq=lq, emax=emax,
                           interpret=interp)
        jax.block_until_ready(out)
    else:
        wfa_batch([b""] * n, [b""] * n, lq, emax, mesh=None)


def wfa_tape_to_ops(tape_row: np.ndarray, n_entries: int):
    """Decode one WFA (slide, op) tape row into the aligner op
    alphabet, reversed (traceback) order like ``moves_to_ops``.  Each
    entry expands to ``slide`` exact matches followed by its op; sub
    steps are always true mismatches (the slide is maximal), so =/X
    needs no sequence re-compare."""
    from racon_tpu.tpu import aligner as al

    ent = tape_row[:n_entries]
    slides = ent >> 2
    opc = ent & 3
    counts = slides + (opc != 0)
    out = np.full(int(counts.sum()), al.OP_EQ, np.uint8)
    ends = np.cumsum(counts)
    has = opc != 0
    opmap = np.array([al.OP_EQ, al.OP_X, al.OP_I, al.OP_D], np.uint8)
    out[(ends - 1)[has]] = opmap[opc[has]]
    return out


def moves_to_ops(moves_row, length, query: bytes, target: bytes):
    """Decode one reversed 2-bit move row into the aligner op alphabet
    (=/X/I/D codes from racon_tpu.tpu.aligner), vectorised."""
    from racon_tpu.tpu import aligner as al

    mv = moves_row[:length][::-1]                  # forward order
    di = (mv != _MV_LEFT).astype(np.int64)
    dj = (mv != _MV_UP).astype(np.int64)
    i_idx = np.cumsum(di) - 1                      # query index used
    j_idx = np.cumsum(dj) - 1
    qa = np.frombuffer(query, np.uint8)
    ta = np.frombuffer(target, np.uint8)
    eq = np.zeros(len(mv), bool)
    m = mv == _MV_DIAG
    eq[m] = qa[i_idx[m]] == ta[j_idx[m]]
    ops = np.where(m, np.where(eq, al.OP_EQ, al.OP_X),
                   np.where(mv == _MV_UP, al.OP_I, al.OP_D))
    return ops.astype(np.uint8)[::-1]              # reversed, like scan
