"""Single-dispatch batched pairwise alignment: Pallas TPU kernel.

Replaces the lax.scan wavefront kernels (racon_tpu/tpu/aligner.py) on
real TPU backends.  The scan kernels pay per-step XLA overhead over
``lq+lt`` anti-diagonals and one host round-trip per (bucket, chunk);
on the tunneled-TPU deployment target those transfers cost ~100 ms of
latency each.  This kernel aligns EVERY queued pair in one
``pallas_call`` and emits a compact 2-bit move tape.

Design notes:

* **8 pairs per grid program, stacked on the sublane axis** (a full
  8-sublane vreg): the banded row DP's critical path is the in-row
  prefix-min chain (log2(wb) serial vector steps, latency-bound
  regardless of width), so eight independent pairs share ONE chain
  per row group -- measured 0.57-0.96 us/row vs ~2 us single-pair.
  Callers sort pairs by length so group partners finish together;
* the row loop bound is the group's longest REAL query, so mixing
  short and long pairs in one shape bucket costs padding memory, not
  padded compute -- no per-length bucket dispatch loop (the
  cudaaligner analog queues per-batch, src/cuda/cudaaligner.cpp:52-86);
* the band follows each pair's proportional diagonal ``i*tl/ql``,
  quantized to 128 columns so the per-row target slice and the
  previous-row realignment are lane-aligned (TPU dynamic lane offsets
  must be 128-multiples); an alignment of cost c deviates at most
  ``(c + |tl-ql|)/2`` columns from that diagonal, so a tape satisfying
  ``cost + |tl-ql| <= wb - 512`` is exact (Ukkonen) and callers
  escalate the rest to a wider band;
* no direction tape is materialised in HBM: the forward pass keeps
  one score-row checkpoint every ``_CKPT`` rows in VMEM, and the
  traceback re-derives each 128-row block's directions from its
  checkpoint on demand, walking all stacked pairs' segments through a
  block before moving down (one recompute per block, not per pair);
* the kernel emits 2-bit moves (diag/up/left) packed 16-per-int32;
  the host reconstructs =/X from the sequences vectorised, then RLEs
  to a CIGAR (the reference also finishes CIGARs on the host,
  src/cuda/cudaaligner.cpp:89-103).
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_BIG = 1 << 20
_CKPT = 128                  # rows between score checkpoints
                             # (halved for wide bands: VMEM dirs block)


def _ckrows(wb: int) -> int:
    """Rows per checkpoint block, shrunk for wide bands so the dirs
    scratch (ckrows x 8 x wb i32) stays inside the ~16 MB VMEM scope."""
    if wb >= 8192:
        return 32
    return 64 if wb >= 4096 else _CKPT
_N_SHIFT = 3                 # band start advances <= 2 quanta per row
_S = 8                       # pairs stacked per grid program
_MV_DIAG, _MV_UP, _MV_LEFT = 0, 1, 2


def available() -> bool:
    """Default on real TPU backends (RACON_TPU_PALLAS_ALIGN=0 falls
    back to the scan-ladder kernels): with 8 pairs sharing each row
    group the kernel measures 0.57-0.96 us/row including the
    traceback pass, in ONE dispatch per band rung."""
    if os.environ.get("RACON_TPU_NO_PALLAS"):
        return False
    if os.environ.get("RACON_TPU_PALLAS_ALIGN", "1") == "0":
        return False
    if os.environ.get("RACON_TPU_PALLAS_INTERPRET") == "1":
        return True
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _kernel(ql_ref, tl_ref, q_ref, t_ref, tape_ref, dist_ref,
            ckpt_hbm, ckstage, dirs, taperow, dsem, regs_s, *,
            lq: int, lt: int, wb: int, ckrows: int):
    g0 = pl.program_id(0) * _S
    nck8 = (lq // ckrows + 1) * 8
    ck0 = pl.program_id(0) * nck8      # this program's HBM region
    q = 128
    tape_w = (lq + lt) // 16 + 1
    tape_rows = (tape_w + 127) // 128
    big = jnp.int32(_BIG)
    cols = lax.broadcasted_iota(jnp.int32, (1, wb), 1)
    cols_s = lax.broadcasted_iota(jnp.int32, (_S, wb), 1)
    rows_s = lax.broadcasted_iota(jnp.int32, (_S, wb), 0)
    iota_c = lax.broadcasted_iota(jnp.int32, (1, 128), 1)

    qls = [ql_ref[g0 + s] for s in range(_S)]
    tls = [tl_ref[g0 + s] for s in range(_S)]
    nqs = [jnp.maximum(x, 1) for x in qls]
    smaxs = [(jnp.maximum(tls[s] + 1 - wb, 0) + q - 1) // q
             for s in range(_S)]
    # q8 fixed-point diagonal slopes, one divide per pair per PROGRAM:
    # the row loop calls sqq twice per pair per row, and a dynamic
    # integer divide on the scalar core is many-cycle.  The clamp
    # bounds i*slope inside int32 (i <= 2^14, slope < 2^17).  Worst-
    # case rounding deficit vs the exact divide is i/256 <= 64 columns
    # (half a quantum, so the band start may sit one 128-column
    # quantum lower); the Ukkonen certificate budget in the dispatcher
    # keeps >= wb/2 - 256 columns of margin per side, which still
    # covers it with a quantum to spare.
    slopes = [jnp.minimum((tls[s] * 256) // nqs[s], (1 << 17) - 1)
              for s in range(_S)]

    def sqq(s, i):
        """Quantized band start for pair s, row i: centered on the
        proportional diagonal (symmetric margins >= wb/2 - 128)."""
        return jnp.clip((((i * slopes[s]) >> 8) - (wb // 2)) >> 7,
                        0, smaxs[s])

    def stackv(vals, dtype=jnp.int32):
        """[_S] scalars -> [_S, 1] column vector."""
        out = jnp.full((_S, 1), 0, dtype)
        ri = lax.broadcasted_iota(jnp.int32, (_S, 1), 0)
        for s, v in enumerate(vals):
            out = jnp.where(ri == s, jnp.asarray(v, dtype), out)
        return out

    # tl as a broadcastable column; per-pair big mask rows beyond tl
    tl_col = stackv(tls)

    def t_band(starts):
        """Stacked [S, wb] target chars at each pair's band start."""
        rows = [t_ref[s, :, pl.ds(pl.multiple_of(starts[s], q), wb)]
                for s in range(_S)]
        return jnp.concatenate(rows, axis=0)

    def row_dp(i, pvp, qchars, i0):
        """One stacked DP row group.  pvp: [S, wb + shift headroom] of
        D[i-1][s_{i-1} + c]; qchars: [S, _CKPT] of this block's query
        chars.  Returns (row_u [S, wb], dirs_row [S, wb])."""
        sq_i = [sqq(s, i) for s in range(_S)]
        s_i = stackv([x * q for x in sq_i])
        dq = stackv([sq_i[s] - sqq(s, i - 1) for s in range(_S)])
        pu = pvp[:, 0:wb]
        for mm in range(1, _N_SHIFT):
            pu = jnp.where(dq == mm, pvp[:, mm * q: mm * q + wb], pu)
        qc = jnp.sum(jnp.where(iota_c == (i - 1 - i0), qchars, 0),
                     axis=1, keepdims=True)           # [S, 1]
        tb = t_band([x * q for x in sq_i])
        j_u = s_i + cols_s
        sub_u = jnp.where(tb == qc, 0, 1)
        du = pu + sub_u
        vu = pu + 1
        t_u = jnp.minimum(jnp.pad(du, ((0, 0), (1, 0)),
                                  constant_values=big)[:, :wb], vu)
        t_u2 = jnp.where(j_u == 0, i, t_u)
        t_u2 = jnp.where(j_u > tl_col, big, t_u2)
        x = t_u2 - j_u
        sh = 1
        while sh < wb:
            x = jnp.minimum(
                x, jnp.pad(x, ((0, 0), (sh, 0)),
                           constant_values=big)[:, :wb])
            sh <<= 1
        row = jnp.minimum(x + j_u, big)
        dshift = jnp.pad(du, ((0, 0), (1, 0)),
                         constant_values=big)[:, :wb]
        dr = jnp.where(
            row == dshift, _MV_DIAG,
            jnp.where(row == vu, _MV_UP, _MV_LEFT)).astype(jnp.int32)
        dr = jnp.where(j_u == 0, _MV_UP, dr)
        return row, dr

    def pad_row(row):
        return jnp.pad(row, ((0, 0), (0, _N_SHIFT * q)),
                       constant_values=big)

    # ---- pass 1: forward scores, checkpoints every _CKPT rows -------
    def ck_save(slot, rows):
        # tiled HBM slices must be 8-row aligned AND 8 rows long --
        # exactly one _S=8 row group per checkpoint slot
        ckstage[0:_S, :] = rows
        cp = pltpu.make_async_copy(
            ckstage,
            ckpt_hbm.at[pl.ds(pl.multiple_of(ck0 + slot * 8, 8),
                              8), :],
            dsem)
        cp.start()
        cp.wait()

    def ck_load(slot):
        cp = pltpu.make_async_copy(
            ckpt_hbm.at[pl.ds(pl.multiple_of(ck0 + slot * 8, 8),
                              8), :],
            ckstage, dsem)
        cp.start()
        cp.wait()
        return ckstage[0:_S, :]

    init = jnp.where(cols_s > tl_col, big, cols_s)   # D[0][j] = j
    ck_save(0, init)
    max_ql = qls[0]
    for s in range(1, _S):
        max_ql = jnp.maximum(max_ql, qls[s])

    def qchars_blk(i0):
        # char window anchored to 128 lanes (ckrows may be 64)
        i0b = (i0 // 128) * 128
        rows = [q_ref[s, :, pl.ds(pl.multiple_of(i0b, 128), 128)]
                for s in range(_S)]
        return jnp.concatenate(rows, axis=0), i0b     # [S, 128]

    ql_col1 = stackv(qls)

    def blk_fwd(bk, pv):
        i0 = bk * ckrows
        qchars, i0b = qchars_blk(i0)

        def row_step(i, pv):
            row, _ = row_dp(i, pv, qchars, i0b)
            # a pair whose query ended keeps its final row frozen so
            # the end score survives to the loop exit
            row = jnp.where(ql_col1 < i, pv[:, 0:wb], row)
            return pad_row(row)

        top = jnp.minimum((bk + 1) * ckrows, max_ql)
        pv = lax.fori_loop(i0 + 1, top + 1, row_step, pv)

        @pl.when(top == (bk + 1) * ckrows)
        def _():
            ck_save(bk + 1, pv[:, 0:wb])
        return pv

    nblk = (max_ql + ckrows - 1) // ckrows
    pv = lax.fori_loop(0, nblk, blk_fwd, pad_row(init))

    # NOTE on the freeze: once i passes ql_s, pair s's row stops
    # updating, so its band start must also stop moving -- sqq(s, i)
    # with i > ql_s would drift.  The freeze keeps the row contents of
    # row ql_s, whose band start is sqq(s, ql_s); the end-score read
    # below uses exactly that start, so they agree.
    for s in range(_S):
        c_end = tls[s] - sqq(s, qls[s]) * q
        dval = jnp.sum(jnp.where((rows_s == s) &
                                 (cols_s == jnp.clip(c_end, 0,
                                                     wb - 1)),
                                 pv[:, 0:wb], 0))
        dval = jnp.where((c_end < 0) | (c_end >= wb), big, dval)
        dist_ref[s, 0:1, 0:1] = jnp.full((1, 1), dval, jnp.int32)

    # ---- pass 2: checkpointed traceback, all pairs per block --------
    for s in range(_S):
        tape_ref[s, :, :] = jnp.zeros((tape_rows, 128), jnp.int32)
    # regs per pair s at base s*8: 0 word, 1 word count, 2 bit count,
    # 3 i, 4 j
    for s in range(_S):
        regs_s[s * 8 + 0] = jnp.int32(0)
        regs_s[s * 8 + 1] = jnp.int32(0)
        regs_s[s * 8 + 2] = jnp.int32(0)
        regs_s[s * 8 + 3] = qls[s]
        regs_s[s * 8 + 4] = tls[s]

    def put_word(s, w):
        """Append one finished 16-move word: accumulate into the
        pair's 128-lane row register and flush whole rows -- the tape
        output packs 128 words per sublane row, so nothing is stored
        through the ~800ns dynamic-scalar path and the block is not
        lane-padded 128x in VMEM."""
        wcnt = regs_s[s * 8 + 1]
        lane = wcnt % 128
        taperow[s:s + 1, :] = jnp.where(iota_c == lane, w,
                                        taperow[s:s + 1, :])

        @pl.when(lane == 127)
        def _():
            tape_ref[s, pl.ds(wcnt // 128, 1), :] = taperow[s:s + 1, :]
        regs_s[s * 8 + 1] = wcnt + 1

    def emit(s, mv):
        w = regs_s[s * 8] | (mv << (regs_s[s * 8 + 2] * 2))
        nb = regs_s[s * 8 + 2] + 1
        full = nb == 16

        @pl.when(full)
        def _():
            put_word(s, w)
            regs_s[s * 8] = jnp.int32(0)
            regs_s[s * 8 + 2] = jnp.int32(0)

        @pl.when(jnp.logical_not(full))
        def _():
            regs_s[s * 8] = w
            regs_s[s * 8 + 2] = nb

    def blk_bwd(bkr, _):
        bk = nblk - 1 - bkr
        i0 = bk * ckrows
        any_here = regs_s[3] > i0
        for s in range(1, _S):
            any_here = any_here | (regs_s[s * 8 + 3] > i0)

        @pl.when(any_here)
        def _():
            # rebuild this block's direction rows from its checkpoint
            qchars, i0b = qchars_blk(i0)

            def row_step(i, pv):
                row, dr = row_dp(i, pv, qchars, i0b)
                dirs[pl.ds(pl.multiple_of((i - 1 - i0) * 8, 8),
                           _S), :] = dr
                row = jnp.where(ql_col1 < i, pv[:, 0:wb], row)
                return pad_row(row)

            top = jnp.minimum(i0 + ckrows, max_ql)
            pv0 = pad_row(ck_load(bk))
            lax.fori_loop(i0 + 1, top + 1, row_step, pv0)

            for s in range(_S):
                def w_cond(c):
                    i, j = c
                    return (i > i0) | ((i0 == 0) &
                                       ((i > 0) | (j > 0)))

                def w_body(c, s=s):
                    i, j = c

                    @pl.when(i == 0)
                    def _():
                        emit(s, jnp.int32(_MV_LEFT))

                    @pl.when(i > 0)
                    def _():
                        s_i = sqq(s, i) * q
                        cc = jnp.clip(j - s_i, 0, wb - 1)
                        drow = dirs[pl.ds((i - 1 - i0) * 8 + s,
                                          1), :]
                        mv = jnp.sum(jnp.where(cols == cc, drow, 0))
                        mv = jnp.where(j <= 0, _MV_UP, mv)
                        emit(s, mv)
                        regs_s[s * 8 + 3] = jnp.where(mv != _MV_LEFT,
                                                      i - 1, i)
                        regs_s[s * 8 + 4] = jnp.where(mv != _MV_UP,
                                                      j - 1, j)
                    ni = jnp.where(i == 0, i, regs_s[s * 8 + 3])
                    nj = jnp.where(i == 0, j - 1, regs_s[s * 8 + 4])
                    regs_s[s * 8 + 3] = ni
                    regs_s[s * 8 + 4] = nj
                    return ni, nj

                ii, jj = lax.while_loop(
                    w_cond, w_body,
                    (regs_s[s * 8 + 3], regs_s[s * 8 + 4]))
                regs_s[s * 8 + 3] = ii
                regs_s[s * 8 + 4] = jj
        return 0

    lax.fori_loop(0, nblk, blk_bwd, 0)
    for s in range(_S):
        @pl.when(regs_s[s * 8 + 2] > 0)
        def _(s=s):
            put_word(s, regs_s[s * 8])

        # flush the partial final row (garbage tail lanes are beyond
        # the move count the host slices by)
        @pl.when(regs_s[s * 8 + 1] % 128 > 0)
        def _(s=s):
            tape_ref[s, pl.ds(regs_s[s * 8 + 1] // 128, 1), :] = \
                taperow[s:s + 1, :]
        dist_ref[s, 1:2, 0:1] = jnp.full(
            (1, 1),
            regs_s[s * 8 + 1] * 16 - jnp.where(
                regs_s[s * 8 + 2] > 0, 16 - regs_s[s * 8 + 2], 0),
            jnp.int32)


@functools.partial(jax.jit, static_argnums=(4, 5, 6, 7))
def _align(q, t, ql, tl, lq: int, lt: int, wb: int,
           interpret: bool = False):
    b = q.shape[0]
    tape_w = (lq + lt) // 16 + 1
    tape_rows = (tape_w + 127) // 128
    q_i = q.astype(jnp.int32)[:, None, :]
    t_i = jnp.pad(t.astype(jnp.int32), ((0, 0), (0, wb + 128)),
                  constant_values=-1)[:, None, :]
    ckrows = _ckrows(wb)
    kern = functools.partial(_kernel, lq=lq, lt=lt, wb=wb,
                             ckrows=ckrows)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b // _S,),
        in_specs=[
            pl.BlockSpec((_S, 1, lq), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_S, 1, lt + wb + 128),
                         lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((_S, tape_rows, 128), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_S, 8, 1), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),      # ckpt HBM buffer
        ),
        scratch_shapes=[
            pltpu.VMEM((8, wb), jnp.int32),                    # stage
            pltpu.VMEM((ckrows * 8, wb), jnp.int32),           # dirs
            pltpu.VMEM((8, 128), jnp.int32),                   # taperow
            pltpu.SemaphoreType.DMA(()),
            pltpu.SMEM((8 * _S,), jnp.int32),                  # regs
        ],
    )
    nck8 = (lq // ckrows + 1) * 8
    tape, meta, _ = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct((b, tape_rows, 128),
                                        jnp.int32),
                   jax.ShapeDtypeStruct((b, 8, 1), jnp.int32),
                   jax.ShapeDtypeStruct((b // _S * nck8, wb),
                                        jnp.int32)),
        interpret=interpret,
    )(ql, tl, q_i, t_i)
    return tape, meta


def per_pair_bytes(bd: int, wb: int) -> int:
    """Device bytes one queued pair costs at band ``wb``: the
    checkpoint HBM region plus q/t/tape buffers (shared by the
    dispatch chunking and the shape-prediction prewarm)."""
    return (bd // _ckrows(wb) + 1) * wb * 4 + 6 * bd


def pad_pairs(n: int, n_dev: int = 1) -> int:
    """Batch padding rule: power of two (floor 32), a multiple of the
    stacking factor and of the mesh size.  The floor keeps the
    compiled-variant set small enough for the prebuild manifest to
    cover it: a final-rung straggler batch of 8 pairs would otherwise
    mint its own kernel variant whose first-contact compile costs far
    more than 24 empty lanes ever will (empty pairs cost ~nothing --
    the row loops follow real lengths)."""
    from racon_tpu.utils.tuning import pow2_at_least

    n_pad = pow2_at_least(max(n, 32), _S)
    return n_pad + (-n_pad) % (_S * n_dev)


def prewarm(n: int, lq: int, lt: int, wb: int, mesh=None) -> None:
    """Populate the jit dispatch cache for one (batch, dims, band)
    variant with an all-empty batch through THE SAME entry production
    dispatch uses (sharded when the mesh has more than one device);
    run from a background thread so later band rungs are already
    traced+compiled when the first rung finishes."""
    from racon_tpu.parallel.mesh_utils import interpret_mode

    n_dev = len(mesh.devices) if mesh is not None else 1
    if n_dev > 1:
        interp = interpret_mode()
        q = jnp.zeros((n, lq), jnp.uint8)
        t = jnp.zeros((n, lt), jnp.uint8)
        zl = jnp.zeros((n,), jnp.int32)
        out = _align_sharded(q, t, zl, zl, mesh=mesh, lq=lq, lt=lt,
                             wb=wb, interpret=interp)
        jax.block_until_ready(out)
    else:
        # route through align_batch so the AOT-shelf callable the
        # production dispatch will use is the one warmed here
        align_batch([b""] * n, [b""] * n, lq, lt, wb, mesh=None)


@functools.partial(jax.jit,
                   static_argnames=("mesh", "lq", "lt", "wb",
                                    "interpret"))
def _align_sharded(q, t, ql, tl, *, mesh, lq: int, lt: int, wb: int,
                   interpret: bool):
    """The stacked kernel sharded over the mesh batch axis (one grid
    of programs per device, no collectives — the analog of the
    reference's per-device aligner queues, cudapolisher.cpp:170-188)."""
    from racon_tpu.parallel.mesh_utils import shard_batch_map

    def shard_fn(q, t, ql, tl):
        return _align(q, t, ql, tl, lq, lt, wb, interpret)

    return shard_batch_map(shard_fn, mesh, 4, 2)(q, t, ql, tl)


def align_dispatch(queries, targets, lq: int, lt: int, wb: int,
                   mesh=None):
    """Enqueue one aligner batch and return a zero-arg collect
    closure producing (moves, lens, dists) -- the async half of
    ``align_batch``.  A caller can dispatch chunk k+1 (and run host
    decode for chunk k) while chunk k computes, hiding the tunnel's
    per-transfer latency behind device time (the POA megabatch
    pipeline's analog, racon_tpu/tpu/polisher.py)."""
    from racon_tpu.tpu.aligner import encode_batch, _QPAD, _TPAD

    import threading
    import time

    n_real = len(queries)
    n_dev = len(mesh.devices) if mesh is not None else 1
    # pad the pair count to a power of two so grid sizes (and thus
    # compiled variants) stay bucketed; empty pairs cost ~nothing
    n_pad = pad_pairs(n_real, n_dev)
    queries = list(queries) + [b""] * (n_pad - n_real)
    targets = list(targets) + [b""] * (n_pad - n_real)
    q = encode_batch(queries, lq, _QPAD)
    t = encode_batch(targets, lt, _TPAD)
    ql = np.array([len(s) for s in queries], np.int32)
    tl = np.array([len(s) for s in targets], np.int32)
    from racon_tpu.parallel.mesh_utils import interpret_mode

    interp = interpret_mode()
    t_disp = time.monotonic()
    if n_dev > 1:
        tape, meta = _align_sharded(q, t, ql, tl, mesh=mesh, lq=lq,
                                    lt=lt, wb=wb, interpret=interp)
    else:
        from racon_tpu.utils import aot_shelf

        def build(qq, tt, qql, ttl):
            return _align(qq, tt, qql, ttl, lq, lt, wb, interp)

        tape, meta = aot_shelf.call(
            ("align", n_pad, lq, lt, wb, interp), __file__, build,
            (q, t, ql, tl))
    tape.copy_to_host_async()
    meta.copy_to_host_async()

    # host-independent per-dispatch device time: the watcher blocks
    # on the outputs from dispatch-enqueue on, so host work between
    # dispatch and collect (decoding the previous chunk under the
    # two-deep pipeline) never inflates the span -- the bench's
    # align_device_s (VERDICT r5 #8)
    span = {}

    def _watch():
        try:
            jax.block_until_ready((tape, meta))
            span["s"] = time.monotonic() - t_disp
        except Exception:
            pass  # dispatch errors surface at collect()

    watcher = threading.Thread(target=_watch, daemon=True,
                               name="racon-align-devtime")
    watcher.start()

    def collect():
        tp = np.asarray(tape)[:n_real].reshape(n_real, -1) \
            .astype(np.uint32)
        mt = np.asarray(meta)[:n_real, :, 0]
        watcher.join()
        n = tp.shape[1] * 16
        moves = np.zeros((tp.shape[0], n), np.uint8)
        for sh in range(16):
            moves[:, sh::16] = (tp >> (2 * sh)) & 3
        return moves, mt[:, 1], mt[:, 0]

    collect.device_s = lambda: span.get("s", 0.0)
    return collect


def align_batch(queries, targets, lq: int, lt: int, wb: int,
                mesh=None):
    """Align padded pair batches; returns (moves, lens, dists).

    moves: [B, n] uint8 of 2-bit codes in traceback (reversed) order,
    lens: [B] number of valid moves, dists: [B] band edit distance
    (_BIG when the endpoint fell outside the band)."""
    return align_dispatch(queries, targets, lq, lt, wb, mesh=mesh)()


def moves_to_ops(moves_row, length, query: bytes, target: bytes):
    """Decode one reversed 2-bit move row into the aligner op alphabet
    (=/X/I/D codes from racon_tpu.tpu.aligner), vectorised."""
    from racon_tpu.tpu import aligner as al

    mv = moves_row[:length][::-1]                  # forward order
    di = (mv != _MV_LEFT).astype(np.int64)
    dj = (mv != _MV_UP).astype(np.int64)
    i_idx = np.cumsum(di) - 1                      # query index used
    j_idx = np.cumsum(dj) - 1
    qa = np.frombuffer(query, np.uint8)
    ta = np.frombuffer(target, np.uint8)
    eq = np.zeros(len(mv), bool)
    m = mv == _MV_DIAG
    eq[m] = qa[i_idx[m]] == ta[j_idx[m]]
    ops = np.where(m, np.where(eq, al.OP_EQ, al.OP_X),
                   np.where(mv == _MV_UP, al.OP_I, al.OP_D))
    return ops.astype(np.uint8)[::-1]              # reversed, like scan
