"""Single-dispatch batched pairwise alignment: Pallas TPU kernel.

Replaces the lax.scan wavefront kernels (racon_tpu/tpu/aligner.py) on
real TPU backends.  The scan kernels pay per-step XLA overhead over
``lq+lt`` anti-diagonals and one host round-trip per (bucket, chunk);
on the tunneled-TPU deployment target those transfers cost ~100 ms of
latency each.  This kernel aligns EVERY queued pair in one
``pallas_call``: one grid program per pair runs a banded row-wise DP
with the working set in VMEM and emits a compact 2-bit move tape.

Design notes:

* the row loop bound is each pair's REAL query length, so mixing
  short and long pairs in one shape bucket costs only padding memory,
  not padded compute — no per-length bucketing, no bucket dispatch
  loop (the cudaaligner analog queues per-batch,
  src/cuda/cudaaligner.cpp:52-86);
* the band follows the proportional diagonal ``i*tl/ql``, quantized
  to 128 columns so the per-row target slice and previous-row
  realignment are lane-aligned (TPU dynamic lane offsets must be
  128-multiples); an alignment of cost c deviates at most c columns
  from that diagonal, so a tape whose cost fits the band margin is
  exact (Ukkonen) and callers escalate the rest to a wider band;
* no direction tape is materialised in HBM: the forward pass keeps
  one score-row checkpoint every ``_CKPT`` rows in VMEM, and the
  traceback re-derives each 128-row block's directions from its
  checkpoint on demand (classic checkpointed traceback — ~2x compute
  for ~lq*wb/4 bytes of saved HBM traffic per pair);
* the kernel emits 2-bit moves (diag/up/left) packed 16-per-int32;
  the host reconstructs =/X from the sequences vectorised, then RLEs
  to a CIGAR (the reference also finishes CIGARs on the host,
  src/cuda/cudaaligner.cpp:89-103).
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_BIG = 1 << 20
_CKPT = 128                  # rows between score checkpoints
_N_SHIFT = 3                 # band start advances <= 2 quanta per row
_MV_DIAG, _MV_UP, _MV_LEFT, _MV_STOP = 0, 1, 2, 3


def available() -> bool:
    """Opt-in (RACON_TPU_PALLAS_ALIGN=1): on the current deployment
    the measured per-row cost of the wide-band left-chain leaves this
    kernel slower end-to-end than the hybrid scan-ladder + CPU-WFA
    path, so the polisher defaults to that; the kernel is kept (and
    tested) as the single-dispatch option for transfer-latency-bound
    deployments with narrower bands."""
    if os.environ.get("RACON_TPU_NO_PALLAS"):
        return False
    if not os.environ.get("RACON_TPU_PALLAS_ALIGN"):
        return False
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _kernel(ql_ref, tl_ref, q_ref, t_ref, tape_ref, dist_ref,
            ckpt, dirs, regs_s, *,
            lq: int, lt: int, wb: int):
    i_prog = pl.program_id(0)
    ql = ql_ref[i_prog]
    tl = tl_ref[i_prog]
    q = 128
    nck = lq // _CKPT + 1
    tape_w = (lq + lt) // 16 + 1
    big = jnp.int32(_BIG)
    cols = lax.broadcasted_iota(jnp.int32, (1, wb), 1)
    iota_c = lax.broadcasted_iota(jnp.int32, (1, _CKPT), 1)
    nq = jnp.maximum(ql, 1)
    smax_q = (jnp.maximum(tl + 1 - wb, 0) + q - 1) // q

    def sqq(i):
        """Quantized band start for row i: centered on the
        proportional diagonal (symmetric margins >= wb/2 - 128; paths
        deviate either side, unlike the POA layer DP)."""
        return jnp.clip(((i * tl) // nq - (wb // 2)) // q, 0, smax_q)

    # t chars in u space: tb[c] = t[s + c] needs a 128-aligned slice,
    # t_ref is padded by the wrapper so s + wb stays in range
    def t_band(s):
        return t_ref[0, :, pl.ds(pl.multiple_of(s, q), wb)]

    def row_dp(i, pvp, qchars, i0):
        """One DP row.  pvp: previous row D[i-1][s_{i-1} + c] padded
        to wb + shift headroom.  Returns (row_u, dirs_row) where
        row_u[c] = D[i][s_i + c]."""
        sq_i = sqq(i)
        s_i = sq_i * q
        dq = sq_i - sqq(i - 1)
        pu = pvp[:, 0:wb]
        for mm in range(1, _N_SHIFT):
            pu = jnp.where(dq == mm, pvp[:, mm * q: mm * q + wb], pu)
        qc = jnp.sum(jnp.where(iota_c == (i - 1 - i0), qchars, 0))
        tb = t_band(s_i)
        j_u = s_i + cols                 # column of slot c, u space
        sub_u = jnp.where(tb == qc, 0, 1)
        # vert/diag in u space (diag shifts right once, post-min)
        du = pu + sub_u
        vu = pu + 1
        t_u = jnp.minimum(jnp.pad(du, ((0, 0), (1, 0)),
                                  constant_values=big)[:, :wb], vu)
        # boundary column j == 0 (cell D[i][0] = i) and out-of-range
        t_u2 = jnp.where(j_u == 0, i, t_u)
        t_u2 = jnp.where(j_u > tl, big, t_u2)
        # left chain: D[c] = min(T[c], D[c-1] + 1)
        x = t_u2 - j_u
        sh = 1
        while sh < wb:
            x = jnp.minimum(
                x, jnp.pad(x, ((0, 0), (sh, 0)),
                           constant_values=big)[:, :wb])
            sh <<= 1
        row = jnp.minimum(x + j_u, big)
        dshift = jnp.pad(du, ((0, 0), (1, 0)),
                         constant_values=big)[:, :wb]
        dr = jnp.where(
            row == dshift, _MV_DIAG,
            jnp.where(row == vu, _MV_UP, _MV_LEFT)).astype(jnp.int32)
        dr = jnp.where(j_u == 0, _MV_UP, dr)
        return row, dr

    def pad_row(row):
        return jnp.pad(row, ((0, 0), (0, _N_SHIFT * q)),
                       constant_values=big)

    # ---- pass 1: forward scores, checkpoints every _CKPT rows -------
    init = jnp.where(cols > tl, big, cols)       # D[0][j] = j, s_0 = 0
    ckpt[0:1, :] = init

    def blk_fwd(bk, pv):
        i0 = bk * _CKPT
        qchars = q_ref[0, :, pl.ds(pl.multiple_of(i0, _CKPT), _CKPT)]

        def row_step(i, pv):
            row, _ = row_dp(i, pv, qchars, i0)
            return pad_row(row)

        top = jnp.minimum((bk + 1) * _CKPT, ql)
        pv = lax.fori_loop(i0 + 1, top + 1, row_step, pv)

        @pl.when(top == (bk + 1) * _CKPT)
        def _():
            ckpt[pl.ds(bk + 1, 1), :] = pv[:, 0:wb]
        return pv

    nblk = (ql + _CKPT - 1) // _CKPT
    pv = lax.fori_loop(0, nblk, blk_fwd, pad_row(init))

    c_end = tl - sqq(ql) * q
    dist = jnp.sum(jnp.where(cols == jnp.clip(c_end, 0, wb - 1),
                             pv[:, 0:wb], 0))
    dist = jnp.where((c_end < 0) | (c_end >= wb), big, dist)
    dist_ref[0, 0:1, 0:1] = jnp.full((1, 1), dist, jnp.int32)

    # ---- pass 2: checkpointed traceback -----------------------------
    tape_ref[0, :, :] = jnp.zeros((tape_w, 1), jnp.int32)
    # regs: 0 cur word, 1 word count, 2 bit count, 3 i, 4 j
    regs_s[0] = jnp.int32(0)
    regs_s[1] = jnp.int32(0)
    regs_s[2] = jnp.int32(0)
    regs_s[3] = ql
    regs_s[4] = tl

    def emit(mv):
        w = regs_s[0] | (mv << (regs_s[2] * 2))
        nb = regs_s[2] + 1
        full = nb == 16

        @pl.when(full)
        def _():
            tape_ref[0, pl.ds(regs_s[1], 1), 0:1] = jnp.full(
                (1, 1), w, jnp.int32)
            regs_s[0] = jnp.int32(0)
            regs_s[1] = regs_s[1] + 1
            regs_s[2] = jnp.int32(0)

        @pl.when(jnp.logical_not(full))
        def _():
            regs_s[0] = w
            regs_s[2] = nb

    def blk_bwd(bkr, _):
        bk = nblk - 1 - bkr
        i0 = bk * _CKPT

        @pl.when(regs_s[3] > i0)
        def _():
            # rebuild this block's direction rows from its checkpoint
            qchars = q_ref[0, :, pl.ds(pl.multiple_of(i0, _CKPT), _CKPT)]

            def row_step(i, pv):
                row, dr = row_dp(i, pv, qchars, i0)
                dirs[pl.ds(i - 1 - i0, 1), :] = dr
                return pad_row(row)

            top = jnp.minimum(i0 + _CKPT, ql)
            pv0 = pad_row(ckpt[pl.ds(bk, 1), :])
            lax.fori_loop(i0 + 1, top + 1, row_step, pv0)

            # walk while inside this block
            def w_cond2(c):
                i = c[0]
                j = c[1]
                return (i > i0) | ((i0 == 0) & ((i > 0) | (j > 0)))

            def w_body(c):
                i, j = c

                @pl.when(i == 0)
                def _():
                    emit(jnp.int32(_MV_LEFT))

                @pl.when(i > 0)
                def _():
                    s_i = sqq(i) * q
                    cc = jnp.clip(j - s_i, 0, wb - 1)
                    drow = dirs[pl.ds(i - 1 - i0, 1), :]
                    mv = jnp.sum(jnp.where(cols == cc, drow, 0))
                    mv = jnp.where(j <= 0, _MV_UP, mv)
                    emit(mv)
                    regs_s[3] = jnp.where(mv != _MV_LEFT, i - 1, i)
                    regs_s[4] = jnp.where(mv != _MV_UP, j - 1, j)

                ni = jnp.where(i == 0, i, regs_s[3])
                nj = jnp.where(i == 0, j - 1, regs_s[4])
                regs_s[3] = ni
                regs_s[4] = nj
                return ni, nj

            ii, jj = lax.while_loop(w_cond2, w_body,
                                    (regs_s[3], regs_s[4]))
            regs_s[3] = ii
            regs_s[4] = jj
        return 0

    lax.fori_loop(0, nblk, blk_bwd, 0)
    # flush the partial word + record the tape length
    @pl.when(regs_s[2] > 0)
    def _():
        tape_ref[0, pl.ds(regs_s[1], 1), 0:1] = jnp.full(
            (1, 1), regs_s[0], jnp.int32)
        regs_s[1] = regs_s[1] + 1
    dist_ref[0, 1:2, 0:1] = jnp.full(
        (1, 1), regs_s[1] * 16 - jnp.where(regs_s[2] > 0,
                                           16 - regs_s[2], 0),
        jnp.int32)


@functools.partial(jax.jit, static_argnums=(4, 5, 6))
def _align(q, t, ql, tl, lq: int, lt: int, wb: int):
    b = q.shape[0]
    tape_w = (lq + lt) // 16 + 1
    q_i = q.astype(jnp.int32)[:, None, :]
    t_i = jnp.pad(t.astype(jnp.int32), ((0, 0), (0, wb + 128)),
                  constant_values=-1)[:, None, :]
    kern = functools.partial(_kernel, lq=lq, lt=lt, wb=wb)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, 1, lq), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, lt + wb + 128), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, tape_w, 1), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, 1), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((lq // _CKPT + 1, wb), jnp.int32),   # ckpt
            pltpu.VMEM((_CKPT, wb), jnp.int32),             # dirs
            pltpu.SMEM((8,), jnp.int32),                    # regs
        ],
    )
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct((b, tape_w, 1), jnp.int32),
                   jax.ShapeDtypeStruct((b, 8, 1), jnp.int32)),
    )(ql, tl, q_i, t_i)


def align_batch(queries, targets, lq: int, lt: int, wb: int):
    """Align padded pair batches; returns (moves, lens, dists).

    moves: [B, n] uint8 of 2-bit codes in traceback (reversed) order,
    lens: [B] number of valid moves, dists: [B] band edit distance
    (_BIG when the endpoint fell outside the band).
    """
    from racon_tpu.tpu.aligner import encode_batch, _QPAD, _TPAD

    q = encode_batch(queries, lq, _QPAD)
    t = encode_batch(targets, lt, _TPAD)
    ql = np.array([len(s) for s in queries], np.int32)
    tl = np.array([len(s) for s in targets], np.int32)
    tape, meta = _align(q, t, ql, tl, lq, lt, wb)
    tape = np.asarray(tape)[:, :, 0].astype(np.uint32)
    meta = np.asarray(meta)[:, :, 0]
    n = tape.shape[1] * 16
    moves = np.zeros((tape.shape[0], n), np.uint8)
    for sh in range(16):
        moves[:, sh::16] = (tape >> (2 * sh)) & 3
    return moves, meta[:, 1], meta[:, 0]


def moves_to_ops(moves_row, length, query: bytes, target: bytes):
    """Decode one reversed 2-bit move row into the aligner op alphabet
    (=/X/I/D codes from racon_tpu.tpu.aligner), vectorised."""
    from racon_tpu.tpu import aligner as al

    mv = moves_row[:length][::-1]                  # forward order
    di = (mv != _MV_LEFT).astype(np.int64)
    dj = (mv != _MV_UP).astype(np.int64)
    i_idx = np.cumsum(di) - 1                      # query index used
    j_idx = np.cumsum(dj) - 1
    qa = np.frombuffer(query, np.uint8)
    ta = np.frombuffer(target, np.uint8)
    eq = np.zeros(len(mv), bool)
    m = mv == _MV_DIAG
    eq[m] = qa[i_idx[m]] == ta[j_idx[m]]
    ops = np.where(m, np.where(eq, al.OP_EQ, al.OP_X),
                   np.where(mv == _MV_UP, al.OP_I, al.OP_D))
    return ops.astype(np.uint8)[::-1]              # reversed, like scan
