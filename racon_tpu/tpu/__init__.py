"""TPU device path: batched JAX/XLA kernels for the two DP workloads
(overlap alignment, per-window POA consensus) and the mesh-sharded
TPUPolisher that drives them with CPU fallback."""
