"""Device pre-pass for minimizer seeding (r24 internal mapper).

Builds the mapper's 2-bit packed forward / reverse-complement k-mer
match words on the accelerator with the same uint32 bit-twiddling the
WFA kernel uses for its packed wavefront lanes (align_pallas): a
k-pass shift/OR over the base codes, entirely in 32-bit integer ops so
the result is bit-identical to the numpy host path in
racon_tpu.overlap.minimizers — no x64, no floats, no nondeterminism.

This is a pure placement optimization: RACON_TPU_MAP_DEVICE_SEED moves
the word build between host and device, never changes the words, and
is therefore EPOCH_EXCLUDEd (the equality is pinned by
tests/test_overlap_discovery.py).  Sequences are padded up to a bucket
length so jit retraces stay bounded across read-length diversity.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

#: pad sequences to multiples of this many bases before dispatch, so
#: the jitted word builder compiles once per bucket, not per read
BUCKET = 8192


@functools.lru_cache(maxsize=None)
def _builder(k: int):
    import jax
    import jax.numpy as jnp

    def build(codes):
        c = codes.astype(jnp.uint32) & jnp.uint32(3)
        cc = jnp.uint32(3) - c
        nk = codes.shape[0] - k + 1
        fw = jnp.zeros((nk,), dtype=jnp.uint32)
        rv = jnp.zeros((nk,), dtype=jnp.uint32)
        for j in range(k):
            fw = fw | (c[j:j + nk] << jnp.uint32(2 * (k - 1 - j)))
            rv = rv | (cc[j:j + nk] << jnp.uint32(2 * j))
        return fw, rv

    return jax.jit(build)


def kmer_words_device(codes: np.ndarray, k: int
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Device twin of minimizers.kmer_words: returns (fw, rv) uint32
    arrays of length len(codes)-k+1, bit-equal to the host build.

    Pads with invalid-base code 4 (masked to 'A' by the &3, exactly as
    on host; the padded tail words are sliced off before return) so the
    jit cache is keyed by bucket count, not exact length."""
    nk = codes.size - k + 1
    if nk <= 0:
        z = np.empty(0, dtype=np.uint32)
        return z, z
    padded = -(-codes.size // BUCKET) * BUCKET
    if padded != codes.size:
        buf = np.full(padded, 4, dtype=np.uint8)
        buf[:codes.size] = codes
        codes = buf
    fw, rv = _builder(int(k))(codes)
    return (np.asarray(fw)[:nk].astype(np.uint32, copy=False),
            np.asarray(rv)[:nk].astype(np.uint32, copy=False))
