"""Batched global alignment on TPU (cudaaligner-equivalent).

Re-creates, TPU-first, what the reference gets from ClaraGenomicsAnalysis
cudaaligner (reference: src/cuda/cudaaligner.cpp:39-44 ``create_aligner``
with ``global_alignment``, batched ``align_all`` + host CIGAR): a batch
of global (NW, unit-cost / Levenshtein, matching edlib's scoring used at
src/overlap.cpp:205-224) alignments computed in one ``jit``-compiled
call.

Design (TPU-idiomatic, not a CUDA translation):

* fixed-shape padded batches ``[B, L]`` — callers bucket work by length;
* **anti-diagonal wavefront DP**: a ``lax.scan`` over the ``Lq+Lt``
  anti-diagonals; every cell of a diagonal is independent, so each step
  is pure vector work on the VPU across ``B x (Lt+1)`` lanes (no
  intra-row dependency, no associative scan needed);
* direction codes are written to HBM as ``uint8`` (op codes 1-4), the
  score matrix itself is never materialised;
* **traceback runs on device** as a second ``lax.scan`` doing one gather
  per step, vectorised over the batch, so only the compact op tape
  ``[B, Lq+Lt]`` travels device->host (the reference also finishes CIGARs
  on the host, src/cuda/cudaaligner.cpp:89-103);
* op tape -> CIGAR is a tiny numpy RLE on the host.

Alignments whose dimensions exceed the configured cap must be routed to
the CPU aligner by the caller, mirroring the reference's
``exceeded_max_length`` skip statuses (src/cuda/cudaaligner.cpp:64-72).
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from racon_tpu.obs import decision as obs_decision
from racon_tpu.utils.tuning import scan_unroll as _unroll

# base encoding: A/C/G/T -> 0..3, anything else 4; pads never match
_ENCODE = np.full(256, 4, dtype=np.uint8)
for _i, _b in enumerate(b"ACGT"):
    _ENCODE[_b] = _i
_QPAD = 5
_TPAD = 6

# op codes written by the DP/traceback (CIGAR alphabet)
OP_STOP, OP_EQ, OP_X, OP_I, OP_D = 0, 1, 2, 3, 4
_OP_CHARS = np.array([0, ord("="), ord("X"), ord("I"), ord("D")],
                     dtype=np.uint8)

_BIG = np.int32(1 << 20)


def encode_batch(seqs: Sequence[bytes], length: int,
                 pad: int) -> np.ndarray:
    """Encode byte strings into a padded ``[B, length]`` uint8 array."""
    out = np.full((len(seqs), length), pad, dtype=np.uint8)
    for i, s in enumerate(seqs):
        a = np.frombuffer(s, dtype=np.uint8)
        out[i, : len(a)] = _ENCODE[a]
    return out


# 2-bit direction codes stored by the DP (packed 4 cells/byte in HBM);
# match/mismatch is recomputed from the bases during traceback
_DIR_DIAG, _DIR_UP, _DIR_LEFT = 0, 1, 2


@functools.partial(jax.jit, static_argnums=(4, 5))
def _align_kernel(q: jax.Array, t: jax.Array, ql: jax.Array,
                  tl: jax.Array, lq: int, lt: int):
    """Batched unit-cost global alignment.

    q: [B, lq] uint8, t: [B, lt] uint8, ql/tl: [B] int32 true lengths.
    Returns the op tape [B, lq+lt] uint8 (reversed traceback order).
    """
    b = q.shape[0]
    n_diag = lq + lt
    packed_w = (lt + 4) // 4             # packed row width (cols lt+1)
    cols = jnp.arange(lt + 1, dtype=jnp.int32)

    # rq_pad[lt + m] = q[lq - 1 - m], so the slice starting at
    # lt + lq - d puts q[d - 1 - j] at column j (see DP recurrence)
    rq = jnp.flip(q, axis=1)                       # rq[m] = q[lq-1-m]
    rq_pad = jnp.full((b, lq + 2 * lt + 1), _QPAD, dtype=jnp.uint8)
    rq_pad = lax.dynamic_update_slice(rq_pad, rq, (0, lt))

    t_pad = jnp.concatenate(
        [jnp.full((b, 1), _TPAD, dtype=jnp.uint8), t], axis=1)  # t[j-1]

    # derive from a batch input so the carry is batch-varying under
    # shard_map (scan requires carry in/out types to match)
    zero_b = jnp.zeros_like(ql)[:, None]
    init_prev = cols[None, :] + zero_b
    init_prev2 = jnp.zeros((b, lt + 1), jnp.int32) + zero_b

    def step(carry, d):
        prev, prev2 = carry          # diagonals d-1 and d-2
        # cell (i, j), i = d - j: up = D[i-1][j] = prev[j];
        # left = D[i][j-1] = prev[j-1]; diag = D[i-1][j-1] = prev2[j-1]
        left = jnp.concatenate(
            [jnp.full((b, 1), _BIG, jnp.int32), prev[:, :-1]], axis=1)
        diag = jnp.concatenate(
            [jnp.full((b, 1), _BIG, jnp.int32), prev2[:, :-1]], axis=1)
        qd = lax.dynamic_slice(rq_pad, (0, lt + lq - d), (b, lt + 1))
        sub = (qd != t_pad).astype(jnp.int32)
        c_diag = diag + sub
        c_up = prev + 1
        c_left = left + 1
        cur = jnp.minimum(jnp.minimum(c_diag, c_up), c_left)
        # boundary cells of this diagonal: j == 0 -> D[d][0] = d;
        # j == d -> D[0][d] = d
        cur = jnp.where((cols == 0) | (cols == d), d, cur)
        dirs = jnp.where(
            cur == c_diag, jnp.uint8(_DIR_DIAG),
            jnp.where(cur == c_up, jnp.uint8(_DIR_UP),
                      jnp.uint8(_DIR_LEFT)))
        # pack 4 cells/byte (boundary cells are reconstructed from i/j
        # during traceback, so their stored code is irrelevant)
        pad = jnp.zeros((b, packed_w * 4 - (lt + 1)), jnp.uint8)
        dp = jnp.concatenate([dirs, pad], axis=1)
        packed = (dp[:, 0::4] | (dp[:, 1::4] << 2) |
                  (dp[:, 2::4] << 4) | (dp[:, 3::4] << 6))
        return (cur, prev), packed

    (_, _), dir_rows = lax.scan(
        step, (init_prev, init_prev2),
        jnp.arange(1, n_diag + 1, dtype=jnp.int32), unroll=_unroll(1))
    # dir_rows: [n_diag, B, packed_w] for diagonals 1..n_diag

    lanes = jnp.arange(b)
    q_pad1 = jnp.concatenate(
        [jnp.full((b, 1), _QPAD, jnp.uint8), q], axis=1)   # q[i-1] at i

    # device traceback: walk from (ql, tl) to (0, 0)
    def tb_step(carry, _):
        i, j = carry
        done = (i == 0) & (j == 0)
        byte = dir_rows[i + j - 1, lanes, j >> 2]
        code = (byte >> ((j & 3) * 2)) & 3
        # boundary rows/columns force the only legal move
        code = jnp.where(i == 0, jnp.uint8(_DIR_LEFT), code)
        code = jnp.where(j == 0, jnp.uint8(_DIR_UP), code)
        qc = q_pad1[lanes, i]
        tc = t_pad[lanes, j]
        op = jnp.where(
            code == _DIR_DIAG,
            jnp.where(qc == tc, OP_EQ, OP_X),
            jnp.where(code == _DIR_UP, OP_I, OP_D)).astype(jnp.uint8)
        op = jnp.where(done, jnp.uint8(OP_STOP), op)
        di = jnp.where((op == OP_EQ) | (op == OP_X) | (op == OP_I), 1, 0)
        dj = jnp.where((op == OP_EQ) | (op == OP_X) | (op == OP_D), 1, 0)
        return (i - di, j - dj), op

    (_, _), ops = lax.scan(tb_step, (ql, tl), None, length=n_diag,
                        unroll=_unroll(1))
    return jnp.transpose(ops)  # [B, n_diag] reversed op tape


@functools.partial(jax.jit, static_argnums=(4, 5, 6))
def _banded_align_kernel(q: jax.Array, t: jax.Array, ql: jax.Array,
                         tl: jax.Array, lq: int, lt: int, hw: int):
    """Banded batched unit-cost global alignment (half-width ``hw``).

    Restricts the DP to |j - i| <= hw (Ukkonen band): any alignment of
    cost <= hw stays inside, so a result whose tape cost is <= hw is
    exact; callers escalate the rest to a wider band (the edlib
    band-doubling strategy, reference CPU analog
    racon_tpu/native/align.cpp, batched for the TPU).  Per anti-diagonal
    step the state is the ``hw+2``-wide band slice instead of the full
    ``lt+1`` row, cutting both VPU work and the direction-tape HBM
    traffic by ~``lt/hw``.

    Returns the reversed op tape [B, lq+lt] uint8 like _align_kernel.
    Lanes with |tl - ql| > hw or tape cost > hw must be re-run wider.
    """
    b = q.shape[0]
    n_diag = lq + lt
    wb = hw + 2                       # band slot width
    packed_w = (wb + 3) // 4
    slots = jnp.arange(wb, dtype=jnp.int32)
    big = jnp.int32(_BIG)

    # jlo(d): first in-band column j on anti-diagonal d
    def jlo_f(d):
        return jnp.maximum(0, (d - hw + 1) >> 1)

    rq = jnp.flip(q, axis=1)                       # rq[m] = q[lq-1-m]
    pad_rq = lt + wb + 2
    rq_pad = jnp.full((b, lq + 2 * pad_rq), _QPAD, dtype=jnp.uint8)
    rq_pad = lax.dynamic_update_slice(rq_pad, rq, (0, pad_rq))
    t_pad = jnp.full((b, lt + wb + 2), _TPAD, dtype=jnp.uint8)
    t_pad = lax.dynamic_update_slice(t_pad, t, (0, 1))  # t_pad[x]=t[x-1]

    zero_b = jnp.zeros_like(ql)[:, None]

    def padded(x):
        edge = jnp.full((b, 1), big, jnp.int32)
        return jnp.concatenate([edge, x, edge], axis=1)

    # diagonal 0 holds only cell (0,0) at slot 0
    prev_init = jnp.where(slots[None, :] == 0, 0, big) + zero_b
    prev2_init = jnp.full((b, wb), big, jnp.int32) + zero_b

    def step(carry, d):
        prev, prev2 = carry           # padded [B, wb+2]: diags d-1, d-2
        jlo = jlo_f(d)
        d1 = jlo - jlo_f(d - 1)       # slot shift vs diag d-1 (0/1)
        d2 = jlo - jlo_f(d - 2)       # slot shift vs diag d-2 (0/1)
        up = lax.dynamic_slice(prev, (0, 1 + d1), (b, wb))
        left = lax.dynamic_slice(prev, (0, d1), (b, wb))
        diag = lax.dynamic_slice(prev2, (0, d2), (b, wb))
        j_abs = jlo + slots           # [wb]
        i_abs = d - j_abs
        qd = lax.dynamic_slice(rq_pad, (0, pad_rq + lq - d + jlo),
                               (b, wb))
        td = lax.dynamic_slice(t_pad, (0, jlo), (b, wb))
        sub = (qd != td).astype(jnp.int32)
        c_diag = diag + sub
        c_up = up + 1
        c_left = left + 1
        cur = jnp.minimum(jnp.minimum(c_diag, c_up), c_left)
        cur = jnp.where((j_abs == 0) | (i_abs == 0), d, cur)
        invalid = (j_abs > lt) | (i_abs > lq) | (i_abs < 0)
        cur = jnp.where(invalid[None, :], big, jnp.minimum(cur, big))
        dirs = jnp.where(
            cur == c_diag, jnp.uint8(_DIR_DIAG),
            jnp.where(cur == c_up, jnp.uint8(_DIR_UP),
                      jnp.uint8(_DIR_LEFT)))
        pad = jnp.zeros((b, packed_w * 4 - wb), jnp.uint8)
        dp = jnp.concatenate([dirs, pad], axis=1)
        packed = (dp[:, 0::4] | (dp[:, 1::4] << 2) |
                  (dp[:, 2::4] << 4) | (dp[:, 3::4] << 6))
        return (padded(cur), prev), packed

    (_, _), dir_rows = lax.scan(
        step, (padded(prev_init), padded(prev2_init)),
        jnp.arange(1, n_diag + 1, dtype=jnp.int32), unroll=_unroll(1))
    # dir_rows: [n_diag, B, packed_w] for diagonals 1..n_diag

    lanes = jnp.arange(b)
    q_pad1 = jnp.concatenate(
        [jnp.full((b, 1), _QPAD, jnp.uint8), q], axis=1)

    def tb_step(carry, _):
        i, j = carry
        done = (i == 0) & (j == 0)
        d = i + j
        s = jnp.clip(j - jnp.maximum(0, (d - hw + 1) >> 1), 0, wb - 1)
        byte = dir_rows[jnp.maximum(d - 1, 0), lanes, s >> 2]
        code = (byte >> ((s & 3) * 2)) & 3
        code = jnp.where(i == 0, jnp.uint8(_DIR_LEFT), code)
        code = jnp.where(j == 0, jnp.uint8(_DIR_UP), code)
        qc = q_pad1[lanes, i]
        tc = t_pad[lanes, j]
        op = jnp.where(
            code == _DIR_DIAG,
            jnp.where(qc == tc, OP_EQ, OP_X),
            jnp.where(code == _DIR_UP, OP_I, OP_D)).astype(jnp.uint8)
        op = jnp.where(done, jnp.uint8(OP_STOP), op)
        di = jnp.where((op == OP_EQ) | (op == OP_X) | (op == OP_I), 1, 0)
        dj = jnp.where((op == OP_EQ) | (op == OP_X) | (op == OP_D), 1, 0)
        return (i - di, j - dj), op

    (_, _), ops = lax.scan(tb_step, (ql, tl), None, length=n_diag,
                        unroll=_unroll(1))
    return jnp.transpose(ops)


# band-doubling ladder (half-widths); the final fallback is the
# unbanded kernel — mirrors edlib's iterative widening, batched
BAND_LADDER = (512, 2048, 8192)


def _pow2_batch(n: int, lo: int = 8) -> int:
    from racon_tpu.utils.tuning import pow2_at_least
    return pow2_at_least(n, lo)


def band_align_batch(queries: Sequence[bytes], targets: Sequence[bytes],
                     blq: int, blt: int, dispatch=None,
                     allow_full: bool = True,
                     mem_budget: int = 2 << 30,
                     need_ratio: float = 0.2):
    """Align a bucket of pairs via the banded ladder.

    Each pair starts at the narrowest rung that could plausibly hold
    its alignment (>= |len difference| and >= ``need_ratio`` of its
    larger dimension — the default 20% is ONT-scale divergence, and
    callers that probed the dataset pass the measured ratio instead,
    so a guaranteed-to-fail narrow pass is skipped); lanes whose tape
    cost is <= the half-width are exact (Ukkonen) and accepted, the
    rest re-run wider.  Lanes still unresolved past the ladder run
    the unbanded kernel when ``allow_full``, else are returned for
    the caller's CPU fallback — the reference's
    exceeded_max_alignment_difference contract
    (src/cuda/cudaaligner.cpp:64-72).

    ``dispatch`` overrides the kernel call (used for mesh sharding);
    it receives (q, t, ql, tl, lq, lt, hw) with hw=0 meaning unbanded.

    Returns (ops, cells, unresolved): the reversed op tape
    [n, blq+blt] uint8, the number of DP cells actually computed (band
    cells, not full matrices — the honest throughput denominator), and
    the indices whose rows in ``ops`` are not valid (empty when
    ``allow_full``).
    """
    n = len(queries)
    ql_all = np.array([len(s) for s in queries], dtype=np.int64)
    tl_all = np.array([len(s) for s in targets], dtype=np.int64)
    ops_out = np.zeros((n, blq + blt), dtype=np.uint8)
    cells = 0
    # smallest plausible rung per lane: the band must hold the length
    # difference plus the divergence-scaled cost estimate
    need = np.maximum(
        np.abs(ql_all - tl_all),
        (np.maximum(ql_all, tl_all)
         * min(max(need_ratio, 0.02), 0.67)).astype(np.int64))

    if dispatch is None:
        def dispatch(q, t, ql, tl, lq, lt, hw):
            if hw:
                return _banded_align_kernel(q, t, ql, tl, lq, lt, hw)
            return _align_kernel(q, t, ql, tl, lq, lt)

    def run_one(idx, hw):
        nonlocal cells
        bb = _pow2_batch(len(idx))
        qs = [queries[i] for i in idx]
        ts = [targets[i] for i in idx]
        q = encode_batch(qs + [b""] * (bb - len(idx)), blq, _QPAD)
        t = encode_batch(ts + [b""] * (bb - len(idx)), blt, _TPAD)
        ql = np.zeros(bb, np.int32)
        ql[:len(idx)] = ql_all[idx]
        tl = np.zeros(bb, np.int32)
        tl[:len(idx)] = tl_all[idx]
        ops = np.asarray(dispatch(q, t, ql, tl, blq, blt, hw))
        cells += bb * (blq + blt) * ((hw + 2) if hw else (blt + 1))
        return ops[:len(idx)]

    def run(idx, hw):
        # chunk by THIS rung's direction-tape footprint: a wide rung
        # (8192) costs ~16x the narrow one per lane, so a fixed lane
        # count would exhaust HBM on divergent workloads
        width = (hw + 5) // 4 if hw else (blt + 4) // 4
        per_lane = (blq + blt) * width
        cap = max(1, int(mem_budget // per_lane))
        cap = 1 << (cap.bit_length() - 1)   # pow2: padding respects it
        outs = [run_one(idx[k:k + cap], hw)
                for k in range(0, len(idx), cap)]
        return np.concatenate(outs) if len(outs) > 1 else outs[0]

    pending = np.arange(n)
    for hw in BAND_LADDER:
        if len(pending) == 0 or hw >= max(blq, blt):
            break
        idx = pending[need[pending] <= hw]
        if len(idx) == 0:
            continue
        ops = run(idx, hw)
        cost = ((ops != OP_STOP) & (ops != OP_EQ)).sum(axis=1)
        ok = cost <= hw
        ops_out[idx[ok]] = ops[ok]
        pending = np.setdiff1d(pending, idx[ok], assume_unique=True)
        # ladder-path exemplar (r16): pairs whose measured tape cost
        # broke this rung's certificate re-run wider — telemetry
        # only, the retry itself is unchanged
        n_retry = int(len(idx) - int(ok.sum()))
        if n_retry:
            obs_decision.DECISIONS.record(
                "align_retry", engine="band", rung=int(hw),
                pairs=n_retry)
    # past the ladder, the unbanded kernel is exact for everything; it
    # is only prohibitive on the largest buckets, where callers with
    # allow_full=False route the (rare) ultra-divergent pairs to the
    # CPU aligner instead (the reference's
    # exceeded_max_alignment_difference contract).  The full kernel's
    # tape is (blq+blt)*ceil((blt+1)/4) bytes/lane — ~4x a 2048-band —
    # so dispatch it in budget-sized slices rather than at the
    # caller's band-sized chunking.
    if len(pending) and (allow_full
                         or max(blq, blt) <= max(BAND_LADDER)):
        # run() self-chunks by the full kernel's tape footprint
        ops_out[pending] = run(pending, 0)
        pending = pending[:0]
    if len(pending):
        obs_decision.DECISIONS.record("align_cpu_fallthrough",
                                      pairs=int(len(pending)))
    return ops_out, cells, pending


# op code -> "MIDNSHP=X" index for the breaking-points fast path
_RUN_CODE = np.array([0, 7, 8, 1, 2], dtype=np.int64)


def ops_to_runs(ops_row: np.ndarray):
    """RLE a reversed op tape row into (lengths, codes) arrays in the
    Overlap.cigar_runs convention ("MIDNSHP=X" indices), skipping the
    CIGAR string entirely."""
    fwd = ops_row[ops_row != OP_STOP][::-1]
    if fwd.size == 0:
        return (np.empty(0, np.int64), np.empty(0, np.int64))
    change = np.flatnonzero(np.diff(fwd)) + 1
    starts = np.concatenate(([0], change))
    ends = np.concatenate((change, [fwd.size]))
    return ((ends - starts).astype(np.int64),
            _RUN_CODE[fwd[starts].astype(np.int64)])


def ops_to_cigar(ops_row: np.ndarray) -> str:
    """RLE a reversed op tape row into a standard =/X/I/D CIGAR."""
    ops_row = ops_row[ops_row != OP_STOP][::-1]
    if ops_row.size == 0:
        return ""
    change = np.flatnonzero(np.diff(ops_row)) + 1
    starts = np.concatenate(([0], change))
    ends = np.concatenate((change, [ops_row.size]))
    return "".join(f"{e - s}{chr(_OP_CHARS[ops_row[s]])}"
                   for s, e in zip(starts, ends))


class TPUBatchAligner:
    """Batched aligner with fixed-shape bucketed dispatch.

    Mirrors CUDABatchAligner's add/align/get contract
    (src/cuda/cudaaligner.hpp:34-62): ``add`` rejects pairs beyond the
    configured maximum (caller falls back to CPU), ``align_all`` runs the
    device kernel, ``cigars`` returns host CIGAR strings.
    """

    def __init__(self, max_query_length: int, max_target_length: int,
                 max_alignments: int):
        self.max_q = int(max_query_length)
        self.max_t = int(max_target_length)
        self.max_alignments = int(max_alignments)
        self.queries: List[bytes] = []
        self.targets: List[bytes] = []
        self._ops: np.ndarray | None = None
        self.distances: np.ndarray | None = None

    def add(self, query: bytes, target: bytes) -> bool:
        """Queue one pair; False if it must go to the CPU path."""
        if len(self.queries) >= self.max_alignments:
            return False
        if len(query) > self.max_q or len(target) > self.max_t:
            return False
        self.queries.append(query)
        self.targets.append(target)
        return True

    def __len__(self) -> int:
        return len(self.queries)

    def align_all(self) -> None:
        if not self.queries:
            return
        lq = max(len(s) for s in self.queries)
        lt = max(len(s) for s in self.targets)
        # round bucket dims up to multiples of 128 (TPU lane width) to
        # bound the number of compiled kernel variants
        lq = min((lq + 127) // 128 * 128, self.max_q)
        lt = min((lt + 127) // 128 * 128, self.max_t)
        self._ops, _, _ = band_align_batch(self.queries, self.targets,
                                           lq, lt)
        # edit distance = every non-'=' op on the tape
        self.distances = np.sum(
            (self._ops != OP_STOP) & (self._ops != OP_EQ),
            axis=1).astype(np.int32)

    def cigars(self) -> List[str]:
        assert self._ops is not None, "align_all() not called"
        return [ops_to_cigar(self._ops[i])
                for i in range(len(self.queries))]

    def reset(self) -> None:
        self.queries = []
        self.targets = []
        self._ops = None
        self.distances = None


def align_pairs(pairs: Sequence[Tuple[bytes, bytes]],
                max_len: int = 1 << 14) -> List[str]:
    """Convenience one-shot batched alignment (used by tests/bench)."""
    aligner = TPUBatchAligner(max_len, max_len, len(pairs))
    for q, t in pairs:
        ok = aligner.add(q, t)
        assert ok, "pair exceeds max_len"
    aligner.align_all()
    return aligner.cigars()
