"""Job-agnostic device executor: cross-job continuous batching.

The serve daemon (racon_tpu/serve) runs up to ``RACON_TPU_SERVE_JOBS``
polishing jobs concurrently, but before this module each job's
polisher owned its own slice of the device FIFO: every megabatch --
POA windows through ``TPUPoaBatchEngine.consensus_batch_async``,
align pairs through ``align_pallas.wfa_dispatch``/``align_dispatch``
-- was built from ONE job's ready work.  At the many-small-jobs
operating point the device therefore runs half-empty batches while
other jobs' ready windows wait in their own queues (the reference
racon-gpu wins precisely by filling its fixed cudapoa batch caps).

This module inverts that ownership.  ``DeviceExecutor`` is a
process-wide service that accepts *tagged work units* from any number
of concurrent jobs (tenants), fuses compatible units into shared
megabatches, dispatches them through the unchanged engine/Pallas
paths, and demuxes results back to each submitter by position.

Byte contract
-------------
Fusion must never change any job's output bytes.  That holds because
every fused path is *per-item independent*:

* POA: a window's consensus depends only on that window's sequences
  (graph build, bucketed kernel run, and traceback are all per-window;
  batch maxima only change padding, which is masked).  The engine is
  result-stateless -- config + inputs only -- so SHARING one engine
  across jobs is safe, and a fused batch returns, for each unit, the
  exact sequence of per-window results the unit's own dispatch would
  have produced, in the unit's own order.
* Align: ``wfa_dispatch``/``align_dispatch`` batch independent
  per-pair lanes (padding via ``pad_pairs``); concatenating two
  units' pairs and slicing the stacked result rows is identical to
  two separate dispatches.

What is NOT fused: the CPU scan path (``band_align_batch`` under
``_align_chunk``) -- its internal chunking/memory heuristics depend
on batch composition, so it stays per-job.

Compatibility buckets
---------------------
Units only fuse when a shared dispatch is exactly equivalent to the
separate ones: POA units must share the engine (full scoring/cap
config, same device mesh) and ``trim``; align units must share the
rung geometry (bucket dims, error cap / band width) and mesh.  Mixed
window types inside one fused POA batch are fine -- the engine
already splits per type internally.

Fusion window and fairness
--------------------------
A dispatcher thread holds the head unit of a bucket for up to
``RACON_TPU_FUSE_WAIT_MS`` (default 5 ms) waiting for batchmates, or
less if the bucket reaches its occupancy target (the largest
participating unit's device batch cap -- fusing never exceeds the
memory envelope any single participant already sized for).  Batch
formation is weighted deficit-round-robin over tenants with pending
units, and a per-tenant in-flight quota
(``RACON_TPU_SERVE_TENANT_QUOTA``, default 2 outstanding device
submissions) keeps one streaming mega-job from starving small
tenants: an at-quota tenant's units are held back while any other
tenant has pending work (the quota is work-conserving -- alone, a
tenant runs unthrottled).

With ``RACON_TPU_FUSE_ADAPT=1`` (r22, default off) the dispatcher
tunes the window online from observed batch occupancy: an occupancy
EWMA below ~0.55 (batches dispatching underfilled at window expiry)
grows the wait multiplicatively, above ~0.9 (batches filling before
the window binds) shrinks it, always clamped to
[0, ``RACON_TPU_FUSE_WAIT_MS``] with a dead-band hysteresis between.
The current value exports as the ``fusion_wait_ms`` gauge.  The
window is pure policy — it decides WHEN a bucket dispatches, never
what the fused batch computes — so output bytes are identical with
adaptation on or off.

Single-tenant degradation
-------------------------
With fusion disabled (``RACON_TPU_FUSE=0``) or fewer than two
registered tenants (the standalone CLI registers none), submissions
take a synchronous passthrough: the direct engine / align_pallas call
on the calling thread with the caller's pool -- bit-for-bit and
thread-for-thread identical to the pre-executor code.
``RACON_TPU_FUSE_FORCE=1`` routes even single-tenant work through the
dispatcher (same bytes, different threading) so the fused code path
can be pinned under the full tier-1 suite (ci/cpu/fusion_tier1.sh).

Crash containment
-----------------
A failure while dispatching or collecting a fused batch falls back to
retrying each unit individually; a unit whose own retry fails raises
in that unit's ``collect()`` only, so one job's poisoned window can
never fail its batchmates.

Observability: ``fused_megabatches`` / ``fusion_units_fused``
counters, a ``fusion_occupancy`` histogram (fused size / occupancy
target), and per-tenant queue-wait SLO histograms
(``serve_tenant_wait_s.<tenant>``) in the process registry; the
serve daemon surfaces ``DeviceExecutor.stats()`` under ``fusion`` in
its ``metrics``/``top`` telemetry.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import OrderedDict

from racon_tpu.obs import REGISTRY
from racon_tpu.obs import context as obs_context
from racon_tpu.obs import flight as obs_flight
from racon_tpu.obs.trace import TRACER

_mono = time.monotonic

#: flow-event ids linking a unit's submit instant to the fused
#: dispatch span it rode (Chrome trace ``id`` field)
_FLOW_IDS = itertools.count(1)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def fuse_enabled() -> bool:
    return os.environ.get("RACON_TPU_FUSE", "1") != "0"


def fuse_forced() -> bool:
    return os.environ.get("RACON_TPU_FUSE_FORCE", "0") == "1"


def fuse_wait_s() -> float:
    return max(0.0, _env_float("RACON_TPU_FUSE_WAIT_MS", 5.0)) / 1e3


def fuse_adapt_on() -> bool:
    """Online fusion-window tuning (r22): the dispatcher adjusts its
    fuse wait between 0 and ``RACON_TPU_FUSE_WAIT_MS`` from observed
    batch occupancy.  Policy plane only — the window decides WHEN a
    bucket dispatches, never what the fused batch computes, so bytes
    stay pinned with the knob on or off."""
    return os.environ.get("RACON_TPU_FUSE_ADAPT", "0") == "1"


#: adaptive-window controller constants: EMA smoothing, the
#: occupancy dead band (hysteresis — no adjustment inside it), the
#: multiplicative step sizes, and dispatches between adjustments
_ADAPT_ALPHA = 0.3
_ADAPT_BAND = (0.55, 0.9)
_ADAPT_UP = 1.25
_ADAPT_DOWN = 0.8
_ADAPT_EVERY = 4


def tenant_quota() -> int:
    """Max outstanding device submissions per tenant while other
    tenants have pending work; <= 0 disables the quota."""
    return _env_int("RACON_TPU_SERVE_TENANT_QUOTA", 2)


def _mesh_key(mesh):
    if mesh is None:
        return None
    try:
        return tuple(str(d) for d in mesh.devices.flat)
    except AttributeError:
        return tuple(str(d) for d in getattr(mesh, "devices", ()))


# ---------------------------------------------------------------------------
# work units
# ---------------------------------------------------------------------------

class _Unit:
    """One tenant's submission: a POA window batch or an align pair
    batch, fused whole (never split) into a shared dispatch."""

    __slots__ = ("kind", "tenant", "payload", "size", "cap", "pool",
                 "t_submit", "done", "fused", "lo", "hi", "retry",
                 "fuse_dispatch", "flow_id", "jobs")

    def __init__(self, kind, tenant, payload, size, cap, pool):
        self.kind = kind            # "poa" | "wfa" | "band"
        self.tenant = tenant or "default"
        self.payload = payload
        self.size = size
        self.cap = cap              # submitter's own device batch cap
        self.pool = pool
        self.t_submit = _mono()
        self.done = threading.Event()
        self.fused = None           # _FusedDispatch once dispatched
        self.lo = self.hi = 0       # slice of the fused batch
        self.retry = None           # per-unit fallback dispatch fn
        self.flow_id = 0            # trace flow-event id
        self.jobs = ()              # serve job ids this unit belongs to


class _FusedDispatch:
    """One shared device dispatch covering >= 1 units.  The collect
    is memoized under a lock: the first unit to collect runs it, the
    rest read the cached rows.  A failure poisons only the shared
    attempt -- each unit then retries individually (crash
    containment)."""

    def __init__(self, collect, n_items, units):
        self._collect = collect
        self._lock = threading.Lock()
        self._result = None
        self._error = None
        self._ran = False
        self.n_items = n_items
        self.units = units

    def result(self):
        with self._lock:
            if not self._ran:
                try:
                    self._result = self._collect()
                except BaseException as exc:  # containment boundary
                    self._error = exc
                self._ran = True
            if self._error is not None:
                raise _FusedBatchError(self._error)
            return self._result

    def device_s(self) -> float:
        ds = getattr(self._collect, "device_s", None)
        try:
            return float(ds()) if callable(ds) else 0.0
        except Exception:
            return 0.0


class _FusedBatchError(Exception):
    """Shared dispatch failed; units fall back to individual retries."""

    def __init__(self, cause):
        super().__init__(str(cause))
        self.cause = cause


# ---------------------------------------------------------------------------
# POA engine handle
# ---------------------------------------------------------------------------

class PoaEngineHandle:
    """Per-polisher view of a shared ``TPUPoaBatchEngine``.

    Mimics the slice of the engine API the polisher consumes
    (``will_dispatch_async``, ``consensus_batch_async`` and the
    observability counters) while the engine itself is shared across
    jobs.  Counters are reported as deltas from a creation-time
    snapshot; under concurrent sharing a delta can attribute another
    job's dispatch to this handle -- the same documented one-registry
    ambiguity serve/session.py accepts for the process-wide shelf
    counters.  The numbers feed logs, metrics and calibration (and
    calibration is frozen in serve), never output bytes.
    """

    def __init__(self, executor, engine, tenant, cap):
        self._ex = executor
        self._eng = engine
        self.tenant = tenant
        self.cap = max(0, int(cap))
        self._base = {
            "device_s": engine.device_s,
            "cells": engine.cells,
            "n_rounds": engine.n_rounds,
            "n_skipped_layers": engine.n_skipped_layers,
            "reject": dict(engine.reject_counts),
            "phase": dict(engine.phase_walls),
        }

    # -- engine API the polisher drives ------------------------------------
    def will_dispatch_async(self, windows) -> bool:
        return self._eng.will_dispatch_async(windows)

    def consensus_batch_async(self, windows, trim, pool=None):
        return self._ex.submit_poa(self, windows, trim, pool)

    # -- observability deltas ----------------------------------------------
    @property
    def device_s(self):
        return self._eng.device_s - self._base["device_s"]

    @property
    def cells(self):
        return self._eng.cells - self._base["cells"]

    @property
    def n_rounds(self):
        return self._eng.n_rounds - self._base["n_rounds"]

    @property
    def n_skipped_layers(self):
        return (self._eng.n_skipped_layers
                - self._base["n_skipped_layers"])

    @property
    def reject_counts(self):
        base = self._base["reject"]
        return {k: v - base.get(k, 0)
                for k, v in self._eng.reject_counts.items()}

    @property
    def phase_walls(self):
        base = self._base["phase"]
        return {k: v - base.get(k, 0.0)
                for k, v in self._eng.phase_walls.items()}


# ---------------------------------------------------------------------------
# the executor
# ---------------------------------------------------------------------------

class DeviceExecutor:
    """Process-wide device dispatch service (see module docstring)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._engines = {}                  # config key -> engine
        self._engine_lock = threading.Lock()
        self._buckets = OrderedDict()       # fuse key -> [_Unit]
        self._n_pending = 0
        self._tenants = {}                  # name -> ref count
        self._weights = {}                  # name -> DRR weight
        self._deficit = {}                  # name -> DRR deficit
        self._inflight = {}                 # name -> device submissions
        self._dispatcher = None
        self._shutdown = False
        self._own_pool = None
        # r22 adaptive fusion window: current wait (None = seed from
        # the env ceiling on first use), occupancy EMA, dispatches
        # since the last adjustment
        self._adapt_wait_s = None
        self._adapt_occ = None
        self._adapt_since = 0

    # -- tenancy ------------------------------------------------------------
    def register_tenant(self, name: str, weight: float = 1.0):
        name = str(name or "default")
        with self._cond:
            self._tenants[name] = self._tenants.get(name, 0) + 1
            self._weights[name] = max(0.1, float(weight))
            self._inflight.setdefault(name, 0)

    def release_tenant(self, name: str):
        name = str(name or "default")
        with self._cond:
            n = self._tenants.get(name, 0) - 1
            if n > 0:
                self._tenants[name] = n
            else:
                self._tenants.pop(name, None)
                self._weights.pop(name, None)
                self._deficit.pop(name, None)
                if not self._inflight.get(name, 0):
                    self._inflight.pop(name, None)
            self._cond.notify_all()

    def _fusion_active(self) -> bool:
        if not fuse_enabled():
            return False
        return fuse_forced() or len(self._tenants) >= 2

    # -- engines ------------------------------------------------------------
    def _make_engine(self, match, mismatch, gap, vcap, pcap, lcap,
                     kcap, max_depth, banded, mesh):
        # monkeypatch seam for tests (stub engines)
        from racon_tpu.tpu.poa import TPUPoaBatchEngine

        return TPUPoaBatchEngine(match, mismatch, gap, vcap=vcap,
                                 pcap=pcap, lcap=lcap, kcap=kcap,
                                 max_depth=max_depth, banded=banded,
                                 mesh=mesh)

    def poa_handle(self, match, mismatch, gap, vcap, pcap, lcap,
                   kcap, max_depth, banded, mesh, tenant=None,
                   cap=0) -> PoaEngineHandle:
        """A shared engine for this config (AOT-shelf shapes are keyed
        by the same tuple, so sharing also shares warm kernels)."""
        key = (match, mismatch, gap, vcap, pcap, lcap, kcap,
               max_depth, bool(banded), _mesh_key(mesh))
        with self._engine_lock:
            engine = self._engines.get(key)
            if engine is None:
                engine = self._make_engine(match, mismatch, gap, vcap,
                                           pcap, lcap, kcap, max_depth,
                                           banded, mesh)
                self._engines[key] = engine
        handle = PoaEngineHandle(self, engine, tenant, cap)
        # the engine-identity tuple doubles as the result cache's
        # device-space config key (racon_tpu/cache/keying.poa_key)
        handle.cfg_key = key
        return handle

    # -- submissions ---------------------------------------------------------
    def _tag_unit(self, unit: _Unit) -> None:
        """Attribute the unit to its serve job(s) — the submitting
        thread's job context when present, else every job currently
        running under the unit's tenant (polisher pool threads carry
        no contextvar) — and emit the flow-start event that Perfetto
        ties to the fused dispatch this unit ends up riding.
        Observability only; no-op overhead outside the daemon."""
        ctx = obs_context.current()
        if ctx is not None:
            unit.jobs = (ctx.job_id,)
        else:
            unit.jobs = tuple(obs_context.jobs_for_tenant(unit.tenant))
        unit.flow_id = next(_FLOW_IDS)
        if TRACER.capturing:
            jobs = list(unit.jobs)
            TRACER.add_instant(
                f"executor.submit.{unit.kind}", cat="fuse",
                args={"tenant": unit.tenant, "size": unit.size,
                      "flow": unit.flow_id}, jobs=jobs)
            TRACER.add_flow(f"executor.unit.{unit.kind}",
                            unit.flow_id, "s", jobs=jobs)

    # -- result cache (r18) --------------------------------------------------
    def _cache_partition(self, kind, n, key_fn):
        """Split ``n`` items into cache hits and misses BEFORE any
        device dispatch.  Returns ``None`` when the cache is off,
        else ``(cache, keys, hits, miss)`` where ``keys[i]`` is None
        for uncacheable items (they ride the miss dispatch but are
        never filled), ``hits`` maps item index -> decoded value and
        ``miss`` lists indices to compute.  Hits never occupy
        megabatch slots — an all-hit submission touches neither the
        fusion queue nor the engine."""
        from racon_tpu import cache as rcache

        if n == 0 or not rcache.enabled():
            return None
        cache = rcache.result_cache()
        epoch = rcache.keying.engine_epoch()
        keys, hits, miss = [None] * n, {}, []
        for i in range(n):
            k = key_fn(i, epoch)
            if k is None:
                miss.append(i)
                continue
            keys[i] = k
            v = cache.get(k)
            if v is rcache.MISS:
                miss.append(i)
            else:
                hits[i] = v
        if hits:
            obs_flight.FLIGHT.record(
                "cache_hit", unit_kind=kind, hits=len(hits),
                misses=len(miss), items=n)
        return cache, keys, hits, miss

    def submit_poa(self, handle: PoaEngineHandle, windows, trim,
                   pool=None):
        """Returns a zero-arg collect closure, like the engine's.

        Consults the content-addressed result cache first: cached
        windows are served from memory, only the misses are
        dispatched (fused or passthrough), and the collect closure
        merges + fills.  ``collect.cache_hits`` tells the polisher
        to exclude the batch from calibration measurement — a
        partially-served batch's wall says nothing about device
        rates (policy only; bytes are identical either way)."""
        windows = list(windows)
        from racon_tpu.cache import keying as _keying

        cfg = getattr(handle, "cfg_key", None)
        part = None if cfg is None else self._cache_partition(
            "poa", len(windows),
            lambda i, epoch: (
                _keying.poa_key("dev", cfg, trim, windows[i], epoch)
                if len(windows[i].sequences) >= 3 else None))
        if part is None:
            return self._submit_poa_raw(handle, windows, trim, pool)
        cache, keys, hits, miss = part
        inner = self._submit_poa_raw(
            handle, [windows[i] for i in miss], trim, pool) \
            if miss else None

        def collect():
            out = [None] * len(windows)
            if inner is not None:
                rows = inner()
                for j, i in enumerate(miss):
                    out[i] = rows[j]
                    if keys[i] is not None:
                        cache.put(keys[i], rows[j])
            for i, v in hits.items():
                out[i] = v
            return out

        collect.cache_hits = len(hits)
        return collect

    def _submit_poa_raw(self, handle: PoaEngineHandle, windows, trim,
                        pool=None):
        engine = handle._eng
        if not self._fusion_active():
            return engine.consensus_batch_async(windows, trim,
                                                pool=pool)
        key = ("poa", id(engine), bool(trim))
        unit = _Unit("poa", handle.tenant, list(windows),
                     len(windows), handle.cap, pool)
        self._tag_unit(unit)
        unit.retry = lambda u: engine.consensus_batch_async(
            u.payload, trim, pool=u.pool or self._pool())
        self._enqueue(key, unit, lambda units, pool: (
            engine.consensus_batch_async(
                [w for u in units for w in u.payload], trim,
                pool=pool),
            sum(u.size for u in units)))

        def collect(u=unit):
            rows, whole = self._collect_unit(u)
            return rows if whole else rows[u.lo:u.hi]

        return collect

    def align_wfa(self, queries, targets, lq, emax, mesh=None,
                  tenant=None):
        """Cache-aware WFA pair dispatch: cached pairs are served
        from memory, only miss pairs hit the device; the collect
        re-stacks rows in submission order (row widths are fixed per
        (lq, emax) AOT key, and consumers only read ``tape[:nent]``,
        so zero-padding to the widest row is byte-neutral)."""
        queries, targets = list(queries), list(targets)
        from racon_tpu.cache import keying as _keying

        mk = _mesh_key(mesh)
        part = self._cache_partition(
            "wfa", len(queries),
            lambda i, epoch: _keying.wfa_key(
                queries[i], targets[i], lq, emax, mk, epoch))
        if part is None:
            return self._align_wfa_raw(queries, targets, lq, emax,
                                       mesh, tenant)
        cache, keys, hits, miss = part
        inner = self._align_wfa_raw(
            [queries[i] for i in miss], [targets[i] for i in miss],
            lq, emax, mesh, tenant) if miss else None
        return self._align_cached_collect(len(queries), inner, cache,
                                          keys, hits, miss, n_arrays=3)

    def _align_wfa_raw(self, queries, targets, lq, emax, mesh=None,
                       tenant=None):
        from racon_tpu.tpu import align_pallas

        if not self._fusion_active():
            return align_pallas.wfa_dispatch(queries, targets, lq,
                                             emax, mesh=mesh)
        key = ("wfa", lq, emax, _mesh_key(mesh))
        unit = _Unit("wfa", tenant, (list(queries), list(targets)),
                     len(queries), 0, None)
        self._tag_unit(unit)
        unit.retry = lambda u: align_pallas.wfa_dispatch(
            u.payload[0], u.payload[1], lq, emax, mesh=mesh)
        self._enqueue(key, unit, lambda units, pool: (
            align_pallas.wfa_dispatch(
                [q for u in units for q in u.payload[0]],
                [t for u in units for t in u.payload[1]],
                lq, emax, mesh=mesh),
            sum(u.size for u in units)))
        return self._align_collect(unit)

    def align_band(self, queries, targets, lq, lt, wb, mesh=None,
                   centers=None, tenant=None):
        """Cache-aware banded pair dispatch (see :meth:`align_wfa`);
        keys hash the per-pair pinned center path too — an empirical
        center changes the band, so it must change the key."""
        queries, targets = list(queries), list(targets)
        cent = list(centers) if centers is not None \
            else [None] * len(queries)
        from racon_tpu.cache import keying as _keying

        mk = _mesh_key(mesh)
        part = self._cache_partition(
            "band", len(queries),
            lambda i, epoch: _keying.band_key(
                queries[i], targets[i], lq, lt, wb, cent[i], mk,
                epoch))
        if part is None:
            return self._align_band_raw(queries, targets, lq, lt, wb,
                                        mesh, cent, tenant)
        cache, keys, hits, miss = part
        inner = self._align_band_raw(
            [queries[i] for i in miss], [targets[i] for i in miss],
            lq, lt, wb, mesh, [cent[i] for i in miss], tenant) \
            if miss else None
        return self._align_cached_collect(len(queries), inner, cache,
                                          keys, hits, miss, n_arrays=3)

    def _align_cached_collect(self, n, inner, cache, keys, hits,
                              miss, n_arrays):
        """Collect closure merging cached align rows with the miss
        dispatch's stacked arrays (``(rows_2d, col_1d, col_1d)``
        shape for both wfa and band).  Fills the cache from the
        fresh rows; with zero hits the fresh arrays pass through
        untouched."""
        import numpy as np

        def collect():
            fresh = inner() if inner is not None else None
            if fresh is not None:
                rows2d = np.asarray(fresh[0])
                cols = [np.asarray(a) for a in fresh[1:]]
                for j, i in enumerate(miss):
                    if keys[i] is not None:
                        cache.put(keys[i], (rows2d[j],)
                                  + tuple(int(c[j]) for c in cols))
                if not hits:
                    return fresh
            rows, col_vals = [None] * n, \
                [[0] * n for _ in range(n_arrays - 1)]
            for i, v in hits.items():
                rows[i] = np.asarray(v[0])
                for a, cv in enumerate(v[1:]):
                    col_vals[a][i] = cv
            if fresh is not None:
                for j, i in enumerate(miss):
                    rows[i] = rows2d[j]
                    for a, c in enumerate(cols):
                        col_vals[a][i] = int(c[j])
            width = max(r.shape[0] for r in rows)
            dtype = rows[0].dtype
            stacked = np.zeros((n, width), dtype=dtype)
            for i, r in enumerate(rows):
                stacked[i, :r.shape[0]] = r
            out = (stacked,) + tuple(
                np.asarray(cv, dtype=np.int64) for cv in col_vals)
            return out

        def device_s():
            ds = getattr(inner, "device_s", None)
            try:
                return float(ds()) if callable(ds) else 0.0
            except Exception:
                return 0.0

        collect.device_s = device_s
        collect.cache_hits = len(hits)
        return collect

    def _align_band_raw(self, queries, targets, lq, lt, wb,
                        mesh=None, centers=None, tenant=None):
        from racon_tpu.tpu import align_pallas

        if not self._fusion_active():
            return align_pallas.align_dispatch(queries, targets, lq,
                                               lt, wb, mesh=mesh,
                                               centers=centers)
        key = ("band", lq, lt, wb, _mesh_key(mesh))
        cent = list(centers) if centers is not None \
            else [None] * len(queries)
        unit = _Unit("band", tenant,
                     (list(queries), list(targets), cent),
                     len(queries), 0, None)
        self._tag_unit(unit)
        unit.retry = lambda u: align_pallas.align_dispatch(
            u.payload[0], u.payload[1], lq, lt, wb, mesh=mesh,
            centers=u.payload[2])
        self._enqueue(key, unit, lambda units, pool: (
            align_pallas.align_dispatch(
                [q for u in units for q in u.payload[0]],
                [t for u in units for t in u.payload[1]],
                lq, lt, wb, mesh=mesh,
                centers=[c for u in units for c in u.payload[2]]),
            sum(u.size for u in units)))
        return self._align_collect(unit)

    def _align_collect(self, unit):
        """Align collects return stacked arrays -- slice this unit's
        rows back out -- and expose per-unit ``device_s`` prorated by
        pair share (observability only)."""

        def collect(u=unit):
            rows, whole = self._collect_unit(u)
            if whole:
                return tuple(rows)
            return tuple(r[u.lo:u.hi] for r in rows)

        def device_s(u=unit):
            if u.fused is None or not u.fused.n_items:
                return 0.0
            return u.fused.device_s() * (u.size / u.fused.n_items)

        collect.device_s = device_s
        return collect

    # -- queueing + dispatch -------------------------------------------------
    def _enqueue(self, key, unit, fuse_dispatch):
        unit.fuse_dispatch = fuse_dispatch
        with self._cond:
            self._buckets.setdefault(key, []).append(unit)
            self._n_pending += 1
            if self._dispatcher is None or not self._dispatcher.is_alive():
                self._dispatcher = threading.Thread(
                    target=self._dispatcher_loop,
                    name="racon-tpu-executor", daemon=True)
                self._dispatcher.start()
            self._cond.notify_all()

    def _collect_unit(self, unit):
        """Returns ``(rows, whole)``: ``whole`` is True when rows
        cover only this unit (individual retry path) and False when
        they are the full fused result the caller must slice."""
        unit.done.wait()
        try:
            return unit.fused.result(), False
        except _FusedBatchError as exc:
            # crash containment made visible (r16): the poisoned-unit
            # fallback used to be a bare counter; the flight ring now
            # carries WHICH unit stood alone and why, so `inspect`
            # timelines show the retry next to the fused dispatch
            # that failed
            cause = exc.cause if exc.cause is not None else exc
            obs_flight.FLIGHT.record(
                "unit_retry", unit_kind=unit.kind,
                tenant=unit.tenant, items=unit.size,
                jobs=sorted(unit.jobs) or None,
                error=type(cause).__name__)
            from racon_tpu.obs.decision import DECISIONS
            DECISIONS.record(
                "unit_retry", unit_kind=unit.kind,
                tenant=unit.tenant, items=unit.size,
                jobs=sorted(unit.jobs) or None,
                error=type(cause).__name__)
            # shared attempt failed: this unit stands alone.  Its own
            # retry failing raises HERE -- in this unit's collect --
            # and nowhere else.
            return unit.retry(unit)(), True

    def _occupancy_target(self, units) -> int:
        cap = max((u.cap for u in units), default=0)
        return cap if cap > 0 else 0

    def _eligible(self, tenant, quota) -> bool:
        if quota <= 0 or len(self._tenants) < 2:
            return True
        if self._inflight.get(tenant, 0) < quota:
            return True
        # work-conserving: at-quota tenants run when nobody else waits
        others = any(u.tenant != tenant
                     for us in self._buckets.values() for u in us)
        return not others

    def _form_batch(self, key):
        """Weighted deficit-round-robin pick (whole units, total size
        <= the occupancy target) honoring the in-flight quota.  Called
        under the lock; removes picked units from the bucket."""
        units = self._buckets.get(key, [])
        quota = tenant_quota()
        target = self._occupancy_target(units)
        by_tenant = OrderedDict()
        for u in units:
            by_tenant.setdefault(u.tenant, []).append(u)
        picked, total = [], 0
        quantum = max(1, target or max(u.size for u in units))
        # credit every eligible tenant once per formation, scaled by
        # weight; then take ONE unit per tenant per cycle so no tenant
        # can fill the whole target before the others are visited
        for tenant in by_tenant:
            if self._eligible(tenant, quota):
                self._deficit[tenant] = (
                    self._deficit.get(tenant, 0.0)
                    + quantum * self._weights.get(tenant, 1.0))
        progress = True
        while progress and by_tenant \
                and not (target and total >= target):
            progress = False
            for tenant in list(by_tenant):
                if not self._eligible(tenant, quota):
                    continue
                queue = by_tenant[tenant]
                u = queue[0]
                if picked and target and total + u.size > target:
                    continue
                if self._deficit.get(tenant, 0.0) < u.size:
                    # short on credit this formation; it accrues on
                    # the next one, so a unit larger than one quantum
                    # waits rounds, never forever
                    continue
                self._deficit[tenant] -= u.size
                picked.append(queue.pop(0))
                total += u.size
                progress = True
                if not queue:
                    # classic DRR: an emptied queue forfeits deficit
                    del by_tenant[tenant]
                    self._deficit[tenant] = 0.0
                if target and total >= target:
                    break
        if picked:
            remaining = [u for u in units if u not in picked]
            if remaining:
                self._buckets[key] = remaining
            else:
                self._buckets.pop(key, None)
            self._n_pending -= len(picked)
            for u in picked:
                self._inflight[u.tenant] = (
                    self._inflight.get(u.tenant, 0) + 1)
        return picked, total, target

    def _current_fuse_wait_s(self) -> float:
        """The fuse window in effect: the env ceiling, or (adaptive
        mode, r22) the controller's current value clamped to
        [0, ceiling] — so adaptive mode can never hold a unit longer
        than the static configuration would."""
        ceil = fuse_wait_s()
        if not fuse_adapt_on():
            return ceil
        w = self._adapt_wait_s
        if w is None:
            self._adapt_wait_s = w = ceil
        return min(max(0.0, w), ceil)

    def _adapt_tick(self, occupancy: float) -> None:
        """Fold one dispatch's occupancy into the adaptive window.
        An occupancy EMA below the dead band means batches dispatch
        underfilled at window expiry — earn a longer wait (more time
        for batchmates); above the band, batches fill before the
        window binds — earn a shorter one and stop paying the window
        in queue latency.  Inside the band: hold (hysteresis).  Runs
        on the dispatcher thread only; clocks feed the wait DURATION,
        a policy input — never batch contents."""
        ceil = fuse_wait_s()
        if not fuse_adapt_on() or ceil <= 0.0:
            return
        prev = self._adapt_occ
        self._adapt_occ = occupancy if prev is None else \
            prev + _ADAPT_ALPHA * (occupancy - prev)
        self._adapt_since += 1
        if self._adapt_since < _ADAPT_EVERY:
            return
        self._adapt_since = 0
        w = self._adapt_wait_s if self._adapt_wait_s is not None \
            else ceil
        if self._adapt_occ < _ADAPT_BAND[0]:
            # a zero window still re-opens: step from a 2% floor
            w = min(ceil, max(w, 0.02 * ceil) * _ADAPT_UP)
        elif self._adapt_occ > _ADAPT_BAND[1]:
            w = w * _ADAPT_DOWN
        else:
            return
        self._adapt_wait_s = min(max(0.0, w), ceil)
        REGISTRY.set("fusion_wait_ms",
                     round(self._adapt_wait_s * 1e3, 4))

    def _bucket_ripe(self, key, now) -> bool:
        units = self._buckets.get(key)
        if not units:
            return False
        head = min(u.t_submit for u in units)
        if now - head >= self._current_fuse_wait_s():
            return True
        target = self._occupancy_target(units)
        if target and sum(u.size for u in units) >= target:
            return True
        # every known tenant already queued here: nothing to wait for
        if len(self._tenants) >= 2 and \
                {u.tenant for u in units} >= set(self._tenants):
            return True
        return False

    def _dispatcher_loop(self):
        while True:
            with self._cond:
                while self._n_pending == 0 and not self._shutdown:
                    self._cond.wait()
                if self._shutdown:
                    return
                now = _mono()
                ripe = [k for k in self._buckets
                        if self._bucket_ripe(k, now)]
                if not ripe:
                    heads = [min(u.t_submit for u in us)
                             for us in self._buckets.values() if us]
                    wait = (min(heads) + self._current_fuse_wait_s()
                            - now) if heads else 0.05
                    self._cond.wait(max(1e-4, min(wait, 0.05)))
                    continue
                key = min(ripe, key=lambda k: min(
                    u.t_submit for u in self._buckets[k]))
                picked, total, target = self._form_batch(key)
                if not picked:
                    # every pending tenant at quota: wait for a
                    # collect to decrement in-flight
                    self._cond.wait(0.02)
                    continue
            self._dispatch(picked, total, target, now)

    def _dispatch(self, units, total, target, now):
        tenants = {u.tenant for u in units}
        lo = 0
        for u in units:
            u.lo, u.hi = lo, lo + u.size
            lo += u.size
            if u.tenant in self._tenants:
                REGISTRY.observe(f"serve_tenant_wait_s.{u.tenant}",
                                 max(0.0, now - u.t_submit))
        REGISTRY.add("fusion_dispatches")
        REGISTRY.add("fusion_units_fused", len(units))
        if len(units) > 1:
            REGISTRY.add("fused_megabatches")
            if len(tenants) > 1:
                REGISTRY.add("fused_cross_tenant")
        occupancy = total / target if target else 1.0
        REGISTRY.observe("fusion_occupancy", occupancy)
        self._adapt_tick(occupancy)
        try:
            collect, n_items = units[0].fuse_dispatch(
                units, self._pool())
            fused = _FusedDispatch(collect, n_items, units)
        except BaseException as exc:  # containment: fall back per unit
            fused = _FusedDispatch(_raiser(exc), total, units)
        # attribution: the shared dispatch span + per-unit flow
        # finishes land on the "executor" lane, tagged with every job
        # whose work rode this megabatch; the flight recorder keeps
        # the same summary for post-mortem inspection
        jobs = sorted({j for u in units for j in u.jobs})
        t1 = _mono()
        if TRACER.capturing:
            TRACER.add_span(
                "executor.fused_dispatch", now, t1, cat="fuse",
                lane="executor",
                args={"kind": units[0].kind, "units": len(units),
                      "items": total, "occupancy": round(occupancy, 4),
                      "tenants": sorted(tenants), "jobs": jobs},
                jobs=jobs)
            for u in units:
                TRACER.add_flow(f"executor.unit.{u.kind}", u.flow_id,
                                "f", lane="executor", t=t1,
                                jobs=list(u.jobs))
        obs_flight.FLIGHT.record(
            "fused_dispatch", unit_kind=units[0].kind,
            units=len(units), items=total,
            occupancy=round(occupancy, 4), tenants=sorted(tenants),
            jobs=jobs or None)
        # in-flight decrements on completion of the shared device
        # work: piggyback on the first collect (wrapped BEFORE units
        # wake so no collect can slip past the accounting)
        orig_result = fused.result
        decremented = threading.Event()

        def result():
            try:
                return orig_result()
            finally:
                if not decremented.is_set():
                    decremented.set()
                    with self._cond:
                        for u in units:
                            t = u.tenant
                            if self._inflight.get(t, 0) > 0:
                                self._inflight[t] -= 1
                        self._cond.notify_all()

        fused.result = result
        for u in units:
            u.fused = fused
            u.done.set()

    def _pool(self):
        if self._own_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._own_pool = ThreadPoolExecutor(
                max_workers=max(2, os.cpu_count() or 2),
                thread_name_prefix="racon-tpu-exec")
        return self._own_pool

    # -- introspection -------------------------------------------------------
    def pending_units(self) -> int:
        """Fusion-queue backlog: units submitted but not yet
        dispatched.  Cheaper than :meth:`stats` (no bucket walk, no
        registry reads) — the ``health`` op's poll-loop source."""
        with self._cond:
            return self._n_pending

    def stats(self) -> dict:
        with self._cond:
            pending = {str(k[0]): sum(u.size for u in us)
                       for k, us in self._buckets.items() if us}
            doc = {
                "enabled": fuse_enabled(),
                "active": self._fusion_active(),
                "tenants": dict(self._tenants),
                "inflight": {k: v for k, v in self._inflight.items()
                             if v},
                "pending_units": self._n_pending,
                "pending_items": pending,
                "quota": tenant_quota(),
                "fuse_wait_ms": self._current_fuse_wait_s() * 1e3,
                "fuse_wait_ceiling_ms": fuse_wait_s() * 1e3,
                "fuse_adapt": fuse_adapt_on(),
            }
        for key in ("fusion_dispatches", "fusion_units_fused",
                    "fused_megabatches", "fused_cross_tenant"):
            doc[key] = REGISTRY.value(key)
        return doc

    def close(self):
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()
        if self._own_pool is not None:
            self._own_pool.shutdown(wait=False)
            self._own_pool = None


def _raiser(exc):
    def collect():
        raise exc
    return collect


# ---------------------------------------------------------------------------
# process-wide singleton
# ---------------------------------------------------------------------------

_EXECUTOR = None
_EXECUTOR_LOCK = threading.Lock()


def get_executor() -> DeviceExecutor:
    global _EXECUTOR
    with _EXECUTOR_LOCK:
        if _EXECUTOR is None:
            _EXECUTOR = DeviceExecutor()
        return _EXECUTOR


def _reset_for_tests():
    """Drop the singleton (tests only -- live collects keep working,
    they hold their own unit/engine references)."""
    global _EXECUTOR
    with _EXECUTOR_LOCK:
        if _EXECUTOR is not None:
            _EXECUTOR.close()
        _EXECUTOR = None
