"""Batched POA consensus on TPU (cudapoa-equivalent).

Re-creates, TPU-first, what the reference gets from ClaraGenomicsAnalysis
cudapoa (reference: src/cuda/cudabatch.cpp:52-265): batched partial-order
alignment consensus over windows.  The CUDA design keeps whole POA
graphs resident on the GPU and runs one thread block per window; that
shape does not map to XLA's static-shape compilation model, so the TPU
design splits the work differently:

* **lockstep layers**: all windows of a batch advance one layer per
  round; round ``d`` runs ONE ``jit``-compiled batched DP aligning every
  window's d-th layer against its current graph — the device sees only
  fixed-shape arrays ``[B, V, ...]``;
* **graphs live on the host** in C++ (racon_tpu/native/poa_batch.cpp,
  reusing the CPU engine's PoaGraph): each round exports per-window
  subgraphs (topo-ordered bases, capped predecessor lists, sink flags)
  and applies the device-produced alignment paths (spoa add_alignment
  semantics);
* the DP scan runs over graph ranks; the in-row gap chain is closed
  with an associative max-plus scan, so each row step is pure vector
  work across ``B x (L+1)`` lanes;
* **traceback runs on device** (one gather per step) and only compact
  paths ``[B, V+L, 2]`` travel device->host.

Windows that overflow the caps (graph nodes > vcap, in-degree > pcap)
are failed over to the CPU engine, exactly the reference's rejection
contract (cudabatch.cpp:124-127 -> cudapolisher.cpp:357-386); over-long
layers are skipped and only reduce coverage (cudabatch.cpp:136-155).
"""

from __future__ import annotations

import ctypes
import functools
import os
import threading
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from racon_tpu.obs import devutil as obs_devutil
from racon_tpu.obs import metrics as obs_metrics
from racon_tpu.obs import trace as obs_trace
from racon_tpu.obs import decision as obs_decision
from racon_tpu.ops import cpu as cpu_ops
from racon_tpu.utils.tuning import poa_band_cols, scan_unroll as _unroll

# the sanctioned clock (racon_tpu/obs): phase walls feed only the
# engine's reporting counters, never control flow
_mono = obs_trace.now

_BIG = np.int32(1 << 28)

# traceback tape sentinels (host side)
PATH_NONE = -1      # no node / no seq position in this step
PATH_DONE = -3      # walk finished


@functools.partial(jax.jit, static_argnums=(6, 7, 8, 9, 10, 11, 12))
def _poa_kernel(bases, preds, nrows, sinks, seq, slen,
                v: int, l: int, p: int, k: int,
                match: int, mismatch: int, gap: int):
    """Batched global NW of sequences against DAGs in topo-rank order.

    bases: [B, V] uint8 node bases (rank order)
    preds: [B, V, P] int16 predecessor DP-row indices (0 = virtual
           start row, -1 = pad); in-edges reach back at most ``k`` rows
           (enforced by rt_poab_export; violators fall back to CPU)
    nrows: [B] int32 valid rank count
    sinks: [B, V] uint8 sink flags
    seq:   [B, L] uint8 layer bases, slen: [B] int32

    The DP carries only a ring buffer of the last ``k`` score rows (the
    full [B, V, L] matrix never exists), so the per-step state stays
    VMEM-sized; sink scores are folded on the fly.  Returns
    (node_tape, seq_tape): [B, V+L] int32 each, the reversed alignment
    path per lane; node entries are 0-based ranks or PATH_NONE, seq
    entries are positions or PATH_NONE, PATH_DONE after the walk
    reaches the origin.
    """
    b = bases.shape[0]
    cols = jnp.arange(l + 1, dtype=jnp.int32)
    lanes = jnp.arange(b)
    neg = jnp.float32(-_BIG)
    colsf = cols.astype(jnp.float32)

    # virtual start row H[0][j] = j*gap (always addressable as pred 0);
    # scores are exact in f32 (|score| <= |scores|*(V+L) << 2^24)
    vrow = (colsf * gap)[None, :] + jnp.zeros((b, 1), jnp.float32)

    zero_b = jnp.zeros_like(nrows)          # batch-varying seed
    ring_init = jnp.full((b, k, l + 1), neg, jnp.float32) \
        + zero_b[:, None, None]
    best_init = (jnp.full((b,), neg, jnp.float32) + zero_b,
                 jnp.zeros((b,), jnp.int32) + zero_b)

    def step(carry, r):
        ring, best_score, best_row = carry
        pidx = preds[:, r - 1, :].astype(jnp.int32)        # [B, P]
        # per-lane pred-row pick as a gather along the ring axis; unlike
        # a one-hot matmul this scales ~flat in P and K (measured: p=16
        # k=128 costs +12% vs p=8 k=64, where the einsum cost 3.2x)
        slot = (pidx - 1) & (k - 1)
        gathered = jnp.take_along_axis(ring, slot[:, :, None], axis=1)
        hp = jnp.where((pidx > 0)[:, :, None], gathered,
                       jnp.where((pidx == 0)[:, :, None],
                                 vrow[:, None, :], neg))
        base_r = bases[:, r - 1]
        sub = jnp.where(seq == base_r[:, None], match,
                        mismatch).astype(jnp.float32)       # [B, L]
        diag_c = hp[:, :, :-1] + sub[:, None, :]            # [B,P,L]
        vert_c = hp + gap                                   # [B,P,L+1]
        diag_full = jnp.concatenate(
            [jnp.full((b, p, 1), neg, jnp.float32), diag_c], axis=2)
        t_best = jnp.maximum(jnp.max(diag_full, axis=1),
                             jnp.max(vert_c, axis=1))       # [B, L+1]
        # close the in-row gap chain: H[r][j] = max_{k<=j} T[k]+(j-k)g
        shifted = t_best - colsf * gap
        hr = lax.associative_scan(jnp.maximum, shifted,
                                  axis=1) + colsf * gap
        # direction codes with preference diag(p) < vert(p) < horiz,
        # recomputed against the final row value (always achievable)
        horiz = jnp.concatenate(
            [jnp.full((b, 1), neg, jnp.float32), hr[:, :-1] + gap],
            axis=1)
        cand = jnp.concatenate(
            [diag_full, vert_c, horiz[:, None, :]], axis=1)  # [B,2P+1,L+1]
        dirs = jnp.argmax(cand == hr[:, None, :],
                          axis=1).astype(jnp.uint8)
        ring = lax.dynamic_update_slice(
            ring, hr[:, None, :], (0, (r - 1) & (k - 1), 0))
        # fold sink-row end scores (earliest rank wins ties via strict >)
        is_sink = (sinks[:, r - 1] > 0) & (r <= nrows)
        s_r = hr[lanes, slen]
        better = is_sink & (s_r > best_score)
        best_score = jnp.where(better, s_r, best_score)
        best_row = jnp.where(better, r, best_row)
        return (ring, best_score, best_row), dirs

    (_, _, best_row), dir_rows = lax.scan(
        step, (ring_init,) + best_init,
        jnp.arange(1, v + 1, dtype=jnp.int32), unroll=_unroll(1))
    # dir_rows: [V, B, L+1] for ranks 1..V

    def tb_step(carry, _):
        r, j = carry
        done = (r == 0) & (j == 0)
        code = dir_rows[r - 1, lanes, j].astype(jnp.int32)
        is_diag = (code < p) & (r > 0)
        is_vert = (code >= p) & (code < 2 * p) & (r > 0)
        # r == 0 (virtual row) or horiz code: consume a seq char
        slot = jnp.where(is_diag, code, code - p)
        slot = jnp.clip(slot, 0, p - 1)
        pred_r = preds[lanes, jnp.maximum(r - 1, 0), slot].astype(
            jnp.int32)
        node = jnp.where(is_diag | is_vert, r - 1, PATH_NONE)
        spos = jnp.where(is_vert, PATH_NONE, j - 1)
        node = jnp.where(done, PATH_DONE, node)
        spos = jnp.where(done, PATH_DONE, spos)
        nr = jnp.where(is_diag | is_vert, pred_r, r)
        nj = jnp.where(is_vert, j, jnp.maximum(j - 1, 0))
        nr = jnp.where(done, r, nr)
        nj = jnp.where(done, j, nj)
        return (nr, nj), (node, spos)

    (_, _), (node_tape, seq_tape) = lax.scan(
        tb_step, (best_row.astype(jnp.int32), slen), None, length=v + l,
        unroll=_unroll(1))
    return jnp.transpose(node_tape), jnp.transpose(seq_tape)


@functools.partial(jax.jit,
                   static_argnums=(6, 7, 8, 9, 10, 11, 12, 13))
def _poa_kernel_banded(bases, preds, nrows, sinks, seq, slen,
                       v: int, l: int, p: int, k: int, wb: int,
                       match: int, mismatch: int, gap: int):
    """Banded variant of :func:`_poa_kernel`.

    Same inputs/outputs, but each rank's DP row is restricted to a
    ``wb``-column band centred on the rank's expected sequence position
    ``r * slen / nrows`` (layers align near the graph diagonal; indel
    drift within a 500 bp window is far below wb/2).  The ring buffer,
    candidate tensors and direction tape all shrink from ``l+1`` to
    ``wb`` columns, which is what the round cost is bound by (HBM
    traffic).  Band starts are a deterministic function of (r, slen,
    nrows), so the traceback recomputes them instead of storing them.
    The CUDA analog is cudapoa's banded NW (reference:
    src/cuda/cudabatch.cpp:54-62 banded flag).

    TPU-critical detail: band starts are QUANTIZED to ``wb//4`` so that
    cross-band realignment (pred rows and the sequence slice) is a
    select over a handful of statically-shifted slices — per-element
    ``take_along_axis`` gathers on the lane dimension are ~14x slower
    than the whole unbanded row DP (measured on v5e).
    """
    b = bases.shape[0]
    q = wb // 4                       # band-start quantum
    n_shift = 5                       # pred rows can lag <= 4 quanta
    cols = jnp.arange(wb, dtype=jnp.int32)
    colsf = cols.astype(jnp.float32)
    lanes = jnp.arange(b)
    neg = jnp.float32(-_BIG)
    nr = jnp.maximum(nrows, 1)
    # max band start in quanta: CEIL so the top band still reaches
    # column slen (s_max*q >= slen+1-wb; and s_max*q <= slen since
    # q <= wb), keeping the alignment endpoint inside the band
    smax_q = (jnp.maximum(slen + 1 - wb, 0) + q - 1) // q

    def band_start_q(r):
        """Quantized band start (in units of q) for rank(s) r ([B] or
        scalar), clamped so rank nrows can reach column slen (the
        alignment endpoint)."""
        c = ((r * slen) // nr - (wb // 2)) // q
        return jnp.clip(c, 0, smax_q)

    # per-lane sequence slices at every quantized start, precomputed
    # once: seq_sl[m][b, c] = seq[b, m*q + c - 1] (static slices)
    n_seq_sl = (max(0, l + 1 - wb) + q - 1) // q + 1
    seq_padl = jnp.concatenate(
        [jnp.zeros((b, 1), jnp.uint8), seq,
         jnp.zeros((b, wb), jnp.uint8)], axis=1)
    seq_sl = jnp.stack([seq_padl[:, m * q: m * q + wb]
                        for m in range(n_seq_sl)])   # [M, B, wb]

    zero_f = jnp.zeros_like(nrows).astype(jnp.float32)
    ring_init = jnp.full((b, k, wb), neg, jnp.float32) \
        + zero_f[:, None, None]
    best_init = (jnp.full((b,), neg, jnp.float32) + zero_f,
                 jnp.zeros((b,), jnp.int32) + jnp.zeros_like(nrows))

    def step(carry, r):
        ring, best_score, best_row = carry
        sq_r = band_start_q(r)                           # [B] (units q)
        s_r = sq_r * q
        pidx = preds[:, r - 1, :].astype(jnp.int32)      # [B, P]
        slot = (pidx - 1) & (k - 1)
        g1 = jnp.take_along_axis(ring, slot[:, :, None], axis=1)
        # realign pred rows (stored from their own band starts) to this
        # rank's band: hp_ext[c] = H_pred[s_r + c - 1], c in [0, wb].
        # delta is a whole number of quanta, so the realignment is a
        # select over n_shift statically-shifted slices of g1.
        sq_p = jnp.clip(
            ((pidx * slen[:, None]) // nr[:, None] - (wb // 2)) // q,
            0, smax_q[:, None])                          # [B, P]
        dq = sq_r[:, None] - sq_p                        # [B, P] >= 0
        g1_pad = jnp.concatenate(
            [jnp.full((b, p, 1), neg, jnp.float32), g1,
             jnp.full((b, p, n_shift * q), neg, jnp.float32)], axis=2)
        hp_ext = jnp.full((b, p, wb + 1), neg, jnp.float32)
        for m in range(n_shift):
            # slice m: H_pred values at columns s_p + m*q + c - 1
            hp_ext = jnp.where((dq == m)[:, :, None],
                               g1_pad[:, :, m * q: m * q + wb + 1],
                               hp_ext)
        j_ext = s_r[:, None] + jnp.arange(wb + 1,
                                          dtype=jnp.int32)[None, :] - 1
        vv = jnp.where(j_ext >= 0, j_ext.astype(jnp.float32) * gap,
                       neg)                              # virtual row
        hp_ext = jnp.where((pidx > 0)[:, :, None], hp_ext,
                           jnp.where((pidx == 0)[:, :, None],
                                     vv[:, None, :], neg))
        base_r = bases[:, r - 1]
        # sequence chars for this band: select the precomputed slice
        sb = seq_sl[0]
        for m in range(1, n_seq_sl):
            sb = jnp.where((sq_r == m)[:, None], seq_sl[m], sb)
        j_sub = s_r[:, None] + cols[None, :] - 1         # seq index
        sub_ok = (j_sub >= 0) & (j_sub < slen[:, None]) \
            & (sb == base_r[:, None])
        sub = jnp.where(sub_ok, match, mismatch).astype(jnp.float32)
        diag_c = hp_ext[:, :, :wb] + sub[:, None, :]     # [B, P, wb]
        vert_c = hp_ext[:, :, 1:] + gap                  # [B, P, wb]
        t_best = jnp.maximum(jnp.max(diag_c, axis=1),
                             jnp.max(vert_c, axis=1))    # [B, wb]
        shifted = t_best - colsf * gap
        hr = lax.associative_scan(jnp.maximum, shifted,
                                  axis=1) + colsf * gap
        horiz = jnp.concatenate(
            [jnp.full((b, 1), neg, jnp.float32), hr[:, :-1] + gap],
            axis=1)
        cand = jnp.concatenate(
            [diag_c, vert_c, horiz[:, None, :]], axis=1)  # [B,2P+1,wb]
        dirs = jnp.argmax(cand == hr[:, None, :],
                          axis=1).astype(jnp.uint8)
        ring = lax.dynamic_update_slice(
            ring, hr[:, None, :], (0, (r - 1) & (k - 1), 0))
        is_sink = (sinks[:, r - 1] > 0) & (r <= nrows)
        c_end = slen - s_r
        s_end = jnp.take_along_axis(
            hr, jnp.clip(c_end, 0, wb - 1)[:, None], axis=1)[:, 0]
        better = is_sink & (c_end < wb) & (s_end > best_score)
        best_score = jnp.where(better, s_end, best_score)
        best_row = jnp.where(better, r, best_row)
        return (ring, best_score, best_row), dirs

    (_, _, best_row), dir_rows = lax.scan(
        step, (ring_init,) + best_init,
        jnp.arange(1, v + 1, dtype=jnp.int32), unroll=_unroll(1))
    # dir_rows: [V, B, wb] for ranks 1..V

    def tb_step(carry, _):
        r, j = carry
        done = (r == 0) & (j == 0)
        c = jnp.clip(j - band_start_q(r) * q, 0, wb - 1)
        code = dir_rows[jnp.maximum(r - 1, 0), lanes, c].astype(
            jnp.int32)
        is_diag = (code < p) & (r > 0)
        is_vert = (code >= p) & (code < 2 * p) & (r > 0)
        slot = jnp.where(is_diag, code, code - p)
        slot = jnp.clip(slot, 0, p - 1)
        pred_r = preds[lanes, jnp.maximum(r - 1, 0), slot].astype(
            jnp.int32)
        node = jnp.where(is_diag | is_vert, r - 1, PATH_NONE)
        spos = jnp.where(is_vert, PATH_NONE, j - 1)
        node = jnp.where(done, PATH_DONE, node)
        spos = jnp.where(done, PATH_DONE, spos)
        nr_ = jnp.where(is_diag | is_vert, pred_r, r)
        nj = jnp.where(is_vert, j, jnp.maximum(j - 1, 0))
        nr_ = jnp.where(done, r, nr_)
        nj = jnp.where(done, j, nj)
        return (nr_, nj), (node, spos)

    (_, _), (node_tape, seq_tape) = lax.scan(
        tb_step, (best_row.astype(jnp.int32), slen), None, length=v + l,
        unroll=_unroll(1))
    return jnp.transpose(node_tape), jnp.transpose(seq_tape)


class _NativeBatch:
    """ctypes wrapper over the poa_batch.cpp lockstep API."""

    _bound = False

    @classmethod
    def _bind(cls):
        lib = cpu_ops.get_library()
        if not cls._bound:
            i8p = ctypes.POINTER(ctypes.c_uint8)
            lib.rt_poab_create.restype = ctypes.c_void_p
            lib.rt_poab_create.argtypes = [ctypes.c_int32]
            lib.rt_poab_destroy.argtypes = [ctypes.c_void_p]
            lib.rt_poab_seed.argtypes = [
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p,
                ctypes.c_int32, ctypes.c_char_p, ctypes.c_uint8]
            lib.rt_poab_export.restype = ctypes.c_int32
            lib.rt_poab_export.argtypes = [
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32,
                np.ctypeslib.ndpointer(np.uint8),
                np.ctypeslib.ndpointer(np.int16),
                np.ctypeslib.ndpointer(np.uint8),
                np.ctypeslib.ndpointer(np.int32)]
            lib.rt_poab_apply.argtypes = [
                ctypes.c_void_p, ctypes.c_int32,
                np.ctypeslib.ndpointer(np.int32),
                np.ctypeslib.ndpointer(np.int32),
                ctypes.c_int32, ctypes.c_char_p, ctypes.c_int32,
                ctypes.c_char_p, ctypes.c_uint8, ctypes.c_int32]
            lib.rt_poab_num_nodes.restype = ctypes.c_int32
            lib.rt_poab_num_nodes.argtypes = [ctypes.c_void_p,
                                              ctypes.c_int32]
            lib.rt_poab_consensus.restype = ctypes.c_int64
            lib.rt_poab_consensus.argtypes = [
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int32)]
            cls._bound = True
        return lib

    def __init__(self, n_windows: int):
        self.lib = self._bind()
        self.handle = ctypes.c_void_p(
            self.lib.rt_poab_create(n_windows))

    def close(self):
        if self.handle:
            self.lib.rt_poab_destroy(self.handle)
            self.handle = None

    def __del__(self):
        self.close()


class TPUPoaBatchEngine:
    """Lockstep batched POA over a megabatch of windows.

    Caps (vcap/pcap/lcap/max_depth) mirror the CUDA batch limits
    (max nodes per graph, max sequences per POA = 200,
    src/cuda/cudapolisher.cpp:229).
    """

    def __init__(self, match: int, mismatch: int, gap: int,
                 vcap: int = 2048, pcap: int = 16, lcap: int = 1024,
                 kcap: int = 128, max_depth: int = 200,
                 banded: bool = False, mesh=None):
        self.match, self.mismatch, self.gap = match, mismatch, gap
        self.vcap, self.pcap, self.lcap = vcap, pcap, lcap
        self.kcap = kcap
        self.max_depth = max_depth
        # banded (-b): halve the auto quarter-of-bucket DP band
        # (cudapoa banded analog, cudabatch.cpp:54-62); see
        # racon_tpu.utils.tuning.poa_band_cols for the 256 floor
        self.banded = banded
        self.cells = 0
        # mesh: shard each round's batch axis over the devices
        # (reference analog: per-device POA batch queues,
        # src/cuda/cudapolisher.cpp:231-243)
        self.mesh = mesh
        self.n_skipped_layers = 0
        # rejection observability: export failure code -> count
        # (-1 vcap, -2 pcap, -3 kcap; reference analog: the per-entry
        # status counters in cudabatch.cpp:136-155); guarded by a lock
        # because export() runs on the polisher's thread pool
        self.reject_counts = {-1: 0, -2: 0, -3: 0}
        self._reject_lock = threading.Lock()
        # per-phase wall accounting (cumulative over rounds):
        # export/apply are host C++ graph work, dispatch is the blocking
        # device step, extract is final consensus generation
        self.phase_walls = {"export": 0.0, "dispatch": 0.0,
                            "apply": 0.0, "extract": 0.0}
        # host-independent cumulative device time (watcher-thread
        # spans from poa_pallas.poa_full_dispatch; 0.0 on the
        # lockstep path, which has no async dispatch to watch)
        self.device_s = 0.0
        self.n_rounds = 0

    def consensus_batch(self, windows, trim: bool, pool=None) \
            -> List[Tuple[Optional[bytes], bool]]:
        """Polish a batch of Window objects on device (blocking).

        Returns one (consensus, polished) pair per window; consensus is
        None when the window overflowed the device caps and must be
        re-polished on the CPU (reference: cudapolisher.cpp:357-386).
        """
        return self.consensus_batch_async(windows, trim, pool)()

    def consensus_batch_async(self, windows, trim: bool, pool=None):
        """Dispatch a batch and return a zero-arg collect closure.

        On a TPU backend (or with Pallas interpret mode forced) the
        whole POA runs inside ONE Pallas dispatch
        (racon_tpu/tpu/poa_pallas.py, the cudapoa-shaped design),
        sharded over the mesh batch axis when the mesh has more than
        one device, and the dispatch returns BEFORE the device
        finishes -- callers can pack/dispatch the next megabatch while
        this one computes (upload + host packing overlap device time).
        Otherwise the portable lockstep lax.scan engine runs
        synchronously and the closure just returns its results.
        """
        from racon_tpu.tpu import poa_pallas
        if self.will_dispatch_async(windows):
            # the kernel's window type is a compile-time constant;
            # split mixed batches so each window trims per its own
            # type (parity with the per-window lockstep/CPU paths).
            # _fits_full_device rejects configurations that exceed the
            # kernel's VMEM budget -> lockstep below.
            types = {w.type.value for w in windows}
            if len(types) <= 1:
                return self._run_full_device_async(windows, trim)
            collects = []
            for tv in sorted(types):
                idxs = [i for i, w in enumerate(windows)
                        if w.type.value == tv]
                collects.append(
                    (idxs, self._run_full_device_async(
                        [windows[i] for i in idxs], trim)))

            def collect_mixed():
                results: List[Tuple[Optional[bytes], bool]] = \
                    [None] * len(windows)
                for idxs, coll in collects:
                    for i, r in zip(idxs, coll()):
                        results[i] = r
                return results

            return collect_mixed
        n = len(windows)

        def run_lockstep():
            nb = _NativeBatch(n)
            try:
                return self._run(nb, windows, trim, pool)
            finally:
                nb.close()

        # lockstep runs synchronously at dispatch time; its interval
        # IS the engine-busy window on backends without the Pallas
        # kernel (the watcher threads never run there)
        t0 = _mono()
        out = run_lockstep()
        obs_devutil.DEVICE_UTIL.record("poa", t0, _mono())
        return lambda: out

    # -- full on-device path (flagship Pallas kernel) ------------------

    def will_dispatch_async(self, windows) -> bool:
        """True when ``consensus_batch_async`` would return before the
        device finishes (the full-device Pallas path); the lockstep
        fallback runs synchronously at dispatch time, so pipelining
        callers must not attribute its wall to an in-flight batch."""
        from racon_tpu.tpu import poa_pallas
        return poa_pallas.available() and \
            self._fits_full_device(windows)

    def _fits_full_device(self, windows) -> bool:
        """Side-effect-free VMEM precheck (d1 from raw layer counts,
        an upper bound on what _order_layers keeps)."""
        from racon_tpu.tpu import poa_pallas
        from racon_tpu.utils.tuning import pow2_at_least

        lp = self.lcap
        wb = poa_pallas.band_width(lp, self.banded)
        depth = max((min(len(w.sequences) - 1, self.max_depth)
                     for w in windows), default=0)
        d1 = max(8, pow2_at_least(depth + 1, 8))
        return poa_pallas.fits(self.vcap, lp, d1, self.pcap,
                               self.pcap, 8, wb)

    def _order_layers(self, w):
        idx = sorted(range(1, len(w.sequences)),
                     key=lambda i: w.positions[i][0])
        kept = [i for i in idx
                if len(w.sequences[i]) <= self.lcap][:self.max_depth]
        self.n_skipped_layers += len(idx) - len(kept)
        return kept

    def _run_full_device_async(self, windows, trim):
        """Dispatch one megabatch; returns a zero-arg collect closure.
        Callers must have passed _fits_full_device first."""
        from racon_tpu.tpu import poa_pallas
        from racon_tpu.utils.tuning import pow2_at_least

        # <3-sequence windows keep the backbone verbatim (reference:
        # cudabatch.cpp:214-222) -- short-circuit them before packing
        # so they cost no device work or d1/b_pad head-room
        if any(len(w.sequences) < 3 for w in windows):
            out: List[Tuple[Optional[bytes], bool]] = \
                [None] * len(windows)
            dev_idx = []
            for i, w in enumerate(windows):
                if len(w.sequences) < 3:
                    out[i] = (w.sequences[0], False)
                else:
                    dev_idx.append(i)
            sub = self._run_full_device_async(
                [windows[i] for i in dev_idx], trim) if dev_idx \
                else None

            def collect_shortcut():
                if sub is not None:
                    for i, r in zip(dev_idx, sub()):
                        out[i] = r
                return out

            return collect_shortcut

        n = len(windows)
        layer_lists = [self._order_layers(w) for w in windows]
        v, lp = self.vcap, self.lcap
        # -b narrows the band; the on-device DP needs >= 256 columns
        # (quantum 128), so the narrow setting clamps up
        wb = poa_pallas.band_width(lp, self.banded)
        d1 = max(8, pow2_at_least(
            max((len(ll) for ll in layer_lists), default=0) + 1, 8))
        b_pad = max(8, pow2_at_least(n, 8))

        t0 = _mono()
        seqs = np.zeros((b_pad, d1, lp), np.uint8)
        wts = np.ones((b_pad, d1, lp), np.uint8)
        meta = np.zeros((b_pad, d1, 8), np.int32)
        nlay = np.zeros(b_pad, np.int32)
        bblen = np.ones(b_pad, np.int32)
        seqs[:, 0, 0] = ord("A")        # pad windows: 1-base backbone
        host_fail = [False] * n
        for b, w in enumerate(windows):
            bb = w.sequences[0]
            if len(bb) > min(lp, v):
                host_fail[b] = True     # vcap analog, CPU re-polish
                continue
            bblen[b] = len(bb)
            seqs[b, 0, :len(bb)] = np.frombuffer(bb, np.uint8)
            q0 = w.qualities[0]
            if q0:
                wts[b, 0, :len(bb)] = \
                    np.frombuffer(q0, np.uint8).astype(np.int32) \
                    .clip(33, None).astype(np.uint8) - 33
            offset = int(0.01 * len(bb))
            nlay[b] = len(layer_lists[b])
            for d, li in enumerate(layer_lists[b], start=1):
                s = w.sequences[li]
                seqs[b, d, :len(s)] = np.frombuffer(s, np.uint8)
                ql = w.qualities[li]
                if ql:
                    wts[b, d, :len(s)] = \
                        np.frombuffer(ql, np.uint8).astype(np.int32) \
                        .clip(33, None).astype(np.uint8) - 33
                begin, end = w.positions[li]
                full = 1 if (begin < offset
                             and end > len(bb) - offset) else 0
                meta[b, d, :4] = (begin, end, full, len(s))
        with self._reject_lock:
            self.phase_walls["export"] += _mono() - t0

        t_disp = _mono()
        handle = poa_pallas.poa_full_dispatch(
            seqs, wts, meta, nlay, bblen, v=v, lp=lp, d1=d1,
            p=self.pcap, s=self.pcap, a=8, k=self.kcap, wb=wb,
            match=self.match, mismatch=self.mismatch, gap=self.gap,
            wtype=windows[0].type.value, trim=1 if trim else 0,
            mesh=self.mesh)

        def collect():
            t0 = _mono()
            cons, mout = handle()
            blocked = _mono() - t0
            # NOTE under the double-buffered pipeline: "dispatch"
            # counts only the UN-overlapped blocking residual (device
            # time hidden behind the next batch's packing shows up in
            # no bucket), so phase walls no longer sum to the stage
            # wall; the watcher-thread span below is the
            # host-independent per-dispatch device time.  Counter
            # updates take the lock: the streaming pipeline
            # (racon_tpu/tpu/polisher.py) shares one engine between
            # the speculative align-stage consumer thread and the
            # stage-time dispatch loop
            dev_s = getattr(handle, "device_s", lambda: 0.0)()
            with self._reject_lock:
                self.phase_walls["dispatch"] += blocked
                self.device_s += dev_s
            if dev_s > 0:
                # per-megabatch device-time distribution (the engine
                # only keeps the aggregate; the serve-layer latency
                # percentiles want the shape)
                obs_metrics.REGISTRY.observe(
                    "poa_megabatch_device_s", dev_s)
            if os.environ.get("RACON_TPU_POA_TRACE"):
                import sys
                live = nlay[:n][nlay[:n] > 0]
                lo = int(live.min()) if live.size else 0
                print(f"[poa-trace] b={n}(pad {b_pad}) d1={d1} "
                      f"depths {lo}..{int(nlay[:n].max())} "
                      f"span {_mono() - t_disp:.2f}s "
                      f"blocked {blocked:.2f}s",
                      file=sys.stderr, flush=True)
            with self._reject_lock:
                self.n_rounds += 1
                self.cells += int(mout[:n, 4].sum()) * wb

            t1 = _mono()
            results: List[Tuple[Optional[bytes], bool]] = []
            code_map = {poa_pallas.FAIL_VCAP: -1,
                        poa_pallas.FAIL_EDGE: -2,
                        poa_pallas.FAIL_ALIGNED: -2,
                        poa_pallas.FAIL_KCAP: -3,
                        poa_pallas.FAIL_PATH: -3}
            for b, w in enumerate(windows):
                length = int(mout[b, 0])
                if host_fail[b] or length < 0:
                    code = code_map.get(int(mout[b, 2]), -1)
                    with self._reject_lock:
                        self.reject_counts[code] = \
                            self.reject_counts.get(code, 0) + 1
                    obs_decision.DECISIONS.record("poa_reject", code=code,
                                     phase="extract")
                    results.append((None, False))
                    continue
                if int(mout[b, 1]) == 2:
                    w.warn_chimeric()
                results.append(
                    (bytes(cons[b, :length].astype(np.uint8)), True))
            with self._reject_lock:
                self.phase_walls["extract"] += _mono() - t1
            return results

        return collect

    # -- helpers -------------------------------------------------------

    def _run(self, nb, windows, trim, pool):
        lib, handle = nb.lib, nb.handle
        n = len(windows)
        layer_lists = [self._order_layers(w) for w in windows]

        def seed(i):
            w = windows[i]
            backbone = w.sequences[0]
            qual = w.qualities[0]
            lib.rt_poab_seed(handle, i, backbone, len(backbone),
                             qual if qual else b"\x00" * len(backbone),
                             1 if qual else 0)

        _map(pool, seed, range(n))

        failed = [False] * n
        max_rounds = max((len(ll) for ll in layer_lists), default=0)

        v, l, p = self.vcap, self.lcap, self.pcap
        bases = np.zeros((n, v), dtype=np.uint8)
        preds = np.full((n, v, p), -1, dtype=np.int16)
        sinks = np.zeros((n, v), dtype=np.uint8)
        rank2node = np.zeros((n, v), dtype=np.int32)
        nrows = np.zeros(n, dtype=np.int32)
        seq_arr = np.zeros((n, l), dtype=np.uint8)
        slen = np.zeros(n, dtype=np.int32)

        for d in range(max_rounds):
            active = [i for i in range(n)
                      if not failed[i] and d < len(layer_lists[i])]
            if not active:
                break
            nrows[:] = 0
            slen[:] = 0

            def export(i):
                w = windows[i]
                li = layer_lists[i][d]
                begin, end = w.positions[li]
                blen = len(w.sequences[0])
                offset = int(0.01 * blen)
                full = begin < offset and end > blen - offset
                rows = lib.rt_poab_export(
                    handle, i, begin, end, 1 if full else 0, v, p,
                    self.kcap, bases[i], preds[i].reshape(-1),
                    sinks[i], rank2node[i])
                if rows < 0:
                    failed[i] = True
                    with self._reject_lock:
                        self.reject_counts[rows] = \
                            self.reject_counts.get(rows, 0) + 1
                    obs_decision.DECISIONS.record("poa_reject", code=int(rows),
                                     phase="export")
                    return
                nrows[i] = rows
                s = w.sequences[li]
                seq_arr[i, :len(s)] = np.frombuffer(s, dtype=np.uint8)
                slen[i] = len(s)

            t0 = _mono()
            _map(pool, export, active)
            self.phase_walls["export"] += _mono() - t0
            active = [i for i in active if not failed[i]]
            if not active:
                continue

            # NOTE: no active-lane compaction — the rank scan's cost is
            # per-step overhead x steps, independent of batch width
            # (measured: compacting tail rounds to 32 lanes saved
            # nothing and the extra compiled shapes cost ~5s), so idle
            # lanes in late rounds ride along for free
            t0 = _mono()
            node_tape, seq_tape = self._dispatch(
                bases, preds, nrows, sinks, seq_arr, slen)
            self.phase_walls["dispatch"] += _mono() - t0
            self.n_rounds += 1

            def apply(i):
                w = windows[i]
                li = layer_lists[i][d]
                nt, st = node_tape[i], seq_tape[i]
                k = int(np.argmax(nt == PATH_DONE)) \
                    if (nt == PATH_DONE).any() else nt.shape[0]
                # reversed tape -> forward path; translate ranks -> ids
                pn = nt[:k][::-1].astype(np.int32)
                ps = st[:k][::-1].astype(np.int32)
                mask = pn >= 0
                pn = np.where(mask, rank2node[i][np.clip(pn, 0, None)],
                              PATH_NONE)
                pn = np.ascontiguousarray(pn)
                ps = np.ascontiguousarray(ps)
                s = w.sequences[li]
                q = w.qualities[li]
                lib.rt_poab_apply(
                    handle, i, pn, ps, len(pn), s, len(s),
                    q if q else b"\x00" * len(s), 1 if q else 0,
                    int(w.positions[li][0]))

            t0 = _mono()
            _map(pool, apply, active)
            self.phase_walls["apply"] += _mono() - t0

        # consensus extraction (pooled; the native call releases the GIL)
        results: List[Tuple[Optional[bytes], bool]] = [None] * n
        out_cap = 4 * self.lcap + 4096

        def extract(i):
            if failed[i]:
                results[i] = (None, False)
                return
            # gate on the RAW window sequence count, like the reference
            # (cudabatch.cpp:214-222): layers skipped for length/depth
            # only reduce coverage, they do not demote the window
            if len(windows[i].sequences) < 3:
                # <3 sequences -> backbone verbatim, unpolished
                # (reference: cudabatch.cpp:214-222, window.cpp:68-71)
                results[i] = (windows[i].sequences[0], False)
                return
            out = ctypes.create_string_buffer(out_cap)
            status = ctypes.c_int32(0)
            length = lib.rt_poab_consensus(
                handle, i, windows[i].type.value, 1 if trim else 0,
                out, out_cap, ctypes.byref(status))
            if length < 0:
                results[i] = (None, False)
                return
            if status.value == 2:
                windows[i].warn_chimeric()
            results[i] = (out.raw[:length], True)

        t0 = _mono()
        _map(pool, extract, range(n))
        self.phase_walls["extract"] += _mono() - t0
        return results

    @staticmethod
    def _pow2(n: int, lo: int) -> int:
        from racon_tpu.utils.tuning import pow2_at_least
        return pow2_at_least(n, lo)

    def _band_cols(self, l_b: int) -> int:
        """Effective band width for layer bucket ``l_b`` (0 = unbanded:
        the band would cover the whole row anyway)."""
        return poa_band_cols(l_b, self.banded)

    def _dispatch(self, bases, preds, nrows, sinks, seq_arr, slen):
        # bucket this round's static dims to the active maxima so scan
        # length tracks real graph sizes, not the worst-case caps
        v_b = min(self._pow2(int(nrows.max()), 128), self.vcap)
        l_b = min(self._pow2(int(slen.max()), 128), self.lcap)
        wb = self._band_cols(l_b)
        self.cells += bases.shape[0] * v_b * (wb if wb else l_b + 1)
        args = (bases[:, :v_b], preds[:, :v_b, :], nrows,
                sinks[:, :v_b], seq_arr[:, :l_b], slen)
        n_dev = len(self.mesh.devices) if self.mesh is not None else 1
        if n_dev > 1:
            from racon_tpu.parallel import mesh_utils
            args = [mesh_utils.pad_to_multiple(np.ascontiguousarray(a),
                                               n_dev, 0)
                    for a in args]
            node_tape, seq_tape = mesh_utils.sharded_poa(
                self.mesh, *args, v=v_b, l=l_b, p=self.pcap,
                k=self.kcap, wb=wb, match=self.match,
                mismatch=self.mismatch, gap=self.gap)
            b = bases.shape[0]
            return np.asarray(node_tape)[:b], np.asarray(seq_tape)[:b]
        if wb:
            node_tape, seq_tape = _poa_kernel_banded(
                *(jnp.asarray(a) for a in args), v_b, l_b, self.pcap,
                self.kcap, wb, self.match, self.mismatch, self.gap)
        else:
            node_tape, seq_tape = _poa_kernel(
                *(jnp.asarray(a) for a in args), v_b, l_b, self.pcap,
                self.kcap, self.match, self.mismatch, self.gap)
        return np.asarray(node_tape), np.asarray(seq_tape)


def _map(pool, fn, items):
    if pool is None:
        for it in items:
            fn(it)
    else:
        list(pool.map(fn, items))
