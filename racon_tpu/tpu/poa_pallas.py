"""Full on-device batched POA: the flagship Pallas TPU kernel.

One grid program per window runs the ENTIRE partial-order-alignment
consensus -- graph construction, per-layer banded DP, traceback, graph
merge, heaviest-bundle consensus, TGS trim -- with the POA graph
resident in VMEM/SMEM.  This is the cudapoa architecture (reference:
one CUDA thread block per POA group, src/cuda/cudabatch.cpp:52-265)
mapped to the TensorCore: host involvement is ONE upload of the layer
sequences and ONE download of the finished consensus per megabatch.

Why not the lockstep host-graph design (racon_tpu/tpu/poa.py)?  On the
tunneled-TPU deployment target, host<->device transfers cost ~100 ms
latency each way regardless of size; the lockstep engine pays two per
layer round (~38 rounds on the reference sample workload), which
dominates its wall clock.  This kernel pays two per megabatch.

Graph representation (per program, V node slots):

* per-node scalars in SMEM: base, anchor (backbone position), nseqs,
  list-next, aligned-group-last, topo rank (epoch-tagged);
* adjacency in VMEM int32 arrays: preds/pred weights [V,P], succs/succ
  weights/succ anchors [V,S], aligned groups [V,A];
* topological order is maintained as a singly-linked list grouped by
  alignment column: new columns insert after the previous path node's
  column, new aligned members insert adjacent to their column.  Edges
  only ever point column-forward, so the list stays topologically
  valid and each layer needs one O(V) walk instead of a Kahn sort
  (spoa re-sorts per added sequence; cudapoa re-sorts on device).

The per-layer DP is the same banded graph-vs-sequence recurrence as
the scan kernels in poa.py (band quantum q = wb//4, pred rows fetched
from a [K, wb] VMEM ring, in-row gap chain closed with a max-plus
doubling scan), with first-slot-on-tie direction codes so tracebacks
are deterministic.  Graph-semantics parity target is the native CPU
engine (racon_tpu/native/poa_graph.hpp); like the CUDA path vs spoa,
cost-equal alignment ties may resolve differently, so consensus
equality is validated within an edit tolerance, not byte-for-byte.

Windows that overflow any cap (V nodes, P/S edges, A aligned, K rank
reach, path length) fail with a code and fall back to the CPU engine,
the reference's rejection contract (cudabatch.cpp:124-155 ->
cudapolisher.cpp:357-386).
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_BIG = 1 << 28
_N_SHIFT = 4          # pred band may lag <= 3 quanta of 128
_INF32 = np.int32(2147483647 // 2)

# fail codes (observability parity with the lockstep export codes)
FAIL_VCAP = 1
FAIL_EDGE = 2         # pred/succ slot overflow (pcap analog)
FAIL_KCAP = 3         # pred rank reach > K
FAIL_ALIGNED = 4
FAIL_PATH = 5


def available() -> bool:
    """True when the on-device POA path should be used: a real TPU
    backend, or any backend with interpret mode forced (the multichip
    dryrun and the sharding tests set RACON_TPU_PALLAS_INTERPRET=1 so
    the production dispatch path is exercised without TPU hardware)."""
    if os.environ.get("RACON_TPU_NO_PALLAS"):
        return False
    if os.environ.get("RACON_TPU_PALLAS_INTERPRET") == "1":
        return True
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def band_width(lp: int, banded: bool = False) -> int:
    """The on-device DP band width for layer cap ``lp``: the shared
    band policy (racon_tpu.utils.tuning.poa_band_cols -- one source
    of truth with the lockstep engine and the memory/prewarm shape
    predictions) rounded up to the 128-lane quantum and clamped to
    the padded row."""
    from racon_tpu.utils.tuning import poa_band_cols

    wb = poa_band_cols(lp, banded) or (lp + 1)   # 0 = degenerate
    return min((wb + 127) & ~127, ((lp + 127) & ~127))


def prewarm(b: int, d1: int, *, v: int, lp: int, wb: int,
            p: int = 16, s: int = 16, a: int = 8, k: int = 128,
            match: int = 5, mismatch: int = -4, gap: int = -8,
            wtype: int = 1, trim: int = 1, mesh=None) -> None:
    """Populate the jit dispatch cache for one kernel shape by running
    an inert 1-base batch (device-side zeros, no host upload) through
    THE SAME entry production dispatch uses (sharded when the mesh has
    more than one device).  Called from a background thread while the
    align stage owns the device: kernel tracing (~1 s) plus the
    persistent-cache compile load (~1.5 s) dominate cold starts when
    paid serially."""
    seqs = np.zeros((b, d1, lp), np.uint8)
    seqs[:, 0, 0] = ord("A")
    wts = np.ones((b, d1, lp), np.uint8)
    meta = np.zeros((b, d1, 8), np.int32)
    nlay = np.zeros((b,), np.int32)
    bblen = np.ones((b,), np.int32)
    poa_full_batch(seqs, wts, meta, nlay, bblen, v=v, lp=lp, d1=d1,
                   p=p, s=s, a=a, k=k, wb=wb, match=match,
                   mismatch=mismatch, gap=gap, wtype=wtype, trim=trim,
                   mesh=mesh)


def fits(v: int, lp: int, d1: int, p: int, s: int, a: int,
         wb: int) -> bool:
    """Conservative per-program VMEM estimate: ring + dirs (v x wb),
    adjacency, lane-padded path/output refs, double-buffered input
    blocks.  Configurations over budget (e.g. -w 1000 doubles every
    cap) use the lockstep engine instead of failing to compile."""
    vmem = (v * wb * 8                        # ring f32 + dirs i32
            + v * (p + s) * 4                 # adjacency ids (VMEM)
            + v * a * 4                       # aligned groups
            + 2 * 8 * (lp + 256) * 4          # staged chw + chars rows
            + (wb + _N_SHIFT * 128) * 4       # pred-fold staging row
            + 2 * 2 * d1 * lp * 4             # seq/wts blocks x2 buf
            + 2 * v * 128 * 4)                # cons out x2 buf
    # SMEM: per-node scalars + pred mirror + weights + the packed
    # path + the layer chw mirror; configs past the budget fail over
    # to the lockstep engine instead of dying in the Mosaic compiler
    smem = (v * (p + 8 + 13)
            + (v + lp) + 8 * (lp + 256) + d1 * 8) * 4
    return vmem <= (13 << 20) and smem <= (768 << 10)


def _kernel(nlay_ref, bblen_ref,
            seqs_ref, wts_ref, meta_ref,
            cons_ref, mout_ref,
            preds_v, succs_v, stage_v,
            ring_v, dirs, accs, arga, chw_v, chars_v, aligsm_v,
            base_s, anch_s, nseq_s, nxt_s, glast_s,
            bandq_s, pcnt_s, scnt_s, predsm_s, order_s,
            score_s, cpred_s, predw_s,
            path_s, gcnt_s, regs_s,
            minsucc_s, chw_s, sem, *,
            v: int, lp: int, d1: int, p: int, s_: int, a_: int,
            k: int, wb: int,
            match: int, mismatch: int, gap: int,
            wtype: int, trim: int):
    i = pl.program_id(0)
    nlay = nlay_ref[i]
    bbl = bblen_ref[i]

    def stage_chw():
        """Copy the staged packed char*256+weight rows into SMEM: the
        merge/seed phases read row 0 per position, and a scalar SMEM
        read is ~20 ns where each vector->scalar lane extraction costs
        a VPU sync (~1 us) -- the round-3 merge bottleneck.  The copy
        moves the whole (8, lp+256) staging block because DMA slices
        must be 8-sublane aligned; rows 1-7 are ballast."""
        cp = pltpu.make_async_copy(chw_v, chw_s, sem)
        cp.start()
        cp.wait()
    q = 128               # band-start quantum: 128-aligned lane slices
                          # are free; 64-offset slices cost a rotation
    tape = v + lp
    negf = jnp.float32(-float(_BIG))
    matchf = jnp.float32(match)
    mismatchf = jnp.float32(mismatch)
    gapf = jnp.float32(gap)
    cols_i = lax.broadcasted_iota(jnp.int32, (1, wb), 1)
    colsf = cols_i.astype(jnp.float32)
    colsg = colsf * jnp.float32(gap)
    iota_p = lax.broadcasted_iota(jnp.int32, (1, p), 1)
    iota_s = lax.broadcasted_iota(jnp.int32, (1, s_), 1)
    iota_a = lax.broadcasted_iota(jnp.int32, (1, a_), 1)
    iota_c128 = lax.broadcasted_iota(jnp.int32, (1, 128), 1)
    # path pack radix: entry = (node+2)*pkr + (spos+2); spos < lp and
    # node < v, so pkr must clear lp (the wrapper asserts the product
    # fits int32)
    pkr = 1
    while pkr < lp + 8:
        pkr <<= 1

    # ---- scratch bulk init (scratch persists across grid programs) --
    # edge WEIGHTS (and the succ-slot -> pred-slot mirror) live in
    # SMEM: the merge phase accumulates a weight on almost every path
    # step, and a scalar SMEM R/W is ~20 ns where a dynamic-sublane
    # VMEM row RMW is ~800 ns; weight slots are written at edge
    # creation before any read, so they need no bulk init
    iota_v0 = lax.broadcasted_iota(jnp.int32, (v, 1), 0)
    bblm = jnp.minimum(bbl, v)
    # backbone chain adjacency, vectorized (one column store each)
    preds_v[:, :] = jnp.full((v, p), -1, jnp.int32)
    preds_v[:, 0:1] = jnp.where((iota_v0 > 0) & (iota_v0 < bblm),
                                iota_v0 - 1, -1)
    succs_v[:, :] = jnp.full((v, s_), -1, jnp.int32)
    succs_v[:, 0:1] = jnp.where(iota_v0 < bblm - 1, iota_v0 + 1, -1)
    chw_v[:, :] = jnp.zeros((8, lp + 256), jnp.int32)
    chars_v[:, :] = jnp.zeros((8, lp + 256), jnp.int32)
    # the pred-fold staging row: [0, wb) is overwritten per fold, the
    # [wb, wb + N_SHIFT*q) tail stays -inf so a lagging pred's shifted
    # window reads -inf beyond its band (replaces the pad+4-select
    # fold with one store + one 128-aligned dynamic-lane load)
    stage_v[0:1, :] = jnp.full((1, wb + _N_SHIFT * q), negf,
                               jnp.float32)

    def init_bandq(j, _):
        bandq_s[j] = jnp.int32(-1)
        gcnt_s[j] = jnp.int32(0)
        return 0

    lax.fori_loop(0, v, init_bandq, 0)

    # regs: 0 fail, 1 head, 2 nodes_len, 3 n_seqs_incl, 4 rank_steps
    regs_s[0] = jnp.int32(0)
    regs_s[3] = jnp.int32(1)
    regs_s[4] = jnp.int32(0)

    def e11(val2d):
        """(1,1) value -> scalar."""
        return val2d[0, 0]

    def vload(ref, row):
        return ref[pl.ds(row, 1), :]

    def min_idx(mask, width, iota_row):
        """First lane index where mask is true, else width."""
        return e11(jnp.min(jnp.where(mask, iota_row, width),
                           axis=1, keepdims=True))

    # ---- seed the backbone chain (add_alignment with an empty path:
    # racon_tpu/native/poa_graph.hpp add_alignment initial branch) ----
    @pl.when(bbl > v)
    def _():
        regs_s[0] = jnp.int32(FAIL_VCAP)

    # stage char*256+weight in VMEM (the DP band load windows into it)
    # and mirror it into SMEM (seed/merge read per position)
    chw_v[0:1, 0:lp] = seqs_ref[0, 0:1, :] * 256 + wts_ref[0, 0:1, :]
    stage_chw()

    def chw_at(j):
        """(char, weight) at dynamic position j: scalar SMEM reads of
        the mirrored row, no VPU involvement."""
        x = chw_s[0, j]
        return x // 256, x % 256

    def seed(j, prev_w):
        c, w = chw_at(j)
        base_s[j] = c
        anch_s[j] = j
        nseq_s[j] = jnp.int32(1)
        nxt_s[j] = jnp.where(j + 1 < bbl, j + 1, -1)
        glast_s[j] = j
        pcnt_s[j] = jnp.where(j > 0, 1, 0)
        scnt_s[j] = jnp.where(j + 1 < bbl, 1, 0)
        minsucc_s[j] = jnp.where(j + 1 < bbl, j + 1, _INF32)
        predsm_s[j * 8] = j - 1

        @pl.when(j > 0)
        def _():
            # chain ids/anchors were written vectorized above; only
            # the data-dependent weight is per-node (pred-side only:
            # consensus scores in-edges, so succ weights would be
            # dead state -- racon_tpu/native/poa_graph.hpp keeps both
            # but only reads pred weights in consensus_path too)
            predw_s[j * p] = prev_w + w
        return w

    lax.fori_loop(0, jnp.minimum(bbl, v), seed, jnp.int32(0))
    regs_s[1] = jnp.int32(0)                   # list head
    regs_s[2] = jnp.minimum(bbl, v)            # nodes_len

    # ---- helpers shared by the merge step ---------------------------

    def insert_after(pos, node):
        """Linked-list insert; pos == -1 -> new head."""
        @pl.when(pos >= 0)
        def _():
            nxt_s[node] = nxt_s[pos]
            nxt_s[pos] = node

        @pl.when(pos < 0)
        def _():
            nxt_s[node] = regs_s[1]
            regs_s[1] = node

    def new_node(c, anchor, pos):
        """Allocate a node and insert it after list position pos."""
        nid = regs_s[2]
        ok = nid < v

        @pl.when(ok)
        def _():
            base_s[nid] = c
            anch_s[nid] = anchor
            nseq_s[nid] = jnp.int32(0)
            glast_s[nid] = nid
            bandq_s[nid] = jnp.int32(-1)
            # slot 0 must be initialized: a zero-pred node's traceback
            # diag code still reads mirror slot 0 (cnt-bounded readers
            # cover slots >= 1 only)
            predsm_s[nid * 8] = jnp.int32(-1)
            pcnt_s[nid] = jnp.int32(0)
            scnt_s[nid] = jnp.int32(0)
            gcnt_s[nid] = jnp.int32(0)
            minsucc_s[nid] = _INF32
            regs_s[2] = nid + 1
            insert_after(pos, nid)

        @pl.when(jnp.logical_not(ok) & (regs_s[0] == 0))
        def _():
            regs_s[0] = jnp.int32(FAIL_VCAP)
        return jnp.where(ok, nid, 0)

    def add_edge(u, t, w):
        """poa_graph.hpp add_edge: accumulate weight on an existing
        u->t edge else append.  The accumulate (the per-path-step hot
        case) is pure SMEM: the hit search walks t's <=8-slot PRED id
        mirror (scalar reads, no vector->scalar sync; in-degree is 1
        for most nodes so the first probe usually decides).  Only the
        pred-side weight exists: consensus scores in-edges only."""
        pc_ = pcnt_s[t]
        found = jnp.int32(-1)
        for pp in range(7, -1, -1):     # descending: first hit wins
            found = jnp.where((pp < pc_) & (predsm_s[t * 8 + pp] == u),
                              pp, found)

        def deep_search(_):
            # rare: in-degree > 8, search the full VMEM id row
            prow = vload(preds_v, t)
            return min_idx(prow == u, p, iota_p)

        def mirror_hit(_):
            return jnp.where(found >= 0, found, p)

        hit = lax.cond((found < 0) & (pc_ > 8), deep_search,
                       mirror_hit, 0)

        @pl.when(hit < p)
        def _():
            hp = t * p + hit
            predw_s[hp] = predw_s[hp] + w

        @pl.when(hit >= p)
        def _():
            free = scnt_s[u]
            prow = vload(preds_v, t)
            pfree = pcnt_s[t]
            okk = (free < s_) & (pfree < p)

            @pl.when(okk)
            def _():
                srow = vload(succs_v, u)
                succs_v[pl.ds(u, 1), :] = jnp.where(iota_s == free, t,
                                                    srow)
                minsucc_s[u] = jnp.minimum(minsucc_s[u], anch_s[t])
                preds_v[pl.ds(t, 1), :] = jnp.where(iota_p == pfree, u,
                                                    prow)
                predw_s[t * p + pfree] = w
                scnt_s[u] = free + 1
                pcnt_s[t] = pfree + 1

                @pl.when(pfree < 8)
                def _():
                    predsm_s[t * 8 + pfree] = u

            @pl.when(jnp.logical_not(okk) & (regs_s[0] == 0))
            def _():
                # don't overwrite an earlier fail (a vcap overflow
                # returns node 0 as the merge target, whose slots then
                # overflow too -- without the guard every vcap reject
                # gets misreported as a pcap reject)
                regs_s[0] = jnp.int32(FAIL_EDGE)

    # ---- per-layer loop ---------------------------------------------

    def layer(d, _):
        @pl.when(regs_s[0] == 0)
        def _do_layer():
            begin = meta_ref[0, d, 0]
            end = meta_ref[0, d, 1]
            fsp = meta_ref[0, d, 2]
            m = meta_ref[0, d, 3]
            regs_s[3] = regs_s[3] + jnp.where(m > 0, 1, 0)
            # stage chars (DP band loads) and char*256+weight (SMEM
            # mirror for the merge) once per layer
            chars_v[0:1, 0:lp] = seqs_ref[0, pl.ds(d, 1), :]
            chw_v[0:1, 0:lp] = chars_v[0:1, 0:lp] * 256 \
                + wts_ref[0, pl.ds(d, 1), :]
            stage_chw()

            # 1+2) fused walk + banded DP: ONE pass over the topo list
            # computes each in-subset node's row AND folds the sink
            # scores inline.  Band placement is ANCHOR-based -- a
            # node's expected query column scales with its backbone
            # anchor -- so no pre-walk is needed to count subset ranks
            # (the former separate walk cost ~0.24 us per listed node,
            # ~25% of the kernel).  Anchors are non-decreasing along
            # edges, so a predecessor's band never leads its
            # successor's, preserving the dq >= 0 invariant the
            # rank-based placement had.
            end_eff = jnp.where(fsp > 0, _INF32 - 1, end)
            smax_q = (jnp.maximum(m + 1 - wb, 0) + q - 1) // q
            span = jnp.maximum(end - begin, 1)
            # q8 fixed-point band slope per subset rank: nr is the
            # list length for full-span layers (their subset is the
            # whole graph) and a backbone-density estimate for partial
            # layers; one multiply+shift per rank replaces a dynamic
            # divide (nvis <= v, slope < 2^18 only when nr_est is 1
            # and m is at cap -- products stay inside int32)
            nr_est = jnp.where(
                fsp > 0, regs_s[2],
                jnp.maximum(1, (span * regs_s[2]) // bblm))
            slope_q8 = (m * 256) // jnp.maximum(nr_est, 1)
            regs_s[6] = jnp.int32(-1)          # best sink node
            regs_s[7] = jnp.int32(-_BIG)       # best sink score

            def slot_meta(pid, cnt, t):
                """(epoch-valid, band-start) for one pred slot."""
                be = bandq_s[jnp.clip(pid, 0, v - 1)]
                valid = (t < cnt) & (pid >= 0) & ((be >> 8) == d)
                return valid, jnp.where(valid, be & 255, 0)

            def pred_fold(pid, valid, sqp, sq_r):
                """One predecessor's H row realigned to this rank's
                band, in vert space (u[c] = H_pred[s_r + c]); the diag
                view is u shifted by one, applied once per rank after
                the fold since the shift commutes with the max.

                The row is staged into stage_v[0, :wb] and re-read at
                lane offset dq*q (128-aligned, so the dynamic slice is
                free); the staging tail stays -inf, covering the
                shifted window's overhang.  One store + one load + one
                select replaces the former pad + N_SHIFT selects."""
                dq = sq_r - sqp
                ok = valid & (dq >= 0) & (dq < _N_SHIFT)
                dqc = jnp.clip(dq, 0, _N_SHIFT - 1)
                stage_v[0:1, 0:wb] = ring_v[pl.ds(jnp.maximum(pid, 0),
                                                  1), :]
                hv = stage_v[0:1, pl.ds(pl.multiple_of(dqc * q, q),
                                        wb)]
                hv = jnp.where(ok, hv, negf)
                # a predecessor whose band lags out of shift range
                # cannot contribute; silently degrading would corrupt
                # the consensus, so the window must fail to the CPU
                # engine (the lockstep path's kcap reject analog)
                bad = valid & jnp.logical_not(ok)
                return hv, jnp.where(valid, 1, 0), bad

            def acc_update(hv, t):
                a0 = accs[0:1, :]
                up = hv > a0
                accs[0:1, :] = jnp.where(up, hv, a0)
                arga[0:1, :] = jnp.where(up, t, arga[0:1, :])

            def epilogue(node, s_r, accu, argu):
                """Row finish shared by both in-degree branches: sub
                scores, the three-way move max, the in-row gap chain,
                direction codes, stores."""
                # this band's seq chars: one 128-aligned window load;
                # chars past the layer length are 0 pads and never
                # equal a real base, so no explicit j < m mask
                sb = chars_v[0:1, pl.ds(pl.multiple_of(s_r, q), wb)]
                sub_u = jnp.where(sb == base_s[node], matchf,
                                  mismatchf)
                dmax_u = accu + sub_u
                vmax = accu + gapf
                dmax = jnp.pad(dmax_u, ((0, 0), (1, 0)),
                               constant_values=negf)[:, :wb]
                t_best = jnp.maximum(dmax, vmax)
                x = t_best - colsg
                sh = 1
                while sh < wb:
                    x = jnp.maximum(
                        x, jnp.pad(x, ((0, 0), (sh, 0)),
                                   constant_values=negf)[:, :wb])
                    sh <<= 1
                hr = x + colsg
                argd = jnp.pad(argu, ((0, 0), (1, 0)),
                               constant_values=0)[:, :wb]
                code = jnp.where(
                    dmax == hr, argd,
                    jnp.where(vmax == hr, argu + p,
                              2 * p)).astype(jnp.int32)
                dirs[pl.ds(node, 1), :] = code
                ring_v[pl.ds(node, 1), :] = hr

            def dp_cond(c):
                return c[0] >= 0

            def dp_body(c):
                node, nvis = c
                anc = anch_s[node]
                in_sub = (fsp > 0) | ((anc >= begin) & (anc <= end))

                @pl.when(in_sub)
                def _():
                    cnt = pcnt_s[node]
                    # rank-based band placement from the carried
                    # in-subset counter: sq is monotone along the topo
                    # list, so a successor's band never lags any
                    # predecessor's (the dq >= 0 invariant), exactly
                    # like the pre-fusion two-pass design.  Subset
                    # SINKS snap to the last quantum: their row is
                    # only ever read at column m - s_r (the inline
                    # sink fold below), and the floor-quantized
                    # interpolation can misplace by up to q-1 columns,
                    # which at narrow bands (-b, wb == q) would push
                    # the end column out of every sink's band and fail
                    # the window
                    is_sink_n = minsucc_s[node] > end_eff
                    sq_r = jnp.where(
                        is_sink_n, smax_q,
                        jnp.clip(
                            (((nvis * slope_q8) >> 8) - (q // 2)) >> 7,
                            0, smax_q))
                    s_r = sq_r * q
                    pid0 = jnp.where(cnt > 0, predsm_s[node * 8], -1)
                    val0, sqp0 = slot_meta(pid0, cnt, 0)
                    pid1 = predsm_s[node * 8 + 1]
                    val1, sqp1 = slot_meta(pid1, cnt, 1)
                    pid2 = predsm_s[node * 8 + 2]
                    val2, sqp2 = slot_meta(pid2, cnt, 2)
                    pid3 = predsm_s[node * 8 + 3]
                    val3, sqp3 = slot_meta(pid3, cnt, 3)
                    vvb = s_r.astype(jnp.float32) * gapf

                    regs_s[8] = jnp.int32(0)   # nreal slots 1+
                    regs_s[9] = jnp.int32(0)   # nbad slots 1+
                    hv0, nv0, bad0 = pred_fold(pid0, val0, sqp0, sq_r)

                    @pl.when(cnt > 1)
                    def _():
                        accs[0:1, :] = hv0
                        arga[0:1, :] = jnp.zeros((1, wb), jnp.int32)
                        for t, (pid, val, sqp) in (
                                (1, (pid1, val1, sqp1)),
                                (2, (pid2, val2, sqp2)),
                                (3, (pid3, val3, sqp3))):
                            @pl.when(cnt > t)
                            def _(t=t, pid=pid, val=val, sqp=sqp):
                                hv, nv, bad = pred_fold(pid, val, sqp,
                                                        sq_r)
                                acc_update(hv, t)
                                regs_s[8] = regs_s[8] + nv
                                regs_s[9] = regs_s[9] + \
                                    jnp.where(bad, 1, 0)

                        @pl.when(cnt > 4)
                        def _deep():
                            prow = vload(preds_v, node)

                            def deep_step(t, nr2):
                                pid = e11(jnp.sum(
                                    jnp.where(iota_p == t, prow, 0),
                                    axis=1, keepdims=True))
                                val, sqp = slot_meta(pid, cnt, t)
                                hv, nv, bad = pred_fold(pid, val, sqp,
                                                        sq_r)
                                acc_update(hv, t)

                                @pl.when(bad)
                                def _():
                                    regs_s[0] = jnp.int32(FAIL_KCAP)
                                return nr2 + nv

                            regs_s[8] = regs_s[8] + lax.fori_loop(
                                4, cnt, deep_step, jnp.int32(0))

                    nreal = nv0 + regs_s[8]

                    @pl.when((jnp.where(bad0, 1, 0) + regs_s[9]) > 0)
                    def _():
                        regs_s[0] = jnp.int32(FAIL_KCAP)

                    novel = nreal == 0
                    multi = cnt > 1
                    accu = jnp.where(novel, colsg + vvb,
                                     jnp.where(multi, accs[0:1, :],
                                               hv0))
                    argu = jnp.where(novel | jnp.logical_not(multi),
                                     0, arga[0:1, :])
                    epilogue(node, s_r, accu, argu)

                    bandq_s[node] = (d << 8) | sq_r

                    # inline sink fold: only true subset sinks pay the
                    # vector->scalar score extraction
                    @pl.when(minsucc_s[node] > end_eff)
                    def _sink():
                        c_end = m - s_r

                        @pl.when(c_end < wb)
                        def _():
                            hrow = ring_v[pl.ds(node, 1), :]
                            ccl = jnp.clip(c_end, 0, wb - 1)
                            s_end = jnp.sum(jnp.where(
                                cols_i == ccl, hrow,
                                jnp.float32(0))).astype(jnp.int32)

                            @pl.when(s_end > regs_s[7])
                            def _():
                                regs_s[7] = s_end
                                regs_s[6] = node
                return nxt_s[node], nvis + jnp.where(in_sub, 1, 0)

            _, nvis = lax.while_loop(dp_cond, dp_body,
                                     (regs_s[1], jnp.int32(0)))
            regs_s[4] = regs_s[4] + nvis
            best_node = regs_s[6]

            # no subset sink landed within band reach of the layer
            # end (the nr estimate misplaced the bands): tracing back
            # from node -1 would fabricate an all-new path, so the
            # window must fail to the CPU engine instead
            @pl.when((best_node < 0) & (nvis > 0))
            def _():
                regs_s[0] = jnp.int32(FAIL_KCAP)


            # 3) traceback -> reversed path in path_s, packed as
            # (node+2)*pkr + (spos+2); node -1 = no node (horiz),
            # carried node -1 = virtual start row
            def tb_cond(c):
                node, j, step = c
                return ((node >= 0) | (j > 0)) & (step < tape)

            def tb_body(c):
                node, j, step = c
                nodec = jnp.maximum(node, 0)
                be = bandq_s[nodec]
                s0 = jnp.where(node >= 0, be & 255, 0) * q
                cc = jnp.clip(j - s0, 0, wb - 1)
                drow = dirs[pl.ds(nodec, 1), :]
                code = jnp.sum(jnp.where(cols_i == cc, drow, 0))
                is_diag = (code < p) & (node >= 0)
                is_vert = (code >= p) & (code < 2 * p) & (node >= 0)
                take = is_diag | is_vert
                slot = jnp.clip(jnp.where(is_diag, code, code - p),
                                0, p - 1)

                def mirror(_):
                    return predsm_s[nodec * 8 + jnp.clip(slot, 0, 7)]

                def deep(_):
                    prow = vload(preds_v, nodec)
                    return jnp.sum(jnp.where(iota_p == slot, prow, 0))

                pid = lax.cond(slot < 8, mirror, deep, 0)
                pvalid = (pid >= 0) & \
                    ((bandq_s[jnp.clip(pid, 0, v - 1)] >> 8) == d)
                pnode = jnp.where(pvalid, pid, -1)
                en = jnp.where(take, node, -1)
                es = jnp.where(is_vert, -1, j - 1)
                path_s[step] = (en + 2) * pkr + (es + 2)
                nn = jnp.where(take, pnode, node)
                nj = jnp.where(is_vert, j, jnp.maximum(j - 1, 0))
                return nn, nj, step + 1

            _, _, plen = lax.while_loop(
                tb_cond, tb_body, (best_node, m, jnp.int32(0)))

            @pl.when(plen >= tape)
            def _():
                regs_s[0] = jnp.int32(FAIL_PATH)

            # 4) merge (poa_graph.hpp add_alignment), walking the
            # reversed path backward = forward order; chars/weights
            # come from the row staged at layer start
            def merge(t, carry):
                # flattened per-step control flow: the dominant case
                # (match into an existing same-base node) runs with
                # ONE vector->scalar sync (the char extraction) and
                # no lax.cond; rare cases (insertion, mismatch into
                # an aligned group) sit behind one pl.when
                prev, prev_w = carry
                packed = path_s[plen - 1 - t]
                nid = packed // pkr - 2
                j = packed % pkr - 2
                has = j >= 0
                c, w = chw_at(jnp.maximum(j, 0))
                fast = has & (nid >= 0) & \
                    (base_s[jnp.maximum(nid, 0)] == c)
                regs_s[10] = nid        # resolved target (fast case)

                @pl.when(has & jnp.logical_not(fast))
                def _slow():
                    def t_new(_):
                        anchor = jnp.where(
                            prev < 0, begin,
                            anch_s[jnp.maximum(prev, 0)])
                        pos = jnp.where(
                            prev < 0, -1,
                            glast_s[jnp.maximum(prev, 0)])
                        return new_node(c, anchor, pos)

                    def t_aligned(_):
                        # mismatch: reuse an aligned sibling with the
                        # same base else create one (poa_graph.hpp
                        # aligned-group branch).  Group lists live in
                        # VMEM as (sib * 256 + sib_base) entries: the
                        # base tag makes the same-base search one
                        # vector compare + extract, and group members
                        # have distinct bases by construction so at
                        # most one entry matches
                        gc = gcnt_s[nid]
                        arow = vload(aligsm_v, nid)
                        h = e11(jnp.min(jnp.where(
                            (arow % 256 == c) & (iota_a < gc),
                            arow // 256, v), axis=1, keepdims=True))
                        found = jnp.where(h < v, h, -1)

                        def mk_new(_):
                            tgt = new_node(c, anch_s[nid],
                                           glast_s[nid])

                            @pl.when(gc >= a_)
                            def _():
                                regs_s[0] = jnp.int32(FAIL_ALIGNED)

                            @pl.when(gc < a_)
                            def _():
                                # tgt's group = nid's members + nid
                                nb = base_s[nid]
                                aligsm_v[pl.ds(tgt, 1), :] = jnp.where(
                                    iota_a == gc, nid * 256 + nb, arow)
                                gcnt_s[tgt] = gc + 1

                                # append tgt to each member (groups
                                # already full skip the append, like
                                # the full-row no-op store before)
                                def ap(aa, _):
                                    sib = e11(jnp.sum(jnp.where(
                                        iota_a == aa, arow, 0), axis=1,
                                        keepdims=True)) // 256
                                    gs = gcnt_s[sib]

                                    @pl.when(gs < a_)
                                    def _():
                                        srow_a = vload(aligsm_v, sib)
                                        aligsm_v[pl.ds(sib, 1), :] = \
                                            jnp.where(iota_a == gs,
                                                      tgt * 256 + c,
                                                      srow_a)
                                        gcnt_s[sib] = gs + 1
                                    glast_s[sib] = tgt
                                    return 0

                                lax.fori_loop(0, gc, ap, 0)
                                aligsm_v[pl.ds(nid, 1), :] = jnp.where(
                                    iota_a == gc, tgt * 256 + c, arow)
                                gcnt_s[nid] = gc + 1
                                glast_s[nid] = tgt
                            return tgt

                        return lax.cond(found >= 0, lambda _: found,
                                        mk_new, 0)

                    regs_s[10] = lax.cond(nid < 0, t_new, t_aligned, 0)

                target = regs_s[10]

                @pl.when(has)
                def _():
                    nseq_s[target] = nseq_s[target] + 1

                    @pl.when(prev >= 0)
                    def _():
                        add_edge(prev, target, prev_w + w)

                return (jnp.where(has, target, prev),
                        jnp.where(has, w, prev_w))

            lax.fori_loop(0, plen, merge,
                          (jnp.int32(-1), jnp.int32(0)))
        return 0

    lax.fori_loop(1, nlay + 1, layer, 0)

    # ---- consensus: heaviest bundle over the full graph -------------
    fail = regs_s[0]

    mout_ref[0, :, :] = jnp.zeros((8, 1), jnp.int32)
    mout_ref[0, 0:1, 0:1] = jnp.full((1, 1),
                                     jnp.where(fail == 0, 0, -1),
                                     jnp.int32)
    mout_ref[0, 2:3, 0:1] = jnp.full((1, 1), fail, jnp.int32)
    mout_ref[0, 3:4, 0:1] = jnp.full((1, 1), regs_s[2], jnp.int32)
    mout_ref[0, 4:5, 0:1] = jnp.full((1, 1), regs_s[4], jnp.int32)

    @pl.when(fail == 0)
    def _consensus():
        # walk the list once for a full topo order
        def wcond(c):
            return c[0] >= 0

        def wbody(c):
            node, r = c
            order_s[r] = node
            return nxt_s[node], r + 1

        _, n_all = lax.while_loop(wcond, wbody,
                                  (regs_s[1], jnp.int32(0)))

        # forward DP: per node pick the heaviest in-edge (ties ->
        # higher predecessor score; slot order = insertion order,
        # matching poa_graph.hpp consensus_path).  Ids come from the
        # SMEM mirror for the common <=4-pred case, weights from SMEM.
        def cdp(r, best_sink):
            node = order_s[r]
            cnt = pcnt_s[node]

            def pick(t, carry):
                bu, bw = carry

                def mirror(_):
                    return predsm_s[node * 8 + jnp.clip(t, 0, 7)]

                def deep(_):
                    prow = vload(preds_v, node)
                    return e11(jnp.sum(
                        jnp.where(iota_p == t, prow, 0), axis=1,
                        keepdims=True))

                pid = lax.cond(t < 8, mirror, deep, 0)
                w = predw_s[node * p + t]
                sc = score_s[jnp.maximum(pid, 0)]
                bsc = score_s[jnp.maximum(bu, 0)]
                tk = (pid >= 0) & ((w > bw) |
                                   ((w == bw) & (bu >= 0) &
                                    (sc > bsc)))
                return (jnp.where(tk, pid, bu), jnp.where(tk, w, bw))

            best_u, best_w = lax.fori_loop(
                0, cnt, pick, (jnp.int32(-1), jnp.int32(-1)))
            score_s[node] = jnp.where(
                best_u >= 0,
                score_s[jnp.maximum(best_u, 0)] + best_w, 0)
            cpred_s[node] = best_u
            is_sink = minsucc_s[node] >= _INF32
            better = is_sink & (
                (best_sink < 0) |
                (score_s[node] > score_s[jnp.maximum(best_sink, 0)]))
            return jnp.where(better, node, best_sink)

        best_sink = lax.fori_loop(0, n_all, cdp, jnp.int32(-1))

        # backtrack into pthn_v (reversed), then emit forward
        def bcond(c):
            return c[0] >= 0

        def bbody(c):
            node, ln = c
            path_s[ln] = (node + 2) * pkr + 2
            return cpred_s[node], ln + 1

        _, clen = lax.while_loop(bcond, bbody,
                                 (best_sink, jnp.int32(0)))

        # TGS trim (rt_poab_consensus: threshold (n_seqs - 1) / 2)
        avg = (regs_s[3] - 1) // 2

        def scan_fwd(t, first):
            node = path_s[clen - 1 - t] // pkr - 2   # forward pos t
            cov = nseq_s[node]
            hit = (first < 0) & (cov >= avg)
            return jnp.where(hit, t, first)

        def scan_bwd(t, last):
            node = path_s[t] // pkr - 2
            cov = nseq_s[node]
            hit = (last < 0) & (cov >= avg)
            return jnp.where(hit, clen - 1 - t, last)

        if wtype == 1 and trim:
            cbegin = lax.fori_loop(0, clen, scan_fwd, jnp.int32(-1))
            cend = lax.fori_loop(0, clen, scan_bwd, jnp.int32(-1))
            chim = (cbegin < 0) | (cend < 0) | (cbegin >= cend)
            cbegin = jnp.where(chim, 0, cbegin)
            cend = jnp.where(chim, clen - 1, cend)
            status = jnp.where(chim, 2, 0).astype(jnp.int32)
        else:
            cbegin = jnp.int32(0)
            cend = clen - 1
            status = jnp.int32(0)

        length = jnp.maximum(cend - cbegin + 1, 0)

        def emit(t, _):
            node = path_s[clen - 1 - (cbegin + t)] // pkr - 2
            cons_ref[0, pl.ds(t, 1), 0:1] = jnp.full(
                (1, 1), base_s[node], jnp.int32)
            return 0

        lax.fori_loop(0, length, emit, 0)
        mout_ref[0, 0:1, 0:1] = jnp.full((1, 1), length, jnp.int32)
        mout_ref[0, 1:2, 0:1] = jnp.full((1, 1), status, jnp.int32)


@functools.partial(
    jax.jit,
    static_argnums=(5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18))
def _poa_full(seqs, wts, meta, nlay, bblen,
              v: int, lp: int, d1: int, p: int, s_: int, a_: int,
              k: int, wb: int, match: int, mismatch: int, gap: int,
              wtype: int, trim: int, interpret: bool = False):
    """seqs/wts: [B, D1, LP] uint8 (d=0 = backbone), meta: [B, D1, 8]
    int32 (begin, end, full_span, slen, ...), nlay/bblen: [B] int32.
    Returns (cons [B, V, 1] int32, mout [B, 8, 1] int32)."""
    b = seqs.shape[0]
    pkr = 1
    while pkr < lp + 8:
        pkr <<= 1
    assert (v + 2) * pkr < 2 ** 31, "path packing overflows int32"
    seqs_l = seqs.astype(jnp.int32)
    wts_l = wts.astype(jnp.int32)

    kern = functools.partial(
        _kernel, v=v, lp=lp, d1=d1, p=p, s_=s_, a_=a_, k=k, wb=wb,
        match=match, mismatch=mismatch, gap=gap,
        wtype=wtype, trim=trim)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, d1, lp), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d1, lp), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d1, 8), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, v, 1), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, 1), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((v, p), jnp.int32),       # preds
            pltpu.VMEM((v, s_), jnp.int32),      # succs
            pltpu.VMEM((1, wb + _N_SHIFT * 128), jnp.float32),  # stage
            pltpu.VMEM((v, wb), jnp.float32),    # ring (node-indexed)
            pltpu.VMEM((v, wb), jnp.int32),      # dirs (node-indexed)
            pltpu.VMEM((8, wb), jnp.float32),    # accs
            pltpu.VMEM((8, wb), jnp.int32),      # arga
            pltpu.VMEM((8, lp + 256), jnp.int32),  # staged chr*256+wt
            pltpu.VMEM((8, lp + 256), jnp.int32),  # staged chars only
            pltpu.VMEM((v, a_), jnp.int32),      # aligned groups
            pltpu.SMEM((v,), jnp.int32),         # base
            pltpu.SMEM((v,), jnp.int32),         # anchor
            pltpu.SMEM((v,), jnp.int32),         # nseqs
            pltpu.SMEM((v,), jnp.int32),         # next
            pltpu.SMEM((v,), jnp.int32),         # group-last
            pltpu.SMEM((v,), jnp.int32),         # band (epoch<<8|sq)
            pltpu.SMEM((v,), jnp.int32),         # pred count
            pltpu.SMEM((v,), jnp.int32),         # succ count
            pltpu.SMEM((8 * v,), jnp.int32),     # pred id mirror
            pltpu.SMEM((v,), jnp.int32),         # order
            pltpu.SMEM((v,), jnp.int32),         # consensus score
            pltpu.SMEM((v,), jnp.int32),         # consensus pred
            pltpu.SMEM((v * p,), jnp.int32),     # pred weights
            pltpu.SMEM((v + lp,), jnp.int32),    # packed path
            pltpu.SMEM((v,), jnp.int32),         # aligned-group count
            pltpu.SMEM((12,), jnp.int32),        # regs
            pltpu.SMEM((v,), jnp.int32),         # min succ anchor
            pltpu.SMEM((8, lp + 256), jnp.int32),  # chw SMEM mirror
            pltpu.SemaphoreType.DMA,             # chw staging sem
        ],
    )
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct((b, v, 1), jnp.int32),
                   jax.ShapeDtypeStruct((b, 8, 1), jnp.int32)),
        interpret=interpret,
    )(nlay, bblen, seqs_l, wts_l, meta)


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "v", "lp", "d1", "p", "s_", "a_", "k",
                     "wb", "match", "mismatch", "gap", "wtype", "trim",
                     "interpret"))
def _poa_full_sharded(seqs, wts, meta, nlay, bblen, *, mesh,
                      v, lp, d1, p, s_, a_, k, wb,
                      match, mismatch, gap, wtype, trim, interpret):
    """The same kernel sharded over the mesh batch axis with shard_map:
    one compile, XLA places one grid per device, no collectives — the
    TPU-native analog of the reference's fully independent per-device
    batch queues (src/cuda/cudapolisher.cpp:231-243)."""
    from racon_tpu.parallel.mesh_utils import shard_batch_map

    def shard_fn(seqs, wts, meta, nlay, bblen):
        return _poa_full(seqs, wts, meta, nlay, bblen,
                         v, lp, d1, p, s_, a_, k, wb,
                         match, mismatch, gap, wtype, trim, interpret)

    return shard_batch_map(shard_fn, mesh, 5, 2)(
        seqs, wts, meta, nlay, bblen)


def poa_full_batch(seqs, wts, meta, nlay, bblen, **kw):
    """NumPy-facing wrapper: dispatch + blocking collect.  Returns
    (cons_chars [B, V] int32 np, mout [B, 8] int32 np).  mout rows:
    0 length (-1 = failed -> CPU re-polish), 1 status (2 = chimeric
    warning), 2 fail code, 3 nodes used, 4 total DP rank steps (for
    cells accounting)."""
    return poa_full_dispatch(seqs, wts, meta, nlay, bblen, **kw)()


def poa_full_dispatch(seqs, wts, meta, nlay, bblen, *,
                      v, lp, d1, p=16, s=16, a=8, k=128, wb=256,
                      match=5, mismatch=-4, gap=-8, wtype=1, trim=1,
                      mesh=None):
    """Enqueue one megabatch and return a zero-arg ``collect``
    closure.  The upload and kernel run asynchronously after dispatch,
    so a caller can pack (and dispatch) the NEXT megabatch while this
    one computes -- the tunnel's upload latency and the host packing
    then overlap device time (the cudapolisher analog runs per-device
    batch queues on threads, src/cuda/cudapolisher.cpp:257-336).

    With a multi-device ``mesh`` the batch axis is sharded across the
    devices (callers pad the batch; this pads further to a mesh
    multiple with inert 1-base windows)."""
    from racon_tpu.parallel.mesh_utils import interpret_mode

    n_dev = len(mesh.devices) if mesh is not None else 1
    interp = interpret_mode()
    b0 = seqs.shape[0]
    if n_dev > 1:
        if b0 % n_dev:
            from racon_tpu.parallel.mesh_utils import pad_to_multiple

            # inert pad windows: 1-base 'A' backbone, no layers
            seqs = pad_to_multiple(seqs, n_dev, 0)
            seqs[b0:, 0, 0] = ord("A")
            wts = pad_to_multiple(wts, n_dev, 1)
            meta = pad_to_multiple(meta, n_dev, 0)
            nlay = pad_to_multiple(nlay, n_dev, 0)
            bblen = pad_to_multiple(bblen, n_dev, 1)
        cons, mout = _poa_full_sharded(
            jnp.asarray(seqs), jnp.asarray(wts), jnp.asarray(meta),
            jnp.asarray(nlay), jnp.asarray(bblen), mesh=mesh,
            v=v, lp=lp, d1=d1, p=p, s_=s, a_=a, k=k, wb=wb,
            match=match, mismatch=mismatch, gap=gap, wtype=wtype,
            trim=trim, interpret=interp)
    else:
        from racon_tpu.utils import aot_shelf

        statics = (v, lp, d1, p, s, a, k, wb, match, mismatch, gap,
                   wtype, trim, interp)

        def build(se, wt, me, nl, bb):
            return _poa_full(se, wt, me, nl, bb, *statics)

        cons, mout = aot_shelf.call(
            ("poa_full", seqs.shape[0]) + statics, __file__, build,
            (jnp.asarray(seqs), jnp.asarray(wts), jnp.asarray(meta),
             jnp.asarray(nlay), jnp.asarray(bblen)))
    # start both device->host copies before blocking on either: the
    # tunnel's per-transfer latency dominates, so pipelining them
    # saves one round trip
    cons.copy_to_host_async()
    mout.copy_to_host_async()

    def collect():
        # slice off mesh-multiple pad rows: the contract is [B, ...]
        return np.asarray(cons)[:b0, :, 0], np.asarray(mout)[:b0, :, 0]

    return collect
