"""Full on-device batched POA: the flagship Pallas TPU kernel.

One grid program runs the ENTIRE partial-order-alignment consensus --
graph construction, per-layer banded DP, traceback, graph merge,
heaviest-bundle consensus, TGS trim -- for a GROUP of S windows
(``pick_windows_per_program``: 5 at the stock w=500 caps, 2 at
w=1000), with all S POA graphs resident in VMEM/SMEM.  This is the
cudapoa architecture (reference: one CUDA thread block per POA group,
src/cuda/cudabatch.cpp:52-265) mapped to the TensorCore: host
involvement is ONE upload of the layer sequences and ONE download of
the finished consensus per megabatch.

Why several windows per program?  The per-rank DP is a serial
dependency chain (pred row -> fold -> move max -> log2(wb) gap-chain
steps -> row store), and measurement shows the kernel is bound by
that chain's LATENCY, not by op count or vector width: duplicating
any individual phase inside the rank body costs ~nothing (the VLIW
scheduler hides it in the chain's stalls), while running the whole
walk twice costs the full +78%.  Another window's chain is exactly
such independent work: interleaving S windows' rank bodies in one
straight-line region lets the scheduler fill one chain's stalls with
the others' ops, targeting ~Sx per-window throughput at unchanged op
count.  S is capped by SMEM: each window's per-node scalars must
stay scalar-addressable.  The r6 diet packs them to 13 ints/node
(down from the r5 diet's 26): the ten per-node scalar arrays hold
values < 2^16, so they live as five half-width PAIRS packed two
fields per int32 (base|nseq, anch|minsucc, nxt|glast, pcnt|scnt,
gcnt|bandq), the whole pred-weight mirror spills to a VMEM row per
node (weights exceed 16 bits and their accumulate is a masked
vector add, not a chain-latency scalar read), and the consensus
score array -- the one field that genuinely needs 32 bits -- aliases
the path tape, which is dead until the consensus backtrack.  That
takes the stock w=500 shape from S=3 to S=5 and w=1000 from 1 to 2.

On top of S, the joint DP walk steps KRANK ranks of every window per
while-loop iteration (multi-rank stepping): topo runs of single-
predecessor backbone nodes -- the overwhelmingly common case -- make
almost every unrolled step productive, so the loop's per-iteration
overhead (condition fold, carry shuffle, region boundary) is paid
once per KRANK ranks and the straight-line region grows to
S x KRANK interleavable rank bodies.  Inert tail steps (a window
whose walk already ended) are free: the rank body is fully gated on
node >= 0.

Why not the lockstep host-graph design (racon_tpu/tpu/poa.py)?  On
the tunneled-TPU deployment target, host<->device transfers cost
~100 ms latency each way regardless of size; the lockstep engine pays
two per layer round (~38 rounds on the reference sample workload),
which dominates its wall clock.  This kernel pays two per megabatch.

Graph representation (per window, V node slots):

* per-node scalars in SMEM: base, anchor (backbone position), nseqs,
  list-next, aligned-group-last, topo rank (epoch-tagged), pred id
  mirror (8 slots) and pred weights;
* adjacency ids in VMEM int32 arrays: preds [V,P], succs [V,S];
  aligned groups [V,A] as base-tagged entries (sib * 256 + sib_base);
* topological order is maintained as a singly-linked list grouped by
  alignment column: new columns insert after the previous path node's
  column, new aligned members insert adjacent to their column.  Edges
  only ever point column-forward, so the list stays topologically
  valid and each layer needs one O(V) walk instead of a Kahn sort
  (spoa re-sorts per added sequence; cudapoa re-sorts on device).

The per-layer DP is the same banded graph-vs-sequence recurrence as
the scan kernels in poa.py (band quantum q = 128, pred rows fetched
from per-node VMEM rows, in-row gap chain closed with a max-plus
doubling scan), with first-slot-on-tie direction codes so tracebacks
are deterministic.  Graph-semantics parity target is the native CPU
engine (racon_tpu/native/poa_graph.hpp); like the CUDA path vs spoa,
cost-equal alignment ties may resolve differently, so consensus
equality is validated within an edit tolerance, not byte-for-byte.

Windows that overflow any cap (V nodes, P/S edges, A aligned, band
reach, path length) fail with a code and fall back to the CPU engine,
the reference's rejection contract (cudabatch.cpp:124-155 ->
cudapolisher.cpp:357-386).
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from racon_tpu.obs import devutil as obs_devutil
from racon_tpu.obs import trace as obs_trace

# the sanctioned clock (racon_tpu/obs): the watcher span feeds only
# the trace and the device_s reporting counter, never control flow
_mono = obs_trace.now

_BIG = 1 << 28
_N_SHIFT = 4          # pred band may lag <= 3 quanta of 128

# fail codes (observability parity with the lockstep export codes)
FAIL_VCAP = 1
FAIL_EDGE = 2         # pred/succ slot overflow (pcap analog)
FAIL_KCAP = 3         # band reach: pred band lagged out of shift
                      # range, or no subset sink within band reach
FAIL_ALIGNED = 4
FAIL_PATH = 5

_NREG = 16            # regs slots per window


def available() -> bool:
    """True when the on-device POA path should be used: a real TPU
    backend, or any backend with interpret mode forced (the multichip
    dryrun and the sharding tests set RACON_TPU_PALLAS_INTERPRET=1 so
    the production dispatch path is exercised without TPU hardware)."""
    if os.environ.get("RACON_TPU_NO_PALLAS"):
        return False
    if os.environ.get("RACON_TPU_PALLAS_INTERPRET") == "1":
        return True
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def band_width(lp: int, banded: bool = False) -> int:
    """The on-device DP band width for layer cap ``lp``: the shared
    band policy (racon_tpu.utils.tuning.poa_band_cols -- one source
    of truth with the lockstep engine and the memory/prewarm shape
    predictions) rounded up to the 128-lane quantum and clamped to
    the padded row."""
    from racon_tpu.utils.tuning import poa_band_cols

    wb = poa_band_cols(lp, banded) or (lp + 1)   # 0 = degenerate
    return min((wb + 127) & ~127, ((lp + 127) & ~127))


def prewarm(b: int, d1: int, *, v: int, lp: int, wb: int,
            p: int = 16, s: int = 16, a: int = 8, k: int = 128,
            match: int = 5, mismatch: int = -4, gap: int = -8,
            wtype: int = 1, trim: int = 1, mesh=None) -> None:
    """Populate the jit dispatch cache for one kernel shape by running
    an inert 1-base batch (device-side zeros, no host upload) through
    THE SAME entry production dispatch uses (sharded when the mesh has
    more than one device).  Called from a background thread while the
    align stage owns the device: kernel tracing (~1 s) plus the
    persistent-cache compile load (~1.5 s) dominate cold starts when
    paid serially."""
    seqs = np.zeros((b, d1, lp), np.uint8)
    seqs[:, 0, 0] = ord("A")
    wts = np.ones((b, d1, lp), np.uint8)
    meta = np.zeros((b, d1, 8), np.int32)
    nlay = np.zeros((b,), np.int32)
    bblen = np.ones((b,), np.int32)
    poa_full_batch(seqs, wts, meta, nlay, bblen, v=v, lp=lp, d1=d1,
                   p=p, s=s, a=a, k=k, wb=wb, match=match,
                   mismatch=mismatch, gap=gap, wtype=wtype, trim=trim,
                   mesh=mesh)


def _fits_s(v: int, lp: int, d1: int, p: int, s: int, a: int,
            wb: int, s_win: int, krank: int = 1) -> bool:
    """Conservative per-program VMEM/SMEM estimate for the kernel at
    ``s_win`` windows per program and ``krank`` ranks per joint DP
    iteration."""
    vmem = (s_win * v * wb * 4                # packed score|code rows
            + s_win * v * (p + s) * 4         # adjacency ids (VMEM)
            + s_win * v * a * 4               # aligned groups
            + s_win * v * p * 4               # pred-weight rows (all
                                              # p slots; r6 diet moved
                                              # the 8-slot SMEM mirror
                                              # here)
            + 2 * 8 * (lp + 256) * 4          # staged chw + chars rows
            + 2 * 2 * s_win * d1 * lp * 4)    # seq/wts blocks x2 buf
    # the kernel is granted a 64M scoped-vmem limit (v5e has 128M);
    # the compiler's stack temporaries for the interleaved straight-
    # line window bodies come out of the same scope (measured r5:
    # ~3M per window body at krank=1, d1=32; each extra unrolled rank
    # body adds ~0.75M since the per-window carried state is shared
    # across the unroll) -- budget declared + temps against 44M,
    # leaving 20M slack for pipeline buffers and measurement error
    temps = s_win * ((3 << 20) + ((3 << 20) >> 2) * (krank - 1))
    # SMEM per window after the r6 diet: FIVE packed v-sized arrays
    # (base|nseq, anch|minsucc, nxt|glast, pcnt|scnt, gcnt|bandq --
    # every field < 2^16; consensus cpred/order reuse the bandq/glast
    # halves, consensus score aliases the 32-bit path tape), the
    # 8-slot pred id mirror, the packed path and regs; shared: the
    # chw mirror and the consensus staging
    smem = (s_win * (v * (5 + 8) + (v + lp) + _NREG)
            + 8 * (lp + 256) + s_win * (v // 128) * 128
            + s_win * d1 * 8) * 4
    return vmem + temps <= (44 << 20) and smem <= (768 << 10)


def _forced_env_factor(name: str) -> int:
    """Parse a forced kernel-shape factor env var; None when unset.
    Malformed values fail LOUDLY naming the variable (a typo silently
    routing every window to the lockstep engine cost a round of
    confusion, ADVICE r5)."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return None
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(
            f"{name} must be a positive integer, got {raw!r}")
    if not 1 <= val <= 8:
        raise ValueError(f"{name} must be in [1, 8], got {val}")
    return val


def pick_windows_per_program(v: int, lp: int, d1: int, p: int = 16,
                             s: int = 16, a: int = 8,
                             wb: int = 256) -> int:
    """Largest windows-per-program factor the budget allows (0 = the
    shape does not fit at all and the caller must use the lockstep
    engine).  More windows per program = more independent serial DP
    chains for the VLIW scheduler to interleave (see module
    docstring); the stock w=500 config fits 5 after the r6 SMEM diet,
    the w=1000 config 2."""
    force = _forced_env_factor("RACON_TPU_POA_SWIN")
    if force is not None:
        if _fits_s(v, lp, d1, p, s, a, wb, force):
            return force
        import warnings
        warnings.warn(
            f"RACON_TPU_POA_SWIN={force} exceeds the kernel budget "
            f"for shape v={v} lp={lp} d1={d1} wb={wb}; the flagship "
            "kernel is unavailable and windows fall back to the "
            "lockstep engine", RuntimeWarning, stacklevel=2)
        return 0
    for s_win in (6, 5, 4, 3, 2, 1):
        if _fits_s(v, lp, d1, p, s, a, wb, s_win):
            return s_win
    return 0


def pick_rank_unroll(v: int, lp: int, d1: int, p: int = 16,
                     s: int = 16, a: int = 8, wb: int = 256,
                     s_win: int = 0) -> int:
    """Ranks of every window processed per joint DP iteration
    (multi-rank stepping, see module docstring).  Largest of 4/2/1
    whose compiler-temp footprint still fits next to ``s_win``
    interleaved windows; RACON_TPU_POA_KRANK forces it (budget-
    rejected forces warn and fall back to the policy pick)."""
    if not s_win:
        s_win = pick_windows_per_program(v, lp, d1, p, s, a, wb)
    if s_win <= 0:
        return 1
    force = _forced_env_factor("RACON_TPU_POA_KRANK")
    if force is not None:
        if _fits_s(v, lp, d1, p, s, a, wb, s_win, force):
            return force
        import warnings
        warnings.warn(
            f"RACON_TPU_POA_KRANK={force} exceeds the kernel budget "
            f"for shape v={v} lp={lp} d1={d1} wb={wb} at "
            f"{s_win} windows/program; using the policy pick instead",
            RuntimeWarning, stacklevel=2)
    for krank in (4, 2, 1):
        if _fits_s(v, lp, d1, p, s, a, wb, s_win, krank):
            return krank
    return 1


def fits(v: int, lp: int, d1: int, p: int, s: int, a: int,
         wb: int) -> bool:
    """True when the flagship kernel can run this shape at SOME
    windows-per-program factor.  Configurations over budget use the
    lockstep engine instead of failing to compile."""
    return pick_windows_per_program(v, lp, d1, p, s, a, wb) > 0


def padded_batch(b: int, n_dev: int, v: int, lp: int, d1: int,
                 p: int = 16, s: int = 16, a: int = 8,
                 wb: int = 256) -> int:
    """The batch size dispatch will actually run for a caller-side
    batch of ``b``: rounded up to a multiple of the windows-per-program
    factor times the device count.  Prewarm/prebuild paths must
    predict THIS number or they compile a variant production never
    uses (and the AOT-shelf key never matches)."""
    s_win = max(1, pick_windows_per_program(v, lp, d1, p, s, a, wb))
    mult = s_win * max(1, n_dev)
    return b + (-b) % mult


# packed SMEM pairs (r6 diet): each (v,) int32 array holds TWO
# 16-bit fields, lo | hi << 16 (every field's range is < 2^16):
#   bnsq: base | nseq        anms: anch | minsucc (0xFFFF = inf)
#   nxgl: nxt+1 | glast      pcsc: pcnt | scnt
#   gcbq: gcnt | bandq       (bandq packs (d << 8) | band quantum;
#                             0 = no epoch, valid only when the
#                             stored d matches the current layer)
# consensus reuse: cpred lives in the bandq half (biased +1), order
# in the glast half, and the 32-bit score array aliases the path
# tape (dead until the consensus backtrack).
_SCRATCH_PER_WIN = ("preds", "succs", "ring", "accs",
                    "arga", "aligsm", "predwv", "bnsq", "anms",
                    "nxgl", "pcsc", "gcbq", "predsm", "path", "regs")

_INF16 = np.int32(0xFFFF)     # minsucc "no successor" sentinel


def _kernel(nlay_ref, bblen_ref,
            seqs_ref, wts_ref, meta_ref,
            cons_ref, mout_ref, *scr,
            v: int, lp: int, d1: int, p: int, s_: int, a_: int,
            k: int, wb: int, s_win: int, krank: int,
            match: int, mismatch: int, gap: int,
            wtype: int, trim: int, prof: int = 0):
    S = s_win
    i = pl.program_id(0)
    nlay_u = [nlay_ref[S * i + u] for u in range(S)]
    bbl_u = [bblen_ref[S * i + u] for u in range(S)]
    # every per-window array is its own ref: the S windows' walks
    # interleave in one straight-line region, and DISTINCT refs are
    # what lets the scheduler prove window B's loads cannot alias
    # window A's stores (a shared ref with u*v offsets serializes the
    # group -- measured r5: zero speedup from pairing until the split)
    grp = {}
    for gi, name in enumerate(_SCRATCH_PER_WIN):
        grp[name] = tuple(scr[gi * S + u] for u in range(S))
    chw_v, chars_v, chw_s, cons_sm, sem = \
        scr[len(_SCRATCH_PER_WIN) * S:]
    preds_u = grp["preds"]
    succs_u = grp["succs"]
    ring_u = grp["ring"]
    accs_u = grp["accs"]
    arga_u = grp["arga"]
    aligsm_u = grp["aligsm"]
    predwv_u = grp["predwv"]
    bnsq_u = grp["bnsq"]
    anms_u = grp["anms"]
    nxgl_u = grp["nxgl"]
    pcsc_u = grp["pcsc"]
    gcbq_u = grp["gcbq"]
    predsm_u = grp["predsm"]
    path_u = grp["path"]
    regs_u = grp["regs"]
    # consensus score is the one per-node field needing 32 bits; it
    # aliases the path tape, dead until the consensus backtrack (the
    # backtrack only starts after the forward DP's last score read)
    score_u = path_u

    M16 = jnp.int32(0xFFFF)
    NM16 = jnp.int32(-65536)          # ~M16: keep-hi mask

    def lo16(x):
        """Unsigned lo half of a packed word."""
        return x & M16

    def hi16(x):
        """Unsigned hi half of a packed word (mask because the int32
        arithmetic shift sign-extends when the hi field's top bit is
        set, e.g. the 0xFFFF minsucc sentinel)."""
        return (x >> 16) & M16

    def stage_chw():
        """Copy the staged packed char*256+weight rows into SMEM: the
        merge/seed phases read row u per position, and a scalar SMEM
        read is ~20 ns where each vector->scalar lane extraction costs
        a VPU sync -- the round-3 merge bottleneck.  The copy moves
        the whole (8, lp+256) staging block because DMA slices must be
        8-sublane aligned; rows S..7 are ballast."""
        cp = pltpu.make_async_copy(chw_v, chw_s, sem)
        cp.start()
        cp.wait()
    q = 128               # band-start quantum: 128-aligned lane slices
                          # are free; 64-offset slices cost a rotation
    tape = v + lp
    negf = jnp.float32(-float(_BIG))
    matchf = jnp.float32(match)
    mismatchf = jnp.float32(mismatch)
    gapf = jnp.float32(gap)
    cols_i = lax.broadcasted_iota(jnp.int32, (1, wb), 1)
    colsf = cols_i.astype(jnp.float32)
    colsg = colsf * jnp.float32(gap)
    iota_p = lax.broadcasted_iota(jnp.int32, (1, p), 1)
    iota_s = lax.broadcasted_iota(jnp.int32, (1, s_), 1)
    iota_a = lax.broadcasted_iota(jnp.int32, (1, a_), 1)
    # path pack radix: entry = (node+2)*pkr + (spos+2); spos < lp and
    # node < v, so pkr must clear lp (the wrapper asserts the product
    # fits int32)
    pkr = 1
    while pkr < lp + 8:
        pkr <<= 1

    def e11(val2d):
        """(1,1) value -> scalar."""
        return val2d[0, 0]

    def vload(ref, row):
        return ref[pl.ds(row, 1), :]

    def min_idx(mask, width, iota_row):
        """First lane index where mask is true, else width."""
        return e11(jnp.min(jnp.where(mask, iota_row, width),
                           axis=1, keepdims=True))

    # ---- scratch bulk init (scratch persists across grid programs) --
    iota_v0 = lax.broadcasted_iota(jnp.int32, (v, 1), 0)
    bblm_u = [jnp.minimum(bbl_u[u], v) for u in range(S)]
    for u in range(S):
        # backbone chain adjacency, vectorized (one column store each)
        preds_u[u][:, :] = jnp.full((v, p), -1, jnp.int32)
        preds_u[u][:, 0:1] = jnp.where(
            (iota_v0 > 0) & (iota_v0 < bblm_u[u]), iota_v0 - 1, -1)
        succs_u[u][:, :] = jnp.full((v, s_), -1, jnp.int32)
        succs_u[u][:, 0:1] = jnp.where(
            iota_v0 < bblm_u[u] - 1, iota_v0 + 1, -1)
    chw_v[:, :] = jnp.zeros((8, lp + 256), jnp.int32)
    chars_v[:, :] = jnp.zeros((8, lp + 256), jnp.int32)

    def init_nodes(j, _):
        for u in range(S):
            # gcnt 0, bandq no-epoch -- one packed store per node
            gcbq_u[u][j] = jnp.int32(0)
        return 0

    lax.fori_loop(0, v, init_nodes, 0)

    # regs: 0 fail, 1 head, 2 nodes_len, 3 n_seqs_incl, 4 rank_steps,
    # 6 best sink node, 7 best sink score, 8 nreal, 9 nbad, 10 target
    for u in range(S):
        regs_u[u][0] = jnp.int32(0)
        regs_u[u][1] = jnp.int32(0)
        regs_u[u][2] = bblm_u[u]
        regs_u[u][3] = jnp.int32(1)
        regs_u[u][4] = jnp.int32(0)

        @pl.when(bbl_u[u] > v)
        def _(u=u):
            regs_u[u][0] = jnp.int32(FAIL_VCAP)

    # ---- seed the backbone chains (add_alignment with an empty path:
    # racon_tpu/native/poa_graph.hpp add_alignment initial branch) ----
    # stage char*256+weight in VMEM (the DP band load windows into it)
    # and mirror it into SMEM (seed/merge read per position)
    for u in range(S):
        chw_v[u:u + 1, 0:lp] = seqs_ref[u, 0:1, :] * 256 \
            + wts_ref[u, 0:1, :]
    stage_chw()

    def chw_at(u, j):
        """(char, weight) at dynamic position j: scalar SMEM reads of
        the mirrored row, no VPU involvement."""
        x = chw_s[u, j]
        return x // 256, x % 256

    def seed_one(u, j, prev_w, act):
        c, w = chw_at(u, j)

        @pl.when(act)
        def _():
            has_nxt = j + 1 < bbl_u[u]
            bnsq_u[u][j] = c | (1 << 16)              # base, nseq=1
            anms_u[u][j] = j | (jnp.where(has_nxt, j + 1,
                                          _INF16) << 16)
            nxgl_u[u][j] = jnp.where(has_nxt, j + 2, 0) \
                | (j << 16)                           # nxt+1, glast=j
            pcsc_u[u][j] = jnp.where(j > 0, 1, 0) \
                | (jnp.where(has_nxt, 1, 0) << 16)
            predsm_u[u][(j) * 8 + 0] = j - 1

            @pl.when(j > 0)
            def _():
                # chain ids/anchors were written vectorized above;
                # only the data-dependent weight is per-node
                # (pred-side only: consensus scores in-edges, so succ
                # weights would be dead state)
                wrow = vload(predwv_u[u], j)
                predwv_u[u][pl.ds(j, 1), :] = jnp.where(
                    iota_p == 0, prev_w + w, wrow)
        return jnp.where(act, w, prev_w)

    def seed(j, carry):
        ws = list(carry)
        for u in range(S):
            ws[u] = seed_one(u, j, ws[u], j < bblm_u[u])
        return tuple(ws)

    bblm_max = bblm_u[0]
    for u in range(1, S):
        bblm_max = jnp.maximum(bblm_max, bblm_u[u])
    lax.fori_loop(0, bblm_max, seed, (jnp.int32(0),) * S)

    # ---- helpers shared by the merge step (u is a python int) -------

    def insert_after(u, pos, node):
        """Linked-list insert; pos == -1 -> new head.  nxt lives in
        the lo half of nxgl (biased +1, 0 = end of list)."""
        @pl.when(pos >= 0)
        def _():
            w_pos = nxgl_u[u][pos]
            nxgl_u[u][node] = (nxgl_u[u][node] & NM16) | (w_pos & M16)
            nxgl_u[u][pos] = (w_pos & NM16) | (node + 1)

        @pl.when(pos < 0)
        def _():
            nxgl_u[u][node] = (nxgl_u[u][node] & NM16) \
                | (regs_u[u][1] + 1)
            regs_u[u][1] = node

    def new_node(u, c, anchor, pos):
        """Allocate a node and insert it after list position pos."""
        nid = regs_u[u][2]
        ok = nid < v

        @pl.when(ok)
        def _():
            bnsq_u[u][nid] = c                   # base; nseq = 0
            anms_u[u][nid] = anchor | NM16       # minsucc = 0xFFFF
            nxgl_u[u][nid] = nid << 16           # no nxt; glast = nid
            gcbq_u[u][nid] = jnp.int32(0)        # gcnt 0, no epoch
            pcsc_u[u][nid] = jnp.int32(0)
            # slot 0 must be initialized: a zero-pred node's traceback
            # diag code still reads mirror slot 0 (cnt-bounded readers
            # cover slots >= 1 only)
            predsm_u[u][(nid) * 8 + 0] = jnp.int32(-1)
            regs_u[u][2] = nid + 1
            insert_after(u, pos, nid)

        @pl.when(jnp.logical_not(ok) & (regs_u[u][0] == 0))
        def _():
            regs_u[u][0] = jnp.int32(FAIL_VCAP)
        return jnp.where(ok, nid, 0)

    def add_edge(u, nu, t, w):
        """poa_graph.hpp add_edge: accumulate weight on an existing
        nu->t edge else append.  The hit search walks t's <=8-slot
        PRED id mirror in SMEM (scalar reads, no vector->scalar sync;
        in-degree is 1 for most nodes so the first probe usually
        decides); the weight accumulate is a masked vector add on the
        node's VMEM weight row -- no scalar extraction either way.
        Only the pred-side weight exists: consensus scores in-edges
        only."""
        pc_ = lo16(pcsc_u[u][t])
        found = jnp.int32(-1)
        for pp in range(7, -1, -1):     # descending: first hit wins
            found = jnp.where((pp < pc_) &
                              (predsm_u[u][(t) * 8 + pp] == nu),
                              pp, found)

        def deep_search(_):
            # rare: in-degree > 8, search the full VMEM id row
            prow = vload(preds_u[u], t)
            return min_idx(prow == nu, p, iota_p)

        def mirror_hit(_):
            return jnp.where(found >= 0, found, p)

        hit = lax.cond((found < 0) & (pc_ > 8), deep_search,
                       mirror_hit, 0)

        @pl.when(hit < p)
        def _():
            wrow = vload(predwv_u[u], t)
            predwv_u[u][pl.ds(t, 1), :] = jnp.where(
                iota_p == hit, wrow + w, wrow)

        @pl.when(hit >= p)
        def _():
            free = hi16(pcsc_u[u][nu])
            prow = vload(preds_u[u], t)
            pfree = lo16(pcsc_u[u][t])
            okk = (free < s_) & (pfree < p)

            @pl.when(okk)
            def _():
                srow = vload(succs_u[u], nu)
                succs_u[u][pl.ds(nu, 1), :] = jnp.where(
                    iota_s == free, t, srow)
                wam = anms_u[u][nu]
                ms = jnp.minimum(hi16(wam), lo16(anms_u[u][t]))
                anms_u[u][nu] = (wam & M16) | (ms << 16)
                preds_u[u][pl.ds(t, 1), :] = jnp.where(
                    iota_p == pfree, nu, prow)
                pcsc_u[u][nu] = (pcsc_u[u][nu] & M16) \
                    | ((free + 1) << 16)
                pcsc_u[u][t] = (pcsc_u[u][t] & NM16) | (pfree + 1)
                wrow = vload(predwv_u[u], t)
                predwv_u[u][pl.ds(t, 1), :] = jnp.where(
                    iota_p == pfree, w, wrow)

                @pl.when(pfree < 8)
                def _():
                    predsm_u[u][(t) * 8 + 0 + pfree] = nu

            @pl.when(jnp.logical_not(okk) & (regs_u[u][0] == 0))
            def _():
                # don't overwrite an earlier fail (a vcap overflow
                # returns node 0 as the merge target, whose slots then
                # overflow too -- without the guard every vcap reject
                # gets misreported as a pcap reject)
                regs_u[u][0] = jnp.int32(FAIL_EDGE)

    # ---- per-layer loop (joint over the pair) -----------------------

    def layer(d, _):
        act_u = [(regs_u[u][0] == 0) & (d <= nlay_u[u])
                 for u in range(S)]

        act_any = act_u[0]
        for u in range(1, S):
            act_any = act_any | act_u[u]

        @pl.when(act_any)
        def _do_layer():
            # per-window layer metadata (meta rows exist for every
            # d < d1, so reads past a window's own nlay are safe and
            # their uses are act-gated)
            begin_u = [meta_ref[u, d, 0] for u in range(S)]
            end_u = [meta_ref[u, d, 1] for u in range(S)]
            fsp_u = [meta_ref[u, d, 2] for u in range(S)]
            m_u = [meta_ref[u, d, 3] for u in range(S)]
            for u in range(S):
                regs_u[u][3] = regs_u[u][3] + jnp.where(
                    act_u[u] & (m_u[u] > 0), 1, 0)
                # stage chars (DP band loads) and char*256+weight
                # (SMEM mirror for the merge) once per layer
                chars_v[u:u + 1, 0:lp] = seqs_ref[u, pl.ds(d, 1), :]
                chw_v[u:u + 1, 0:lp] = chars_v[u:u + 1, 0:lp] * 256 \
                    + wts_ref[u, pl.ds(d, 1), :]
            stage_chw()

            # 1+2) fused walk + banded DP: ONE joint pass over both
            # windows' topo lists; each joint iteration runs one rank
            # of each window so the two serial score chains interleave
            # in a single straight-line region (the whole point of
            # pairing, see module docstring).  Band placement is
            # rank-based from the carried in-subset counter: sq is
            # monotone along the topo list, so a successor's band
            # never lags any predecessor's (the dq >= 0 invariant).
            # full-span sentinel is 0xFFFE: minsucc is a 16-bit field
            # now, real anchors are <= lp << 0xFFFE, and only the
            # 0xFFFF no-successor sentinel exceeds it
            end_eff_u = [jnp.where(fsp_u[u] > 0,
                                   jnp.int32(0xFFFE), end_u[u])
                         for u in range(S)]
            smax_u = [(jnp.maximum(m_u[u] + 1 - wb, 0) + q - 1) // q
                      for u in range(S)]
            # q8 fixed-point band slope per subset rank: nr is the
            # list length for full-span layers (their subset is the
            # whole graph) and a backbone-density estimate for partial
            # layers; one multiply+shift per rank replaces a dynamic
            # divide (nvis <= v, slope < 2^18 only when nr_est is 1
            # and m is at cap -- products stay inside int32)
            slope_u = []
            for u in range(S):
                span = jnp.maximum(end_u[u] - begin_u[u], 1)
                nr_est = jnp.where(
                    fsp_u[u] > 0, regs_u[u][2],
                    jnp.maximum(1, (span * regs_u[u][2])
                                // bblm_u[u]))
                slope_u.append((m_u[u] * 256)
                               // jnp.maximum(nr_est, 1))
                regs_u[u][6] = jnp.int32(-1)    # best sink node
                # sink-score floor: unreachable rows hold clipped
                # -inf (-2^24 after the pack clip), so the init must
                # sit ABOVE that (else a sink whose end column only
                # ever received propagated -inf would win the fold
                # and the no-reachable-sink reject below could never
                # fire) yet below any real score
                # (|score| <= max|param| * (v + lp) << 2^22)
                regs_u[u][7] = jnp.int32(-(1 << 22))

            def slot_meta(u, pid, cnt, t):
                """(epoch-valid, band-start) for one pred slot."""
                be = hi16(gcbq_u[u][jnp.clip(pid, 0, v - 1)])
                valid = (t < cnt) & (pid >= 0) & ((be >> 8) == d)
                return valid, jnp.where(valid, be & 255, 0)

            def pred_fold(u, pid, valid, sqp, sq_r):
                """One predecessor's H row realigned to this rank's
                band, in vert space (u[c] = H_pred[s_r + c]); the diag
                view is u shifted by one, applied once per rank after
                the fold since the shift commutes with the max.

                dq (the band lag) is < _N_SHIFT quanta, so the
                realignment is a SELECT over the 4 static left-shifted
                views of the row -- pure register ops.  (The r4 design
                staged the row into a scratch ref and re-read it at a
                dynamic lane offset; that VMEM write->dynamic-read
                round trip stalled the pipeline once per slot per
                rank and dominated the kernel wall.)"""
                dq = sq_r - sqp
                ok = valid & (dq >= 0) & (dq < _N_SHIFT)
                hvp = ring_u[u][pl.ds(jnp.clip(pid, 0, v - 1), 1), :]
                # unpack the score (arithmetic >> 6 floors negatives
                # correctly since the packed code is non-negative)
                h0 = (hvp >> 6).astype(jnp.float32)
                hv = h0
                for kq in range(1, _N_SHIFT):
                    shk = jnp.pad(h0, ((0, 0), (0, kq * q)),
                                  constant_values=negf)[:, kq * q:
                                                        kq * q + wb]
                    hv = jnp.where(dq == kq, shk, hv)
                hv = jnp.where(ok, hv, negf)
                # a predecessor whose band lags out of shift range
                # cannot contribute; silently degrading would corrupt
                # the consensus, so the window must fail to the CPU
                # engine (the lockstep path's kcap reject analog)
                bad = valid & jnp.logical_not(ok)
                return hv, jnp.where(valid, 1, 0), bad

            def acc_update(u, hv, t):
                a0 = accs_u[u][0:1, :]
                up = hv > a0
                accs_u[u][0:1, :] = jnp.where(up, hv, a0)
                arga_u[u][0:1, :] = jnp.where(up, t, arga_u[u][0:1, :])

            def dp_pre(u, node, nvis):
                """Scalar prolog + first-slot fold for one rank of
                window u; node -1 = walk done (inert).  Pure compute
                with clamped indices (garbage-safe): the two windows'
                prologs run back to back in one basic block."""
                live = node >= 0
                nodec = jnp.maximum(node, 0)
                wam = anms_u[u][nodec]
                anc = lo16(wam)
                in_sub = live & act_u[u] & (
                    (fsp_u[u] > 0) |
                    ((anc >= begin_u[u]) & (anc <= end_u[u])))
                cnt = lo16(pcsc_u[u][nodec])
                # subset SINKS snap to the last quantum: their row is
                # only ever read at column m - s_r (the inline sink
                # fold below), and the floor-quantized interpolation
                # can misplace by up to q-1 columns, which at narrow
                # bands would push the end column out of reach
                is_sink_n = hi16(wam) > end_eff_u[u]
                sq_r = jnp.where(
                    is_sink_n, smax_u[u],
                    jnp.clip(
                        (((nvis * slope_u[u]) >> 8) - (q // 2)) >> 7,
                        0, smax_u[u]))
                s_r = sq_r * q
                pid0 = jnp.where(cnt > 0, predsm_u[u][(nodec) * 8 + 0],
                                 -1)
                val0, sqp0 = slot_meta(u, pid0, cnt, 0)
                pid1 = predsm_u[u][(nodec) * 8 + 1]
                val1, sqp1 = slot_meta(u, pid1, cnt, 1)
                pid2 = predsm_u[u][(nodec) * 8 + 2]
                val2, sqp2 = slot_meta(u, pid2, cnt, 2)
                pid3 = predsm_u[u][(nodec) * 8 + 3]
                val3, sqp3 = slot_meta(u, pid3, cnt, 3)
                vvb = s_r.astype(jnp.float32) * gapf

                hv0, nv0, bad0 = pred_fold(u, pid0, val0, sqp0,
                                           sq_r)
                hv1, nv1, bad1 = pred_fold(u, pid1, val1, sqp1,
                                           sq_r)
                hv2, nv2, bad2 = pred_fold(u, pid2, val2, sqp2,
                                           sq_r)
                hv3, nv3, bad3 = pred_fold(u, pid3, val3, sqp3,
                                           sq_r)
                # first-slot-wins argmax tree (matches the former
                # sequential strict-> update order exactly)
                a01 = jnp.maximum(hv0, hv1)
                g01 = jnp.where(hv1 > hv0, 1, 0)
                a23 = jnp.maximum(hv2, hv3)
                g23 = jnp.where(hv3 > hv2, 3, 2)
                accf = jnp.maximum(a01, a23)
                argf = jnp.where(a23 > a01, g23, g01)
                return dict(node=node, nvis=nvis, live=live,
                            nodec=nodec, in_sub=in_sub, cnt=cnt,
                            is_sink_n=is_sink_n, sq_r=sq_r, s_r=s_r,
                            vvb=vvb, accf=accf, argf=argf,
                            nv03=nv0 + nv1 + nv2 + nv3,
                            nbad03=(jnp.where(bad0, 1, 0)
                                    + jnp.where(bad1, 1, 0)
                                    + jnp.where(bad2, 1, 0)
                                    + jnp.where(bad3, 1, 0)),
                            deep=cnt > 4,
                            nxt=jnp.where(live & act_u[u],
                                          lo16(nxgl_u[u][nodec]) - 1,
                                          -1),
                            nvis2=nvis + jnp.where(in_sub, 1, 0))

            def dp_deep(u, st):
                """Slots 4+ fold (rare: in-degree > 4), in its own
                act-gated region; folds on top of the slot 0-3 tree
                into accs/arga + regs 8."""
                in_sub, deep_c = st["in_sub"], st["deep"]
                nodec, cnt = st["nodec"], st["cnt"]
                sq_r = st["sq_r"]

                @pl.when(in_sub & deep_c)
                def _():
                    regs_u[u][8] = jnp.int32(0)   # nreal slots 4+
                    accs_u[u][0:1, :] = st["accf"]
                    arga_u[u][0:1, :] = st["argf"]
                    prow = vload(preds_u[u], nodec)

                    def deep_step(t, nr2):
                        pid = e11(jnp.sum(
                            jnp.where(iota_p == t, prow, 0),
                            axis=1, keepdims=True))
                        val, sqp = slot_meta(u, pid, cnt, t)
                        hv, nv, bad = pred_fold(u, pid, val, sqp,
                                                sq_r)
                        acc_update(u, hv, t)

                        @pl.when(bad)
                        def _():
                            regs_u[u][0] = jnp.int32(FAIL_KCAP)
                        return nr2 + nv

                    regs_u[u][8] = lax.fori_loop(4, cnt, deep_step,
                                                 jnp.int32(0))

            def dp_epi(u, st):
                """Pure epilogue: the serial gap-chain.  Both windows'
                epilogues are emitted back to back with no region
                boundary between them, so the VLIW scheduler can fill
                one chain's latency stalls with the other's ops."""
                nodec, deep_c, vvb = st["nodec"], st["deep"], st["vvb"]
                s_r = st["s_r"]
                nreal = st["nv03"] + jnp.where(deep_c, regs_u[u][8], 0)
                nbad = st["nbad03"]
                novel = nreal == 0
                accu = jnp.where(novel, colsg + vvb,
                                 jnp.where(deep_c, accs_u[u][0:1, :],
                                           st["accf"]))
                argu = jnp.where(novel, 0,
                                 jnp.where(deep_c, arga_u[u][0:1, :],
                                           st["argf"]))
                sb = chars_v[u:u + 1, pl.ds(pl.multiple_of(s_r, q),
                                            wb)]
                sub_u = jnp.where(sb == lo16(bnsq_u[u][nodec]),
                                  matchf, mismatchf)
                dmax_u = accu + sub_u
                vmax = accu + gapf
                dmax = jnp.pad(dmax_u, ((0, 0), (1, 0)),
                               constant_values=negf)[:, :wb]
                t_best = jnp.maximum(dmax, vmax)
                x = t_best - colsg
                if not (prof & 2):   # profiling: skip the gap chain
                    sh = 1
                    while sh < wb:
                        x = jnp.maximum(
                            x, jnp.pad(x, ((0, 0), (sh, 0)),
                                       constant_values=negf)[:, :wb])
                        sh <<= 1
                hr = x + colsg
                argd = jnp.pad(argu, ((0, 0), (1, 0)),
                               constant_values=0)[:, :wb]
                code = jnp.where(
                    dmax == hr, argd,
                    jnp.where(vmax == hr, argu + p,
                              2 * p)).astype(jnp.int32)
                # pack score and direction code into ONE row (halves
                # the dominant VMEM scratch and saves a store): codes
                # are < 2p+1 <= 33 < 64, scores are exact ints well
                # under 2^24 (|score| <= |gap|*(v+lp)); -inf clamps to
                # -2^24, still far below any reachable score
                hpk = (jnp.clip(hr, -float(1 << 24),
                                float(1 << 24)).astype(jnp.int32)
                       * 64 + code)
                return hr, hpk, nbad

            def dp_store(u, st, hr, hpk, nbad):
                """Gated stores + sink fold for one rank."""
                in_sub, nodec = st["in_sub"], st["nodec"]
                sq_r, s_r = st["sq_r"], st["s_r"]

                @pl.when(in_sub)
                def _():
                    ring_u[u][pl.ds(nodec, 1), :] = hpk
                    gcbq_u[u][nodec] = (gcbq_u[u][nodec] & M16) \
                        | (((d << 8) | sq_r) << 16)

                    @pl.when(nbad > 0)
                    def _():
                        regs_u[u][0] = jnp.int32(FAIL_KCAP)

                    # inline sink fold: only true subset sinks pay the
                    # vector->scalar score extraction
                    @pl.when(st["is_sink_n"])
                    def _sink():
                        c_end = m_u[u] - s_r

                        @pl.when(c_end < wb)
                        def _():
                            ccl = jnp.clip(c_end, 0, wb - 1)
                            s_end = jnp.sum(jnp.where(
                                cols_i == ccl, hr,
                                jnp.float32(0))).astype(jnp.int32)

                            @pl.when(s_end > regs_u[u][7])
                            def _():
                                regs_u[u][7] = s_end
                                regs_u[u][6] = st["node"]

            def dp_cond(c):
                alive = c[0] >= 0
                for u in range(1, S):
                    alive = alive | (c[2 * u] >= 0)
                return alive

            def dp_body(c):
                # phase-by-phase across ALL windows: each phase's S
                # bodies are emitted back to back in one straight-line
                # region so the VLIW scheduler can interleave the
                # independent chains (the whole point of grouping).
                # Multi-rank stepping: krank ranks of every window per
                # iteration -- backbone runs of single-pred nodes (the
                # common case) keep every unrolled step productive,
                # and inert tail steps (node -1) are fully gated
                c = list(c)
                for _kr in range(krank):
                    sts = [dp_pre(u, c[2 * u], c[2 * u + 1])
                           for u in range(S)]
                    for u in range(S):
                        dp_deep(u, sts[u])
                    es = [dp_epi(u, sts[u]) for u in range(S)]
                    for u in range(S):
                        dp_store(u, sts[u], *es[u])
                    for u in range(S):
                        c[2 * u] = sts[u]["nxt"]
                        c[2 * u + 1] = sts[u]["nvis2"]
                return tuple(c)

            head_u = [jnp.where(act_u[u], regs_u[u][1], -1)
                      for u in range(S)]
            init = []
            for u in range(S):
                init.extend((head_u[u], jnp.int32(0)))
            fin = lax.while_loop(dp_cond, dp_body, tuple(init))
            nvis_u = [fin[2 * u + 1] for u in range(S)]
            for u in range(S):
                regs_u[u][4] = regs_u[u][4] + nvis_u[u]

                # no subset sink landed within band reach of the
                # layer end: tracing back from node -1 would fabricate
                # an all-new path, so the window must fail to the CPU
                # engine instead
                @pl.when(act_u[u] & (regs_u[u][6] < 0) &
                         (nvis_u[u] > 0))
                def _(u=u):
                    regs_u[u][0] = jnp.int32(FAIL_KCAP)

            # 3) traceback -> reversed path in path_s, packed as
            # (node+2)*pkr + (spos+2); node -1 = no node (horiz),
            # carried node -1 = virtual start row.  Joint loop: both
            # windows' steps interleave so the per-step extract
            # latencies overlap.
            tact_u = [act_u[u] & (regs_u[u][0] == 0)
                      for u in range(S)]
            if prof & 1:   # profiling: skip traceback+merge
                tact_u = [jnp.bool_(False) for _ in range(S)]

            def tb_pre(u, node, jj, step, live):
                """Pure step compute (incl. the per-step direction
                extract, the latency to hide); both windows' pres run
                in one block."""
                nodec = jnp.maximum(node, 0)
                be = hi16(gcbq_u[u][nodec])
                s0 = jnp.where(node >= 0, be & 255, 0) * q
                cc = jnp.clip(jj - s0, 0, wb - 1)
                drow = ring_u[u][pl.ds(nodec, 1), :]
                code = jnp.sum(jnp.where(cols_i == cc, drow, 0)) % 64
                is_diag = (code < p) & (node >= 0)
                is_vert = (code >= p) & (code < 2 * p) & (node >= 0)
                take = is_diag | is_vert
                slot = jnp.clip(jnp.where(is_diag, code, code - p),
                                0, p - 1)
                pidm = predsm_u[u][(nodec) * 8
                                   + jnp.clip(slot, 0, 7)]
                return dict(node=node, jj=jj, step=step, live=live,
                            nodec=nodec, take=take, is_vert=is_vert,
                            slot=slot, pidm=pidm)

            def tb_fin(u, st):
                node, jj, step = st["node"], st["jj"], st["step"]
                live, nodec = st["live"], st["nodec"]
                take, is_vert = st["take"], st["is_vert"]
                slot = st["slot"]

                def deep(_):
                    prow = vload(preds_u[u], nodec)
                    return jnp.sum(jnp.where(iota_p == slot, prow, 0))

                def keep(_):
                    return st["pidm"]

                pid = lax.cond(slot >= 8, deep, keep, 0)
                pvalid = (pid >= 0) & \
                    ((hi16(gcbq_u[u][jnp.clip(pid, 0, v - 1)]) >> 8)
                     == d)
                pnode = jnp.where(pvalid, pid, -1)
                en = jnp.where(take, node, -1)
                es = jnp.where(is_vert, -1, jj - 1)

                @pl.when(live)
                def _():
                    path_u[u][jnp.clip(step, 0, tape - 1)] = \
                        (en + 2) * pkr + (es + 2)
                nn2 = jnp.where(take, pnode, node)
                nj = jnp.where(is_vert, jj, jnp.maximum(jj - 1, 0))
                return (jnp.where(live, nn2, node),
                        jnp.where(live, nj, jj),
                        step + jnp.where(live, 1, 0))

            def tb_live(c, u):
                n, j, sc = c[3 * u], c[3 * u + 1], c[3 * u + 2]
                return ((n >= 0) | (j > 0)) & (sc < tape)

            def tb_cond(c):
                alive = tb_live(c, 0)
                for u in range(1, S):
                    alive = alive | tb_live(c, u)
                return alive

            def tb_body(c):
                sts = [tb_pre(u, c[3 * u], c[3 * u + 1], c[3 * u + 2],
                              tb_live(c, u))
                       for u in range(S)]
                out = []
                for u in range(S):
                    out.extend(tb_fin(u, sts[u]))
                return tuple(out)

            tb0 = [jnp.where(tact_u[u], regs_u[u][6], -1)
                   for u in range(S)]
            tbm = [jnp.where(tact_u[u], m_u[u], 0) for u in range(S)]
            init_tb = []
            for u in range(S):
                init_tb.extend((tb0[u], tbm[u], jnp.int32(0)))
            fin_tb = lax.while_loop(tb_cond, tb_body, tuple(init_tb))
            plen_u = [fin_tb[3 * u + 2] for u in range(S)]
            for u in range(S):
                @pl.when(tact_u[u] & (plen_u[u] >= tape))
                def _(u=u):
                    regs_u[u][0] = jnp.int32(FAIL_PATH)

            # 4) merge (poa_graph.hpp add_alignment), walking the
            # reversed path backward = forward order; chars/weights
            # come from the rows staged at layer start.  Joint loop:
            # the two windows' scalar chase chains interleave.
            mact_u = [act_u[u] & (regs_u[u][0] == 0)
                      for u in range(S)]
            mlen_u = [jnp.where(mact_u[u], plen_u[u], 0)
                      for u in range(S)]

            def m_pre(u, t, prev, prev_w):
                """Pure step decode (the scalar chase chain); both
                windows' pres run in one block."""
                act = t < mlen_u[u]
                packed = path_u[u][jnp.clip(mlen_u[u] - 1 - t, 0,
                                            tape - 1)]
                nid = packed // pkr - 2
                jj = packed % pkr - 2
                has = act & (jj >= 0)
                # clamp to the staged row: an inactive lane decodes a
                # garbage path slot, and OOB SMEM reads are UB even
                # when the result is masked out
                c, w = chw_at(u, jnp.clip(jj, 0, lp - 1))
                fast = has & (nid >= 0) & \
                    (lo16(bnsq_u[u][jnp.clip(nid, 0, v - 1)]) == c)
                return dict(prev=prev, prev_w=prev_w, nid=nid,
                            has=has, c=c, w=w, fast=fast)

            def m_apply(u, st):
                # flattened per-step control flow: the dominant case
                # (match into an existing same-base node) runs with no
                # lax.cond; rare cases (insertion, mismatch into an
                # aligned group) sit behind one pl.when
                prev, prev_w = st["prev"], st["prev_w"]
                nid, has = st["nid"], st["has"]
                c, w, fast = st["c"], st["w"], st["fast"]
                regs_u[u][10] = nid  # resolved target (fast case)

                @pl.when(has & jnp.logical_not(fast))
                def _slow():
                    def t_new(_):
                        anchor = jnp.where(
                            prev < 0, begin_u[u],
                            lo16(anms_u[u][jnp.maximum(prev, 0)]))
                        pos = jnp.where(
                            prev < 0, -1,
                            hi16(nxgl_u[u][jnp.maximum(prev, 0)]))
                        return new_node(u, c, anchor, pos)

                    def t_aligned(_):
                        # mismatch: reuse an aligned sibling with the
                        # same base else create one (poa_graph.hpp
                        # aligned-group branch).  Group lists live in
                        # VMEM as (sib * 256 + sib_base) entries: the
                        # base tag makes the same-base search one
                        # vector compare + extract, and group members
                        # have distinct bases by construction so at
                        # most one entry matches
                        gc = lo16(gcbq_u[u][nid])
                        arow = vload(aligsm_u[u], nid)
                        h = e11(jnp.min(jnp.where(
                            (arow % 256 == c) & (iota_a < gc),
                            arow // 256, v), axis=1, keepdims=True))
                        found = jnp.where(h < v, h, -1)

                        def mk_new(_):
                            tgt = new_node(
                                u, c, lo16(anms_u[u][nid]),
                                hi16(nxgl_u[u][nid]))

                            @pl.when(gc >= a_)
                            def _():
                                regs_u[u][0] = \
                                    jnp.int32(FAIL_ALIGNED)

                            @pl.when(gc < a_)
                            def _():
                                # tgt's group = nid's members + nid
                                nb = lo16(bnsq_u[u][nid])
                                aligsm_u[u][pl.ds(tgt, 1), :] = \
                                    jnp.where(iota_a == gc,
                                              nid * 256 + nb, arow)
                                gcbq_u[u][tgt] = \
                                    (gcbq_u[u][tgt] & NM16) | (gc + 1)

                                # append tgt to each member (groups
                                # already full skip the append)
                                def ap(aa, _):
                                    sib = e11(jnp.sum(jnp.where(
                                        iota_a == aa, arow, 0),
                                        axis=1, keepdims=True)) // 256
                                    gs = lo16(gcbq_u[u][sib])

                                    @pl.when(gs < a_)
                                    def _():
                                        srw = vload(aligsm_u[u], sib)
                                        aligsm_u[u][
                                            pl.ds(sib, 1),
                                            :] = jnp.where(
                                                iota_a == gs,
                                                tgt * 256 + c, srw)
                                        gcbq_u[u][sib] = \
                                            (gcbq_u[u][sib] & NM16) \
                                            | (gs + 1)
                                    nxgl_u[u][sib] = \
                                        (nxgl_u[u][sib] & M16) \
                                        | (tgt << 16)
                                    return 0

                                lax.fori_loop(0, gc, ap, 0)
                                aligsm_u[u][pl.ds(nid, 1), :] = \
                                    jnp.where(iota_a == gc,
                                              tgt * 256 + c, arow)
                                gcbq_u[u][nid] = \
                                    (gcbq_u[u][nid] & NM16) | (gc + 1)
                                nxgl_u[u][nid] = \
                                    (nxgl_u[u][nid] & M16) \
                                    | (tgt << 16)
                            return tgt

                        return lax.cond(found >= 0, lambda _: found,
                                        mk_new, 0)

                    regs_u[u][10] = lax.cond(nid < 0, t_new,
                                                t_aligned, 0)

                target = regs_u[u][10]

                @pl.when(has)
                def _():
                    # nseq is the hi half of bnsq: +1<<16 bumps it
                    # without touching the base half
                    bnsq_u[u][target] = bnsq_u[u][target] + (1 << 16)

                    @pl.when(prev >= 0)
                    def _():
                        add_edge(u, prev, target, prev_w + w)

                return (jnp.where(has, target, prev),
                        jnp.where(has, w, prev_w))

            def mbody(t, carry):
                sts = [m_pre(u, t, carry[2 * u], carry[2 * u + 1])
                       for u in range(S)]
                out = []
                for u in range(S):
                    out.extend(m_apply(u, sts[u]))
                return tuple(out)

            mlen_max = mlen_u[0]
            for u in range(1, S):
                mlen_max = jnp.maximum(mlen_max, mlen_u[u])
            lax.fori_loop(0, mlen_max, mbody,
                          (jnp.int32(-1), jnp.int32(0)) * S)
        return 0

    nlay_max = nlay_u[0]
    for u in range(1, S):
        nlay_max = jnp.maximum(nlay_max, nlay_u[u])
    lax.fori_loop(1, nlay_max + 1, layer, 0)

    # ---- consensus: heaviest bundle over each full graph ------------
    for u in range(S):
        fail = regs_u[u][0]
        for r in range(8):
            mout_ref[u, r, 0] = jnp.int32(0)
        mout_ref[u, 0, 0] = jnp.where(fail == 0, 0, -1)
        mout_ref[u, 2, 0] = fail
        mout_ref[u, 3, 0] = regs_u[u][2]
        mout_ref[u, 4, 0] = regs_u[u][4]

        @pl.when(fail == 0)
        def _consensus(u=u):
            # walk the list once for a full topo order; order reuses
            # the glast half of nxgl (group-last is dead by now), so
            # each step is one RMW store next to the lo-half nxt read
            def wcond(c):
                return c[0] >= 0

            def wbody(c):
                node, r = c
                nxgl_u[u][r] = (nxgl_u[u][r] & M16) | (node << 16)
                return lo16(nxgl_u[u][node]) - 1, r + 1

            _, n_all = lax.while_loop(wcond, wbody,
                                      (regs_u[u][1], jnp.int32(0)))

            # forward DP: per node pick the heaviest in-edge (ties ->
            # higher predecessor score; slot order = insertion order,
            # matching poa_graph.hpp consensus_path).  Scores need the
            # full 32 bits, so they alias the path tape (dead until
            # the backtrack below); weights come off the node's VMEM
            # row, loaded once per node
            def cdp(r, best_sink):
                node = hi16(nxgl_u[u][r])
                cnt = lo16(pcsc_u[u][node])
                wrow = vload(predwv_u[u], node)

                def pick(t, carry):
                    bu, bw = carry
                    tc = jnp.clip(t, 0, 7)
                    pidm = predsm_u[u][(node) * 8 + 0 + tc]

                    def deep(_):
                        # spilled slot: id from the VMEM row
                        prow = vload(preds_u[u], node)
                        return e11(jnp.sum(
                            jnp.where(iota_p == t, prow, 0), axis=1,
                            keepdims=True))

                    def keep(_):
                        return pidm

                    pid = lax.cond(t >= 8, deep, keep, 0)
                    w = e11(jnp.sum(
                        jnp.where(iota_p == t, wrow, 0), axis=1,
                        keepdims=True))
                    sc = score_u[u][jnp.maximum(pid, 0)]
                    bsc = score_u[u][jnp.maximum(bu, 0)]
                    tk = (pid >= 0) & ((w > bw) |
                                       ((w == bw) & (bu >= 0) &
                                        (sc > bsc)))
                    return (jnp.where(tk, pid, bu),
                            jnp.where(tk, w, bw))

                best_u, best_w = lax.fori_loop(
                    0, cnt, pick, (jnp.int32(-1), jnp.int32(-1)))
                score_u[u][node] = jnp.where(
                    best_u >= 0,
                    score_u[u][jnp.maximum(best_u, 0)] + best_w, 0)
                # cpred reuses the bandq half of gcbq, biased +1
                # (0 = no predecessor); gcnt is dead, overwrite whole
                gcbq_u[u][node] = (best_u + 1) << 16
                is_sink = hi16(anms_u[u][node]) >= _INF16
                better = is_sink & (
                    (best_sink < 0) |
                    (score_u[u][node] >
                     score_u[u][jnp.maximum(best_sink, 0)]))
                return jnp.where(better, node, best_sink)

            best_sink = lax.fori_loop(0, n_all, cdp, jnp.int32(-1))

            # backtrack (reversed), then emit forward
            def bcond(c):
                return c[0] >= 0

            def bbody(c):
                node, ln = c
                # the path store may clobber score slots, but the
                # forward DP above made its last score read; the
                # chain itself lives in the gcbq cpred half
                path_u[u][ln] = (node + 2) * pkr + 2
                return hi16(gcbq_u[u][node]) - 1, ln + 1

            _, clen = lax.while_loop(bcond, bbody,
                                     (best_sink, jnp.int32(0)))

            # TGS trim (rt_poab_consensus: threshold (n_seqs - 1) / 2)
            avg = (regs_u[u][3] - 1) // 2

            def scan_fwd(t, first):
                node = path_u[u][clen - 1 - t] // pkr - 2
                cov = hi16(bnsq_u[u][node])
                hit = (first < 0) & (cov >= avg)
                return jnp.where(hit, t, first)

            def scan_bwd(t, last):
                node = path_u[u][t] // pkr - 2
                cov = hi16(bnsq_u[u][node])
                hit = (last < 0) & (cov >= avg)
                return jnp.where(hit, clen - 1 - t, last)

            if wtype == 1 and trim:
                cbegin = lax.fori_loop(0, clen, scan_fwd,
                                       jnp.int32(-1))
                cend = lax.fori_loop(0, clen, scan_bwd, jnp.int32(-1))
                chim = (cbegin < 0) | (cend < 0) | (cbegin >= cend)
                cbegin = jnp.where(chim, 0, cbegin)
                cend = jnp.where(chim, clen - 1, cend)
                status = jnp.where(chim, 2, 0).astype(jnp.int32)
            else:
                cbegin = jnp.int32(0)
                cend = clen - 1
                status = jnp.int32(0)

            length = jnp.maximum(cend - cbegin + 1, 0)

            def emit(t, _):
                node = path_u[u][clen - 1 - (cbegin + t)] \
                    // pkr - 2
                cons_sm[u, t // 128, t % 128] = \
                    lo16(bnsq_u[u][node])
                return 0

            lax.fori_loop(0, length, emit, 0)
            mout_ref[u, 0, 0] = length
            mout_ref[u, 1, 0] = status

    # one DMA ships both consensuses to the VMEM output (dynamic-lane
    # scalar stores into VMEM are not lowerable, and an SMEM output
    # window this size gets pathologically padded by the pipeline)
    cpo = pltpu.make_async_copy(cons_sm, cons_ref, sem)
    cpo.start()
    cpo.wait()


@functools.partial(
    jax.jit,
    static_argnums=(5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18,
                    19, 20, 21))
def _poa_full(seqs, wts, meta, nlay, bblen,
              v: int, lp: int, d1: int, p: int, s_: int, a_: int,
              k: int, wb: int, match: int, mismatch: int, gap: int,
              wtype: int, trim: int, s_win: int = 0, krank: int = 0,
              interpret: bool = False, prof: int = 0):
    """seqs/wts: [B, D1, LP] uint8 (d=0 = backbone), meta: [B, D1, 8]
    int32 (begin, end, full_span, slen, ...), nlay/bblen: [B] int32.
    B must be a multiple of the windows-per-program factor ``s_win``
    (0 = pick the largest that fits); ``krank`` is the multi-rank
    stepping factor (0 = policy pick).
    Returns (cons [B, V, 1] int32, mout [B, 8, 1] int32)."""
    b = seqs.shape[0]
    if not s_win:
        s_win = pick_windows_per_program(v, lp, d1, p, s_, a_, wb)
    assert s_win > 0, "shape does not fit the flagship kernel"
    assert b % s_win == 0, \
        f"batch {b} not a multiple of group factor {s_win}"
    if not krank:
        krank = pick_rank_unroll(v, lp, d1, p, s_, a_, wb, s_win)
    pkr = 1
    while pkr < lp + 8:
        pkr <<= 1
    assert (v + 2) * pkr < 2 ** 31, "path packing overflows int32"
    # the packed 16-bit SMEM fields (node ids, anchors, band epochs)
    # must stay in range; every production cap is far inside these
    assert v <= 0x8000 and lp < 0xFFFE and d1 <= 256, \
        "caps overflow the packed 16-bit scalar fields"
    seqs_l = seqs.astype(jnp.int32)
    wts_l = wts.astype(jnp.int32)

    kern = functools.partial(
        _kernel, v=v, lp=lp, d1=d1, p=p, s_=s_, a_=a_, k=k, wb=wb,
        s_win=s_win, krank=krank, match=match, mismatch=mismatch,
        gap=gap, wtype=wtype, trim=trim, prof=prof)
    # one ref PER WINDOW so the scheduler can prove the interleaved
    # walks never alias (see _kernel); order must match
    # _SCRATCH_PER_WIN
    per_win = {
        "preds": pltpu.VMEM((v, p), jnp.int32),
        "succs": pltpu.VMEM((v, s_), jnp.int32),
        "ring": pltpu.VMEM((v, wb), jnp.int32),   # packed score|code
        "accs": pltpu.VMEM((1, wb), jnp.float32),
        "arga": pltpu.VMEM((1, wb), jnp.int32),
        "aligsm": pltpu.VMEM((v, a_), jnp.int32),  # aligned groups
        "predwv": pltpu.VMEM((v, p), jnp.int32),   # pred weights
        "bnsq": pltpu.SMEM((v,), jnp.int32),
        "anms": pltpu.SMEM((v,), jnp.int32),
        "nxgl": pltpu.SMEM((v,), jnp.int32),   # hi half: cons order
        "pcsc": pltpu.SMEM((v,), jnp.int32),
        "gcbq": pltpu.SMEM((v,), jnp.int32),   # hi half: cons cpred
        "predsm": pltpu.SMEM((8 * v,), jnp.int32),  # pred id mirror
        "path": pltpu.SMEM((v + lp,), jnp.int32),   # also cons score
        "regs": pltpu.SMEM((_NREG,), jnp.int32),
    }
    assert set(per_win) == set(_SCRATCH_PER_WIN)
    scratch = []
    for name in _SCRATCH_PER_WIN:
        scratch.extend([per_win[name]] * s_win)
    scratch += [
        pltpu.VMEM((8, lp + 256), jnp.int32),   # staged chr*w
        pltpu.VMEM((8, lp + 256), jnp.int32),   # staged chars
        pltpu.SMEM((8, lp + 256), jnp.int32),   # chw mirror
        pltpu.SMEM((s_win, v // 128, 128), jnp.int32),  # consensus
        pltpu.SemaphoreType.DMA,                # staging sem
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b // s_win,),
        in_specs=[
            pl.BlockSpec((s_win, d1, lp), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((s_win, d1, lp), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((s_win, d1, 8), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=(
            pl.BlockSpec((s_win, v // 128, 128),
                         lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((s_win, 8, 1), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.SMEM),
        ),
        scratch_shapes=tuple(scratch),
    )
    assert v % 128 == 0, "node cap must be lane-aligned"
    kwargs = {}
    if not interpret:
        # the compiler's stack temporaries for S interleaved
        # straight-line window bodies exceed Mosaic's default 16M
        # scoped-vmem limit from S=3 up; v5e has 128M of VMEM, so
        # grant the kernel a 64M scope (declared scratch + temps)
        kwargs["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=64 << 20)
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct((b, v // 128, 128), jnp.int32),
                   jax.ShapeDtypeStruct((b, 8, 1), jnp.int32)),
        interpret=interpret,
        **kwargs,
    )(nlay, bblen, seqs_l, wts_l, meta)


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "v", "lp", "d1", "p", "s_", "a_", "k",
                     "wb", "match", "mismatch", "gap", "wtype", "trim",
                     "s_win", "krank", "interpret"))
def _poa_full_sharded(seqs, wts, meta, nlay, bblen, *, mesh,
                      v, lp, d1, p, s_, a_, k, wb,
                      match, mismatch, gap, wtype, trim, s_win, krank,
                      interpret):
    """The same kernel sharded over the mesh batch axis with shard_map:
    one compile, XLA places one grid per device, no collectives — the
    TPU-native analog of the reference's fully independent per-device
    batch queues (src/cuda/cudapolisher.cpp:231-243)."""
    from racon_tpu.parallel.mesh_utils import shard_batch_map

    def shard_fn(seqs, wts, meta, nlay, bblen):
        return _poa_full(seqs, wts, meta, nlay, bblen,
                         v, lp, d1, p, s_, a_, k, wb,
                         match, mismatch, gap, wtype, trim, s_win,
                         krank, interpret)

    return shard_batch_map(shard_fn, mesh, 5, 2)(
        seqs, wts, meta, nlay, bblen)


def poa_full_batch(seqs, wts, meta, nlay, bblen, **kw):
    """NumPy-facing wrapper: dispatch + blocking collect.  Returns
    (cons_chars [B, V] int32 np, mout [B, 8] int32 np).  mout rows:
    0 length (-1 = failed -> CPU re-polish), 1 status (2 = chimeric
    warning), 2 fail code, 3 nodes used, 4 total DP rank steps (for
    cells accounting)."""
    return poa_full_dispatch(seqs, wts, meta, nlay, bblen, **kw)()


def _pad_pairs(seqs, wts, meta, nlay, bblen, mult):
    """Pad the batch to a multiple of ``mult`` with inert 1-base
    windows ('A' backbone, no layers)."""
    from racon_tpu.parallel.mesh_utils import pad_to_multiple

    b0 = seqs.shape[0]
    seqs = pad_to_multiple(seqs, mult, 0)
    seqs[b0:, 0, 0] = ord("A")
    wts = pad_to_multiple(wts, mult, 1)
    meta = pad_to_multiple(meta, mult, 0)
    nlay = pad_to_multiple(nlay, mult, 0)
    bblen = pad_to_multiple(bblen, mult, 1)
    return seqs, wts, meta, nlay, bblen


def poa_full_dispatch(seqs, wts, meta, nlay, bblen, *,
                      v, lp, d1, p=16, s=16, a=8, k=128, wb=256,
                      match=5, mismatch=-4, gap=-8, wtype=1, trim=1,
                      mesh=None):
    """Enqueue one megabatch and return a zero-arg ``collect``
    closure.  The upload and kernel run asynchronously after dispatch,
    so a caller can pack (and dispatch) the NEXT megabatch while this
    one computes -- the tunnel's upload latency and the host packing
    then overlap device time (the cudapolisher analog runs per-device
    batch queues on threads, src/cuda/cudapolisher.cpp:257-336).

    With a multi-device ``mesh`` the batch axis is sharded across the
    devices (callers pad the batch; this pads further to a mesh-and-
    group multiple with inert 1-base windows)."""
    import threading

    from racon_tpu.parallel.mesh_utils import interpret_mode

    n_dev = len(mesh.devices) if mesh is not None else 1
    interp = interpret_mode()
    b0 = seqs.shape[0]
    s_win = pick_windows_per_program(v, lp, d1, p, s, a, wb)
    assert s_win > 0, "shape does not fit the flagship kernel"
    krank = pick_rank_unroll(v, lp, d1, p, s, a, wb, s_win)
    mult = s_win * n_dev
    if b0 % mult:
        seqs, wts, meta, nlay, bblen = _pad_pairs(
            seqs, wts, meta, nlay, bblen, mult)
    t_disp = _mono()
    if n_dev > 1:
        cons, mout = _poa_full_sharded(
            jnp.asarray(seqs), jnp.asarray(wts), jnp.asarray(meta),
            jnp.asarray(nlay), jnp.asarray(bblen), mesh=mesh,
            v=v, lp=lp, d1=d1, p=p, s_=s, a_=a, k=k, wb=wb,
            match=match, mismatch=mismatch, gap=gap, wtype=wtype,
            trim=trim, s_win=s_win, krank=krank, interpret=interp)
    else:
        from racon_tpu.utils import aot_shelf

        statics = (v, lp, d1, p, s, a, k, wb, match, mismatch, gap,
                   wtype, trim, s_win, krank, interp)

        def build(se, wt, me, nl, bb):
            return _poa_full(se, wt, me, nl, bb, *statics)

        cons, mout = aot_shelf.call(
            ("poa_full", seqs.shape[0]) + statics, __file__, build,
            (jnp.asarray(seqs), jnp.asarray(wts), jnp.asarray(meta),
             jnp.asarray(nlay), jnp.asarray(bblen)))
    # start both device->host copies before blocking on either: the
    # tunnel's per-transfer latency dominates, so pipelining them
    # saves one round trip
    cons.copy_to_host_async()
    mout.copy_to_host_async()

    # host-independent per-dispatch device time: a watcher thread
    # blocks on the outputs the moment the dispatch is enqueued, so
    # the measured span (upload + kernel + download) cannot be
    # inflated by whatever the host does between dispatch and collect
    # (the two-deep pipeline packs the NEXT megabatch there) -- the
    # bench's poa_device_s, distinguishing kernel regressions from
    # host jitter (VERDICT r5 #8)
    span = {}

    def _watch():
        try:
            jax.block_until_ready((cons, mout))
            t_end = _mono()
            span["s"] = t_end - t_disp
            obs_trace.TRACER.add_span(
                "device.poa_megabatch", t_disp, t_end, cat="device",
                lane="device", args={"b": int(b0)})
            obs_devutil.DEVICE_UTIL.record("poa", t_disp, t_end)
        except Exception:
            pass  # dispatch errors surface at collect()

    watcher = threading.Thread(target=_watch, daemon=True,
                               name="racon-poa-devtime")
    watcher.start()

    def collect():
        # slice off pad rows: the contract is [B, ...]
        c = np.asarray(cons)
        watcher.join()
        return (c.reshape(c.shape[0], -1)[:b0, :],
                np.asarray(mout)[:b0, :, 0])

    collect.device_s = lambda: span.get("s", 0.0)
    return collect
