"""TPUPolisher: device-offloaded stages behind the Polisher seam.

Mirrors CUDAPolisher's structure (reference: src/cuda/cudapolisher.cpp):
the same two virtual-method overrides on the same base class —
``find_overlap_breaking_points`` (aligner stage, cudapolisher.cpp:72-217)
and ``generate_consensuses`` (POA stage, cudapolisher.cpp:219-421) —
each gated independently by its batches argument, each falling back to
the CPU path for any work item the device path rejects
(cudapolisher.cpp:212-216, 357-386).

TPU-first differences from the CUDA design: instead of per-device batch
queues driven by host threads, work is packed host-side into
fixed-shape, power-of-two-bucketed batches and dispatched through one
jit-compiled kernel per bucket shape, sharded over a 1-D device mesh on
the batch axis (racon_tpu/parallel/mesh_utils.py).  JAX's async dispatch
plays the role of CUDA streams.
"""

from __future__ import annotations

import os
import threading
from typing import List

import numpy as np

from racon_tpu.core import overlap as overlap_mod
from racon_tpu.core.overlap import Overlap
from racon_tpu.core.polisher import Polisher, PolisherType
from racon_tpu.core.window import WindowType
from racon_tpu.obs import MetricAttr
from racon_tpu.obs import calhealth as obs_calhealth
from racon_tpu.obs import devutil as obs_devutil
from racon_tpu.obs import faultinject
from racon_tpu.obs import flight as obs_flight
from racon_tpu.obs import trace as obs_trace
from racon_tpu.obs import decision as obs_decision

# the one sanctioned clock (racon_tpu/obs; timestamps feed only the
# trace/metrics/calibration records, never control flow)
_now = obs_trace.now


_PREWARM_THREADS: list = []


def _spawn_prewarm(target, name: str) -> None:
    """Start a background trace/compile thread and register it for the
    exit join: a daemon thread torn down mid-C++-call aborts the
    process (measured r5: 'FATAL: exception not rethrown' whenever a
    polish exits before a prewarm compile finishes), so atexit joins
    them -- by then the work is idempotent shelf population."""
    import threading

    t = threading.Thread(target=target, daemon=True, name=name)
    _PREWARM_THREADS.append(t)
    t.start()


def join_prewarm_threads(timeout: float = None) -> None:
    for t in list(_PREWARM_THREADS):
        t.join(timeout)
        if not t.is_alive():
            _PREWARM_THREADS.remove(t)


import atexit as _atexit

_atexit.register(join_prewarm_threads)


def _prewarm_shelf_work(match: int, mismatch: int, gap: int,
                        trim: bool) -> None:
    """AOT-shelf prewarm body: load/trace every manifest kernel
    variant for one scoring config.  Best-effort: any failure leaves
    the normal first-contact path intact."""
    try:
        from racon_tpu.utils import aot_shelf
        from racon_tpu.utils.xla_cache import \
            enable_compilation_cache
        if not aot_shelf.enabled():
            return   # CPU/interpret backends trace cheaply
        enable_compilation_cache()
        from racon_tpu import prebuild
        for entry in prebuild.config_entries(match, mismatch,
                                             gap, trim):
            try:
                prebuild._build_one(entry)
            except Exception:
                pass
    except Exception:
        pass


def spawn_cli_prewarm(match: int, mismatch: int, gap: int,
                      trim: bool) -> None:
    """Start AOT-shelf prewarm at CLI entry, BEFORE input parsing:
    the jax import (~seconds) and the shelved kernel-variant loads
    (~0.1 s each) run on a background thread while the main thread
    parses FASTA/PAF, instead of serializing after parsing inside the
    first dispatch (r5 cold_wall 13.7 s vs 3.5 s warm — parsing time
    was never hidden behind compile/deserialize time).
    RACON_TPU_CLI_PREWARM=0 disables."""
    if os.environ.get("RACON_TPU_CLI_PREWARM", "1") == "0":
        return
    _spawn_prewarm(
        lambda: _prewarm_shelf_work(match, mismatch, gap, trim),
        "racon-cli-prewarm")


_prewarmed_configs: set = set()
_prewarm_once_lock = threading.Lock()


def prewarm_once(match: int, mismatch: int, gap: int,
                 trim: bool) -> bool:
    """Synchronous, idempotent shelf prewarm — the serve daemon's
    warm-start API (racon_tpu/serve/server.py).  Unlike the one-shot
    CLI there is no input parse to race against, so the work runs in
    the foreground ONCE per (scoring config) per process; every
    later call is a no-op.  Returns True when the work actually ran
    — the run is counted in the global registry
    (``serve_prewarm_runs``), which is how the warm-start test pins
    that job 2 triggered no prewarm."""
    key = (match, mismatch, gap, trim)
    with _prewarm_once_lock:
        if key in _prewarmed_configs:
            return False
        _prewarmed_configs.add(key)
    from racon_tpu.obs.metrics import REGISTRY
    REGISTRY.add("serve_prewarm_runs")
    _prewarm_shelf_work(match, mismatch, gap, trim)
    return True


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def _rate_split(dev_costs, cpu_costs) -> int:
    """Deterministic rate-model boundary: the k minimizing
    max(device time for the first k items, CPU time for the rest) —
    a pure function of the input, so repeated runs are
    byte-reproducible."""
    dev_pre = 0.0
    suf = sum(cpu_costs)
    best, cut = None, len(dev_costs)
    for k in range(len(dev_costs) + 1):
        if k:
            dev_pre += dev_costs[k - 1]
            suf -= cpu_costs[k - 1]
        t = max(dev_pre, suf)
        if best is None or t < best:
            best, cut = t, k
    return cut


def _split_cut(weights, share: float) -> int:
    """Deterministic hybrid boundary: first index where the weight
    prefix reaches ``share`` of the total (device owns [0, cut))."""
    total = sum(weights) or 1
    acc = 0
    for k, w in enumerate(weights):
        if acc >= share * total:
            return k
        acc += w
    return len(weights)


class TPUPolisher(Polisher):
    # absolute per-alignment dimension cap; larger pairs go to the CPU
    # aligner (the reference's exceeded_max_length contract,
    # src/cuda/cudaaligner.cpp:64-72)
    MAX_ALIGN_DIM = 16384
    # HBM budget for one batch's packed direction tape (2 bits/cell)
    ALIGN_MEM_BUDGET = 2 << 30
    MAX_ALIGNMENTS_PER_BATCH = 1024

    # registry-backed run metrics (racon_tpu/obs): these attributes
    # READ/WRITE the per-run metrics registry (self.metrics), so the
    # polisher's public counters, bench.py and the --metrics-json run
    # report all share one store and can never disagree
    align_cells = MetricAttr("align_cells")
    poa_cells = MetricAttr("poa_cells")
    poa_device_windows = MetricAttr("poa_device_windows")
    poa_eligible_windows = MetricAttr("poa_eligible_windows")
    poa_device_s = MetricAttr("poa_device_s")
    align_device_s = MetricAttr("align_device_s")
    align_wfa_device_s = MetricAttr("align_wfa_device_s")
    align_band_device_s = MetricAttr("align_band_device_s")
    pipeline_overlap_s = MetricAttr("pipeline_overlap_s")
    poa_spec_used = MetricAttr("poa_spec_used")
    poa_spec_wasted = MetricAttr("poa_spec_wasted")

    def __init__(self, sparser, oparser, tparser, type_: PolisherType,
                 window_length: int, quality_threshold: float,
                 error_threshold: float, trim: bool, match: int,
                 mismatch: int, gap: int, num_threads: int,
                 tpu_poa_batches: int, tpu_banded_alignment: bool,
                 tpu_aligner_batches: int):
        super().__init__(sparser, oparser, tparser, type_, window_length,
                         quality_threshold, error_threshold, trim, match,
                         mismatch, gap, num_threads)
        self.tpu_poa_batches = tpu_poa_batches
        self.tpu_banded_alignment = tpu_banded_alignment
        self.tpu_aligner_batches = tpu_aligner_batches
        self.max_align_dim = _env_int("RACON_TPU_MAX_ALIGN_DIM",
                                      self.MAX_ALIGN_DIM)
        self.align_mem_budget = _env_int("RACON_TPU_ALIGN_BUDGET",
                                         self.ALIGN_MEM_BUDGET)
        self._mesh = None
        # DP-cell counters + stage walls for throughput reporting
        self.align_cells = 0
        # starting-rung mispredictions per band (bench observability)
        self.align_retry_counts = {}
        # per-run probed dataset divergence (see _probe_divergence);
        # the p50 default matches the scan ladder's historical 20%
        # starting-rung guess so unprobed runs keep their exact
        # pre-probe behavior
        self.align_probe_ratio = 1 / 3
        self.align_probe_p50 = 1 / 5
        self.poa_cells = 0
        self.poa_reject_counts = {}
        # hybrid observability: windows consensused on device vs total
        # device-eligible (>= 3 sequences) windows
        self.poa_device_windows = 0
        self.poa_eligible_windows = 0
        self.stage_walls = {}
        # host-independent per-dispatch device time (watcher-thread
        # spans), distinguishing kernel regressions from host jitter
        # in bench records (VERDICT r5 #8).  The align stage splits
        # its span per ENGINE: the wavefront (WFA) kernel whose cost
        # scales with distance vs the banded kernel whose cost scales
        # with band x rows -- the per-engine numbers are what the
        # bench emits as align_wfa_device_s / align_band_device_s
        self.poa_device_s = 0.0
        self.align_device_s = 0.0
        self.align_wfa_device_s = 0.0
        self.align_band_device_s = 0.0
        # streaming pipeline state (RACON_TPU_PIPELINE, default on):
        # cross-stage target/window streaming + speculative device POA
        # during the align stage.  Engine ASSIGNMENT stays the
        # deterministic rate-model argmin computed at stage time over
        # the full window set -- speculative results are only USED for
        # windows that argmin assigns to the device, so output bytes
        # are identical to the staged path and timing only changes
        # WHEN work runs, never who runs it.
        self._pipeline_mode = False
        self._ledger = None
        self._poa_engine = None
        self._spec_results = {}
        self._spec_cap = 0
        self._consumer = None
        self._consumer_stop = False
        self._decode_futs = []
        self._decode_buf = []
        self._decode_buf_cols = 0
        self._decode_col_budget = 4_000_000
        self._stream_errors = []
        self._stream_lock = threading.Lock()
        self._align_device_free = threading.Event()
        self._poa_first_dispatch_t = None
        self._align_end_t = None
        self.pipeline_overlap_s = 0.0
        self.poa_spec_used = 0
        self.poa_spec_wasted = 0
        self.poa_split_detail = {}
        # durability hooks (r17, racon_tpu/serve/session.py wires
        # them for served jobs; standalone runs leave all three
        # unset):
        #   _checkpoint_cb  — called with [(ordinal, consensus, ok)]
        #     after each committed POA megabatch demux (the
        #     write-ahead journal's checkpoint record);
        #   _resume_windows — {ordinal: (consensus|None, ok)} replayed
        #     from a dead daemon's journal, adopted exactly like
        #     speculative results (device-assigned windows only) so
        #     resumed bytes equal uninterrupted bytes;
        #   _calib_pin      — the job's admission-time calibration
        #     snapshot (calibrate.epoch_snapshot()["data"]), piped
        #     into every get_rates call so a resume after the machine
        #     recalibrated still prices the SAME argmin split.
        self._checkpoint_cb = None
        self._resume_windows = None
        self._calib_pin = None
        self.poa_resumed_windows = 0
        from racon_tpu.utils.xla_cache import enable_compilation_cache
        enable_compilation_cache()

    @property
    def mesh(self):
        if self._mesh is None:
            from racon_tpu.parallel import mesh_utils
            self._mesh = mesh_utils.default_mesh()
        return self._mesh

    # ------------------------------------------------------------------
    # POA consensus stage (reference: src/cuda/cudapolisher.cpp:219-421)
    # ------------------------------------------------------------------

    # depth cap per window, mirroring MAX_DEPTH_PER_WINDOW
    # (src/cuda/cudapolisher.cpp:229)
    MAX_DEPTH_PER_WINDOW = 200

    def _poa_batch_size(self, vcap: int, lcap: int, n_dev: int) -> int:
        """Windows per megabatch, derived from device memory split
        across ``tpu_poa_batches`` batches — the analog of cudapoa's
        ``mem_per_batch = 0.9 * free / cudapoa_batches``
        (src/cuda/cudapolisher.cpp:231-242).  RACON_TPU_POA_BATCH
        overrides."""
        override = _env_int("RACON_TPU_POA_BATCH", 0)
        if override > 0:
            return override
        try:
            import jax
            limit = jax.devices()[0].memory_stats()["bytes_limit"]
        except Exception:
            limit = 8 << 30  # backends without memory stats (CPU mesh)
        from racon_tpu.utils.tuning import poa_band_cols
        wb = poa_band_cols(
            lcap, self.tpu_banded_alignment) or (lcap + 1)
        # per-lane round footprint: direction tape + score ring +
        # predecessor lists + candidate temporaries (x2 safety)
        bytes_per_lane = 2 * (vcap * wb + 128 * wb * 4
                              + vcap * 16 * 2 + 40 * wb * 4)
        mem_per_batch = 0.9 * limit * n_dev / max(
            1, self.tpu_poa_batches)
        b = int(mem_per_batch // bytes_per_lane)
        return max(n_dev, min(b, 4096))

    def _poa_caps(self):
        """Device cap selection: power-of-two graph/layer caps scaled
        from the window length (the CUDA analog sizes batches from free
        GPU memory, src/cuda/cudapolisher.cpp:231-242).

        The graph-node cap stays 4x the window length regardless of
        -b: measured r5, real 30x-coverage windows need ~2.5-3x
        window length in graph nodes (a vcap of 2x rejected 41/41
        sample windows), so banding narrows only the DP band
        (poa_band_cols), not the graph."""
        w = self.window_length
        vcap = self._bucket_dim(4 * w)
        lcap = self._bucket_dim(2 * w)
        return vcap, lcap


    def _tail_workers(self, device_only_env: str) -> int:
        """CPU workers for a hybrid stage: all but one thread, zero
        when the env forces device-only execution."""
        if os.environ.get(device_only_env):
            return 0
        return max(0, self.num_threads - 1)

    # ------------------------------------------------------------------
    # streaming pipeline (cross-stage target/window streaming)
    # ------------------------------------------------------------------

    def _pipeline_enabled(self) -> bool:
        """Cross-stage streaming gate: on by default whenever the POA
        stage is device-offloaded (RACON_TPU_PIPELINE=0 restores the
        strictly staged align-then-POA ordering).  Output bytes are
        identical either way -- see _device_generate_consensuses."""
        return (os.environ.get("RACON_TPU_PIPELINE", "1") != "0"
                and self.tpu_poa_batches > 0)

    def _make_poa_engine(self):
        """A handle on the process-wide device executor's shared
        engine for this scoring/cap config (racon_tpu/tpu/executor).
        Standalone the handle is a passthrough; under the serve
        daemon its dispatches fuse with other jobs' compatible
        batches.  The handle's cap is this polisher's own device
        batch size -- the executor's fused-batch occupancy target,
        so sharing never exceeds the memory envelope a single job
        already sized for."""
        from racon_tpu.tpu import executor

        vcap, lcap = self._poa_caps()
        n_dev = len(self.mesh.devices)
        cap = min(self._poa_batch_size(vcap, lcap, n_dev),
                  n_dev * _env_int("RACON_TPU_POA_MEGABATCH", 256))
        return executor.get_executor().poa_handle(
            self.match, self.mismatch, self.gap, vcap=vcap, pcap=16,
            lcap=lcap, kcap=128, max_depth=self.MAX_DEPTH_PER_WINDOW,
            banded=self.tpu_banded_alignment, mesh=self.mesh,
            tenant=getattr(self, "_executor_tenant", None), cap=cap)

    def _pipeline_begin(self, overlaps: List[Overlap]) -> None:
        """Set up the producer/consumer seam before the align stage:
        create the window skeleton, register every overlap's window
        range with the completion ledger (per-target accounting at
        window granularity -- a single-contig polish still streams),
        and start the speculative POA consumer."""
        from racon_tpu.core.window import WindowLedger

        self._create_windows(self._targets_size, self.window_type)
        self._ledger = WindowLedger(len(self.windows))
        w = self.window_length
        for idx, o in enumerate(overlaps):
            # coverage is counted here, over the full deterministic
            # overlap list, so the residual _build_windows pass must
            # not double count (core/polisher.py _coverage_counted)
            self.targets_coverages[o.t_id] += 1
            lo = self._first_window_id[o.t_id] + o.t_begin // w
            hi = self._first_window_id[o.t_id] \
                + max(o.t_end - 1, o.t_begin) // w
            self._ledger.register(id(o), idx, lo, hi)
        self._coverage_counted = True
        self._ledger.seal()
        self._spec_results = {}
        self._decode_futs = []
        self._decode_buf = []
        self._decode_buf_cols = 0
        self._decode_col_budget = max(
            1, _env_int("RACON_TPU_BP_COLS", 4_000_000))
        self._consumer_stop = False
        self._poa_first_dispatch_t = None
        self._poa_engine = self._make_poa_engine()
        vcap, lcap = self._poa_caps()
        n_dev = len(self.mesh.devices)
        self._spec_cap = min(
            self._poa_batch_size(vcap, lcap, n_dev),
            n_dev * _env_int("RACON_TPU_POA_MEGABATCH", 256))
        self._consumer = threading.Thread(
            target=self._poa_consumer_loop, daemon=True,
            name="racon-poa-stream")
        self._consumer.start()

    def _notify_overlap_done(self, o: Overlap) -> None:
        led = self._ledger
        if led is None or not self._pipeline_mode:
            return
        try:
            if o.breaking_points is not None \
                    and o.breaking_points is not overlap_mod.ROUTED:
                with self.metrics.timer("host.fragment_s"):
                    frags = [(self._ledger_ordinal(o), wid, data, qual,
                              b, e)
                             for wid, data, qual, b, e
                             in self._overlap_window_fragments(o)]
                # the ROUTED sentinel (a shared empty points array)
                # tells the staged fall-through work(o) this overlap
                # is done: find_breaking_points early-returns instead
                # of RE-ALIGNING it on the CPU (pre-r7 the fall
                # -through re-aligned every streamed overlap and threw
                # the result away via the ledger's duplicate-complete
                # no-op -- bytes were safe, host time was not)
                o.breaking_points = overlap_mod.ROUTED
            else:
                frags = []
            newly = led.complete(id(o), frags)
        except Exception as exc:   # never lose a routing bug silently
            with self._stream_lock:
                self._stream_errors.append(exc)
            return
        if not newly:
            return
        ready = []
        for wid, wfrags in newly:
            win = self.windows[wid]
            for _, _, data, qual, begin, end in wfrags:
                win.add_layer(data, qual, begin, end)
            # only device-eligible windows feed the consumer; trivial
            # (<3 sequences) windows keep the backbone at stage time
            if len(win.sequences) >= 3:
                ready.append(wid)
        led.push_ready(ready)

    def _ledger_ordinal(self, o: Overlap) -> int:
        with self._ledger.cond:
            reg = self._ledger._reg.get(id(o))
        return reg[0] if reg else 0

    def _finish_overlap_batch(self, batch: List[Overlap]) -> None:
        """Pool task: decode a chunk's breaking points in ONE
        vectorized pass (core/overlap.decode_breaking_points_batch)
        while the device computes the next chunk, then advance the
        completion ledger for every member.  Replaces the pre-r7
        one-pool-task-per-overlap decode, whose per-record Python
        CIGAR walk was the largest host stage on the mega bench."""
        try:
            with self.metrics.timer("host.bp_decode_s"):
                overlap_mod.decode_breaking_points_batch(
                    batch, self.window_length)
        except Exception:
            # fall through to the per-overlap path, which isolates a
            # poison record to its own error instead of the slab's
            pass
        for o in batch:
            try:
                if o.breaking_points is None:
                    o.find_breaking_points(self.sequences,
                                           self.window_length)
                self._notify_overlap_done(o)
            except Exception as exc:
                with self._stream_lock:
                    self._stream_errors.append(exc)

    def _stream_decode(self, o: Overlap) -> None:
        """Buffer breaking-point decode + ledger notify for an overlap
        whose alignment just arrived from the device (no-op when the
        pipeline is off: the staged fall-through pass handles it).
        Buffers flush to the pool as a batch at a decode-column budget
        (RACON_TPU_BP_COLS) and at each consume-chunk boundary
        (_stream_decode_flush); the queued futures are drained before
        the fall-through pass so exactly one thread ever computes a
        given overlap's points."""
        if not self._pipeline_mode:
            return
        runs = o.cigar_runs
        cols = int(runs[0].sum()) if runs is not None else 0
        with self._stream_lock:
            self._decode_buf.append(o)
            self._decode_buf_cols += cols
            if self._decode_buf_cols < self._decode_col_budget \
                    and len(self._decode_buf) < 4096:
                return
            batch, self._decode_buf = self._decode_buf, []
            self._decode_buf_cols = 0
        self._decode_futs.append(
            self._pool.submit(self._finish_overlap_batch, batch))

    def _stream_decode_flush(self) -> None:
        """Submit whatever the decode buffer holds (called at consume
        -chunk boundaries so decode overlaps the next device chunk)."""
        if not self._pipeline_mode:
            return
        with self._stream_lock:
            batch, self._decode_buf = self._decode_buf, []
            self._decode_buf_cols = 0
        if batch:
            self._decode_futs.append(
                self._pool.submit(self._finish_overlap_batch, batch))

    def _drain_stream_decodes(self) -> None:
        self._stream_decode_flush()
        for f in self._decode_futs:
            f.result()   # batch tasks never raise; this is a join
        self._decode_futs = []

    def _mark_align_device_free(self) -> None:
        """The align stage's last device dispatch is enqueued: from
        here speculative POA megabatches queue behind it and fill the
        device time the align stage's CPU tail used to leave idle
        (dispatching earlier would push the align chunks back -- the
        device queue is FIFO)."""
        self._align_device_free.set()

    def _note_poa_dispatch(self) -> None:
        if self._poa_first_dispatch_t is None:
            self._poa_first_dispatch_t = _now()

    def _poa_consumer_loop(self) -> None:
        """Speculative POA consumer: while the align stage drains,
        dispatch megabatches of ready windows through the SAME engine
        the stage will use.  Results land in _spec_results keyed by
        window id; the stage later uses them only for windows the
        deterministic rate-model argmin assigns to the device (the
        rest are recomputed by the CPU engine exactly as in the staged
        path), so speculation never reaches the output bytes."""
        from racon_tpu.tpu import align_pallas as _ap

        led = self._ledger
        eng = self._poa_engine
        min_take = max(1, _env_int("RACON_TPU_PIPE_MIN", 32))
        depth = _ap.pipeline_depth()
        inflight = []

        def collect_one():
            idxs, coll = inflight.pop(0)
            t0 = _now()
            try:
                for i, r in zip(idxs, coll()):
                    self._spec_results[i] = r
            except Exception as exc:
                with self._stream_lock:
                    self._stream_errors.append(exc)
            obs_trace.TRACER.add_span(
                "poa.spec_megabatch_collect", t0, _now(), cat="poa",
                args={"n": len(idxs)})

        while True:
            stop = self._consumer_stop
            take = []
            if not stop and self._align_device_free.is_set():
                # leftovers below min_take stay queued for the stage
                # (tiny speculative batches mint fresh kernel-variant
                # shapes for no overlap gain); at stop nothing new is
                # taken -- there is no align time left to hide it in
                take = led.pop_ready(self._spec_cap, min_take)
            if take:
                # deepest-first: megabatch rounds drain uniformly and
                # the deepest windows are the likeliest device
                # assignees under the argmin (least speculation waste)
                take.sort(
                    key=lambda i: -len(self.windows[i].sequences))
                batch = [self.windows[i] for i in take]
                self._note_poa_dispatch()
                self.metrics.add("poa_spec_megabatches")
                obs_trace.TRACER.add_instant(
                    "poa.spec_megabatch_dispatch", cat="poa",
                    args={"n": len(take)})
                try:
                    coll = eng.consensus_batch_async(batch, self.trim,
                                                     pool=self._pool)
                    inflight.append((take, coll))
                except Exception as exc:
                    with self._stream_lock:
                        self._stream_errors.append(exc)
                while len(inflight) >= depth:
                    collect_one()
                continue
            if stop:
                while inflight:
                    collect_one()
                return
            with led.cond:
                led.cond.wait(0.02)

    def _pipeline_align_done(self) -> None:
        """End of the align stage: complete any overlap the streaming
        hooks missed (stash drains sort by overlap ordinal, so layer
        order stays canonical regardless of completion order), stop
        the consumer, and surface any error a pool-side decode
        swallowed."""
        self._align_end_t = _now()
        self._mark_align_device_free()
        led = self._ledger
        if led is not None and led.remaining():
            # every overlap was notified by the fall-through pass, so
            # leftover registrations mean a completion hook errored --
            # fail loudly rather than emit a consensus with silently
            # missing layers
            with self._stream_lock:
                self._stream_errors.append(RuntimeError(
                    f"streaming seam left {len(led.remaining())} "
                    "overlap(s) unrouted"))
        self._consumer_stop = True
        if led is not None:
            with led.cond:
                led.cond.notify_all()
        with self._stream_lock:
            return list(self._stream_errors)

    def _join_consumer(self) -> None:
        if self._consumer is not None:
            self._consumer_stop = True
            if self._ledger is not None:
                with self._ledger.cond:
                    self._ledger.cond.notify_all()
            self._consumer.join()
            self._consumer = None

    def close(self) -> None:
        """Per-run teardown for multi-polish processes (the serve
        daemon): stop the speculative consumer if an error path left
        it running, then release the pool.  Process-wide warm state
        (jit caches, AOT shelf, calibration, the mesh) is exactly
        what a server keeps — nothing here touches it."""
        self._join_consumer()
        super().close()

    # ------------------------------------------------------------------
    # POA consensus stage entry
    # ------------------------------------------------------------------

    def generate_consensuses(self) -> List[bool]:
        if self.tpu_poa_batches <= 0:
            return super().generate_consensuses()
        t0 = _now()
        with obs_trace.device_span("racon_tpu.device_poa"):
            flags = self._device_generate_consensuses()
        end = _now()
        start = t0
        if self._poa_first_dispatch_t is not None:
            # the POA stage's span starts at its FIRST dispatch --
            # under the pipeline that is during the align stage, and
            # the overlap of the two spans is the wall the streaming
            # seam removed (bench: pipeline_overlap_s; wall ~
            # align + poa - overlap instead of align + poa)
            start = min(start, self._poa_first_dispatch_t)
            if self._align_end_t is not None:
                self.pipeline_overlap_s = max(
                    0.0, self._align_end_t - self._poa_first_dispatch_t)
        self.stage_walls["device_poa"] = end - start
        self.metrics.set("stage_wall_s.device_poa", end - start)
        return flags

    def _device_generate_consensuses(self) -> List[bool]:
        vcap, lcap = self._poa_caps()
        n_dev = len(self.mesh.devices)
        batch_size = self._poa_batch_size(vcap, lcap, n_dev)
        # the full-device engine uploads B x depth x lcap bytes per
        # megabatch; cap B so one upload stays ~10 MB per device
        batch_size = min(batch_size,
                         n_dev * _env_int("RACON_TPU_POA_MEGABATCH",
                                          256))
        # -b narrows the POA band (cudapoa banded analog); default is
        # the auto band (l_b/4, floor 256).  Under the pipeline the
        # engine already exists (the speculative consumer used it
        # during the align stage) and is reused so its counters span
        # both phases.
        engine = self._poa_engine or self._make_poa_engine()
        self._poa_engine = None
        # speculative results from the align-stage consumer (empty
        # when the pipeline is off or nothing became ready in time)
        self._join_consumer()
        if self._ledger is not None:
            # speculative backlog high-water (obs): how deep the
            # ready queue got before the consumer drained it
            self.metrics.peak("ledger_ready_high_water",
                              self._ledger.ready_high_water)
        spec = self._spec_results

        # trivial windows (<3 sequences) keep the backbone and count as
        # unpolished (window.cpp:68-71); the rest go to the device in
        # depth-sorted megabatches so lockstep rounds drain uniformly
        flags = [False] * len(self.windows)
        eligible = [i for i, w in enumerate(self.windows)
                    if len(w.sequences) >= 3]
        for i, w in enumerate(self.windows):
            if len(w.sequences) < 3:
                w.consensus = w.sequences[0]
        eligible.sort(key=lambda i: -len(self.windows[i].sequences))
        self.poa_eligible_windows = len(eligible)
        self.poa_device_windows = 0

        # hybrid execution: the host cores are an engine too, running
        # the native POA CONCURRENTLY with the device megabatches --
        # the heterogeneous analog of the reference's per-GPU shared
        # batch queue (src/cuda/cudapolisher.cpp:257-336).  Two
        # scheduling modes:
        #   * default: a DETERMINISTIC rate-model argmin over
        #     per-window costs depth*(1+depth/48)*(len/500) at the
        #     measured device/CPU-worker rates, so repeated runs emit
        #     byte-identical output (the two engines resolve cost-ties
        #     differently, so assignment must not depend on timing --
        #     and FOR THE SAME REASON output bytes are a function of
        #     the thread count and device count: the committed goldens
        #     hold for the CI config, -t 8 on one chip, exactly like
        #     the reference's CUDA golden pins its CI config);
        #   * RACON_TPU_STEAL=1: self-balancing work stealing (device
        #     pops deep windows, CPU workers steal shallow ones) --
        #     faster when the engines' relative rates are unknown, at
        #     the price of run-to-run output variation.
        import threading
        from collections import deque

        from racon_tpu.utils import calibrate

        lock = threading.Lock()
        n_workers = self._tail_workers("RACON_TPU_POA_DEVICE_ONLY")
        steal = bool(os.environ.get("RACON_TPU_STEAL")) and n_workers
        work = deque(eligible)
        # per-window cost units depth * (1 + depth/48) * (len/500) --
        # superlinear in depth because inserts grow the graph -- feed
        # both the split model and the in-run rate measurement
        unit_of = {}
        for i in eligible:
            w0 = self.windows[i]
            depth = min(len(w0.sequences) - 1,
                        self.MAX_DEPTH_PER_WINDOW)
            unit_of[i] = depth * (1 + depth / 48.0) \
                * (len(w0.sequences[0]) / 500.0)
        meas = {"dev": [], "cpu_w": 0.0, "cpu_u": 0.0}
        if steal or not n_workers:
            dev_left = len(eligible)     # device may reach everything
        elif "RACON_TPU_POA_SPLIT" in os.environ:
            # manual device-share override (fraction of depth^2 weight)
            dev_left = _split_cut(
                [len(self.windows[i].sequences) ** 2
                 for i in eligible],
                float(os.environ["RACON_TPU_POA_SPLIT"]))
        else:
            # deterministic rate-model argmin (like the align stage)
            # at SELF-CALIBRATED us/unit rates: measured on this
            # machine by a previous run and persisted next to the XLA
            # cache (defaults reflect the r6 kernel until then; env
            # pins for golden CI configs) -- racon_tpu/utils/calibrate
            r_dev, r_cpu, r_src = calibrate.get_rates(
                "poa", n_dev, self.POA_DEV_US_PER_UNIT,
                self.POA_CPU_US_PER_UNIT, pin=self._calib_pin)
            # price the CPU tail over the RESERVED-down worker count:
            # the host also runs the data plane (decode, routing,
            # stitching), so a full-worker rate overstated the tail
            # and capped the device share (no-op under env-pinned
            # rates, keeping golden configs byte-stable)
            n_priced = calibrate.host_reserved_workers(n_workers,
                                                       r_src)
            dev_left = _rate_split(
                [unit_of[i] * r_dev / n_dev for i in eligible],
                [unit_of[i] * r_cpu / n_priced for i in eligible])
            self.logger.log(
                f"[racon_tpu::TPUPolisher::polish] poa split: device "
                f"{dev_left}/{len(eligible)} windows "
                f"({r_src} rates {r_dev:.2f}/{r_cpu:.2f}, "
                f"{n_priced}/{n_workers} cpu workers priced)")

        # split observability (bench: poa_split_detail): the decision
        # inputs that produced this cut, so a capped device share is
        # attributable to the calibrated rates vs the depth/length
        # distribution without rerunning (ISSUE r8: the 0.71 share
        # with 0 rejects was unexplainable from the shipped record)
        sd_dev, sd_cpu, sd_src = calibrate.get_rates(
            "poa", n_dev, self.POA_DEV_US_PER_UNIT,
            self.POA_CPU_US_PER_UNIT, pin=self._calib_pin)
        units = [unit_of[i] for i in eligible]
        depths = [len(self.windows[i].sequences) - 1 for i in eligible]
        total_u = sum(units) or 1.0

        def _q(v, q):
            return v[min(len(v) - 1, int(q * len(v)))] if v else 0

        self.poa_split_detail = {
            "mode": ("steal" if steal else
                     "device_only" if not n_workers else
                     "env_split" if "RACON_TPU_POA_SPLIT" in os.environ
                     else "rate_model"),
            "rate_dev_us_per_unit": round(sd_dev, 4),
            "rate_cpu_us_per_unit": round(sd_cpu, 4),
            "rate_source": sd_src,
            "n_dev": n_dev, "n_cpu_workers": n_workers,
            "n_cpu_workers_priced": calibrate.host_reserved_workers(
                n_workers, sd_src),
            "cut": int(dev_left), "n_eligible": len(eligible),
            "dev_unit_share": round(sum(units[:dev_left]) / total_u, 4),
            "unit_total": round(total_u, 1),
            "depth_p50": _q(sorted(depths), 0.5),
            "depth_p90": _q(sorted(depths), 0.9),
            "depth_max": max(depths, default=0),
            "unit_p50": round(_q(sorted(units), 0.5), 2),
            "unit_p90": round(_q(sorted(units), 0.9), 2),
        }
        # decision record (r16): the split verdict and the rates that
        # priced it, job-tagged for `racon-tpu explain`
        obs_decision.DECISIONS.record(
            "poa_split", mode=self.poa_split_detail["mode"],
            rate_dev=round(sd_dev, 4), rate_cpu=round(sd_cpu, 4),
            source=sd_src, cut=int(dev_left),
            n_eligible=len(eligible),
            dev_unit_share=self.poa_split_detail["dev_unit_share"])

        # apply speculative consensuses: ONLY for windows this stage's
        # deterministic argmin assigns to the device (assignment never
        # follows speculation, so bytes match the staged path); spec
        # results for CPU-assigned windows are discarded and those
        # windows recomputed by the CPU engine below.  Under
        # RACON_TPU_STEAL (documented as run-to-run varying) every
        # spec result is used.
        spec_failed: List[int] = []
        # the device-assigned set under the ORIGINAL cut: both the
        # speculative results and the r17 journal-replayed checkpoint
        # results below adopt ONLY inside it, so neither mechanism
        # can move a window between engines
        assigned = eligible if steal else eligible[:dev_left]
        adopted_ckpt: List[tuple] = []
        if spec:
            resolved = [i for i in assigned if i in spec]
            for i in resolved:
                cons, ok = spec[i]
                if cons is None:
                    # device reject: CPU re-polish below, exactly as a
                    # stage-time dispatch of this window would have
                    spec_failed.append(i)
                    adopted_ckpt.append((i, None, False))
                else:
                    self.windows[i].consensus = cons
                    flags[i] = ok
                    self.poa_device_windows += 1
                    adopted_ckpt.append((i, cons, ok))
            self.poa_spec_used = len(resolved)
            self.poa_spec_wasted = len(spec) - len(resolved)
            obs_decision.DECISIONS.record(
                "poa_spec", used=len(resolved),
                wasted=len(spec) - len(resolved),
                cpu_recompute=len(spec_failed) or None)
            if resolved:
                rset = set(resolved)
                work = deque(i for i in eligible if i not in rset)
                dev_left -= len(resolved)
            if steal or not n_workers:
                dev_left = len(work)
            self.logger.log(
                f"[racon_tpu::TPUPolisher::polish] poa stream: "
                f"{self.poa_spec_used}/{len(spec)} speculative "
                f"window(s) adopted "
                f"({self.poa_spec_wasted} recomputed on CPU)")

        # resume from journaled checkpoints (r17): a restarted daemon
        # replays the dead incarnation's committed megabatches into
        # _resume_windows; they adopt exactly like speculative
        # results — device-assigned windows only, split untouched —
        # so the resumed run's bytes equal an uninterrupted run's by
        # the same argument that pins the speculative path.  A
        # ``None`` consensus replays a journaled device reject into
        # the same CPU re-polish the original dispatch took.
        resume = self._resume_windows
        if resume:
            aset = set(assigned)
            resumed = [i for i in work if i in resume and i in aset]
            for i in resumed:
                cons, ok = resume[i]
                if cons is None:
                    spec_failed.append(i)
                else:
                    self.windows[i].consensus = cons
                    flags[i] = bool(ok)
                    self.poa_device_windows += 1
            self.poa_resumed_windows = len(resumed)
            self.metrics.set("poa_resumed_windows", len(resumed))
            if resumed:
                rs = set(resumed)
                work = deque(i for i in work if i not in rs)
                if steal or not n_workers:
                    dev_left = len(work)
                else:
                    dev_left -= len(resumed)
                obs_decision.DECISIONS.record(
                    "poa_resume", used=len(resumed),
                    replayed=len(resume))
                self.logger.log(
                    f"[racon_tpu::TPUPolisher::polish] poa resume: "
                    f"{len(resumed)}/{len(resume)} checkpointed "
                    f"window(s) adopted from the journal")
        if adopted_ckpt and self._checkpoint_cb is not None:
            # spec-adopted windows are committed now — journal them
            # now, so a crash before the first megabatch still
            # resumes them (resumed windows were already journaled
            # by the incarnation that computed them)
            self._checkpoint_cb(adopted_ckpt)

        from racon_tpu import cache as _rcache
        _epoch = _rcache.keying.engine_epoch() if _rcache.enabled() \
            else None

        def cpu_worker():
            while True:
                with lock:
                    if len(work) <= (0 if steal else dev_left):
                        return
                    i = work.pop()
                t1 = _now()
                flags[i], hit = self._consensus_cached(
                    self.windows[i], _epoch)
                if hit:
                    # a cache lookup's wall says nothing about the
                    # CPU engine rate: keep it out of the measurement
                    continue
                with lock:
                    meas["cpu_w"] += _now() - t1
                    meas["cpu_u"] += unit_of[i]

        workers = [self._pool.submit(cpu_worker)
                   for _ in range(n_workers)]

        failed: List[int] = list(spec_failed)
        # double-buffered pipeline: dispatch megabatch k+1 (upload +
        # kernel enqueue are async) BEFORE collecting k, so host
        # packing and the tunnel's upload latency overlap device
        # compute -- the async analog of the reference's threaded
        # per-device batch queues (src/cuda/cudapolisher.cpp:257-336).
        # RACON_TPU_PIPE_DEPTH (default 2) sets how many megabatches
        # stay in flight; results apply in FIFO order, so output stays
        # deterministic.
        from racon_tpu.tpu import align_pallas as _ap
        depth = _ap.pipeline_depth()
        pipe = deque()          # (idxs, collect_fn) FIFO
        mark = _now()

        def apply(idxs, collect, record=True):
            nonlocal mark
            results = collect()
            # cache-served windows shrink the measured wall while the
            # unit count stays: a batch with any hits would corrupt
            # the stored device rate, so it records nothing (r18;
            # policy only — the demux below is identical either way)
            record = record and not getattr(collect, "cache_hits", 0)
            # chaos site (r17): device results landed on the host but
            # the demux below has not committed them — a kill here
            # must replay this whole megabatch on restart
            faultinject.hit("pre-demux")
            now = _now()
            u_batch = sum(unit_of[i] for i in idxs)
            if record:
                meas["dev"].append((now - mark, u_batch))
                # calibration health (r16): this megabatch's wall vs
                # what the split-model rate predicted for it
                pred = calibrate.predict_chunk_wall(
                    "poa", u_batch, sd_dev, n_dev)
                obs_calhealth.observe("poa", pred, now - mark,
                                      registry=self.metrics)
                obs_decision.DECISIONS.record(
                    "poa_chunk", n=len(idxs),
                    units=round(u_batch, 1),
                    predicted_s=round(pred, 6),
                    measured_s=round(now - mark, 6))
            obs_trace.TRACER.add_span(
                "poa.megabatch", mark, now, cat="poa",
                args={"n": len(idxs), "recorded": bool(record)})
            mark = now
            ckpt = []
            for i, (cons, ok) in zip(idxs, results):
                if cons is None:
                    failed.append(i)
                    ckpt.append((i, None, False))
                else:
                    self.windows[i].consensus = cons
                    flags[i] = ok
                    self.poa_device_windows += 1
                    ckpt.append((i, cons, ok))
            if self._checkpoint_cb is not None:
                # the megabatch is committed: journal it (r17).  The
                # callback writes AFTER the commit above, so a crash
                # between commit and journal merely replays one
                # megabatch — never resumes uncommitted state.
                self._checkpoint_cb(ckpt)
            # r21 cancel-after-checkpoint: a superseded straggler
            # stops HERE, right after its megabatch committed and
            # journaled, so every window it checkpointed stays
            # replayable and nothing half-applied is abandoned
            self._poll_cancel()
            self.logger.bar("[racon_tpu::TPUPolisher::polish] "
                            "generating consensus (device)")

        while True:
            self._poll_cancel()
            with lock:
                limit = len(work) if steal else min(len(work),
                                                    dev_left)
                take = min(batch_size, limit)
                if steal:
                    take = min(take, max(16, (limit + 1) // 2))
                idxs = [work.popleft() for _ in range(take)]
                dev_left -= take
            if not idxs:
                break
            batch = [self.windows[i] for i in idxs]
            self._note_poa_dispatch()
            if not engine.will_dispatch_async(batch):
                # the lockstep fallback runs synchronously at dispatch
                # time: drain the pipeline first so the in-flight
                # batch's measured interval stays honest, and skip
                # recording the lockstep batch (its engine rate is not
                # the full-device rate the calibration models)
                while pipe:
                    apply(*pipe.popleft())
                collect = engine.consensus_batch_async(
                    batch, self.trim, pool=self._pool)
                # chaos site (r17): same exposure as the pipelined
                # branch below — the megabatch is dispatched,
                # nothing about it journaled yet
                faultinject.hit("mid-megabatch")
                apply(idxs, collect, record=False)
                continue
            collect = engine.consensus_batch_async(batch, self.trim,
                                                   pool=self._pool)
            pipe.append((idxs, collect))
            # chaos site (r17): a megabatch is in flight on the
            # device, nothing about it journaled yet
            faultinject.hit("mid-megabatch")
            while len(pipe) >= depth:
                apply(*pipe.popleft())
        while pipe:
            apply(*pipe.popleft())
        for fut in workers:
            fut.result()

        # CPU re-polish of device-rejected windows
        # (reference: src/cuda/cudapolisher.cpp:357-386)
        if failed:
            rc = engine.reject_counts
            self.logger.log(
                f"[racon_tpu::TPUPolisher::polish] {len(failed)} "
                "window(s) fell back to the CPU engine "
                f"(vcap {rc.get(-1, 0)}, pcap {rc.get(-2, 0)}, "
                f"kcap {rc.get(-3, 0)})")
            def repolish(i):
                return self._consensus_cached(self.windows[i],
                                              _epoch)[0]
            cpu_flags = list(self._pool.map(repolish, failed))
            for i, f in zip(failed, cpu_flags):
                flags[i] = f
        if engine.n_skipped_layers:
            self.logger.log(
                f"[racon_tpu::TPUPolisher::polish] skipped "
                f"{engine.n_skipped_layers} over-long layer(s)")
        # drop the first device dispatch when later ones exist: the
        # first pays one-time trace/compile/deserialize costs.
        # Single-megabatch runs (the 47 kb sample) keep their one
        # sample -- dispatch latency biases it slow, but the two-pass
        # refinement corrects most of that, and a biased-then-refined
        # rate schedules far better than the frozen default a
        # small-job-only machine would otherwise keep forever
        # (measured r5: the sample's POA split never left 32/96
        # because the drop left zero recorded megabatches).  Such
        # single-megabatch samples store PROVISIONALLY: they never
        # freeze the calibration, so a later multi-megabatch run can
        # still overwrite them (ADVICE r5: two small jobs froze a
        # dispatch-latency-biased split at generation 2).
        recorded = meas["dev"][1:] if len(meas["dev"]) > 1 \
            else meas["dev"]
        dev_w = sum(w for w, _ in recorded)
        dev_u = sum(u for _, u in recorded)
        _, _, _src = calibrate.get_rates(
            "poa", n_dev, self.POA_DEV_US_PER_UNIT,
            self.POA_CPU_US_PER_UNIT, pin=self._calib_pin)
        if dev_u > 0 and meas["cpu_u"] > 0 and _src != "env":
            # env-pinned runs (CI, tests) never mutate the machine's
            # calibration cache
            calibrate.store_rates(
                "poa", n_dev, dev_w * 1e6 * n_dev / dev_u,
                meas["cpu_w"] * 1e6 / meas["cpu_u"],
                provisional=len(meas["dev"]) <= 1)
        self.poa_device_s = engine.device_s
        self.poa_cells += engine.cells
        self.poa_reject_counts = dict(engine.reject_counts)
        self.poa_phase_walls = dict(engine.phase_walls)
        self.poa_rounds = engine.n_rounds
        # mirror the engine's tallies into the run registry (the
        # engine predates the registry and is shared by the
        # speculative consumer, so it keeps its own lock-guarded
        # counters; the registry is the reporting surface)
        m = self.metrics
        m.set("poa_rounds", engine.n_rounds)
        for code, cnt in engine.reject_counts.items():
            if cnt:
                m.add(f"poa_reject.{code}", cnt)
        for phase, wall in engine.phase_walls.items():
            m.set(f"poa_phase_s.{phase}", round(wall, 6))
        return flags

    # ------------------------------------------------------------------
    # aligner stage (reference: src/cuda/cudapolisher.cpp:72-217)
    # ------------------------------------------------------------------

    def _prewarm_poa_async(self, overlaps: List[Overlap]) -> None:
        """Trace+compile the PREDICTED POA kernel variants on a daemon
        thread while the align stage owns the device.  Tracing plus
        the persistent-cache compile load cost ~2.5 s per variant and
        otherwise serialize after the align stage; the window depth
        (-> d1 bucket) and first-megabatch size are estimated from the
        filtered overlaps, and a mispredicted shape only wastes
        background work."""
        if self.tpu_poa_batches <= 0:
            return
        import jax

        from racon_tpu.tpu import poa_pallas
        if not poa_pallas.available() or \
                jax.devices()[0].platform != "tpu":
            return
        import threading

        from racon_tpu.utils.tuning import pow2_at_least

        # exact window-depth upper bound from the filtered overlaps: a
        # coverage diff-array over window indices per target (the
        # first megabatch takes the DEEPEST windows, so d1 follows the
        # max depth, clipped by the engine's per-window layer cap)
        tlen = {}
        for o in overlaps:
            tlen[o.t_id] = max(tlen.get(o.t_id, 0), o.t_end)
        w = self.window_length
        diff = {t: np.zeros(length // w + 2, np.int32)
                for t, length in tlen.items()}
        for o in overlaps:
            d = diff[o.t_id]
            d[o.t_begin // w] += 1
            d[o.t_end // w + 1] -= 1
        max_depth = max((int(np.cumsum(d).max()) for d in diff.values()),
                        default=0)
        max_depth = min(max_depth, self.MAX_DEPTH_PER_WINDOW)
        d1_top = max(8, pow2_at_least(max_depth + 1, 8))
        d1s = sorted({d1_top, max(8, d1_top // 2)})
        vcap, lcap = self._poa_caps()
        wb = poa_pallas.band_width(lcap, self.tpu_banded_alignment)
        n_dev = len(self.mesh.devices)
        n_win = sum(length // self.window_length + 1
                    for length in tlen.values())
        take = min(self._poa_batch_size(vcap, lcap, n_dev),
                   n_dev * _env_int("RACON_TPU_POA_MEGABATCH", 256),
                   max(8, int(0.55 * n_win)))
        b_pad = max(8, pow2_at_least(take, 8))

        wtype = self.window_type.value
        mesh = self.mesh

        def work():
            for d1 in d1s:
                try:
                    if poa_pallas.fits(vcap, lcap, d1, 16, 16, 8, wb):
                        # predict the post-pad batch dispatch will use
                        # (multiple of windows-per-program x devices)
                        bp = poa_pallas.padded_batch(
                            b_pad, n_dev, vcap, lcap, d1, wb=wb)
                        poa_pallas.prewarm(
                            bp, d1, v=vcap, lp=lcap, wb=wb,
                            match=self.match, mismatch=self.mismatch,
                            gap=self.gap, wtype=wtype, mesh=mesh)
                except Exception:
                    return  # prewarm is best-effort only

        _spawn_prewarm(work, "racon-poa-prewarm")

    def find_overlap_breaking_points(self, overlaps: List[Overlap]) -> None:
        self._align_device_free.clear()
        self._pipeline_mode = (self._pipeline_enabled()
                               and self._targets_size > 0)
        if self._pipeline_mode:
            self._pipeline_begin(overlaps)
        try:
            if self.tpu_aligner_batches > 0:
                self._prewarm_poa_async(overlaps)
                t0 = _now()
                with obs_trace.device_span("racon_tpu.device_align"):
                    self._device_align_overlaps(overlaps)
                self.stage_walls["device_align"] = _now() - t0
                self.metrics.set("stage_wall_s.device_align",
                                 self.stage_walls["device_align"])
            else:
                # no device align work: speculative POA megabatches
                # may dispatch immediately and overlap the CPU align
                self._mark_align_device_free()
            if self._pipeline_mode:
                self._drain_stream_decodes()
            # CPU path computes breaking points for everything, running
            # the CPU aligner only for overlaps still lacking a CIGAR
            # (cudapolisher.cpp:212-216); its per-overlap hook advances
            # the streaming ledger for anything not already notified
            super().find_overlap_breaking_points(overlaps)
        finally:
            # never leaves the consumer running on an error path; the
            # raise of any swallowed streaming error happens OUTSIDE
            # the finally so a propagating exception is not masked
            errs = (self._pipeline_align_done()
                    if self._pipeline_mode else [])
        if errs:
            raise errs[0]

    @staticmethod
    def _bucket_dim(n: int) -> int:
        """Round up to the power-of-two bucket (min 512) to bound the
        number of compiled kernel variants."""
        from racon_tpu.utils.tuning import pow2_at_least
        return pow2_at_least(n, 512)

    # DEFAULT hybrid-split rates (r3 hardware measurements), used only
    # until the first run self-calibrates and persists machine rates
    # (racon_tpu/utils/calibrate.py); RACON_TPU_RATE_ALIGN_* pins them
    DEV_NS_PER_ROW = 1100
    CPU_NS_PER_CELL = 4.0
    # device WFA rate (ns per e-step per pair): modeled from the
    # kernel's per-e-step vector body + refill DMA (~4-6 us per
    # 8-pair program step) until the first run calibrates the
    # "align_wfa" stage; RACON_TPU_RATE_ALIGN_WFA_{DEV,CPU} pins it
    WFA_DEV_NS_PER_STEP = 700
    # POA defaults (us per cost unit): the device rate tracks the r6
    # kernel (S=5 interleave + 4-rank stepping, ~2.4x the r5 rate the
    # old 0.30 default described) so an UNCALIBRATED first run already
    # hands the device its winning share instead of starving it for a
    # generation; RACON_TPU_RATE_POA_* pins both
    POA_DEV_US_PER_UNIT = 0.13
    POA_CPU_US_PER_UNIT = 2.0

    def _device_align_overlaps(self, overlaps: List[Overlap]) -> None:
        pending = []  # (dim, overlap), dim = max span side
        for o in overlaps:
            # SAM-ingested overlaps arrive with cigar_runs (no string
            # round trip since r7) and must not be re-aligned
            if o.cigar or o.cigar_runs is not None \
                    or o.breaking_points is not None:
                continue
            lq = o.q_end - o.q_begin
            lt = o.t_end - o.t_begin
            if max(lq, lt) > self.max_align_dim or min(lq, lt) == 0:
                continue  # CPU fallback
            pending.append((max(lq, lt), o))
        if not pending:
            self._mark_align_device_free()
            return
        pending.sort(key=lambda x: -x[0])
        from racon_tpu.tpu import align_pallas as _ap
        if _ap.available():
            self._hybrid_pallas_align(pending)
        else:
            self._hybrid_scan_align(pending)
        self._mark_align_device_free()

    def _probe_divergence(self, pending, cpu_ops) -> float:
        """CPU-align a deterministic spread of ~9 pending pairs and
        return the p75 of edit distance / dimension -- the dataset's
        divergence, which feeds both the WFA CPU cost model and the
        device band starting rung.  A property of the DATA, so it is
        probed per run rather than persisted per machine (a ratio
        learned on 10%-divergence data starved a 25%-divergence run).
        Probed pairs keep their breaking points and leave ``pending``,
        so the probe's work is never repeated; edit distances are
        exact, keeping the split a pure function of the input."""
        n = len(pending)
        if n < 4:
            return 1 / 3
        idxs = sorted({min(n - 1, int(q * n))
                       for q in (0.1, 0.2, 0.3, 0.4, 0.5,
                                 0.6, 0.7, 0.8, 0.9)})

        def one(i):
            d, o = pending[i]
            q = o.query_span(self.sequences)
            t = o.target_span(self.sequences)
            cigar, dist = cpu_ops.align_with_distance(q, t)
            o.cigar = cigar
            o.find_breaking_points(self.sequences, self.window_length)
            self._notify_overlap_done(o)
            return dist / max(d, 1)

        ratios = sorted(self._pool.map(one, idxs))
        for i in reversed(idxs):
            del pending[i]
        self.align_probe_p50 = ratios[(len(ratios) - 1) // 2]
        return ratios[int(0.75 * (len(ratios) - 1))]

    def _hybrid_pallas_align(self, pending) -> None:
        """Stacked-kernel-first hybrid: the device owns a prefix of
        the length-sorted queue (one dispatch per band rung, all
        shapes in one bucket since the kernel's row loops follow real
        lengths), while CPU WFA workers drain the small tail
        concurrently.  The cut is a deterministic rate-model argmin —
        a pure function of the input, so repeated runs emit
        byte-identical output (the engines resolve cost ties
        differently, so assignment must not depend on timing).
        RACON_TPU_ALIGN_SPLIT overrides the cut; RACON_TPU_STEAL only
        affects the scan/POA hybrid loops (this path dispatches the
        whole device share at once, so there is nothing to steal)."""
        import threading
        from collections import deque

        from racon_tpu.ops import cpu as cpu_ops
        from racon_tpu.utils import calibrate

        n_workers = self._tail_workers("RACON_TPU_ALIGN_DEVICE_ONLY")
        n_dev = len(self.mesh.devices)
        r_dev, r_cpu, r_src = calibrate.get_rates(
            "align", n_dev, float(self.DEV_NS_PER_ROW),
            float(self.CPU_NS_PER_CELL), pin=self._calib_pin)
        if r_src != "env":
            # the CPU rate calibrates as its own stage: the device
            # rate only stores on multi-chunk runs, and entangling the
            # two meant the CPU measurement was silently dropped
            # whenever the device side had a single chunk.  An env pin
            # (RACON_TPU_RATE_ALIGN_{DEV,CPU} -- CI's golden configs,
            # tests/conftest.py) still pins BOTH rates above.
            r_cpu, _, _ = calibrate.get_rates(
                "align_cpu", n_dev, float(self.CPU_NS_PER_CELL), 1.0,
                pin=self._calib_pin)
        # CPU cost model: the native engine is WFA, O(d + s^2) in the
        # DISTANCE s, not O(d^2) full DP -- at 10-15% divergence that
        # is a ~100x difference, and the old d^2 model starved the CPU
        # side of work it does in milliseconds.  s is estimated as
        # ratio * d (measured r5 on 11 kb pairs: with ratio 0.114 and
        # the 4.0 ns/cell default this model predicts 6.8/14.7/25.8 ms
        # per pair at 10/15/20% divergence -- the measured values to
        # within 5%).
        probe_ratio = self._probe_divergence(pending, cpu_ops)
        ratio = min(max(probe_ratio, 0.05), 0.67)
        self.align_probe_ratio = ratio
        obs_decision.DECISIONS.record("align_probe", n_pending=len(pending),
                         p50=round(self.align_probe_p50, 4),
                         p75=round(probe_ratio, 4),
                         ratio=round(ratio, 4))
        dims = [d for d, _ in pending]

        def cpu_cells(d):
            return d + (ratio * d) ** 2

        # device cost model is per-ENGINE: pairs the WFA rung will
        # take cost ~est_e e-steps (distance-scaling, like the CPU
        # WFA) where the banded kernel costs ~rows -- without this
        # split the rate model priced every device pair at band
        # rates and handed the ONT-divergence align stage back to
        # one contended host core (the 0.83x mega_ont leg)
        wfa_cap = self._wfa_emax_cap()
        r_wfa, _, _ = calibrate.get_rates(
            "align_wfa", n_dev, float(self.WFA_DEV_NS_PER_STEP), 1.0,
            pin=self._calib_pin)

        def dev_cost(i):
            d, o = pending[i]
            if wfa_cap:
                est = self._wfa_need(o, ratio)
                if est <= wfa_cap:
                    return est * r_wfa / n_dev
            return d * r_dev / n_dev

        if not n_workers:
            cut = len(pending)
        elif "RACON_TPU_ALIGN_SPLIT" in os.environ:
            # manual device-share override (fraction of dim weight)
            cut = _split_cut(
                dims, float(os.environ["RACON_TPU_ALIGN_SPLIT"]))
        else:
            cut = _rate_split(
                [dev_cost(i) for i in range(len(pending))],
                [r_cpu * cpu_cells(d) / n_workers for d in dims])
        obs_decision.DECISIONS.record(
            "align_split", cut=int(cut), n_pending=len(pending),
            rate_dev=round(r_dev, 4), rate_wfa=round(r_wfa, 4),
            rate_cpu=round(r_cpu, 4), source=r_src)

        work = deque(pending[cut:])
        lock = threading.Lock()
        n_cpu_done = 0
        meas = {"cpu_w": 0.0, "cpu_u": 0.0}

        def cpu_worker():
            nonlocal n_cpu_done
            while True:
                with lock:
                    if not work:
                        return
                    d, o = work.pop()
                    n_cpu_done += 1
                t1 = _now()
                o.find_breaking_points(self.sequences,
                                       self.window_length,
                                       aligner=cpu_ops.align)
                self._notify_overlap_done(o)
                with lock:
                    meas["cpu_w"] += _now() - t1
                    meas["cpu_u"] += cpu_cells(float(d))

        workers = [self._pool.submit(cpu_worker)
                   for _ in range(n_workers)]
        if cut:
            self._align_disp = []
            self._pallas_align([o for _, o in pending[:cut]])
        # device share fully dispatched: speculative POA megabatches
        # may now queue behind it while the CPU workers drain
        self._mark_align_device_free()
        for f in workers:
            f.result()
        # the WFA-shaped CPU rate (ns per modeled cell) transfers
        # across workloads better than the old d^2 model because the
        # divergence enters through the probed ratio, not the rate;
        # structured indels still inflate it (measured r5: ~4 ns on a
        # uniform-error synthetic, ~9 ns on real ONT), which the
        # two-pass machine calibration averages over
        if meas["cpu_u"] > 0 and n_cpu_done >= 16 and r_src != "env":
            # never persist measurements from env-pinned runs (CI and
            # the test suite pin rates; their runs must not mutate the
            # user's calibration cache)
            calibrate.store_rates(
                "align_cpu", n_dev,
                meas["cpu_w"] * 1e9 / meas["cpu_u"])
        if cut:
            # drop the first dispatch per (engine, rung) and store
            # only when later chunks exist: first dispatches pay
            # one-time trace/compile costs, and single-chunk runs are
            # too small for fixed dispatch latency not to swamp the
            # signal.  The two engines calibrate as separate stages
            # ("align" = banded ns/row, "align_wfa" = ns/e-step) so
            # the split model prices each pair at the engine that
            # will actually run it
            by_rung = {}
            for eng, rung, w, units in self._align_disp:
                by_rung.setdefault((eng, rung), []).append((w, units))
                # calibration health (r16): this chunk's wall vs what
                # the stage rate predicted for its unit count — the
                # same rates the split argmin priced admission with
                stage, rate = ("align_wfa", r_wfa) if eng == "wfa" \
                    else ("align", r_dev)
                pred = calibrate.predict_chunk_wall(
                    stage, units, rate, n_dev)
                obs_calhealth.observe(
                    "align_wfa" if eng == "wfa" else "align_band",
                    pred, w, registry=self.metrics)
                obs_decision.DECISIONS.record(
                    "align_chunk", engine=eng, rung=int(rung),
                    units=round(units, 1),
                    predicted_s=round(pred, 6),
                    measured_s=round(w, 6))
            for eng, stage in (("band", "align"), ("wfa", "align_wfa")):
                dev_w = sum(w for k, ch in by_rung.items()
                            if k[0] == eng for w, _ in ch[1:])
                dev_u = sum(u for k, ch in by_rung.items()
                            if k[0] == eng for _, u in ch[1:])
                if dev_u > 0 and r_src != "env":
                    calibrate.store_rates(
                        stage, n_dev, dev_w * 1e9 * n_dev / dev_u)
        if n_cpu_done:
            self.logger.log(
                f"[racon_tpu::TPUPolisher::align] cpu-aligned "
                f"{n_cpu_done} overlaps concurrently")

    def _hybrid_scan_align(self, pending) -> None:
        """Scan-ladder hybrid for backends without the Pallas kernel:
        the device consumes same-bucket runs from the large end of the
        queue while CPU WFA workers take the small-bucket tail (device
        dispatches release the GIL while blocking).  A CPU-taken
        overlap gets the full base-class treatment (CIGAR + breaking
        points), so the fall-through pass skips it."""
        import threading
        from collections import deque

        from racon_tpu.ops import cpu as cpu_ops

        # square power-of-two buckets (max dim): with banded DP the
        # padding on the smaller dim costs only extra scan steps, and
        # merging asymmetric shapes avoids tiny batches each paying a
        # full wavefront dispatch + its own compiled variant
        pending = [(self._bucket_dim(d), o) for d, o in pending]

        n_workers = self._tail_workers("RACON_TPU_ALIGN_DEVICE_ONLY")
        steal = bool(os.environ.get("RACON_TPU_STEAL")) and n_workers
        work = deque(pending)
        if steal or not n_workers:
            dev_left = len(pending)
        else:
            # deterministic static boundary (see the POA stage): the
            # CPU owns the small-bucket tail past the cut
            dev_left = _split_cut(
                [p[0] for p in pending],
                float(os.environ.get("RACON_TPU_ALIGN_SPLIT",
                                     "0.5")))
        obs_decision.DECISIONS.record(
            "align_split", cut=int(dev_left), n_pending=len(pending),
            source="scan")

        lock = threading.Lock()
        n_cpu_done = 0

        def cpu_worker():
            nonlocal n_cpu_done
            while True:
                with lock:
                    if len(work) <= (0 if steal else dev_left):
                        return
                    _, o = work.pop()
                    n_cpu_done += 1
                o.find_breaking_points(self.sequences,
                                       self.window_length,
                                       aligner=cpu_ops.align)
                self._notify_overlap_done(o)

        workers = [self._pool.submit(cpu_worker)
                   for _ in range(n_workers)]

        n_dev = len(self.mesh.devices)
        n_done = 0
        while True:
            with lock:
                limit = len(work) if steal else min(len(work),
                                                    dev_left)
                if limit <= 0:
                    break
                bd = work[0][0]
                bytes_per_lane = 2 * bd * ((min(2048, bd) + 5) // 4)
                max_b = max(n_dev, int(self.align_mem_budget
                                       // bytes_per_lane))
                max_b = min(max_b, self.MAX_ALIGNMENTS_PER_BATCH)
                if steal:
                    max_b = min(max_b, max(8, (limit + 1) // 2))
                chunk = []
                while work and len(chunk) < min(max_b, limit) \
                        and work[0][0] == bd:
                    chunk.append(work.popleft()[1])
                dev_left -= len(chunk)
            self._align_chunk(chunk, bd, bd, n_dev)
            n_done += len(chunk)
            self.logger.log(
                f"[racon_tpu::TPUPolisher::align] device-aligned "
                f"{n_done} overlaps (bucket {bd}x{bd})")
        self._mark_align_device_free()
        for f in workers:
            f.result()
        if n_cpu_done:
            self.logger.log(
                f"[racon_tpu::TPUPolisher::align] cpu-aligned "
                f"{n_cpu_done} overlaps concurrently")

    def _wfa_emax_cap(self) -> int:
        """Max e-step the device WFA rung may use (0 disables it);
        RACON_TPU_WFA_EMAX caps it, RACON_TPU_WFA=0 turns the rung
        off entirely."""
        from racon_tpu.tpu import align_pallas
        if not align_pallas.wfa_available():
            return 0
        return max(0, _env_int("RACON_TPU_WFA_EMAX", 2048))

    @staticmethod
    def _wfa_need(o: Overlap, ratio: float) -> int:
        """Estimated edit distance of one overlap at probed
        divergence ``ratio`` -- the WFA rung admission estimate (a
        pair whose true distance exceeds the rung wastes a full
        forward pass, so admission uses the p75 ratio, conservative
        where the banded starting rung uses the median)."""
        lq = o.q_end - o.q_begin
        lt = o.t_end - o.t_begin
        return abs(lq - lt) + int(max(lq, lt) * ratio)

    _WFA_RUNGS = (512, 1024, 2048)

    def _pallas_align(self, overlaps: List[Overlap]) -> None:
        """Device alignment ladder (align_pallas kernels), cheapest
        engine first:

        1. **WFA rung** -- the wavefront kernel, whose cost scales
           with edit DISTANCE: pairs whose estimated distance fits an
           e-step rung run there first; a finishing pair's distance
           is exact (no band certificate needed) and its tape decodes
           to the native CPU engine's CIGAR byte-for-byte.
        2. **Re-centered banded rungs** -- pairs the WFA rejects
           (distance or indel drift past the rung) fall to the banded
           kernel; RETRY pairs follow a measured diagonal path
           (estimate_center_knots) instead of the proportional line,
           accepted when the recovered path keeps >= 2 quanta of
           band margin (path_center_margin) -- large indel drift no
           longer escalates the rung ladder to the widest bands.
        3. Pairs the widest band cannot resolve take the CPU
           fall-through (the reference's
           exceeded_max_alignment_difference contract,
           src/cuda/cudaaligner.cpp:64-72)."""
        from racon_tpu.tpu import align_pallas, aligner

        queries = [o.query_span(self.sequences) for o in overlaps]
        targets = [o.target_span(self.sequences) for o in overlaps]
        dim = max(max(len(s) for s in queries),
                  max(len(s) for s in targets))
        bd = min((dim + 127) // 128 * 128, self.max_align_dim)
        ratio = min(max(self.align_probe_p50, 0.05), 0.67)
        ratio75 = min(max(self.align_probe_ratio, 0.05), 0.67)
        dabs = [abs(len(q) - len(t))
                for q, t in zip(queries, targets)]
        # banded-rung cost estimate (median divergence; see the
        # starting-rung rationale in the git history: the median pair
        # should start at the rung that just certifies it) and the
        # re-centered admission estimate (cost only -- the measured
        # center absorbs the length-difference drift)
        needc = [int(max(len(q), len(t)) * ratio)
                 for q, t in zip(queries, targets)]
        need = [max(dabs[i], needc[i]) for i in range(len(overlaps))]
        # WFA admission (p75 divergence: a pair past the rung wastes
        # a full forward pass, so over-admitting is the costly error)
        wfa_need = [dabs[i] + int(max(len(queries[i]),
                                      len(targets[i])) * ratio75)
                    for i in range(len(overlaps))]
        pending = list(range(len(overlaps)))
        n_dev = len(self.mesh.devices)

        wfa_cap = self._wfa_emax_cap()
        wfa_rungs = [e for e in self._WFA_RUNGS if e <= wfa_cap]
        wfa_groups = {}
        if wfa_rungs:
            for i in pending:
                for e in wfa_rungs:
                    if wfa_need[i] <= e - 32:
                        wfa_groups.setdefault(e, []).append(i)
                        break
            # sub-16-pair rungs ride the next rung up (a tiny batch
            # pays a whole dispatch + often a fresh variant)
            for e in wfa_rungs[:-1]:
                if 0 < len(wfa_groups.get(e, ())) < 16:
                    nxt = wfa_rungs[wfa_rungs.index(e) + 1]
                    wfa_groups.setdefault(nxt, [])[:0] = \
                        wfa_groups.pop(e)
        rungs = (2048, 4096, 8192)
        # the first rung to run (WFA when any group exists, else the
        # first band) traces in the foreground; everything later
        # prewarns in the background while it owns the device
        later = [("wfa", e) for e in sorted(wfa_groups)[1:]] \
            + [("band", wb)
               for wb in (rungs if wfa_groups else rungs[1:])]
        self._prewarm_align_rungs(later, wfa_groups, need, dabs, bd)

        # RACON_TPU_WFA=0 pins the whole pre-r7 ladder (no WFA rung,
        # no measured-center retries) -- the TPU CI golden configs
        # rely on this to keep their committed bytes valid
        recenter = align_pallas.wfa_available()
        use_emp: set = set()       # pairs on measured-center retry
        knots: dict = {}

        def emp_knots(i):
            if i not in knots:
                knots[i] = align_pallas.estimate_center_knots(
                    queries[i], targets[i], bd)
            return knots[i]

        # ---- 1. WFA rungs: distance-scaling device path ----------
        depth = align_pallas.pipeline_depth()
        for emax in sorted(wfa_groups):
            idx = [i for i in wfa_groups[emax] if i in set(pending)]
            if not idx:
                continue
            max_b = max(8 * n_dev,
                        int(self.align_mem_budget
                            // align_pallas.wfa_per_pair_bytes(
                                bd, emax)))
            max_b = min(max_b, self.MAX_ALIGNMENTS_PER_BATCH)
            if len(idx) > max_b:
                # depth chunks in flight => each fits 1/depth of the
                # HBM budget
                max_b = min(max_b, max(8 * n_dev, max_b // depth))
            chunks = [idx[c0:c0 + max_b]
                      for c0 in range(0, len(idx), max_b)]

            def dispatch(sub, emax=emax):
                # routed through the process-wide executor: under
                # serve, compatible rungs from concurrent jobs fuse
                # into one shared dispatch (per-pair lanes, so the
                # sliced results are byte-identical to a solo call)
                from racon_tpu.tpu import executor

                return executor.get_executor().align_wfa(
                    [queries[i] for i in sub],
                    [targets[i] for i in sub], bd, emax,
                    mesh=self.mesh,
                    tenant=getattr(self, "_executor_tenant", None))

            t_rung = _now()     # rung span start: chunk spans nest in
            tally = {"cert": 0, "mark": t_rung}
            still = set()
            self.metrics.add(f"align_rung_admit.wfa{emax}", len(idx))

            def consume(sub, coll, emax=emax, tally=tally,
                        still=still):
                tapes, nents, dists = coll()
                dev_s = getattr(coll, "device_s", lambda: 0.0)()
                self.align_device_s += dev_s
                self.align_wfa_device_s += dev_s
                if dev_s > 0:
                    self.metrics.observe("align_chunk_device_s.wfa",
                                         dev_s)
                steps = float(sum(min(int(d), emax) for d in dists))
                now = _now()
                obs_trace.TRACER.add_span(
                    f"align.chunk.wfa{emax}", tally["mark"], now,
                    cat="align", args={"n": len(sub)})
                # chunks with cache-served lanes are excluded from
                # the rate measurement: their wall covers fewer
                # device steps than the unit count claims (r18)
                if not getattr(coll, "cache_hits", 0) and \
                        hasattr(self, "_align_disp"):
                    self._align_disp.append(
                        ("wfa", emax, now - tally["mark"], steps))
                tally["mark"] = now
                # e-steps actually run x diagonal extent = the honest
                # cell count for a wavefront engine
                self.align_cells += int(steps) * (2 * emax + 1)
                for k, i in enumerate(sub):
                    if int(dists[k]) <= emax:
                        ops = align_pallas.wfa_tape_to_ops(
                            tapes[k], int(nents[k]))
                        overlaps[i].cigar_runs = \
                            aligner.ops_to_runs(ops)
                        self._stream_decode(overlaps[i])
                        tally["cert"] += 1
                    else:
                        still.add(i)
                self._stream_decode_flush()

            align_pallas.run_pipelined(chunks, dispatch, consume,
                                       depth)
            obs_trace.TRACER.add_span(
                f"align.rung.wfa{emax}", t_rung, _now(), cat="align",
                args={"n": len(idx), "chunks": len(chunks)})
            n_cert = tally["cert"]
            idx_set = set(idx)
            pending = [i for i in pending
                       if i in still or i not in idx_set]
            # WFA rejects carry measured centers into the band rungs
            use_emp.update(still)
            if still:
                self.align_retry_counts[f"wfa{emax}"] = \
                    self.align_retry_counts.get(f"wfa{emax}", 0) \
                    + len(still)
                self.metrics.add(f"align_rung_retry.wfa{emax}",
                                 len(still))
                obs_decision.DECISIONS.record("align_retry", engine="wfa",
                                 rung=emax, pairs=len(still))
            self.logger.log(
                f"[racon_tpu::TPUPolisher::align] wfa-aligned "
                f"{n_cert}/{len(idx)} overlaps (emax {emax}"
                + (f", {len(still)} to band" if still else "") + ")")

        # ---- 2. banded rungs (re-centered for retries) -----------
        for wb in rungs:
            if not pending:
                break
            # admission: the Ukkonen certificate bound for
            # proportional pairs; cost-only for measured-center pairs
            # (the knots absorb the drift); the forced last rung
            # still skips pairs that provably cannot certify
            idx = [i for i in pending
                   if need[i] + dabs[i] <= wb - 512
                   or (i in use_emp and needc[i] <= wb - 512)
                   or (wb == rungs[-1] and 2 * dabs[i] <= wb - 512)]
            if not idx:
                continue
            if len(idx) < 16 and wb != rungs[-1]:
                continue
            # chunk the dispatch so one batch's device footprint
            # (checkpoint HBM region + q/t/tape) stays in budget;
            # two-deep pipeline => each chunk fits HALF the budget
            max_b = max(8 * n_dev,
                        int(self.align_mem_budget
                            // align_pallas.per_pair_bytes(bd, wb)))
            max_b = min(max_b, self.MAX_ALIGNMENTS_PER_BATCH)
            if len(idx) > max_b:
                max_b = min(max_b, max(8 * n_dev, max_b // depth))
            chunks = [idx[c0:c0 + max_b]
                      for c0 in range(0, len(idx), max_b)]

            def dispatch(sub, wb=wb):
                from racon_tpu.tpu import executor

                return executor.get_executor().align_band(
                    [queries[i] for i in sub],
                    [targets[i] for i in sub],
                    bd, bd, wb, mesh=self.mesh,
                    centers=[emp_knots(i) if i in use_emp else None
                             for i in sub],
                    tenant=getattr(self, "_executor_tenant", None))

            t_rung = _now()     # rung span start: chunk spans nest in
            tally = {"cert": 0, "mark": t_rung}
            still = set()
            self.metrics.add(f"align_rung_admit.band{wb}", len(idx))

            def consume(sub, coll, wb=wb, tally=tally, still=still):
                moves, lens, dists = coll()
                dev_s = getattr(coll, "device_s", lambda: 0.0)()
                self.align_device_s += dev_s
                self.align_band_device_s += dev_s
                if dev_s > 0:
                    self.metrics.observe("align_chunk_device_s.band",
                                         dev_s)
                now = _now()
                obs_trace.TRACER.add_span(
                    f"align.chunk.band{wb}", tally["mark"], now,
                    cat="align", args={"n": len(sub)})
                # cache-served lanes: same measurement exclusion as
                # the wfa rung above (r18)
                if not getattr(coll, "cache_hits", 0) and \
                        hasattr(self, "_align_disp"):
                    self._align_disp.append(
                        ("band", wb, now - tally["mark"],
                         float(sum(len(queries[i]) for i in sub))))
                tally["mark"] = now
                self.align_cells += sum(len(queries[i])
                                        for i in sub) * wb
                for k, i in enumerate(sub):
                    if i in use_emp:
                        ok = int(dists[k]) < align_pallas._BIG and \
                            align_pallas.path_center_margin(
                                moves[k], int(lens[k]), knots[i],
                                wb) >= 256
                    else:
                        ok = dists[k] + dabs[i] <= wb - 512
                    if ok:
                        ops = align_pallas.moves_to_ops(
                            moves[k], int(lens[k]), queries[i],
                            targets[i])
                        overlaps[i].cigar_runs = \
                            aligner.ops_to_runs(ops)
                        self._stream_decode(overlaps[i])
                        tally["cert"] += 1
                    else:
                        still.add(i)
                self._stream_decode_flush()

            align_pallas.run_pipelined(chunks, dispatch, consume,
                                       depth)
            obs_trace.TRACER.add_span(
                f"align.rung.band{wb}", t_rung, _now(), cat="align",
                args={"n": len(idx), "chunks": len(chunks)})
            n_cert = tally["cert"]
            idx_set = set(idx)
            pending = [i for i in pending
                       if i in still or i not in idx_set]
            # a rung failure switches the pair to measured centers
            # for its retry -- the escalation-cutting move
            if recenter:
                use_emp.update(still)
            # mispredicted starting rungs double-pay the kernel; the
            # counter keeps that visible (bench prints it).  Only
            # failures with a WIDER rung left are retries;
            # final-rung failures are permanent CPU fall-throughs
            if wb != rungs[-1]:
                self.align_retry_counts[wb] = \
                    self.align_retry_counts.get(wb, 0) + len(still)
                if still:
                    self.metrics.add(f"align_rung_retry.band{wb}",
                                     len(still))
                    obs_decision.DECISIONS.record("align_retry", engine="band",
                                     rung=wb, pairs=len(still))
            elif still:
                self.metrics.add("align_rung_cpu_fallthrough",
                                 len(still))
                obs_decision.DECISIONS.record("align_cpu_fallthrough",
                                 pairs=len(still))
            tag = (f", {len(still)} "
                   + ("retries" if wb != rungs[-1] else "cpu")
                   if still else "")
            self.logger.log(
                f"[racon_tpu::TPUPolisher::align] device-aligned "
                f"{n_cert}/{len(idx)} overlaps (band {wb}{tag})")
        # survivors lack a CIGAR and take the CPU fall-through
        # (the reference's exceeded_max_alignment_difference skip)

    def _prewarm_align_rungs(self, later, wfa_groups, need, dabs,
                             bd) -> None:
        """Trace+compile the LATER rungs' kernel variants (WFA rungs
        past the first, every banded rung) on a daemon thread while
        the first rung owns the device (the rung sets are re-derived
        exactly as the dispatch loop will, minus retries — a
        retry-shifted batch shape just costs one more foreground
        trace, same as before)."""
        import jax

        from racon_tpu.tpu import align_pallas
        try:
            if jax.devices()[0].platform != "tpu":
                return
        except Exception:
            return

        n_dev = len(self.mesh.devices)
        in_wfa = {i for idxs in wfa_groups.values() for i in idxs}
        shapes = []
        band_rungs = [r for eng, r in later if eng == "band"]
        for eng, rung in later:
            if eng == "wfa":
                idx = wfa_groups.get(rung, ())
                if not idx:
                    continue
                max_b = max(8 * n_dev,
                            int(self.align_mem_budget
                                // align_pallas.wfa_per_pair_bytes(
                                    bd, rung)))
                max_b = min(max_b, self.MAX_ALIGNMENTS_PER_BATCH)
                shapes.append(("wfa", align_pallas.pad_pairs(
                    min(len(idx), max_b), n_dev), rung))
                continue
            idx = [i for i in range(len(need)) if i not in in_wfa
                   and (need[i] + dabs[i] <= rung - 512
                        or (rung == band_rungs[-1]
                            and 2 * dabs[i] <= rung - 512))]
            if not idx:
                continue
            in_wfa.update(idx)      # taken: later rungs see the rest
            max_b = max(8 * n_dev,
                        int(self.align_mem_budget
                            // align_pallas.per_pair_bytes(bd, rung)))
            max_b = min(max_b, self.MAX_ALIGNMENTS_PER_BATCH)
            shapes.append(("band", align_pallas.pad_pairs(
                min(len(idx), max_b), n_dev), rung))

        if not shapes:
            return
        mesh = self.mesh

        def work():
            for eng, n_pad, rung in shapes:
                try:
                    if eng == "wfa":
                        align_pallas.wfa_prewarm(n_pad, bd, rung,
                                                 mesh=mesh)
                    else:
                        align_pallas.prewarm(n_pad, bd, bd, rung,
                                             mesh=mesh)
                except Exception:
                    return

        _spawn_prewarm(work, "racon-align-prewarm")

    def _align_chunk(self, chunk: List[Overlap], blq: int, blt: int,
                     n_dev: int) -> None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from racon_tpu.parallel import mesh_utils
        from racon_tpu.tpu import aligner

        queries = [o.query_span(self.sequences) for o in chunk]
        targets = [o.target_span(self.sequences) for o in chunk]

        dispatch = None
        if n_dev > 1:
            sharding = NamedSharding(self.mesh, P("batch"))

            def dispatch(q, t, ql, tl, lq, lt, hw):
                args = [jax.device_put(
                            mesh_utils.pad_to_multiple(a, n_dev, f),
                            sharding)
                        for a, f in ((q, aligner._QPAD),
                                     (t, aligner._TPAD), (ql, 0),
                                     (tl, 0))]
                return mesh_utils.sharded_align(self.mesh, *args, lq=lq,
                                                lt=lt, hw=hw)

        # result cache (r18): the ladder's per-pair answer depends
        # only on (pair bytes, bucket dims, need ratio) — chunking
        # and the memory budget only batch lanes, they never change
        # one lane's result — so pairs already resolved in an earlier
        # job/round skip the ladder entirely.  Unresolved lanes cache
        # a None marker: replaying the CPU fall-through is the same
        # decision the ladder would make again.
        from racon_tpu import cache as rcache

        cached, keys, cache = {}, [None] * len(chunk), None
        if rcache.enabled():
            cache = rcache.result_cache()
            epoch = rcache.keying.engine_epoch()
            for idx in range(len(chunk)):
                keys[idx] = rcache.keying.scan_key(
                    queries[idx], targets[idx], blq, blt,
                    self.align_probe_p50, epoch)
                v = cache.get(keys[idx])
                if v is not rcache.MISS:
                    cached[idx] = v
            if cached:
                obs_flight.FLIGHT.record(
                    "cache_hit", unit_kind="scan", hits=len(cached),
                    misses=len(chunk) - len(cached),
                    items=len(chunk))
        miss = [i for i in range(len(chunk)) if i not in cached]

        # overlaps the ladder cannot resolve go to the CPU aligner
        # (reference: exceeded_max_alignment_difference skip,
        # src/cuda/cudaaligner.cpp:64-72 + cudapolisher.cpp:212-216).
        # The probed per-run divergence replaces the hardcoded 20%
        # starting-rung guess (a 5%-divergence dataset used to pay a
        # rung it never needed)
        # the scan ladder runs synchronously, so its interval IS the
        # engine-busy window on backends without the Pallas kernel
        # (where the align_pallas watcher threads never run)
        runs_of: dict = {}
        if miss:
            t0 = _now()
            ops, cells, unresolved = aligner.band_align_batch(
                [queries[i] for i in miss],
                [targets[i] for i in miss], blq, blt,
                dispatch=dispatch, allow_full=False,
                mem_budget=self.align_mem_budget,
                need_ratio=self.align_probe_p50)
            t1 = _now()
            obs_devutil.DEVICE_UTIL.record("align_band", t0, t1)
            # calibration health + decision exemplar (r16): the scan
            # ladder prices admission with the same stored "align"
            # rate the hybrid split uses, so its chunks score drift
            # identically.  Units count only the lanes actually run
            # — cache hits never pollute the rate (r18).
            from racon_tpu.utils import calibrate
            r_dev, _, _ = calibrate.get_rates(
                "align", n_dev, float(self.DEV_NS_PER_ROW),
                float(self.CPU_NS_PER_CELL), pin=self._calib_pin)
            units = float(sum(len(queries[i]) for i in miss))
            pred = calibrate.predict_chunk_wall("align", units, r_dev,
                                                n_dev)
            obs_calhealth.observe("align_band", pred, t1 - t0,
                                  registry=self.metrics)
            obs_decision.DECISIONS.record(
                "align_chunk", engine="band", rung=int(blq),
                units=round(units, 1), predicted_s=round(pred, 6),
                measured_s=round(t1 - t0, 6))
            self.align_cells += cells
            skip = set(unresolved.tolist())
            for j, i in enumerate(miss):
                runs = None if j in skip \
                    else aligner.ops_to_runs(ops[j])
                runs_of[i] = runs
                if cache is not None and keys[i] is not None:
                    cache.put(keys[i], runs)
        runs_of.update(cached)
        for idx, o in enumerate(chunk):
            runs = runs_of.get(idx)
            if runs is not None:
                o.cigar_runs = tuple(runs)
                # pipelined mode: breaking points decode on the pool
                # while the next chunk owns the device, advancing the
                # streaming ledger (no-op when the pipeline is off)
                self._stream_decode(o)
        self._stream_decode_flush()
