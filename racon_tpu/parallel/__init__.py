"""Device-mesh utilities: 1-D sharding over the batch (window/overlap)
axis via jax.sharding / shard_map, single-host ICI today, multi-host DCN
by target sharding (the wrapper's --split equivalent)."""
