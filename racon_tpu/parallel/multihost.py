"""Multi-host scale-out: target-sharded polishing over jax.distributed.

The reference scales past one machine by running independent racon
processes on slices of the target set (the wrapper's --split flow,
scripts/racon_wrapper.py); its GPU build adds nothing cross-host — the
CUDA polisher's per-device batch queues never communicate
(src/cuda/cudapolisher.cpp:231-243).  The TPU-native analog keeps that
shape: polishing is data-parallel over TARGETS, so each host process
owns a deterministic contiguous slice of the target sequences, runs
the full hybrid polish on its local chips, and emits its slice; rank 0
(or the caller) concatenates in rank order.  ``jax.distributed``
provides process bootstrap + the global device view; there are still
NO collectives in the hot path — ICI/DCN carry nothing but the
coordinator handshake, exactly like the reference's NCCL-free design.

Usage (one process per host, same arguments everywhere)::

    RACON_TPU_COORD=host0:9876 RACON_TPU_NPROC=4 RACON_TPU_RANK=$i \
        racon-tpu -c 1 reads.fq.gz ovl.paf.gz draft.fa.gz > part$i.fa

Every process parses the shared inputs (the reference wrapper's
subprocesses do the same), polishes only its target slice, and writes
that slice; ``cat part*.fa`` in rank order equals the single-process
output byte-for-byte (asserted by tests/test_multihost.py on a
2-process CPU dryrun).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple


def env_config() -> Optional[Tuple[str, int, int]]:
    """(coordinator, num_processes, rank) when multi-host env is set,
    else None (single-host mode)."""
    coord = os.environ.get("RACON_TPU_COORD")
    if not coord:
        return None
    nproc = int(os.environ.get("RACON_TPU_NPROC", "1"))
    rank = int(os.environ.get("RACON_TPU_RANK", "0"))
    if nproc <= 1:
        return None
    if not 0 <= rank < nproc:
        raise ValueError(f"RACON_TPU_RANK {rank} out of range for "
                         f"RACON_TPU_NPROC {nproc}")
    return coord, nproc, rank


_initialized = False


def maybe_initialize() -> Tuple[int, int]:
    """Bootstrap jax.distributed when configured; returns
    (num_processes, rank) — (1, 0) in single-host mode.  Idempotent.
    Must run before the first JAX backend touch (the polisher factory
    calls it before building the device mesh)."""
    global _initialized
    cfg = env_config()
    if cfg is None:
        return 1, 0
    coord, nproc, rank = cfg
    if not _initialized:
        import jax

        jax.distributed.initialize(
            coordinator_address=coord, num_processes=nproc,
            process_id=rank,
            # each host drives only its local chips: the work is
            # target-sharded, so no global array ever spans hosts
            local_device_ids=None)
        _initialized = True
    return nproc, rank


def target_slice(n_targets: int, nproc: int, rank: int) -> slice:
    """Deterministic contiguous slice of the target index space for
    one rank: sizes differ by at most one, earlier ranks take the
    remainder (the wrapper --split analog, but by count rather than
    bytes; deterministic in the input alone so the concatenated
    output is reproducible)."""
    base, rem = divmod(n_targets, nproc)
    begin = rank * base + min(rank, rem)
    return slice(begin, begin + base + (1 if rank < rem else 0))
