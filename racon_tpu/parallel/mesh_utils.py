"""Device-mesh scaling utilities.

The framework's parallelism is data-parallel over the leading batch axis
of fixed-shape work batches (windows / overlaps) — the TPU-native
equivalent of racon-gpu's independent per-device batch queues
(reference: src/cuda/cudapolisher.cpp:170-188,231-243, which use no
inter-device communication at all).  A 1-D mesh shards the batch axis
over ICI; there are no collectives in the hot path, and host-side
result concatenation is the only "all-gather".
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map


def interpret_mode() -> bool:
    """Pallas kernels run in interpret mode on non-TPU backends (the
    CPU dryrun mesh and the sharding tests)."""
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:
        return True


def shard_batch_map(fn, mesh: Mesh, n_in: int, n_out: int):
    """``shard_map`` over the 1-D batch axis with Pallas-friendly
    settings (the vma/rep output check is off: ``pallas_call``
    out_shapes carry no vma annotation)."""
    spec = P("batch")
    out = spec if n_out == 1 else (spec,) * n_out
    try:
        return shard_map(fn, mesh=mesh, in_specs=(spec,) * n_in,
                         out_specs=out, check_vma=False)
    except TypeError:  # older jax spells it check_rep
        return shard_map(fn, mesh=mesh, in_specs=(spec,) * n_in,
                         out_specs=out, check_rep=False)


def default_mesh(max_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over all (or the first ``max_devices``) LOCAL devices.

    Local, not global: under jax.distributed the work is
    target-sharded per host (racon_tpu/parallel/multihost.py) and each
    rank's batches are host-side numpy arrays, so a mesh spanning
    another host's non-addressable chips could never be fed."""
    devices = jax.local_devices()
    if max_devices is not None:
        devices = devices[:max_devices]
    return Mesh(np.array(devices), axis_names=("batch",))


def pad_to_multiple(arr: np.ndarray, multiple: int,
                    fill) -> np.ndarray:
    """Pad the leading axis up to a multiple (mesh-divisible batches)."""
    b = arr.shape[0]
    rem = (-b) % multiple
    if rem == 0:
        return arr
    pad_block = np.full((rem,) + arr.shape[1:], fill, dtype=arr.dtype)
    return np.concatenate([arr, pad_block], axis=0)


@functools.partial(jax.jit,
                   static_argnames=("mesh", "lq", "lt", "hw"))
def _sharded_align_impl(q, t, ql, tl, *, mesh: Mesh, lq: int, lt: int,
                        hw: int = 0):
    from racon_tpu.tpu.aligner import _align_kernel, _banded_align_kernel

    spec = P("batch")

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(spec, spec, spec, spec),
                       out_specs=spec)
    def shard_fn(q, t, ql, tl):
        if hw:
            return _banded_align_kernel(q, t, ql, tl, lq, lt, hw)
        return _align_kernel(q, t, ql, tl, lq, lt)

    return shard_fn(q, t, ql, tl)


def sharded_align(mesh: Mesh, q, t, ql, tl, *, lq: int, lt: int,
                  hw: int = 0):
    """Batched alignment sharded over the mesh batch axis.

    The batch must be divisible by the mesh size (use
    ``pad_to_multiple``); each device runs the wavefront kernel
    (banded when ``hw`` > 0) on its shard independently.
    """
    return _sharded_align_impl(q, t, ql, tl, mesh=mesh, lq=lq, lt=lt,
                               hw=hw)


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "v", "l", "p", "k", "wb", "match",
                     "mismatch", "gap"))
def _sharded_poa_impl(bases, preds, nrows, sinks, seq, slen, *,
                      mesh: Mesh, v: int, l: int, p: int, k: int,
                      wb: int, match: int, mismatch: int, gap: int):
    from racon_tpu.tpu.poa import _poa_kernel, _poa_kernel_banded

    spec = P("batch")

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(spec,) * 6,
                       out_specs=(spec, spec))
    def shard_fn(bases, preds, nrows, sinks, seq, slen):
        if wb:
            return _poa_kernel_banded(bases, preds, nrows, sinks, seq,
                                      slen, v, l, p, k, wb, match,
                                      mismatch, gap)
        return _poa_kernel(bases, preds, nrows, sinks, seq, slen,
                           v, l, p, k, match, mismatch, gap)

    return shard_fn(bases, preds, nrows, sinks, seq, slen)


def sharded_poa(mesh: Mesh, bases, preds, nrows, sinks, seq, slen, *,
                v: int, l: int, p: int, k: int, match: int,
                mismatch: int, gap: int, wb: int = 0):
    """One batched POA layer-round sharded over the mesh batch axis.

    TPU-native analog of racon-gpu's per-device POA batch queues
    (reference: src/cuda/cudapolisher.cpp:231-243): windows are
    embarrassingly parallel, so the round's fixed-shape arrays shard on
    the leading axis with no collectives in the hot path.
    """
    return _sharded_poa_impl(bases, preds, nrows, sinks, seq, slen,
                             mesh=mesh, v=v, l=l, p=p, k=k, wb=wb,
                             match=match, mismatch=mismatch, gap=gap)
