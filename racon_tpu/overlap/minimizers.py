"""Host-vectorized k-mer minimizer extraction.

Mirrors minimap2's sketch (reference: minimap2 sketch.c) with numpy in
place of the per-base C loop: 2-bit packed forward/reverse-complement
k-mer words built by a k-pass rolling OR, an invertible 32-bit mixer so
minimizer choice is position-independent, and windowed argmin over a
zero-copy sliding view to pick one minimizer per w-window.

Everything is uint32: k is clamped to <= 15 so a canonical k-mer fits
in 30 bits, the mixer is a bijection on the full 32-bit domain, and —
because it is invertible — two distinct k-mers can never collide, which
is what lets chaining trust anchors without re-verifying base equality.
The same word-building runs bit-identically on device via
racon_tpu.tpu.seedmatch (RACON_TPU_MAP_DEVICE_SEED=1): host and device
produce equal uint32 arrays, so the knob moves arithmetic, not bytes.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

#: base -> 2-bit code; anything not ACGT/acgt is 4 (invalid)
_CODES = np.full(256, 4, dtype=np.uint8)
for _i, _b in enumerate(b"ACGT"):
    _CODES[_b] = _i
for _i, _b in enumerate(b"acgt"):
    _CODES[_b] = _i

#: sentinel hash for masked (invalid / strand-ambiguous) k-mer slots
SENTINEL = np.uint32(0xFFFFFFFF)

#: canonical k-mers must fit 2k <= 30 bits (uint32 lanes, device parity)
MAX_K = 15


def mix32(h: np.ndarray) -> np.ndarray:
    """Invertible 32-bit finalizer (lowbias32).  Bijective on uint32,
    so distinct k-mers keep distinct hashes — anchors are exact."""
    h = np.asarray(h, dtype=np.uint32)
    h = (h ^ (h >> np.uint32(16))) * np.uint32(0x7FEB352D)
    h = (h ^ (h >> np.uint32(15))) * np.uint32(0x846CA68B)
    return h ^ (h >> np.uint32(16))


def encode(data) -> np.ndarray:
    """bytes/buffer -> per-base 2-bit codes (4 = invalid), zero-copy in."""
    return _CODES[np.frombuffer(data, dtype=np.uint8)]


def kmer_words(codes: np.ndarray, k: int,
               device: bool = False) -> Tuple[np.ndarray, np.ndarray]:
    """Forward and reverse-complement k-mer words over ``codes``.

    ``fw[i]`` packs codes[i:i+k] big-endian (first base most
    significant); ``rv[i]`` is the word of the reverse complement of
    the same window.  Invalid bases contribute ``code & 3`` here and
    are masked out by the validity scan in :func:`extract`.  With
    ``device`` set, the k-pass shift/OR build runs on the accelerator
    (racon_tpu.tpu.seedmatch) with bit-identical results; any device
    failure falls back to the host path silently.
    """
    nk = codes.size - k + 1
    if nk <= 0:
        z = np.empty(0, dtype=np.uint32)
        return z, z
    if device:
        try:
            from racon_tpu.tpu import seedmatch
            return seedmatch.kmer_words_device(codes, k)
        except Exception:
            pass
    c = codes.astype(np.uint32) & np.uint32(3)
    cc = np.uint32(3) - c
    fw = np.zeros(nk, dtype=np.uint32)
    rv = np.zeros(nk, dtype=np.uint32)
    for j in range(k):
        fw |= c[j:j + nk] << np.uint32(2 * (k - 1 - j))
        rv |= cc[j:j + nk] << np.uint32(2 * j)
    return fw, rv


def extract(data, k: int, w: int, device: bool = False
            ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Minimizers of ``data``: (positions int64, hashes uint32,
    strands uint8).

    strand 0 means the forward k-mer is canonical, 1 means the
    reverse complement is.  One minimizer per window of w consecutive
    k-mer starts (leftmost-lowest-hash), deduplicated; k-mers touching
    non-ACGT bases and strand-ambiguous palindromes are masked before
    selection, exactly like minimap2 skips them.
    """
    k = max(3, min(int(k), MAX_K))
    w = max(1, int(w))
    codes = encode(data)
    n = codes.size
    nk = n - k + 1
    empty = (np.empty(0, dtype=np.int64), np.empty(0, dtype=np.uint32),
             np.empty(0, dtype=np.uint8))
    if nk <= 0:
        return empty
    fw, rv = kmer_words(codes, k, device=device)
    strand = (rv < fw).astype(np.uint8)
    hashes = mix32(np.where(strand, rv, fw))
    # mask k-mers spanning an invalid base, and palindromes (fw == rv)
    bad_base = np.concatenate(([0], np.cumsum(codes >= 4)))
    invalid = (bad_base[k:] - bad_base[:-k]) > 0
    hashes = np.where(invalid | (fw == rv), SENTINEL, hashes)
    nw = nk - w + 1
    if nw <= 0:
        # sequence shorter than one full window: keep the global min
        best = int(np.argmin(hashes))
        if hashes[best] == SENTINEL:
            return empty
        return (np.array([best], dtype=np.int64),
                hashes[best:best + 1], strand[best:best + 1])
    win = np.lib.stride_tricks.sliding_window_view(hashes, w)
    pos = np.argmin(win, axis=1) + np.arange(nw, dtype=np.int64)
    sel = np.unique(pos)
    sel = sel[hashes[sel] != SENTINEL]
    return sel, hashes[sel], strand[sel]
