"""Anchor collinear chaining: sorted-diagonal banding + LIS-style DP.

Per query: extract minimizers, look them up in the target index, and
turn the matching (query pos, target pos) anchor pairs into PAF-shaped
:class:`~racon_tpu.core.overlap.Overlap` records:

1. project reverse-strand anchors onto chain coordinates
   (qT = q_len - k - q_pos) so every colinear match is increasing in
   both axes regardless of orientation,
2. band: sort anchors by (target, strand, diagonal = t_pos - qT) and
   cut a new candidate cluster wherever the diagonal jumps more than
   ``band`` — a cheap stand-in for minimap2's chaining heuristic that
   keeps the DP quadratic-free,
3. chain: inside each band run an O(m log m) patience-LIS over
   (qT asc, t_pos desc) for the longest strictly-increasing anchor
   chain, then split it at gaps over ``max_gap`` and keep the longest
   piece,
4. admit chains with at least ``min_chain`` anchors; coordinates are
   the chain's bounding span (approximate, CIGAR-free) — downstream
   the polisher re-aligns breaking points per window exactly as it
   does for an external PAF, so approximate ends cost accuracy
   nothing.

Determinism: numpy sorts are stable, LIS tie-breaks are positional,
and emitted overlaps are ordered (query, -span, target, t_begin) — the
same inputs and knobs always produce the same overlap list and
therefore the same FASTA bytes.
"""

from __future__ import annotations

import os
from bisect import bisect_left
from typing import List, Optional, Sequence as PySequence, Tuple

import numpy as np

from racon_tpu.core.overlap import Overlap
from racon_tpu.overlap import minimizers
from racon_tpu.overlap.index import MinimizerIndex


class MapParams:
    """Mapper knobs.  k/w/occ_cap/min_chain/band/max_gap change which
    overlaps exist, hence bytes — they live in KNOWN_KNOBS and fold
    into the cache engine epoch.  device_seed only relocates the seed
    arithmetic (bit-equal) and is epoch-excluded."""

    __slots__ = ("k", "w", "occ_cap", "min_chain", "band", "max_gap",
                 "device_seed")

    def __init__(self, k: int = 13, w: int = 5, occ_cap: int = 64,
                 min_chain: int = 4, band: int = 500,
                 max_gap: int = 10_000, device_seed: bool = False):
        self.k = max(3, min(int(k), minimizers.MAX_K))
        self.w = max(1, int(w))
        self.occ_cap = max(1, int(occ_cap))
        self.min_chain = max(1, int(min_chain))
        self.band = max(1, int(band))
        self.max_gap = max(1, int(max_gap))
        self.device_seed = bool(device_seed)

    def doc(self) -> dict:
        return {"k": self.k, "w": self.w, "occ_cap": self.occ_cap,
                "min_chain": self.min_chain, "band": self.band,
                "max_gap": self.max_gap,
                "device_seed": int(self.device_seed)}


def params_from_env() -> MapParams:
    env = os.environ.get
    return MapParams(
        k=int(env("RACON_TPU_MAP_K", "13")),
        w=int(env("RACON_TPU_MAP_W", "5")),
        occ_cap=int(env("RACON_TPU_MAP_OCC", "64")),
        min_chain=int(env("RACON_TPU_MAP_MIN_CHAIN", "4")),
        band=int(env("RACON_TPU_MAP_BAND", "500")),
        max_gap=int(env("RACON_TPU_MAP_MAX_GAP", "10000")),
        device_seed=env("RACON_TPU_MAP_DEVICE_SEED", "0") == "1")


def _expand_ranges(left: np.ndarray, right: np.ndarray) -> np.ndarray:
    """Concatenate [left[i], right[i]) ranges into one index vector."""
    cnt = right - left
    total = int(cnt.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64)
    cum = np.cumsum(cnt) - cnt
    return (np.repeat(left, cnt)
            + (np.arange(total, dtype=np.int64) - np.repeat(cum, cnt)))


def _lis(qT: np.ndarray, tpos: np.ndarray) -> List[int]:
    """Longest chain with strictly increasing qT AND tpos.

    Anchors are sorted (qT asc, tpos desc); a strictly-increasing LIS
    on tpos then cannot take two anchors with equal qT, which makes
    the classic patience trick orientation-safe.  Returns anchor
    indices in chain order."""
    order = np.lexsort((-tpos, qT))
    t = tpos[order]
    tails: List[int] = []       # tpos value ending the best chain of len j+1
    tails_at: List[int] = []    # index (into order) of that anchor
    parent = np.full(t.size, -1, dtype=np.int64)
    for i in range(t.size):
        j = bisect_left(tails, t[i])
        if j == len(tails):
            tails.append(int(t[i]))
            tails_at.append(i)
        else:
            tails[j] = int(t[i])
            tails_at[j] = i
        parent[i] = tails_at[j - 1] if j > 0 else -1
    chain: List[int] = []
    at = tails_at[-1]
    while at >= 0:
        chain.append(int(order[at]))
        at = parent[at]
    chain.reverse()
    return chain


def _best_segment(chain: List[int], qT: np.ndarray, tpos: np.ndarray,
                  max_gap: int) -> List[int]:
    """Split the chain at query/target gaps over max_gap, keep the
    longest segment (earliest wins ties)."""
    best_lo = lo = 0
    best_n = 1
    for i in range(1, len(chain)):
        a, b = chain[i - 1], chain[i]
        if (tpos[b] - tpos[a] > max_gap) or (qT[b] - qT[a] > max_gap):
            if i - lo > best_n:
                best_lo, best_n = lo, i - lo
            lo = i
    if len(chain) - lo > best_n:
        best_lo, best_n = lo, len(chain) - lo
    return chain[best_lo:best_lo + best_n]


def chain_query(name: str, data: bytes, idx: MinimizerIndex,
                params: MapParams, target_names: PySequence[str],
                target_lengths: PySequence[int]
                ) -> Tuple[List[Overlap], int, int]:
    """Map one query against the index.  Returns (overlaps,
    admitted_chains, rejected_chains)."""
    q_len = len(data)
    qpos, qh, qstrand = minimizers.extract(data, params.k, params.w,
                                           device=params.device_seed)
    if qh.size == 0 or idx.hashes.size == 0:
        return [], 0, 0
    left, right = idx.lookup(qh)
    rows = _expand_ranges(left, right)
    if rows.size == 0:
        return [], 0, 0
    qi = np.repeat(np.arange(qh.size, dtype=np.int64), right - left)
    a_tid = idx.tid[rows].astype(np.int64)
    a_tpos = idx.tpos[rows]
    rel = (idx.tstrand[rows] ^ qstrand[qi]).astype(np.int64)
    a_qpos = qpos[qi]
    k = params.k
    qT = np.where(rel == 1, q_len - k - a_qpos, a_qpos)
    diag = a_tpos - qT
    order = np.lexsort((a_tpos, qT, diag, rel, a_tid))
    a_tid, rel, diag = a_tid[order], rel[order], diag[order]
    qT, a_tpos = qT[order], a_tpos[order]
    # band cuts: new (target, strand) group or diagonal jump > band
    cut = np.ones(a_tid.size, dtype=bool)
    if a_tid.size > 1:
        cut[1:] = ((a_tid[1:] != a_tid[:-1]) | (rel[1:] != rel[:-1])
                   | (diag[1:] - diag[:-1] > params.band))
    starts = np.flatnonzero(cut)
    ends = np.append(starts[1:], a_tid.size)
    overlaps: List[Overlap] = []
    admitted = rejected = 0
    for lo, hi in zip(starts, ends):
        if hi - lo < params.min_chain:
            rejected += 1
            continue
        c_qT = qT[lo:hi]
        c_tpos = a_tpos[lo:hi]
        chain = _lis(c_qT, c_tpos)
        chain = _best_segment(chain, c_qT, c_tpos, params.max_gap)
        if len(chain) < params.min_chain:
            rejected += 1
            continue
        admitted += 1
        tid = int(a_tid[lo])
        strand = int(rel[lo])
        qT_b, qT_e = int(c_qT[chain[0]]), int(c_qT[chain[-1]])
        t_begin = int(c_tpos[chain[0]])
        t_end = int(c_tpos[chain[-1]]) + k
        # extend the anchor bounding box toward the query ends
        # (clamped by the target): sparse chains on short/noisy reads
        # otherwise cover a fraction of the true span, starving the
        # window router — the breaking-point re-alignment downstream
        # absorbs any over-extension with gaps, exactly as it does
        # for an external mapper's approximate coordinates
        t_len = int(target_lengths[tid])
        ext = min(qT_b, t_begin)
        qT_b -= ext
        t_begin -= ext
        ext = min(q_len - k - qT_e, t_len - t_end)
        qT_e += ext
        t_end += ext
        if strand == 0:
            q_begin, q_end = qT_b, qT_e + k
        else:
            q_begin, q_end = q_len - k - qT_e, q_len - qT_b
        overlaps.append((len(chain), tid, t_begin, Overlap.from_paf(
            name, q_len, q_begin, q_end, "-" if strand else "+",
            target_names[tid], int(target_lengths[tid]), t_begin,
            t_end)))
    # deterministic emission: best span first, then target coordinates
    overlaps.sort(key=lambda rec: (-(rec[0]), rec[1], rec[2]))
    return [rec[3] for rec in overlaps], admitted, rejected


def map_sequences(queries: PySequence, targets: PySequence,
                  params: Optional[MapParams] = None,
                  idx: Optional[MinimizerIndex] = None
                  ) -> Tuple[List[Overlap], dict]:
    """Map every query against the target set.

    ``queries``/``targets`` are core Sequence objects (or any objects
    with ``name``/``data``).  Returns (overlaps, stats); overlaps are
    grouped per query in input order, PAF-shaped, ready for the same
    transmute/error-filter path a parsed PAF takes."""
    params = params or params_from_env()
    if idx is None:
        idx = MinimizerIndex.build(targets, params.k, params.w,
                                   params.occ_cap,
                                   device=params.device_seed)
    t_names = [t.name for t in targets]
    t_lens = [len(t.data) for t in targets]
    out: List[Overlap] = []
    admitted = rejected = 0
    for q in queries:
        ovl, adm, rej = chain_query(q.name, q.data, idx, params,
                                    t_names, t_lens)
        out.extend(ovl)
        admitted += adm
        rejected += rej
    stats = {"queries": len(queries), "targets": len(targets),
             "overlaps": len(out), "chains_admitted": admitted,
             "chains_rejected": rejected,
             "index_entries": idx.total_entries,
             "masked_entries": idx.masked_entries,
             "masked_hashes": idx.masked_hashes}
    stats.update({"map_" + key: val for key, val in params.doc().items()})
    return out, stats
