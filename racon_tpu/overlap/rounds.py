"""Multi-round polishing driver (r24).

polish -> write the polished draft -> re-map the reads against it ->
re-polish, N rounds.  Round 1 may consume an external overlaps file;
every later round re-discovers overlaps internally (the draft just
changed, so any client-supplied PAF is stale by definition).

Cache synergy: windows whose content did not move between rounds
digest identically (racon_tpu/cache content addressing), so round 2+
POA units come back as cache hits and only windows whose fragments
actually changed recompute.  The driver records the per-round
``cache_hit`` delta in ``rounds_report`` so callers (serve report,
tests, CI) can pin that reuse.

Determinism: each round is the deterministic single-round pipeline and
intermediate drafts are written canonically (``>name\\ndata\\n``), so
the same inputs + knobs produce byte-identical final FASTA, standalone
or served.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Callable, List, Optional, Tuple

from racon_tpu.obs import REGISTRY
from racon_tpu.obs import trace as obs_trace


def write_fasta(path: str, sequences) -> None:
    """Canonical FASTA writer shared by the rounds driver and the
    wrapper's client-side rounds loop: one record per line pair,
    exactly the CLI's stdout byte contract."""
    with open(path, "wb") as fh:
        fh.write(b"".join(b">" + seq.name.encode() + b"\n" + seq.data
                          + b"\n" for seq in sequences))


def polish_rounds(sequences_path: str, overlaps_path: Optional[str],
                  target_path: str, type_, window_length: int,
                  quality_threshold: float, error_threshold: float,
                  trim: bool, match: int, mismatch: int, gap: int,
                  num_threads: int, rounds: int = 1,
                  drop_unpolished: bool = True,
                  tpu_poa_batches: int = 0,
                  tpu_banded_alignment: bool = False,
                  tpu_aligner_batches: int = 0,
                  configure: Optional[Callable] = None,
                  workdir: Optional[str] = None) -> Tuple[List, object]:
    """Run ``rounds`` polishing rounds and return
    ``(polished_sequences, last_polisher)``.

    ``overlaps_path=None`` turns on internal mapping from round 1;
    with a path, round 1 parses it and rounds 2+ map internally.
    ``configure(polisher)`` is the serve tier's seam-wiring hook
    (tenant, shard, stage hint, cancel poll), applied to every
    round's polisher before ``initialize``.

    Intermediate rounds never drop unpolished targets (a target must
    survive to be re-polished); ``drop_unpolished`` applies to the
    final round only.  The last polisher is returned OPEN so callers
    can read its metrics/stage walls — they own the ``close()``.  Its
    ``rounds_report`` attribute holds the per-round stats list.
    """
    from racon_tpu.core.polisher import create_polisher

    rounds = max(1, int(rounds))
    target = target_path
    tmpdir: Optional[str] = None
    report: List[dict] = []
    polisher = None
    polished: List = []
    try:
        for i in range(rounds):
            final = i == rounds - 1
            hits0 = int(REGISTRY.value("cache_hit", 0))
            t0 = obs_trace.now()
            polisher = create_polisher(
                sequences_path,
                overlaps_path if i == 0 else None,
                target, type_, window_length, quality_threshold,
                error_threshold, trim, match, mismatch, gap,
                num_threads, tpu_poa_batches=tpu_poa_batches,
                tpu_banded_alignment=tpu_banded_alignment,
                tpu_aligner_batches=tpu_aligner_batches)
            try:
                if configure is not None:
                    configure(polisher)
                polisher.initialize()
                polished = polisher.polish(drop_unpolished if final
                                           else False)
            except BaseException:
                polisher.close()
                raise
            report.append({
                "round": i + 1,
                "wall_s": round(obs_trace.now() - t0, 6),
                "map_s": round(float(
                    polisher.metrics.value("host.map_s", 0.0)), 6),
                "overlaps": int(
                    polisher.metrics.value("map_overlaps", 0)),
                "cache_hit": int(REGISTRY.value("cache_hit", 0))
                - hits0,
                "n_sequences": len(polished),
            })
            if final:
                break
            polisher.close()
            polisher = None
            if tmpdir is None:
                tmpdir = tempfile.mkdtemp(prefix="rtrounds_",
                                          dir=workdir)
            target = os.path.join(tmpdir, f"round{i + 1}.fasta")
            write_fasta(target, polished)
    finally:
        if tmpdir is not None:
            shutil.rmtree(tmpdir, ignore_errors=True)
    polisher.rounds_report = report
    return polished, polisher
