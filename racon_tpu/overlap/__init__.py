"""Internal overlap discovery (r24): a minimap-lite read->draft mapper.

The reference pipeline never runs racon alone — real assemblies run
minimap2 to discover read->draft overlaps and polish 2-4 rounds.  This
package closes that gap in-process:

- :mod:`minimizers` — host-vectorized k-mer minimizer extraction
  (numpy rolling 2-bit pack + invertible 32-bit mix + windowed argmin,
  no per-base Python),
- :mod:`index`      — target-side minimizer hash index with
  occurrence-cap masking of repeats,
- :mod:`chain`      — anchor collinear chaining (sorted-diagonal
  banding + LIS-style DP) emitting PAF-shaped
  :class:`~racon_tpu.core.overlap.Overlap` records that feed the
  existing breaking-point re-align path exactly like an external PAF,
- :mod:`rounds`     — the multi-round driver: polish -> re-map reads
  against the polished draft -> re-polish, N rounds.

Determinism contract: mapping is pure data plane.  Same inputs =>
byte-identical overlaps => byte-identical FASTA.  The mapper knobs
(RACON_TPU_MAP_K/W/OCC/MIN_CHAIN/BAND/MAX_GAP) change bytes, so they
are registered in provenance KNOWN_KNOBS and fold into the cache
engine epoch (NOT EPOCH_EXCLUDEd).  RACON_TPU_MAP_DEVICE_SEED only
moves the seeding arithmetic between host and device with bit-equal
results, so it is epoch-excluded like every placement knob.
"""

from racon_tpu.overlap.chain import MapParams, map_sequences, params_from_env
from racon_tpu.overlap.rounds import polish_rounds

__all__ = ["MapParams", "map_sequences", "params_from_env",
           "polish_rounds", "map_files"]


def map_files(sequences_path: str, target_path: str, params=None):
    """Map reads from ``sequences_path`` against ``target_path`` and
    return (overlaps, stats).  Standalone convenience over the same
    code path the polisher uses — fastio scan parsers stream both
    files, then :func:`chain.map_sequences` does the work."""
    from racon_tpu.io import fastio
    from racon_tpu.io.parsers import create_sequence_parser

    reads = fastio.drain(create_sequence_parser(sequences_path))
    targets = fastio.drain(create_sequence_parser(target_path))
    return map_sequences(reads, targets, params=params)
