"""Target-side minimizer hash index with occurrence-cap repeat masking.

One flat sorted table over every target's minimizers: (hash, target
id, target position, target strand), sorted by hash with a stable sort
so same-hash anchors keep (target, position) order — lookups are two
searchsorteds, and iteration order (hence chaining, hence bytes) is
deterministic.  Hashes occurring more than ``occ_cap`` times across
the target set are repeats by definition and are dropped wholesale
before lookup, the same job minimap2's -f/--mask-level does: repeat
seeds explode the anchor count without adding placement information.
"""

from __future__ import annotations

from typing import List, Sequence as PySequence

import numpy as np

from racon_tpu.overlap import minimizers


class MinimizerIndex:
    """Immutable minimizer table over a target set."""

    __slots__ = ("k", "w", "occ_cap", "hashes", "tid", "tpos",
                 "tstrand", "n_targets", "masked_hashes",
                 "masked_entries", "total_entries", "device")

    def __init__(self, k: int, w: int, occ_cap: int, device: bool = False):
        self.k = max(3, min(int(k), minimizers.MAX_K))
        self.w = max(1, int(w))
        self.occ_cap = max(1, int(occ_cap))
        self.device = bool(device)
        self.hashes = np.empty(0, dtype=np.uint32)
        self.tid = np.empty(0, dtype=np.int32)
        self.tpos = np.empty(0, dtype=np.int64)
        self.tstrand = np.empty(0, dtype=np.uint8)
        self.n_targets = 0
        self.masked_hashes = 0
        self.masked_entries = 0
        self.total_entries = 0

    @classmethod
    def build(cls, targets: PySequence, k: int, w: int, occ_cap: int,
              device: bool = False) -> "MinimizerIndex":
        """Index every target's data buffer.  ``targets`` is any
        sequence of objects with a ``data`` bytes attribute (core
        Sequence) or raw bytes."""
        idx = cls(k, w, occ_cap, device=device)
        hs: List[np.ndarray] = []
        tids: List[np.ndarray] = []
        poss: List[np.ndarray] = []
        strands: List[np.ndarray] = []
        for t, target in enumerate(targets):
            data = getattr(target, "data", target)
            pos, h, s = minimizers.extract(data, idx.k, idx.w,
                                           device=idx.device)
            if h.size == 0:
                continue
            hs.append(h)
            tids.append(np.full(h.size, t, dtype=np.int32))
            poss.append(pos)
            strands.append(s)
        idx.n_targets = len(targets)
        if not hs:
            return idx
        h = np.concatenate(hs)
        tid = np.concatenate(tids)
        pos = np.concatenate(poss)
        strand = np.concatenate(strands)
        order = np.argsort(h, kind="stable")
        h, tid, pos, strand = h[order], tid[order], pos[order], strand[order]
        idx.total_entries = int(h.size)
        uniq, inverse, counts = np.unique(h, return_inverse=True,
                                          return_counts=True)
        keep = counts[inverse] <= idx.occ_cap
        idx.masked_hashes = int((counts > idx.occ_cap).sum())
        idx.masked_entries = int(h.size - keep.sum())
        idx.hashes = h[keep]
        idx.tid = tid[keep]
        idx.tpos = pos[keep]
        idx.tstrand = strand[keep]
        return idx

    def lookup(self, query_hashes: np.ndarray):
        """(left, right) bounds into the table for each query hash —
        table rows [left[i], right[i]) match query_hashes[i]."""
        left = np.searchsorted(self.hashes, query_hashes, side="left")
        right = np.searchsorted(self.hashes, query_hashes, side="right")
        return left, right
