"""Window domain object.

A window holds a backbone slice of the target plus read fragments
("layers") routed to it via overlap breaking points, and produces a POA
consensus (reference: src/window.cpp).  The consensus computation itself
is delegated to an engine (native C++ CPU engine, or batched on TPU);
this object only holds the data and mirrors the reference's window-level
policies: fewer than 3 sequences -> backbone copied verbatim and the
window counts as unpolished (src/window.cpp:68-71); layers sorted by
start position (src/window.cpp:84-85); TGS consensus end-trim at
coverage < (n_layers - 1) / 2 (src/window.cpp:118-139).
"""

from __future__ import annotations

import enum
import sys
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple


class WindowType(enum.Enum):
    NGS = 0   # short accurate reads (mean length <= 1000)
    TGS = 1   # long noisy reads


class WindowLedger:
    """Per-window completion accounting for cross-stage streaming.

    The polish pipeline's two stages are linked by windows: a window
    can enter POA as soon as every overlap that COULD route a layer
    into it has its breaking points.  This ledger tracks that: each
    overlap registers the window-id range its target span covers
    (``register``), alignment completion decrements the range
    (``complete``), and windows whose pending count hits zero are
    handed back together with their stashed layer fragments.

    Determinism: fragments are stashed with the overlap's ordinal (its
    index in the filtered overlap list) and a window's stash is drained
    sorted by ordinal, so the layer insertion order per window is the
    overlap-list order — byte-identical to the staged
    ``Polisher._build_windows`` routing no matter in which order
    alignments finish.  All state is guarded by one lock; ``cond``
    doubles as the producer/consumer wakeup for the streaming POA
    consumer (racon_tpu/tpu/polisher.py).
    """

    def __init__(self, n_windows: int):
        import numpy as np

        self.pending = np.zeros(n_windows, np.int32)
        self.cond = threading.Condition()
        # id(overlap) -> (ordinal, lo, hi); popped on completion so a
        # duplicate completion notification is a no-op
        self._reg: Dict[int, Tuple[int, int, int]] = {}
        # window id -> [(ordinal, fragment...), ...]
        self._stash: Dict[int, list] = {}
        self.ready: deque = deque()
        # ready-queue high-water mark: how deep the speculative POA
        # consumer's backlog ever got (obs metric
        # ledger_ready_high_water; the polisher publishes it)
        self.ready_high_water = 0
        self._sealed = False
        self.n_completed = 0

    def register(self, key: int, ordinal: int, lo: int, hi: int) -> None:
        """Mark windows [lo, hi] as pending one more overlap."""
        with self.cond:
            if self._sealed:
                raise RuntimeError("WindowLedger sealed")
            self._reg[key] = (ordinal, lo, hi)
            self.pending[lo:hi + 1] += 1

    def seal(self) -> None:
        """End of registration: from here on zero-pending windows are
        complete (windows no overlap covers are complete immediately,
        but carry no fragments — callers skip them)."""
        with self.cond:
            self._sealed = True

    def complete(self, key: int, frags) -> List[Tuple[int, list]]:
        """Record one overlap's completion with its routed fragments
        ``(ordinal, window_id, *fragment)``.  Returns
        ``[(window_id, ordinal_sorted_fragments), ...]`` for every
        window that became fully routed; unknown/duplicate keys are
        no-ops (the catch-all completion pass may re-notify)."""
        import numpy as np

        with self.cond:
            reg = self._reg.pop(key, None)
            if reg is None:
                return []
            _, lo, hi = reg
            for fr in frags:
                self._stash.setdefault(fr[1], []).append(fr)
            seg = self.pending[lo:hi + 1]
            seg -= 1
            self.n_completed += 1
            newly = (lo + np.flatnonzero(seg == 0)).tolist()
            return [(wid, sorted(self._stash.pop(wid, []),
                                 key=lambda fr: fr[0]))
                    for wid in newly]

    def remaining(self) -> List[int]:
        """Registered-but-uncompleted overlap keys, ordinal order."""
        with self.cond:
            return [k for k, _ in sorted(self._reg.items(),
                                         key=lambda kv: kv[1][0])]

    def push_ready(self, wids: List[int]) -> None:
        """Publish fully-routed (and caller-filtered) windows to the
        consumer and wake it."""
        if not wids:
            return
        with self.cond:
            self.ready.extend(wids)
            self.ready_high_water = max(self.ready_high_water,
                                        len(self.ready))
            self.cond.notify_all()

    def pop_ready(self, cap: int, min_n: int = 1) -> List[int]:
        """Take up to ``cap`` ready windows, or none when fewer than
        ``min_n`` are queued (tiny speculative batches waste dispatch
        overhead and mint fresh kernel-variant shapes)."""
        with self.cond:
            if len(self.ready) < max(1, min_n):
                return []
            n = min(cap, len(self.ready))
            return [self.ready.popleft() for _ in range(n)]

    def n_ready(self) -> int:
        with self.cond:
            return len(self.ready)


class Window:
    __slots__ = ("id", "rank", "type", "consensus", "sequences",
                 "qualities", "positions")

    def __init__(self, id_: int, rank: int, type_: WindowType,
                 backbone: bytes, quality: bytes):
        if len(backbone) == 0 or len(backbone) != len(quality):
            raise RuntimeError(
                "[racon_tpu::Window] empty backbone sequence/unequal "
                "quality length!")
        self.id = id_
        self.rank = rank
        self.type = type_
        self.consensus: bytes = b""
        # layer 0 is the backbone; positions are window-relative
        self.sequences: List[bytes] = [backbone]
        self.qualities: List[Optional[bytes]] = [quality]
        self.positions: List[Tuple[int, int]] = [(0, 0)]

    @property
    def backbone(self) -> bytes:
        return self.sequences[0]

    def add_layer(self, sequence: bytes, quality: Optional[bytes],
                  begin: int, end: int) -> None:
        if len(sequence) == 0 or begin == end:
            return
        if quality is not None and len(sequence) != len(quality):
            raise RuntimeError(
                "[racon_tpu::Window::add_layer] unequal quality size!")
        if begin >= end or begin > len(self.backbone) or \
                end > len(self.backbone):
            raise RuntimeError(
                "[racon_tpu::Window::add_layer] layer begin and end "
                "positions are invalid!")
        self.sequences.append(sequence)
        self.qualities.append(quality)
        self.positions.append((begin, end))

    def num_layers(self) -> int:
        return len(self.sequences)

    def generate_consensus(self, engine, trim: bool) -> bool:
        """Run POA consensus through ``engine``; returns polished flag.

        ``engine.consensus(window, trim) -> bytes`` encapsulates graph
        seeding with the backbone, aligned layer incorporation in
        start-position order, consensus + coverages, and the TGS trim --
        see racon_tpu.ops.cpu.PoaEngine for the CPU implementation.
        """
        if len(self.sequences) < 3:
            self.consensus = self.sequences[0]
            return False
        self.consensus = engine.consensus(self, trim)
        return True

    def warn_chimeric(self) -> None:
        print(f"[racon_tpu::Window::generate_consensus] warning: contig "
              f"{self.id} might be chimeric in window {self.rank}!",
              file=sys.stderr)
