"""Window domain object.

A window holds a backbone slice of the target plus read fragments
("layers") routed to it via overlap breaking points, and produces a POA
consensus (reference: src/window.cpp).  The consensus computation itself
is delegated to an engine (native C++ CPU engine, or batched on TPU);
this object only holds the data and mirrors the reference's window-level
policies: fewer than 3 sequences -> backbone copied verbatim and the
window counts as unpolished (src/window.cpp:68-71); layers sorted by
start position (src/window.cpp:84-85); TGS consensus end-trim at
coverage < (n_layers - 1) / 2 (src/window.cpp:118-139).
"""

from __future__ import annotations

import enum
import sys
from typing import List, Optional, Tuple


class WindowType(enum.Enum):
    NGS = 0   # short accurate reads (mean length <= 1000)
    TGS = 1   # long noisy reads


class Window:
    __slots__ = ("id", "rank", "type", "consensus", "sequences",
                 "qualities", "positions")

    def __init__(self, id_: int, rank: int, type_: WindowType,
                 backbone: bytes, quality: bytes):
        if len(backbone) == 0 or len(backbone) != len(quality):
            raise RuntimeError(
                "[racon_tpu::Window] empty backbone sequence/unequal "
                "quality length!")
        self.id = id_
        self.rank = rank
        self.type = type_
        self.consensus: bytes = b""
        # layer 0 is the backbone; positions are window-relative
        self.sequences: List[bytes] = [backbone]
        self.qualities: List[Optional[bytes]] = [quality]
        self.positions: List[Tuple[int, int]] = [(0, 0)]

    @property
    def backbone(self) -> bytes:
        return self.sequences[0]

    def add_layer(self, sequence: bytes, quality: Optional[bytes],
                  begin: int, end: int) -> None:
        if len(sequence) == 0 or begin == end:
            return
        if quality is not None and len(sequence) != len(quality):
            raise RuntimeError(
                "[racon_tpu::Window::add_layer] unequal quality size!")
        if begin >= end or begin > len(self.backbone) or \
                end > len(self.backbone):
            raise RuntimeError(
                "[racon_tpu::Window::add_layer] layer begin and end "
                "positions are invalid!")
        self.sequences.append(sequence)
        self.qualities.append(quality)
        self.positions.append((begin, end))

    def num_layers(self) -> int:
        return len(self.sequences)

    def generate_consensus(self, engine, trim: bool) -> bool:
        """Run POA consensus through ``engine``; returns polished flag.

        ``engine.consensus(window, trim) -> bytes`` encapsulates graph
        seeding with the backbone, aligned layer incorporation in
        start-position order, consensus + coverages, and the TGS trim --
        see racon_tpu.ops.cpu.PoaEngine for the CPU implementation.
        """
        if len(self.sequences) < 3:
            self.consensus = self.sequences[0]
            return False
        self.consensus = engine.consensus(self, trim)
        return True

    def warn_chimeric(self) -> None:
        print(f"[racon_tpu::Window::generate_consensus] warning: contig "
              f"{self.id} might be chimeric in window {self.rank}!",
              file=sys.stderr)
