"""Polisher: the pipeline orchestrator (reference: src/polisher.{hpp,cpp}).

Drives parse -> overlap filtering -> breaking points -> windowing ->
batched consensus -> stitching.  The accelerator seam is the same as the
reference's (src/polisher.hpp:55,74): two overridable methods,
``find_overlap_breaking_points`` and ``generate_consensuses``; the
TPUPolisher subclass (racon_tpu.tpu.polisher) overrides both to run the
batched device kernels with CPU fallback for whatever the device path
rejects, exactly like CUDAPolisher (src/cuda/cudapolisher.cpp).
"""

from __future__ import annotations

import concurrent.futures
import enum
from typing import Dict, List, Optional

from racon_tpu.core import overlap as overlap_mod
from racon_tpu.core.overlap import InvalidInputError, Overlap
from racon_tpu.core.sequence import Sequence
from racon_tpu.core.window import Window, WindowType
from racon_tpu.io.parsers import (create_overlap_parser,
                                  create_sequence_parser)
from racon_tpu.obs import REGISTRY, Registry
from racon_tpu.obs import calhealth as obs_calhealth
from racon_tpu.obs import trace as obs_trace
from racon_tpu.ops import cpu
from racon_tpu.utils.logger import Logger

CHUNK_SIZE = 1024 * 1024 * 1024  # reference kChunkSize (polisher.cpp:26)


class JobCanceledError(RuntimeError):
    """The serve tier canceled this job (r21 straggler rebalancing:
    the router superseded a slow shard with a replacement attempt and
    sent best-effort ``cancel`` to the original).  Raised from the
    polisher's cancel poll sites — always BETWEEN committed units, so
    a canceled job's journal/checkpoint state stays consistent."""


class PolisherType(enum.Enum):
    kC = 0  # contig polishing
    kF = 1  # fragment (read) error correction


def create_polisher(sequences_path: str, overlaps_path: Optional[str],
                    target_path: str, type_: PolisherType,
                    window_length: int, quality_threshold: float,
                    error_threshold: float, trim: bool, match: int,
                    mismatch: int, gap: int, num_threads: int,
                    tpu_poa_batches: int = 0,
                    tpu_banded_alignment: bool = False,
                    tpu_aligner_batches: int = 0) -> "Polisher":
    """Factory mirroring racon::createPolisher (src/polisher.cpp:55-159).

    TPU offload is selected per stage by ``tpu_poa_batches`` /
    ``tpu_aligner_batches`` the same way the reference gates CUDA
    offload by --cudapoa-batches / --cudaaligner-batches.

    ``overlaps_path=None`` (r24) selects internal overlap discovery:
    instead of parsing a PAF/MHAP/SAM file, initialize() maps the
    reads against the targets with the built-in minimap-lite mapper
    (racon_tpu/overlap) and feeds the discovered overlaps through the
    exact same filter/align path.
    """
    if not isinstance(type_, PolisherType):
        raise InvalidInputError("invalid polisher type!")
    if window_length == 0:
        raise InvalidInputError("invalid window length!")

    sparser = create_sequence_parser(sequences_path)
    oparser = (create_overlap_parser(overlaps_path)
               if overlaps_path is not None else None)
    tparser = create_sequence_parser(target_path)

    if tpu_poa_batches > 0 or tpu_aligner_batches > 0:
        try:
            from racon_tpu.tpu.polisher import TPUPolisher
        except ImportError as exc:
            raise InvalidInputError(
                f"TPU support is not available ({exc})") from exc
        return TPUPolisher(sparser, oparser, tparser, type_, window_length,
                           quality_threshold, error_threshold, trim, match,
                           mismatch, gap, num_threads, tpu_poa_batches,
                           tpu_banded_alignment, tpu_aligner_batches)
    return Polisher(sparser, oparser, tparser, type_, window_length,
                    quality_threshold, error_threshold, trim, match,
                    mismatch, gap, num_threads)


class _MappedOverlapSource:
    """Parser-shaped view over internally discovered overlaps (r24).

    Lets ``_load_overlaps`` run its existing transmute/filter loop
    unchanged over in-memory mapper output: one chunk, then done.  No
    ``set_stage`` on purpose — staged-input plans describe file byte
    ranges and do not apply to mapped records."""

    def __init__(self, records: List[Overlap]):
        self._records = records
        self._done = False

    def reset(self) -> None:
        self._done = False

    def close(self) -> None:
        self._records = []

    def parse(self, dst: List[Overlap], max_bytes: int) -> bool:
        if not self._done:
            dst.extend(self._records)
            self._done = True
        return False


class Polisher:
    def __init__(self, sparser, oparser, tparser, type_: PolisherType,
                 window_length: int, quality_threshold: float,
                 error_threshold: float, trim: bool, match: int,
                 mismatch: int, gap: int, num_threads: int):
        self.sparser = sparser
        self.oparser = oparser
        self.tparser = tparser
        self.type = type_
        self.window_length = window_length
        self.quality_threshold = quality_threshold
        self.error_threshold = error_threshold
        self.trim = trim
        self.match, self.mismatch, self.gap = match, mismatch, gap
        self.num_threads = max(1, num_threads)

        self.sequences: List[Sequence] = []
        self.windows: List[Window] = []
        self.targets_coverages: List[int] = []
        self._owned_targets = None   # multi-host target mask
        # r20 scatter: the serve tier sets (index, count) on a
        # target-sharded sub-job; initialize() turns it into the same
        # target_slice ownership mask the multi-host path uses
        self._target_shard = None
        # r21 serve seams (racon_tpu/serve/session.py wires both):
        # a staged-input hint shipped with a scattered sub-job
        # (spec["stage"] -> ranged overlap scan), and a cancel poll
        # the straggler rebalancer uses to stop a superseded original
        self._stage_hint = None
        self._cancel_check = None
        # streaming bookkeeping (racon_tpu/tpu/polisher.py pipeline):
        # window-id offsets per target, and whether the subclass
        # already counted per-target coverages at registration time
        self._first_window_id: List[int] = []
        self._targets_size = 0
        self._coverage_counted = False
        # r24 internal mapping: oparser None means initialize()
        # discovers overlaps with racon_tpu/overlap instead of
        # parsing a file; stats land here for reports/decisions
        self._map_stats: Optional[dict] = None
        # per-stage wall clocks surfaced in --metrics-json and the
        # serve report (the TPU subclass adds its device stages)
        self.stage_walls: Dict[str, float] = {}
        self.dummy_quality = b"!" * window_length
        # per-run metrics registry (racon_tpu/obs): every counter this
        # run records also propagates into the process-wide REGISTRY,
        # so bench.py reads per-polish numbers here and the CLI's
        # --metrics-json report is assembled from the same store
        self.metrics = Registry(parent=REGISTRY)
        self.engine = cpu.PoaEngine(match, mismatch, gap)
        self.logger = Logger()
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self.num_threads)

    # ------------------------------------------------------------------
    # initialize: reference src/polisher.cpp:191-459
    # ------------------------------------------------------------------

    def initialize(self) -> None:
        if self.windows:
            print("[racon_tpu::Polisher::initialize] warning: object "
                  "already initialized!")
            return

        self.logger.log()
        # run-wall anchor for the derived host.share gauge (obs clock;
        # records only, never feeds control flow)
        self._t_run_start = obs_trace.now()
        with obs_trace.span("racon_tpu.load_targets", cat="stage",
                            metric="host.parse_s",
                            registry=self.metrics):
            self.tparser.reset()
            self.tparser.parse(self.sequences, -1)
        targets_size = len(self.sequences)
        if targets_size == 0:
            raise InvalidInputError("empty target sequences set!")

        # multi-host scale-out: under jax.distributed each rank owns a
        # deterministic contiguous slice of the targets, builds
        # windows only for those, and emits only those (the wrapper
        # --split flow, cross-host; racon_tpu/parallel/multihost.py).
        # Ownership is a MASK, not a slice: every id mapping (MHAP's
        # order-based ids included) must see the full target set.
        from racon_tpu.parallel import multihost
        nproc, rank = multihost.maybe_initialize()
        self._owned_targets = None
        if nproc > 1:
            sl = multihost.target_slice(targets_size, nproc, rank)
            self._owned_targets = [sl.start <= i < sl.stop
                                   for i in range(targets_size)]
            self.logger.log(
                f"[racon_tpu::Polisher::initialize] multi-host rank "
                f"{rank}/{nproc}: targets [{sl.start}, {sl.stop})")
        # r20 scatter (racon_tpu/serve/scatter.py): a scattered
        # sub-job owns one target_slice shard of the full target set.
        # Reusing the multi-host mask means the shard's emitted bytes
        # are exactly the slice the `cat part*.fa` contract pins, so
        # concatenating shard outputs in index order reproduces the
        # unsharded run byte-for-byte.  A multi-host rank is never
        # also a serve shard (the mask above wins).
        if self._owned_targets is None and self._target_shard:
            index, count = self._target_shard
            sl = multihost.target_slice(targets_size, count, index)
            self._owned_targets = [sl.start <= i < sl.stop
                                   for i in range(targets_size)]
            self.logger.log(
                f"[racon_tpu::Polisher::initialize] target shard "
                f"{index}/{count}: targets [{sl.start}, {sl.stop})")

        name_to_id: Dict[str, int] = {}
        id_to_id: Dict[int, int] = {}
        for i in range(targets_size):
            name_to_id[self.sequences[i].name + "t"] = i
            id_to_id[i << 1 | 1] = i

        has_name = [True] * targets_size
        has_data = [True] * targets_size
        has_reverse_data = [False] * targets_size

        self.logger.log("[racon_tpu::Polisher::initialize] loaded target "
                        "sequences")
        self.logger.log()

        # reads, with duplicate read-as-target dedup
        # (reference: src/polisher.cpp:228-263)
        sequences_size = 0
        total_sequences_length = 0
        self.sparser.reset()
        _t_seq = obs_trace.now()
        while True:
            chunk_start = len(self.sequences)
            status = self.sparser.parse(self.sequences, CHUNK_SIZE)
            kept: List[Sequence] = []
            n_dropped = 0
            for i in range(chunk_start, len(self.sequences)):
                seq = self.sequences[i]
                total_sequences_length += len(seq.data)
                existing = name_to_id.get(seq.name + "t")
                if existing is not None:
                    if len(seq.data) != \
                            len(self.sequences[existing].data) or \
                            len(seq.quality) != \
                            len(self.sequences[existing].quality):
                        raise InvalidInputError(
                            f"duplicate sequence {seq.name} with unequal "
                            "data")
                    name_to_id[seq.name + "q"] = existing
                    id_to_id[sequences_size << 1 | 0] = existing
                    n_dropped += 1
                else:
                    new_id = i - n_dropped
                    name_to_id[seq.name + "q"] = new_id
                    id_to_id[sequences_size << 1 | 0] = new_id
                    kept.append(seq)
                sequences_size += 1
            del self.sequences[chunk_start:]
            self.sequences.extend(kept)
            if not status:
                break
        _t_seq_end = obs_trace.now()
        obs_trace.TRACER.add_span("racon_tpu.load_sequences", _t_seq,
                                  _t_seq_end, cat="stage")
        self.metrics.add("host.parse_s", _t_seq_end - _t_seq)

        if sequences_size == 0:
            raise InvalidInputError("empty sequences set!")

        n_total = len(self.sequences)
        has_name += [False] * (n_total - targets_size)
        has_data += [False] * (n_total - targets_size)
        has_reverse_data += [False] * (n_total - targets_size)

        window_type = (WindowType.NGS
                       if total_sequences_length / sequences_size <= 1000
                       else WindowType.TGS)
        # recorded for subclasses that predict device-kernel variants
        # or create windows before the align stage finishes
        # (racon_tpu/tpu/polisher.py prewarm + streaming pipeline)
        self.window_type = window_type
        self._targets_size = targets_size

        self.logger.log("[racon_tpu::Polisher::initialize] loaded sequences")
        self.logger.log()

        # parsed overlaps bill the parse budget; internally mapped
        # ones bill the map stage (host.map_s + stage_walls["map"]),
        # which is how the stage reaches calhealth drift and the
        # serve `explain` cost waterfall
        mapping = self.oparser is None
        with obs_trace.span("racon_tpu.load_overlaps", cat="stage",
                            metric=("host.map_s" if mapping
                                    else "host.parse_s"),
                            registry=self.metrics):
            overlaps = self._load_overlaps(name_to_id, id_to_id,
                                           has_data, has_reverse_data)
        if mapping:
            self.stage_walls["map"] = float(
                self.metrics.value("host.map_s", 0.0))
        # a multi-host rank may legitimately own zero overlaps (its
        # targets drew none); only single-process runs treat an empty
        # set as invalid input
        if not overlaps and self._owned_targets is None:
            raise InvalidInputError("empty overlap set!")

        self.logger.log("[racon_tpu::Polisher::initialize] loaded overlaps")
        self.logger.log()

        # materialise reverse complements in the pool
        # (reference: src/polisher.cpp:368-377)
        with obs_trace.span("racon_tpu.transmute", cat="stage"):
            list(self._pool.map(
                lambda args: args[0].transmute(*args[1:]),
                [(s, has_name[j], has_data[j], has_reverse_data[j])
                 for j, s in enumerate(self.sequences)]))

        with obs_trace.span("racon_tpu.align_stage", cat="stage",
                            metric="stage_wall_s.align",
                            registry=self.metrics):
            self.find_overlap_breaking_points(overlaps)

        self.logger.log()
        with obs_trace.span("racon_tpu.build_windows", cat="stage"):
            self._build_windows(targets_size, window_type, overlaps)
        self.logger.log("[racon_tpu::Polisher::initialize] transformed data "
                        "into windows")

    def _poll_cancel(self) -> None:
        """Raise :class:`JobCanceledError` if the serve tier flagged
        this job canceled (r21 rebalancing).  Poll sites sit between
        committed units only, so cancellation never tears a unit."""
        if self._cancel_check is not None and self._cancel_check():
            raise JobCanceledError("job canceled by the serve tier")

    def _configure_stage(self):
        """Apply the r21 staged-input plan to the overlap parser
        before the parse: a validated router-shipped hint wins; a
        sharded polisher with no (valid) hint self-builds the index
        from its own line tables; anything that cannot be exact —
        staging off, line parsers, non-PAF input, malformed rows —
        falls back to the unchanged full parse by returning None."""
        from racon_tpu.io import staging

        if self._owned_targets is None or not staging.stage_enabled() \
                or not hasattr(self.oparser, "set_stage"):
            return None
        plan = None
        if self._stage_hint is not None:
            plan = staging.plan_from_hint(
                self._stage_hint, self.oparser.path, self._target_shard)
        if plan is None:
            names = [self.sequences[i].name
                     for i in range(self._targets_size)]
            index = staging.get_index(self.oparser.path, names)
            if index is None:
                return None
            plan = index.ranges_for(self._owned_targets)
            plan["total_bytes"] = index.total_bytes
        self.oparser.set_stage(plan["ranges"])
        self.metrics.set("host.staged_bytes",
                         int(plan.get("staged_bytes", 0)))
        self.metrics.set("host.parse_skipped_bytes",
                         max(0, int(plan.get("total_bytes", 0))
                             - int(plan.get("staged_bytes", 0))))
        return plan

    def _discover_overlaps(self) -> List[Overlap]:
        """r24 internal mapping: run the minimap-lite mapper over the
        already-loaded reads/targets and return PAF-shaped Overlap
        records, ready for the same transmute/filter loop a parsed
        file takes.  Reads deduplicated into targets are not mapped —
        their only admissible overlap (self vs self) is exactly what
        the ``q_id == t_id`` filter drops anyway."""
        from racon_tpu.obs import decision as obs_decision
        from racon_tpu.overlap import chain as overlap_chain

        params = overlap_chain.params_from_env()
        targets = self.sequences[:self._targets_size]
        queries = self.sequences[self._targets_size:]
        raw, stats = overlap_chain.map_sequences(queries, targets,
                                                 params=params)
        self._map_stats = stats
        self.metrics.add("map_queries", len(queries))
        self.metrics.add("map_overlaps", len(raw))
        self.metrics.add("map_chains_admitted",
                         stats["chains_admitted"])
        self.metrics.add("map_chains_rejected",
                         stats["chains_rejected"])
        obs_decision.DECISIONS.record(
            "map_chain", queries=len(queries),
            targets=len(targets), overlaps=len(raw),
            admitted=stats["chains_admitted"],
            rejected=stats["chains_rejected"],
            masked_entries=stats["masked_entries"],
            knobs=params.doc())
        self.logger.log(
            f"[racon_tpu::Polisher::initialize] mapped {len(queries)} "
            f"reads -> {len(raw)} overlaps "
            f"({stats['chains_rejected']} chains rejected)")
        return raw

    def _load_overlaps(self, name_to_id, id_to_id, has_data,
                       has_reverse_data) -> List[Overlap]:
        """Stream overlaps, transmute, and filter (polisher.cpp:283-354)."""
        if self.oparser is None:
            # internal mapping: same downstream loop, fed from an
            # in-memory single-chunk source instead of a file parser
            self.oparser = _MappedOverlapSource(self._discover_overlaps())
        self._configure_stage()
        overlaps: List[Optional[Overlap]] = []

        def remove_invalid(begin: int, end: int) -> None:
            for i in range(begin, end):
                if overlaps[i] is None:
                    continue
                o = overlaps[i]
                if o.error > self.error_threshold or o.q_id == o.t_id:
                    overlaps[i] = None
                    continue
                if self.type == PolisherType.kC:
                    # keep only the longest overlap per query
                    for j in range(i + 1, end):
                        if overlaps[j] is None:
                            continue
                        if o.length > overlaps[j].length:
                            overlaps[j] = None
                        else:
                            overlaps[i] = None
                            break

        self.oparser.reset()
        l = 0
        while True:
            status = self.oparser.parse(overlaps, CHUNK_SIZE)
            c = l
            for i in range(l, len(overlaps)):
                overlaps[i].transmute(self.sequences, name_to_id, id_to_id)
                if not overlaps[i].is_valid:
                    overlaps[i] = None
                    continue
                while overlaps[c] is None:
                    c += 1
                if overlaps[c].q_id != overlaps[i].q_id:
                    remove_invalid(c, i)
                    c = i
            if not status:
                remove_invalid(c, len(overlaps))
                c = len(overlaps)

            for i in range(l, c):
                if overlaps[i] is None:
                    continue
                if self._owned_targets is not None and \
                        not self._owned_targets[overlaps[i].t_id]:
                    # multi-host: another rank owns this target.  The
                    # drop must come AFTER remove_invalid (the longest
                    # -per-query winner is chosen over ALL targets,
                    # matching single-process output) but BEFORE the
                    # flag marking, so this rank never materializes
                    # reverse complements for reads whose overlaps
                    # all belong to other ranks
                    overlaps[i] = None
                    continue
                if overlaps[i].strand:
                    has_reverse_data[overlaps[i].q_id] = True
                else:
                    has_data[overlaps[i].q_id] = True

            # compact nulls from l onward (reference shrinkToFit,
            # src/polisher.cpp:348-349)
            n_removed_before_c = sum(
                1 for o in overlaps[l:c] if o is None)
            overlaps[l:] = [o for o in overlaps[l:] if o is not None]
            l = c - n_removed_before_c
            if not status:
                break
        return overlaps  # type: ignore[return-value]

    # ------------------------------------------------------------------
    # accelerator seam #1 (reference: src/polisher.cpp:461-483)
    # ------------------------------------------------------------------

    def _batch_decode_breaking_points(self,
                                      overlaps: List[Overlap]) -> None:
        """Vectorized pre-pass: decode breaking points for every
        overlap already carrying ``cigar_runs`` (SAM ingest, or a
        staged device-align pass) in slab-sized batches fanned over
        the pool — ``work(o)`` then sees points set and skips the
        per-overlap walk.  A failed slab is left undecoded so the
        per-overlap path isolates a poison record to its own error."""
        slabs = overlap_mod.iter_decode_slabs(overlaps)
        if not slabs:
            return

        def one(slab):
            try:
                with self.metrics.timer("host.bp_decode_s"):
                    overlap_mod.decode_breaking_points_batch(
                        slab, self.window_length)
            except Exception:
                pass

        if len(slabs) > 1 and self.num_threads > 1:
            list(self._pool.map(one, slabs))
        else:
            for slab in slabs:
                one(slab)

    def find_overlap_breaking_points(self, overlaps: List[Overlap]) -> None:
        self._batch_decode_breaking_points(overlaps)

        def work(o: Overlap) -> None:
            o.find_breaking_points(self.sequences, self.window_length,
                                   aligner=cpu.align)
            self._notify_overlap_done(o)

        self._run_pooled([(work, (o,)) for o in overlaps],
                         "[racon_tpu::Polisher::initialize] aligning "
                         "overlaps",
                         "[racon_tpu::Polisher::initialize] aligned "
                         "overlaps")

    def _run_pooled(self, tasks, bar_message: str,
                    done_message: str) -> list:
        """Fan tasks over the pool with the reference's 20-bin bar."""
        futures = [self._pool.submit(fn, *args) for fn, args in tasks]
        results = []
        step = len(futures) // 20
        for i, f in enumerate(futures):
            self._poll_cancel()
            results.append(f.result())
            if step != 0 and (i + 1) % step == 0 and (i + 1) // step < 20:
                self.logger.bar(bar_message)
        if step != 0:
            self.logger.bar(bar_message)
        else:
            self.logger.log(done_message)
        return results

    def _notify_overlap_done(self, o: Overlap) -> None:
        """Per-overlap completion hook: fired (possibly from a pool
        thread) once ``o.breaking_points`` exists.  The base pipeline
        does nothing; the TPU polisher's streaming pipeline overrides
        this to advance its per-target/per-window completion ledger
        and route the overlap's window fragments as the align stage
        drains (racon_tpu/tpu/polisher.py)."""

    # ------------------------------------------------------------------
    # windowing (reference: src/polisher.cpp:383-456)
    # ------------------------------------------------------------------

    def _create_windows(self, targets_size: int,
                        window_type: WindowType) -> None:
        """Backbone window skeleton per owned target.  Idempotent: the
        streaming pipeline creates the windows BEFORE the align stage
        (so completed targets can enter POA while later ones are still
        aligning) and the staged path creates them here."""
        if self.windows:
            return
        id_to_first_window_id = [0] * (targets_size + 1)
        for i in range(targets_size):
            if self._owned_targets is not None \
                    and not self._owned_targets[i]:
                # multi-host: another rank emits this target; no
                # windows means polish() skips it entirely
                id_to_first_window_id[i + 1] = id_to_first_window_id[i]
                continue
            data = self.sequences[i].data
            quality = self.sequences[i].quality
            k = 0
            for j in range(0, len(data), self.window_length):
                length = min(j + self.window_length, len(data)) - j
                q = (self.dummy_quality[:length] if not quality
                     else quality[j:j + length])
                self.windows.append(Window(i, k, window_type,
                                           data[j:j + length], q))
                k += 1
            id_to_first_window_id[i + 1] = id_to_first_window_id[i] + k
        self._first_window_id = id_to_first_window_id
        self.targets_coverages = [0] * targets_size

    def _overlap_window_fragments(self, o: Overlap):
        """Yield ``(window_id, data, quality, begin, end)`` for every
        breaking-point pair of ``o`` that passes the length/quality
        filters — the routing rule of the staged ``_build_windows``,
        factored out so the streaming seam can route per overlap as
        alignments complete.  Caller clears ``o.breaking_points``."""
        points = o.breaking_points
        if points is None or len(points) == 0:
            return
        import numpy as np

        w = self.window_length
        sequence = self.sequences[o.q_id]
        # check the stored slot: reverse_quality exists iff transmute
        # materialised it; the property would create it as a side
        # effect (reference getter has none, src/sequence.hpp)
        has_quality = bool(sequence.quality) or \
            bool(sequence._reverse_quality)
        quality_src = (sequence.reverse_quality if o.strand
                       else sequence.quality)
        data_src = (sequence.reverse_complement if o.strand
                    else sequence.data)
        pts = np.asarray(points, dtype=np.int64)
        t_first = pts[0::2, 0]
        q_first = pts[0::2, 1]
        t_last = pts[1::2, 0]
        q_last = pts[1::2, 1]
        keep = (q_last - q_first) >= 0.02 * w
        if has_quality and quality_src:
            idx = np.flatnonzero(keep)
            if idx.size:
                # prefix sums turn each fragment's mean quality into
                # two gathers; int64/int64 true division matches the
                # old Python sum()/len() float exactly (sums < 2^53)
                prefix = np.concatenate(([0], np.cumsum(
                    np.frombuffer(quality_src, np.uint8)
                    .astype(np.int64))))
                total = prefix[q_last[idx]] - prefix[q_first[idx]]
                count = q_last[idx] - q_first[idx]
                keep[idx] = ~((total / count - 33)
                              < self.quality_threshold)
        first_wid = self._first_window_id[o.t_id]
        for j in np.flatnonzero(keep).tolist():
            tf, tl = int(t_first[j]), int(t_last[j])
            qf, ql = int(q_first[j]), int(q_last[j])
            window_start = (tf // w) * w
            yield (first_wid + tf // w, data_src[qf:ql],
                   quality_src[qf:ql] if quality_src else None,
                   tf - window_start, tl - window_start - 1)

    def _build_windows(self, targets_size: int, window_type: WindowType,
                       overlaps: List[Overlap]) -> None:
        self._create_windows(targets_size, window_type)
        with self.metrics.timer("host.fragment_s"):
            for o in overlaps:
                if not self._coverage_counted:
                    self.targets_coverages[o.t_id] += 1
                if o.breaking_points is None or \
                        len(o.breaking_points) == 0:
                    # already routed by the streaming seam (the ROUTED
                    # sentinel) or carried no points at all
                    continue
                for wid, data, quality, begin, end in \
                        self._overlap_window_fragments(o):
                    self.windows[wid].add_layer(data, quality, begin,
                                                end)
                o.breaking_points = None

    # ------------------------------------------------------------------
    # accelerator seam #2 + polish (reference: src/polisher.cpp:485-547)
    # ------------------------------------------------------------------

    def _consensus_cached(self, window, epoch=None):
        """One window's POA consensus through the content-addressed
        result cache (racon_tpu/cache): hit -> adopt the cached
        bytes, miss -> compute and fill.  Returns ``(polished_flag,
        was_hit)``.  Windows below the 3-layer polish threshold
        bypass the cache — the backbone copy is cheaper than a
        lookup.  The "cpu" key space is disjoint from the device
        engine's: the two pipelines resolve cost ties independently,
        so their results must never alias."""
        from racon_tpu import cache as rcache

        if len(window.sequences) < 3 or not rcache.enabled():
            return window.generate_consensus(
                self.engine, self.trim), False
        c = rcache.result_cache()
        if epoch is None:
            epoch = rcache.keying.engine_epoch()
        key = rcache.keying.poa_key(
            "cpu", (self.match, self.mismatch, self.gap), self.trim,
            window, epoch)
        v = c.get(key)
        if v is not rcache.MISS:
            cons, ok = v
            window.consensus = cons
            return bool(ok), True
        ok = window.generate_consensus(self.engine, self.trim)
        c.put(key, (window.consensus, ok))
        return ok, False

    def generate_consensuses(self) -> List[bool]:
        """Generate consensus for every window; returns polished flags."""
        from racon_tpu import cache as rcache

        epoch = rcache.keying.engine_epoch() if rcache.enabled() \
            else None
        return self._run_pooled(
            [(lambda w=w: self._consensus_cached(w, epoch)[0], ())
             for w in self.windows],
            "[racon_tpu::Polisher::polish] generating consensus",
            "[racon_tpu::Polisher::polish] generated consensus")

    def polish(self, drop_unpolished_sequences: bool) -> List[Sequence]:
        self.logger.log()
        with obs_trace.span("racon_tpu.consensus_stage", cat="stage",
                            metric="stage_wall_s.consensus",
                            registry=self.metrics):
            polished_flags = self.generate_consensuses()

        # stitch each target's window run independently and in
        # parallel over the pool (the window list is read-only here);
        # results collect in group order, so output bytes match the
        # old sequential bytearray accumulation exactly
        groups = []
        start = 0
        for i in range(len(self.windows)):
            if i == len(self.windows) - 1 or self.windows[i + 1].rank == 0:
                groups.append((start, i + 1))
                start = i + 1

        def stitch(bounds) -> Optional[Sequence]:
            lo, hi = bounds
            num_polished_windows = sum(
                1 for i in range(lo, hi) if polished_flags[i])
            window = self.windows[hi - 1]
            polished_ratio = num_polished_windows / (window.rank + 1)
            if drop_unpolished_sequences and not polished_ratio > 0:
                return None
            polished_data = b"".join(self.windows[i].consensus
                                     for i in range(lo, hi))
            tags = "r" if self.type == PolisherType.kF else ""
            tags += f" LN:i:{len(polished_data)}"
            tags += f" RC:i:{self.targets_coverages[window.id]}"
            tags += f" XC:f:{polished_ratio:.6f}"
            return Sequence(self.sequences[window.id].name + tags,
                            polished_data)

        with self.metrics.timer("host.stitch_s"):
            if len(groups) > 1 and self.num_threads > 1:
                stitched = list(self._pool.map(stitch, groups))
            else:
                stitched = [stitch(g) for g in groups]
        dst = [s for s in stitched if s is not None]
        self._finish_host_budget()
        self.windows = []
        self.sequences = []
        return dst

    def _finish_host_budget(self) -> None:
        """Derive the run's host-stage budget gauges: total host data
        -plane seconds (CPU-seconds — concurrent stages can sum past
        the wall) and the share of the run wall they represent."""
        host_s = sum(float(self.metrics.value(k, 0.0))
                     for k in ("host.parse_s", "host.map_s",
                               "host.bp_decode_s", "host.fragment_s",
                               "host.stitch_s"))
        self.metrics.set("host.stage_s", round(host_s, 6))
        # calibration health (r16): host stages have no calibrate
        # rate, so drift is measured against the stage's own learned
        # per-unit rate (racon_tpu/obs/calhealth.observe_units) —
        # unit counts are the natural stage denominators
        units = {"host.parse": len(self.sequences),
                 "host.map": int(self.metrics.value("map_queries", 0)),
                 "host.bp_decode": len(self.sequences),
                 "host.fragment": len(self.windows),
                 "host.stitch": self._targets_size}
        for stage, n in units.items():
            wall = float(self.metrics.value(stage + "_s", 0.0))
            if wall > 0:
                obs_calhealth.observe_units(stage, max(1, n), wall,
                                            registry=self.metrics)
        wall = obs_trace.now() - getattr(self, "_t_run_start",
                                         obs_trace.now())
        if wall > 0:
            self.metrics.set("host.share",
                             round(min(1.0, host_s / wall), 6))

    def total_log(self) -> None:
        self.logger.total("[racon_tpu::Polisher::] total =")

    def close(self) -> None:
        """Release per-run resources (the worker pool).  The one-shot
        CLI never needs this (``os._exit`` reaps everything), but a
        long-lived process running many polishes — bench.py, the
        serve daemon — would otherwise leak one thread pool (and
        three parser file handles) per job
        (racon_tpu/serve/session.py calls this per job)."""
        self._pool.shutdown(wait=True)
        for parser in (self.sparser, self.oparser, self.tparser):
            close = getattr(parser, "close", None)
            if close is not None:
                close()
