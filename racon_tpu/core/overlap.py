"""Overlap domain object.

Mirrors racon's Overlap (reference: src/overlap.cpp): three format
constructors (MHAP/PAF/SAM), name/id resolution against the loaded
sequence set (``transmute``), and per-window breaking-point extraction by
walking the alignment CIGAR (``find_breaking_points_from_cigar``,
reference: src/overlap.cpp:226-292).  The CIGAR walk is vectorised with
numpy instead of the reference's per-base loop.

When an overlap record carries no CIGAR (PAF/MHAP), one is produced by a
global alignment of the query span vs the target span -- on the CPU via
the native edlib-equivalent engine, or in bulk on the TPU by the batched
aligner (racon_tpu.tpu.aligner), which pre-fills ``cigar`` exactly like
the reference's CUDABatchAligner (src/cuda/cudaaligner.cpp:89-103).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence as Seq, Tuple

import numpy as np

_CIGAR_RE = re.compile(rb"(\d+)([MIDNSHP=X])")


class InvalidInputError(RuntimeError):
    """Unrecoverable input inconsistency (reference exits(1))."""


class Overlap:
    __slots__ = ("q_name", "q_id", "q_begin", "q_end", "q_length",
                 "t_name", "t_id", "t_begin", "t_end", "t_length",
                 "strand", "length", "error", "cigar", "cigar_runs",
                 "is_valid", "is_transmuted", "breaking_points")

    def __init__(self):
        self.q_name: Optional[str] = None
        self.q_id: int = 0
        self.q_begin = 0
        self.q_end = 0
        self.q_length = 0
        self.t_name: Optional[str] = None
        self.t_id: int = 0
        self.t_begin = 0
        self.t_end = 0
        self.t_length = 0
        self.strand = False
        self.length = 0
        self.error = 0.0
        self.cigar: str = ""
        self.cigar_runs = None     # (lengths, codes) device fast path
        self.is_valid = True
        self.is_transmuted = False
        self.breaking_points: Optional[np.ndarray] = None  # (2k, 2) [t, q]

    # -- format constructors (reference: src/overlap.cpp:15-108) -----------

    @classmethod
    def from_mhap(cls, a_id: int, b_id: int, a_rc: int, a_begin: int,
                  a_end: int, a_length: int, b_rc: int, b_begin: int,
                  b_end: int, b_length: int) -> "Overlap":
        o = cls()
        o.q_id = a_id - 1          # MHAP ids are 1-based
        o.q_begin, o.q_end, o.q_length = a_begin, a_end, a_length
        o.t_id = b_id - 1
        o.t_begin, o.t_end, o.t_length = b_begin, b_end, b_length
        o.strand = bool(a_rc ^ b_rc)
        o._set_span_error()
        return o

    @classmethod
    def from_paf(cls, q_name: str, q_length: int, q_begin: int, q_end: int,
                 orientation: str, t_name: str, t_length: int, t_begin: int,
                 t_end: int) -> "Overlap":
        o = cls()
        o.q_name, o.q_length, o.q_begin, o.q_end = q_name, q_length, q_begin, q_end
        o.t_name, o.t_length, o.t_begin, o.t_end = t_name, t_length, t_begin, t_end
        o.strand = orientation == "-"
        o._set_span_error()
        return o

    @classmethod
    def from_sam(cls, q_name: str, flag: int, t_name: str, t_begin: int,
                 cigar: str) -> "Overlap":
        o = cls()
        o.q_name, o.t_name = q_name, t_name
        o.t_begin = t_begin - 1    # SAM POS is 1-based
        o.strand = bool(flag & 0x10)
        o.is_valid = not (flag & 0x4)
        o.cigar = cigar
        if len(cigar) < 2 and o.is_valid:
            raise InvalidInputError("missing alignment from SAM object")
        ops = _CIGAR_RE.findall(cigar.encode())
        q_aln = t_aln = q_clip = 0
        for num, op in ops:
            n = int(num)
            if op in b"M=X":
                q_aln += n
                t_aln += n
            elif op == b"I":
                q_aln += n
            elif op in b"DN":
                t_aln += n
            elif op in b"SH":
                q_clip += n
        # a leading clip, if any, is the query start offset
        # (reference: src/overlap.cpp:60-69)
        q_begin = 0
        for num, op in ops:
            if op in b"SH":
                q_begin = int(num)
                break
            if op in b"M=XIDNP":
                break
        o.q_begin = q_begin
        o.q_end = q_begin + q_aln
        o.q_length = q_clip + q_aln
        if o.strand:
            o.q_begin, o.q_end = o.q_length - o.q_end, o.q_length - o.q_begin
        o.t_end = o.t_begin + t_aln
        o.length = max(q_aln, t_aln)
        o.error = (1 - min(q_aln, t_aln) / o.length) if o.length else 0.0
        return o

    def _set_span_error(self) -> None:
        q_span = self.q_end - self.q_begin
        t_span = self.t_end - self.t_begin
        self.length = max(q_span, t_span)
        self.error = (1 - min(q_span, t_span) / self.length) if self.length \
            else 0.0

    # -- id resolution (reference: src/overlap.cpp:129-177) -----------------

    def transmute(self, sequences: Seq, name_to_id: Dict[str, int],
                  id_to_id: Dict[int, int]) -> None:
        if not self.is_valid or self.is_transmuted:
            return

        if self.q_name is not None:
            qid = name_to_id.get(self.q_name + "q")
            if qid is None:
                self.is_valid = False
                return
            self.q_id = qid
            self.q_name = None
        else:
            qid = id_to_id.get(self.q_id << 1 | 0)
            if qid is None:
                self.is_valid = False
                return
            self.q_id = qid

        if self.q_length != len(sequences[self.q_id].data):
            raise InvalidInputError(
                "unequal lengths in sequence and overlap file for sequence "
                f"{sequences[self.q_id].name}")

        if self.t_name is not None:
            tid = name_to_id.get(self.t_name + "t")
            if tid is None:
                self.is_valid = False
                return
            self.t_id = tid
            self.t_name = None
        else:
            tid = id_to_id.get(self.t_id << 1 | 1)
            if tid is None:
                self.is_valid = False
                return
            self.t_id = tid

        if self.t_length != 0 and \
                self.t_length != len(sequences[self.t_id].data):
            raise InvalidInputError(
                "unequal lengths in target and overlap file for target "
                f"{sequences[self.t_id].name}")

        # SAM records learn the target length here
        self.t_length = len(sequences[self.t_id].data)
        self.is_transmuted = True

    # -- alignment slices ---------------------------------------------------

    def query_span(self, sequences: Seq) -> bytes:
        """Strand-aware query slice (reference: src/overlap.cpp:193-194)."""
        seq = sequences[self.q_id]
        if not self.strand:
            return seq.data[self.q_begin:self.q_end]
        rc = seq.reverse_complement
        return rc[self.q_length - self.q_end:self.q_length - self.q_begin]

    def target_span(self, sequences: Seq) -> bytes:
        return sequences[self.t_id].data[self.t_begin:self.t_end]

    # -- breaking points ----------------------------------------------------

    def find_breaking_points(self, sequences: Seq, window_length: int,
                             aligner=None) -> None:
        """Produce (target, query) window breaking points.

        ``aligner(q: bytes, t: bytes) -> str`` supplies a CIGAR when the
        record has none (reference uses edlib, src/overlap.cpp:205-224).
        """
        if not self.is_transmuted:
            raise InvalidInputError("overlap is not transmuted")
        if self.breaking_points is not None:
            return
        if not self.cigar and self.cigar_runs is None:
            if aligner is None:
                raise InvalidInputError(
                    "overlap has no CIGAR and no aligner was provided")
            self.cigar = aligner(self.query_span(sequences),
                                 self.target_span(sequences))
        self.find_breaking_points_from_cigar(window_length)
        self.cigar = ""
        self.cigar_runs = None

    def find_breaking_points_from_cigar(self, window_length: int) -> None:
        """Vectorised CIGAR walk (reference: src/overlap.cpp:226-292).

        Emits, for every window of the target the alignment spans, the
        (t, q) coordinates of the first match in the window and one past
        the last match.
        """
        w = window_length
        if self.cigar_runs is not None:
            # fast path: device aligners hand over (lengths, codes)
            # run arrays directly, skipping the CIGAR string round
            # trip (build + regex parse cost ~30 ms per long overlap)
            lengths, codes = self.cigar_runs
            lengths = lengths.astype(np.int64, copy=False)
            codes = codes.astype(np.int64, copy=False)
        else:
            ops = _CIGAR_RE.findall(self.cigar.encode())
            if not ops:
                self.breaking_points = np.empty((0, 2), dtype=np.int64)
                return
            lengths = np.array([int(n) for n, _ in ops],
                               dtype=np.int64)
            codes = np.array([b"MIDNSHP=X".index(op)
                              for _, op in ops], dtype=np.int64)
        if lengths.size == 0:
            self.breaking_points = np.empty((0, 2), dtype=np.int64)
            return
        # advance masks per op: M(0) = X(8) = '='(7) advance both;
        # I(1) query; D(2)/N(3) target; S/H/P consume nothing.
        advances_t = np.isin(codes, (0, 2, 3, 7, 8))
        advances_q = np.isin(codes, (0, 1, 7, 8))
        matches = np.isin(codes, (0, 7, 8))
        keep = advances_t | advances_q
        lengths, advances_t, advances_q, matches = (
            lengths[keep], advances_t[keep], advances_q[keep], matches[keep])
        if lengths.size == 0:
            self.breaking_points = np.empty((0, 2), dtype=np.int64)
            return

        t_adv = np.repeat(advances_t, lengths)
        q_adv = np.repeat(advances_q, lengths)
        is_match = np.repeat(matches, lengths)

        q_start = (self.q_length - self.q_end if self.strand
                   else self.q_begin) - 1
        t_pos = self.t_begin - 1 + np.cumsum(t_adv)
        q_pos = q_start + np.cumsum(q_adv)

        boundary = t_adv & (
            (((t_pos + 1) % w == 0) & (t_pos < self.t_end - 1)) |
            (t_pos == self.t_end - 1))
        n_boundaries = int(boundary.sum())
        if n_boundaries == 0:
            self.breaking_points = np.empty((0, 2), dtype=np.int64)
            return

        seg_id = np.cumsum(boundary) - boundary  # boundary col closes its seg
        m_idx = np.flatnonzero(is_match)
        if m_idx.size == 0:
            self.breaking_points = np.empty((0, 2), dtype=np.int64)
            return
        m_seg = seg_id[m_idx]
        segs = np.arange(n_boundaries)
        lo = np.searchsorted(m_seg, segs, side="left")
        hi = np.searchsorted(m_seg, segs, side="right")
        has_match = lo < hi
        lo, hi = lo[has_match], hi[has_match]
        first_cols = m_idx[lo]
        last_cols = m_idx[hi - 1]

        points = np.empty((2 * first_cols.size, 2), dtype=np.int64)
        points[0::2, 0] = t_pos[first_cols]
        points[0::2, 1] = q_pos[first_cols]
        points[1::2, 0] = t_pos[last_cols] + 1
        points[1::2, 1] = q_pos[last_cols] + 1
        self.breaking_points = points
