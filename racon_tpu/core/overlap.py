"""Overlap domain object.

Mirrors racon's Overlap (reference: src/overlap.cpp): three format
constructors (MHAP/PAF/SAM), name/id resolution against the loaded
sequence set (``transmute``), and per-window breaking-point extraction by
walking the alignment CIGAR (``find_breaking_points_from_cigar``,
reference: src/overlap.cpp:226-292).  The CIGAR walk is vectorised with
numpy instead of the reference's per-base loop.

When an overlap record carries no CIGAR (PAF/MHAP), one is produced by a
global alignment of the query span vs the target span -- on the CPU via
the native edlib-equivalent engine, or in bulk on the TPU by the batched
aligner (racon_tpu.tpu.aligner), which pre-fills ``cigar`` exactly like
the reference's CUDABatchAligner (src/cuda/cudaaligner.cpp:89-103).
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Sequence as Seq, Tuple

import numpy as np

_CIGAR_RE = re.compile(rb"(\d+)([MIDNSHP=X])")

#: codes index into this op alphabet everywhere runs are exchanged
_OPS = b"MIDNSHP=X"

#: routed sentinel: the streaming seam stamps this shared empty
#: breaking-points array on overlaps whose fragments already reached
#: the window ledger, so the staged fall-through pass sees "done"
#: (find_breaking_points early-returns) instead of re-aligning them
ROUTED = np.empty((0, 2), dtype=np.int64)
ROUTED.setflags(write=False)


class InvalidInputError(RuntimeError):
    """Unrecoverable input inconsistency (reference exits(1))."""


class Overlap:
    __slots__ = ("q_name", "q_id", "q_begin", "q_end", "q_length",
                 "t_name", "t_id", "t_begin", "t_end", "t_length",
                 "strand", "length", "error", "cigar", "cigar_runs",
                 "is_valid", "is_transmuted", "breaking_points")

    def __init__(self):
        self.q_name: Optional[str] = None
        self.q_id: int = 0
        self.q_begin = 0
        self.q_end = 0
        self.q_length = 0
        self.t_name: Optional[str] = None
        self.t_id: int = 0
        self.t_begin = 0
        self.t_end = 0
        self.t_length = 0
        self.strand = False
        self.length = 0
        self.error = 0.0
        self.cigar: str = ""
        self.cigar_runs = None     # (lengths, codes) device fast path
        self.is_valid = True
        self.is_transmuted = False
        self.breaking_points: Optional[np.ndarray] = None  # (2k, 2) [t, q]

    # -- format constructors (reference: src/overlap.cpp:15-108) -----------

    @classmethod
    def from_mhap(cls, a_id: int, b_id: int, a_rc: int, a_begin: int,
                  a_end: int, a_length: int, b_rc: int, b_begin: int,
                  b_end: int, b_length: int) -> "Overlap":
        o = cls()
        o.q_id = a_id - 1          # MHAP ids are 1-based
        o.q_begin, o.q_end, o.q_length = a_begin, a_end, a_length
        o.t_id = b_id - 1
        o.t_begin, o.t_end, o.t_length = b_begin, b_end, b_length
        o.strand = bool(a_rc ^ b_rc)
        o._set_span_error()
        return o

    @classmethod
    def from_paf(cls, q_name: str, q_length: int, q_begin: int, q_end: int,
                 orientation: str, t_name: str, t_length: int, t_begin: int,
                 t_end: int) -> "Overlap":
        o = cls()
        o.q_name, o.q_length, o.q_begin, o.q_end = q_name, q_length, q_begin, q_end
        o.t_name, o.t_length, o.t_begin, o.t_end = t_name, t_length, t_begin, t_end
        o.strand = orientation == "-"
        o._set_span_error()
        return o

    @classmethod
    def from_sam(cls, q_name: str, flag: int, t_name: str, t_begin: int,
                 cigar: str) -> "Overlap":
        return cls.from_sam_bytes(q_name, flag, t_name, t_begin,
                                  cigar.encode())

    @classmethod
    def from_sam_bytes(cls, q_name: str, flag: int, t_name: str,
                       t_begin: int, cigar: bytes) -> "Overlap":
        """SAM constructor over the raw CIGAR bytes: parses the ops
        once into ``cigar_runs`` so the breaking-point decode skips
        the string round trip (the line parser used to run the regex
        at ingest AND again at decode time)."""
        is_valid = not (flag & 0x4)
        if len(cigar) < 2 and is_valid:
            raise InvalidInputError("missing alignment from SAM object")
        ops = _CIGAR_RE.findall(cigar)
        n = len(ops)
        lengths = np.fromiter((int(num) for num, _ in ops),
                              dtype=np.int64, count=n)
        codes = np.fromiter((_OPS.index(op) for _, op in ops),
                            dtype=np.int64, count=n)
        o = cls._from_sam_fields(q_name, flag, t_name, t_begin,
                                 *_sam_run_fields(lengths, codes))
        o.cigar_runs = (lengths, codes)
        return o

    @classmethod
    def _from_sam_fields(cls, q_name: str, flag: int, t_name: str,
                         t_begin: int, q_aln: int, t_aln: int,
                         q_clip: int, lead_clip: int) -> "Overlap":
        """Field assembly shared by the per-record and the batched
        (io/fastio.py) SAM constructors; ``lead_clip`` is the query
        start offset (reference: src/overlap.cpp:60-69)."""
        o = cls()
        o.q_name, o.t_name = q_name, t_name
        o.t_begin = t_begin - 1    # SAM POS is 1-based
        o.strand = bool(flag & 0x10)
        o.is_valid = not (flag & 0x4)
        o.q_begin = lead_clip
        o.q_end = lead_clip + q_aln
        o.q_length = q_clip + q_aln
        if o.strand:
            o.q_begin, o.q_end = o.q_length - o.q_end, o.q_length - o.q_begin
        o.t_end = o.t_begin + t_aln
        o.length = max(q_aln, t_aln)
        o.error = (1 - min(q_aln, t_aln) / o.length) if o.length else 0.0
        return o

    def _set_span_error(self) -> None:
        q_span = self.q_end - self.q_begin
        t_span = self.t_end - self.t_begin
        self.length = max(q_span, t_span)
        self.error = (1 - min(q_span, t_span) / self.length) if self.length \
            else 0.0

    # -- id resolution (reference: src/overlap.cpp:129-177) -----------------

    def transmute(self, sequences: Seq, name_to_id: Dict[str, int],
                  id_to_id: Dict[int, int]) -> None:
        if not self.is_valid or self.is_transmuted:
            return

        if self.q_name is not None:
            qid = name_to_id.get(self.q_name + "q")
            if qid is None:
                self.is_valid = False
                return
            self.q_id = qid
            self.q_name = None
        else:
            qid = id_to_id.get(self.q_id << 1 | 0)
            if qid is None:
                self.is_valid = False
                return
            self.q_id = qid

        if self.q_length != len(sequences[self.q_id].data):
            raise InvalidInputError(
                "unequal lengths in sequence and overlap file for sequence "
                f"{sequences[self.q_id].name}")

        if self.t_name is not None:
            tid = name_to_id.get(self.t_name + "t")
            if tid is None:
                self.is_valid = False
                return
            self.t_id = tid
            self.t_name = None
        else:
            tid = id_to_id.get(self.t_id << 1 | 1)
            if tid is None:
                self.is_valid = False
                return
            self.t_id = tid

        if self.t_length != 0 and \
                self.t_length != len(sequences[self.t_id].data):
            raise InvalidInputError(
                "unequal lengths in target and overlap file for target "
                f"{sequences[self.t_id].name}")

        # SAM records learn the target length here
        self.t_length = len(sequences[self.t_id].data)
        self.is_transmuted = True

    # -- alignment slices ---------------------------------------------------

    def query_span(self, sequences: Seq) -> bytes:
        """Strand-aware query slice (reference: src/overlap.cpp:193-194)."""
        seq = sequences[self.q_id]
        if not self.strand:
            return seq.data[self.q_begin:self.q_end]
        rc = seq.reverse_complement
        return rc[self.q_length - self.q_end:self.q_length - self.q_begin]

    def target_span(self, sequences: Seq) -> bytes:
        return sequences[self.t_id].data[self.t_begin:self.t_end]

    # -- breaking points ----------------------------------------------------

    def find_breaking_points(self, sequences: Seq, window_length: int,
                             aligner=None) -> None:
        """Produce (target, query) window breaking points.

        ``aligner(q: bytes, t: bytes) -> str`` supplies a CIGAR when the
        record has none (reference uses edlib, src/overlap.cpp:205-224).
        """
        if not self.is_transmuted:
            raise InvalidInputError("overlap is not transmuted")
        if self.breaking_points is not None:
            return
        if not self.cigar and self.cigar_runs is None:
            if aligner is None:
                raise InvalidInputError(
                    "overlap has no CIGAR and no aligner was provided")
            self.cigar = aligner(self.query_span(sequences),
                                 self.target_span(sequences))
        self.find_breaking_points_from_cigar(window_length)
        self.cigar = ""
        self.cigar_runs = None

    def find_breaking_points_from_cigar(self, window_length: int) -> None:
        """Vectorised CIGAR walk (reference: src/overlap.cpp:226-292).

        Emits, for every window of the target the alignment spans, the
        (t, q) coordinates of the first match in the window and one past
        the last match.
        """
        w = window_length
        if self.cigar_runs is not None:
            # fast path: device aligners hand over (lengths, codes)
            # run arrays directly, skipping the CIGAR string round
            # trip (build + regex parse cost ~30 ms per long overlap)
            lengths, codes = self.cigar_runs
            lengths = lengths.astype(np.int64, copy=False)
            codes = codes.astype(np.int64, copy=False)
        else:
            ops = _CIGAR_RE.findall(self.cigar.encode())
            if not ops:
                self.breaking_points = np.empty((0, 2), dtype=np.int64)
                return
            lengths = np.array([int(n) for n, _ in ops],
                               dtype=np.int64)
            codes = np.array([b"MIDNSHP=X".index(op)
                              for _, op in ops], dtype=np.int64)
        if lengths.size == 0:
            self.breaking_points = np.empty((0, 2), dtype=np.int64)
            return
        # advance masks per op: M(0) = X(8) = '='(7) advance both;
        # I(1) query; D(2)/N(3) target; S/H/P consume nothing.
        advances_t = np.isin(codes, (0, 2, 3, 7, 8))
        advances_q = np.isin(codes, (0, 1, 7, 8))
        matches = np.isin(codes, (0, 7, 8))
        keep = advances_t | advances_q
        lengths, advances_t, advances_q, matches = (
            lengths[keep], advances_t[keep], advances_q[keep], matches[keep])
        if lengths.size == 0:
            self.breaking_points = np.empty((0, 2), dtype=np.int64)
            return

        t_adv = np.repeat(advances_t, lengths)
        q_adv = np.repeat(advances_q, lengths)
        is_match = np.repeat(matches, lengths)

        q_start = (self.q_length - self.q_end if self.strand
                   else self.q_begin) - 1
        t_pos = self.t_begin - 1 + np.cumsum(t_adv)
        q_pos = q_start + np.cumsum(q_adv)

        boundary = t_adv & (
            (((t_pos + 1) % w == 0) & (t_pos < self.t_end - 1)) |
            (t_pos == self.t_end - 1))
        n_boundaries = int(boundary.sum())
        if n_boundaries == 0:
            self.breaking_points = np.empty((0, 2), dtype=np.int64)
            return

        seg_id = np.cumsum(boundary) - boundary  # boundary col closes its seg
        m_idx = np.flatnonzero(is_match)
        if m_idx.size == 0:
            self.breaking_points = np.empty((0, 2), dtype=np.int64)
            return
        m_seg = seg_id[m_idx]
        segs = np.arange(n_boundaries)
        lo = np.searchsorted(m_seg, segs, side="left")
        hi = np.searchsorted(m_seg, segs, side="right")
        has_match = lo < hi
        lo, hi = lo[has_match], hi[has_match]
        first_cols = m_idx[lo]
        last_cols = m_idx[hi - 1]

        points = np.empty((2 * first_cols.size, 2), dtype=np.int64)
        points[0::2, 0] = t_pos[first_cols]
        points[0::2, 1] = q_pos[first_cols]
        points[1::2, 0] = t_pos[last_cols] + 1
        points[1::2, 1] = q_pos[last_cols] + 1
        self.breaking_points = points


# ---------------------------------------------------------------------------
# batched CIGAR-run parsing + breaking-point decode
# ---------------------------------------------------------------------------

def _sam_run_fields(lengths: np.ndarray,
                    codes: np.ndarray) -> Tuple[int, int, int, int]:
    """(q_aln, t_aln, q_clip, lead_clip) aggregates of one run list —
    the numbers ``from_sam``'s per-op loop used to accumulate."""
    q_aln = int(lengths[np.isin(codes, (0, 1, 7, 8))].sum())
    t_aln = int(lengths[np.isin(codes, (0, 2, 3, 7, 8))].sum())
    q_clip = int(lengths[np.isin(codes, (4, 5))].sum())
    lead_clip = int(lengths[0]) if codes.size and codes[0] in (4, 5) else 0
    return q_aln, t_aln, q_clip, lead_clip


def parse_cigar_runs_batch(arr: np.ndarray, starts: np.ndarray,
                           ends: np.ndarray):
    """Parse many CIGAR byte spans of one buffer into per-record
    ``(lengths, codes)`` run arrays in a single vectorized pass.

    Replicates ``_CIGAR_RE.findall`` semantics (a digit run directly
    followed by an op char forms a run; anything else is skipped) via
    a flat concatenated column space: op positions come from one mask,
    each op's number from a right-aligned digit matrix.  Returns
    ``(runs, bad)`` where ``runs[i]`` is record *i*'s (lengths, codes)
    and ``bad[i]`` flags a record the vector path must not answer for
    (a >18-digit run length would overflow the digit matrix; callers
    re-parse those rows with the regex)."""
    n = int(starts.size)
    bad = np.zeros(n, dtype=bool)
    lens = (ends - starts).astype(np.int64)
    total = int(lens.sum())
    empty = (np.empty(0, np.int64), np.empty(0, np.int64))
    if total == 0:
        return [empty] * n, bad
    off = np.concatenate(([0], np.cumsum(lens)))
    pos = np.arange(total, dtype=np.int64) - np.repeat(off[:-1], lens) \
        + np.repeat(starts.astype(np.int64), lens)
    cat = arr[pos].astype(np.int64)
    rec = np.repeat(np.arange(n, dtype=np.int64), lens)
    is_digit = (cat >= 48) & (cat <= 57)
    op_pos = np.flatnonzero(~is_digit)
    lut = np.full(256, -1, dtype=np.int64)
    for k, ch in enumerate(_OPS):
        lut[ch] = k
    op_code = lut[cat[op_pos]]
    op_rec = rec[op_pos]
    prev_op = np.concatenate(([-1], op_pos[:-1]))
    num_start = np.maximum(prev_op + 1, off[op_rec])
    num_len = op_pos - num_start
    valid = (op_code >= 0) & (num_len > 0)
    too_wide = valid & (num_len > 18)
    if too_wide.any():
        bad[np.unique(op_rec[too_wide])] = True
        valid &= ~too_wide
    v_pos = op_pos[valid]
    v_rec = op_rec[valid]
    v_code = op_code[valid]
    v_ns = num_start[valid]
    v_nl = num_len[valid]
    width = int(v_nl.max()) if v_nl.size else 0
    if width:
        cols = v_pos[:, None] - width + np.arange(width, dtype=np.int64)
        in_num = cols >= v_ns[:, None]
        digits = np.where(in_num, cat[np.maximum(cols, 0)] - 48, 0)
        v_num = digits @ (10 ** np.arange(width - 1, -1, -1,
                                          dtype=np.int64))
    else:
        v_num = np.empty(0, np.int64)
    bounds = np.searchsorted(v_rec, np.arange(n + 1))
    runs = [(np.ascontiguousarray(v_num[bounds[i]:bounds[i + 1]]),
             np.ascontiguousarray(v_code[bounds[i]:bounds[i + 1]]))
            for i in range(n)]
    return runs, bad


def iter_decode_slabs(overlaps, col_budget: int = None):
    """Partition run-carrying overlaps into slabs whose total expanded
    (per-base) column count stays under ``col_budget``
    (RACON_TPU_BP_COLS), bounding the batched decode's working set."""
    if col_budget is None:
        try:
            col_budget = int(os.environ.get("RACON_TPU_BP_COLS",
                                            "4000000"))
        except ValueError:
            col_budget = 4_000_000
    col_budget = max(1, col_budget)
    slabs, cur, cols = [], [], 0
    for o in overlaps:
        if o.breaking_points is not None or o.cigar_runs is None:
            continue
        lengths = np.asarray(o.cigar_runs[0])
        c = int(lengths.sum()) if lengths.size else 0
        if cur and cols + c > col_budget:
            slabs.append(cur)
            cur, cols = [], 0
        cur.append(o)
        cols += c
    if cur:
        slabs.append(cur)
    return slabs


#: expanded-column count past which one overlap decodes faster alone
_BP_SINGLE_MIN_COLS = 4096


def decode_breaking_points_batch(overlaps, window_length: int,
                                 col_budget: int = None) -> None:
    """Breaking-point decode for a batch of run-carrying overlaps in
    a few vectorized passes instead of one numpy walk per overlap.

    Packs every overlap's kept runs into one flat column space, runs
    the cumsum/boundary/searchsorted walk of
    ``find_breaking_points_from_cigar`` once per slab, and scatters
    the per-overlap (2k, 2) point arrays back — the points are
    element-identical to the single-overlap decode
    (tests/test_fastio.py pins the equality).  Overlaps without runs
    or with points already present are left untouched.

    Batching pays when the per-overlap fixed numpy cost dominates
    (measured 3.5x on short expanded spans); past a few thousand
    expanded columns that cost is amortized and the slab's extra
    per-column bookkeeping (overlap ids, segment rebasing) makes the
    single walk cheaper — such overlaps route to it directly."""
    small = []
    for o in overlaps:
        if o.breaking_points is not None or o.cigar_runs is None:
            continue
        if int(np.asarray(o.cigar_runs[0]).sum()) \
                >= _BP_SINGLE_MIN_COLS:
            o.find_breaking_points_from_cigar(window_length)
            o.cigar = ""
            o.cigar_runs = None
        else:
            small.append(o)
    for slab in iter_decode_slabs(small, col_budget):
        _decode_bp_slab(slab, window_length)


# op-code advance masks as lookup tables over the 0..8 code space
# (M I D N S H P = X) — one gather instead of an np.isin scan per mask
_ADV_T_LUT = np.array([1, 0, 1, 1, 0, 0, 0, 1, 1], dtype=bool)
_ADV_Q_LUT = np.array([1, 1, 0, 0, 0, 0, 0, 1, 1], dtype=bool)
_MATCH_LUT = np.array([1, 0, 0, 0, 0, 0, 0, 1, 1], dtype=bool)


def _decode_bp_slab(overlaps, window_length: int) -> None:
    w = window_length
    n_all = len(overlaps)
    if n_all == 0:
        return
    # one flat run space for the whole slab: per-overlap Python work
    # is limited to attribute gathers (the pre-r7 version ran three
    # np.isin scans + four fancy indexes PER OVERLAP, which cost more
    # than the single-overlap decode it replaced)
    runs = [(np.asarray(o.cigar_runs[0]).astype(np.int64, copy=False),
             np.asarray(o.cigar_runs[1]).astype(np.int64, copy=False))
            for o in overlaps]
    run_counts = np.fromiter((r[0].size for r in runs), np.int64, n_all)
    all_l = np.concatenate([r[0] for r in runs]) \
        if int(run_counts.sum()) else np.empty(0, np.int64)
    all_c = np.concatenate([r[1] for r in runs]) \
        if all_l.size else np.empty(0, np.int64)
    at_all = _ADV_T_LUT[all_c]
    aq_all = _ADV_Q_LUT[all_c]
    keep_all = at_all | aq_all
    run_ovl = np.repeat(np.arange(n_all, dtype=np.int64), run_counts)
    # expanded column count per overlap (weighted bincount is exact:
    # run lengths are far below 2^53)
    col_all = np.bincount(run_ovl[keep_all],
                          weights=all_l[keep_all].astype(np.float64),
                          minlength=n_all).astype(np.int64)
    live = col_all > 0
    for i in np.flatnonzero(~live):
        o = overlaps[i]
        o.breaking_points = np.empty((0, 2), dtype=np.int64)
        o.cigar = ""
        o.cigar_runs = None
    if not live.any():
        return
    todo = [overlaps[i] for i in np.flatnonzero(live)]
    n = len(todo)
    # compact the run space to live overlaps' kept runs
    kept = keep_all & live[run_ovl]
    runs_l = all_l[kept]
    kept_at = at_all[kept]
    kept_aq = aq_all[kept]
    kept_m = _MATCH_LUT[all_c[kept]]
    col_counts = col_all[live]
    col_off = np.concatenate(([0], np.cumsum(col_counts)))
    t_adv = np.repeat(kept_at, runs_l)
    q_adv = np.repeat(kept_aq, runs_l)
    is_match = np.repeat(kept_m, runs_l)
    # per-overlap positions: one global cumsum, re-based per overlap
    cs_t = np.cumsum(t_adv)
    cs_q = np.cumsum(q_adv)
    last = col_off[1:-1] - 1
    base_t = np.concatenate(([0], cs_t[last]))
    base_q = np.concatenate(([0], cs_q[last]))
    t_begin = np.fromiter((o.t_begin for o in todo), np.int64, n)
    t_end = np.fromiter((o.t_end for o in todo), np.int64, n)
    q_start = np.fromiter(
        (((o.q_length - o.q_end) if o.strand else o.q_begin)
         for o in todo), np.int64, n)
    t_pos = np.repeat(t_begin - 1 - base_t, col_counts) + cs_t
    q_pos = np.repeat(q_start - 1 - base_q, col_counts) + cs_q
    t_end_cols = np.repeat(t_end, col_counts)
    boundary = t_adv & (
        (((t_pos + 1) % w == 0) & (t_pos < t_end_cols - 1)) |
        (t_pos == t_end_cols - 1))
    cum_b = np.cumsum(boundary)
    b_ends = cum_b[col_off[1:] - 1]
    b_base = np.concatenate(([0], b_ends[:-1]))
    n_bounds = b_ends - b_base   # boundaries (= segments) per overlap
    col_ovl = np.repeat(np.arange(n, dtype=np.int64), col_counts)
    # local segment id; a boundary column closes its own segment
    loc_seg = cum_b - boundary - np.repeat(b_base, col_counts)
    m_idx = np.flatnonzero(is_match)
    m_ovl = col_ovl[m_idx]
    m_loc = loc_seg[m_idx]
    # trailing match columns past an overlap's last boundary carry no
    # segment (the single-overlap walk's searchsorted never selects
    # them); dropping them here keeps them out of the NEXT overlap's
    # first segment in the flat key space
    in_seg = m_loc < n_bounds[m_ovl]
    m_idx, m_ovl, m_loc = m_idx[in_seg], m_ovl[in_seg], m_loc[in_seg]
    seg_off = np.concatenate(([0], np.cumsum(n_bounds)))
    total_segs = int(seg_off[-1])
    if m_idx.size and total_segs:
        keys = seg_off[m_ovl] + m_loc   # nondecreasing
        seg_ids = np.arange(total_segs, dtype=np.int64)
        lo = np.searchsorted(keys, seg_ids, side="left")
        hi = np.searchsorted(keys, seg_ids, side="right")
        has = lo < hi
        first_cols = m_idx[lo[has]]
        last_cols = m_idx[hi[has] - 1]
        t_first = t_pos[first_cols]
        q_first = q_pos[first_cols]
        t_last = t_pos[last_cols] + 1
        q_last = q_pos[last_cols] + 1
        seg_ovl = np.repeat(np.arange(n, dtype=np.int64), n_bounds)
        counts = np.bincount(seg_ovl[has], minlength=n)
    else:
        counts = np.zeros(n, np.int64)
        t_first = q_first = t_last = q_last = np.empty(0, np.int64)
    # one interleaved (2*total, 2) buffer; segments are grouped by
    # overlap, so the global even/odd interleave IS the concatenation
    # of the per-overlap interleaves — each overlap gets a view
    all_pts = np.empty((2 * int(counts.sum()), 2), dtype=np.int64)
    all_pts[0::2, 0] = t_first
    all_pts[0::2, 1] = q_first
    all_pts[1::2, 0] = t_last
    all_pts[1::2, 1] = q_last
    out_off = np.concatenate(([0], np.cumsum(counts))).tolist()
    for i, o in enumerate(todo):
        o.breaking_points = all_pts[2 * out_off[i]:2 * out_off[i + 1]]
        o.cigar = ""
        o.cigar_runs = None
