"""Sequence domain object.

Mirrors the behaviour of racon's Sequence (reference: src/sequence.cpp):
uppercase on parse, qualities dropped when they are all-'!' (sum zero),
lazy reverse complement with reversed quality, and ``transmute`` to free
unused storage.  Data is held as immutable ``bytes``; window layers slice
it zero-copy via memoryview.
"""

from __future__ import annotations

from typing import Optional

_COMPLEMENT = bytes.maketrans(b"ACGTacgt", b"TGCAtgca")


class Sequence:
    __slots__ = ("name", "data", "quality", "_reverse_complement",
                 "_reverse_quality")

    def __init__(self, name: str, data: bytes, quality: bytes = b""):
        self.name = name
        self.data = data
        self.quality = quality
        self._reverse_complement: Optional[bytes] = None
        self._reverse_quality: Optional[bytes] = None

    # -- constructors matching the bioparser-injected ctors ----------------

    @classmethod
    def from_fasta(cls, header: bytes, data: bytes) -> "Sequence":
        name = header.split()[0].decode() if header.split() else ""
        return cls(name, data.upper())

    @classmethod
    def from_fastq(cls, header: bytes, data: bytes,
                   quality: bytes) -> "Sequence":
        name = header.split()[0].decode() if header.split() else ""
        # qualities that are all '!' carry no information and are dropped
        # (reference: src/sequence.cpp:34-41)
        if quality.count(b"!") == len(quality):
            quality = b""
        return cls(name, data.upper(), quality)

    # -- lazy reverse complement ------------------------------------------

    @property
    def reverse_complement(self) -> bytes:
        if self._reverse_complement is None:
            self.create_reverse_complement()
        return self._reverse_complement

    @property
    def reverse_quality(self) -> bytes:
        if self._reverse_quality is None:
            self.create_reverse_complement()
        return self._reverse_quality

    def create_reverse_complement(self) -> None:
        if self._reverse_complement is not None:
            return
        self._reverse_complement = self.data.translate(_COMPLEMENT)[::-1]
        self._reverse_quality = self.quality[::-1]

    def transmute(self, has_name: bool, has_data: bool,
                  has_reverse_data: bool) -> None:
        """Free unused storage (reference: src/sequence.cpp:86-100)."""
        if not has_name:
            self.name = ""
        if has_reverse_data:
            self.create_reverse_complement()
        if not has_data:
            self.data = b""
            self.quality = b""

    def __len__(self) -> int:
        return len(self.data)

    def __repr__(self) -> str:
        return f"Sequence({self.name!r}, len={len(self.data)})"
