"""Command-line interface (reference: src/main.cpp).

Same contract as racon: three positional inputs (sequences, overlaps,
target sequences), polished FASTA on stdout, and the same option set with
the CUDA flags mirrored as TPU flags:

  racon:  -c/--cudapoa-batches, -b/--cuda-banded-alignment,
          --cudaaligner-batches     (src/main.cpp:35-38)
  here:   -c/--tpupoa-batches,  -b/--tpu-banded-alignment,
          --tpualigner-batches

``-c`` keeps racon's optional-argument behaviour (bare -c means 1,
src/main.cpp:111-123).  ``-q -1`` disables the quality filter (any
negative threshold always passes).
"""

from __future__ import annotations

import os
import sys

from racon_tpu import __version__
from racon_tpu.core.overlap import InvalidInputError
from racon_tpu.core.polisher import PolisherType, create_polisher
from racon_tpu.io.parsers import (MalformedInputError,
                                  UnsupportedFormatError)

USAGE = """usage: racon-tpu [options ...] <sequences> <overlaps> <target sequences>
       racon-tpu [run] [options ...] [--rounds N] <sequences> <target sequences>
       racon-tpu serve --socket PATH [options ...]
       racon-tpu route --socket PATH --backends S1,S2,.. [--tcp HOST:PORT]
       racon-tpu submit --socket PATH [options ...] <sequences> <overlaps> <target sequences>
       racon-tpu submit --socket PATH [options ...] [--rounds N] <sequences> <target sequences>
       racon-tpu status --socket PATH [--json]
       racon-tpu top (--socket PATH | --fleet S1,S2,..) [--interval S] [--once] [--json]
       racon-tpu metrics (--socket PATH | --fleet S1,S2,..) [--json|--prometheus]
       racon-tpu inspect (--socket PATH | --dump FILE | --fleet ADDR --job-key K) [--job N] [--trace-out FILE] [--json]
       racon-tpu explain (--socket PATH | --metrics-json FILE) [--job N] [--json]

    subcommands (racon_tpu/serve — persistent polishing service):
        serve    start the warm-kernel job daemon on a unix socket
        route    start a fault-tolerant router fronting several
                 serve daemons: health-probed placement, spillover
                 on backpressure, per-backend circuit breakers, and
                 exactly-once crash failover (idempotent job keys +
                 journal dedup); --tcp adds a host-crossing TCP
                 listener with the same framed protocol
        submit   run one polish through a daemon (same options and
                 stdout contract as the one-shot form; --trace FILE
                 saves the job's server-side trace slice;
                 --trace-context ID propagates a caller trace id
                 into the daemon's spans and flight events;
                 --job-key KEY makes the submit idempotent — a
                 duplicate key joins the live job or is answered
                 from the daemon's write-ahead journal record;
                 --retry N retries retryable failures — queue_full,
                 draining, daemon restarting — with jittered
                 exponential backoff)
        status   print a daemon's queue/registry/provenance snapshot
                 (--json for the raw document)
        top      live telemetry view over the daemon's watch stream;
                 --fleet polls many daemons and renders per-daemon
                 rows + the exactly-merged fleet SLO table
                 (--once --json for one machine-readable frame)
        metrics  one-shot telemetry scrape of one daemon or a fleet,
                 as JSON or Prometheus text (fleet samples carry
                 instance="<daemon_id>" labels)
        inspect  render a job's timeline (queue wait, exec, fused
                 dispatches with occupancy) from a live daemon's
                 flight recorder or a post-mortem flight dump;
                 --fleet --job-key K assembles one job's fleet-wide
                 lineage (scatter/rebalance/failover/dedup/gather)
                 with clock-aligned per-daemon lanes and an optional
                 merged Perfetto trace (--trace-out)
        explain  render the decision plane: a job's cost waterfall
                 (stage walls, decision counts) and the per-stage
                 predicted-vs-actual calibration-health table, from
                 a live daemon or a --metrics-json run report


    #default output is stdout
    <sequences>
        input file in FASTA/FASTQ format (can be compressed with gzip)
        containing sequences used for correction
    <overlaps>
        input file in MHAP/PAF/SAM format (can be compressed with gzip)
        containing overlaps between sequences and target sequences;
        OMIT this input (two positionals) to discover overlaps with
        the built-in minimap-lite mapper (racon_tpu/overlap) — no
        minimap2 required
    <target sequences>
        input file in FASTA/FASTQ format (can be compressed with gzip)
        containing sequences which will be corrected

    options:
        -u, --include-unpolished
            output unpolished target sequences
        -f, --fragment-correction
            perform fragment correction instead of contig polishing
            (overlaps file should contain dual/self overlaps!)
        -w, --window-length <int>
            default: 500
            size of window on which POA is performed
        -q, --quality-threshold <float>
            default: 10.0
            threshold for average base quality of windows used in POA
        -e, --error-threshold <float>
            default: 0.3
            maximum allowed error rate used for filtering overlaps
        --no-trimming
            disables consensus trimming at window ends
        -m, --match <int>
            default: 3
            score for matching bases
        -x, --mismatch <int>
            default: -5
            score for mismatching bases
        -g, --gap <int>
            default: -4
            gap penalty (must be negative)
        -t, --threads <int>
            default: 1
            number of threads
        --version
            prints the version number
        -h, --help
            prints the usage
        -c, --tpupoa-batches <int>
            default: 0
            number of batches for TPU accelerated polishing
        -b, --tpu-banded-alignment
            use banding approximation for alignment on TPU
        --tpualigner-batches <int>
            default: 0
            number of batches for TPU accelerated alignment
        --trace <file>
            write a Chrome trace-event JSON of the run (loadable in
            Perfetto / chrome://tracing); RACON_TPU_TRACE equivalent
        --metrics-json <file>
            write the run report (metrics registry + environment
            provenance); RACON_TPU_METRICS_JSON equivalent
        --rounds <int>
            default: 1
            number of polishing rounds: after each round the reads
            are re-mapped against the polished draft and it is
            polished again (rounds past the first always use the
            internal mapper — any supplied overlaps file describes
            the ORIGINAL draft only)
"""


def parse_args(argv):
    """getopt-style parse preserving racon's -c optional-arg quirk."""
    opts = {
        "window_length": 500, "quality_threshold": 10.0,
        "error_threshold": 0.3, "trim": True, "match": 3, "mismatch": -5,
        "gap": -4, "threads": 1, "type": PolisherType.kC,
        "drop_unpolished": True, "tpu_poa_batches": 0,
        "tpu_banded_alignment": False, "tpu_aligner_batches": 0,
        "rounds": 1,
        # observability (racon_tpu/obs): env defaults keep library
        # and CLI runs on one switch
        "trace": os.environ.get("RACON_TPU_TRACE") or None,
        "metrics_json": os.environ.get("RACON_TPU_METRICS_JSON")
        or None,
    }
    positionals = []
    i = 0
    n = len(argv)

    def take_value(flag):
        nonlocal i
        i += 1
        if i >= n:
            print(f"[racon_tpu::] error: missing argument for {flag}!",
                  file=sys.stderr)
            raise SystemExit(1)
        return argv[i]

    while i < n:
        a = argv[i]
        if a in ("-u", "--include-unpolished"):
            opts["drop_unpolished"] = False
        elif a in ("-f", "--fragment-correction"):
            opts["type"] = PolisherType.kF
        elif a in ("-w", "--window-length"):
            opts["window_length"] = int(take_value(a))
        elif a.startswith("--window-length="):
            opts["window_length"] = int(a.split("=", 1)[1])
        elif a in ("-q", "--quality-threshold"):
            opts["quality_threshold"] = float(take_value(a))
        elif a.startswith("--quality-threshold="):
            opts["quality_threshold"] = float(a.split("=", 1)[1])
        elif a in ("-e", "--error-threshold"):
            opts["error_threshold"] = float(take_value(a))
        elif a.startswith("--error-threshold="):
            opts["error_threshold"] = float(a.split("=", 1)[1])
        elif a in ("-T", "--no-trimming"):
            opts["trim"] = False
        elif a in ("-m", "--match"):
            opts["match"] = int(take_value(a))
        elif a in ("-x", "--mismatch"):
            opts["mismatch"] = int(take_value(a))
        elif a in ("-g", "--gap"):
            opts["gap"] = int(take_value(a))
        elif a in ("-t", "--threads"):
            opts["threads"] = int(take_value(a))
        elif a in ("-c", "--tpupoa-batches", "--cudapoa-batches"):
            # optional argument: bare -c means 1 (src/main.cpp:111-123)
            opts["tpu_poa_batches"] = 1
            if i + 1 < n and argv[i + 1] and not argv[i + 1].startswith("-"):
                i += 1
                opts["tpu_poa_batches"] = int(argv[i])
        elif a.startswith("--tpupoa-batches="):
            opts["tpu_poa_batches"] = int(a.split("=", 1)[1])
        elif a in ("-b", "--tpu-banded-alignment", "--cuda-banded-alignment"):
            opts["tpu_banded_alignment"] = True
        elif a in ("--tpualigner-batches", "--cudaaligner-batches"):
            opts["tpu_aligner_batches"] = int(take_value(a))
        elif a.startswith("--tpualigner-batches="):
            opts["tpu_aligner_batches"] = int(a.split("=", 1)[1])
        elif a == "--rounds":
            opts["rounds"] = int(take_value(a))
        elif a.startswith("--rounds="):
            opts["rounds"] = int(a.split("=", 1)[1])
        elif a == "--trace":
            opts["trace"] = take_value(a)
        elif a.startswith("--trace="):
            opts["trace"] = a.split("=", 1)[1]
        elif a == "--metrics-json":
            opts["metrics_json"] = take_value(a)
        elif a.startswith("--metrics-json="):
            opts["metrics_json"] = a.split("=", 1)[1]
        elif a == "--version":
            print(__version__)
            raise SystemExit(0)
        elif a in ("-h", "--help"):
            print(USAGE, end="")
            raise SystemExit(0)
        elif a.startswith("-") and a != "-":
            print(f"[racon_tpu::] error: unknown option {a}!",
                  file=sys.stderr)
            raise SystemExit(1)
        else:
            positionals.append(a)
        i += 1

    return opts, positionals


def _log_run_summary(polisher, opts) -> None:
    """One-line end-of-run health summary at default verbosity: the
    speculative-pipeline counters (adopted vs wasted speculation, the
    ledger's ready-queue high-water mark) used to be visible only
    inside bench runs; a production polish should say whether its
    speculation paid off without re-running under bench.py."""
    m = getattr(polisher, "metrics", None)
    if m is None:
        return
    if opts["tpu_poa_batches"] > 0:
        print("[racon_tpu::] pipeline summary: "
              f"spec used {int(m.value('poa_spec_used'))}"
              f"/wasted {int(m.value('poa_spec_wasted'))} window(s), "
              "ledger ready peak "
              f"{int(m.value('ledger_ready_high_water'))}, "
              f"overlap {float(m.value('pipeline_overlap_s')):.2f} s, "
              f"device poa {float(m.value('poa_device_s')):.2f} s / "
              f"align {float(m.value('align_device_s')):.2f} s",
              file=sys.stderr)
    # host data-plane budget (r7): where host CPU-seconds went and
    # their share of the run wall, so "is the host the wall" is
    # answerable from a production run's stderr (CPU-only runs too)
    print("[racon_tpu::] host budget: "
          f"parse {float(m.value('host.parse_s')):.2f} s, "
          f"map {float(m.value('host.map_s')):.2f} s, "
          f"bp decode {float(m.value('host.bp_decode_s')):.2f} s, "
          f"fragment {float(m.value('host.fragment_s')):.2f} s, "
          f"stitch {float(m.value('host.stitch_s')):.2f} s, "
          f"host share {float(m.value('host.share')):.3f}",
          file=sys.stderr)


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    # serving subcommands dispatch before option parsing: they own
    # their own argv shape (and the serve daemon must NOT inherit
    # the one-shot assumptions below — racing prewarm thread,
    # os._exit — it prewarms once, synchronously, and exits only
    # after a graceful drain)
    if argv and argv[0] == "serve":
        from racon_tpu.serve import server as serve_server
        raise SystemExit(serve_server.main(argv[1:]))
    if argv and argv[0] == "route":
        from racon_tpu.serve import router as serve_router
        raise SystemExit(serve_router.main(argv[1:]))
    if argv and argv[0] == "submit":
        from racon_tpu.serve import client as serve_client
        raise SystemExit(serve_client.main_submit(argv[1:]))
    if argv and argv[0] == "status":
        from racon_tpu.serve import client as serve_client
        raise SystemExit(serve_client.main_status(argv[1:]))
    if argv and argv[0] == "top":
        from racon_tpu.serve import top as serve_top
        raise SystemExit(serve_top.main(argv[1:]))
    if argv and argv[0] == "metrics":
        from racon_tpu.serve import fleet as serve_fleet
        raise SystemExit(serve_fleet.main_metrics(argv[1:]))
    if argv and argv[0] == "inspect":
        from racon_tpu.serve import inspect as serve_inspect
        raise SystemExit(serve_inspect.main(argv[1:]))
    if argv and argv[0] == "explain":
        from racon_tpu.serve import explain as serve_explain
        raise SystemExit(serve_explain.main(argv[1:]))
    if argv and argv[0] == "run":
        # explicit alias for the one-shot form (reads -> assembly
        # without a PAF reads best as `racon-tpu run reads draft`)
        argv = argv[1:]
    try:
        opts, inputs = parse_args(argv)
    except ValueError as exc:
        print(f"[racon_tpu::] error: invalid option value ({exc})!",
              file=sys.stderr)
        raise SystemExit(1)

    if len(inputs) == 2:
        # two positionals = reads + draft: internal overlap discovery
        inputs = [inputs[0], None, inputs[1]]
    elif len(inputs) < 3:
        print("[racon_tpu::] error: missing input file(s)!", file=sys.stderr)
        print(USAGE, end="", file=sys.stderr)
        raise SystemExit(1)

    from racon_tpu import obs
    from racon_tpu.obs import flight as obs_flight
    if opts["trace"]:
        # exported to the environment too, so every module (and the
        # prewarm threads spawned below) sees one switch
        obs.enable_trace(opts["trace"])
    # one-shot flight recording: only persisted when an explicit dump
    # path is configured (a default-on dump would litter TMPDIR on
    # every CLI run); the crash hook still dumps on an unhandled
    # exception so a dying run leaves its record
    flight_dump = os.environ.get("RACON_TPU_FLIGHT_DUMP")
    if flight_dump and obs_flight.enabled():
        obs_flight.FLIGHT.install_dump_on_crash(flight_dump)
    obs_flight.FLIGHT.record(
        "run", inputs=[os.path.basename(p) for p in inputs[:3]
                       if p is not None],
        rounds=opts["rounds"], threads=opts["threads"])

    if opts["tpu_poa_batches"] > 0 or opts["tpu_aligner_batches"] > 0:
        # kick off the AOT-shelf prewarm NOW, before the (multi-second)
        # input parse below: the jax import and the shelved kernel
        # loads run behind the parse instead of after it
        # (racon_tpu/tpu/polisher.py spawn_cli_prewarm)
        try:
            from racon_tpu.tpu.polisher import spawn_cli_prewarm
            spawn_cli_prewarm(opts["match"], opts["mismatch"],
                              opts["gap"], opts["trim"])
        except ImportError:
            pass   # TPU support missing: create_polisher reports it

    try:
        with obs.span("racon_tpu.run", cat="stage"):
            from racon_tpu.overlap import rounds as overlap_rounds
            polished, polisher = overlap_rounds.polish_rounds(
                inputs[0], inputs[1], inputs[2], opts["type"],
                opts["window_length"], opts["quality_threshold"],
                opts["error_threshold"], opts["trim"], opts["match"],
                opts["mismatch"], opts["gap"], opts["threads"],
                rounds=opts["rounds"],
                drop_unpolished=opts["drop_unpolished"],
                tpu_poa_batches=opts["tpu_poa_batches"],
                tpu_banded_alignment=opts["tpu_banded_alignment"],
                tpu_aligner_batches=opts["tpu_aligner_batches"])
        polisher.total_log()
        _log_run_summary(polisher, opts)
    except (InvalidInputError, UnsupportedFormatError,
            MalformedInputError, FileNotFoundError) as exc:
        print(f"[racon_tpu::] error: {exc}", file=sys.stderr)
        raise SystemExit(1)

    out = sys.stdout.buffer
    # one write per record batch instead of 4 syscall-sized pieces per
    # record: serialization is part of the host wall on the mega leg
    out.write(b"".join(b">" + seq.name.encode() + b"\n" + seq.data
                       + b"\n" for seq in polished))
    # flush the TEXT layer before the buffer layer: anything printed
    # via print()/sys.stdout sits in the text wrapper, and os._exit
    # skips the interpreter teardown that would normally drain it --
    # without this a redirected stdout could lose those bytes
    # (ADVICE r5)
    sys.stdout.flush()
    out.flush()
    # run report + trace: written AFTER the polished bytes are safely
    # flushed (the stdout contract comes first) and BEFORE the hard
    # exit below would discard them
    if opts["metrics_json"]:
        from racon_tpu.obs import provenance
        provenance.write_metrics_json(
            opts["metrics_json"], run_registry=polisher.metrics,
            details={
                "rounds": getattr(polisher, "rounds_report", []),
                "stage_walls": {
                    k: round(v, 6) for k, v in
                    getattr(polisher, "stage_walls", {}).items()},
                "poa_split_detail": getattr(polisher,
                                            "poa_split_detail", {}),
                "align_retry_counts": {
                    str(k): v for k, v in
                    getattr(polisher, "align_retry_counts",
                            {}).items()},
                "poa_reject_counts": {
                    str(k): v for k, v in
                    getattr(polisher, "poa_reject_counts",
                            {}).items()},
            })
        print(f"[racon_tpu::] metrics report written to "
              f"{opts['metrics_json']}", file=sys.stderr)
    if obs.TRACER.enabled and obs.TRACER.out_path():
        path = obs.write_trace()
        print(f"[racon_tpu::] trace written to {path} "
              "(open in Perfetto / chrome://tracing)",
              file=sys.stderr)
    # the flight ring must be persisted HERE, before the hard exit
    # below skips interpreter teardown (same bug class as the stdout
    # text-layer flush above): an os._exit would otherwise discard
    # the buffered events with no dump written
    if flight_dump and obs_flight.enabled():
        obs_flight.FLIGHT.record("run_done",
                                 n_sequences=len(polished))
        path = obs_flight.FLIGHT.dump(flight_dump, reason="run_done")
        print(f"[racon_tpu::] flight dump written to {path}",
              file=sys.stderr)
    # hard-exit once the output is flushed: background prewarm
    # compiles may still be in flight, and waiting for them (or
    # letting interpreter teardown abort them mid-C++-call) serves no
    # one -- the binary's contract is the bytes on stdout.  The
    # atexit join of the prewarm threads (tpu/polisher.py
    # join_prewarm_threads) therefore never runs on THIS path; it
    # exists for library/embedded callers that import racon_tpu and
    # let the interpreter exit normally
    sys.stderr.flush()
    os._exit(0)


if __name__ == "__main__":
    sys.exit(main())
