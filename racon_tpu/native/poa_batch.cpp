// Lockstep batch API over PoaGraph for the TPU POA path.
//
// The TPU consensus stage (racon_tpu/tpu/poa.py) advances a batch of
// windows one layer per round: the device runs one batched
// NW-against-graph DP + traceback for every window's d-th layer at
// once, while the graphs themselves live here on the host — this file
// provides the per-round export of each window's current (sub)graph as
// fixed-shape arrays for the device kernel, and the application of the
// returned alignment paths (spoa add_alignment semantics).  This is the
// TPU-native replacement for what racon-gpu gets from cudapoa's
// device-resident graphs (reference: src/cuda/cudabatch.cpp:71-265);
// the rejection/overflow statuses mirror cudabatch.cpp:124-155.
//
// All functions are safe to call concurrently for DIFFERENT window
// indices (each window owns an independent graph); calls release the
// GIL on the Python side.

#include "poa_graph.hpp"

#include <cstring>
#include <memory>
#include <numeric>
#include <vector>

using namespace racon_native;

namespace {

struct WindowState {
    PoaGraph graph;
    int32_t backbone_len = 0;
    int32_t n_seqs = 0;            // sequences incorporated (incl backbone)
    // scratch reused across rounds (per window -> per thread safe)
    std::vector<uint8_t> subset;
    std::vector<int32_t> weights;
};

struct Batch {
    std::vector<WindowState> windows;
};

}  // namespace

extern "C" {

void* rt_poab_create(int32_t n_windows) {
    auto* b = new Batch();
    b->windows.resize(n_windows);
    return b;
}

void rt_poab_destroy(void* h) {
    delete static_cast<Batch*>(h);
}

// Seed window w's graph with its backbone (layer 0).
void rt_poab_seed(void* h, int32_t w, const char* backbone, int32_t blen,
                  const char* qual, uint8_t has_qual) {
    WindowState& ws = static_cast<Batch*>(h)->windows[w];
    ws.backbone_len = blen;
    ws.graph.nodes.reserve(blen * 2);
    make_weights(qual, has_qual, blen, ws.weights);
    ws.graph.add_alignment(AlignmentPath(), backbone, blen,
                           ws.weights.data(), 0);
    ws.n_seqs = 1;
}

// Export the subgraph for aligning a layer spanning [begin, end]
// backbone anchors (full_span: whole graph, reference
// src/window.cpp:87-103).  Writes, in topological rank order:
//   bases[vcap]          node base (uint8)
//   preds[vcap * pcap]   predecessor DP-row indices (rank+1; 0 = the
//                        virtual start row; -1 pad)
//   sinks[vcap]          1 if the node has no successor in the subset
//   rank2node[vcap]      node id per rank (for path translation)
// Returns n_rows, or -1 if the subset exceeds vcap (window must fall
// back to the CPU path), -2 if a node's in-degree exceeds pcap, or -3
// if an in-edge reaches back more than kcap ranks (the device DP keeps
// only a kcap-row ring buffer of score rows).
int32_t rt_poab_export(void* h, int32_t w, int32_t begin, int32_t end,
                       int32_t full_span, int32_t vcap, int32_t pcap,
                       int32_t kcap, uint8_t* bases, int16_t* preds,
                       uint8_t* sinks, int32_t* rank2node) {
    WindowState& ws = static_cast<Batch*>(h)->windows[w];
    const PoaGraph& g = ws.graph;
    const size_t n = g.nodes.size();

    ws.subset.assign(n, 0);
    if (full_span) {
        std::fill(ws.subset.begin(), ws.subset.end(), 1);
    } else {
        for (size_t v = 0; v < n; ++v) {
            int32_t a = g.nodes[v].anchor;
            ws.subset[v] = (a >= begin && a <= end) ? 1 : 0;
        }
    }

    std::vector<int32_t> order = g.topo_order(ws.subset);
    const int32_t rows = static_cast<int32_t>(order.size());
    // preds stores rank+1 as int16: reject rows beyond its range even
    // when the caller's vcap is larger (user-settable -w can push
    // vcap past 32767), so the cast below can never overflow
    if (rows > vcap || rows > INT16_MAX - 1) return -1;

    std::vector<int32_t> rank(n, -1);
    for (int32_t r = 0; r < rows; ++r) rank[order[r]] = r;

    std::memset(preds, 0xFF, sizeof(int16_t) * vcap * pcap);  // -1 pad
    std::memset(sinks, 0, vcap);
    for (int32_t r = 0; r < rows; ++r) {
        const Node& node = g.nodes[order[r]];
        bases[r] = static_cast<uint8_t>(node.base);
        rank2node[r] = order[r];
        int32_t np = 0;
        for (int32_t e : node.in_edges) {
            int32_t u = g.edges[e].from;
            if (rank[u] >= 0) {
                if (np >= pcap) return -2;
                if (r - rank[u] > kcap) return -3;
                preds[r * pcap + np++] = static_cast<int16_t>(rank[u] + 1);
            }
        }
        if (np == 0) preds[r * pcap] = 0;  // virtual start row
        bool sink = true;
        for (int32_t e : node.out_edges) {
            if (rank[g.edges[e].to] >= 0) { sink = false; break; }
        }
        sinks[r] = sink ? 1 : 0;
    }
    return rows;
}

// Incorporate a layer along the device-produced path.  path_nodes holds
// node IDS (already translated from ranks via rank2node; -1 = none),
// path_seq holds sequence positions (-1 = node skipped).
void rt_poab_apply(void* h, int32_t w, const int32_t* path_nodes,
                   const int32_t* path_seq, int32_t path_len,
                   const char* seq, int32_t slen, const char* qual,
                   uint8_t has_qual, int32_t begin_anchor) {
    WindowState& ws = static_cast<Batch*>(h)->windows[w];
    AlignmentPath path;
    path.reserve(path_len);
    for (int32_t i = 0; i < path_len; ++i) {
        path.emplace_back(path_nodes[i], path_seq[i]);
    }
    make_weights(qual, has_qual, slen, ws.weights);
    ws.graph.add_alignment(path, seq, slen, ws.weights.data(),
                           begin_anchor);
    ++ws.n_seqs;
}

int32_t rt_poab_num_nodes(void* h, int32_t w) {
    return static_cast<int32_t>(
        static_cast<Batch*>(h)->windows[w].graph.nodes.size());
}

// Heaviest-bundle consensus + TGS trim for window w; same semantics as
// rt_poa_consensus's tail (poa.cpp), with n_seqs = layers actually
// incorporated (device-rejected layers only reduce coverage, mirroring
// cudabatch.cpp:136-155).
int64_t rt_poab_consensus(void* h, int32_t w, int32_t window_type,
                          int32_t trim, char* out, int64_t out_cap,
                          int32_t* status) {
    WindowState& ws = static_cast<Batch*>(h)->windows[w];
    *status = 0;

    std::vector<int32_t> cons = ws.graph.consensus_path();
    std::vector<int32_t> coverages(cons.size());
    for (size_t i = 0; i < cons.size(); ++i) {
        coverages[i] = ws.graph.nodes[cons[i]].nseqs;
    }

    int64_t begin = 0, end = static_cast<int64_t>(cons.size()) - 1;
    if (window_type == 1 && trim) {  // kTGS
        int32_t average_coverage = (ws.n_seqs - 1) / 2;
        for (; begin < (int64_t)cons.size(); ++begin) {
            if (coverages[begin] >= average_coverage) break;
        }
        for (; end >= 0; --end) {
            if (coverages[end] >= average_coverage) break;
        }
        if (begin >= end) {
            *status = 2;  // chimeric warning; keep untrimmed
            begin = 0;
            end = static_cast<int64_t>(cons.size()) - 1;
        }
    }

    int64_t length = end - begin + 1;
    if (length < 0) length = 0;
    if (length + 1 > out_cap) return -1;
    for (int64_t i = 0; i < length; ++i) {
        out[i] = ws.graph.nodes[cons[begin + i]].base;
    }
    out[length] = '\0';
    return length;
}

}  // extern "C"
