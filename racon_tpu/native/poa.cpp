// Partial-order-alignment consensus engine (spoa-equivalent).
//
// Re-provides, for the CPU fallback path, what racon gets from the
// vendored spoa library (reference: vendor/spoa; call sites
// src/window.cpp:73-116 and src/polisher.cpp:181-184): a POA graph
// seeded with the window backbone, global (kNW, linear gap) alignment of
// each read layer against the graph (or against the subgraph spanning
// the layer's backbone interval for partial-span layers), quality-
// weighted alignment incorporation, and a heaviest-bundle consensus walk
// returning per-base coverages.  The whole per-window consensus --
// including layer ordering by start position and the TGS coverage trim
// (src/window.cpp:84-85,118-139) -- runs natively behind one C call so
// Python threads can release the GIL around it.
//
// Semantics mirrored from the reference's call sites:
//   * base weights: Phred quality char minus 33, or 1 when the layer has
//     no qualities (cudapoa uses the same convention,
//     src/cuda/cudabatch.cpp:177-186);
//   * edge weight accumulates (w[prev] + w[cur]) per traversing sequence;
//   * consensus = heaviest-bundle: per node pick the heaviest in-edge
//     (ties -> higher predecessor score), then backtrack from the best
//     sink; coverage of a consensus base = number of sequences whose
//     path visits that node;
//   * TGS trim: cut consensus ends while coverage < (n_seqs - 1) / 2,
//     warn (status=2) without trimming when everything is below.

#include "poa_graph.hpp"

#include <cstring>
#include <numeric>
#include <vector>

using namespace racon_native;

extern "C" {

// Consensus over one window.  Sequence 0 is the backbone; begins/ends are
// window-relative layer spans.  Returns consensus length, or -1 if
// out_cap is too small.  status: 0 ok, 2 chimeric warning (TGS trim found
// no coverage plateau; consensus kept untrimmed).
int64_t rt_poa_consensus(const char* seqs_blob, const int64_t* offsets,
                         const char* quals_blob, const uint8_t* has_qual,
                         const int32_t* begins, const int32_t* ends,
                         int32_t n_seqs, int32_t window_type, int32_t trim,
                         int32_t match, int32_t mismatch, int32_t gap,
                         char* out, int64_t out_cap, int32_t* status) {
    *status = 0;
    const char* backbone = seqs_blob + offsets[0];
    const int32_t backbone_len =
        static_cast<int32_t>(offsets[1] - offsets[0]);

    PoaGraph graph;
    graph.nodes.reserve(backbone_len * 3);
    std::vector<int32_t> weights;
    make_weights(quals_blob + offsets[0], has_qual[0], backbone_len, weights);
    graph.add_alignment(AlignmentPath(), backbone, backbone_len,
                        weights.data(), 0);

    // layer order: ascending start position (src/window.cpp:84-85)
    std::vector<int32_t> rank(n_seqs - 1);
    std::iota(rank.begin(), rank.end(), 1);
    std::stable_sort(rank.begin(), rank.end(), [&](int32_t a, int32_t b) {
        return begins[a] < begins[b];
    });

    const int32_t offset = static_cast<int32_t>(0.01 * backbone_len);
    std::vector<uint8_t> subset;
    for (int32_t idx : rank) {
        const char* seq = seqs_blob + offsets[idx];
        const int32_t m = static_cast<int32_t>(offsets[idx + 1] -
                                               offsets[idx]);
        if (m == 0) continue;
        make_weights(quals_blob + offsets[idx], has_qual[idx], m, weights);

        subset.assign(graph.nodes.size(), 0);
        bool full_span = begins[idx] < offset &&
                         ends[idx] > backbone_len - offset;
        if (full_span) {
            std::fill(subset.begin(), subset.end(), 1);
        } else {
            for (size_t v = 0; v < graph.nodes.size(); ++v) {
                int32_t a = graph.nodes[v].anchor;
                subset[v] = (a >= begins[idx] && a <= ends[idx]) ? 1 : 0;
            }
        }
        AlignmentPath path = graph.align(seq, m, subset, match, mismatch,
                                         gap);
        graph.add_alignment(path, seq, m, weights.data(), begins[idx]);
    }

    std::vector<int32_t> cons = graph.consensus_path();
    std::vector<int32_t> coverages(cons.size());
    for (size_t i = 0; i < cons.size(); ++i) {
        coverages[i] = graph.nodes[cons[i]].nseqs;
    }

    int64_t begin = 0, end = static_cast<int64_t>(cons.size()) - 1;
    if (window_type == 1 && trim) {  // kTGS
        int32_t average_coverage = (n_seqs - 1) / 2;
        for (; begin < (int64_t)cons.size(); ++begin) {
            if (coverages[begin] >= average_coverage) break;
        }
        for (; end >= 0; --end) {
            if (coverages[end] >= average_coverage) break;
        }
        if (begin >= end) {
            *status = 2;  // chimeric warning; keep untrimmed
            begin = 0;
            end = static_cast<int64_t>(cons.size()) - 1;
        }
    }

    int64_t length = end - begin + 1;
    if (length < 0) length = 0;
    if (length + 1 > out_cap) return -1;
    for (int64_t i = 0; i < length; ++i) {
        out[i] = graph.nodes[cons[begin + i]].base;
    }
    out[length] = '\0';
    return length;
}

}  // extern "C"
