// Banded global Levenshtein alignment with traceback -> CIGAR, plus a
// score-only edit distance.  This is the CPU fallback / accuracy-oracle
// aligner re-providing what racon gets from edlib
// (reference: vendor/edlib, call site src/overlap.cpp:205-224): global
// (NW) alignment of an overlap's query span vs target span, emitting a
// standard CIGAR where 'M' covers both matches and mismatches, 'I'
// consumes query and 'D' consumes target.
//
// Algorithm: Ukkonen banded DP with band doubling.  The band covers
// diagonals d = j - i in [dmin - k, dmax + k] around the corner-to-corner
// diagonal; if the computed distance exceeds k the band may have clipped
// the optimal path, so k doubles and the DP reruns (exact once dist <= k
// or the band spans the full matrix).  Directions are stored 2 bits/cell
// over the band only, so memory is O((|q|+|t|) * k / 4) bytes.

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr int32_t kInf = INT32_MAX / 4;

enum Dir : uint8_t { DIAG = 0, DEL = 1, INS = 2, NONE = 3 };
// DIAG: from (i-1, j-1)  -> 'M'
// DEL : from (i,   j-1)  -> 'D' (consumes target)
// INS : from (i-1, j  )  -> 'I' (consumes query)

struct BandedResult {
    int32_t distance = -1;
    bool within_band = false;
};

// One banded pass.  dirs (if non-null) receives 2-bit packed directions,
// rows of width `band_w` cells starting at diagonal `dmin`.
BandedResult banded_pass(const char* q, int32_t qn, const char* t,
                         int32_t tn, int32_t k, std::vector<uint8_t>* dirs,
                         int32_t* out_dmin, int32_t* out_band_w) {
    const int32_t d_lo = std::min(0, tn - qn) - k;
    const int32_t d_hi = std::max(0, tn - qn) + k;
    const int32_t band_w = d_hi - d_lo + 1;
    *out_dmin = d_lo;
    *out_band_w = band_w;

    std::vector<int32_t> prev(band_w, kInf), cur(band_w, kInf);
    if (dirs) {
        dirs->assign(static_cast<size_t>(qn + 1) *
                         ((band_w + 3) / 4), 0xFF);
    }
    auto set_dir = [&](int32_t i, int32_t b, Dir d) {
        if (!dirs) return;
        size_t idx = static_cast<size_t>(i) * ((band_w + 3) / 4) + b / 4;
        int shift = (b % 4) * 2;
        (*dirs)[idx] = ((*dirs)[idx] & ~(uint8_t(3) << shift)) |
                       (uint8_t(d) << shift);
    };

    // row 0: (0, j), j = d - 0
    for (int32_t b = 0; b < band_w; ++b) {
        int32_t j = d_lo + b;
        if (j < 0 || j > tn) continue;
        prev[b] = j;
        set_dir(0, b, j == 0 ? NONE : DEL);
    }

    for (int32_t i = 1; i <= qn; ++i) {
        std::fill(cur.begin(), cur.end(), kInf);
        for (int32_t b = 0; b < band_w; ++b) {
            int32_t j = i + d_lo + b;
            if (j < 0 || j > tn) continue;
            int32_t best = kInf;
            Dir dir = NONE;
            if (j > 0) {
                // (i-1, j-1) is the same band index b in row i-1
                int32_t v = prev[b];
                if (v < kInf) {
                    int32_t c = v + (q[i - 1] == t[j - 1] ? 0 : 1);
                    if (c < best) { best = c; dir = DIAG; }
                }
            }
            if (b + 1 < band_w) {  // (i-1, j) is band index b+1 in row i-1
                int32_t v = prev[b + 1];
                if (v < kInf && v + 1 < best) { best = v + 1; dir = INS; }
            }
            if (b > 0) {           // (i, j-1) is band index b-1, same row
                int32_t v = cur[b - 1];
                if (v < kInf && v + 1 < best) { best = v + 1; dir = DEL; }
            }
            cur[b] = best;
            if (dir != NONE) set_dir(i, b, dir);
        }
        std::swap(prev, cur);
    }

    int32_t end_b = tn - qn - d_lo;
    BandedResult r;
    if (end_b >= 0 && end_b < band_w && prev[end_b] < kInf) {
        r.distance = prev[end_b];
        r.within_band = r.distance <= k ||
                        (d_hi - d_lo >= qn + tn);  // band covers everything
    }
    return r;
}

std::string traceback_cigar(const char* q, int32_t qn, const char* t,
                            int32_t tn, const std::vector<uint8_t>& dirs,
                            int32_t dmin, int32_t band_w) {
    auto get_dir = [&](int32_t i, int32_t j) -> Dir {
        int32_t b = j - i - dmin;
        size_t idx = static_cast<size_t>(i) * ((band_w + 3) / 4) + b / 4;
        int shift = (b % 4) * 2;
        return Dir((dirs[idx] >> shift) & 3);
    };
    std::string ops;  // reversed op chars
    ops.reserve(qn + tn);
    int32_t i = qn, j = tn;
    while (i > 0 || j > 0) {
        Dir d = get_dir(i, j);
        switch (d) {
            case DIAG: ops.push_back('M'); --i; --j; break;
            case INS:  ops.push_back('I'); --i; break;
            case DEL:  ops.push_back('D'); --j; break;
            default:   return std::string();  // corrupt band; caller retries
        }
    }
    // run-length encode reversed ops into a CIGAR
    std::string cigar;
    cigar.reserve(ops.size() / 4 + 8);
    for (size_t p = ops.size(); p > 0;) {
        char op = ops[p - 1];
        size_t run = 0;
        while (p > 0 && ops[p - 1] == op) { --p; ++run; }
        cigar += std::to_string(run);
        cigar.push_back(op);
    }
    return cigar;
}

}  // namespace

extern "C" {

// Score-only global edit distance (test oracle; the reference's tests use
// edlib's default config the same way, test/racon_test.cpp:16-25).
int32_t rt_edit_distance(const char* q, int32_t qn, const char* t,
                         int32_t tn) {
    // two-row full DP; O(qn*tn) time, O(tn) space
    std::vector<int32_t> prev(tn + 1), cur(tn + 1);
    for (int32_t j = 0; j <= tn; ++j) prev[j] = j;
    for (int32_t i = 1; i <= qn; ++i) {
        cur[0] = i;
        const char qc = q[i - 1];
        for (int32_t j = 1; j <= tn; ++j) {
            int32_t best = prev[j - 1] + (qc == t[j - 1] ? 0 : 1);
            best = std::min(best, prev[j] + 1);
            best = std::min(best, cur[j - 1] + 1);
            cur[j] = best;
        }
        std::swap(prev, cur);
    }
    return prev[tn];
}

// Global alignment with CIGAR.  Returns the CIGAR length written (excl.
// NUL), or -1 if cigar_cap is too small, or -2 on internal failure.
int64_t rt_align(const char* q, int32_t qn, const char* t, int32_t tn,
                 char* cigar_out, int64_t cigar_cap, int32_t* distance_out) {
    if (qn == 0 || tn == 0) {
        std::string cigar;
        if (qn > 0) cigar = std::to_string(qn) + "I";
        else if (tn > 0) cigar = std::to_string(tn) + "D";
        if ((int64_t)cigar.size() + 1 > cigar_cap) return -1;
        std::memcpy(cigar_out, cigar.c_str(), cigar.size() + 1);
        if (distance_out) *distance_out = qn + tn;
        return (int64_t)cigar.size();
    }
    int32_t k = std::max<int32_t>(64, std::abs(tn - qn) / 8 + 16);
    const int32_t k_cap = qn + tn;
    while (true) {
        std::vector<uint8_t> dirs;
        int32_t dmin = 0, band_w = 0;
        BandedResult r = banded_pass(q, qn, t, tn, k, &dirs, &dmin, &band_w);
        if (r.distance >= 0 && r.within_band) {
            std::string cigar = traceback_cigar(q, qn, t, tn, dirs, dmin,
                                                band_w);
            if (!cigar.empty()) {
                if ((int64_t)cigar.size() + 1 > cigar_cap) return -1;
                std::memcpy(cigar_out, cigar.c_str(), cigar.size() + 1);
                if (distance_out) *distance_out = r.distance;
                return (int64_t)cigar.size();
            }
        }
        if (k >= k_cap) return -2;
        k = std::min(k * 2, k_cap);
    }
}

}  // extern "C"
