// Global Levenshtein alignment with traceback -> CIGAR, plus a
// score-only edit distance.  This is the CPU fallback / accuracy-oracle
// aligner re-providing what racon gets from edlib
// (reference: vendor/edlib, call site src/overlap.cpp:205-224): global
// (NW) alignment of an overlap's query span vs target span, emitting a
// standard CIGAR where 'M' covers both matches and mismatches, 'I'
// consumes query and 'D' consumes target.
//
// Primary algorithm: furthest-reaching edit wavefronts (Landau-Vishkin /
// WFA for unit costs).  L[e][d] is the furthest query row i whose cell
// (i, i+d) on diagonal d = j - i costs exactly e after sliding along
// exact matches; time and memory are O(N + D^2) for distance D, so a
// typical 10 kb ONT overlap (D ~ 500-2000) costs ~1-4 M steps instead of
// the ~10^8 cells of a banded DP.  The full wavefront history is kept
// for direct traceback; if D^2 would exceed a memory cap the aligner
// falls back to the original Ukkonen banded DP with band doubling
// (kept below), which is O((|q|+|t|) * k) time but bounded memory.

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr int32_t kInf = INT32_MAX / 4;
constexpr int32_t kNeg = INT32_MIN / 4;

// Run-length encode a reversed op-char string into a CIGAR.
std::string rle_cigar(const std::string& ops) {
    std::string cigar;
    cigar.reserve(ops.size() / 8 + 8);
    for (size_t p = ops.size(); p > 0;) {
        char op = ops[p - 1];
        size_t run = 0;
        while (p > 0 && ops[p - 1] == op) { --p; ++run; }
        cigar += std::to_string(run);
        cigar.push_back(op);
    }
    return cigar;
}

// Extend exact matches along diagonal d starting from query row i
// (word-at-a-time, the LCP "slide" step of the wavefront recurrence).
inline int32_t slide(const char* q, int32_t qn, const char* t, int32_t tn,
                     int32_t i, int32_t d) {
    int32_t j = i + d;
    while (i + 8 <= qn && j + 8 <= tn) {
        uint64_t a, b;
        std::memcpy(&a, q + i, 8);
        std::memcpy(&b, t + j, 8);
        uint64_t x = a ^ b;
        if (x) return i + (__builtin_ctzll(x) >> 3);
        i += 8;
        j += 8;
    }
    while (i < qn && j < tn && q[i] == t[j]) { ++i; ++j; }
    return i;
}

// Wavefront e lives at hist[e*e .. e*e + 2e], entry d at hist[e*e + d + e].
inline size_t wf_base(int32_t e) {
    return static_cast<size_t>(e) * static_cast<size_t>(e);
}

// Compute the best pre-slide row for wavefront (e, d) from wavefront
// e-1 (stored at prev).  Candidates: deletion keeps i (from d-1),
// substitution and insertion advance i (from d and d+1).  Invalid or
// out-of-matrix candidates yield kNeg.
inline int32_t wf_candidate(const int32_t* prev, int32_t e1, int32_t d,
                            int32_t qn, int32_t tn) {
    int32_t best = kNeg;
    if (d - 1 >= -e1 && d - 1 <= e1) {       // deletion: (i, j-1)
        int32_t v = prev[d - 1 + e1];
        if (v > kNeg && v + d <= tn && v >= best) best = v;
    }
    if (d >= -e1 && d <= e1) {               // substitution: (i-1, j-1)
        int32_t v = prev[d + e1];
        if (v > kNeg && v + 1 <= qn && v + 1 + d <= tn && v + 1 > best)
            best = v + 1;
    }
    if (d + 1 >= -e1 && d + 1 <= e1) {       // insertion: (i-1, j)
        int32_t v = prev[d + 1 + e1];
        if (v > kNeg && v + 1 <= qn && v + 1 > best) best = v + 1;
    }
    return best;
}

// Full-history wavefront alignment.  On success fills *cigar and
// *distance and returns true; returns false if the history would exceed
// max_entries (caller falls back to the banded DP).
bool wfa_align(const char* q, int32_t qn, const char* t, int32_t tn,
               size_t max_entries, std::string* cigar,
               int32_t* distance) {
    const int32_t final_d = tn - qn;
    std::vector<int32_t> hist;
    hist.reserve(4096);
    hist.push_back(slide(q, qn, t, tn, 0, 0));
    int32_t dist = -1;
    if (final_d == 0 && hist[0] >= qn) {
        dist = 0;
    } else {
        for (int32_t e = 1;; ++e) {
            size_t need = wf_base(e + 1);
            if (need > max_entries) return false;
            hist.resize(need, kNeg);
            // take pointers only after the resize (it may reallocate)
            int32_t* cur = hist.data() + wf_base(e);
            const int32_t* prev = hist.data() + wf_base(e - 1);
            const int32_t dlo = std::max(-e, -qn);
            const int32_t dhi = std::min(e, tn);
            for (int32_t d = dlo; d <= dhi; ++d) {
                int32_t i0 = wf_candidate(prev, e - 1, d, qn, tn);
                if (i0 <= kNeg) continue;
                cur[d + e] = slide(q, qn, t, tn, i0, d);
            }
            if (final_d >= -e && final_d <= e &&
                cur[final_d + e] >= qn) {
                dist = e;
                break;
            }
        }
    }

    // Traceback: walk wavefronts backwards, re-deriving each pre-slide
    // row with the same candidate rule as the forward pass.
    std::string ops;  // reversed op chars
    ops.reserve(static_cast<size_t>(qn) + 16);
    int32_t e = dist, d = final_d;
    int32_t i = hist[wf_base(e) + d + e];
    while (e > 0) {
        const int32_t* prev = hist.data() + wf_base(e - 1);
        const int32_t e1 = e - 1;
        int32_t i0 = wf_candidate(prev, e1, d, qn, tn);
        ops.append(static_cast<size_t>(i - i0), 'M');  // slid matches
        // which predecessor attained i0? (same preference as forward)
        int32_t ins_v = (d + 1 >= -e1 && d + 1 <= e1) ? prev[d + 1 + e1]
                                                      : kNeg;
        int32_t sub_v = (d >= -e1 && d <= e1) ? prev[d + e1] : kNeg;
        if (ins_v > kNeg && ins_v + 1 <= qn && ins_v + 1 == i0) {
            ops.push_back('I');
            i = i0 - 1;
            ++d;
        } else if (sub_v > kNeg && sub_v + 1 <= qn &&
                   sub_v + 1 + d <= tn && sub_v + 1 == i0) {
            ops.push_back('M');  // mismatch
            i = i0 - 1;
        } else {
            ops.push_back('D');
            i = i0;
            --d;
        }
        --e;
    }
    ops.append(static_cast<size_t>(i), 'M');  // e == 0 slide from origin
    *cigar = rle_cigar(ops);
    *distance = dist;
    return true;
}

// Score-only wavefront distance with two rolling wavefronts -- O(D)
// memory, no cap needed.
int32_t wfa_distance(const char* q, int32_t qn, const char* t, int32_t tn) {
    const int32_t final_d = tn - qn;
    std::vector<int32_t> prev(1, slide(q, qn, t, tn, 0, 0)), cur;
    if (final_d == 0 && prev[0] >= qn) return 0;
    for (int32_t e = 1;; ++e) {
        cur.assign(2 * static_cast<size_t>(e) + 1, kNeg);
        const int32_t dlo = std::max(-e, -qn);
        const int32_t dhi = std::min(e, tn);
        for (int32_t d = dlo; d <= dhi; ++d) {
            int32_t i0 = wf_candidate(prev.data(), e - 1, d, qn, tn);
            if (i0 <= kNeg) continue;
            cur[d + e] = slide(q, qn, t, tn, i0, d);
        }
        if (final_d >= -e && final_d <= e && cur[final_d + e] >= qn)
            return e;
        std::swap(prev, cur);
    }
}

enum Dir : uint8_t { DIAG = 0, DEL = 1, INS = 2, NONE = 3 };
// DIAG: from (i-1, j-1)  -> 'M'
// DEL : from (i,   j-1)  -> 'D' (consumes target)
// INS : from (i-1, j  )  -> 'I' (consumes query)

struct BandedResult {
    int32_t distance = -1;
    bool within_band = false;
};

// One banded pass.  dirs (if non-null) receives 2-bit packed directions,
// rows of width `band_w` cells starting at diagonal `dmin`.
BandedResult banded_pass(const char* q, int32_t qn, const char* t,
                         int32_t tn, int32_t k, std::vector<uint8_t>* dirs,
                         int32_t* out_dmin, int32_t* out_band_w) {
    const int32_t d_lo = std::min(0, tn - qn) - k;
    const int32_t d_hi = std::max(0, tn - qn) + k;
    const int32_t band_w = d_hi - d_lo + 1;
    *out_dmin = d_lo;
    *out_band_w = band_w;

    std::vector<int32_t> prev(band_w, kInf), cur(band_w, kInf);
    if (dirs) {
        dirs->assign(static_cast<size_t>(qn + 1) *
                         ((band_w + 3) / 4), 0xFF);
    }
    auto set_dir = [&](int32_t i, int32_t b, Dir d) {
        if (!dirs) return;
        size_t idx = static_cast<size_t>(i) * ((band_w + 3) / 4) + b / 4;
        int shift = (b % 4) * 2;
        (*dirs)[idx] = ((*dirs)[idx] & ~(uint8_t(3) << shift)) |
                       (uint8_t(d) << shift);
    };

    // row 0: (0, j), j = d - 0
    for (int32_t b = 0; b < band_w; ++b) {
        int32_t j = d_lo + b;
        if (j < 0 || j > tn) continue;
        prev[b] = j;
        set_dir(0, b, j == 0 ? NONE : DEL);
    }

    for (int32_t i = 1; i <= qn; ++i) {
        std::fill(cur.begin(), cur.end(), kInf);
        for (int32_t b = 0; b < band_w; ++b) {
            int32_t j = i + d_lo + b;
            if (j < 0 || j > tn) continue;
            int32_t best = kInf;
            Dir dir = NONE;
            if (j > 0) {
                // (i-1, j-1) is the same band index b in row i-1
                int32_t v = prev[b];
                if (v < kInf) {
                    int32_t c = v + (q[i - 1] == t[j - 1] ? 0 : 1);
                    if (c < best) { best = c; dir = DIAG; }
                }
            }
            if (b + 1 < band_w) {  // (i-1, j) is band index b+1 in row i-1
                int32_t v = prev[b + 1];
                if (v < kInf && v + 1 < best) { best = v + 1; dir = INS; }
            }
            if (b > 0) {           // (i, j-1) is band index b-1, same row
                int32_t v = cur[b - 1];
                if (v < kInf && v + 1 < best) { best = v + 1; dir = DEL; }
            }
            cur[b] = best;
            if (dir != NONE) set_dir(i, b, dir);
        }
        std::swap(prev, cur);
    }

    int32_t end_b = tn - qn - d_lo;
    BandedResult r;
    if (end_b >= 0 && end_b < band_w && prev[end_b] < kInf) {
        r.distance = prev[end_b];
        r.within_band = r.distance <= k ||
                        (d_hi - d_lo >= qn + tn);  // band covers everything
    }
    return r;
}

std::string traceback_cigar(int32_t qn, int32_t tn,
                            const std::vector<uint8_t>& dirs,
                            int32_t dmin, int32_t band_w) {
    auto get_dir = [&](int32_t i, int32_t j) -> Dir {
        int32_t b = j - i - dmin;
        size_t idx = static_cast<size_t>(i) * ((band_w + 3) / 4) + b / 4;
        int shift = (b % 4) * 2;
        return Dir((dirs[idx] >> shift) & 3);
    };
    std::string ops;  // reversed op chars
    ops.reserve(qn + tn);
    int32_t i = qn, j = tn;
    while (i > 0 || j > 0) {
        Dir d = get_dir(i, j);
        switch (d) {
            case DIAG: ops.push_back('M'); --i; --j; break;
            case INS:  ops.push_back('I'); --i; break;
            case DEL:  ops.push_back('D'); --j; break;
            default:   return std::string();  // corrupt band; caller retries
        }
    }
    return rle_cigar(ops);
}

}  // namespace

extern "C" {

// Score-only global edit distance (test oracle; the reference's tests use
// edlib's default config the same way, test/racon_test.cpp:16-25).
int32_t rt_edit_distance(const char* q, int32_t qn, const char* t,
                         int32_t tn) {
    if (qn == 0) return tn;
    if (tn == 0) return qn;
    // O(N + D^2) wavefront distance, O(D) memory
    return wfa_distance(q, qn, t, tn);
}

// Global alignment with CIGAR.  Returns the CIGAR length written (excl.
// NUL), or -1 if cigar_cap is too small, or -2 on internal failure.
int64_t rt_align(const char* q, int32_t qn, const char* t, int32_t tn,
                 char* cigar_out, int64_t cigar_cap, int32_t* distance_out) {
    if (qn == 0 || tn == 0) {
        std::string cigar;
        if (qn > 0) cigar = std::to_string(qn) + "I";
        else if (tn > 0) cigar = std::to_string(tn) + "D";
        if ((int64_t)cigar.size() + 1 > cigar_cap) return -1;
        std::memcpy(cigar_out, cigar.c_str(), cigar.size() + 1);
        if (distance_out) *distance_out = qn + tn;
        return (int64_t)cigar.size();
    }
    // Primary: wavefront alignment, O(N + D^2).  History cap 256 MB of
    // int32 entries (D up to ~8k, comfortably above real ONT overlap
    // distances) -- the cap is PER CALL, so keep it modest: pool
    // threads align concurrently and each may grow toward it before
    // falling back.  RACON_TPU_WFA_MAX_MB overrides.
    size_t max_mb = 256;
    if (const char* env = std::getenv("RACON_TPU_WFA_MAX_MB")) {
        long v = std::atol(env);
        if (v > 0) max_mb = static_cast<size_t>(v);
    }
    {
        std::string cigar;
        int32_t dist = 0;
        if (wfa_align(q, qn, t, tn, max_mb * (1024 * 1024 / 4), &cigar,
                      &dist)) {
            if ((int64_t)cigar.size() + 1 > cigar_cap) return -1;
            std::memcpy(cigar_out, cigar.c_str(), cigar.size() + 1);
            if (distance_out) *distance_out = dist;
            return (int64_t)cigar.size();
        }
    }
    // Fallback for distances past the cap: banded DP with band doubling.
    int32_t k = std::max<int32_t>(64, std::abs(tn - qn) / 8 + 16);
    const int32_t k_cap = qn + tn;
    while (true) {
        std::vector<uint8_t> dirs;
        int32_t dmin = 0, band_w = 0;
        BandedResult r = banded_pass(q, qn, t, tn, k, &dirs, &dmin, &band_w);
        if (r.distance >= 0 && r.within_band) {
            std::string cigar = traceback_cigar(qn, tn, dirs, dmin,
                                                band_w);
            if (!cigar.empty()) {
                if ((int64_t)cigar.size() + 1 > cigar_cap) return -1;
                std::memcpy(cigar_out, cigar.c_str(), cigar.size() + 1);
                if (distance_out) *distance_out = r.distance;
                return (int64_t)cigar.size();
            }
        }
        if (k >= k_cap) return -2;
        k = std::min(k * 2, k_cap);
    }
}

}  // extern "C"
