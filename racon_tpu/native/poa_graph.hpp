// POA graph engine shared by the single-window CPU entry point
// (poa.cpp) and the lockstep batch API (poa_batch.cpp).  Split out of
// poa.cpp so both translation units use one implementation.
#pragma once

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

namespace racon_native {

constexpr int32_t kNegInf = INT32_MIN / 4;

struct Edge {
    int32_t from, to;
    int64_t weight;
};

struct Node {
    char base;
    int32_t anchor;               // backbone position this node hangs off
    int32_t nseqs = 0;            // sequences whose path includes the node
    std::vector<int32_t> in_edges;    // edge ids
    std::vector<int32_t> out_edges;   // edge ids
    std::vector<int32_t> aligned;     // node ids in the same column
};

// One alignment column: node id (-1 = none) and sequence position (-1 =
// node skipped).  Same convention as spoa::Alignment.
using AlignmentPath = std::vector<std::pair<int32_t, int32_t>>;

class PoaGraph {
  public:
    std::vector<Node> nodes;
    std::vector<Edge> edges;

    int32_t add_node(char base, int32_t anchor) {
        nodes.push_back(Node{base, anchor});
        return static_cast<int32_t>(nodes.size()) - 1;
    }

    void add_edge(int32_t u, int32_t v, int64_t w) {
        for (int32_t e : nodes[u].out_edges) {
            if (edges[e].to == v) {
                edges[e].weight += w;
                return;
            }
        }
        edges.push_back(Edge{u, v, w});
        int32_t e = static_cast<int32_t>(edges.size()) - 1;
        nodes[u].out_edges.push_back(e);
        nodes[v].in_edges.push_back(e);
    }

    // Kahn topological order over a node subset (subset[v] true).
    std::vector<int32_t> topo_order(const std::vector<uint8_t>& subset) const {
        std::vector<int32_t> indeg(nodes.size(), 0), order;
        order.reserve(nodes.size());
        for (size_t v = 0; v < nodes.size(); ++v) {
            if (!subset[v]) continue;
            int32_t d = 0;
            for (int32_t e : nodes[v].in_edges) {
                if (subset[edges[e].from]) ++d;
            }
            indeg[v] = d;
            if (d == 0) order.push_back(static_cast<int32_t>(v));
        }
        // process in ascending id for determinism
        std::vector<int32_t> queue = order;
        std::make_heap(queue.begin(), queue.end(), std::greater<int32_t>());
        order.clear();
        while (!queue.empty()) {
            std::pop_heap(queue.begin(), queue.end(), std::greater<int32_t>());
            int32_t v = queue.back();
            queue.pop_back();
            order.push_back(v);
            for (int32_t e : nodes[v].out_edges) {
                int32_t u = edges[e].to;
                if (!subset[u]) continue;
                if (--indeg[u] == 0) {
                    queue.push_back(u);
                    std::push_heap(queue.begin(), queue.end(),
                                   std::greater<int32_t>());
                }
            }
        }
        return order;
    }

    // Global NW of seq vs the subgraph induced by `subset`.
    AlignmentPath align(const char* seq, int32_t m,
                        const std::vector<uint8_t>& subset,
                        int32_t match, int32_t mismatch, int32_t gap) const {
        std::vector<int32_t> order = topo_order(subset);
        const int32_t rows = static_cast<int32_t>(order.size());
        std::vector<int32_t> rank(nodes.size(), -1);
        for (int32_t r = 0; r < rows; ++r) rank[order[r]] = r;

        const int64_t stride = m + 1;
        std::vector<int32_t> H(static_cast<size_t>(rows + 1) * stride,
                               kNegInf);
        // virtual start row
        for (int32_t j = 0; j <= m; ++j) H[j] = j * gap;

        // per row: predecessors within the subset (row indices, 0=virtual)
        std::vector<std::vector<int32_t>> pred_rows(rows);
        for (int32_t r = 0; r < rows; ++r) {
            const Node& node = nodes[order[r]];
            for (int32_t e : node.in_edges) {
                int32_t u = edges[e].from;
                if (rank[u] >= 0) pred_rows[r].push_back(rank[u] + 1);
            }
            if (pred_rows[r].empty()) pred_rows[r].push_back(0);
        }

        for (int32_t r = 0; r < rows; ++r) {
            const Node& node = nodes[order[r]];
            int32_t* row = &H[static_cast<size_t>(r + 1) * stride];
            int32_t best0 = kNegInf;
            for (int32_t pr : pred_rows[r]) {
                best0 = std::max(best0,
                                 H[static_cast<size_t>(pr) * stride] + gap);
            }
            row[0] = best0;
            for (int32_t pi = 0; pi < (int32_t)pred_rows[r].size(); ++pi) {
                const int32_t* prow =
                    &H[static_cast<size_t>(pred_rows[r][pi]) * stride];
                if (pi == 0) {
                    for (int32_t j = 1; j <= m; ++j) {
                        int32_t diag = prow[j - 1] +
                            (node.base == seq[j - 1] ? match : mismatch);
                        int32_t vert = prow[j] + gap;
                        row[j] = std::max(diag, vert);
                    }
                } else {
                    for (int32_t j = 1; j <= m; ++j) {
                        int32_t diag = prow[j - 1] +
                            (node.base == seq[j - 1] ? match : mismatch);
                        int32_t vert = prow[j] + gap;
                        int32_t cand = std::max(diag, vert);
                        if (cand > row[j]) row[j] = cand;
                    }
                }
            }
            for (int32_t j = 1; j <= m; ++j) {
                int32_t horiz = row[j - 1] + gap;
                if (horiz > row[j]) row[j] = horiz;
            }
        }

        // end: best sink (no out-edges within subset) at column m
        int32_t best_row = 0, best_score = H[m];  // virtual row if no rows
        bool found_sink = false;
        for (int32_t r = 0; r < rows; ++r) {
            const Node& node = nodes[order[r]];
            bool sink = true;
            for (int32_t e : node.out_edges) {
                if (rank[edges[e].to] >= 0) { sink = false; break; }
            }
            if (!sink) continue;
            int32_t s = H[static_cast<size_t>(r + 1) * stride + m];
            if (!found_sink || s > best_score) {
                best_score = s;
                best_row = r + 1;
                found_sink = true;
            }
        }

        // traceback (recompute candidate scores; integer-exact)
        AlignmentPath path;
        path.reserve(rows + m);
        int32_t r = best_row, j = m;
        while (r > 0 || j > 0) {
            int32_t cur = H[static_cast<size_t>(r) * stride + j];
            bool moved = false;
            if (r > 0) {
                const Node& node = nodes[order[r - 1]];
                for (int32_t pr : pred_rows[r - 1]) {
                    const int32_t* prow = &H[static_cast<size_t>(pr) * stride];
                    if (j > 0 && cur == prow[j - 1] +
                            (node.base == seq[j - 1] ? match : mismatch)) {
                        path.emplace_back(order[r - 1], j - 1);
                        r = pr;
                        --j;
                        moved = true;
                        break;
                    }
                    if (cur == prow[j] + gap) {
                        path.emplace_back(order[r - 1], -1);
                        r = pr;
                        moved = true;
                        break;
                    }
                }
            }
            if (!moved) {
                // horizontal: seq char consumed without a node
                path.emplace_back(-1, j - 1);
                --j;
            }
        }
        std::reverse(path.begin(), path.end());
        return path;
    }

    // Incorporate an aligned sequence (spoa Graph::add_alignment).
    void add_alignment(const AlignmentPath& path, const char* seq, int32_t m,
                       const int32_t* weights, int32_t begin_anchor) {
        AlignmentPath full;
        const AlignmentPath* use = &path;
        const bool initial = path.empty();
        if (initial) {
            full.reserve(m);
            for (int32_t j = 0; j < m; ++j) full.emplace_back(-1, j);
            use = &full;
        }
        int32_t prev = -1, prev_j = -1;
        for (const auto& [node_id, j] : *use) {
            if (j == -1) continue;  // graph node skipped by this sequence
            char c = seq[j];
            int32_t target;
            if (node_id == -1) {
                // the initial (backbone) chain defines the anchor system:
                // node anchor == backbone position; later insertions hang
                // off the previous node's anchor
                int32_t anchor = initial ? begin_anchor + j
                                 : prev == -1 ? begin_anchor
                                              : nodes[prev].anchor;
                target = add_node(c, anchor);
            } else if (nodes[node_id].base == c) {
                target = node_id;
            } else {
                target = -1;
                for (int32_t a : nodes[node_id].aligned) {
                    if (nodes[a].base == c) { target = a; break; }
                }
                if (target == -1) {
                    target = add_node(c, nodes[node_id].anchor);
                    std::vector<int32_t> group = nodes[node_id].aligned;
                    group.push_back(node_id);
                    for (int32_t a : group) {
                        nodes[a].aligned.push_back(target);
                        nodes[target].aligned.push_back(a);
                    }
                }
            }
            ++nodes[target].nseqs;
            if (prev != -1) {
                add_edge(prev, target, static_cast<int64_t>(weights[prev_j]) +
                                       weights[j]);
            }
            prev = target;
            prev_j = j;
        }
    }

    // Heaviest-bundle consensus; fills coverages with per-base nseqs.
    std::vector<int32_t> consensus_path() const {
        std::vector<uint8_t> all(nodes.size(), 1);
        std::vector<int32_t> order = topo_order(all);
        std::vector<int64_t> score(nodes.size(), 0);
        std::vector<int32_t> pred(nodes.size(), -1);
        for (int32_t v : order) {
            int64_t best_w = -1;
            int32_t best_u = -1;
            for (int32_t e : nodes[v].in_edges) {
                const Edge& ed = edges[e];
                if (ed.weight > best_w ||
                    (ed.weight == best_w && best_u >= 0 &&
                     score[ed.from] > score[best_u])) {
                    best_w = ed.weight;
                    best_u = ed.from;
                }
            }
            if (best_u >= 0) {
                pred[v] = best_u;
                score[v] = score[best_u] + best_w;
            }
        }
        int32_t best_sink = -1;
        for (int32_t v : order) {
            if (!nodes[v].out_edges.empty()) continue;
            if (best_sink == -1 || score[v] > score[best_sink]) {
                best_sink = v;
            }
        }
        std::vector<int32_t> path;
        for (int32_t v = best_sink; v != -1; v = pred[v]) path.push_back(v);
        std::reverse(path.begin(), path.end());
        return path;
    }
};

inline void make_weights(const char* qual, uint8_t has_qual, int32_t n,
                  std::vector<int32_t>& w) {
    w.resize(n);
    if (has_qual) {
        for (int32_t i = 0; i < n; ++i) {
            w[i] = static_cast<int32_t>(qual[i]) - 33;
        }
    } else {
        std::fill(w.begin(), w.end(), 1);
    }
}

}  // namespace racon_native
