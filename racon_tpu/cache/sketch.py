"""Compact digest sketch over cached unit keys (r22).

The fleet router wants to know, per backend, "how much of THIS job's
work is already in that daemon's result cache?" — a placement
question, so an approximate answer is fine but a wrong-bytes answer
is impossible by construction (the sketch feeds pricing only; the
cache itself still verifies every real lookup by full 32-byte key).

Structure: a counting Bloom filter over 32-byte content digests.
Cache keys are blake2b output — uniformly random — so the K slot
indices come straight from the digest bytes, no extra hashing.
Counters are 8-bit saturating (a counter that reaches 255 sticks:
decrementing it on evict could underflow another key's membership,
and a sticky counter only ever over-reports warmth — a mis-pricing,
never a mis-compute).  ``discard`` on evict keeps the filter honest
under LRU churn, which a plain Bloom filter cannot do.

The wire export is the one-bit projection (counter > 0) packed to
``M / 8`` bytes — 8 KiB at the default M=65536 — base64-encoded in
the daemon's ``health``/``metrics`` cache block and epoch-tagged
with :func:`racon_tpu.cache.keying.engine_epoch` so a router never
scores digests from one knob environment against a sketch built in
another.

False-positive envelope: with K=4 and M=65536 the projected bitmap
answers "maybe present" wrongly for about ``(1 - e^(-4n/65536))^4``
of absent keys — under 0.5% at 10k live entries, a few percent at
30k.  Staleness (a probe-interval-old snapshot) and saturation skew
the estimated hit fraction the same direction; all of it only moves
the placement price.
"""

from __future__ import annotations

import base64

SKETCH_SCHEMA = "racon-tpu-sketch-v1"

#: counter slots; the exported bitmap is M bits = M/8 bytes
M = 65536
#: slot indices drawn per digest
K = 4

_SAT = 255


def _slots(key: bytes):
    """K independent slot indices from a uniformly-random digest.
    M is a power of two, so the modulo keeps the bytes' uniformity."""
    return [int.from_bytes(key[4 * i:4 * i + 4], "little") % M
            for i in range(K)]


class DigestSketch:
    """Counting Bloom filter over 32-byte digests.  NOT thread-safe:
    the owner (ResultCache) already serializes fills/evicts under its
    own lock."""

    __slots__ = ("_counts", "adds", "drops")

    def __init__(self):
        self._counts = bytearray(M)
        self.adds = 0
        self.drops = 0

    def add(self, key: bytes) -> None:
        counts = self._counts
        for s in _slots(key):
            if counts[s] < _SAT:
                counts[s] += 1
        self.adds += 1

    def discard(self, key: bytes) -> None:
        counts = self._counts
        for s in _slots(key):
            # saturated counters stick (see module docstring)
            if 0 < counts[s] < _SAT:
                counts[s] -= 1
        self.drops += 1

    def __contains__(self, key: bytes) -> bool:
        counts = self._counts
        return all(counts[s] for s in _slots(key))

    def export(self, epoch_hex: str, n: int) -> dict:
        """The wire form: one-bit projection of the counters plus the
        engine-epoch tag and the owner's live entry count ``n`` (what
        the router divides hit counts by to sanity-check density)."""
        bits = bytearray(M // 8)
        counts = self._counts
        for i in range(M):
            if counts[i]:
                bits[i >> 3] |= 1 << (i & 7)
        return {
            "schema": SKETCH_SCHEMA,
            "m": M,
            "k": K,
            "n": int(n),
            "epoch": epoch_hex,
            "bits": base64.b64encode(bytes(bits)).decode("ascii"),
        }


def decode_bits(doc: dict):
    """Packed bitmap bytes from an exported sketch doc, or None when
    the doc is missing/foreign/corrupt (treated as an empty — cold —
    sketch by every consumer)."""
    if not isinstance(doc, dict) or doc.get("schema") != SKETCH_SCHEMA:
        return None
    if doc.get("m") != M or doc.get("k") != K:
        return None
    try:
        bits = base64.b64decode(doc.get("bits") or "", validate=True)
    except (TypeError, ValueError):
        return None
    return bits if len(bits) == M // 8 else None


def bits_contain(bits: bytes, key: bytes) -> bool:
    return all(bits[s >> 3] & (1 << (s & 7)) for s in _slots(key))


def hit_fraction(doc: dict, digests) -> float:
    """Estimated fraction of ``digests`` present in an exported
    sketch — the router's per-backend warmth estimate.  0.0 for an
    undecodable doc or an empty sample."""
    bits = decode_bits(doc)
    if bits is None or not digests:
        return 0.0
    hits = sum(1 for d in digests if bits_contain(bits, d))
    return hits / len(digests)
