"""Canonical content keys for the result cache (r18).

Every cacheable work unit is reduced to a fixed-size blake2b digest
over (a) the unit's canonical input bytes, (b) the full engine
configuration that shapes the computation, and (c) the engine-code
*epoch* — a fingerprint of the package version plus every resolved
``RACON_TPU_*`` knob that can influence output bytes.  Two units
share a key iff recomputing either would provably produce the same
output bytes, which is exactly the byte-determinism contract pinned
since PR 3: a hit is then indistinguishable from recomputation.

Key spaces are deliberately disjoint per compute path: the CPU POA
engine and the device POA pipeline resolve cost ties independently,
so ``poa_key`` takes a ``space`` tag ("cpu" / "dev") and the device
space additionally carries the engine-config tuple the PR 9 executor
fuses on (scoring, caps, banded flag, mesh — ``PoaEngineHandle.
cfg_key``).  Align keys carry the rung geometry (bucket dims, error
cap / band width), the per-pair empirical center when one is pinned,
and the mesh.

The epoch EXCLUDES knobs that are proven output-neutral and vary
between otherwise-identical runs: the cache's own knobs (changing
the byte budget must never invalidate entries) and the pure
observability/durability planes (trace, flight, decisions, journal,
fleet scraper — each pinned byte-identical on/off by its own tier-1
tests).  Everything else — kernel shapes, ladder caps, scoring,
split policy — is hashed, so any knob delta that COULD change bytes
changes every key.
"""

from __future__ import annotations

import hashlib
import struct

#: knobs that never affect output bytes (each pinned by tests) and
#: therefore stay OUT of the epoch fingerprint.  The cache's own
#: knobs lead the list: resizing the budget or toggling persistence
#: must not orphan every existing entry.
EPOCH_EXCLUDE = frozenset({
    "RACON_TPU_CACHE",
    "RACON_TPU_CACHE_MB",
    "RACON_TPU_CACHE_PERSIST",
    "RACON_TPU_CACHE_DIR",
    "RACON_TPU_XLA_CACHE_DIR",
    # observability planes (pinned byte-identical on/off)
    "RACON_TPU_TRACE",
    "RACON_TPU_METRICS_JSON",
    "RACON_TPU_FLIGHT",
    "RACON_TPU_FLIGHT_RING",
    "RACON_TPU_FLIGHT_DUMP",
    "RACON_TPU_DECISIONS",
    "RACON_TPU_DECISIONS_RING",
    "RACON_TPU_SERVE_SAMPLE_S",
    "RACON_TPU_BENCH_GATE",
    # durability + fleet planes (replay/scrape only)
    "RACON_TPU_JOURNAL",
    "RACON_TPU_JOURNAL_DIR",
    "RACON_TPU_JOURNAL_FSYNC",
    "RACON_TPU_FAULT",
    "RACON_TPU_FLEET_INTERVAL_S",
    "RACON_TPU_FLEET_TIMEOUT_S",
    "RACON_TPU_FLEET_STALE_S",
    # fleet router (r19): placement policy — which backend runs a
    # job never changes the job's bytes
    "RACON_TPU_ROUTE_PROBE_S",
    "RACON_TPU_ROUTE_PROBE_TIMEOUT_S",
    "RACON_TPU_ROUTE_BREAKER_FAILS",
    "RACON_TPU_ROUTE_BREAKER_COOLDOWN_S",
    "RACON_TPU_ROUTE_TCP",
    # scatter/gather (r20): shard count is placement policy, never a
    # bytes decision — the shard mask only changes WHICH targets a
    # process emits, and concatenation in shard order is pinned
    # byte-identical to the unsharded run (target_slice contract)
    "RACON_TPU_SCATTER_MIN_WALL_S",
    "RACON_TPU_SCATTER_MAX_SHARDS",
    # r21: staged parsing is pinned byte-identical to the full parse
    # (tests/test_fastio.py fuzz + tests/test_scatter.py), and the
    # straggler factor only moves WHERE a shard's attempt runs
    "RACON_TPU_STAGE",
    "RACON_TPU_SCATTER_REBALANCE",
    # r22 closed control loop: affinity routing moves WHERE a job
    # runs, the adaptive fusion window moves WHEN a bucket
    # dispatches, drift epochs move WHEN rates recalibrate (per-job
    # pins keep in-flight jobs on their admission snapshot), and the
    # class knobs move ordering/admission — all pinned byte-identical
    # on/off (tests/test_control.py)
    "RACON_TPU_ROUTE_AFFINITY",
    "RACON_TPU_FUSE_ADAPT",
    "RACON_TPU_CALIB_DRIFT_EPOCH",
    "RACON_TPU_CLASS_TARGET_P99_S",
    "RACON_TPU_CLASS_HEADROOM",
    # r24 internal mapping: ONLY the placement/pricing knobs.  The
    # mapper's k/w/occ/min-chain/band/max-gap knobs change which
    # overlaps exist (bytes!) and deliberately stay IN the epoch.
    "RACON_TPU_MAP_DEVICE_SEED",
    "RACON_TPU_SERVE_MAP_MBPS",
})

DIGEST_SIZE = 32


def engine_epoch() -> bytes:
    """Fingerprint of the code + knob environment results depend on.

    Cheap (one env sweep + one small hash) but not free — batch call
    sites fetch it once per submission and pass it to the per-unit
    key functions below.
    """
    import racon_tpu
    from racon_tpu.obs import provenance

    h = hashlib.blake2b(digest_size=16)
    h.update(racon_tpu.__version__.encode())
    for name, info in sorted(provenance.resolved_knobs().items()):
        if name in EPOCH_EXCLUDE:
            continue
        h.update(b"\0%s=%s" % (name.encode(), info["value"].encode()))
    return h.digest()


def _h(tag: bytes, epoch: bytes):
    h = hashlib.blake2b(digest_size=DIGEST_SIZE)
    h.update(tag)
    h.update(epoch)
    return h


def _as_bytes(seq) -> bytes:
    if isinstance(seq, bytes):
        return seq
    if isinstance(seq, (bytearray, memoryview)):
        return bytes(seq)
    import numpy as np

    a = np.ascontiguousarray(seq)
    return a.dtype.str.encode() + a.tobytes()


def window_digest(window) -> bytes:
    """Canonical content digest of one Window: type + every layer's
    (sequence, quality, begin, end) in insertion order — which the
    WindowLedger already pins to overlap-ordinal order, so streamed
    and staged builds of the same window digest identically."""
    h = hashlib.blake2b(digest_size=DIGEST_SIZE)
    h.update(b"win1|%d|%d" % (int(window.type.value),
                              len(window.sequences)))
    for i, seq in enumerate(window.sequences):
        qual = window.qualities[i]
        begin, end = window.positions[i]
        h.update(struct.pack("<IIIi", len(seq),
                             len(qual) if qual else 0,
                             int(begin), int(end)))
        h.update(seq)
        if qual:
            h.update(qual)
    return h.digest()


def poa_key(space: str, cfg_key, trim: bool, window,
            epoch: bytes) -> bytes:
    """One POA window unit.  ``space`` separates the CPU engine from
    the device pipeline (distinct tie-breaking); ``cfg_key`` is the
    full engine-config tuple (the executor's fuse/engine key for the
    device space, (match, mismatch, gap) for the CPU engine)."""
    h = _h(b"poa|", epoch)
    h.update(space.encode())
    h.update(repr(cfg_key).encode())
    h.update(b"|t%d|" % int(bool(trim)))
    h.update(window_digest(window))
    return h.digest()


def wfa_key(query, target, lq: int, emax: int, mesh_key,
            epoch: bytes) -> bytes:
    """One WFA align pair: pair bytes + rung geometry (bucket dim,
    error cap) + mesh."""
    h = _h(b"wfa|", epoch)
    h.update(repr((int(lq), int(emax), mesh_key)).encode())
    q = _as_bytes(query)
    h.update(struct.pack("<I", len(q)))
    h.update(q)
    h.update(_as_bytes(target))
    return h.digest()


def band_key(query, target, lq: int, lt: int, wb: int, center,
             mesh_key, epoch: bytes) -> bytes:
    """One banded align pair: pair bytes + rung geometry (bucket
    dims, band width), the per-pair empirical center path when one
    is pinned, and the mesh."""
    h = _h(b"band|", epoch)
    h.update(repr((int(lq), int(lt), int(wb), mesh_key)).encode())
    if center is None:
        h.update(b"c0|")
    else:
        c = _as_bytes(center)
        h.update(b"c1|" + struct.pack("<I", len(c)))
        h.update(c)
    q = _as_bytes(query)
    h.update(struct.pack("<I", len(q)))
    h.update(q)
    h.update(_as_bytes(target))
    return h.digest()


def scan_key(query, target, blq: int, blt: int, need_ratio,
             epoch: bytes) -> bytes:
    """One CPU scan-ladder pair (band_align_batch): the ladder's
    per-pair result depends only on the pair bytes, the bucket dims
    and the probe need ratio — chunking and the memory budget only
    batch, they never change a lane's answer."""
    h = _h(b"scan|", epoch)
    h.update(repr((int(blq), int(blt),
                   round(float(need_ratio), 9))).encode())
    q = _as_bytes(query)
    h.update(struct.pack("<I", len(q)))
    h.update(q)
    h.update(_as_bytes(target))
    return h.digest()
