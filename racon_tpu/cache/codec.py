"""Self-describing value encoding for the result cache (r18).

The cache stores ENCODED blobs, not live objects: byte accounting is
then exact (the LRU budget bounds real memory), the persistent tier
appends the same bytes it holds in memory, and a decode round-trip
is the only thing a hit costs.  The format is a tiny tagged tree —
just enough for the unit-result shapes the polish pipeline produces:

* POA window unit:   ``(consensus_bytes | None, polished_bool)``
* WFA align pair:    ``(tape_row ndarray, n_entries, distance)``
* banded align pair: ``(moves_row ndarray, path_len, distance)``
* scan-ladder pair:  ``(lengths ndarray, codes ndarray)`` cigar runs
  or ``None`` for an unresolved lane

Tags: N=None T=True F=False I=int(le64) Y=bytes S=str(utf8)
A=ndarray(dtype-str + shape + raw bytes) L=sequence(decoded as a
tuple).  ``decode`` raises :class:`CodecError` on ANY malformed
input — a corrupt persistent frame must degrade to a miss, never to
wrong bytes (the caller treats the error as cache-miss).
"""

from __future__ import annotations

import struct

_U32 = struct.Struct("<I")
_I64 = struct.Struct("<q")


class CodecError(ValueError):
    """Blob does not decode cleanly; callers treat it as a miss."""


def _enc(value, out: list) -> None:
    if value is None:
        out.append(b"N")
    elif value is True:
        out.append(b"T")
    elif value is False:
        out.append(b"F")
    elif isinstance(value, int):
        out.append(b"I" + _I64.pack(value))
    elif isinstance(value, (bytes, bytearray, memoryview)):
        b = bytes(value)
        out.append(b"Y" + _U32.pack(len(b)) + b)
    elif isinstance(value, str):
        b = value.encode()
        out.append(b"S" + _U32.pack(len(b)) + b)
    elif isinstance(value, (tuple, list)):
        out.append(b"L" + _U32.pack(len(value)))
        for v in value:
            _enc(v, out)
    else:
        import numpy as np

        if isinstance(value, np.integer):
            out.append(b"I" + _I64.pack(int(value)))
            return
        a = np.ascontiguousarray(value)
        ds = a.dtype.str.encode()
        raw = a.tobytes()
        out.append(b"A" + _U32.pack(len(ds)) + ds
                   + _U32.pack(a.ndim)
                   + b"".join(_U32.pack(d) for d in a.shape)
                   + _U32.pack(len(raw)) + raw)


def encode(value) -> bytes:
    parts: list = []
    _enc(value, parts)
    return b"".join(parts)


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        if n < 0 or self.pos + n > len(self.buf):
            raise CodecError("truncated blob")
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return b

    def u32(self) -> int:
        return _U32.unpack(self.take(4))[0]


def _dec(r: _Reader):
    tag = r.take(1)
    if tag == b"N":
        return None
    if tag == b"T":
        return True
    if tag == b"F":
        return False
    if tag == b"I":
        return _I64.unpack(r.take(8))[0]
    if tag == b"Y":
        return r.take(r.u32())
    if tag == b"S":
        return r.take(r.u32()).decode()
    if tag == b"L":
        n = r.u32()
        if n > len(r.buf):
            raise CodecError("implausible sequence length")
        return tuple(_dec(r) for _ in range(n))
    if tag == b"A":
        import numpy as np

        ds = r.take(r.u32()).decode()
        ndim = r.u32()
        if ndim > 8:
            raise CodecError("implausible ndarray rank")
        shape = tuple(r.u32() for _ in range(ndim))
        raw = r.take(r.u32())
        try:
            a = np.frombuffer(raw, dtype=np.dtype(ds))
            # copy: frombuffer views are read-only, and consumers
            # (op-tape replay, run decoding) expect ordinary arrays
            return a.reshape(shape).copy()
        except (TypeError, ValueError) as exc:
            raise CodecError(f"bad ndarray blob: {exc}") from exc
    raise CodecError(f"unknown tag {tag!r}")


def decode(blob: bytes):
    r = _Reader(blob)
    value = _dec(r)
    if r.pos != len(blob):
        raise CodecError("trailing bytes after value")
    return value
