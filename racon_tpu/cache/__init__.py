"""Content-addressed unit-result cache (r18).

Byte-determinism — pinned since PR 3 and re-proven at every crash
site in PR 13 — makes memoization semantically free: an identical
(canonical input bytes, engine key, code epoch) work unit MUST
produce identical output bytes, so serving a cached result is
indistinguishable from recomputing it.  This package turns repeat
polish traffic (overlapping references across jobs, ``--split``
parts sharing contigs, ``--rounds N`` windows that already
converged) from a load problem into a lookup problem:

* :mod:`racon_tpu.cache.keying` — canonical digests per unit kind
  (POA window, WFA pair, banded pair, CPU scan pair) + the
  engine-code epoch that makes a knob change invalidate every key.
* :mod:`racon_tpu.cache.store`  — the byte-budgeted in-process LRU
  and the optional shared persistent segment tier.
* :mod:`racon_tpu.cache.codec`  — exact-size tagged value blobs.

Consulted at unit submit in the device executor
(racon_tpu/tpu/executor.py — hits demux immediately without
occupying megabatch slots), in the CPU scan ladder and in the staged
``core/polisher.py`` path, so the win exists on every backend.

Knobs (provenance.KNOWN_KNOBS):

* ``RACON_TPU_CACHE``          — "0" disables (default on)
* ``RACON_TPU_CACHE_MB``       — LRU byte budget in MB (default 256)
* ``RACON_TPU_CACHE_PERSIST``  — persistent tier: unset/"0" = off,
  "1" = ``<cache_root>/results`` under the RACON_TPU_CACHE_DIR root
  the XLA/AOT caches already share, any other value = that directory
* ``RACON_TPU_CACHE_DIR``      — the shared cache ROOT (pre-existing
  knob; also holds xla/, aot/, calibration.json)

Policy/observability never leak into bytes: a hit batch is excluded
from calibration measurement (the collect closures carry a
``cache_hits`` attribute the polishers gate recording on), and
cache-on/off/persistent outputs are pinned byte-identical in
tests/test_cache.py.
"""

from __future__ import annotations

import os
import threading

from racon_tpu.cache import keying  # noqa: F401  (re-export)
from racon_tpu.cache.store import MISS, ResultCache  # noqa: F401

_DEF_MB = 256.0
_MIN_BUDGET = 4096

_lock = threading.Lock()
_cache = None
_cfg = None


def enabled() -> bool:
    return os.environ.get("RACON_TPU_CACHE", "1") != "0"


def budget_bytes() -> int:
    try:
        mb = float(os.environ.get("RACON_TPU_CACHE_MB", "")
                   or _DEF_MB)
    except ValueError:
        mb = _DEF_MB
    return max(_MIN_BUDGET, int(mb * (1 << 20)))


def persist_dir():
    """Directory of the shared persistent tier, or None (off)."""
    v = os.environ.get("RACON_TPU_CACHE_PERSIST", "")
    if not v or v == "0":
        return None
    if v == "1":
        from racon_tpu.utils.xla_cache import cache_root

        root = cache_root()
        return os.path.join(root, "results") if root else None
    return v


def result_cache() -> ResultCache:
    """The process-wide cache, rebuilt when its config knobs change
    (tests flip budgets/persistence via the environment)."""
    global _cache, _cfg
    cfg = (budget_bytes(), persist_dir())
    with _lock:
        if _cache is None or cfg != _cfg:
            if _cache is not None:
                _cache.close()
            _cache = ResultCache(cfg[0], persist_dir=cfg[1])
            _cfg = cfg
        return _cache


def stats() -> dict:
    """The telemetry block served under ``cache`` in the daemon's
    ``metrics`` / ``health`` / ``explain`` frames."""
    if not enabled():
        return {"enabled": False}
    with _lock:
        live = _cache
    if live is None:
        return {"enabled": True, "entries": 0, "bytes": 0,
                "hits": 0, "misses": 0, "fills": 0, "evicts": 0,
                "hit_ratio": 0.0, "budget_bytes": budget_bytes()}
    return live.stats()


def sketch_doc():
    """Epoch-tagged digest-sketch export of the live cache, or None
    when the cache is disabled or not yet instantiated (a router
    treats an absent sketch as cold)."""
    if not enabled():
        return None
    with _lock:
        live = _cache
    return live.sketch_doc() if live is not None else None


def note_content(digest: bytes) -> None:
    """Mark a job-level content digest warm in the live cache's
    sketch (no-op when the cache is disabled)."""
    if not enabled():
        return
    result_cache().note_content(digest)


def _reset_for_tests() -> None:
    global _cache, _cfg
    with _lock:
        if _cache is not None:
            _cache.close()
        _cache = None
        _cfg = None
