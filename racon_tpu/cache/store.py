"""The result cache's two storage tiers (r18).

Tier 1 — in-process LRU.  An ``OrderedDict`` of encoded blobs under
one lock, byte-budgeted (``RACON_TPU_CACHE_MB``): inserting past the
budget evicts from the cold end.  Hits move to the hot end.  The
budget bounds the ENCODED payload bytes exactly (codec blobs, not
Python object overhead).

Tier 2 — optional shared persistent segments.  Append-only
``seg-<pid>.rseg`` files in a shared directory, length-prefixed the
same way the wire protocol / job journal frame records
(``u32BE length | body``), body = 32-byte key digest + crc32(u32BE)
+ blob.  The first frame of every segment is a JSON magic record
carrying ``schema: "racon-tpu-rcache-v1"``.  ``_scan_segments``
tolerates a torn tail exactly like ``serve/journal.scan`` — a crash
mid-append loses at most the frame being written — and every blob
read back is crc-checked and codec-validated, so corruption of any
shape degrades to a MISS, never to wrong bytes.  Segments are
per-pid so concurrent fleet daemons never interleave writes; each
process indexes every segment in the directory at open, which is
how restarts and fleet peers inherit each other's fills.

Counters (process registry, summed exactly by the fleet
aggregator): ``cache_hit`` / ``cache_miss`` / ``cache_fill`` /
``cache_evict``; gauges ``cache_hit_ratio`` and ``cache_bytes``.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from collections import OrderedDict

from racon_tpu.cache import codec, sketch
from racon_tpu.obs import REGISTRY

SCHEMA = "racon-tpu-rcache-v1"

_LEN = struct.Struct(">I")
_CRC = struct.Struct(">I")
#: refuse frames past this size on scan (a torn length prefix must
#: not make a restart try to allocate gigabytes)
FRAME_MAX = 1 << 30
_KEY_SIZE = 32

#: distinguished miss sentinel — ``None`` is a legitimate cached value
MISS = object()


class ResultCache:
    """One process's content-addressed result cache (both tiers)."""

    def __init__(self, budget_bytes: int, persist_dir=None):
        self.budget = max(0, int(budget_bytes))
        self.persist_dir = persist_dir
        self._lock = threading.Lock()
        self._lru: OrderedDict = OrderedDict()   # key -> blob
        self._bytes = 0
        self._hits = self._misses = 0
        self._fills = self._evicts = 0
        self._disk_hits = 0
        # persistent tier: key -> (path, offset, length, crc)
        self._pindex: dict = {}
        self._seg = None
        self._seg_path = None
        # digest sketch (r22): counting Bloom over every live key —
        # LRU ∪ persistent index ∪ job-level content digests — the
        # compact warmth summary the fleet router prices against.
        # Maintained under self._lock next to the structures it
        # mirrors; drift (saturation, content digests outliving their
        # units) only mis-prices placement, never bytes.
        self._sketch = sketch.DigestSketch()
        self._content_n = 0
        if persist_dir:
            try:
                os.makedirs(persist_dir, exist_ok=True)
                self._scan_segments()
            except OSError:
                self.persist_dir = None
        for key in self._pindex:
            self._sketch.add(key)

    # -- lookups -----------------------------------------------------------

    def get(self, key: bytes):
        """Decoded value for ``key``, or :data:`MISS`."""
        with self._lock:
            blob = self._lru.get(key)
            if blob is not None:
                self._lru.move_to_end(key)
                self._hits += 1
                self._note_lookup(hit=True)
                loc = None
            else:
                loc = self._pindex.get(key)
        if blob is None:
            if loc is not None:
                blob = self._read_segment(key, loc)
            if blob is None:
                with self._lock:
                    self._misses += 1
                    self._note_lookup(hit=False)
                return MISS
            with self._lock:
                self._hits += 1
                self._disk_hits += 1
                self._note_lookup(hit=True)
                self._insert(key, blob)
        try:
            return codec.decode(blob)
        except codec.CodecError:
            # never serve wrong bytes: drop the entry, report a miss
            with self._lock:
                dropped = self._lru.pop(key, None)
                if dropped is not None:
                    self._bytes -= len(dropped)
                if self._pindex.pop(key, None) is not None \
                        or dropped is not None:
                    self._sketch.discard(key)
                self._hits -= 1
                self._misses += 1
                self._note_lookup(hit=False)
            return MISS

    def put(self, key: bytes, value) -> None:
        """Fill ``key``; duplicate/racing fills keep the first entry."""
        try:
            blob = codec.encode(value)
        except Exception:
            return                      # uncacheable value: skip
        with self._lock:
            if key in self._lru or key in self._pindex:
                return
            self._insert(key, blob)
            self._fills += 1
        REGISTRY.add("cache_fill")
        self._append_segment(key, blob)

    # -- LRU internals (call under self._lock) -----------------------------

    def _insert(self, key: bytes, blob: bytes) -> None:
        if key in self._lru:
            return
        if self.budget and len(blob) > self.budget:
            return                      # larger than the whole budget
        self._lru[key] = blob
        self._bytes += len(blob)
        if key not in self._pindex:
            # pindex keys are already sketched (seed scan / append),
            # so a disk-hit promotion must not double-count its key
            self._sketch.add(key)
        while self.budget and self._bytes > self.budget and \
                len(self._lru) > 1:
            old_key, old = self._lru.popitem(last=False)
            self._bytes -= len(old)
            self._evicts += 1
            if old_key not in self._pindex:
                # still reachable through the persistent tier = still
                # warm for placement purposes; only a full departure
                # leaves the sketch
                self._sketch.discard(old_key)
            REGISTRY.add("cache_evict")
        REGISTRY.set("cache_bytes", self._bytes)

    def _note_lookup(self, hit: bool) -> None:
        REGISTRY.add("cache_hit" if hit else "cache_miss")
        total = self._hits + self._misses
        if total:
            REGISTRY.set("cache_hit_ratio",
                         round(self._hits / total, 4))

    # -- persistent tier ---------------------------------------------------

    def _scan_segments(self) -> None:
        """Index every intact frame of every segment in the shared
        directory (our own past incarnations AND fleet peers).  Stops
        at the first torn/corrupt frame of each file."""
        try:
            names = sorted(n for n in os.listdir(self.persist_dir)
                           if n.endswith(".rseg"))
        except OSError:
            return
        for name in names:
            path = os.path.join(self.persist_dir, name)
            try:
                f = open(path, "rb")
            except OSError:
                continue
            with f:
                first = True
                while True:
                    head = f.read(_LEN.size)
                    if len(head) < _LEN.size:
                        break
                    (n,) = _LEN.unpack(head)
                    if n > FRAME_MAX:
                        break
                    body = f.read(n)
                    if len(body) < n:
                        break
                    if first:
                        first = False
                        try:
                            magic = json.loads(body)
                        except ValueError:
                            break
                        if not (isinstance(magic, dict)
                                and magic.get("schema") == SCHEMA):
                            break
                        continue
                    if n < _KEY_SIZE + _CRC.size:
                        break
                    key = body[:_KEY_SIZE]
                    (crc,) = _CRC.unpack(
                        body[_KEY_SIZE:_KEY_SIZE + _CRC.size])
                    off = f.tell() - n + _KEY_SIZE + _CRC.size
                    self._pindex.setdefault(
                        key, (path, off, n - _KEY_SIZE - _CRC.size,
                              crc))

    def _read_segment(self, key: bytes, loc):
        """Blob for an indexed key, crc-verified; any failure drops
        the index entry and returns None (a miss)."""
        path, off, length, crc = loc
        try:
            with open(path, "rb") as f:
                f.seek(off)
                blob = f.read(length)
        except OSError:
            blob = b""
        if len(blob) != length or zlib.crc32(blob) != crc:
            with self._lock:
                self._pindex.pop(key, None)
            return None
        return blob

    def _append_segment(self, key: bytes, blob: bytes) -> None:
        if not self.persist_dir:
            return
        with self._lock:
            try:
                if self._seg is None:
                    self._seg_path = os.path.join(
                        self.persist_dir,
                        f"seg-{os.getpid()}.rseg")
                    self._seg = open(self._seg_path, "ab")
                    if not self._seg.tell():
                        magic = json.dumps(
                            {"schema": SCHEMA, "pid": os.getpid()},
                            separators=(",", ":")).encode()
                        self._seg.write(
                            _LEN.pack(len(magic)) + magic)
                body = key + _CRC.pack(zlib.crc32(blob)) + blob
                self._seg.write(_LEN.pack(len(body)) + body)
                self._seg.flush()
                off = self._seg.tell() - len(blob)
                self._pindex.setdefault(
                    key, (self._seg_path, off, len(blob),
                          zlib.crc32(blob)))
            except OSError:
                # persistence is an optimization; never fail the run
                try:
                    if self._seg is not None:
                        self._seg.close()
                except OSError:
                    pass
                self._seg = None
                self.persist_dir = None

    # -- digest sketch (r22) -----------------------------------------------

    def note_content(self, digest: bytes) -> None:
        """Record a job-level content digest (serve/affinity.py
        ``job_digest_sample``) as warm: the router derives the same
        digests from a submit's input files and scores them against
        this sketch.  Content digests are never discarded (they do
        not map 1:1 to evictable entries); a long-lived daemon's
        sketch therefore over-reports old content — a placement
        mis-pricing that decays as jobs churn, never a bytes risk."""
        with self._lock:
            self._sketch.add(digest)
            self._content_n += 1

    def sketch_doc(self) -> dict:
        """The epoch-tagged wire export of the digest sketch (see
        :mod:`racon_tpu.cache.sketch`)."""
        from racon_tpu.cache import keying

        epoch_hex = keying.engine_epoch().hex()
        with self._lock:
            n = len(self._lru) + len(self._pindex) + self._content_n
            return self._sketch.export(epoch_hex, n)

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            total = self._hits + self._misses
            doc = {
                "enabled": True,
                "entries": len(self._lru),
                "bytes": self._bytes,
                "budget_bytes": self.budget,
                "hits": self._hits,
                "misses": self._misses,
                "fills": self._fills,
                "evicts": self._evicts,
                "disk_hits": self._disk_hits,
                "hit_ratio": (round(self._hits / total, 4)
                              if total else 0.0),
                "sketch_adds": self._sketch.adds,
                "sketch_drops": self._sketch.drops,
                "sketch_content": self._content_n,
            }
            if self.persist_dir:
                doc["persist"] = {"dir": self.persist_dir,
                                  "indexed": len(self._pindex)}
            return doc

    def close(self) -> None:
        with self._lock:
            if self._seg is not None:
                try:
                    self._seg.close()
                except OSError:
                    pass
                self._seg = None
