"""Shard-aware input staging (r21): the one-pass slice index.

A scattered sub-job (racon_tpu/serve/scatter.py) owns one
``target_slice`` shard of the targets, yet before r21 it parsed the
ENTIRE overlaps file and dropped (K-1)/K of the rows after transmute —
the redundant per-shard parse was the dominant serial term in
``route_scatter_efficiency``.  This module builds, in one pass over
the fastio line table, an index from target-shard to the line/byte
ranges of the overlaps file that can contribute rows to that shard, so
shard i mmaps the same file but materializes only its slice
(``_OverlapScanParser.set_stage`` in racon_tpu/io/fastio.py).

Correctness contract — byte-identity with the full parse for owned
targets rests on how ``Polisher._load_overlaps`` filters
(racon_tpu/core/polisher.py): ``remove_invalid`` (error threshold,
self-overlap, and kC's longest-per-query) operates over CONTIGUOUS
same-``q_id`` runs, and the ownership-mask drop happens strictly
AFTER it.  Three rules make the staged stream indistinguishable:

* selection is by whole query-run, never by row: a run (maximal
  contiguous stretch of lines sharing PAF column 0) is staged iff it
  touches at least one owned target, so longest-per-query sees the
  same candidate set it would in the full parse;
* run boundaries are preserved: if dropping the runs between two
  staged runs would make two same-name runs adjacent (the cursor in
  ``_load_overlaps`` would fuse them), the separator run right after
  the first is staged too — its rows transmute, filter, and then die
  on the ownership mask exactly as in the full parse;
* rows nobody can own are staged everywhere: a run referencing an
  unknown target name is selected for every shard, so its
  invalid-marking (or its diagnostics) surface identically.

The index is refused (``build_index`` returns ``None`` -> full-parse
fallback) whenever any row would NOT survive the strict column checks
below — a malformed row must raise the line parser's exact
``path:line`` diagnostics, and the cheapest way to guarantee that is
to not stage at all.  v1 indexes PAF only (``.paf``/``.paf.gz``;
query name = column 0, target name = column 5); MHAP/SAM fall back to
full parse.

Staging is policy, never bytes: ``RACON_TPU_STAGE`` (default on; =0
restores the full parse everywhere) is in the cache's EPOCH_EXCLUDE
set and the record stream for owned targets is pinned byte-identical
by tests/test_fastio.py + tests/test_scatter.py.
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import Dict, List, Optional

import numpy as np


def stage_enabled() -> bool:
    """RACON_TPU_STAGE selects ranged scanning for target-sharded
    parses (default on); "0" is the escape hatch back to the full
    parse.  Read per use so tests can flip it between polishes."""
    return os.environ.get("RACON_TPU_STAGE", "1") != "0"


#: extensions the v1 index understands (PAF only)
_PAF_EXTENSIONS = (".paf", ".paf.gz")


def fasta_names(path: str) -> List[str]:
    """Target names in file order — the exact ``Sequence.name`` rule
    (first whitespace-separated token of the header), read from the
    fastio header-line table without joining any sequence data.
    Raises on unreadable/undecodable headers; callers treat any
    exception as "cannot stage"."""
    from racon_tpu.io import fastio

    p = fastio.FastaScanParser(path)
    try:
        p._ensure_index()
        names = []
        for h in p._hdr_lines.tolist():
            header = bytes(p._buf[int(p._starts[h]) + 1:
                                  int(p._ends[h])])
            parts = header.split()
            names.append(parts[0].decode() if parts else "")
        return names
    finally:
        p.close()


class StageIndex:
    """Per-(overlaps, targets) slice index: query-runs with their
    line/byte extents and touched target ids.  Built once, answers
    ``ranges_for(mask)`` for every shard of the plan."""

    def __init__(self, path: str, sig: List[int], total_lines: int,
                 total_bytes: int):
        self.path = path
        self.sig = sig                    # [st_size, st_mtime_ns]
        self.total_lines = total_lines
        self.total_bytes = total_bytes    # decompressed buffer size
        self.run_lo: List[int] = []       # first line index of run
        self.run_hi: List[int] = []       # one past last line index
        self.run_blo: List[int] = []      # raw byte extent (buffer
        self.run_bhi: List[int] = []      # coordinates for .gz)
        self.run_q: List[bytes] = []      # the run's query name
        #: per run: sorted target-id tuple, or None = stage everywhere
        #: (a row referenced a target name outside the target set)
        self.run_targets: List[Optional[tuple]] = []

    def ranges_for(self, mask) -> dict:
        """Merged ``[line_lo, line_hi)`` ranges for the shard owning
        the ``True`` targets of ``mask``, plus the staged/total byte
        and line accounting the pricing and telemetry satellites
        consume."""
        owned = {i for i, m in enumerate(mask) if m}
        picked: List[int] = []
        prev = None
        for ri in range(len(self.run_lo)):
            ts = self.run_targets[ri]
            if ts is not None and owned.isdisjoint(ts):
                continue
            if prev is not None and ri > prev + 1 \
                    and self.run_q[ri] == self.run_q[prev]:
                # dropping the gap would fuse two same-query runs in
                # the staged stream; keep the separator run so
                # _load_overlaps sees the same run boundaries
                picked.append(prev + 1)
            picked.append(ri)
            prev = ri
        ranges: List[List[int]] = []
        extents: List[List[int]] = []
        staged_lines = 0
        reads = set()
        for ri in picked:
            reads.add(self.run_q[ri])
            if ranges and ranges[-1][1] == self.run_lo[ri]:
                ranges[-1][1] = self.run_hi[ri]
                extents[-1][1] = self.run_bhi[ri]
            else:
                ranges.append([self.run_lo[ri], self.run_hi[ri]])
                extents.append([self.run_blo[ri], self.run_bhi[ri]])
            staged_lines += self.run_hi[ri] - self.run_lo[ri]
        staged_bytes = sum(b[1] - b[0] for b in extents)
        return {"ranges": ranges,
                "staged_bytes": staged_bytes,
                "total_bytes": self.total_bytes,
                "staged_lines": staged_lines,
                "total_lines": self.total_lines,
                "reads": len(reads)}


def _file_sig(path: str) -> List[int]:
    st = os.stat(path)
    return [int(st.st_size), int(st.st_mtime_ns)]


def build_index(path: str, target_names: List[str]) \
        -> Optional[StageIndex]:
    """One pass over the overlaps file -> :class:`StageIndex`, or
    ``None`` whenever staging cannot be exact (non-PAF extension, a
    row that fails the strict column checks, undecodable names): the
    caller then runs the unchanged full parse, so malformed input
    keeps its exact line-parser diagnostics."""
    if not path.endswith(_PAF_EXTENSIONS):
        return None
    from racon_tpu.io import fastio

    try:
        sig = _file_sig(path)
        scan = fastio._ScanParserBase(path)
    except (OSError, FileNotFoundError):
        return None
    # same later-wins rule as Polisher.initialize's name_to_id
    tmap: Dict[str, int] = {n: i for i, n in enumerate(target_names)}
    try:
        try:
            scan._ensure_scanned()
        except OSError:
            return None
        s, e, rawnext = scan._starts, scan._ends, scan._rawnext
        buf = scan._buf
        idx = StageIndex(path, sig, int(s.size), scan._size)
        lines = np.flatnonzero(e > s).tolist()
        s_l, e_l, rn_l = s.tolist(), e.tolist(), rawnext.tolist()
        cur_q = None
        cur_targets: Optional[set] = set()
        run_lo = run_blo = 0

        def flush(hi_line: int, bhi: int) -> None:
            idx.run_lo.append(run_lo)
            idx.run_hi.append(hi_line)
            idx.run_blo.append(run_blo)
            idx.run_bhi.append(bhi)
            idx.run_q.append(cur_q)
            idx.run_targets.append(
                None if cur_targets is None
                else tuple(sorted(cur_targets)))

        prev_line = None
        for i in lines:
            line = bytes(buf[s_l[i]:e_l[i]])
            f = line.split(b"\t")
            if len(f) < 9:
                return None
            try:
                # the exact fields Overlap.from_paf converts: any row
                # int()/.decode() would reject must not be skippable
                int(f[1]); int(f[2]); int(f[3])          # noqa: E702
                int(f[6]); int(f[7]); int(f[8])          # noqa: E702
                f[0].decode()
                t_name = f[5].decode()
            except (ValueError, UnicodeDecodeError):
                return None
            q = f[0]
            if q != cur_q:
                if prev_line is not None:
                    flush(prev_line + 1, rn_l[prev_line])
                cur_q = q
                cur_targets = set()
                run_lo, run_blo = i, s_l[i]
            tid = tmap.get(t_name)
            if cur_targets is not None:
                if tid is None:
                    cur_targets = None    # unowned: stage everywhere
                else:
                    cur_targets.add(tid)
            prev_line = i
        if prev_line is not None:
            flush(prev_line + 1, rn_l[prev_line])
        return idx
    finally:
        scan.close()


#: small in-process memo: plan-time router builds and per-shard
#: self-builds of the same (overlaps, targets) pair share one index
_MEMO: Dict[tuple, Optional[StageIndex]] = {}
_MEMO_LOCK = threading.Lock()
_MEMO_CAP = 8


def get_index(path: str, target_names: List[str]) \
        -> Optional[StageIndex]:
    """Memoized :func:`build_index`: keyed by the overlaps file's
    identity (realpath + size + mtime) and a digest of the target
    name order (the tid mapping).  A changed file re-keys, so a stale
    index is never served."""
    try:
        sig = _file_sig(path)
        names_digest = hashlib.blake2b(
            "\n".join(target_names).encode(), digest_size=16).hexdigest()
        key = (os.path.realpath(path), sig[0], sig[1], names_digest)
    except (OSError, UnicodeEncodeError):
        return None
    with _MEMO_LOCK:
        if key in _MEMO:
            return _MEMO[key]
    idx = build_index(path, target_names)
    with _MEMO_LOCK:
        if len(_MEMO) >= _MEMO_CAP:
            _MEMO.pop(next(iter(_MEMO)))
        _MEMO[key] = idx
    return idx


def shard_hint(index: StageIndex, shard, n_targets: int) -> dict:
    """The ``spec["stage"]`` document the router ships with a
    fanned-out sub-job: the shard's merged line ranges plus the
    byte/line/read accounting, self-describing enough for the
    receiving daemon to validate (path + file signature + shard
    coordinates) before trusting it."""
    from racon_tpu.parallel import multihost

    index_i, count = shard
    sl = multihost.target_slice(n_targets, count, index_i)
    mask = [sl.start <= t < sl.stop for t in range(n_targets)]
    plan = index.ranges_for(mask)
    plan.update({"v": 1, "format": "paf", "path": index.path,
                 "sig": list(index.sig),
                 "shard": [int(index_i), int(count)]})
    return plan


def plan_from_hint(hint, path: str, shard) -> Optional[dict]:
    """Validate a shipped ``spec["stage"]`` hint against THIS
    daemon's view of the input: same file (realpath + size + mtime),
    same shard coordinates, sane ranges.  Any mismatch returns
    ``None`` — the polisher then self-builds or falls back to the
    full parse; a stale hint must never stage the wrong slice."""
    if not isinstance(hint, dict) or hint.get("v") != 1 \
            or hint.get("format") != "paf":
        return None
    try:
        if list(map(int, hint.get("shard") or [])) \
                != [int(x) for x in (shard or [])]:
            return None
        if os.path.realpath(str(hint["path"])) != os.path.realpath(path):
            return None
        if [int(x) for x in hint["sig"]] != _file_sig(path):
            return None
        ranges = [[int(lo), int(hi)] for lo, hi in hint["ranges"]]
    except (KeyError, TypeError, ValueError, OSError):
        return None
    prev = 0
    for lo, hi in ranges:
        if lo < prev or hi < lo:
            return None
        prev = hi
    out = {"ranges": ranges}
    for k in ("staged_bytes", "total_bytes", "staged_lines",
              "total_lines", "reads"):
        try:
            out[k] = int(hint.get(k, 0))
        except (TypeError, ValueError):
            out[k] = 0
    return out


def validate_stage_field(stage) -> Optional[str]:
    """Schema check for a submitted ``spec["stage"]`` (the scheduler
    rejects malformed ones up front as ``bad_request`` rather than
    failing mid-parse).  Returns an error string or ``None``."""
    if not isinstance(stage, dict):
        return "stage must be an object"
    if stage.get("v") != 1:
        return "stage.v must be 1"
    if not isinstance(stage.get("path"), str):
        return "stage.path must be a string"
    ranges = stage.get("ranges")
    if not isinstance(ranges, list):
        return "stage.ranges must be a list"
    for r in ranges:
        if not (isinstance(r, (list, tuple)) and len(r) == 2
                and all(isinstance(x, int) and x >= 0 for x in r)):
            return "stage.ranges entries must be [lo, hi] int pairs"
    return None
