from racon_tpu.io.parsers import (  # noqa: F401
    FastaParser,
    FastqParser,
    MhapParser,
    PafParser,
    SamParser,
    create_sequence_parser,
    create_overlap_parser,
)
