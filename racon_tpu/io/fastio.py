"""Vectorized zero-copy parsers (the RACON_TPU_FAST_IO ingest path).

The line parsers in :mod:`racon_tpu.io.parsers` walk files one Python
string at a time — on the mega bench that loop IS the parse wall.  The
scan parsers here read the whole file once (mmap for plain files, one
``gzip.decompress`` for compressed ones), build a line-offset table
with a single numpy newline scan, and parse record fields in batched
vector passes; only record CONSTRUCTION remains per-row Python.

Contract: byte-for-byte the same record stream, chunking behavior, and
error diagnostics as the line parsers (tests/test_fastio.py pins the
equivalence over the sample data and edge-case fuzz inputs; the
factories in parsers.py select between the two via RACON_TPU_FAST_IO,
default on).  Two rules keep that equivalence cheap to maintain:

* chunk boundaries are computed from the same "raw bytes consumed"
  arithmetic the line parsers use (including their quirks: FASTA does
  not count prelude lines, the overlap parsers do not count blank
  lines);
* any row the vector pass cannot answer for bit-exactly (non-digit
  int field, missing columns, non-ASCII strand byte, >18-digit run
  length) falls back to the line parser's ``record_from_line`` for
  that row, which reproduces both tolerant parses and the exact
  exception text of malformed input.
"""

from __future__ import annotations

import gzip
import mmap
import os
from typing import List, Optional

import numpy as np

from racon_tpu.core.overlap import (InvalidInputError, Overlap,
                                    _sam_run_fields,
                                    parse_cigar_runs_batch)
from racon_tpu.core.sequence import Sequence
# one-way import: parsers.py only reaches back here lazily inside its
# factory functions, so this cannot cycle
from racon_tpu.io import parsers as _line

#: missing-column sentinel: larger than any file offset, small enough
#: that sentinel arithmetic (+1, +18) stays inside int64
_BIG = np.int64(2) ** 62

#: per-call vector block bounds: line count and summed line bytes (the
#: SAM path expands CIGAR columns ~8x, so the byte bound dominates)
_BLOCK_LINES = 65536
_BLOCK_BYTES = 8_000_000


class _ScanParserBase:
    """Whole-buffer loader + numpy line table shared by every scan
    parser.  ``reset`` drops the buffer so the next parse re-reads the
    file (matching the line parsers' close-and-reopen)."""

    format_label = "Scan"

    def __init__(self, path: str):
        if not os.path.isfile(path):
            raise FileNotFoundError(path)
        self.path = path
        self._mm = None
        self._buf = None
        self._arr: Optional[np.ndarray] = None
        self._starts: Optional[np.ndarray] = None
        self._ends: Optional[np.ndarray] = None
        self._rawnext: Optional[np.ndarray] = None
        self._size = 0
        self._post_reset()

    def reset(self) -> None:
        self._release()
        self._post_reset()

    def close(self) -> None:
        self._release()

    def _post_reset(self) -> None:
        """Per-parser cursor state; overridden."""

    def _release(self) -> None:
        self._arr = None
        self._starts = None
        self._ends = None
        self._rawnext = None
        self._buf = None
        mm, self._mm = self._mm, None
        if mm is not None:
            try:
                mm.close()
            except (BufferError, ValueError):
                pass   # a live numpy view defers the unmap to GC

    def _ensure_scanned(self) -> None:
        if self._arr is not None:
            return
        with open(self.path, "rb") as fh:
            magic = fh.read(2)
        if magic == b"\x1f\x8b":
            with open(self.path, "rb") as fh:
                self._buf = gzip.decompress(fh.read())
        else:
            with open(self.path, "rb") as fh:
                if os.fstat(fh.fileno()).st_size:
                    self._mm = mmap.mmap(fh.fileno(), 0,
                                         access=mmap.ACCESS_READ)
                    self._buf = self._mm
                else:
                    self._buf = b""
        arr = np.frombuffer(self._buf, dtype=np.uint8)
        self._arr = arr
        self._size = int(arr.size)
        nl = np.flatnonzero(arr == 10).astype(np.int64)
        starts = np.concatenate(([0], nl + 1))
        raw_ends = np.concatenate((nl, [self._size]))
        if starts.size and starts[-1] == self._size:
            # file ends in a newline: no phantom final line
            starts = starts[:-1]
            raw_ends = raw_ends[:-1]
        # logical line ends strip the trailing \r run (CRLF files; one
        # pass per \r of the longest run, i.e. 2 passes for CRLF)
        ends = raw_ends.copy()
        while True:
            has_cr = (ends > starts) & \
                (arr[np.maximum(ends - 1, 0)] == 13)
            if not has_cr.any():
                break
            ends = ends - has_cr
        self._starts = starts
        self._ends = ends
        rawnext = np.empty(starts.size, dtype=np.int64)
        if starts.size:
            rawnext[:-1] = starts[1:]
            rawnext[-1] = self._size
        self._rawnext = rawnext

    def _line(self, idx: int) -> bytes:
        """Logical (stripped) bytes of line ``idx``."""
        return bytes(self._buf[int(self._starts[idx]):
                               int(self._ends[idx])])


def _gather(table: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """``table[idx]`` with out-of-range entries mapped to the missing
    sentinel (columns a short line does not have)."""
    if table.size == 0:
        return np.full(idx.shape, _BIG, dtype=np.int64)
    return np.where(idx < table.size,
                    table[np.minimum(idx, table.size - 1)], _BIG)


def _parse_int_matrix(arr: np.ndarray, fs: np.ndarray, fe: np.ndarray):
    """Parse an (n, k) matrix of byte spans as base-10 ints via a
    right-aligned digit matrix.  Rows with an empty field, a field
    over 18 digits, or any non-digit byte are flagged bad — the caller
    re-parses those lines in Python, which both accepts the forms
    ``int()`` tolerates (signs, surrounding whitespace) and reproduces
    exact error text for truly malformed input."""
    widths = fe - fs
    bad = (widths <= 0).any(axis=1) | (widths > 18).any(axis=1)
    width = int(min(max(int(widths.max(initial=1)), 1), 18))
    cols = fe[..., None] - width + np.arange(width, dtype=np.int64)
    in_field = cols >= fs[..., None]
    digits = arr[np.clip(cols, 0, arr.size - 1)].astype(np.int64) - 48
    bad |= ~(((digits >= 0) & (digits <= 9)) | ~in_field).all(
        axis=(1, 2))
    vals = np.where(in_field, digits, 0) @ \
        (10 ** np.arange(width - 1, -1, -1, dtype=np.int64))
    return vals, bad


class FastaScanParser(_ScanParserBase):
    """Multi-line FASTA over the line table: headers are the nonempty
    lines starting with '>', each record's data is the join of the
    stripped lines up to the next header."""

    format_label = "Fasta"

    def _post_reset(self) -> None:
        self._next_rec = 0
        self._base_line: Optional[int] = None  # where byte counting starts
        self._hdr_lines: Optional[np.ndarray] = None

    def _ensure_index(self) -> None:
        if self._hdr_lines is not None:
            return
        self._ensure_scanned()
        s, e = self._starts, self._ends
        hdr = np.zeros(s.size, dtype=bool)
        nonempty = np.flatnonzero(e > s)
        hdr[nonempty] = self._arr[s[nonempty]] == 62
        self._hdr_lines = np.flatnonzero(hdr)

    def parse(self, dst: List[Sequence], max_bytes: int) -> bool:
        self._ensure_index()
        hdrs = self._hdr_lines
        rec = self._next_rec
        if rec >= hdrs.size:
            return False
        s, e = self._starts, self._ends
        n_lines = s.size
        if max_bytes < 0:
            stop = int(hdrs.size)
        else:
            # the line parser counts raw bytes from the first header
            # it sees (prelude lines are skipped uncounted) and stops
            # at the first LATER header once over budget
            base_line = (self._base_line if self._base_line is not None
                         else int(hdrs[rec]))
            base = int(s[base_line]) if base_line < n_lines \
                else self._size
            consumed_at = s[hdrs[rec + 1:]] - base
            stop = rec + 1 + int(np.searchsorted(consumed_at, max_bytes,
                                                 side="left"))
        buf = self._buf
        s_l, e_l = s, e
        for j in range(rec, stop):
            h = int(hdrs[j])
            header = bytes(buf[int(s_l[h]) + 1:int(e_l[h])])
            lo = h + 1
            hi = int(hdrs[j + 1]) if j + 1 < hdrs.size else n_lines
            if hi == lo + 1:
                data = bytes(buf[int(s_l[lo]):int(e_l[lo])])
            else:
                data = b"".join(buf[int(s_l[k]):int(e_l[k])]
                                for k in range(lo, hi))
            dst.append(Sequence.from_fasta(header, data))
        if stop < hdrs.size:
            self._next_rec = stop
            self._base_line = int(hdrs[stop]) + 1
            return True
        self._next_rec = int(hdrs.size)
        return False


class FastqScanParser(_ScanParserBase):
    """FASTQ with possibly line-wrapped data/quality sections.  The
    record state machine stays in Python (it is inherently
    sequential: the quality section's extent depends on the data
    length) but runs over plain-int offset tables, not file reads."""

    format_label = "Fastq"

    def _post_reset(self) -> None:
        self._cursor = 0
        self._tab = None

    def _ensure_index(self) -> None:
        if self._tab is not None:
            return
        self._ensure_scanned()
        s, e = self._starts, self._ends
        first = np.full(s.size, -1, dtype=np.int64)
        nonempty = np.flatnonzero(e > s)
        first[nonempty] = self._arr[s[nonempty]]
        self._tab = (s.tolist(), e.tolist(), first.tolist(),
                     self._rawnext.tolist())

    def parse(self, dst: List[Sequence], max_bytes: int) -> bool:
        self._ensure_index()
        s, e, first, rawnext = self._tab
        n = len(s)
        i = self._cursor
        if i >= n:
            return False
        budget = max_bytes if max_bytes >= 0 else float("inf")
        consumed = 0
        buf = self._buf
        while i < n:
            h = i
            consumed += rawnext[i] - s[i]
            i += 1
            if first[h] != 64:      # not an '@' header line
                continue
            data_lines: List[int] = []
            data_len = 0
            while i < n:
                consumed += rawnext[i] - s[i]
                if first[i] == 43:  # '+' separator (consumed)
                    i += 1
                    break
                data_lines.append(i)
                data_len += e[i] - s[i]
                i += 1
            qual_lines: List[int] = []
            qual_len = 0
            while qual_len < data_len and i < n:
                consumed += rawnext[i] - s[i]
                qual_lines.append(i)
                qual_len += e[i] - s[i]
                i += 1
            dst.append(Sequence.from_fastq(
                buf[s[h] + 1:e[h]],
                b"".join(buf[s[k]:e[k]] for k in data_lines),
                b"".join(buf[s[k]:e[k]] for k in qual_lines)))
            if consumed >= budget:
                self._cursor = i
                return True
        self._cursor = i
        return False


class _OverlapScanParser(_ScanParserBase):
    """Shared chunking + per-row fallback for the overlap formats.

    r21 staged scanning (racon_tpu/io/staging.py): ``set_stage``
    restricts record MATERIALIZATION to the given line ranges while
    the budget/chunk arithmetic keeps counting every line exactly as
    before — chunk boundaries, the parse() return value, and the
    global line numbering in malformed-row diagnostics are identical
    to the full parse; only rows outside the ranges are skipped (and
    accounted in ``stage_skipped_bytes``)."""

    #: the matching line parser class; supplies ``record_from_line``
    line_parser = None

    def _post_reset(self) -> None:
        self._cursor = 0
        self._stage_mask = None
        self.stage_skipped_bytes = 0
        if not hasattr(self, "_stage"):
            #: configured line ranges; survives reset() — staging is
            #: parser configuration, not per-parse cursor state
            self._stage = None

    def set_stage(self, ranges) -> None:
        """Materialize records only for lines inside the ``[lo, hi)``
        ranges (ascending, non-overlapping).  ``None`` restores the
        full parse.  Line indices count PHYSICAL lines of the
        (decompressed) buffer, the same table the budget walks."""
        self._stage = (None if ranges is None else
                       [(int(lo), int(hi)) for lo, hi in ranges])
        self._stage_mask = None

    def _select_rows(self, a: int, b: int):
        """The nonempty rows of lines [a, b) that the stage admits,
        with skipped (nonempty, out-of-range) bytes accounted."""
        s, e = self._starts[a:b], self._ends[a:b]
        rows = np.flatnonzero(e > s)
        if self._stage is not None and rows.size:
            if self._stage_mask is None:
                m = np.zeros(self._starts.size, dtype=bool)
                for lo, hi in self._stage:
                    m[lo:hi] = True
                self._stage_mask = m
            keep = self._stage_mask[a:b][rows]
            dropped = rows[~keep]
            if dropped.size:
                self.stage_skipped_bytes += int(
                    (self._rawnext[a:b][dropped] - s[dropped]).sum())
            rows = rows[keep]
        return s, e, rows

    def parse(self, dst: List[Overlap], max_bytes: int) -> bool:
        self._ensure_scanned()
        n = self._starts.size
        i0 = self._cursor
        if i0 >= n:
            return False
        if max_bytes < 0:
            i1, more = n, False
        else:
            # stop AFTER the first nonempty line that crosses the
            # budget; blank lines are skipped uncounted, exactly like
            # the line parser's consumed arithmetic
            s = self._starts[i0:]
            nonempty = self._ends[i0:] > s
            cum = np.cumsum(np.where(nonempty,
                                     self._rawnext[i0:] - s, 0))
            over = np.flatnonzero(nonempty & (cum >= max_bytes))
            if over.size:
                i1, more = i0 + int(over[0]) + 1, True
            else:
                i1, more = n, False
        self._cursor = i1
        # vector passes run over bounded blocks: the field matrices
        # (and the SAM path's expanded CIGAR columns) scale with the
        # block, not the file
        csum = np.cumsum(self._rawnext[i0:i1] - self._starts[i0:i1])
        j = i0
        while j < i1:
            base = int(csum[j - i0 - 1]) if j > i0 else 0
            k = i0 + int(np.searchsorted(csum, base + _BLOCK_BYTES)) + 1
            k = max(j + 1, min(i1, k, j + _BLOCK_LINES))
            self._parse_lines(dst, j, k)
            j = k
        return more

    def _parse_lines(self, dst: List[Overlap], a: int, b: int) -> None:
        raise NotImplementedError

    def _fallback_line(self, dst: List[Overlap], line_idx: int) -> None:
        """Parse one line through the line parser's record factory —
        the escape hatch for rows the vector pass flagged, reproducing
        tolerant parses and exact malformed-input diagnostics."""
        try:
            record = self.line_parser.record_from_line(
                self._line(line_idx))
        except (IndexError, ValueError, UnicodeDecodeError) as exc:
            raise self._malformed(line_idx, exc) from exc
        if record is not None:
            dst.append(record)

    def _malformed(self, line_idx: int, exc: Exception):
        return _line.MalformedInputError(
            f"{self.path}:{line_idx + 1}: malformed "
            f"{self.format_label} record ({exc})")


class PafScanParser(_OverlapScanParser):
    """PAF: 9 leading tab-separated columns; extra columns ignored."""

    format_label = "Paf"
    line_parser = _line.PafParser

    def _parse_lines(self, dst: List[Overlap], a: int, b: int) -> None:
        s, e, rows = self._select_rows(a, b)
        if rows.size == 0:
            return
        ls, le = s[rows], e[rows]
        arr = self._arr
        lo, hi = int(ls[0]), int(le[-1])
        seg = arr[lo:hi]
        tabs = np.flatnonzero(seg == 9).astype(np.int64) + lo
        t0 = np.searchsorted(tabs, ls)
        tab8 = _gather(tabs, t0[:, None] + np.arange(8, dtype=np.int64))
        has9 = tab8[:, 7] < le           # tabs sorted: implies all 8
        tab_after = _gather(tabs, (t0 + 8)[:, None])[:, 0]
        fs = np.empty((ls.size, 9), np.int64)
        fe = np.empty_like(fs)
        fs[:, 0] = ls
        fs[:, 1:] = np.minimum(tab8, _BIG - 2) + 1
        fe[:, :8] = tab8
        fe[:, 8] = np.where(tab_after < le, tab_after, le)
        ints, int_bad = _parse_int_matrix(
            arr, fs[:, (1, 2, 3, 6, 7, 8)], fe[:, (1, 2, 3, 6, 7, 8)])
        # strand: a one-byte '+'/'-' column; any non-ASCII byte there
        # could change .decode() semantics -> per-line fallback
        ascii_cum = np.concatenate(
            ([0], np.cumsum((seg >= 128).astype(np.int64))))
        f4s = np.clip(fs[:, 4] - lo, 0, ascii_cum.size - 1)
        f4e = np.clip(fe[:, 4] - lo, 0, ascii_cum.size - 1)
        strand_bad = (ascii_cum[f4e] - ascii_cum[f4s]) > 0
        minus = (fe[:, 4] - fs[:, 4] == 1) & \
            (arr[np.clip(fs[:, 4], 0, arr.size - 1)] == 45)
        bad = (~has9 | int_bad | strand_bad).tolist()
        minus_l = minus.tolist()
        vals = ints.tolist()
        f0s, f0e = fs[:, 0].tolist(), fe[:, 0].tolist()
        f5s, f5e = fs[:, 5].tolist(), fe[:, 5].tolist()
        lines = (a + rows).tolist()
        buf = self._buf
        for r in range(len(lines)):
            if bad[r]:
                self._fallback_line(dst, lines[r])
                continue
            try:
                q_name = bytes(buf[f0s[r]:f0e[r]]).decode()
                t_name = bytes(buf[f5s[r]:f5e[r]]).decode()
            except UnicodeDecodeError as exc:
                raise self._malformed(lines[r], exc) from exc
            v = vals[r]
            dst.append(Overlap.from_paf(
                q_name, v[0], v[1], v[2],
                "-" if minus_l[r] else "+",
                t_name, v[3], v[4], v[5]))


class MhapScanParser(_OverlapScanParser):
    """MHAP: whitespace-separated columns; ids/coords at tokens
    0,1,4..11 (the float scores at 2,3 are never parsed)."""

    format_label = "Mhap"
    line_parser = _line.MhapParser

    _INT_TOKENS = (0, 1, 4, 5, 6, 7, 8, 9, 10, 11)

    def _parse_lines(self, dst: List[Overlap], a: int, b: int) -> None:
        s, e, rows = self._select_rows(a, b)
        if rows.size == 0:
            return
        ls, le = s[rows], e[rows]
        arr = self._arr
        lo, hi = int(ls[0]), int(le[-1])
        seg = arr[lo:hi]
        ws = ((seg == 32) | (seg == 9) | (seg == 10) | (seg == 13) |
              (seg == 11) | (seg == 12))
        token = ~ws
        tok_s = np.flatnonzero(
            token & np.concatenate(([True], ws[:-1]))).astype(np.int64) + lo
        tok_e = np.flatnonzero(
            token & np.concatenate((ws[1:], [True]))).astype(np.int64) \
            + lo + 1
        t0 = np.searchsorted(tok_s, ls)
        idx = t0[:, None] + np.arange(12, dtype=np.int64)
        starts12 = _gather(tok_s, idx)
        ends12 = _gather(tok_e, idx)
        has12 = ends12[:, 11] <= le       # token 11 ends inside the line
        ints, int_bad = _parse_int_matrix(
            arr, starts12[:, self._INT_TOKENS],
            np.minimum(ends12, _BIG)[:, self._INT_TOKENS])
        bad = (~has12 | int_bad).tolist()
        vals = ints.tolist()
        lines = (a + rows).tolist()
        for r in range(len(lines)):
            if bad[r]:
                self._fallback_line(dst, lines[r])
                continue
            v = vals[r]
            dst.append(Overlap.from_mhap(
                v[0], v[1], v[2], v[3], v[4], v[5], v[6], v[7], v[8],
                v[9]))


class SamScanParser(_OverlapScanParser):
    """SAM alignment lines: '@' headers skipped, 6 leading tab
    columns, CIGARs parsed in one batched pass straight into
    ``cigar_runs`` (no string round trip — satellite fix for the
    per-record regex in core/overlap.py)."""

    format_label = "Sam"
    line_parser = _line.SamParser

    def _parse_lines(self, dst: List[Overlap], a: int, b: int) -> None:
        s, e, rows = self._select_rows(a, b)
        if rows.size == 0:
            return
        ls, le = s[rows], e[rows]
        arr = self._arr
        record = arr[ls] != 64            # '@' header lines skipped
        rows, ls, le = rows[record], ls[record], le[record]
        if rows.size == 0:
            return
        lo, hi = int(ls[0]), int(le[-1])
        tabs = np.flatnonzero(arr[lo:hi] == 9).astype(np.int64) + lo
        t0 = np.searchsorted(tabs, ls)
        tab5 = _gather(tabs, t0[:, None] + np.arange(5, dtype=np.int64))
        has6 = tab5[:, 4] < le
        tab_after = _gather(tabs, (t0 + 5)[:, None])[:, 0]
        f5_end = np.where(tab_after < le, tab_after, le)
        fs1 = np.minimum(tab5, _BIG - 2) + 1
        ints, int_bad = _parse_int_matrix(
            arr, fs1[:, (0, 2)], tab5[:, (1, 3)])
        cig_s = np.minimum(fs1[:, 4], f5_end)
        cig_e = f5_end
        runs, runs_bad = parse_cigar_runs_batch(
            arr, np.where(has6, cig_s, 0), np.where(has6, cig_e, 0))
        bad = (~has6 | int_bad | runs_bad).tolist()
        flags = ints[:, 0].tolist()
        positions = ints[:, 1].tolist()
        clens = (cig_e - cig_s).tolist()
        f0s, f0e = ls.tolist(), tab5[:, 0].tolist()
        f2s, f2e = fs1[:, 1].tolist(), tab5[:, 2].tolist()
        lines = (a + rows).tolist()
        buf = self._buf
        for r in range(len(lines)):
            if bad[r]:
                self._fallback_line(dst, lines[r])
                continue
            flag = flags[r]
            is_valid = not (flag & 0x4)
            if clens[r] < 2 and is_valid:
                # a valid record must carry an alignment; raised RAW,
                # exactly like Overlap.from_sam via the line parser
                raise InvalidInputError(
                    "missing alignment from SAM object")
            try:
                q_name = bytes(buf[f0s[r]:f0e[r]]).decode()
                t_name = bytes(buf[f2s[r]:f2e[r]]).decode()
            except UnicodeDecodeError as exc:
                raise self._malformed(lines[r], exc) from exc
            o = Overlap._from_sam_fields(
                q_name, flag, t_name, positions[r],
                *_sam_run_fields(*runs[r]))
            o.cigar_runs = runs[r]
            dst.append(o)


def drain(parser, chunk_bytes: int = 1 << 26) -> list:
    """Stream every record out of a parser into a fresh list.

    The r24 internal mapper consumes whole files (reads, draft) rather
    than polisher-style incremental chunks; this keeps that loop in one
    place.  Works with any parser exposing the bioparser ``parse(dst,
    max_bytes) -> more`` protocol, closes the parser when drained."""
    records: list = []
    try:
        while parser.parse(records, chunk_bytes):
            pass
    finally:
        close = getattr(parser, "close", None)
        if close is not None:
            close()
    return records
