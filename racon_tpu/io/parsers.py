"""Sequence and overlap file parsers (bioparser-equivalent).

Re-provides the functionality racon gets from the vendored ``bioparser``
library (reference: vendor/bioparser, call sites src/polisher.cpp:86-125):
gzip-transparent, chunked parsers for FASTA/FASTQ sequence files and
MHAP/PAF/SAM overlap files.  ``parse(dst, max_bytes)`` appends parsed
records to ``dst`` and returns True while more data remains, mirroring the
streaming semantics used by Polisher::initialize (src/polisher.cpp:228-263).

Parsers are format-specific and construct records through the factory
callables handed to them, the same dependency direction as bioparser's
friend-constructor injection (reference: src/sequence.hpp:56-57,
src/overlap.hpp:71-73).
"""

from __future__ import annotations

import gzip
import os
from typing import Callable, List, Optional

from racon_tpu.core.sequence import Sequence
from racon_tpu.core.overlap import Overlap


def _open(path: str):
    """Open a possibly-gzipped file in binary mode (zlib-transparent)."""
    with open(path, "rb") as probe:
        magic = probe.read(2)
    if magic == b"\x1f\x8b":
        return gzip.open(path, "rb")
    return open(path, "rb")


class _LineChunkParser:
    """Base for line-oriented parsers with byte-budget chunking."""

    def __init__(self, path: str):
        if not os.path.isfile(path):
            raise FileNotFoundError(path)
        self.path = path
        self._fh = None

    def reset(self) -> None:
        if self._fh is not None:
            self._fh.close()
        self._fh = _open(self.path)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def _ensure_open(self):
        if self._fh is None:
            self.reset()
        return self._fh


class FastaParser(_LineChunkParser):
    """Multi-line FASTA; records created via Sequence.from_fasta."""

    def __init__(self, path: str):
        super().__init__(path)
        self._pending_header: Optional[bytes] = None

    def reset(self) -> None:
        super().reset()
        self._pending_header = None

    def parse(self, dst: List[Sequence], max_bytes: int) -> bool:
        fh = self._ensure_open()
        budget = max_bytes if max_bytes >= 0 else float("inf")
        consumed = 0
        header = self._pending_header
        self._pending_header = None
        data_parts: List[bytes] = []

        def flush():
            if header is not None:
                dst.append(Sequence.from_fasta(header, b"".join(data_parts)))

        for raw in fh:
            line = raw.rstrip(b"\r\n")
            if line.startswith(b">"):
                if header is not None and consumed >= budget:
                    # keep record boundaries: stop before a new record once
                    # over budget (bioparser stops at the first record that
                    # does not fit; we approximate with >= budget)
                    flush()
                    self._pending_header = line[1:]
                    return True
                flush()
                header = line[1:]
                data_parts = []
            else:
                if header is None:
                    continue
                data_parts.append(line)
            consumed += len(raw)
        flush()
        return False


class FastqParser(_LineChunkParser):
    """FASTQ with possibly line-wrapped data/quality sections."""

    def parse(self, dst: List[Sequence], max_bytes: int) -> bool:
        fh = self._ensure_open()
        budget = max_bytes if max_bytes >= 0 else float("inf")
        consumed = 0
        while True:
            header = fh.readline()
            if not header:
                return False
            consumed += len(header)
            header = header.rstrip(b"\r\n")
            if not header.startswith(b"@"):
                continue
            data_parts: List[bytes] = []
            data_len = 0
            while True:
                line = fh.readline()
                if not line:
                    break
                consumed += len(line)
                line = line.rstrip(b"\r\n")
                if line.startswith(b"+"):
                    break
                data_parts.append(line)
                data_len += len(line)
            qual_parts: List[bytes] = []
            qual_len = 0
            while qual_len < data_len:
                line = fh.readline()
                if not line:
                    break
                consumed += len(line)
                line = line.rstrip(b"\r\n")
                qual_parts.append(line)
                qual_len += len(line)
            dst.append(Sequence.from_fastq(header[1:], b"".join(data_parts),
                                           b"".join(qual_parts)))
            if consumed >= budget:
                return True


class _OverlapLineParser(_LineChunkParser):
    record_from_line: Callable[[bytes], Optional[Overlap]]

    def parse(self, dst: List[Overlap], max_bytes: int) -> bool:
        fh = self._ensure_open()
        budget = max_bytes if max_bytes >= 0 else float("inf")
        consumed = 0
        line_no = getattr(self, "_line_no", 0)
        for raw in fh:
            line_no += 1
            line = raw.rstrip(b"\r\n")
            if not line:
                continue
            try:
                record = self.record_from_line(line)
            except (IndexError, ValueError, UnicodeDecodeError) as exc:
                # diagnosable hard error, like bioparser's
                # format-violation exits (reference: vendored bioparser
                # used at src/polisher.cpp:86-125)
                self._line_no = line_no
                raise MalformedInputError(
                    f"{self.path}:{line_no}: malformed "
                    f"{type(self).__name__.replace('Parser', '')} "
                    f"record ({exc})") from exc
            if record is not None:
                dst.append(record)
            consumed += len(raw)
            if consumed >= budget:
                self._line_no = line_no
                return True
        self._line_no = line_no
        return False

    def reset(self) -> None:
        super().reset()
        self._line_no = 0


class PafParser(_OverlapLineParser):
    """PAF: qname qlen qstart qend strand tname tlen tstart tend ..."""

    @staticmethod
    def record_from_line(line: bytes) -> Optional[Overlap]:
        f = line.split(b"\t")
        return Overlap.from_paf(
            q_name=f[0].decode(), q_length=int(f[1]), q_begin=int(f[2]),
            q_end=int(f[3]), orientation=f[4].decode(),
            t_name=f[5].decode(), t_length=int(f[6]), t_begin=int(f[7]),
            t_end=int(f[8]))


class MhapParser(_OverlapLineParser):
    """MHAP: aid bid jaccard minmers arc abeg aend alen brc bbeg bend blen.

    Ids are 1-based in the file; Overlap.from_mhap subtracts 1
    (reference: src/overlap.cpp:15-27).
    """

    @staticmethod
    def record_from_line(line: bytes) -> Optional[Overlap]:
        f = line.split()
        return Overlap.from_mhap(
            a_id=int(f[0]), b_id=int(f[1]),
            a_rc=int(f[4]), a_begin=int(f[5]), a_end=int(f[6]),
            a_length=int(f[7]), b_rc=int(f[8]), b_begin=int(f[9]),
            b_end=int(f[10]), b_length=int(f[11]))


class SamParser(_OverlapLineParser):
    """SAM alignment lines; headers skipped; unmapped flagged invalid."""

    @staticmethod
    def record_from_line(line: bytes) -> Optional[Overlap]:
        if line.startswith(b"@"):
            return None
        f = line.split(b"\t")
        # from_sam_bytes parses the CIGAR from the original bytes and
        # populates cigar_runs directly — no str round trip
        return Overlap.from_sam_bytes(
            q_name=f[0].decode(), flag=int(f[1]), t_name=f[2].decode(),
            t_begin=int(f[3]), cigar=f[5])


_SEQUENCE_EXTENSIONS_FASTA = (".fasta", ".fasta.gz", ".fna", ".fna.gz",
                              ".fa", ".fa.gz")
_SEQUENCE_EXTENSIONS_FASTQ = (".fastq", ".fastq.gz", ".fq", ".fq.gz")


class UnsupportedFormatError(ValueError):
    pass


class MalformedInputError(ValueError):
    """A record violates its declared format (path:line diagnostics)."""


def _fast_io_enabled() -> bool:
    """RACON_TPU_FAST_IO selects the vectorized scan parsers
    (io/fastio.py, default on); "0" is the escape hatch back to the
    line parsers.  Read per parser construction so tests can flip it
    between polishes."""
    return os.environ.get("RACON_TPU_FAST_IO", "1") != "0"


def create_sequence_parser(path: str):
    """Extension-sniffing factory (reference: src/polisher.cpp:83-99)."""
    if path.endswith(_SEQUENCE_EXTENSIONS_FASTA):
        if _fast_io_enabled():
            from racon_tpu.io import fastio
            return fastio.FastaScanParser(path)
        return FastaParser(path)
    if path.endswith(_SEQUENCE_EXTENSIONS_FASTQ):
        if _fast_io_enabled():
            from racon_tpu.io import fastio
            return fastio.FastqScanParser(path)
        return FastqParser(path)
    raise UnsupportedFormatError(
        f"file {path} has unsupported format extension (valid extensions: "
        ".fasta, .fasta.gz, .fna, .fna.gz, .fa, .fa.gz, .fastq, .fastq.gz, "
        ".fq, .fq.gz)")


def create_overlap_parser(path: str):
    """Extension-sniffing factory (reference: src/polisher.cpp:101-115)."""
    if path.endswith((".mhap", ".mhap.gz")):
        if _fast_io_enabled():
            from racon_tpu.io import fastio
            return fastio.MhapScanParser(path)
        return MhapParser(path)
    if path.endswith((".paf", ".paf.gz")):
        if _fast_io_enabled():
            from racon_tpu.io import fastio
            return fastio.PafScanParser(path)
        return PafParser(path)
    if path.endswith((".sam", ".sam.gz")):
        if _fast_io_enabled():
            from racon_tpu.io import fastio
            return fastio.SamScanParser(path)
        return SamParser(path)
    raise UnsupportedFormatError(
        f"file {path} has unsupported format extension (valid extensions: "
        ".mhap, .mhap.gz, .paf, .paf.gz, .sam, .sam.gz)")
