"""Fault-tolerant fleet router: one address in front of N daemons.

``racon-tpu route --socket PATH --backends S1,S2,... [--tcp H:P]``
starts a routing daemon that fronts several ``racon-tpu serve``
backends so a single daemon crash, drain, or full queue is no longer
a client-visible outage — the online, crash-tolerant lift of the
reference wrapper's offline split driver (racon_wrapper.py), and the
fault-tolerance layer of the ROADMAP's fleet-scale serving item.

* **Health-probed placement** — a background loop (period
  ``RACON_TPU_ROUTE_PROBE_S``) sends the cheap ``health`` op to every
  backend, keeping per-backend queue depth / running count /
  draining state fresh (the r15 ``FleetScraper`` pattern: last good
  doc retained, staleness visible).  Every submit is priced per
  backend via :func:`scheduler.estimate_job` — the same
  ``calibrate.predict_walls`` model the daemons' own admission uses,
  including the r13 shared-wall concurrency term (this backend's
  live load + 1) and the r18 hit-ratio discount — and placed on the
  backend with the lowest predicted wall (ties: lowest load, then
  CLI list order, so placement is deterministic under equal load).
* **Content-affinity placement (r22)** — each submit's content-digest
  sample (racon_tpu/serve/affinity.py) is scored against every
  backend's epoch-tagged cache sketch from its health doc
  (racon_tpu/cache/sketch.py); the per-backend estimated hit
  fraction feeds the r18 discount, so the backend whose result cache
  already holds this content wins the pricing outright instead of
  only breaking near-ties.  Sketch staleness (age-guarded at 3
  probe periods + timeout) and Bloom false positives only mis-price
  a placement; bytes are pinned by the cache's full-key lookups.
  ``RACON_TPU_ROUTE_AFFINITY=0`` disables (pure load/price ranking).
* **Spillover** — a backend's retryable reject (``queue_full``,
  ``job_too_large``, ``draining``) is not surfaced: the router tries
  the next-best backend, and only when EVERY eligible backend
  rejected does it sleep (preferring the servers' ``retry_after_s``
  hints over its own backoff) and re-rank for another round.
* **Circuit breakers** — consecutive probe/submit failures
  (``RACON_TPU_ROUTE_BREAKER_FAILS``) flip a backend OPEN: it stops
  receiving placements and probes until a jittered cooldown
  (``RACON_TPU_ROUTE_BREAKER_COOLDOWN_S``) elapses, then ONE
  half-open probe decides — success closes the breaker, failure
  re-opens it.  A dead socket costs one connect per cooldown window,
  not per submit.
* **Draining-aware failover** — a SIGTERM'd backend answers probes
  with ``status: draining``; the router marks it and routes new jobs
  elsewhere while the backend's in-flight jobs (including ones this
  router placed) finish undisturbed — mirroring the daemon's own
  drain contract.
* **Crash failover, exactly-once** — a backend that dies mid-job
  surfaces as a transport error on the blocked submit; the router
  resubmits to a surviving backend under the SAME idempotence
  ``job_key`` (client-supplied, or router-derived when the client
  sent none).  The r17 write-ahead journal dedups any replay of the
  dead backend's work, and byte-determinism makes the surviving
  backend's bytes identical — so the crash is invisible to the
  client (pinned by tests/test_router.py's chaos matrix).  Completed
  keys stay sticky: a duplicate keyed submit routes to the backend
  that ran it, whose journal answers from the record.
* **Scatter/gather mega-job sharding (r20)** — a submit whose
  admission estimate exceeds ``RACON_TPU_SCATTER_MIN_WALL_S`` (or
  that carries an explicit ``shards`` field) is split into K
  target-sharded sub-jobs (racon_tpu/serve/scatter.py) fanned out
  concurrently, each placed independently (cheapest predicted shared
  wall, honoring breakers/draining) under the derived key
  ``<job_key>-shard-<i>of<k>`` — so the r17 journal + the crash failover
  below give exactly-once per SHARD: a backend death mid-shard
  re-places only that shard.  The gather concatenates the shard
  FASTAs in shard order — byte-identical to the unsharded run by the
  ``target_slice`` contract — and answers the client with one merged
  frame whose report carries per-shard sub-blocks.  Shard progress is
  visible in ``route_status`` while a scatter is live.
* **Shard-aware input staging (r21)** — at scatter plan time the
  router builds a one-pass slice index over the overlaps file
  (racon_tpu/io/staging.py) and ships each sub-job a ``stage`` hint:
  the line ranges of the whole query-runs that can contribute rows to
  that shard's targets.  The receiving daemon validates the hint
  (path + file signature + shard coordinates) and parses only those
  ranges — byte-identical to the full parse for owned targets —
  instead of parsing everything and dropping (K-1)/K of it.
  ``RACON_TPU_STAGE=0`` restores the full parse everywhere; planning
  failures (non-PAF input, malformed rows, remote paths) silently
  fall back to unhinted sub-jobs.
* **Cross-shard straggler rebalancing (r21)** — the probe loop
  watches live scatters; a shard whose current attempt has run past
  ``RACON_TPU_SCATTER_REBALANCE x p50`` of the plan's predicted shard
  walls (and at least four probe periods) gets a speculative
  replacement attempt under the derived key
  ``<job_key>-shard-<i>of<k>-r<n>`` on the idlest eligible backend it
  has not tried, while the superseded attempt is asked to
  cancel-after-checkpoint (the ``cancel`` op; daemons stop at their
  next poll site, keeping everything journaled).  First successful
  attempt wins the shard slot, so the gather's bytes are those of the
  unsharded run no matter which attempt delivered them; the
  ``route-mid-rebalance`` fault site pins exactly-once across a
  router death in the middle of the handoff.
* **Cache-affinity tiebreak** — when predicted walls tie within 10%,
  placement prefers the backend whose result cache (r14/r18) reports
  the higher hit ratio — and, among those, one that recently served
  this tenant's content-keyed jobs — recorded as a
  ``route_cache_affinity`` flight event.  Affinity only ever picks
  among near-equal predictions: it can turn a warm cache into a
  fleet-wide property, never override the cost model.
* **TCP front** — ``--tcp HOST:PORT`` (or ``RACON_TPU_ROUTE_TCP``)
  additionally listens on TCP with the SAME length-prefixed JSON
  framing (racon_tpu/serve/protocol.py works on any socket object),
  so clients are no longer confined to the router's host.  ``PORT``
  0 binds an ephemeral port, reported in ``route_status``.

Every routing decision is observable: ``route_submit`` /
``route_spillover`` / ``route_failover`` / ``route_dedup_joins``
counters and ``route_breaker_open.<backend>`` per-backend counters
in the registry, plus a flight event per decision
(``route`` / ``route_spillover`` / ``route_failover`` /
``route_breaker`` / ``route_dedup``) so ``racon-tpu inspect``
reconstructs why a job landed where it did.  The ``route_status``
op (also rendered by ``racon-tpu status``) reports per-backend
breaker state, probe staleness and the counters.

Knobs (all placement policy — none can change job bytes, so all are
``EPOCH_EXCLUDE``'d from cache keys):

* ``RACON_TPU_ROUTE_AFFINITY``           content-affinity placement
  (1; 0 = pure load/price ranking, no cache-locality preference)
* ``RACON_TPU_ROUTE_PROBE_S``            probe period (1.0)
* ``RACON_TPU_ROUTE_PROBE_TIMEOUT_S``    per-probe timeout (2.0)
* ``RACON_TPU_ROUTE_BREAKER_FAILS``      failures to OPEN (3)
* ``RACON_TPU_ROUTE_BREAKER_COOLDOWN_S`` OPEN -> half-open (5.0)
* ``RACON_TPU_ROUTE_TCP``                TCP bind, "" = off
* ``RACON_TPU_SCATTER_MIN_WALL_S``       auto-scatter threshold,
  "" = only explicit ``--shards`` scatters
* ``RACON_TPU_SCATTER_MAX_SHARDS``       shard-count cap (8)
* ``RACON_TPU_SCATTER_REBALANCE``        straggler factor (2.5,
  0 = rebalancing off)
* ``RACON_TPU_STAGE``                    staged inputs (1; 0 = full
  parse — the one staging knob, byte-identical either way)
"""

from __future__ import annotations

import argparse
import collections
import itertools
import os
import random
import signal
import socket
import sys
import threading

from racon_tpu.obs import REGISTRY
from racon_tpu.obs import context as obs_context
from racon_tpu.obs import faultinject
from racon_tpu.obs import flight as obs_flight
from racon_tpu.obs import trace as obs_trace
from racon_tpu.serve import affinity, client, protocol, scatter


def eprint(*args):
    print(*args, file=sys.stderr, flush=True)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def probe_interval_s() -> float:
    return max(0.05, _env_float("RACON_TPU_ROUTE_PROBE_S", 1.0))


def probe_timeout_s() -> float:
    return max(0.1, _env_float("RACON_TPU_ROUTE_PROBE_TIMEOUT_S", 2.0))


def breaker_fails() -> int:
    return max(1, _env_int("RACON_TPU_ROUTE_BREAKER_FAILS", 3))


def breaker_cooldown_s() -> float:
    return max(0.1,
               _env_float("RACON_TPU_ROUTE_BREAKER_COOLDOWN_S", 5.0))


def route_affinity_on() -> bool:
    """Content-affinity placement (r22): score each submit's content
    -digest sample against the backends' cache sketches and fold the
    estimated hit fraction into the placement price.  Default on;
    "0" also disables the older scalar-hit-ratio tiebreak, leaving
    pure load/price ranking (the bench's affinity-off arm)."""
    return os.environ.get("RACON_TPU_ROUTE_AFFINITY", "1") != "0"


#: breaker states (route_status renders them uppercase)
CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

#: spillover rounds before the router gives up and surfaces the
#: last retryable reject (each round re-ranks and re-tries every
#: eligible backend, sleeping on the servers' retry_after_s between)
_MAX_ROUNDS = 3

#: cap on the inter-round spillover sleep
_MAX_ROUND_WAIT_S = 10.0

#: cap on speculative rebalance attempts per shard (r1, r2): a shard
#: slow on its THIRD backend is telling us about the job, not the
#: placement, and further copies only burn fleet capacity
_REBALANCE_MAX_ATTEMPTS = 2

#: r23 bounded forensic reads — mirrors the daemon's
#: server.TRACE_QUERY_MAX_EVENTS (kept local so the router never
#: imports the daemon's heavy module graph)
_TRACE_QUERY_MAX_EVENTS = 4096


class Backend:
    """One fronted daemon: last-known health + its circuit breaker.

    The breaker is a small explicit state machine — CLOSED (normal),
    OPEN (shed: no placements, no probes until ``next_probe``),
    HALF-OPEN (cooldown elapsed; exactly one probe in flight decides)
    — with every transition under one lock and time injected by the
    caller, so the transitions unit-test without a daemon or a
    sleep."""

    def __init__(self, target: str, fails: int = None,
                 cooldown_s: float = None):
        self.target = target
        self._fails_limit = breaker_fails() if fails is None else fails
        self._cooldown_s = (breaker_cooldown_s()
                            if cooldown_s is None else cooldown_s)
        self._lock = threading.Lock()
        self.state = CLOSED
        self.failures = 0          # consecutive probe/submit failures
        self.draining = False
        self.health = None         # last good health doc
        self.t_health = None       # ... and when it arrived
        self.last_error = None
        self.next_probe = 0.0      # earliest half-open probe (OPEN)
        self.opened_count = 0
        self._probing = False      # a half-open probe is in flight

    def note_success(self, doc: dict, now: float) -> bool:
        """A probe answered: refresh health, close the breaker.
        Returns True when this CLOSED a non-closed breaker."""
        with self._lock:
            reopened = self.state != CLOSED
            self.state = CLOSED
            self.failures = 0
            self.health = doc
            self.t_health = now
            self.last_error = None
            self._probing = False
            self.draining = (doc or {}).get("status") == "draining" \
                or not (doc or {}).get("accepting", True)
            return reopened

    def note_failure(self, error: str, now: float) -> bool:
        """A probe or submit transport-failed.  Returns True when
        this OPENED the breaker (the caller records/announces it —
        once per opening, not per failure)."""
        with self._lock:
            self.failures += 1
            self.last_error = error
            self._probing = False
            if self.state == HALF_OPEN or (
                    self.state == CLOSED
                    and self.failures >= self._fails_limit):
                self.state = OPEN
                # jittered cooldown: a fleet of routers (or breakers)
                # must not re-probe a recovering daemon in lockstep
                self.next_probe = now + self._cooldown_s * (
                    0.75 + 0.5 * random.random())
                self.opened_count += 1
                return True
            return False

    def probe_due(self, now: float) -> bool:
        """Whether the probe loop should probe this backend now.
        CLOSED probes every round; OPEN waits out the cooldown, then
        admits exactly ONE half-open probe."""
        with self._lock:
            if self._probing:
                return False
            if self.state == CLOSED:
                return True
            if now >= self.next_probe:
                self.state = HALF_OPEN
                self._probing = True
                return True
            return False

    def eligible(self) -> bool:
        """May receive a NEW placement: breaker closed, not
        draining."""
        with self._lock:
            return self.state == CLOSED and not self.draining

    def mark_draining(self) -> None:
        with self._lock:
            self.draining = True

    def load(self) -> int:
        """Queued + running jobs from the last good health doc (0
        when never probed — optimism costs one spillover, pessimism
        would blackhole a fresh backend)."""
        with self._lock:
            h = self.health or {}
        try:
            return int(h.get("queue_depth") or 0) + \
                int(h.get("running") or 0)
        except (TypeError, ValueError):
            return 0

    def snapshot(self, now: float) -> dict:
        with self._lock:
            h = self.health or {}
            age = None if self.t_health is None \
                else round(now - self.t_health, 3)
            return {
                "target": self.target,
                "breaker": self.state.upper(),
                "failures": self.failures,
                "opened_count": self.opened_count,
                "draining": self.draining,
                "probe_age_s": age,
                "queue_depth": h.get("queue_depth"),
                "running": h.get("running"),
                "daemon_pid": h.get("pid"),
                "last_error": self.last_error,
            }


class _RoutedJob:
    """In-router rendezvous for one idempotence key: concurrent
    duplicate submits join the owner's routing instead of racing two
    placements for one key."""

    def __init__(self, job_key: str):
        self.job_key = job_key
        self.done = threading.Event()
        self.response = None


class FleetRouter:
    def __init__(self, socket_path: str, backends,
                 tcp: str = None):
        if not backends:
            raise ValueError("FleetRouter needs at least one backend")
        self.socket_path = socket_path
        self.backends = [Backend(t) for t in backends]
        self.tcp_spec = tcp or None
        self.probe_interval = probe_interval_s()
        self.probe_timeout = probe_timeout_s()
        self._sock = None
        self._tcp_sock = None
        self.tcp_addr = None         # actual host:port once bound
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._handlers: list = []
        self._in_flight = 0          # live proxied submits
        self._live: dict = {}        # job_key -> _RoutedJob
        self._done_backend: dict = {}  # job_key -> backend target
        # r20 scatter state: placements this router has chosen but
        # whose submits are still in flight (so K concurrent shards
        # spread instead of all picking the same stale-cheapest
        # backend), live shard-progress docs for route_status, and a
        # bounded per-tenant memory of which backends recently served
        # content-keyed jobs (the cache-affinity tiebreak)
        self._plan_lock = threading.Lock()
        self._placing: dict = {}       # backend target -> in-flight
        self._scatter_live: dict = {}  # job_key -> progress doc
        self._tenant_recent: dict = {}  # tenant -> deque of targets
        self._keyseq = itertools.count(1)
        # r23 router forensic parity: the router keeps its own
        # bounded per-job trace capture (like the daemons since r14),
        # keyed by a router-local routing id minted per owned submit
        self._jobseq = itertools.count(1)
        obs_trace.TRACER.enable_job_capture()
        self._t_start = obs_trace.now()
        self._drain_logged = False
        obs_flight.FLIGHT.install_dump_on_crash()
        from racon_tpu.obs import provenance
        provenance.daemon_identity(socket_path)
        REGISTRY.set("route_backends", len(self.backends))

    def _identity(self) -> dict:
        from racon_tpu.obs import provenance
        return provenance.daemon_identity(self.socket_path)

    # -- health probing / breakers -------------------------------------

    def _probe_one(self, backend: Backend) -> None:
        try:
            doc = client.health(backend.target,
                                timeout=self.probe_timeout)
            ok = bool(doc.get("ok"))
            error = None if ok else "health answered ok=false"
        except Exception as exc:    # ServeError or anything transport
            doc, ok = None, False
            error = f"{type(exc).__name__}: {exc}"
        now = obs_trace.now()
        if ok:
            closed = backend.note_success(doc, now)
            REGISTRY.set(f"route_backend_up.{backend.target}", 1)
            if closed:
                obs_flight.FLIGHT.record(
                    "route_breaker", backend=backend.target,
                    state="closed")
                eprint(f"[racon_tpu::route] breaker CLOSED for "
                       f"{backend.target} (half-open probe answered)")
        else:
            opened = backend.note_failure(error, now)
            REGISTRY.set(f"route_backend_up.{backend.target}", 0)
            if opened:
                self._record_breaker_open(backend, error)

    def _record_breaker_open(self, backend: Backend,
                             error: str) -> None:
        REGISTRY.add(f"route_breaker_open.{backend.target}")
        obs_flight.FLIGHT.record(
            "route_breaker", backend=backend.target, state="open",
            failures=backend.failures, error=(error or "")[:200])
        eprint(f"[racon_tpu::route] breaker OPEN for "
               f"{backend.target} after {backend.failures} "
               f"consecutive failure(s): {error}")

    def _probe_round(self) -> None:
        """One concurrent probe round over every due backend (the
        FleetScraper shape: one bounded thread per target, last good
        doc retained on failure)."""
        now = obs_trace.now()
        due = [b for b in self.backends if b.probe_due(now)]
        threads = [threading.Thread(target=self._probe_one, args=(b,),
                                    daemon=True) for b in due]
        for t in threads:
            t.start()
        for t in threads:
            t.join(self.probe_timeout + 5.0)

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval):
            self._probe_round()
            # r21: the same cadence that refreshes backend health
            # watches live scatters for straggling shards
            try:
                self._rebalance_round()
            except Exception as exc:   # a watchdog bug must never
                obs_flight.FLIGHT.record_exception(  # kill probing
                    "route_rebalance_error", exc)

    # -- placement -----------------------------------------------------

    def _price(self, spec: dict, concurrency: int,
               hit_ratio: float = None):
        """Predicted wall for this job at this backend's load — the
        daemons' own admission model (scheduler.estimate_job ->
        calibrate.predict_walls with shared-wall + hit-ratio terms).
        ``hit_ratio`` is the per-backend sketch-estimated hit
        fraction (r22) — when given it replaces the router-local
        trailing ratio in the discount, making the predicted wall
        backend-specific in cache warmth, not just in load.  None
        when the inputs cannot be priced from here (e.g. a
        TCP-remote client naming paths this host cannot stat) —
        ranking then falls back to raw load."""
        from racon_tpu.serve import scheduler
        try:
            return scheduler.estimate_job(spec,
                                          concurrency=concurrency,
                                          hit_ratio=hit_ratio)
        except (OSError, KeyError, TypeError, ValueError):
            return None

    def _placing_inc(self, target: str) -> None:
        with self._lock:
            self._placing[target] = self._placing.get(target, 0) + 1

    def _placing_dec(self, target: str) -> None:
        with self._lock:
            n = self._placing.get(target, 0) - 1
            if n > 0:
                self._placing[target] = n
            else:
                self._placing.pop(target, None)

    def _cache_block(self, backend: Backend, now: float) -> dict:
        """The ``cache`` block of the backend's last good health doc,
        or {} when that doc is older than the probe staleness window
        (3 probe periods + the probe timeout — the same bound
        ``route_status`` reports staleness against).  The age guard
        is the r22 small fix: a dead backend's last-known hot cache
        must not keep attracting placements its breaker will only
        reject later."""
        health, t = backend.health, backend.t_health
        if not health or t is None:
            return {}
        if now - t > 3 * self.probe_interval + self.probe_timeout:
            return {}
        return health.get("cache") or {}

    def _hit_ratio(self, backend: Backend, now: float) -> float:
        """The backend's result-cache hit ratio from its last good
        health doc (0.0 when it reports no cache block or the doc is
        past the staleness window)."""
        try:
            return float(
                self._cache_block(backend, now).get("hit_ratio")
                or 0.0)
        except (TypeError, ValueError):
            return 0.0

    def _affinity_reorder(self, rows: list, tenant: str,
                          now: float) -> list:
        """Scalar cache-locality tiebreak — the pre-r22 fallback used
        only when no content-digest sample exists for the submit
        (affinity off handles neither path; sketch pricing in
        :meth:`_rank` replaces this when a sample is available):
        among backends whose predicted wall is within 10% of the
        best, prefer the hottest result cache, then one that
        recently served this tenant's content-keyed jobs.  First-max
        on ties keeps placement deterministic; unpriceable specs
        (wall == inf) never reorder — affinity refines the cost
        model, it never replaces it.  Rows are the pre-sorted
        ``(wall, load, idx, backend, est)`` tuples."""
        if len(rows) < 2:
            return rows
        best_wall = rows[0][0]
        if not best_wall < float("inf"):
            return rows
        tied = [r for r in rows if r[0] <= best_wall * 1.10]
        if len(tied) < 2:
            return rows
        with self._lock:
            recent = set(self._tenant_recent.get(tenant or "default",
                                                 ()))

        def warmth(row):
            return (round(self._hit_ratio(row[3], now), 3),
                    1 if row[3].target in recent else 0)

        leader = max(tied, key=warmth)
        if leader is rows[0] or warmth(leader) <= warmth(rows[0]):
            return rows
        REGISTRY.add("route_cache_affinity")
        obs_flight.FLIGHT.record(
            "route_cache_affinity", backend=leader[3].target,
            over=rows[0][3].target, tenant=tenant,
            hit_ratio=self._hit_ratio(leader[3], now),
            wall_s=(round(leader[0], 4)
                    if leader[0] < float("inf") else None))
        rows.remove(leader)
        rows.insert(0, leader)
        return rows

    def _affinity_sample(self, spec: dict):
        """(content-digest sample, local engine-epoch hex) for a
        submit, or ``([], None)`` when affinity routing is off or the
        sample cannot be derived (unreadable inputs, TCP-remote
        paths) — ranking then falls back to the scalar tiebreak."""
        if not route_affinity_on():
            return [], None
        try:
            from racon_tpu.cache import keying

            epoch = keying.engine_epoch()
            sample = affinity.job_digest_sample(spec, epoch)
            return sample, epoch.hex()
        except Exception:
            return [], None

    def _note_tenant_backend(self, tenant: str, job_key: str,
                             target: str) -> None:
        """Remember which backend served a tenant's CONTENT-keyed job
        (router-minted ``route-*`` keys carry no content identity, so
        nothing would be warm for their duplicates)."""
        if not job_key or job_key.startswith("route-"):
            return
        with self._lock:
            dq = self._tenant_recent.get(tenant or "default")
            if dq is None:
                dq = collections.deque(maxlen=32)
                self._tenant_recent[tenant or "default"] = dq
            dq.append(target)

    def _rank(self, spec: dict, exclude=(), tenant: str = None) -> list:
        """Eligible backends, best placement first: (predicted wall,
        load, CLI list order) — the last term makes placement
        deterministic under equal load.  Load counts this router's
        own still-in-flight placements on top of the probed depth, so
        K scattered shards planned in one burst spread over the fleet
        instead of all chasing the same stale-cheapest backend.

        r22 content affinity: when the submit yields a content-digest
        sample, each backend's price carries ITS OWN estimated hit
        fraction (sample vs the backend's epoch-tagged cache sketch)
        as the r18 discount — a warm backend's predicted wall shrinks
        by up to 90%, so cache locality is priced against load and
        queue depth in one model instead of breaking near-ties.  A
        stale or foreign-epoch sketch scores as cold; false positives
        only under-price.  Without a sample, near ties fall back to
        the scalar tiebreak (:meth:`_affinity_reorder`)."""
        sample, epoch_hex = self._affinity_sample(spec)
        now = obs_trace.now()
        rows = []
        with self._lock:
            placing = dict(self._placing)
        for idx, backend in enumerate(self.backends):
            if backend.target in exclude or not backend.eligible():
                continue
            load = backend.load() + placing.get(backend.target, 0)
            frac = None
            if sample:
                frac = affinity.backend_hit_fraction(
                    self._cache_block(backend, now).get("sketch"),
                    sample, epoch_hex)
            # pass the warmth kwarg only when there is a fraction to
            # price with -- cold-path calls keep the pre-r22 signature
            est = (self._price(spec, load + 1, hit_ratio=frac)
                   if frac is not None
                   else self._price(spec, load + 1))
            if est is not None and frac is not None:
                est["affinity_hit_fraction"] = round(frac, 4)
            wall = None
            if est:
                wall = est.get("shared_wall_s",
                               est.get("predicted_wall_s"))
            rows.append((wall if wall is not None else float("inf"),
                         load, idx, backend, est))
        rows.sort(key=lambda r: (r[0], r[1], r[2]))
        if not sample:
            rows = self._affinity_reorder(rows, tenant, now)
        elif rows and (rows[0][4] or {}).get("affinity_hit_fraction"):
            REGISTRY.add("route_sketch_affinity")
            obs_flight.FLIGHT.record(
                "route_sketch_affinity", backend=rows[0][3].target,
                tenant=tenant,
                hit_fraction=rows[0][4]["affinity_hit_fraction"],
                wall_s=(round(rows[0][0], 4)
                        if rows[0][0] < float("inf") else None))
        return [(backend, est) for _, _, _, backend, est in rows]

    # -- submit proxying -----------------------------------------------

    def _handle_submit(self, req: dict) -> dict:
        spec = req.get("job")
        if not isinstance(spec, dict):
            return protocol.error_frame(
                "bad_request", "submit carries no job object")
        job_key = req.get("job_key")
        if job_key is not None and \
                not obs_context.valid_trace_id(job_key):
            return protocol.error_frame(
                "bad_request",
                "job_key must be 1..128 chars of "
                "[A-Za-z0-9._:-] starting alphanumeric")
        trace_context = req.get("trace_context")
        if trace_context is not None and \
                not obs_context.valid_trace_id(trace_context):
            return protocol.error_frame(
                "bad_request",
                "trace_context must be 1..128 chars of "
                "[A-Za-z0-9._:-] starting alphanumeric")
        try:
            requested_shards = scatter.parse_requested(
                req.get("shards"))
        except ValueError as exc:
            return protocol.error_frame("bad_request", str(exc))
        if self._stop.is_set():
            REGISTRY.add("route_reject.draining")
            return protocol.error_frame(
                "draining", "router is draining: in-flight jobs "
                "finish, new jobs are rejected", retry_after_s=2.0)
        if job_key is None:
            # failover safety net: the resubmit after a backend crash
            # must carry the SAME key as the original placement, or
            # the surviving backend could re-run work the dead one's
            # journal already recorded
            job_key = f"route-{os.getpid()}-{next(self._keyseq)}"
        if trace_context is None:
            # r23 bugfix: a context-less submit used to reach every
            # backend with NO trace_context, so each scheduler minted
            # its own unlinked <pid>-<job> id and sibling shards /
            # failover retries could not be correlated.  The router
            # adopts the job_key (client-supplied or just minted —
            # both pass the same charset contract) as the fleet-wide
            # trace context; every sub-submit below reads it from
            # this shared req dict, so shards, rebalance attempts and
            # failovers all land under one id
            trace_context = job_key
            req["trace_context"] = trace_context
        # in-router rendezvous: concurrent duplicates of one key join
        # the owner's routing (one placement, every caller gets the
        # same response) — the router-level twin of the scheduler's
        # _by_key rendezvous
        with self._lock:
            live = self._live.get(job_key)
            owner = live is None
            if owner:
                live = _RoutedJob(job_key)
                self._live[job_key] = live
        if not owner:
            REGISTRY.add("route_dedup_joins")
            obs_flight.FLIGHT.record("route_dedup", job_key=job_key,
                                     trace_id=trace_context,
                                     joined="live")
            live.done.wait()
            return live.response
        # r23 router forensic parity: the owned submit gets a
        # router-local routing id; every routing decision below is
        # flight-tagged and span-captured under it, so the router's
        # own per-job slice exists just like a backend's
        rid = next(self._jobseq)
        t_route0 = obs_trace.now()
        try:
            resp = self._submit_planned(spec, req, job_key,
                                        requested_shards, rid=rid)
        except Exception as exc:     # router bug: job fails, router
            obs_flight.FLIGHT.record_exception(   # survives
                "route_error", exc)
            resp = protocol.error_frame(
                "job_failed", f"router error: {exc}",
                type=type(exc).__name__)
        obs_trace.TRACER.add_span(
            "route.submit", t_route0, obs_trace.now(), cat="route",
            args={"job": rid, "job_key": job_key,
                  "trace_id": trace_context,
                  "ok": bool(resp.get("ok"))}, jobs=[rid])
        if req.get("trace") and isinstance(resp, dict):
            # router-side forensics ride the traced response so a
            # routed `submit --trace` is not backend-only
            resp = dict(resp)
            resp["router_pid"] = os.getpid()
            resp["router_flight_events"] = \
                obs_flight.FLIGHT.snapshot(job=rid)
            resp["router_trace_events"] = \
                obs_trace.TRACER.job_slice(rid)
        with self._lock:
            self._live.pop(job_key, None)
            if resp.get("ok") and resp.get("routed_backend"):
                # completed keys stay sticky to the backend whose
                # journal holds the record, so a late duplicate is
                # answered by THAT journal (dedup, not re-run)
                self._done_backend[job_key] = resp["routed_backend"]
        faultinject.hit("route-pre-reply")
        live.response = resp
        live.done.set()
        return resp

    def _submit_planned(self, spec: dict, req: dict, job_key: str,
                        requested, rid: int = None) -> dict:
        """Decide scatter vs unsharded for a submit this router owns,
        then run it.  Auto-scatter prices the whole job once at
        concurrency 1 (the single-backend wall the split is trying to
        beat) and only engages when RACON_TPU_SCATTER_MIN_WALL_S is
        set; an explicit ``shards`` on the submit always wins.
        ``rid`` is the router-local routing id the owned submit's
        forensics are captured under (r23)."""
        n_eligible = sum(1 for b in self.backends if b.eligible())
        wall = None
        if requested is None and scatter.min_wall_s() is not None:
            est = self._price(spec, 1)
            if est:
                wall = est.get("predicted_wall_s")
        k = scatter.plan_shards(requested, wall, n_eligible)
        if k <= 1:
            return self._route_job(spec, req, job_key, rid=rid)
        return self._scatter_job(spec, req, job_key, k, rid=rid)

    def _plan_stage(self, spec: dict, k: int, rid: int = None,
                    trace_id: str = None) -> dict:
        """r21 staged inputs: build the overlaps slice index ONCE at
        plan time (racon_tpu/io/staging.py) and derive each shard's
        ``stage`` hint from it, so the K daemons skip the (K-1)/K of
        the overlap parse their ownership mask would drop anyway.
        Strictly best-effort: any failure (non-PAF input, malformed
        rows, unreadable targets, a TCP-remote client naming paths
        this host cannot read) returns no hints and every shard
        self-plans or full-parses — a hint can speed a shard up,
        never fail it.  The receiving daemons re-validate path + file
        signature + shard coordinates before trusting a hint."""
        from racon_tpu.io import staging
        if not staging.stage_enabled():
            return {}
        # r24: an internal-mapping spec has no overlaps file to
        # slice — each shard maps its reads itself; the rounds field
        # rides the shard specs (scatter.shard_spec copies the whole
        # spec), which IS the per-shard round plan
        if spec.get("overlaps") is None:
            return {}
        try:
            names = staging.fasta_names(spec["targets"])
            index = staging.get_index(spec["overlaps"], names)
            if index is None:
                return {}
            hints = {i: staging.shard_hint(index, (i, k), len(names))
                     for i in range(k)}
        except Exception:
            return {}
        REGISTRY.add("route_stage_plans")
        obs_flight.FLIGHT.record(
            "route_stage_plan", job=rid, trace_id=trace_id, shards=k,
            total_bytes=hints[0].get("total_bytes"),
            staged_bytes=[hints[i].get("staged_bytes")
                          for i in range(k)])
        return hints

    def _scatter_job(self, spec: dict, req: dict, job_key: str,
                     k: int, rid: int = None) -> dict:
        """Fan a mega-job out as K target-sharded sub-jobs and gather
        the merged reply.  Each shard is a full :meth:`_route_job` —
        independently priced, spilled over, failed over — under the
        derived key ``<job_key>-shard-<i>of<k>``, so exactly-once
        per shard rides on the r17 backend journals: a duplicate of
        the WHOLE mega-job (e.g. a client retry through a restarted
        router) re-plans identical shards and every backend answers
        its shard from the record.  An explicit shard count is never
        capped by transient eligibility (scatter.plan_shards), so the
        retry's plan matches the original's even when a breaker
        opened in between; and because ``k`` is baked into the key, a
        retry whose auto/threshold plan DID change simply misses the
        old records and re-runs fresh instead of gathering stale
        slices.

        For that journal rendezvous to actually happen, the duplicate
        must re-MEET its records: shard i's first-choice backend is
        the i-th eligible backend in CLI list order — a deterministic
        mapping that survives router restarts (same ``--backends``
        flag => same mapping) and spreads K shards over the fleet by
        construction.  It is only a preference: cost ranking takes
        over the moment the preferred backend is dead, draining or
        full, and a re-run on a different survivor still returns the
        same bytes (the target_slice contract) — exactly-once decays
        to at-least-once only when the fleet itself changed between
        duplicates.

        r21: each shard runs as a SLOT holding one or more attempts.
        The original attempt runs under the shard key; the probe
        loop's watchdog (:meth:`_rebalance_scan`) may add speculative
        replacement attempts under derived ``-r<n>`` keys when the
        shard straggles.  First successful attempt wins the slot —
        the gather concatenates winners in target order, so the bytes
        are those of the unsharded run regardless of which attempt
        delivered them — and a superseded attempt's ``job_canceled``
        reply never fails the shard."""
        t0 = obs_trace.now()
        trace_ctx = req.get("trace_context")
        REGISTRY.add("route_scatter_jobs")
        REGISTRY.add("route_scatter_shards", k)
        keys = [scatter.shard_key(job_key, i, k) for i in range(k)]
        eligible = [b.target for b in self.backends if b.eligible()]
        prefer = {i: eligible[i % len(eligible)]
                  for i in range(k)} if eligible else {}
        stage_hints = self._plan_stage(spec, k, rid=rid,
                                       trace_id=trace_ctx)
        # the plan's per-shard predicted walls: the p50 is the
        # straggler watchdog's yardstick for "this shard is late"
        predicted = []
        for i in range(k):
            est = self._price(
                scatter.shard_spec(spec, i, k,
                                   stage=stage_hints.get(i)), 1)
            predicted.append(est.get("predicted_wall_s")
                             if est else None)
        walls = sorted(w for w in predicted if w is not None)
        p50 = walls[len(walls) // 2] if walls else None
        slots = []
        for i in range(k):
            hint = stage_hints.get(i) or {}
            staged = hint.get("staged_bytes")
            total = hint.get("total_bytes")
            slots.append({
                "shard": i, "done": threading.Event(),
                "finished": False, "result": None,
                "winner_key": None, "errors": [], "keys": [],
                "pending": 0, "rebalances": 0,
                "backends": set(), "started": None, "lineage": None,
                "staged_bytes": staged,
                "parse_skipped_bytes": (
                    total - staged
                    if staged is not None and total else None),
            })
        progress = {"job_key": job_key, "shards": k, "done": 0,
                    "backends": [None] * k, "p50_wall_s": p50,
                    "rid": rid, "trace": trace_ctx,
                    "slots": slots}

        def settle(i: int, key: str, resp: dict) -> None:
            slot = slots[i]
            cancel_keys, finished = None, False
            with self._lock:
                slot["pending"] -= 1
                if resp.get("ok") and slot["result"] is None:
                    slot["result"] = resp
                    slot["winner_key"] = key
                    progress["backends"][i] = \
                        resp.get("routed_backend")
                    if resp.get("routed_backend"):
                        # per-attempt sticky: a later duplicate of
                        # this key routes straight back to the
                        # journal that recorded it, even if failover
                        # moved the attempt off its preferred backend
                        self._done_backend[key] = \
                            resp["routed_backend"]
                    cancel_keys = [x for x in slot["keys"]
                                   if x != key]
                elif not resp.get("ok"):
                    slot["errors"].append((key, dict(resp)))
                if (slot["result"] is not None
                        or slot["pending"] == 0) \
                        and not slot["finished"]:
                    slot["finished"] = True
                    progress["done"] += 1
                    finished = True
            obs_flight.FLIGHT.record(
                "route_scatter_shard", job=rid, job_key=job_key,
                trace_id=trace_ctx, shard=i,
                key=key, ok=bool(resp.get("ok")),
                winner=(key == slot["winner_key"]),
                backend=resp.get("routed_backend"),
                wall_s=resp.get("wall_s"))
            if cancel_keys:
                # a superseded sibling may still be running its
                # copy: cancel-after-checkpoint, fire-and-forget
                self._broadcast_cancel(cancel_keys)
            if finished:
                slot["done"].set()

        def run_attempt(i: int, key: str, pref) -> None:
            ta = obs_trace.now()
            try:
                resp = self._route_job(
                    scatter.shard_spec(spec, i, k,
                                       stage=stage_hints.get(i)),
                    req, key, prefer=pref, rid=rid)
            except Exception as exc:  # router bug: the attempt fails,
                # the gather must NOT hang on a slot that can never
                # settle
                obs_flight.FLIGHT.record_exception("error", exc)
                resp = {"ok": False,
                        "error": {"code": "job_failed",
                                  "type": type(exc).__name__,
                                  "reason": str(exc)}}
            if rid is not None:
                obs_trace.TRACER.add_span(
                    "route.attempt", ta, obs_trace.now(), cat="route",
                    args={"job": rid, "job_key": job_key, "key": key,
                          "shard": i, "trace_id": trace_ctx,
                          "backend": resp.get("routed_backend"),
                          "ok": bool(resp.get("ok"))},
                    jobs=[rid])
            settle(i, key, resp)

        def launch(i: int, key: str, pref) -> None:
            slot = slots[i]
            with self._lock:
                slot["pending"] += 1
                slot["keys"].append(key)
                slot["started"] = obs_trace.now()
                if pref:
                    slot["backends"].add(pref)
            threading.Thread(
                target=run_attempt, args=(i, key, pref),
                daemon=True,
                name=f"racon-route-shard-{i}").start()

        # the watchdog launches replacement attempts through the
        # same path the originals take
        progress["launch"] = launch
        with self._lock:
            self._scatter_live[job_key] = progress
        obs_flight.FLIGHT.record(
            "route_scatter", job=rid, job_key=job_key,
            trace_id=trace_ctx, shards=k, keys=keys,
            staged=bool(stage_hints), tenant=spec.get("tenant"))
        eprint(f"[racon_tpu::route] scatter: job {job_key} -> {k} "
               f"target shard(s)"
               + (" (staged inputs)" if stage_hints else ""))
        try:
            for i in range(k):
                launch(i, keys[i], prefer.get(i))
            for slot in slots:
                slot["done"].wait()
            faultinject.hit("route-mid-gather")
            results, win_keys = [], []
            for i, slot in enumerate(slots):
                if slot["result"] is not None:
                    results.append(slot["result"])
                    win_keys.append(slot["winner_key"])
                    continue
                # surface the shard's first REAL failure; completed
                # siblings are journaled on their backends, so the
                # client's retry under the same key re-runs ONLY the
                # failures.  A superseded attempt's job_canceled
                # never speaks for the shard
                resp = next(
                    (r for _, r in slot["errors"]
                     if (r.get("error") or {}).get("code")
                     != "job_canceled"),
                    slot["errors"][-1][1] if slot["errors"]
                    else None)
                REGISTRY.add("route_scatter_failed")
                err = dict((resp or {}).get("error")
                           or {"code": "job_failed",
                               "reason": "shard returned no "
                                         "response"})
                err["shard"] = i
                err["shards"] = k
                return {"ok": False, "error": err}
            out = scatter.merge_responses(results, win_keys)
            wall = obs_trace.now() - t0
            out["wall_s"] = round(wall, 6)
            out["scatter"] = {
                "shards": k,
                "backends": list(progress["backends"]),
                "staged_bytes": [s["staged_bytes"] for s in slots],
                "rebalanced": [s["lineage"] for s in slots]}
            obs_flight.FLIGHT.record(
                "route_gather", job=rid, job_key=job_key,
                trace_id=trace_ctx, shards=k,
                winner_keys=list(win_keys),
                wall_s=round(wall, 6),
                n_sequences=out.get("n_sequences"))
            return out
        finally:
            with self._lock:
                self._scatter_live.pop(job_key, None)

    # -- straggler rebalancing (r21) -----------------------------------

    def _idlest_backend(self, exclude=()):
        """The eligible backend with the lowest live load (probed
        depth + this router's in-flight placements), CLI order as
        the tiebreak — where a straggler's replacement attempt
        goes."""
        with self._lock:
            placing = dict(self._placing)
        best = None
        for idx, backend in enumerate(self.backends):
            if backend.target in exclude or not backend.eligible():
                continue
            rank = (backend.load()
                    + placing.get(backend.target, 0), idx)
            if best is None or rank < best[0]:
                best = (rank, backend.target)
        return best[1] if best else None

    def _broadcast_cancel(self, keys) -> None:
        """Best-effort cancel of superseded attempt keys on every
        backend (failover may have moved an attempt anywhere, and a
        cancel for a key a backend never saw is a cheap no-op).
        Runs detached: the daemon stops the job at its next poll
        site AFTER the last committed checkpoint; nothing here
        blocks routing or gathering."""
        targets = [b.target for b in self.backends]
        timeout = self.probe_timeout

        def worker() -> None:
            for key in keys:
                REGISTRY.add("route_cancels")
                for target in targets:
                    try:
                        client.cancel(target, key, timeout=timeout)
                    except Exception:
                        pass

        threading.Thread(target=worker, daemon=True,
                         name="racon-route-cancel").start()

    def _rebalance_round(self) -> None:
        factor = scatter.rebalance_factor()
        if factor is None:
            return
        now = obs_trace.now()
        with self._lock:
            live = list(self._scatter_live.values())
        for prog in live:
            self._rebalance_scan(prog, factor, now)

    def _rebalance_scan(self, prog: dict, factor: float,
                        now: float) -> None:
        """One watchdog pass over a live scatter: any unfinished
        shard whose CURRENT attempt has run past ``max(factor x
        p50(predicted shard walls), 4 probe periods)`` gets a
        speculative replacement on the idlest eligible backend the
        shard has not yet tried, under a derived ``-r<n>`` key
        (scatter.rebalance_key) so the replacement is its own
        exactly-once unit at its backend's journal.  First success
        wins the slot; the superseded attempts are
        cancel-after-checkpoint'd.  The floor of four probe periods
        keeps a fast plan from tripping on probe jitter; launching
        an attempt resets the shard's clock, so a second rebalance
        needs the replacement to straggle too."""
        k = prog["shards"]
        threshold = max(
            factor * float(prog.get("p50_wall_s") or 0.0),
            4.0 * self.probe_interval)
        for slot in prog.get("slots", ()):
            with self._lock:
                started = slot["started"]
                if slot["finished"] or started is None \
                        or now - started <= threshold \
                        or slot["rebalances"] \
                        >= _REBALANCE_MAX_ATTEMPTS:
                    continue
                exclude = set(slot["backends"])
                superseded = list(slot["keys"])
            target = self._idlest_backend(exclude)
            if target is None:
                continue    # nowhere better to run a copy
            with self._lock:
                if slot["finished"] or slot["rebalances"] \
                        >= _REBALANCE_MAX_ATTEMPTS:
                    continue
                slot["rebalances"] += 1
                attempt = slot["rebalances"]
                i = slot["shard"]
                slot["lineage"] = f"{i}of{k}-r{attempt} <- {i}of{k}"
            key = scatter.rebalance_key(prog["job_key"], i, k,
                                        attempt)
            REGISTRY.add("route_rebalance")
            obs_flight.FLIGHT.record(
                "route_rebalance", job=prog.get("rid"),
                job_key=prog["job_key"], trace_id=prog.get("trace"),
                shard=i, attempt=attempt, key=key,
                superseded=superseded, backend=target,
                elapsed_s=round(now - started, 3),
                threshold_s=round(threshold, 3))
            eprint(f"[racon_tpu::route] rebalance: shard {i}of{k} "
                   f"of job {prog['job_key']} straggling "
                   f"({now - started:.1f}s > {threshold:.1f}s); "
                   f"speculative attempt r{attempt} -> {target}")
            faultinject.hit("route-mid-rebalance")
            prog["launch"](i, key, target)
            # cancel-after-checkpoint on the superseded original:
            # it stops at its next poll site, keeping everything it
            # already journaled
            self._broadcast_cancel(superseded)

    def _route_job(self, spec: dict, req: dict, job_key: str,
                   prefer: str = None, rid: int = None) -> dict:
        priority = int(req.get("priority", 0))
        trace_ctx = req.get("trace_context")
        tenant = spec.get("tenant") if isinstance(spec, dict) else None
        dead = set()          # backends that transport-failed: never
        last_reject = None    # retried for THIS job this round-trip
        # a recorded completion outranks the scatter plan's
        # deterministic shard preference; both are soft — cost order
        # resumes for everything behind the front of the list
        sticky = self._done_backend.get(job_key) or prefer
        for round_no in range(_MAX_ROUNDS):
            hint = None
            tried = set()     # retryable rejects this round
            while True:
                # pick under the plan lock so concurrent placements
                # (scattered shards above all) see each other's
                # still-in-flight choices and spread; the forward
                # itself runs outside the lock
                with self._plan_lock:
                    ranked = self._rank(spec, exclude=dead | tried,
                                        tenant=tenant)
                    if sticky is not None:
                        # a completed key's duplicate goes back to
                        # the recording backend first (stable sort
                        # keeps the cost order for the rest)
                        ranked.sort(key=lambda row:
                                    0 if row[0].target == sticky
                                    else 1)
                    if not ranked:
                        break
                    backend, est = ranked[0]
                    self._placing_inc(backend.target)
                try:
                    faultinject.hit("route-pre-forward")
                    REGISTRY.add("route_submit")
                    obs_flight.FLIGHT.record(
                        "route", job=rid, job_key=job_key,
                        trace_id=trace_ctx,
                        backend=backend.target,
                        round=round_no, load=backend.load(),
                        predicted_wall_s=(round(est.get(
                            "shared_wall_s",
                            est.get("predicted_wall_s", 0.0)), 4)
                            if est else None))
                    try:
                        resp = client.submit(
                            backend.target, spec, priority=priority,
                            want_trace=bool(req.get("trace")),
                            trace_context=req.get("trace_context"),
                            job_key=job_key)
                    except client.ServeError as exc:
                        # the backend died (possibly mid-job): crash
                        # failover — feed the breaker and resubmit
                        # the SAME key to the next survivor; the r17
                        # journal dedup makes the retry exactly-once
                        if backend.note_failure(str(exc),
                                                obs_trace.now()):
                            self._record_breaker_open(backend,
                                                      str(exc))
                        REGISTRY.add("route_failover")
                        obs_flight.FLIGHT.record(
                            "route_failover", job=rid,
                            job_key=job_key, trace_id=trace_ctx,
                            backend=backend.target,
                            error=str(exc)[:200])
                        eprint(f"[racon_tpu::route] backend "
                               f"{backend.target} failed mid-submit "
                               f"({exc}); failing over")
                        dead.add(backend.target)
                        continue
                finally:
                    self._placing_dec(backend.target)
                err = (resp.get("error") or {}) \
                    if not resp.get("ok") else {}
                code = err.get("code")
                if code in ("queue_full", "job_too_large",
                            "draining"):
                    # retryable elsewhere: spill to the next-best
                    # backend instead of surfacing the reject
                    if code == "draining":
                        backend.mark_draining()
                    REGISTRY.add("route_spillover")
                    obs_flight.FLIGHT.record(
                        "route_spillover", job=rid, job_key=job_key,
                        trace_id=trace_ctx,
                        backend=backend.target, code=code)
                    try:
                        h = float(err["retry_after_s"])
                        hint = h if hint is None else min(hint, h)
                    except (KeyError, TypeError, ValueError):
                        pass
                    last_reject = resp
                    tried.add(backend.target)
                    continue
                # success, or a reject that is the CLIENT's to see
                # (bad_request / input_not_found / job_failed —
                # another backend would answer the same)
                out = dict(resp)
                out["routed_backend"] = backend.target
                if out.get("ok"):
                    self._note_tenant_backend(tenant, job_key,
                                              backend.target)
                return out
            if round_no + 1 < _MAX_ROUNDS and not self._stop.is_set():
                # every eligible backend rejected retryably: honor
                # the servers' retry_after_s hints (min over the
                # round) before re-ranking, jittered — fall back to
                # doubling when no server sent one
                delay = hint if hint is not None and hint > 0 \
                    else 0.5 * (2 ** round_no)
                delay = min(_MAX_ROUND_WAIT_S, delay) * (
                    0.75 + 0.5 * random.random())
                self._stop.wait(delay)
        if last_reject is not None:
            out = dict(last_reject)
            return out
        REGISTRY.add("route_reject.no_backend")
        return protocol.error_frame(
            "no_backend",
            "no live backend accepted the job "
            f"({len(self.backends)} configured)",
            backends=[b.snapshot(obs_trace.now())["breaker"]
                      for b in self.backends])

    # -- status / telemetry docs ---------------------------------------

    def _route_doc(self) -> dict:
        """The ``route_status`` / ``status`` document: per-backend
        breaker + staleness rows, routing counters, listener
        addresses.  ``router: true`` is what clients key rendering
        off."""
        from racon_tpu.io import staging
        now = obs_trace.now()
        stale_after = 3 * self.probe_interval + self.probe_timeout
        rows = []
        for backend in self.backends:
            row = backend.snapshot(now)
            row["stale"] = (row["probe_age_s"] is None
                            or row["probe_age_s"] > stale_after)
            rows.append(row)
        snap = REGISTRY.snapshot()
        counters = {k: v for k, v in snap["counters"].items()
                    if k.startswith("route_")}
        with self._lock:
            in_flight = self._in_flight
            done_keys = len(self._done_backend)
            scatter_rows = [
                {"job_key": p["job_key"], "shards": p["shards"],
                 "done": p["done"], "backends": list(p["backends"]),
                 "staged_bytes": [s["staged_bytes"]
                                  for s in p.get("slots", ())],
                 "parse_skipped_bytes": [s["parse_skipped_bytes"]
                                         for s in p.get("slots",
                                                        ())],
                 "rebalanced": [s["lineage"]
                                for s in p.get("slots", ())]}
                for p in self._scatter_live.values()]
        return {
            "ok": True,
            "router": True,
            "pid": os.getpid(),
            "socket": self.socket_path,
            "tcp": self.tcp_addr,
            "identity": self._identity(),
            "uptime_s": round(now - self._t_start, 3),
            "draining": self._stop.is_set(),
            "in_flight": in_flight,
            "routed_keys": done_keys,
            "probe_interval_s": self.probe_interval,
            "backends": rows,
            "scatter": {"active": scatter_rows,
                        "min_wall_s": scatter.min_wall_s(),
                        "max_shards": scatter.max_shards(),
                        "rebalance_factor":
                            scatter.rebalance_factor(),
                        "staging": staging.stage_enabled()},
            "counters": counters,
        }

    def _health_doc(self) -> dict:
        up = sum(1 for b in self.backends if b.eligible())
        with self._lock:
            in_flight = self._in_flight
        return {
            "ok": True,
            "router": True,
            # capability flag: a wrapper --server pointed here skips
            # client-side --split and lets the router scatter instead
            "scatter": True,
            "status": ("draining" if self._stop.is_set() else "ok"),
            "accepting": not self._stop.is_set(),
            "pid": os.getpid(),
            "identity": self._identity(),
            "uptime_s": round(obs_trace.now() - self._t_start, 3),
            "backends": len(self.backends),
            "backends_up": up,
            "in_flight_jobs": in_flight,
            "queue_depth": 0,
            "running": in_flight,
            # r23 fleet forensics: capture depths + clock anchors
            # (same block shape as the daemon's — the router has no
            # journal)
            "capture": {
                "flight": obs_flight.FLIGHT.stats(),
                "trace": obs_trace.TRACER.capture_stats(),
                "journal": {"enabled": False},
            },
            "wall_t": round(obs_trace.wall_now(), 6),
            "trace_epoch_wall": round(obs_trace.epoch_wall(), 6),
        }

    def _metrics_doc(self) -> dict:
        """Router telemetry in the daemon ``metrics`` shape (identity
        + snapshot + prometheus) so a FleetScraper/``top --fleet``
        over routers and daemons merges without special cases; the
        ``route`` block carries the breaker rows for rendering."""
        from racon_tpu.obs import export
        REGISTRY.set("route_uptime_s",
                     round(obs_trace.now() - self._t_start, 3))
        snap = REGISTRY.snapshot()
        doc = self._route_doc()
        return {
            "ok": True,
            "router": True,
            "pid": os.getpid(),
            "identity": self._identity(),
            "uptime_s": doc["uptime_s"],
            "route": {"backends": doc["backends"],
                      "counters": doc["counters"],
                      "in_flight": doc["in_flight"],
                      "draining": doc["draining"],
                      "tcp": doc["tcp"]},
            "snapshot": export.json_snapshot(snap),
            "prometheus": export.prometheus_text(snap),
        }

    def _flight_doc(self, req: dict) -> dict:
        """Router flight view — r23 brings it to parity with the
        daemon's: ``job`` (routing id), ``job_key`` (key + derived
        family), ``trace_id`` and ``last`` filters, clock anchors,
        and the per-job trace slice when a routing id is given."""
        try:
            job = req.get("job")
            job = int(job) if job is not None else None
            last = int(req.get("last", 0) or 0)
        except (TypeError, ValueError):
            return protocol.error_frame(
                "bad_request", "flight: job/last must be integers")
        job_key = req.get("job_key")
        trace_id = req.get("trace_id")
        if (job_key is not None and not isinstance(job_key, str)) or \
                (trace_id is not None
                 and not isinstance(trace_id, str)):
            return protocol.error_frame(
                "bad_request",
                "flight: job_key/trace_id must be strings")
        doc = {
            "ok": True,
            "router": True,
            "pid": os.getpid(),
            "identity": self._identity(),
            "ring": obs_flight.FLIGHT.stats(),
            "events": obs_flight.FLIGHT.snapshot(
                job=job, last=last, job_key=job_key,
                trace_id=trace_id),
            "wall_t": round(obs_trace.wall_now(), 6),
            "trace_epoch_wall": round(obs_trace.epoch_wall(), 6),
        }
        if job is not None:
            doc["job_trace"] = obs_trace.TRACER.job_slice(job)
        return doc

    def _trace_query_doc(self, req: dict) -> dict:
        """Bounded per-routing-id trace slice (r23 ``trace_query``
        parity with the daemon's op; same required bounds)."""
        try:
            job = int(req.get("job"))
        except (TypeError, ValueError):
            return protocol.error_frame(
                "bad_request", "trace_query requires a job id")
        try:
            max_events = int(req.get("max_events"))
        except (TypeError, ValueError):
            max_events = 0
        if max_events <= 0:
            return protocol.error_frame(
                "bad_request",
                "trace_query requires max_events > 0 "
                "(unbounded reads are refused)")
        max_events = min(max_events, _TRACE_QUERY_MAX_EVENTS)
        evs = obs_trace.TRACER.job_slice(job)
        return {
            "ok": True, "router": True, "pid": os.getpid(),
            "identity": self._identity(), "job": job,
            "complete": len(evs) <= max_events,
            "events": evs[-max_events:],
            "capture": obs_trace.TRACER.capture_stats(),
            "wall_t": round(obs_trace.wall_now(), 6),
            "trace_epoch_wall": round(obs_trace.epoch_wall(), 6),
        }

    # -- connection handling -------------------------------------------

    def _serve_connection(self, conn) -> None:
        try:
            req = protocol.recv_frame(conn)
            if req is None:
                return
            op = req.get("op") if isinstance(req, dict) else None
            if op == "submit":
                with self._lock:
                    self._in_flight += 1
                try:
                    resp = self._handle_submit(req)
                finally:
                    with self._lock:
                        self._in_flight -= 1
            elif op in ("status", "route_status"):
                resp = self._route_doc()
            elif op == "health":
                resp = self._health_doc()
            elif op == "metrics":
                resp = self._metrics_doc()
            elif op == "flight":
                resp = self._flight_doc(req)
            elif op == "trace_query":
                resp = self._trace_query_doc(req)
            elif op == "journal_query":
                # the router keeps no journal; answer the op (not
                # bad_request) so a fleet-wide forensic sweep treats
                # "no journal here" as data, not as an error row
                resp = {
                    "ok": True, "router": True, "enabled": False,
                    "pid": os.getpid(),
                    "identity": self._identity(),
                    "records": [], "complete": True, "matched": 0,
                    "wall_t": round(obs_trace.wall_now(), 6),
                    "trace_epoch_wall":
                        round(obs_trace.epoch_wall(), 6),
                }
            elif op == "shutdown":
                resp = {"ok": True, "draining": True}
                self._stop.set()
            else:
                resp = protocol.error_frame(
                    "bad_request", f"unknown op {op!r} (router)")
            protocol.send_frame(conn, resp)
        except protocol.ProtocolError as exc:
            REGISTRY.add("route_bad_frames")
            try:
                protocol.send_frame(conn, protocol.error_frame(
                    "bad_request", str(exc)))
            except OSError:
                pass
        except OSError:
            pass   # client went away mid-reply; nothing to salvage
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _spawn_handler(self, conn) -> None:
        t = threading.Thread(target=self._serve_connection,
                             args=(conn,), daemon=True,
                             name="racon-route-conn")
        self._handlers.append(t)
        t.start()
        self._handlers = [h for h in self._handlers if h.is_alive()]

    def _tcp_accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._tcp_sock.accept()
            except socket.timeout:
                if self._stop.is_set() and self._idle():
                    return
                continue
            except OSError:
                return
            self._spawn_handler(conn)

    def _idle(self) -> bool:
        with self._lock:
            return self._in_flight == 0

    # -- lifecycle -----------------------------------------------------

    def _peer_alive(self):
        """Takeover probe (same proof as the daemon's): True =
        answered a health frame (alive), False = connection refused
        (provably dead), None = ambiguous (refuse takeover)."""
        probe = socket.socket(socket.AF_UNIX)
        probe.settimeout(5.0)
        try:
            probe.connect(self.socket_path)
        except ConnectionRefusedError:
            return False
        except OSError:
            return None
        try:
            protocol.send_frame(probe, {"op": "health"})
            resp = protocol.recv_frame(probe)
            return True if isinstance(resp, dict) else None
        except (protocol.ProtocolError, OSError):
            return None
        finally:
            try:
                probe.close()
            except OSError:
                pass

    def _bind_tcp(self) -> None:
        host, _, port = self.tcp_spec.rpartition(":")
        host = host or "127.0.0.1"
        self._tcp_sock = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._tcp_sock.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._tcp_sock.bind((host, int(port)))
        self._tcp_sock.listen(16)
        self._tcp_sock.settimeout(0.25)
        bound = self._tcp_sock.getsockname()
        self.tcp_addr = f"{bound[0]}:{bound[1]}"

    def serve_forever(self) -> int:
        if os.path.exists(self.socket_path):
            alive = self._peer_alive()
            if alive:
                eprint(f"[racon_tpu::route] error: a live server "
                       f"already owns {self.socket_path}; refusing "
                       f"to take over")
                return 1
            if alive is None:
                eprint(f"[racon_tpu::route] error: cannot prove the "
                       f"owner of {self.socket_path} dead; refusing "
                       f"to take over — remove the socket manually "
                       f"if the process is gone")
                return 1
            eprint(f"[racon_tpu::route] stale socket "
                   f"{self.socket_path}: previous owner is dead, "
                   f"taking over")
            os.unlink(self.socket_path)
        # one synchronous probe round BEFORE accepting: the first
        # submit places against real health, not optimistic zeros
        self._probe_round()
        self._sock = socket.socket(socket.AF_UNIX)
        self._sock.bind(self.socket_path)
        self._sock.listen(16)
        self._sock.settimeout(0.25)
        if self.tcp_spec:
            try:
                self._bind_tcp()
            except (OSError, ValueError) as exc:
                eprint(f"[racon_tpu::route] error: cannot bind TCP "
                       f"front {self.tcp_spec!r}: {exc}")
                self._sock.close()
                try:
                    os.unlink(self.socket_path)
                except OSError:
                    pass
                return 1
            threading.Thread(target=self._tcp_accept_loop,
                             daemon=True,
                             name="racon-route-tcp").start()
        threading.Thread(target=self._probe_loop, daemon=True,
                         name="racon-route-probe").start()
        up = sum(1 for b in self.backends if b.eligible())
        eprint(f"[racon_tpu::route] routing on {self.socket_path}"
               + (f" + tcp {self.tcp_addr}" if self.tcp_addr else "")
               + f" -> {len(self.backends)} backend(s), {up} up "
               f"(probe every {self.probe_interval}s)")
        try:
            while True:
                if self._stop.is_set():
                    if not self._drain_logged:
                        self._drain_logged = True
                        eprint("[racon_tpu::route] draining: "
                               "finishing in-flight jobs, rejecting "
                               "new ones")
                        obs_flight.FLIGHT.record(
                            "drain", in_flight=self._in_flight)
                    if self._idle():
                        break
                try:
                    conn, _ = self._sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                self._spawn_handler(conn)
        finally:
            self._shutdown()
        return 0

    def _shutdown(self) -> None:
        self._stop.set()
        # let blocked submit proxies flush their replies
        for h in list(self._handlers):
            h.join(timeout=10)
        for sock in (self._sock, self._tcp_sock):
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        if obs_flight.enabled():
            try:
                path = obs_flight.FLIGHT.dump(reason="route-drain")
                eprint(f"[racon_tpu::route] flight dump: {path}")
            except OSError as exc:
                eprint(f"[racon_tpu::route] flight dump failed: "
                       f"{exc}")
        eprint(f"[racon_tpu::route] drained "
               f"({REGISTRY.value('route_submit')} placement(s)); "
               f"bye")

    def request_stop(self, *_sig) -> None:
        self._stop.set()


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="racon-tpu route",
        description="Fault-tolerant router fronting several "
        "racon-tpu serve daemons: health-probed placement, "
        "spillover on backpressure, circuit breakers, "
        "exactly-once crash failover via idempotent job keys, and "
        "scatter/gather sharding of large jobs across the fleet.")
    p.add_argument("--socket", required=True,
                   help="unix-domain socket path to listen on")
    p.add_argument("--backends", required=True,
                   metavar="SOCK1,SOCK2,...",
                   help="comma-separated backend daemon sockets "
                   "(or host:port TCP fronts)")
    p.add_argument("--tcp", default=None, metavar="HOST:PORT",
                   help="additionally listen on TCP (same framed "
                   "protocol; port 0 = ephemeral, reported in "
                   "route_status).  Default RACON_TPU_ROUTE_TCP "
                   "or off")
    return p


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    backends = [t for t in args.backends.split(",") if t]
    if not backends:
        eprint("[racon_tpu::route] error: --backends needs at least "
               "one socket")
        return 1
    tcp = args.tcp if args.tcp is not None \
        else (os.environ.get("RACON_TPU_ROUTE_TCP") or None)
    router = FleetRouter(args.socket, backends, tcp=tcp)
    signal.signal(signal.SIGTERM, router.request_stop)
    signal.signal(signal.SIGINT, router.request_stop)
    return router.serve_forever()


if __name__ == "__main__":
    sys.exit(main())
