"""Fleet scrape tier: many daemons' telemetry, one merged view.

A single ``racon-tpu serve`` daemon answers ``metrics``/``health``
on its socket (racon_tpu/serve/server.py); a HOST runs several —
one per TPU slice, or a CPU smoke daemon next to a device one — and
the operator question changes from "how is this process doing" to
"how is the FLEET doing, and which daemon is the outlier".  This
module is the read-side answer:

* :class:`FleetScraper` — polls N sockets concurrently (one
  short-lived thread per target per round, bounded by per-target
  timeouts), keeping the last good snapshot per target.  A dead or
  slow daemon degrades to a STALE row — the scrape never throws a
  healthy daemon's data away because a sick one timed out.  In
  background mode (:meth:`FleetScraper.start`) a failing target
  backs off exponentially so a dead socket costs one connect
  attempt per backoff window, not per round.
* :func:`merge_fleet` — scrape rows -> one fleet document: per-daemon
  identity/queue rows plus the EXACT cross-daemon registry merge
  (racon_tpu/obs/aggregate.py) and the fleet SLO table computed from
  it.  Fleet p50/p90/p99 are bit-for-bit the quantiles of the union
  of all daemons' observation streams (fixed bucket ladder — see
  aggregate.py's proof), not an average of averages.
* :func:`watch_fleet` — N concurrent ``watch`` streams multiplexed
  into one iterator of ``{"target", "frame"}`` records; frames keep
  their per-source ``seq`` and identity so nothing is
  cross-attributed.
* :func:`main_metrics` — ``racon-tpu metrics`` one-shot CLI:
  ``--socket PATH`` for one daemon, ``--fleet S1,S2,...`` for the
  merged view, ``--json`` or ``--prometheus`` output (the fleet
  exposition labels every sample ``instance="<daemon_id>"``).

Knobs (registered in provenance.KNOWN_KNOBS):

* ``RACON_TPU_FLEET_INTERVAL_S`` — background scrape period (1.0)
* ``RACON_TPU_FLEET_TIMEOUT_S``  — per-target request timeout (5.0)
* ``RACON_TPU_FLEET_STALE_S``    — age after which a row is stale (10)

Read-only by construction: every op this module sends (``metrics``,
``watch``) touches no queue or job state, so a daemon under active
fleet scrape produces byte-identical FASTA to an unscraped one
(pinned in tests/test_fleet.py).
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import sys
import threading

from racon_tpu.obs import aggregate, export
from racon_tpu.obs import trace as obs_trace
from racon_tpu.serve import client

#: cap on per-target exponential backoff in background mode
_MAX_BACKOFF_S = 30.0


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def fleet_interval_s() -> float:
    return max(0.05, _env_float("RACON_TPU_FLEET_INTERVAL_S", 1.0))


def fleet_timeout_s() -> float:
    return max(0.1, _env_float("RACON_TPU_FLEET_TIMEOUT_S", 5.0))


def fleet_stale_s() -> float:
    return max(0.1, _env_float("RACON_TPU_FLEET_STALE_S", 10.0))


def scrape_concurrently(targets, fn, timeout_s: float = None):
    """Run ``fn(target) -> row`` once per target, one short-lived
    thread each (the same shape :class:`FleetScraper` polls with),
    and return the rows in ``targets`` order.  A worker that hangs
    past the join budget leaves ``None`` in its slot; ``fn`` is
    expected to catch its own errors and degrade to an error row —
    this helper never raises on a worker's behalf.  Shared by the
    r23 fleet forensics collector (racon_tpu/obs/assemble.py)."""
    timeout_s = fleet_timeout_s() if timeout_s is None else timeout_s
    rows = [None] * len(targets)

    def run(idx, target):
        rows[idx] = fn(target)

    threads = [threading.Thread(target=run, args=(i, t), daemon=True)
               for i, t in enumerate(targets)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout_s + 5.0)
    return rows


class FleetScraper:
    """Concurrent multi-target ``metrics`` scraper with per-target
    staleness.  ``targets`` is a list of unix-socket paths.  Use
    :meth:`scrape_once` synchronously, or :meth:`start` /
    :meth:`stop` for a background loop; :meth:`results` reads the
    latest state either way (thread-safe)."""

    def __init__(self, targets, interval_s: float = None,
                 timeout_s: float = None,
                 stale_after_s: float = None):
        if not targets:
            raise ValueError("FleetScraper needs at least one target")
        self.targets = list(targets)
        self.interval_s = (fleet_interval_s()
                           if interval_s is None else interval_s)
        self.timeout_s = (fleet_timeout_s()
                          if timeout_s is None else timeout_s)
        self.stale_after_s = (fleet_stale_s()
                              if stale_after_s is None
                              else stale_after_s)
        self._lock = threading.Lock()
        self._state = {
            t: {"target": t, "ok": False, "doc": None, "t": None,
                "failures": 0, "error": None, "next_due": 0.0}
            for t in self.targets}
        self._stop = threading.Event()
        self._thread = None

    # -- scraping ------------------------------------------------------

    def _scrape_target(self, target: str) -> None:
        try:
            doc = client.metrics(target, timeout=self.timeout_s)
        except Exception as exc:    # ServeError or anything transport
            with self._lock:
                st = self._state[target]
                st["ok"] = False
                st["failures"] += 1
                st["error"] = f"{type(exc).__name__}: {exc}"
                # keep st["doc"]/st["t"]: the last good snapshot
                # stays visible as a STALE row instead of vanishing
                st["next_due"] = obs_trace.now() + min(
                    self.interval_s * (2 ** min(st["failures"], 10)),
                    _MAX_BACKOFF_S)
            return
        with self._lock:
            st = self._state[target]
            st.update(ok=True, doc=doc, t=obs_trace.now(),
                      failures=0, error=None)
            st["next_due"] = st["t"] + self.interval_s

    def scrape_once(self, due_only: bool = False) -> None:
        """One concurrent round over all targets (blocks until every
        target answered or timed out).  ``due_only`` skips targets
        still inside their backoff window (background-loop mode)."""
        now = obs_trace.now()
        with self._lock:
            targets = [t for t in self.targets
                       if not due_only
                       or self._state[t]["next_due"] <= now]
        threads = [threading.Thread(target=self._scrape_target,
                                    args=(t,), daemon=True)
                   for t in targets]
        for th in threads:
            th.start()
        for th in threads:
            th.join(self.timeout_s + 5.0)

    def results(self) -> list:
        """Latest per-target rows (list, ``self.targets`` order).
        ``stale`` is True when the target never answered, last failed,
        or the last good snapshot is older than ``stale_after_s``."""
        now = obs_trace.now()
        rows = []
        with self._lock:
            for t in self.targets:
                st = self._state[t]
                age = None if st["t"] is None else now - st["t"]
                rows.append({
                    "target": t,
                    "ok": st["ok"],
                    "stale": (st["doc"] is None or not st["ok"]
                              or age > self.stale_after_s),
                    "age_s": None if age is None else round(age, 3),
                    "consecutive_failures": st["failures"],
                    "error": st["error"],
                    "doc": st["doc"],
                })
        return rows

    # -- background loop -----------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="racon-tpu-fleet-scrape",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.timeout_s + 10.0)
            self._thread = None

    def _loop(self) -> None:
        # first round is unconditional so results() fills promptly
        self.scrape_once()
        while not self._stop.wait(self.interval_s):
            self.scrape_once(due_only=True)


def merge_fleet(rows) -> dict:
    """Scrape rows (:meth:`FleetScraper.results`) -> one fleet
    document: per-daemon rows keyed by identity, the exact merged
    registry (racon_tpu/obs/aggregate.py), and the fleet SLO table
    over the merge."""
    daemons = []
    snapshots = {}
    alive = stale = 0
    for row in rows:
        doc = row["doc"] or {}
        ident = doc.get("identity") or {}
        q = doc.get("queue") or {}
        if row["stale"]:
            stale += 1
        else:
            alive += 1
        daemons.append({
            "target": row["target"],
            "ok": row["ok"],
            "stale": row["stale"],
            "age_s": row["age_s"],
            "consecutive_failures": row["consecutive_failures"],
            "error": row["error"],
            "identity": ident or None,
            "uptime_s": doc.get("uptime_s"),
            "queue_depth": q.get("queue_depth"),
            "running": len(q.get("running", ())),
            "completed": q.get("completed"),
            "draining": q.get("draining"),
            # r19: a router target's metrics doc carries its backend
            # breaker rows + routing counters; plain daemons carry
            # none — `top --fleet` renders the block when present
            "route": doc.get("route"),
        })
        snap = doc.get("snapshot")
        if snap:
            snapshots[ident.get("daemon_id") or row["target"]] = snap
    merged = aggregate.merge_snapshots(snapshots)
    return {
        "ok": alive > 0,
        "fleet_size": len(rows),
        "alive": alive,
        "stale": stale,
        "daemons": daemons,
        "merged": merged,
        "slo": export.slo_summary(merged),
        # r16: fleet calibration health over the exact merge — the
        # per-stage quantiles come from the union of every daemon's
        # drift-ratio histogram, the EWMA is the per-daemon mean
        "calhealth": export.drift_summary(merged),
    }


def watch_fleet(targets, interval_s: float = None, count: int = 0,
                timeout: float = None):
    """Multiplex N daemons' ``watch`` streams into one generator of
    ``{"target": socket, "frame": frame}`` records (arrival order).
    Each frame keeps its server-assigned per-connection ``seq`` and
    ``identity`` — attribution is per source, never merged.  A
    target that cannot be reached contributes a single
    ``{"ok": False, "error": {...}}`` frame; the generator ends when
    every stream has."""
    targets = list(targets)
    q: queue.Queue = queue.Queue()

    def _reader(t):
        try:
            for frame in client.watch(t, interval_s=interval_s
                                      if interval_s is not None
                                      else fleet_interval_s(),
                                      count=count, timeout=timeout):
                q.put((t, frame))
        except client.ServeError as exc:
            q.put((t, {"ok": False,
                       "error": {"code": "unreachable",
                                 "reason": str(exc)}}))
        finally:
            q.put((t, None))          # end-of-stream sentinel

    for t in targets:
        threading.Thread(target=_reader, args=(t,),
                         daemon=True).start()
    live = len(targets)
    while live:
        t, frame = q.get()
        if frame is None:
            live -= 1
            continue
        yield {"target": t, "frame": frame}


def resolve_fleet_targets(fleet_arg: str, timeout: float = None):
    """``--fleet`` argument -> daemon socket list (r22).

    A comma-separated value is the explicit backend list, as before.
    A single target is probed with ``route_status`` first: a router
    answers with its backend table and the fleet view auto-discovers
    from it (``--fleet ROUTER_SOCK``); a plain daemon (or anything
    that refuses the op) falls back to being the one-element fleet.
    Discovery failures degrade, never fail — a DOWN router behaves
    like a DOWN daemon row."""
    targets = [t for t in (fleet_arg or "").split(",") if t]
    if len(targets) != 1:
        return targets
    try:
        doc = client.route_status(
            targets[0],
            timeout=timeout if timeout is not None
            else fleet_timeout_s())
    except Exception:
        return targets
    backends = [b.get("target") for b in (doc.get("backends") or [])
                if b.get("target")]
    return backends or targets


# -- the `racon-tpu metrics` one-shot CLI ------------------------------


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="racon-tpu metrics",
        description="One-shot telemetry scrape of one daemon "
        "(--socket) or a fleet (--fleet), as JSON or Prometheus "
        "text exposition.")
    g = p.add_mutually_exclusive_group(required=True)
    g.add_argument("--socket",
                   help="unix-domain socket of one daemon")
    g.add_argument("--fleet", metavar="SOCK1,SOCK2,...",
                   help="comma-separated daemon sockets, or a single "
                   "router socket (backends auto-discovered from its "
                   "route_status); output is the merged fleet view")
    f = p.add_mutually_exclusive_group()
    f.add_argument("--json", action="store_true",
                   help="JSON output (default)")
    f.add_argument("--prometheus", action="store_true",
                   help="Prometheus text exposition (fleet samples "
                   "carry instance=\"<daemon_id>\" labels)")
    p.add_argument("--timeout", type=float, default=None,
                   help="per-target timeout in seconds "
                   "(default RACON_TPU_FLEET_TIMEOUT_S)")
    return p


def main_metrics(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    timeout = args.timeout if args.timeout is not None \
        else fleet_timeout_s()
    if args.socket:
        try:
            doc = client.metrics(args.socket, timeout=timeout)
        except client.ServeError as exc:
            print(f"[racon_tpu::metrics] error: {exc}",
                  file=sys.stderr)
            return 1
        if args.prometheus:
            sys.stdout.write(doc.get("prometheus", ""))
        else:
            json.dump(doc, sys.stdout, indent=1)
            print()
        return 0

    targets = resolve_fleet_targets(args.fleet, timeout=timeout)
    scraper = FleetScraper(targets, timeout_s=timeout)
    scraper.scrape_once()
    rows = scraper.results()
    doc = merge_fleet(rows)
    if args.prometheus:
        snapshots = {}
        for row in rows:
            d = row["doc"] or {}
            snap = d.get("snapshot")
            if snap:
                ident = d.get("identity") or {}
                snapshots[ident.get("daemon_id")
                          or row["target"]] = snap
        sys.stdout.write(export.prometheus_text_fleet(snapshots))
    else:
        json.dump(doc, sys.stdout, indent=1)
        print()
    for row in rows:
        if not row["ok"]:
            print(f"[racon_tpu::metrics] {row['target']}: "
                  f"{row['error']}", file=sys.stderr)
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    sys.exit(main_metrics())
