"""Blocking client + the ``racon-tpu submit`` / ``status`` CLIs.

``racon-tpu submit --socket PATH [options ...] <sequences>
<overlaps> <target sequences>`` takes the SAME positional inputs and
option set as the one-shot CLI (the option string is parsed by the
same ``cli.parse_args``), ships them as a job spec to a running
``racon-tpu serve`` daemon, blocks until the job finishes, and
writes the polished FASTA to stdout — byte-identical to what the
one-shot CLI would have printed, minus the cold start.

Exit codes: 0 on success; 1 on a failed job or transport error; 75
(EX_TEMPFAIL) on a backpressure/draining reject — retryable by
contract, so batch drivers can distinguish "try again" from
"broken".  The structured reject reason is printed to stderr as one
JSON line.
"""

from __future__ import annotations

import json
import os
import socket
import sys

from racon_tpu.serve import protocol

EX_TEMPFAIL = 75
#: reject codes a caller may retry verbatim later
RETRYABLE = ("queue_full", "draining")


class ServeError(RuntimeError):
    """Transport-level failure (no server, protocol violation)."""


def is_tcp_address(addr: str) -> bool:
    """``host:port`` addressing (r19 TCP front, racon_tpu/serve/
    router.py): no path separator, a colon, and an all-digits port.
    Anything else — including every existing unix-socket path — keeps
    the unix-domain behaviour, so the rule is backward-compatible by
    construction."""
    if not addr or "/" in addr or os.path.exists(addr):
        return False
    host, sep, port = addr.rpartition(":")
    return bool(sep) and bool(host) and port.isdigit()


def _connect(addr: str, timeout: float = None):
    """Dial ``addr`` — a unix-socket path, or ``host:port`` for the
    router's TCP front — and return the connected socket.  Raises
    OSError on failure (callers wrap into :class:`ServeError`)."""
    if is_tcp_address(addr):
        host, _, port = addr.rpartition(":")
        sock = socket.socket(socket.AF_INET)
        sock.settimeout(timeout)
        try:
            sock.connect((host, int(port)))
        except OSError:
            sock.close()
            raise
        return sock
    sock = socket.socket(socket.AF_UNIX)
    sock.settimeout(timeout)
    try:
        sock.connect(addr)
    except OSError:
        sock.close()
        raise
    return sock


def request(socket_path: str, frame: dict, timeout: float = None):
    """One request/response round trip against a unix socket path or
    a ``host:port`` TCP front.  ``timeout`` bounds every socket
    operation; submits block for the whole job, so the default is no
    timeout."""
    try:
        sock = _connect(socket_path, timeout)
    except OSError as exc:
        raise ServeError(
            f"cannot reach server at {socket_path} ({exc})"
        ) from exc
    try:
        try:
            protocol.send_frame(sock, frame)
            resp = protocol.recv_frame(sock)
        except (OSError, protocol.ProtocolError) as exc:
            raise ServeError(f"transport failure ({exc})") from exc
        if resp is None:
            raise ServeError("server closed the connection without "
                             "a response")
        return resp
    finally:
        sock.close()


def submit(socket_path: str, spec: dict, priority: int = 0,
           timeout: float = None, want_trace: bool = False,
           trace_context: str = None, job_key: str = None,
           shards=None) -> dict:
    """Submit one job and block until it completes (or is rejected).
    Returns the raw response frame; callers check ``resp["ok"]``.
    ``want_trace`` asks the server to attach the job's trace slice
    (``trace_events``) and flight events (``flight_events``).
    ``trace_context`` is an optional caller-chosen trace id
    (traceparent-style, ``[A-Za-z0-9._:-]{1,128}``); the daemon
    adopts it as the job's trace id so spans, flight events and
    ``inspect`` timelines across daemons share one id.
    ``job_key`` (same charset) is the idempotence key (r17): a
    duplicate submit with the same key joins the live job or is
    answered from the daemon's write-ahead journal record — the job
    runs exactly once, across client retries AND daemon restarts.
    ``shards`` (r20, router targets only) asks for scatter/gather
    sharding: an int forces that many target shards, ``"auto"`` lets
    the router split across its eligible backends, 0 forces an
    unsharded run; a plain daemon rejects the field's effects by
    simply never seeing it (the router consumes it)."""
    frame = {"op": "submit", "job": spec, "priority": priority}
    if want_trace:
        frame["trace"] = True
    if trace_context is not None:
        frame["trace_context"] = trace_context
    if job_key is not None:
        frame["job_key"] = job_key
    if shards is not None:
        frame["shards"] = shards
    return request(socket_path, frame, timeout=timeout)


def submit_with_retry(socket_path: str, spec: dict,
                      priority: int = 0, retries: int = 0,
                      timeout: float = None, want_trace: bool = False,
                      trace_context: str = None,
                      job_key: str = None, shards=None) -> dict:
    """:func:`submit`, retried with jittered exponential backoff
    (~0.5 s base, doubling, capped at 30 s; jitter 0.5x..1.5x so a
    herd of clients doesn't re-land in lockstep).

    Retries cover exactly the failures that are safe and useful to
    retry: transport errors (daemon not up yet, restarting after a
    crash — connection refused) and the ``RETRYABLE`` reject codes
    (``queue_full``, ``draining``).  Everything else — bad request,
    failed job — returns/raises immediately.  Pass a ``job_key`` to
    make the retries idempotent by contract: a retry that lands
    after the original was admitted joins the SAME job, and one that
    lands after a daemon crash is answered from the journal record
    instead of re-running.

    When the reject carries a server-supplied ``retry_after_s`` hint
    (r19: the scheduler prices it from its own observed exec walls
    and queue state), that hint wins over the blind exponential
    schedule — the server knows when a slot will actually free.  The
    jittered ``0.5·2^n`` schedule stays as the fallback for
    transport errors and hint-less rejects."""
    import random
    import time

    attempt = 0
    while True:
        hint = None
        try:
            resp = submit(socket_path, spec, priority=priority,
                          timeout=timeout, want_trace=want_trace,
                          trace_context=trace_context,
                          job_key=job_key, shards=shards)
        except ServeError as exc:
            if attempt >= retries:
                raise
            reason = str(exc)
        else:
            err = resp.get("error") or {}
            code = err.get("code")
            if resp.get("ok") or code not in RETRYABLE \
                    or attempt >= retries:
                return resp
            reason = code
            try:
                hint = float(err["retry_after_s"])
            except (KeyError, TypeError, ValueError):
                hint = None
        if hint is not None and hint > 0:
            delay = min(30.0, hint) * (0.75 + 0.5 * random.random())
        else:
            delay = min(30.0, 0.5 * (2 ** attempt))
            delay *= 0.5 + random.random()
        attempt += 1
        print(f"[racon_tpu::submit] retryable failure ({reason}); "
              f"attempt {attempt}/{retries} in {delay:.1f}s",
              file=sys.stderr)
        time.sleep(delay)


def status(socket_path: str, timeout: float = 30.0) -> dict:
    return request(socket_path, {"op": "status"}, timeout=timeout)


def admin(socket_path: str, op: str, timeout: float = 30.0) -> dict:
    """pause / resume / shutdown."""
    return request(socket_path, {"op": op}, timeout=timeout)


def metrics(socket_path: str, timeout: float = 30.0) -> dict:
    """Full telemetry frame incl. the Prometheus text exposition."""
    return request(socket_path, {"op": "metrics"}, timeout=timeout)


def health(socket_path: str, timeout: float = 30.0) -> dict:
    """Cheap liveness/readiness document."""
    return request(socket_path, {"op": "health"}, timeout=timeout)


def cancel(socket_path: str, job_key: str,
           timeout: float = 30.0) -> dict:
    """Best-effort job cancellation by idempotence key (r21: the
    router's straggler rebalancer sends this to a superseded shard's
    backend).  A queued job finishes as ``job_canceled`` without
    running; a running one stops at its next between-units poll site;
    unknown/finished keys are a safe no-op."""
    return request(socket_path, {"op": "cancel", "job_key": job_key},
                   timeout=timeout)


def route_status(socket_path: str, timeout: float = 30.0) -> dict:
    """Router-detail document (the r19 ``route_status`` op): per
    backend breaker state / probe staleness / queue depth, plus the
    router's spillover/failover counters.  Only routers answer it."""
    return request(socket_path, {"op": "route_status"},
                   timeout=timeout)


def flight(socket_path: str, job=None, last: int = 0,
           job_key: str = None, trace_id: str = None,
           timeout: float = 30.0) -> dict:
    """Live flight-recorder view: ring stats + events, optionally
    filtered to one ``job`` (adds its trace slice as ``job_trace``),
    an idempotence-key family (``job_key`` — the key plus its r20/r21
    derived shard/rebalance keys), an exact ``trace_id``, or the
    newest ``last`` events."""
    frame = {"op": "flight"}
    if job is not None:
        frame["job"] = int(job)
    if last:
        frame["last"] = int(last)
    if job_key is not None:
        frame["job_key"] = job_key
    if trace_id is not None:
        frame["trace_id"] = trace_id
    return request(socket_path, frame, timeout=timeout)


def journal_query(socket_path: str, job_key: str = None,
                  job_key_prefix: str = None,
                  max_records: int = 256, max_bytes: int = None,
                  timeout: float = 30.0) -> dict:
    """Bounded read-only slice of a daemon's write-ahead journal
    (r23 ``journal_query``).  A key filter and ``max_records`` are
    REQUIRED by the wire contract — the server answers
    ``bad_request`` to unbounded asks; routers and journal-off
    daemons answer ``{"ok": true, "enabled": false}``."""
    frame = {"op": "journal_query", "max_records": int(max_records)}
    if job_key is not None:
        frame["job_key"] = job_key
    if job_key_prefix is not None:
        frame["job_key_prefix"] = job_key_prefix
    if max_bytes is not None:
        frame["max_bytes"] = int(max_bytes)
    return request(socket_path, frame, timeout=timeout)


def trace_query(socket_path: str, job, max_events: int = 2048,
                timeout: float = 30.0) -> dict:
    """Bounded per-job trace slice (r23 ``trace_query``): the events
    ``submit --trace`` would have attached, readable after the fact.
    ``max_events`` is required by the wire contract."""
    return request(socket_path,
                   {"op": "trace_query", "job": int(job),
                    "max_events": int(max_events)},
                   timeout=timeout)


def explain(socket_path: str, job=None, last: int = 0,
            timeout: float = 30.0) -> dict:
    """Decision-plane view (the ``explain`` op): per-stage
    calibration health (``calhealth``), decision-ring stats, per-kind
    counts and the decision events — optionally filtered to one
    ``job`` or the newest ``last`` events."""
    frame = {"op": "explain"}
    if job is not None:
        frame["job"] = int(job)
    if last:
        frame["last"] = int(last)
    return request(socket_path, frame, timeout=timeout)


def watch(socket_path: str, interval_s: float = 1.0, count: int = 0,
          timeout: float = None):
    """Generator over streamed telemetry frames (the ``watch`` op).
    Yields one dict per frame; ends when the server sent ``count``
    frames (0 = unbounded), drained, or the connection dropped.
    Closing the generator closes the connection."""
    try:
        sock = _connect(socket_path, timeout)
    except OSError as exc:
        raise ServeError(
            f"cannot reach server at {socket_path} ({exc})"
        ) from exc
    try:
        try:
            protocol.send_frame(sock, {"op": "watch",
                                       "interval_s": interval_s,
                                       "count": count})
            while True:
                frame = protocol.recv_frame(sock)
                if frame is None:
                    return
                yield frame
        except (OSError, protocol.ProtocolError) as exc:
            raise ServeError(f"transport failure ({exc})") from exc
    finally:
        sock.close()


def spec_from_opts(opts: dict, inputs, tenant: str = None,
                   job_class: str = None) -> dict:
    """One-shot CLI options -> job spec (racon_tpu/serve/session.py
    resolves omitted keys to the same CLI defaults).  ``tenant`` tags
    the job for the fused device executor's per-tenant fairness and
    SLO accounting; ``job_class`` (r22, ``--class``) picks the
    deadline class (interactive|batch).  Neither affects output
    bytes."""
    spec = {} if tenant is None else {"tenant": tenant}
    if job_class is not None:
        spec["class"] = job_class
    # r24: two inputs (reads, draft) select internal overlap
    # discovery — overlaps=None plus a rounds count is the submit
    # spec's opt-in the scheduler admission checks for
    if len(inputs) == 2:
        inputs = [inputs[0], None, inputs[1]]
    rounds = int(opts.get("rounds", 1) or 1)
    if inputs[1] is None or rounds > 1:
        spec["rounds"] = max(1, rounds)
    spec.update({
        "sequences": os.path.abspath(inputs[0]),
        "overlaps": (os.path.abspath(inputs[1])
                     if inputs[1] is not None else None),
        "targets": os.path.abspath(inputs[2]),
        "type": opts["type"].name,
        "window_length": opts["window_length"],
        "quality_threshold": opts["quality_threshold"],
        "error_threshold": opts["error_threshold"],
        "trim": opts["trim"],
        "match": opts["match"],
        "mismatch": opts["mismatch"],
        "gap": opts["gap"],
        "threads": opts["threads"],
        "drop_unpolished": opts["drop_unpolished"],
        "tpu_poa_batches": opts["tpu_poa_batches"],
        "tpu_banded_alignment": opts["tpu_banded_alignment"],
        "tpu_aligner_batches": opts["tpu_aligner_batches"],
    })
    return spec


def _split_serve_flags(argv):
    """Pull --socket/--priority/--tenant/--class/--trace-context/
    --job-key/--retry/--shards out of the argv so the rest parses
    with the unchanged one-shot ``cli.parse_args``."""
    socket_path, priority, tenant, trace_context = None, 0, None, None
    job_key, retry, shards, job_class = None, 0, None, None
    rest = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--socket":
            i += 1
            socket_path = argv[i] if i < len(argv) else None
        elif a.startswith("--socket="):
            socket_path = a.split("=", 1)[1]
        elif a == "--priority":
            i += 1
            priority = int(argv[i]) if i < len(argv) else 0
        elif a.startswith("--priority="):
            priority = int(a.split("=", 1)[1])
        elif a == "--tenant":
            i += 1
            tenant = argv[i] if i < len(argv) else None
        elif a.startswith("--tenant="):
            tenant = a.split("=", 1)[1]
        elif a == "--trace-context":
            i += 1
            trace_context = argv[i] if i < len(argv) else None
        elif a.startswith("--trace-context="):
            trace_context = a.split("=", 1)[1]
        elif a == "--job-key":
            i += 1
            job_key = argv[i] if i < len(argv) else None
        elif a.startswith("--job-key="):
            job_key = a.split("=", 1)[1]
        elif a == "--retry":
            i += 1
            retry = int(argv[i]) if i < len(argv) else 0
        elif a.startswith("--retry="):
            retry = int(a.split("=", 1)[1])
        elif a == "--shards":
            i += 1
            shards = argv[i] if i < len(argv) else None
        elif a.startswith("--shards="):
            shards = a.split("=", 1)[1]
        elif a == "--class":
            i += 1
            job_class = argv[i] if i < len(argv) else None
        elif a.startswith("--class="):
            job_class = a.split("=", 1)[1]
        else:
            rest.append(a)
        i += 1
    if shards is not None and shards != "auto":
        shards = int(shards)
    return (socket_path, priority, tenant, trace_context, job_key,
            retry, shards, job_class, rest)


def main_submit(argv) -> int:
    from racon_tpu import cli

    socket_path, priority, tenant, trace_context, job_key, retry, \
        shards, job_class, rest = _split_serve_flags(argv)
    if not socket_path:
        print("[racon_tpu::submit] error: --socket PATH is required!",
              file=sys.stderr)
        return 1
    if job_class is not None and \
            job_class not in ("interactive", "batch"):
        print("[racon_tpu::submit] error: --class must be "
              "'interactive' or 'batch'!", file=sys.stderr)
        return 1
    opts, inputs = cli.parse_args(rest)
    if len(inputs) < 2:
        print("[racon_tpu::submit] error: missing input file(s)!",
              file=sys.stderr)
        return 1
    try:
        resp = submit_with_retry(
            socket_path, spec_from_opts(opts, inputs, tenant=tenant,
                                        job_class=job_class),
            priority=priority, retries=max(0, retry),
            want_trace=bool(opts["trace"]),
            trace_context=trace_context, job_key=job_key,
            shards=shards)
    except ServeError as exc:
        print(f"[racon_tpu::submit] error: {exc}", file=sys.stderr)
        return 1
    if not resp.get("ok"):
        err = resp.get("error", {})
        print(json.dumps(err), file=sys.stderr)
        code = err.get("code")
        print(f"[racon_tpu::submit] error: job rejected/failed "
              f"({code}): {err.get('reason')}", file=sys.stderr)
        return EX_TEMPFAIL if code in RETRYABLE else 1

    import base64
    out = sys.stdout.buffer
    out.write(base64.b64decode(resp["fasta_b64"]))
    sys.stdout.flush()
    out.flush()
    if opts["metrics_json"]:
        tmp = opts["metrics_json"] + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(resp["report"], f, indent=1)
        os.replace(tmp, opts["metrics_json"])
        print(f"[racon_tpu::submit] metrics report written to "
              f"{opts['metrics_json']}", file=sys.stderr)
    if opts["trace"]:
        # the job's server-side trace slice as a loadable Chrome
        # trace doc; the flight events ride along under a key
        # Perfetto ignores but `racon-tpu inspect` reads
        events = resp.get("trace_events") or []
        pid = events[0].get("pid", 0) if events else 0
        doc = {
            "traceEvents": [{"name": "process_name", "ph": "M",
                             "pid": pid, "tid": 0,
                             "args": {"name": "racon-tpu serve"}}]
            + events,
            "displayTimeUnit": "ms",
            "flightEvents": resp.get("flight_events") or [],
        }
        tmp = opts["trace"] + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, opts["trace"])
        print(f"[racon_tpu::submit] job trace written to "
              f"{opts['trace']} ({len(events)} event(s))",
              file=sys.stderr)
    print(f"[racon_tpu::submit] job {resp['job_id']} done in "
          f"{resp['wall_s']:.2f} s "
          f"({resp['n_sequences']} sequence(s))", file=sys.stderr)
    return 0


def _print_router_status(doc: dict) -> int:
    """Human rendering of a router ``status``/``route_status`` doc:
    per-backend breaker state (CLOSED/OPEN/HALF-OPEN), probe
    staleness, and the router's routing counters."""
    tcp = f" + tcp {doc['tcp']}" if doc.get("tcp") else ""
    print(f"router      pid {doc.get('pid')} on "
          f"{doc.get('socket')}{tcp}")
    state = "draining" if doc.get("draining") else "routing"
    print(f"state       {state}, up {doc.get('uptime_s', 0):.1f}s, "
          f"{doc.get('in_flight', 0)} in flight")
    c = doc.get("counters") or {}
    print(f"routing     {c.get('route_submit', 0)} submit(s), "
          f"{c.get('route_spillover', 0)} spillover(s), "
          f"{c.get('route_failover', 0)} failover(s), "
          f"{c.get('route_dedup_joins', 0)} dedup join(s)")
    sc = doc.get("scatter") or {}
    if c.get("route_scatter_jobs") or sc.get("active"):
        print(f"scatter     {c.get('route_scatter_jobs', 0)} "
              f"job(s) -> {c.get('route_scatter_shards', 0)} "
              f"shard(s), {c.get('route_cache_affinity', 0)} "
              f"affinity pick(s)")
    for row in sc.get("active") or []:
        print(f"scatter     {row.get('job_key')}: "
              f"{row.get('done')}/{row.get('shards')} shard(s) done")
    backends = doc.get("backends") or []
    if backends:
        print("backend                           breaker    fails  "
              "probe     queue  run  state")
    for b in backends:
        age = b.get("probe_age_s")
        probe = "never" if age is None else f"{age:5.1f}s"
        if b.get("stale"):
            probe += "!"
        qd = b.get("queue_depth")
        run = b.get("running")
        state = "draining" if b.get("draining") else (
            "down" if b.get("breaker") != "CLOSED" else "up")
        print(f"{b.get('target', '?'):<33s} {b.get('breaker'):<9s}  "
              f"{b.get('failures', 0):>5d}  {probe:<8s}  "
              f"{qd if qd is not None else '-':>5}  "
              f"{run if run is not None else '-':>3}  {state}")
    return 0


def main_status(argv) -> int:
    socket_path, _, _, _, _, _, _, _, rest = _split_serve_flags(argv)
    as_json = "--json" in rest
    rest = [a for a in rest if a != "--json"]
    if not socket_path or rest:
        print("usage: racon-tpu status --socket PATH [--json]",
              file=sys.stderr)
        return 1
    try:
        doc = status(socket_path)
    except ServeError as exc:
        print(f"[racon_tpu::status] error: {exc}", file=sys.stderr)
        return 1
    if as_json:
        json.dump(doc, sys.stdout, indent=1)
        print()
        return 0
    if doc.get("router"):
        return _print_router_status(doc)
    q = doc.get("queue", {})
    state = ("draining" if doc.get("draining")
             else "paused" if q.get("paused") else "running")
    print(f"server      pid {doc.get('pid')} on {doc.get('socket')}")
    print(f"state       {state}, up {doc.get('uptime_s', 0):.1f}s")
    print(f"queue       {q.get('queue_depth')}/{q.get('max_queue')} "
          f"queued, {len(q.get('running', []))}/{q.get('max_jobs')} "
          f"running, {q.get('completed')} completed")
    j = doc.get("journal") or {}
    if j.get("enabled"):
        print(f"journal     {j.get('depth')} record(s) "
              f"({j.get('bytes')} B) at {j.get('path')}")
    rec = doc.get("recovered") or {}
    if any(rec.get(k) for k in ("requeued", "completed", "failed")):
        print(f"recovered   {rec.get('requeued', 0)} requeued, "
              f"{rec.get('completed', 0)} completed from record, "
              f"{rec.get('failed', 0)} failed")
    tenants = q.get("tenants") or {}
    if tenants:
        from racon_tpu.obs import export
        hists = (doc.get("registry") or {}).get("histograms", {})
        print("tenant      queued  running  wait p50/p90/p99")
        for name in sorted(tenants):
            row = tenants[name]
            h = hists.get(f"serve_tenant_wait_s.{name}")
            if h and h.get("count"):
                p = export.percentiles(h)
                waits = (f"{p['p50'] * 1e3:.0f}/{p['p90'] * 1e3:.0f}/"
                         f"{p['p99'] * 1e3:.0f} ms")
            else:
                waits = "-"
            print(f"{name:<11s} {row.get('queued', 0):>6d}  "
                  f"{row.get('running', 0):>7d}  {waits}")
    return 0
