"""Scatter/gather planning for mega-job sharding (r20).

The router (racon_tpu/serve/router.py) turns one large submit into K
target-sharded sub-jobs and concatenates their FASTA in shard order.
This module is the pure planning/merging half: how many shards, what
each shard's spec and idempotence key look like, and how the shard
responses fold back into one client frame.  Everything stateful —
placement, fan-out threads, failover, fault sites — stays in the
router.

The byte contract rides on ``target_slice`` (racon_tpu/parallel/
multihost.py): shard ``i`` of ``k`` owns exactly the slice
``target_slice(n_targets, k, i)``, the polisher emits only owned
targets in target order (racon_tpu/core/polisher.py), so gathering in
index order IS the unsharded byte stream.  Sharding is therefore a
placement decision, never a bytes decision — which is why the
RACON_TPU_SCATTER_* knobs live in keying.EPOCH_EXCLUDE.

Keys: shard ``i`` of a mega-job keyed ``K`` planned at ``k`` shards
runs under the derived key ``K-shard-<i>of<k>``.  The r17 journal +
r19 failover then give exactly-once per SHARD: a backend death
mid-shard re-places only that shard under the same derived key, and
a survivor (or the restarted owner) answers the duplicate from its
journal.  The shard COUNT is part of the key because the journal
dedups by key alone: if a duplicate mega-job re-planned a different
``k`` (auto/threshold plans depend on fleet state), a bare
``K-shard-0`` would collide with a record holding a different slice
of the targets and the gather would return wrong bytes.  With ``k``
in the key a re-planned duplicate simply re-runs fresh (at-least-once
across plan changes, exactly-once within a plan).

Rebalance keys (r21): a straggling shard's speculative replacement
runs under ``K-shard-<i>of<k>-r<n>`` — deliberately DISTINCT from the
original's key, so the replacement is a fresh exactly-once unit at
its own backend's journal and can never be answered from the
straggler's records.  First successful attempt wins the shard slot at
the router; the superseded attempt is cancel-after-checkpoint'd and
its ``job_canceled`` reply discarded.

Knobs (provenance.KNOWN_KNOBS; all epoch-excluded):

* ``RACON_TPU_SCATTER_MIN_WALL_S`` (default "" = off): predicted-wall
  threshold above which the router auto-scatters a submit.  An
  explicit ``--shards`` on the submit always wins.
* ``RACON_TPU_SCATTER_REBALANCE`` (default 2.5; 0 = off): straggler
  threshold factor for cross-shard rebalancing — see
  :func:`rebalance_factor`.
* ``RACON_TPU_SCATTER_MAX_SHARDS`` (default 8): cap on the planned
  shard count.  Auto/threshold plans are additionally capped by the
  number of eligible backends (a shard without a backend would just
  queue behind a sibling); an explicit ``--shards K`` is NOT — it
  must re-derive the same plan on a keyed retry even when part of
  the fleet is dark, so the retry meets its journal records.
"""

from __future__ import annotations

import base64
import hashlib
import math
import os


def min_wall_s():
    """The auto-scatter threshold, or None when auto-scatter is off
    (the default: unsharded routing unless the client opts in)."""
    raw = os.environ.get("RACON_TPU_SCATTER_MIN_WALL_S", "")
    if not raw:
        return None
    try:
        value = float(raw)
    except ValueError:
        return None
    return value if value > 0 else None


def rebalance_factor():
    """The straggler threshold factor for r21 cross-shard
    rebalancing, or None when rebalancing is off.  A live shard whose
    elapsed wall exceeds ``factor x p50(predicted shard walls)`` (and
    at least four probe periods, so a fast plan never trips on probe
    jitter) gets a speculative replacement attempt under a derived
    ``-r<n>`` key.  Default 2.5; ``0`` (or any non-positive value)
    disables, unparsable input falls back to the default — placement
    policy only, epoch-excluded like every other scatter knob."""
    raw = os.environ.get("RACON_TPU_SCATTER_REBALANCE", "")
    try:
        value = float(raw or "2.5")
    except ValueError:
        value = 2.5
    return value if value > 0 else None


def max_shards() -> int:
    try:
        value = int(os.environ.get("RACON_TPU_SCATTER_MAX_SHARDS",
                                   "8") or "8")
    except ValueError:
        value = 8
    return max(1, value)


def parse_requested(value):
    """Normalize a submit frame's ``shards`` field.

    Returns None (absent — planner decides from the threshold),
    ``"auto"`` (one shard per eligible backend), or an int.  Raises
    ValueError on anything else so the router can answer
    ``bad_request`` before taking ownership of the job.
    """
    if value is None:
        return None
    if isinstance(value, str):
        text = value.strip().lower()
        if text == "auto":
            return "auto"
        try:
            value = int(text)
        except ValueError:
            raise ValueError(
                "shards must be an integer or 'auto'") from None
    if isinstance(value, bool) or not isinstance(value, int):
        raise ValueError("shards must be an integer or 'auto'")
    if not 0 <= value <= 4096:
        raise ValueError("shards must be in 0..4096 or 'auto'")
    return value


def plan_shards(requested, predicted_wall_s, n_eligible) -> int:
    """The shard count for one submit; <= 1 means run unsharded.

    * explicit K >= 1: honored, capped ONLY by
      RACON_TPU_SCATTER_MAX_SHARDS — never by the momentary eligible
      backend count.  A keyed retry must re-derive the same plan the
      original ran under (same derived keys → journal dedup), and
      eligibility is transient: a breaker that opened between the
      original and the retry must not change the plan.  Shards beyond
      the live backend count just queue behind siblings;
    * ``"auto"``: one shard per eligible backend, capped by
      MAX_SHARDS;
    * 0 / absent with no threshold: unsharded;
    * absent with RACON_TPU_SCATTER_MIN_WALL_S set: scatter only when
      the admission estimate exceeds the threshold, sized so each
      shard's predicted slice comes back under it (capped like auto).
    """
    cap = max(1, min(int(n_eligible), max_shards()))
    if isinstance(requested, int) and requested >= 1:
        return min(requested, max_shards())
    if requested == "auto":
        return cap
    threshold = min_wall_s()
    if requested == 0 or threshold is None \
            or predicted_wall_s is None \
            or predicted_wall_s <= threshold:
        return 1
    return min(math.ceil(predicted_wall_s / threshold), cap)


def shard_key(job_key: str, index: int, count: int) -> str:
    """The derived idempotence key for shard ``index`` of ``count``:
    ``<job_key>-shard-<i>of<k>``, kept inside the r17 journal key
    contract (1..128 chars of [A-Za-z0-9._:-]).  The count is baked
    in because the journal dedups by key alone — a duplicate that
    re-planned a different ``k`` must MISS the old records (its
    shards own different target slices) rather than be answered with
    the wrong bytes.  A base key too long to carry the suffix is
    folded to a digest — still deterministic in the base key, so a
    duplicate mega-job submit derives the same shard keys and dedups
    at the backend journals."""
    suffix = f"-shard-{index}of{count}"
    if len(job_key) + len(suffix) > 128:
        job_key = "sc-" + hashlib.sha256(
            job_key.encode("utf-8")).hexdigest()[:32]
    return job_key + suffix


def rebalance_key(job_key: str, index: int, count: int,
                  attempt: int) -> str:
    """The derived key for rebalance attempt ``n`` of shard ``i``:
    ``<job_key>-shard-<i>of<k>-r<n>`` (r21 straggler rebalancing).
    A DISTINCT key from the original's on purpose: the replacement
    is a fresh exactly-once unit at its own backend's journal, so it
    can never be answered from the straggler's records — first
    successful attempt wins the shard slot at the router.  Same
    length-folding rule as :func:`shard_key`, applied with the full
    suffix so the derived key stays inside the 128-char contract."""
    suffix = f"-shard-{index}of{count}-r{int(attempt)}"
    if len(job_key) + len(suffix) > 128:
        job_key = "sc-" + hashlib.sha256(
            job_key.encode("utf-8")).hexdigest()[:32]
    return job_key + suffix


def shard_spec(spec: dict, index: int, count: int,
               stage: dict = None) -> dict:
    """Shard ``index``'s sub-job spec: the mega-job's spec (tenant,
    inputs, options all inherited) plus the target shard and, when
    the router built a slice index at plan time, the shard's staged
    -input hint (r21; the receiving daemon validates it against its
    own view of the file before trusting it)."""
    sub = dict(spec)
    sub["shard"] = [int(index), int(count)]
    if stage is not None:
        sub["stage"] = stage
    return sub


def merge_responses(responses, keys) -> dict:
    """Gather: fold the K shard responses (in shard order) into one
    client frame.  The FASTA is a plain concatenation — byte-identical
    to the unsharded run by the target_slice contract — and the
    report is a merged metrics doc with per-shard sub-blocks.

    ``responses[i]`` is shard i's successful response frame body (the
    router already annotated ``routed_backend``); ``keys[i]`` its
    derived idempotence key.  The caller fills ``wall_s`` with the
    measured scatter wall (fan-out is concurrent, so shard walls
    don't sum).
    """
    fasta = b"".join(base64.b64decode(r["fasta_b64"])
                     for r in responses)
    per_shard = []
    for i, resp in enumerate(responses):
        est = resp.get("estimate") or {}
        per_shard.append({
            "shard": i,
            "job_key": keys[i],
            "backend": resp.get("routed_backend"),
            "job_id": resp.get("job_id"),
            "trace_id": resp.get("trace_id"),
            "n_sequences": resp.get("n_sequences"),
            "wall_s": resp.get("wall_s"),
            "predicted_wall_s": est.get("predicted_wall_s"),
        })
    return {
        "ok": True,
        "job_id": responses[0].get("job_id"),
        "n_sequences": fasta.count(b">"),
        "wall_s": None,   # router fills with the measured gather wall
        "fasta_b64": base64.b64encode(fasta).decode("ascii"),
        "report": {
            "schema": "racon-tpu-scatter-v1",
            "shards": len(responses),
            "per_shard": per_shard,
            "shard_reports": [r.get("report") for r in responses],
        },
    }
