"""Content-affinity scoring for fleet placement (r22).

The result cache keys units by full content digests, so "is backend
X warm for THIS job?" reduces to set membership: did X recently
cache units derived from the same input content?  Unit keys proper
(poa/wfa/band/scan) only exist after overlap parsing and window
construction — far too heavy for a router sizing up a submit — so
placement uses **job-level content digests**: a fixed, cheap sample
of digests over the submit's input files (size + head / middle /
tail chunks per role, shard-mask- and engine-epoch-folded).  Both
sides derive the identical sample from the spec alone:

* the daemon notes the sample into its cache sketch when a job
  completes (``rcache.note_content``) — "this content's units are
  warm here now";
* the router derives the same sample at submit and asks every
  backend's exported sketch what fraction it contains
  (:func:`racon_tpu.cache.sketch.hit_fraction`), feeding that
  estimate into the predicted-wall pricing as the ``hit_ratio``
  discount.

Folding the engine epoch into every digest means a backend running
a different knob environment — whose cached unit results would NOT
be reusable — naturally scores cold, without any cross-environment
negotiation; the exported sketch's epoch tag makes the same check
explicit and cheap.  Everything here prices placement only: a wrong
fraction (false positive, stale sketch, evicted-but-sticky counter)
routes a job somewhere slower, never changes its bytes.
"""

from __future__ import annotations

import hashlib
import os

from racon_tpu.cache import keying, sketch

#: per-role chunk size for the content digests (head/middle/tail)
CHUNK = 1 << 16

_ROLES = ("sequences", "overlaps", "targets")


def _file_digests(role: str, path: str, shard_tag: bytes,
                  epoch: bytes):
    """Up to four digests for one input file: whole-file signature
    (role + size) plus head/middle/tail chunk digests.  Unreadable
    files yield nothing — the sample just shrinks."""
    try:
        size = os.stat(path).st_size
    except OSError:
        return
    base = b"aff1|" + role.encode() + b"|" + shard_tag + b"|" + epoch
    h = hashlib.blake2b(digest_size=keying.DIGEST_SIZE)
    h.update(base + b"|size|%d" % size)
    yield h.digest()
    offsets = sorted({0, max(0, size // 2 - CHUNK // 2),
                      max(0, size - CHUNK)})
    try:
        with open(path, "rb") as f:
            for slot, off in enumerate(offsets):
                f.seek(off)
                chunk = f.read(CHUNK)
                h = hashlib.blake2b(digest_size=keying.DIGEST_SIZE)
                h.update(base + b"|c%d|%d|" % (slot, size))
                h.update(chunk)
                yield h.digest()
    except OSError:
        return


def job_digest_sample(spec: dict, epoch: bytes = None) -> list:
    """The submit's content-digest sample: up to 12 32-byte digests
    (4 per input role).  Deterministic in (input bytes, shard mask,
    engine epoch) — the same function on router and daemon yields
    the same sample for the same spec."""
    if epoch is None:
        epoch = keying.engine_epoch()
    shard = spec.get("shard")
    if isinstance(shard, (list, tuple)) and len(shard) == 2:
        shard_tag = b"s%d/%d" % (int(shard[0]), int(shard[1]))
    else:
        shard_tag = b"s0/1"
    out = []
    for role in _ROLES:
        path = spec.get(role)
        if isinstance(path, str) and path:
            out.extend(_file_digests(role, path, shard_tag, epoch))
    return out


def note_job_content(spec: dict) -> None:
    """Daemon side: mark a completed job's content sample warm in
    the local cache sketch.  Never raises — affinity bookkeeping
    must not fail a finished job."""
    try:
        from racon_tpu import cache as rcache

        if not rcache.enabled():
            return
        for digest in job_digest_sample(spec):
            rcache.note_content(digest)
    except Exception:
        pass


def backend_hit_fraction(sketch_doc, sample, epoch_hex: str):
    """Router side: estimated fraction of ``sample`` warm in one
    backend's exported sketch.  None — "no usable sketch, fall back"
    — when the doc is absent/undecodable or tagged with a different
    engine epoch than ours (its cached units are not reusable
    here)."""
    if not sample or not isinstance(sketch_doc, dict):
        return None
    if sketch_doc.get("epoch") != epoch_hex:
        return None
    bits = sketch.decode_bits(sketch_doc)
    if bits is None:
        return None
    hits = sum(1 for d in sample if sketch.bits_contain(bits, d))
    return hits / len(sample)
