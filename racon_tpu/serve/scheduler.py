"""Bounded priority job queue with priced admission control.

The device-side analog of the reference's per-GPU batch queues
(src/cuda/cudapolisher.cpp:257-336), lifted one level: instead of
windows queuing for one run's batches, whole polish JOBS queue for
the process's warm engines.

* **Admission control** — every submission is priced before it
  enters the queue: input file sizes feed a bytes-proportional
  align/POA wall model whose combination comes from
  :func:`racon_tpu.utils.calibrate.predict_walls` (the r8 overlapped
  budget model), and ``RACON_TPU_SERVE_MAX_WALL_S`` (unset = no cap)
  rejects jobs whose predicted wall exceeds the cap with a
  ``job_too_large`` error carrying the estimate.
* **Backpressure** — the queue is bounded (``RACON_TPU_SERVE_QUEUE``,
  default 8 pending jobs).  A submission past the bound is rejected
  immediately with a machine-readable ``queue_full`` error (depth +
  bound included) instead of blocking the connection: the caller —
  e.g. a fleet scheduler — decides whether to retry, reroute or shed.
* **Multi-job scheduling** — ``RACON_TPU_SERVE_JOBS`` worker threads
  (default 2) pop jobs in (priority desc, FIFO) order and run them
  concurrently; each job runs as a *tenant* of the process-wide
  device executor (racon_tpu/tpu/executor.py), so concurrent jobs'
  compatible megabatches FUSE into shared full batches instead of
  merely interleaving half-empty ones, with weighted deficit-round-
  robin fairness and a per-tenant in-flight quota
  (``RACON_TPU_SERVE_TENANT_QUOTA``) keeping a streaming mega-job
  from starving small tenants.  Output bytes stay per-job
  deterministic: each job owns its polisher, engine assignment
  inside a polisher is a pure function of that job's input, and the
  executor demuxes fused results by submission slice (see
  racon_tpu/serve/__init__.py).
* **Lifecycle** — ``pause()``/``resume()`` gate the workers without
  touching running jobs (maintenance windows; also what makes the
  backpressure/drain tests timing-independent); ``drain()`` stops
  admission (``draining`` rejects), lets queued+running jobs finish,
  and returns.
"""

from __future__ import annotations

import heapq
import itertools
import os
import threading
from typing import Callable, Optional

from racon_tpu.obs import REGISTRY
from racon_tpu.obs.metrics import hist_quantile
from racon_tpu.obs import context as obs_context
from racon_tpu.obs import decision as obs_decision
from racon_tpu.obs import faultinject
from racon_tpu.obs import flight as obs_flight
from racon_tpu.obs import trace as obs_trace


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


# bytes-per-second priors for the admission price: deliberately crude
# (admission only needs the right order of magnitude to shed a
# monster job) and deliberately NOT the in-run calibrated rates --
# admission prices from file sizes before anything is parsed, and a
# pure-stat model keeps the accept/reject decision a function of the
# submission alone.  RACON_TPU_SERVE_{ALIGN,POA}_MBPS override.
_ALIGN_MB_PER_S = 4.0
_POA_MB_PER_S = 2.0
# r24 internal mapping prior: minimizer extraction + chaining over
# reads+draft bytes (RACON_TPU_SERVE_MAP_MBPS overrides)
_MAP_MB_PER_S = 8.0


def _mean_fusion_occupancy() -> float:
    """Mean of the executor's ``fusion_occupancy`` histogram (0.0
    before any fused dispatch) — the measured input to the r13
    shared-pricing model."""
    h = REGISTRY.snapshot()["histograms"].get("fusion_occupancy")
    if not h or not h.get("count"):
        return 0.0
    return h["sum"] / h["count"]


def _observed_hit_ratio() -> float:
    """Process-wide result-cache hit ratio (r18): hits over lookups
    since daemon start, 0.0 with no traffic or with the cache off.
    Trailing and cross-job by construction — exactly the crudeness
    the admission price already accepts for rates and occupancy."""
    from racon_tpu import cache as rcache

    if not rcache.enabled():
        return 0.0
    hits = REGISTRY.value("cache_hit")
    total = hits + REGISTRY.value("cache_miss")
    return hits / total if total else 0.0


# -- r22 deadline classes ----------------------------------------------
#: valid values for a submission's ``class`` field; rank orders
#: same-priority jobs in the queue (lower rank pops first)
JOB_CLASSES = ("interactive", "batch")
_CLASS_RANK = {"interactive": 0, "batch": 1}


def class_target_p99_s() -> float:
    """The interactive queue-wait SLO target the class machinery
    steers toward (seconds).  Policy plane only."""
    try:
        return float(os.environ.get("RACON_TPU_CLASS_TARGET_P99_S",
                                    "2.0"))
    except ValueError:
        return 2.0


def class_headroom() -> float:
    """Base fraction of the queue reserved for interactive work when
    batch admission is throttled (scaled up by observed SLO misses)."""
    try:
        return min(0.9, max(0.0, float(os.environ.get(
            "RACON_TPU_CLASS_HEADROOM", "0.125"))))
    except ValueError:
        return 0.125


def _class_wait_p99(job_class: str):
    """Observed queue-wait p99 for one class
    (``serve_class_wait_s.<class>``), or None before any job of that
    class has been popped."""
    h = REGISTRY.snapshot()["histograms"].get(
        f"serve_class_wait_s.{job_class}")
    if not h or not h.get("count"):
        return None
    return hist_quantile(h, 0.99)


def _retry_after_hint_s(pending: int, max_jobs: int,
                        job_class: str = None) -> float:
    """Server-priced backoff hint for retryable rejects (r19).

    The mean observed exec wall (``serve_exec_wall_s``) divided by
    the worker count approximates the drain rate, so ``pending``
    jobs clear in about ``mean * pending / max_jobs`` seconds.
    Before any job has run the mean is unknown; 1 s stands in.
    Clamped to 0.25..30 s — the hint guides a retry schedule, it is
    not a promise.  With a ``job_class`` (r22) the hint prices from
    that class's own exec-wall histogram when it has data — a batch
    job retrying against a fleet of short interactive jobs should
    not be told to come back in 250 ms."""
    hists = REGISTRY.snapshot()["histograms"]
    h = hists.get(f"serve_class_exec_s.{job_class}") \
        if job_class else None
    if not h or not h.get("count"):
        h = hists.get("serve_exec_wall_s")
    mean = h["sum"] / h["count"] if h and h.get("count") else 1.0
    return round(min(30.0, max(
        0.25, mean * max(1, pending) / max(1, max_jobs))), 3)


def estimate_job(spec: dict, concurrency: int = 1,
                 hit_ratio: float = None) -> dict:
    """Price a submission from input stats alone.

    Returns the :func:`calibrate.predict_walls` dict (additive wall,
    overlapped floor, predicted wall — plus ``shared_wall_s`` when
    the job would share the device with ``concurrency - 1`` others)
    plus the raw inputs that produced it, so a reject is auditable
    from the response.

    ``hit_ratio`` overrides the trailing process-wide cache ratio in
    the r18 discount — the fleet router passes its per-backend
    sketch-estimated hit fraction here (r22), so the SAME pricing
    model answers both "can this daemon take the job" and "which
    daemon's cache already holds this job's units"."""
    from racon_tpu.utils import calibrate

    sizes = {}
    for key in ("sequences", "overlaps", "targets"):
        path = spec.get(key)
        # r24: overlaps may be absent (internal mapping); the map
        # stage is priced separately below
        sizes[key] = os.stat(path).st_size if path is not None else 0
    align_mbps = float(os.environ.get("RACON_TPU_SERVE_ALIGN_MBPS",
                                      _ALIGN_MB_PER_S))
    poa_mbps = float(os.environ.get("RACON_TPU_SERVE_POA_MBPS",
                                    _POA_MB_PER_S))
    mb = 1024.0 * 1024.0
    # r21 staged shards: a sub-job carrying a stage hint parses only
    # its slice of the overlaps, so the parse/align term prices the
    # STAGED byte fraction, not the full file — before this, scatter
    # thresholds and placement overestimated every shard's wall by
    # the redundant (K-1)/K parse it no longer does
    overlap_bytes = sizes["overlaps"]
    staged_fraction = None
    stage = spec.get("stage")
    if isinstance(stage, dict):
        try:
            sb = int(stage.get("staged_bytes", 0))
            tb = int(stage.get("total_bytes", 0))
        except (TypeError, ValueError):
            sb = tb = 0
        if tb > 0 and 0 <= sb <= tb:
            staged_fraction = sb / tb
            overlap_bytes = sizes["overlaps"] * staged_fraction
    # align work scales with the read+overlap volume, POA with the
    # read volume layered over the targets
    align_s = (sizes["sequences"] + overlap_bytes) / mb / align_mbps
    poa_s = (sizes["sequences"] + sizes["targets"]) / mb / poa_mbps
    # r24 internal mapping: a no-overlaps spec runs the minimap-lite
    # map stage over reads+targets before aligning; priced from its
    # own throughput prior.  A stale externally-supplied PAF never
    # reaches rounds > 1 either — every round past the first re-maps,
    # so the whole pipeline repeats per round.
    map_s = 0.0
    rounds = spec.get("rounds")
    rounds = rounds if isinstance(rounds, int) and rounds >= 1 else 1
    if spec.get("overlaps") is None:
        map_mbps = float(os.environ.get("RACON_TPU_SERVE_MAP_MBPS",
                                        _MAP_MB_PER_S))
        map_s = (sizes["sequences"] + sizes["targets"]) / mb / map_mbps
        align_s += map_s
    if hit_ratio is None:
        hit_ratio = _observed_hit_ratio()
    est = calibrate.predict_walls(align_s, poa_s,
                                  overlap_s=min(align_s, poa_s),
                                  concurrency=concurrency,
                                  occupancy=_mean_fusion_occupancy(),
                                  hit_ratio=hit_ratio)
    if rounds > 1:
        # later rounds re-map + re-polish; cache reuse of unchanged
        # windows is already folded in through hit_ratio
        for field in ("additive_wall_s", "overlap_floor_s",
                      "predicted_wall_s", "shared_wall_s"):
            if isinstance(est.get(field), (int, float)):
                est[field] = round(est[field] * rounds, 6)
        est["rounds"] = rounds
    if map_s > 0.0:
        est["map_s"] = round(map_s, 6)
    est["input_bytes"] = sizes
    if staged_fraction is not None:
        est["staged_fraction"] = round(staged_fraction, 6)
        est["input_bytes"] = dict(sizes)
        est["input_bytes"]["overlaps_staged"] = int(overlap_bytes)
    return est


class Job:
    """One queued submission: spec + completion rendezvous."""

    def __init__(self, job_id: int, spec: dict, priority: int,
                 estimate: dict, tenant: str = "default",
                 trace_context: str = None,
                 job_class: str = "interactive"):
        self.id = job_id
        self.spec = spec
        self.priority = priority
        self.estimate = estimate
        self.tenant = tenant
        # r22 deadline class: orders same-priority work (interactive
        # ahead of batch), steers DRR weight and batch admission
        # headroom — policy only, never bytes
        self.job_class = job_class
        # durability plane (r17, all None/unset when the journal is
        # off): the idempotence key, the write-ahead journal handle
        # the session's checkpoint callback appends through, the
        # replayed resume payload ({"windows": ..., "calib": ...}),
        # the admission-time calibration-epoch snapshot, and the
        # dead incarnation's "<pid>:<id>" this job was requeued from
        self.job_key: Optional[str] = None
        self.journal = None
        self.resume: Optional[dict] = None
        self.calib: Optional[dict] = None
        self.recovered_from: Optional[str] = None
        # the job's trace id is fixed AT ADMISSION: a caller-supplied
        # wire trace context (r15) wins, else the deterministic
        # per-process id — so the admit flight event, the worker's
        # job context and every span/flight event inside the job all
        # carry the same id, across however many daemons a logical
        # request touched
        self.trace_id = trace_context or \
            obs_context.make_trace_id(job_id)
        self.t_submit: Optional[float] = None   # admission timestamp
        self.done = threading.Event()
        self.result: Optional[dict] = None   # set exactly once
        # r21 rebalancing: set by JobScheduler.cancel(); a queued job
        # finishes as job_canceled without running, a running one
        # stops at the polisher's next between-units poll site
        self.cancel_requested = threading.Event()

    def finish(self, result: dict) -> None:
        self.result = result
        self.done.set()


class RejectError(Exception):
    """Admission refusal; ``.error`` is the machine-readable dict."""

    def __init__(self, error: dict):
        super().__init__(error.get("reason", error.get("code")))
        self.error = error


class JobScheduler:
    def __init__(self, runner: Callable[[Job], dict],
                 max_queue: int = None, max_jobs: int = None):
        self._runner = runner
        self.max_queue = (max_queue if max_queue is not None
                          else _env_int("RACON_TPU_SERVE_QUEUE", 8))
        self.max_jobs = max(1, max_jobs if max_jobs is not None
                            else _env_int("RACON_TPU_SERVE_JOBS", 2))
        self._cond = threading.Condition()
        self._heap: list = []   # (-priority, class_rank, seq, Job)
        self._seq = itertools.count()
        self._ids = itertools.count(1)
        self._running: dict = {}         # job_id -> Job
        # idempotence plane (r17): live jobs by key (duplicate keyed
        # submit rendezvous on the SAME Job), terminal outcomes by
        # key (duplicate after completion/restart answers from the
        # record), and the write-ahead journal (None = disabled)
        self._by_key: dict = {}          # job_key -> live Job
        self._completed_by_key: dict = {}  # job_key -> result body
        self._journal = None
        self._paused = False
        self._draining = False
        self._stopped = False
        self._completed = 0
        # r22 drift-triggered recalibration: job boundaries left
        # before drift flags may open another epoch (the calhealth
        # registry gauge keeps its stale value until the first
        # post-recalibration observation, so reopening immediately
        # would re-trigger on old data)
        self._drift_cooldown = 0
        self._workers = [
            threading.Thread(target=self._worker_loop, daemon=True,
                             name=f"racon-serve-worker-{i}")
            for i in range(self.max_jobs)]
        for t in self._workers:
            t.start()

    # -- durability plane (r17) ----------------------------------------

    def attach_journal(self, journal) -> None:
        """Wire the write-ahead journal into the admission and worker
        paths.  The server attaches it BEFORE binding the socket, so
        no submission can race an unjournaled window."""
        self._journal = journal

    def preload_completed(self, results: dict) -> None:
        """Seed the idempotence index with terminal outcomes replayed
        from a previous incarnation's journal (job_key -> result
        frame body): a duplicate keyed submit is answered from the
        record instead of re-running."""
        with self._cond:
            self._completed_by_key.update(results)

    def _journal_append(self, kind: str, **fields) -> None:
        """Best-effort journal write for the worker path: a full disk
        must fail the JOURNAL (counted, visible in health), not the
        job that already ran."""
        if self._journal is None:
            return
        try:
            self._journal.append(kind, **fields)
        except OSError:
            REGISTRY.add("serve_journal_errors")

    def _finished_job(self, job_key: str, result: dict) -> Job:
        """A pre-finished Job wrapping a recorded terminal outcome —
        what a duplicate keyed submit rendezvous on."""
        job = Job(int(result.get("job_id") or 0), None, 0, None)
        job.job_key = job_key
        job.finish(dict(result))
        return job

    def _dedup_lookup(self, job_key: str) -> Optional[Job]:
        """Under ``_cond``: the Job a duplicate keyed submit should
        join, or None if the key is new."""
        done = self._completed_by_key.get(job_key)
        if done is not None:
            return self._finished_job(job_key, done)
        return self._by_key.get(job_key)

    # -- admission -----------------------------------------------------

    def submit(self, spec: dict, priority: int = 0,
               trace_context: str = None, job_key: str = None,
               resume: dict = None,
               recovered_from: str = None) -> Job:
        """Admit a job or raise :class:`RejectError`.  Never blocks on
        queue capacity — backpressure is an immediate structured
        reject, so a full server answers in microseconds.
        ``trace_context`` is the caller's wire trace id (r15): the
        job adopts it as its trace id, so forensics from every daemon
        a logical request touched stitch on one id.

        r17 durability: ``job_key`` is the client's idempotence key —
        a duplicate submit joins the live job or is answered from the
        recorded outcome, never re-run.  ``resume`` /
        ``recovered_from`` are recovery-internal
        (racon_tpu/serve/recover.py): the replayed megabatch
        checkpoints + calibration pin of an interrupted job being
        requeued from a dead incarnation."""
        try:
            return self._submit(spec, priority, trace_context,
                                job_key=job_key, resume=resume,
                                recovered_from=recovered_from)
        except RejectError as exc:
            obs_flight.FLIGHT.record(
                "reject",
                tenant=(spec.get("tenant")
                        if isinstance(spec, dict) else None),
                code=exc.error.get("code"),
                trace_id=trace_context,
                predicted_wall_s=(exc.error.get("estimate") or {})
                .get("predicted_wall_s"))
            raise

    def _submit(self, spec: dict, priority: int,
                trace_context: str = None, job_key: str = None,
                resume: dict = None,
                recovered_from: str = None) -> Job:
        if trace_context is not None and \
                not obs_context.valid_trace_id(trace_context):
            raise RejectError({
                "code": "bad_request",
                "reason": "trace_context must be 1..128 chars of "
                          "[A-Za-z0-9._:-] starting alphanumeric"})
        if job_key is not None and \
                not obs_context.valid_trace_id(job_key):
            raise RejectError({
                "code": "bad_request",
                "reason": "job_key must be 1..128 chars of "
                          "[A-Za-z0-9._:-] starting alphanumeric"})
        # idempotence fast path BEFORE input validation: a duplicate
        # of a recorded job must be answered from the record even if
        # its inputs were cleaned up since the original ran
        if job_key is not None:
            with self._cond:
                hit = self._dedup_lookup(job_key)
            if hit is not None:
                REGISTRY.add("serve_dedup_hits")
                obs_flight.FLIGHT.record(
                    "dedup", job=hit.id, job_key=job_key,
                    trace_id=trace_context,
                    recorded=hit.done.is_set())
                return hit
        for key in ("sequences", "overlaps", "targets"):
            path = spec.get(key)
            if key == "overlaps" and path is None:
                # r24: overlaps are optional WHEN the spec opts into
                # internal mapping by carrying a rounds count.  A
                # bare no-overlaps spec gets a structured reject
                # (not the generic input_not_found) telling the
                # client exactly how to opt in.
                if spec.get("rounds") is not None:
                    continue
                raise RejectError({
                    "code": "missing_overlaps",
                    "reason": "spec has no overlaps input and does "
                              "not request internal mapping",
                    "hint": "resubmit with --rounds N (spec field "
                            "\"rounds\") to map reads against the "
                            "draft with the built-in mapper, or "
                            "supply a PAF/MHAP/SAM overlaps path"})
            if not isinstance(path, str):
                raise RejectError({"code": "bad_request",
                                   "reason": f"missing input '{key}'"})
            if not os.path.isfile(path):
                raise RejectError({
                    "code": "input_not_found",
                    "reason": f"{key} file not found on the server "
                              f"host: {path}"})
        rounds = spec.get("rounds")
        if rounds is not None and (not isinstance(rounds, int)
                                   or isinstance(rounds, bool)
                                   or not 1 <= rounds <= 16):
            raise RejectError({
                "code": "bad_request",
                "reason": "rounds must be an integer in [1, 16]"})
        tenant = spec.get("tenant", "default")
        if not isinstance(tenant, str) or not tenant \
                or len(tenant) > 64:
            raise RejectError({
                "code": "bad_request",
                "reason": "tenant must be a non-empty string "
                          "of at most 64 characters"})
        # r22 deadline class: optional, validated at admission.  The
        # class rides the spec, so routed scatter sub-jobs inherit
        # the mega-job's class like they inherit tenant/priority.
        job_class = spec.get("class", "interactive")
        if job_class not in JOB_CLASSES:
            raise RejectError({
                "code": "bad_request",
                "reason": "class must be one of "
                          + "/".join(JOB_CLASSES)})
        # r20 scatter: a routed sub-job carries its target shard as
        # spec["shard"] = [index, count] (tenant/priority already ride
        # the spec/frame, so a shard inherits both from the mega-job).
        # Validate the shape at admission — a malformed shard must be
        # a bad_request, not a mid-polish job_failed.
        shard = spec.get("shard")
        if shard is not None:
            ok_shape = (isinstance(shard, (list, tuple))
                        and len(shard) == 2
                        and all(isinstance(x, int)
                                and not isinstance(x, bool)
                                for x in shard))
            if not ok_shape or not 0 <= shard[0] < shard[1] \
                    or shard[1] > 4096:
                raise RejectError({
                    "code": "bad_request",
                    "reason": "shard must be [index, count] with "
                              "0 <= index < count <= 4096"})
        # r21 staging: a routed sub-job may carry the router's slice
        # index as spec["stage"].  Validate the shape at admission;
        # the polisher re-validates content (path + file signature)
        # and silently full-parses on mismatch, so only structurally
        # broken hints are rejected here.
        stage = spec.get("stage")
        if stage is not None:
            from racon_tpu.io import staging
            stage_err = staging.validate_stage_field(stage)
            if stage_err is not None:
                raise RejectError({"code": "bad_request",
                                   "reason": stage_err})
        # price against the load the job would actually share the
        # device with (approximate read outside the lock is fine --
        # admission only needs the right order of magnitude)
        with self._cond:
            concurrency = len(self._running) + len(self._heap) + 1
        estimate = estimate_job(spec, concurrency=concurrency)
        cap = os.environ.get("RACON_TPU_SERVE_MAX_WALL_S")
        priced = estimate.get("shared_wall_s",
                              estimate["predicted_wall_s"])
        if cap and priced > float(cap):
            REGISTRY.add("serve_reject.job_too_large")
            raise RejectError({
                "code": "job_too_large",
                "reason": f"predicted wall {priced:.1f}s "
                          f"(at concurrency {concurrency}) exceeds "
                          f"RACON_TPU_SERVE_MAX_WALL_S={cap}",
                "estimate": estimate})
        with self._cond:
            if self._draining:
                REGISTRY.add("serve_reject.draining")
                raise RejectError({
                    "code": "draining",
                    "reason": "server is draining: running jobs "
                              "finish, new jobs are rejected",
                    "retry_after_s": _retry_after_hint_s(
                        len(self._heap) + len(self._running),
                        self.max_jobs, job_class=job_class)})
            if len(self._heap) >= self.max_queue:
                REGISTRY.add("serve_reject.queue_full")
                raise RejectError({
                    "code": "queue_full",
                    "reason": "job queue is at capacity; retry later",
                    "queue_depth": len(self._heap),
                    "max_queue": self.max_queue,
                    "running": len(self._running),
                    # one slot must free before a retry can admit
                    "retry_after_s": _retry_after_hint_s(
                        1, self.max_jobs, job_class=job_class)})
            if job_class == "batch":
                # r22 SLO-driven admission headroom: the queue's tail
                # slots are reserved for interactive work, and the
                # reservation GROWS while the observed interactive
                # queue-wait p99 misses its target — admission derives
                # from measured SLO attainment, not static priority
                reserve = self._batch_reserved_slots()
                if reserve and \
                        len(self._heap) >= self.max_queue - reserve:
                    REGISTRY.add("serve_reject.class_headroom")
                    raise RejectError({
                        "code": "queue_full",
                        "reason": "queue headroom reserved for "
                                  "interactive class; retry later",
                        "queue_depth": len(self._heap),
                        "max_queue": self.max_queue,
                        "reserved_slots": reserve,
                        "running": len(self._running),
                        "retry_after_s": _retry_after_hint_s(
                            1, self.max_jobs, job_class=job_class)})
            if job_key is not None:
                # re-check under the admission lock: two concurrent
                # NEW submits with the same key must admit once
                hit = self._dedup_lookup(job_key)
                if hit is not None:
                    REGISTRY.add("serve_dedup_hits")
                    obs_flight.FLIGHT.record(
                        "dedup", job=hit.id, job_key=job_key,
                        trace_id=trace_context,
                        recorded=hit.done.is_set())
                    return hit
            job = Job(next(self._ids), spec, priority, estimate,
                      tenant=tenant, trace_context=trace_context,
                      job_class=job_class)
            job.t_submit = obs_trace.now()
            job.resume = resume
            job.recovered_from = recovered_from
            job.journal = self._journal
            if self._journal is not None:
                # every journaled job has a key — client-supplied or
                # daemon-minted — because replay merges records
                # across incarnations by key
                job.job_key = job_key or \
                    f"auto-{os.getpid()}-{job.id}"
                # the calibration epoch the job is pinned to: a
                # requeued job carries its ORIGINAL admission
                # snapshot forward (byte-identity across restart),
                # a fresh job snapshots now
                if resume and isinstance(resume.get("calib"), dict):
                    job.calib = resume["calib"]
                else:
                    from racon_tpu.utils import calibrate
                    job.calib = calibrate.epoch_snapshot()
                # write-AHEAD: the admit record is durable before the
                # job is queued (a crash after this line replays it)
                self._journal_append(
                    "admit", job=job.id, job_key=job.job_key,
                    spec=spec, priority=priority, tenant=tenant,
                    trace_id=job.trace_id, calib=job.calib,
                    recovered_from=recovered_from)
            else:
                job.job_key = job_key
            if job.job_key:
                self._by_key[job.job_key] = job
            faultinject.hit("post-admit")
            heapq.heappush(self._heap,
                           (-priority, _CLASS_RANK[job_class],
                            next(self._seq), job))
            REGISTRY.add("serve_jobs_submitted")
            REGISTRY.add("serve_admit")
            REGISTRY.peak("serve_queue_high_water", len(self._heap))
            REGISTRY.set("serve_queue_depth", len(self._heap))
            obs_trace.TRACER.add_instant(
                "serve.submit", cat="serve",
                args={"job": job.id, "tenant": tenant,
                      "trace_id": job.trace_id,
                      "priority": priority,
                      "queue_depth": len(self._heap)})
            obs_flight.FLIGHT.record(
                "admit", job=job.id, tenant=tenant,
                trace_id=job.trace_id, job_key=job.job_key,
                priority=priority, job_class=job_class,
                shard=(list(shard) if shard is not None else None),
                predicted_wall_s=round(
                    estimate.get("predicted_wall_s", 0.0), 4),
                shared_wall_s=(round(estimate["shared_wall_s"], 4)
                               if "shared_wall_s" in estimate
                               else None),
                queue_depth=len(self._heap))
            self._cond.notify()
            return job

    # -- r22 deadline-class policy -------------------------------------

    #: a queued batch job older than this many interactive p99
    #: targets jumps the class ordering — the starvation bound
    CLASS_STARVATION_FACTOR = 4.0

    def _batch_reserved_slots(self) -> int:
        """Queue slots reserved for interactive admissions while
        batch is throttled.  The base reservation is
        ``RACON_TPU_CLASS_HEADROOM`` of the queue; while the observed
        interactive queue-wait p99 exceeds
        ``RACON_TPU_CLASS_TARGET_P99_S`` the reservation scales with
        the miss ratio (capped at half the queue) — measured SLO
        attainment drives admission, not static priority."""
        frac = class_headroom()
        if frac <= 0.0:
            return 0
        target = class_target_p99_s()
        p99 = _class_wait_p99("interactive")
        if target > 0 and p99 is not None and p99 > target:
            frac = min(0.5, frac * min(4.0, p99 / target))
        return min(self.max_queue - 1,
                   int(self.max_queue * frac + 0.5))

    def _class_weight(self, job) -> float:
        """DRR weight for a job's executor tenancy, derived from
        observed per-class SLO attainment (r22) instead of static
        priority alone.  Interactive work always carries at least 2x
        batch weight; when its observed queue-wait p99 misses the
        target, the weight scales with the miss ratio (capped 8x) so
        the executor's deficit-round-robin leans harder toward the
        class that is actually late.  Priority still floors the
        weight, so explicit priorities keep meaning."""
        base = max(1.0, 1.0 + job.priority)
        if job.job_class != "interactive":
            return base
        target = class_target_p99_s()
        p99 = _class_wait_p99("interactive")
        if target <= 0 or p99 is None:
            return max(base, 2.0)
        return max(base, min(8.0, 2.0 * max(1.0, p99 / target)))

    def _pop_next_job(self):
        """Pop the next job honoring the class order with a
        starvation bound: normally strict heap order (priority, then
        interactive-before-batch, then FIFO), but a batch job queued
        longer than CLASS_STARVATION_FACTOR x the interactive p99
        target jumps ahead of an interactive head — so a steady
        interactive stream can delay batch work only boundedly.
        Called under the lock with a non-empty heap."""
        head = self._heap[0][-1]
        bound = self.CLASS_STARVATION_FACTOR * class_target_p99_s()
        if head.job_class == "interactive" and bound > 0:
            now = obs_trace.now()
            aged = [e for e in self._heap
                    if e[-1].job_class == "batch"
                    and e[-1].t_submit is not None
                    and now - e[-1].t_submit > bound]
            if aged:
                entry = min(aged, key=lambda e: e[-1].t_submit)
                self._heap.remove(entry)
                heapq.heapify(self._heap)
                REGISTRY.add("serve_class_aged_pops")
                obs_flight.FLIGHT.record(
                    "class_age_pop", job=entry[-1].id,
                    tenant=entry[-1].tenant,
                    waited_s=round(now - entry[-1].t_submit, 3))
                return entry[-1]
        return heapq.heappop(self._heap)[-1]

    # -- workers -------------------------------------------------------

    def _worker_loop(self) -> None:
        while True:
            with self._cond:
                # event-driven wakeup: every transition that could
                # unblock a worker (submit/resume/start_drain/stop)
                # notifies, so no timeout-poll -- a submission admits
                # the instant a worker is free instead of up to 500 ms
                # later, and an idle daemon stops waking 2x/s
                while not self._stopped and (
                        self._paused or not self._heap):
                    self._cond.wait()
                if self._stopped:
                    return
                job = self._pop_next_job()
                self._running[job.id] = job
                REGISTRY.set("serve_queue_depth", len(self._heap))
                REGISTRY.set("serve_running", len(self._running))
            # SLO clocks: queue wait (admission -> pop), exec wall
            # (pop -> finish), e2e wall (admission -> finish).
            # Observability only -- nothing downstream reads them.
            t_pop = obs_trace.now()
            queue_wait = None
            if job.t_submit is not None:
                queue_wait = t_pop - job.t_submit
                REGISTRY.observe("serve_queue_wait_s", queue_wait)
                REGISTRY.observe(
                    f"serve_queue_wait_s.{job.tenant}", queue_wait)
                REGISTRY.observe(
                    f"serve_class_wait_s.{job.job_class}", queue_wait)
            obs_flight.FLIGHT.record(
                "start", job=job.id, tenant=job.tenant,
                trace_id=job.trace_id, job_key=job.job_key,
                queue_wait_s=(round(queue_wait, 6)
                              if queue_wait is not None else None))
            if job.job_key:
                self._journal_append("start", job=job.id,
                                     job_key=job.job_key,
                                     tenant=job.tenant)
            if job.cancel_requested.is_set():
                # r21: canceled while still queued — finish through
                # the normal terminal path (journal record + dedup
                # index + rendezvous) without ever running
                result = {
                    "ok": False,
                    "error": {"code": "job_canceled",
                              "reason": "job canceled before start "
                                        "(superseded by a rebalanced "
                                        "attempt)"}}
            else:
                # the job is a device-executor tenant for its
                # lifetime: its megabatches fuse with other registered
                # tenants', under the executor's DRR fairness +
                # in-flight quota
                from racon_tpu.tpu import executor as device_executor

                ex = device_executor.get_executor()
                ex.register_tenant(job.tenant,
                                   weight=self._class_weight(job))
                # the job context makes everything recorded during
                # this job's execution — spans, flight events, log
                # lines — attributable to (job, tenant) with no
                # call-site plumbing
                with obs_context.job_context(job.id, job.tenant,
                                             trace_id=job.trace_id):
                    try:
                        result = self._runner(job)
                    except Exception as exc:  # runner bug: job fails,
                        obs_flight.FLIGHT.record_exception(  # server
                            "error", exc)          # and queue survive
                        result = {
                            "ok": False,
                            "error": {"code": "job_failed",
                                      "type": type(exc).__name__,
                                      "reason": str(exc)}}
                    finally:
                        ex.release_tenant(job.tenant)
            t_done = obs_trace.now()
            exec_wall = t_done - t_pop
            obs_trace.TRACER.add_span(
                "serve.exec", t_pop, t_done, cat="serve",
                args={"job": job.id, "tenant": job.tenant,
                      "trace_id": job.trace_id,
                      "ok": bool(result.get("ok"))})
            obs_flight.FLIGHT.record(
                "done", job=job.id, tenant=job.tenant,
                trace_id=job.trace_id, job_key=job.job_key,
                ok=bool(result.get("ok")),
                exec_wall_s=round(exec_wall, 6))
            REGISTRY.observe("serve_exec_wall_s", exec_wall)
            REGISTRY.observe(
                f"serve_class_exec_s.{job.job_class}", exec_wall)
            if job.t_submit is not None:
                REGISTRY.observe("serve_e2e_wall_s",
                                 t_done - job.t_submit)
            # predicted-vs-actual drift of the admission price
            # (calibrate.predict_walls): ratio 1.0 = perfect, the
            # histogram's spread IS the model error
            predicted = (job.estimate or {}).get("predicted_wall_s", 0)
            if predicted and predicted > 0:
                REGISTRY.observe("serve_wall_err_ratio",
                                 exec_wall / predicted)
                # decision-plane twin (r16): the job-level admission
                # drift as an exemplar, so `explain --job N` shows the
                # headline predicted-vs-actual next to the per-stage
                # attribution
                obs_decision.DECISIONS.record(
                    "job_wall", job=job.id, tenant=job.tenant,
                    trace_id=job.trace_id,
                    predicted_s=round(float(predicted), 6),
                    measured_s=round(exec_wall, 6),
                    ratio=round(exec_wall / predicted, 6))
            if result.get("ok"):
                # r22 content affinity: this job's content is warm in
                # the local result cache now — note its digest sample
                # into the sketch the fleet router prices against
                from racon_tpu.serve import affinity

                affinity.note_job_content(job.spec)
            # r22 drift-triggered recalibration: a job boundary is
            # the only place a new calibration epoch may open (jobs
            # in flight keep their r17 pinned rates)
            self._drift_epoch_tick()
            # r23 forensics: the response frame names its trace id, so
            # the fleet assembler correlates shard responses (and
            # journal-deduped replays, which reuse the recorded frame)
            # without guessing; observability-only — FASTA bytes are
            # untouched
            if isinstance(result, dict) and result.get("ok"):
                result.setdefault("trace_id", job.trace_id)
            # terminal record BEFORE the client rendezvous: once the
            # caller sees the result, any crash must replay it from
            # the journal, not re-run the job
            faultinject.hit("pre-done-record")
            if job.job_key:
                if result.get("ok"):
                    self._journal_append("done", job=job.id,
                                         job_key=job.job_key,
                                         result=result)
                else:
                    self._journal_append("error", job=job.id,
                                         job_key=job.job_key,
                                         error=result.get("error"))
            with self._cond:
                del self._running[job.id]
                self._completed += 1
                if job.job_key:
                    self._completed_by_key[job.job_key] = result
                    self._by_key.pop(job.job_key, None)
                REGISTRY.set("serve_running", len(self._running))
                self._cond.notify_all()
            job.finish(result)

    #: job boundaries to wait after a drift epoch closes before
    #: drift flags may open another one
    DRIFT_REOPEN_COOLDOWN = 5

    def _drift_epoch_tick(self) -> None:
        """r22 drift-triggered recalibration, called once per job
        boundary from the worker loop.  When any calhealth stage's
        EWMA drift ratio has left the advisory band, open a
        calibration epoch (calibrate.open_drift_epoch lifts the
        serve-mode freeze for one two-pass recalibration); while an
        epoch is open, count boundaries until it closes.  Policy
        plane only: new rates affect pricing/pacing of jobs admitted
        AFTER they persist — in-flight jobs keep their r17 pinned
        epoch snapshot, so bytes never drift within a job."""
        from racon_tpu.utils import calibrate

        try:
            if not calibrate.drift_epoch_enabled():
                return
            if calibrate.drift_epoch_state()["open"]:
                if calibrate.note_drift_job():
                    # epoch just closed: freeze re-arms, start the
                    # reopen cooldown so the stale EWMA gauge can't
                    # immediately re-trigger
                    self._drift_cooldown = self.DRIFT_REOPEN_COOLDOWN
                    obs_flight.FLIGHT.record("calib_drift_epoch",
                                             state="closed")
                return
            if self._drift_cooldown > 0:
                self._drift_cooldown -= 1
                return
            from racon_tpu.obs import calhealth

            drifted = sorted(
                stage for stage, row in
                calhealth.summary().get("stages", {}).items()
                if row.get("drift"))
            if not drifted:
                return
            if calibrate.open_drift_epoch():
                for stage in drifted:
                    # re-seed the EWMA so the drift flag measures the
                    # NEW rates instead of averaging across the epoch
                    calhealth.reset_stage(stage)
                REGISTRY.add("calib_drift_epochs")
                obs_flight.FLIGHT.record("calib_drift_epoch",
                                         state="open", stages=drifted)
        except Exception:
            # drift bookkeeping is advisory — never fail a job
            # boundary on it
            pass

    # -- cancellation (r21) --------------------------------------------

    def cancel(self, job_key: str) -> dict:
        """Best-effort cancel by idempotence key (the router's
        straggler rebalancer sends this to a superseded original).
        A queued job finishes as ``job_canceled`` without running; a
        running one stops at the polisher's next between-units poll
        site — cancel-after-checkpoint, so everything it journaled
        stays replayable.  Unknown/finished keys are a no-op: cancel
        can always be sent safely."""
        with self._cond:
            job = self._by_key.get(job_key)
            if job is None:
                state = ("finished"
                         if job_key in self._completed_by_key
                         else "unknown")
                return {"ok": True, "job_key": job_key,
                        "state": state}
            job.cancel_requested.set()
            state = ("running" if job.id in self._running
                     else "queued")
        REGISTRY.add("serve_cancel_requests")
        obs_flight.FLIGHT.record("cancel", job=job.id,
                                 job_key=job_key, state=state,
                                 trace_id=job.trace_id)
        return {"ok": True, "job_key": job_key, "state": state}

    # -- lifecycle -----------------------------------------------------

    def pause(self) -> None:
        """Stop popping queued jobs (running ones continue) — a
        maintenance gate; admission stays open."""
        with self._cond:
            self._paused = True

    def resume(self) -> None:
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    def start_drain(self) -> None:
        """Flip to draining: new submissions reject, queued + running
        jobs keep going.  A paused queue resumes — admitted jobs were
        promised execution."""
        first = False
        with self._cond:
            if not self._draining:
                first = True
                queued, running = len(self._heap), len(self._running)
            self._draining = True
            self._paused = False
            self._cond.notify_all()
        if first:
            # the forensic drain marker: a post-SIGTERM flight dump
            # shows when admission closed and what was still in flight
            obs_flight.FLIGHT.record("drain", queued=queued,
                                     running=running)

    def wait_drained(self, timeout: float = None) -> bool:
        """Block until every admitted job finished, then stop the
        workers.  Returns True when everything finished in time."""
        with self._cond:
            ok = self._cond.wait_for(
                lambda: not self._heap and not self._running, timeout)
            self._stopped = True
            self._cond.notify_all()
        return ok

    def drain(self, timeout: float = None) -> bool:
        """Reject new jobs, finish queued + running ones."""
        self.start_drain()
        return self.wait_drained(timeout)

    @property
    def draining(self) -> bool:
        with self._cond:
            return self._draining

    def idle(self) -> bool:
        with self._cond:
            return not self._heap and not self._running

    def snapshot(self) -> dict:
        with self._cond:
            tenants: dict = {}
            classes = {c: {"queued": 0, "running": 0}
                       for c in JOB_CLASSES}
            for entry in self._heap:
                job = entry[-1]
                row = tenants.setdefault(
                    job.tenant, {"queued": 0, "running": 0})
                row["queued"] += 1
                classes[job.job_class]["queued"] += 1
            for job in self._running.values():
                row = tenants.setdefault(
                    job.tenant, {"queued": 0, "running": 0})
                row["running"] += 1
                classes[job.job_class]["running"] += 1
            return {
                "queue_depth": len(self._heap),
                "max_queue": self.max_queue,
                "running": sorted(self._running),
                "max_jobs": self.max_jobs,
                "completed": self._completed,
                "paused": self._paused,
                "draining": self._draining,
                "tenants": {t: tenants[t] for t in sorted(tenants)},
                "classes": classes,
            }
