"""``racon-tpu top``: live terminal status for a polishing daemon —
or, with ``--fleet``, for several at once.

Single-daemon mode subscribes to a server's ``watch`` stream
(racon_tpu/serve/client.py) and renders each telemetry frame as a
compact terminal dashboard — queue state, per-engine device
utilization, serving-SLO latency percentiles — refreshed in place
when stderr is a TTY (ANSI home+clear), appended as plain text
otherwise.

Fleet mode (``--fleet SOCK1,SOCK2,...``) polls every socket through
the scrape tier (racon_tpu/serve/fleet.py) and renders one
per-daemon row each (identity, state, queue occupancy; dead/stale
daemons stay visible as DOWN/STALE rows) above a merged fleet SLO
table whose percentiles are the EXACT quantiles of the union of all
daemons' observation streams (racon_tpu/obs/aggregate.py).

Machine mode: ``--once --json`` prints exactly one frame (the
telemetry frame, or the merged fleet document with ``--fleet``) as
one JSON line and exits — the scripting/router interface (queue
depth + predicted pressure per daemon is the fleet-routing signal
the ROADMAP calls for).

The client is read-only: every op it sends (``watch``/``metrics``)
touches no queue or job state on the server.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from racon_tpu.serve import client


def _fmt_s(v) -> str:
    v = float(v)
    if v >= 3600:
        return f"{v / 3600:.1f}h"
    if v >= 60:
        return f"{v / 60:.1f}m"
    if v >= 1:
        return f"{v:.1f}s"
    return f"{v * 1000:.0f}ms"


def render(doc: dict) -> str:
    """One telemetry frame -> the dashboard text (pure function; the
    tests golden it without a terminal)."""
    q = doc.get("queue", {})
    lines = []
    state = ("draining" if q.get("draining")
             else "paused" if q.get("paused") else "running")
    lines.append(
        f"racon-tpu serve  pid {doc.get('pid')}  "
        f"up {_fmt_s(doc.get('uptime_s', 0))}  [{state}]")
    lines.append(
        f"queue  {q.get('queue_depth', 0)}/{q.get('max_queue', '?')} "
        f"queued  {len(q.get('running', []))}/{q.get('max_jobs', '?')} "
        f"running  {q.get('completed', 0)} done")

    # per-tenant breakdown: scheduler occupancy (queued/running) plus
    # the executor-side fused-queue wait percentiles the r13 SLO
    # histograms record per tenant
    tenants = q.get("tenants") or {}
    slo = doc.get("slo") or {}
    if tenants:
        lines.append("")
        lines.append("tenant       queued  running  wait p50    "
                     "p90       p99")
        for name in sorted(tenants):
            row = tenants[name]
            s = slo.get(f"serve_tenant_wait_s.{name}") or {}
            if s.get("count"):
                waits = (f"{_fmt_s(s['p50']):<8s}  "
                         f"{_fmt_s(s['p90']):<8s}  "
                         f"{_fmt_s(s['p99']):<8s}")
            else:
                waits = "-"
            lines.append(
                f"{name:<12s} {row.get('queued', 0):>6d}  "
                f"{row.get('running', 0):>7d}  {waits}")

    du = doc.get("device_util") or {}
    # r16: the calibration-health EWMA rides every telemetry frame
    # (doc["calhealth"]); engine names ARE calhealth stage names, so
    # the drift ratio (measured/predicted) lands next to each
    # engine's utilization — "!" marks a stage outside the band
    cal = (doc.get("calhealth") or {}).get("stages") or {}

    def _drift(stage: str) -> str:
        s = cal.get(stage) or {}
        if not s.get("n") or s.get("ewma") is None:
            return "-"
        return f"{s['ewma']:.2f}" + ("!" if s.get("drift") else "")

    if du:
        lines.append("")
        lines.append("engine       util  busy      idle      "
                     "dispatches  drift")
        for eng in sorted(du):
            e = du[eng]
            lines.append(
                f"{eng:<12s} {e['util'] * 100:4.0f}%  "
                f"{_fmt_s(e['busy_s']):<8s}  "
                f"{_fmt_s(e['idle_s']):<8s}  "
                f"{e['n_dispatches']!s:<10s}  "
                f"{_drift(eng)}")
        host = sorted(k for k in cal
                      if k.startswith("host.") and cal[k].get("n"))
        for stage in host:
            lines.append(f"{stage:<12s}    -  {'-':<8s}  {'-':<8s}  "
                         f"{'-':<10s}  {_drift(stage)}")

    # r18: result-cache line — hit ratio + resident bytes, so a warm
    # daemon's lookup-instead-of-dispatch win is visible at a glance
    ca = doc.get("cache") or {}
    if ca.get("enabled"):
        total = ca.get("hits", 0) + ca.get("misses", 0)
        lines.append("")
        lines.append(
            f"cache  hit {ca.get('hit_ratio', 0.0) * 100:.0f}% "
            f"({ca.get('hits', 0)}/{total})  "
            f"{ca.get('bytes', 0) / (1 << 20):.1f} MB resident  "
            f"{ca.get('entries', 0)} entries  "
            f"{ca.get('evicts', 0)} evicted")

    slo = doc.get("slo") or {}
    if slo:
        lines.append("")
        lines.append("slo                    count   p50       "
                     "p90       p99")
        for name in sorted(slo):
            s = slo[name]
            if not s.get("count"):
                continue
            lines.append(
                f"{name:<22s} {s['count']:>5d}   "
                f"{_fmt_s(s['p50']):<8s}  {_fmt_s(s['p90']):<8s}  "
                f"{_fmt_s(s['p99']):<8s}")
    return "\n".join(lines) + "\n"


def render_fleet(doc: dict) -> str:
    """One merged fleet document (racon_tpu/serve/fleet.py
    ``merge_fleet``) -> the dashboard text (pure function; the tests
    golden it without a terminal)."""
    lines = [
        f"racon-tpu fleet  {doc.get('fleet_size', 0)} daemon(s)  "
        f"{doc.get('alive', 0)} alive  {doc.get('stale', 0)} stale"]
    lines.append("")
    lines.append("daemon        pid      state     up        "
                 "queued  running  done")
    for d in doc.get("daemons", ()):
        ident = d.get("identity") or {}
        did = (ident.get("daemon_id") or d.get("target", "?"))[:12]
        pid = str(ident.get("pid") or "-")
        route = d.get("route")
        if not ident:
            state = "DOWN"       # never answered: no identity known
        elif d.get("stale"):
            state = "STALE"
        elif route:
            state = ("draining" if route.get("draining")
                     else "router")
        elif d.get("draining"):
            state = "draining"
        else:
            state = "up"
        up = (_fmt_s(d["uptime_s"])
              if d.get("uptime_s") is not None else "-")
        qd = d.get("queue_depth")
        done = d.get("completed")
        lines.append(
            f"{did:<12s}  {pid:<7s}  {state:<8s}  {up:<8s}  "
            f"{'-' if qd is None else qd!s:>6s}  "
            f"{d.get('running', 0)!s:>7s}  "
            f"{'-' if done is None else done!s:>4s}")
        if d.get("error") and state in ("DOWN", "STALE"):
            lines.append(f"              ! {d['error']}")
        if route and state not in ("DOWN", "STALE"):
            # r19: one sub-row per fronted backend — breaker state
            # (CLOSED/OPEN/HALF-OPEN), consecutive failures, probe
            # staleness — plus the routing counters
            c = route.get("counters") or {}
            lines.append(
                f"              route: "
                f"{c.get('route_submit', 0)} placed, "
                f"{c.get('route_spillover', 0)} spilled, "
                f"{c.get('route_failover', 0)} failed over, "
                f"{route.get('in_flight', 0)} in flight")
            for b in route.get("backends", ()):
                age = b.get("probe_age_s")
                probe = "never" if age is None else f"{age:.1f}s"
                if b.get("stale"):
                    probe += " STALE"
                flags = " draining" if b.get("draining") else ""
                lines.append(
                    f"              -> {b.get('target', '?')}  "
                    f"{b.get('breaker')}"
                    f"  fails {b.get('failures', 0)}"
                    f"  probe {probe}{flags}")

    slo = doc.get("slo") or {}
    if slo:
        lines.append("")
        lines.append("fleet slo              count   p50       "
                     "p90       p99")
        for name in sorted(slo):
            s = slo[name]
            if not s.get("count"):
                continue
            lines.append(
                f"{name:<22s} {s['count']:>5d}   "
                f"{_fmt_s(s['p50']):<8s}  {_fmt_s(s['p90']):<8s}  "
                f"{_fmt_s(s['p99']):<8s}")

    # r18: fleet-wide cache effectiveness — the hit/miss counters sum
    # EXACTLY across daemons (racon_tpu/obs/aggregate.py), so the
    # merged ratio is the true fleet ratio, not a mean of ratios;
    # bytes-resident stays per-daemon (a gauge sum means little, but
    # the per_source map keeps attribution)
    merged = (doc.get("merged") or {})
    mc = merged.get("counters") or {}
    hits, misses = mc.get("cache_hit", 0), mc.get("cache_miss", 0)
    if hits or misses:
        ratio = hits / (hits + misses)
        mb = ((merged.get("gauges") or {}).get("cache_bytes")
              or {}).get("sum", 0) / (1 << 20)
        lines.append("")
        lines.append(
            f"fleet cache  hit {ratio * 100:.0f}% "
            f"({hits}/{hits + misses})  {mb:.1f} MB resident  "
            f"{mc.get('cache_fill', 0)} fills  "
            f"{mc.get('cache_evict', 0)} evicted")

    # r16: fleet-wide calibration health from the exactly-merged
    # snapshot union (racon_tpu/serve/fleet.py merge_fleet)
    cal = (doc.get("calhealth") or {}).get("stages") or {}
    rows = {k: v for k, v in cal.items() if v.get("n")}
    if rows:
        lines.append("")
        lines.append("fleet drift            n      ewma     p50     "
                     " p99")
        for name in sorted(rows):
            s = rows[name]
            ew = s.get("ewma")
            lines.append(
                f"{name:<22s} {s['n']:>4d}   "
                f"{'-' if ew is None else format(ew, '6.2f'):>6s}  "
                f"{s.get('p50', 0.0):>6.2f}  {s.get('p99', 0.0):>6.2f}"
                + ("   DRIFT" if s.get("drift") else ""))
    return "\n".join(lines) + "\n"


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="racon-tpu top",
        description="Live status view of one racon-tpu serve daemon "
        "(watch stream) or a fleet of them (scrape tier).")
    g = p.add_mutually_exclusive_group(required=True)
    g.add_argument("--socket",
                   help="unix-domain socket of the server to watch")
    g.add_argument("--fleet", metavar="SOCK1,SOCK2,...",
                   help="comma-separated daemon sockets, or a single "
                   "router socket (backends auto-discovered from its "
                   "route_status); renders per-daemon rows + the "
                   "merged fleet SLO table")
    p.add_argument("--interval", type=float, default=1.0,
                   help="refresh period in seconds (default 1.0)")
    p.add_argument("--count", type=int, default=0,
                   help="exit after N frames (default 0 = forever)")
    p.add_argument("--once", action="store_true",
                   help="print one frame and exit (implies --count 1)")
    p.add_argument("--json", action="store_true",
                   help="print raw frames as JSON lines instead of "
                   "the dashboard")
    return p


def _main_fleet(args, count: int) -> int:
    from racon_tpu.serve import fleet

    scraper = fleet.FleetScraper(
        fleet.resolve_fleet_targets(args.fleet))
    live = sys.stdout.isatty() and not args.json and count != 1
    sent = 0
    try:
        while True:
            scraper.scrape_once()
            doc = fleet.merge_fleet(scraper.results())
            if args.json:
                print(json.dumps(doc, separators=(",", ":")),
                      flush=True)
            else:
                if live:
                    sys.stdout.write("\x1b[H\x1b[J")
                sys.stdout.write(render_fleet(doc))
                sys.stdout.flush()
            sent += 1
            if count and sent >= count:
                return 0 if doc.get("ok") else 1
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    count = 1 if args.once else args.count
    if args.fleet:
        return _main_fleet(args, count)
    live = sys.stdout.isatty() and not args.json and count != 1
    try:
        for doc in client.watch(args.socket,
                                interval_s=args.interval,
                                count=count):
            if args.json:
                print(json.dumps(doc, separators=(",", ":")),
                      flush=True)
            else:
                if live:
                    # home + clear-below: redraw in place without
                    # the full-screen alternate buffer
                    sys.stdout.write("\x1b[H\x1b[J")
                sys.stdout.write(render(doc))
                sys.stdout.flush()
    except client.ServeError as exc:
        print(f"[racon_tpu::top] error: {exc}", file=sys.stderr)
        return 1
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
