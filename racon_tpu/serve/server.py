"""The polishing daemon: warm kernels behind a unix-domain socket.

``racon-tpu serve --socket PATH`` starts a long-lived worker that

* prewarms the AOT shelf ONCE at startup
  (:func:`racon_tpu.tpu.polisher.prewarm_once`) and keeps every
  piece of process-wide warm state resident between jobs: the jax
  import, the in-process jit caches, the deserialized shelf exports
  and the calibration rates — so job N>=2 pays zero compile/prewarm
  cost (the warm-start assertion tests/test_serve.py pins);
* freezes calibration stores (``RACON_TPU_CALIB_FREEZE=1``): a
  served job's bytes must match a standalone CLI run, and letting
  job N's measured rates steer job N+1's split would break that for
  any job order a standalone run never saw;
* accepts length-prefixed JSON frames (racon_tpu/serve/protocol.py)
  on the socket — one request per connection for ``submit`` (the
  connection blocks until the job finishes; that is the client's
  rendezvous — with ``trace: true`` the response also carries the
  job's trace slice + flight events), ``status`` / ``pause`` /
  ``resume`` / ``shutdown`` / ``metrics`` / ``health`` /
  ``flight`` (live flight-recorder ring, optionally filtered to one
  job) answer immediately, and ``watch`` streams
  periodic telemetry frames on its connection until the client
  closes or the server drains (racon-tpu top's feed);
* optionally runs a background telemetry sampler
  (``RACON_TPU_SERVE_SAMPLE_S`` seconds, 0 = off) that refreshes the
  queue/uptime/device-utilization gauges in the process registry so
  scrapes see fresh values even between requests — read-side only,
  job bytes are pinned identical sampler-on vs off
  (tests/test_telemetry.py);
* drains gracefully on SIGTERM/SIGINT or a ``shutdown`` op: running
  AND queued jobs finish, new submissions get a structured
  ``draining`` reject, then the process exits 0;
* self-shuts down after ``RACON_TPU_SERVE_IDLE_S`` seconds (0 =
  never, the default) with no queued/running job and no connection —
  a fleet manager can spawn servers per dataset burst and let them
  reap themselves.

Crash containment: a malformed frame answers ``bad_request`` and
drops only that connection; a failing job answers ``job_failed`` on
its own connection; neither touches the queue or the warm engines.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import sys
import threading

from racon_tpu.obs import REGISTRY
from racon_tpu.obs import context as obs_context
from racon_tpu.obs import decision as obs_decision
from racon_tpu.obs import flight as obs_flight
from racon_tpu.obs import trace as obs_trace
from racon_tpu.serve import journal as serve_journal
from racon_tpu.serve import protocol
from racon_tpu.serve import recover
from racon_tpu.serve.scheduler import JobScheduler, RejectError
from racon_tpu.serve.session import run_job


def eprint(*args):
    print(*args, file=sys.stderr, flush=True)


#: r23 bounded forensic reads: hard caps a ``journal_query`` /
#: ``trace_query`` response never exceeds, whatever the client asked
#: for — a forensic scan must not ship a multi-GB journal in one frame
JOURNAL_QUERY_MAX_RECORDS = 1024
JOURNAL_QUERY_MAX_BYTES = 8 << 20
TRACE_QUERY_MAX_EVENTS = 4096


def _key_filter_match(rec_key, job_key: str = None,
                      prefix: str = None) -> bool:
    """Does a record tagged ``rec_key`` belong to the asked-for job?
    ``job_key`` matches the key itself plus its r20/r21 derived family
    (``<key>-shard-<i>of<k>[-r<n>]``); ``prefix`` is a raw string
    prefix for callers that already hold a derived key."""
    if not isinstance(rec_key, str):
        return False
    if job_key is not None and (
            rec_key == job_key
            or rec_key.startswith(job_key + "-shard-")):
        return True
    return prefix is not None and rec_key.startswith(prefix)


class PolishServer:
    def __init__(self, socket_path: str, max_queue: int = None,
                 max_jobs: int = None, idle_timeout: float = None):
        self.socket_path = socket_path
        self.idle_timeout = (
            idle_timeout if idle_timeout is not None
            else float(os.environ.get("RACON_TPU_SERVE_IDLE_S", "0")))
        self.scheduler = JobScheduler(run_job, max_queue=max_queue,
                                      max_jobs=max_jobs)
        self._sock = None
        self._stop = threading.Event()
        self._handlers: list = []
        self._t_start = obs_trace.now()
        self._last_activity = self._t_start
        self._lock = threading.Lock()
        self._exit_reason = "drain"
        # durability plane (r17): the write-ahead journal handle
        # (opened in serve_forever AFTER the takeover check, so a
        # refused second daemon never writes into the live daemon's
        # journal) and the recovery summary for health/status
        self._journal = None
        self.recovered = {"requeued": 0, "failed": 0, "completed": 0}
        # request-scoped forensics (r14): keep a bounded per-job
        # trace slice for `submit --trace` / `inspect`, and dump the
        # flight ring if any thread dies with an unhandled exception
        obs_trace.TRACER.enable_job_capture()
        obs_flight.FLIGHT.install_dump_on_crash()
        # fleet identity (r15): pin the static identity fields (id,
        # pid, start epoch) at construction so every frame this
        # daemon ever answers carries the same daemon_id
        from racon_tpu.obs import provenance
        provenance.daemon_identity(socket_path)

    def _identity(self) -> dict:
        """The daemon's stable identity block — on every
        ``metrics``/``health``/``watch``/``status`` frame, so a fleet
        scraper attributes telemetry to a process, not a socket."""
        from racon_tpu.obs import provenance
        return provenance.daemon_identity(self.socket_path)

    # -- warm state ----------------------------------------------------

    def prewarm(self, match: int, mismatch: int, gap: int,
                trim: bool) -> None:
        """Populate the AOT shelf / jit caches before the first job.
        Synchronous and idempotent: the daemon has no input parse to
        hide the work behind (unlike the one-shot CLI's racing
        prewarm thread), and a server that answers its first submit
        only after the shelf is warm gives every job — including the
        first — the same latency contract."""
        from racon_tpu.tpu.polisher import prewarm_once

        with obs_trace.span("serve.prewarm", cat="serve"):
            ran = prewarm_once(match, mismatch, gap, trim)
        if ran:
            eprint("[racon_tpu::serve] AOT shelf prewarmed")

    # -- request handling ----------------------------------------------

    def _handle_submit(self, req: dict) -> dict:
        spec = req.get("job")
        if not isinstance(spec, dict):
            return protocol.error_frame("bad_request",
                                        "submit carries no job object")
        trace_context = req.get("trace_context")
        if trace_context is not None and \
                not obs_context.valid_trace_id(trace_context):
            return protocol.error_frame(
                "bad_request",
                "trace_context must be 1..128 chars of "
                "[A-Za-z0-9._:-] starting alphanumeric")
        job_key = req.get("job_key")
        if job_key is not None and \
                not obs_context.valid_trace_id(job_key):
            return protocol.error_frame(
                "bad_request",
                "job_key must be 1..128 chars of "
                "[A-Za-z0-9._:-] starting alphanumeric")
        try:
            job = self.scheduler.submit(
                spec, priority=int(req.get("priority", 0)),
                trace_context=trace_context, job_key=job_key)
        except RejectError as exc:
            return {"ok": False, "error": exc.error}
        job.done.wait()
        self._touch()
        if not req.get("trace"):
            return job.result
        # job-scoped observability rides the response frame: the
        # trace slice (spans + flow events tagged with this job) and
        # the flight events, so the client can render/inspect the
        # job without any follow-up op
        result = dict(job.result or {})
        result["trace_events"] = obs_trace.TRACER.job_slice(job.id)
        result["flight_events"] = obs_flight.FLIGHT.snapshot(
            job=job.id)
        return result

    def _status_doc(self) -> dict:
        from racon_tpu.obs import provenance

        return {
            "ok": True,
            "pid": os.getpid(),
            "socket": self.socket_path,
            "identity": self._identity(),
            "uptime_s": round(obs_trace.now() - self._t_start, 3),
            "draining": self.scheduler.draining,
            "queue": self.scheduler.snapshot(),
            "journal": self._journal_doc(),
            "recovered": dict(self.recovered),
            "idle_timeout_s": self.idle_timeout,
            "registry": REGISTRY.snapshot(),
            "provenance": provenance.environment(probe=False),
        }

    # -- telemetry (r12) -----------------------------------------------

    def telemetry_doc(self, prometheus: bool = False) -> dict:
        """One self-contained telemetry frame: queue state, per-engine
        device utilization, registry snapshot with percentiles, and
        the serving-SLO table.  ``prometheus=True`` additionally
        renders the text exposition (the ``metrics`` op; ``watch``
        frames skip it to stay small)."""
        from racon_tpu.obs import devutil, export

        # publish BEFORE the snapshot so the exposition carries the
        # device_util.* gauges the JSON section reports
        from racon_tpu.tpu import executor as device_executor

        from racon_tpu import cache as rcache

        du = devutil.DEVICE_UTIL.publish(REGISTRY)
        REGISTRY.set("serve_uptime_s",
                     round(obs_trace.now() - self._t_start, 3))
        snap = REGISTRY.snapshot()
        doc = {
            "ok": True,
            "pid": os.getpid(),
            "identity": self._identity(),
            "uptime_s": snap["gauges"]["serve_uptime_s"],
            "queue": self.scheduler.snapshot(),
            "device_util": du,
            "fusion": device_executor.get_executor().stats(),
            "cache": dict(rcache.stats(),
                          sketch=self._cache_health().get("sketch")),
            "slo": export.slo_summary(snap),
            "calhealth": export.drift_summary(snap),
            "snapshot": export.json_snapshot(snap),
        }
        if prometheus:
            doc["prometheus"] = export.prometheus_text(snap)
        return doc

    @staticmethod
    def _clock_anchors() -> dict:
        """Wall-clock anchors every forensic frame carries (r23): the
        daemon's wall time at answer (the collector's offset-probe
        sample) and the wall time of its trace epoch (lifts monotonic
        flight/trace timestamps onto the wall clock).  Rendering
        only — never control flow or bytes."""
        return {"wall_t": round(obs_trace.wall_now(), 6),
                "trace_epoch_wall":
                    round(obs_trace.epoch_wall(), 6)}

    def _capture_doc(self) -> dict:
        """r23 capture depths: how much forensic memory this daemon
        still holds — the flight ring's rollover counter, the per-job
        trace index's eviction counter, and the journal depth — so a
        fleet assembler can warn when a ring rolled over mid-job
        instead of presenting a partial lineage as complete."""
        return {
            "flight": obs_flight.FLIGHT.stats(),
            "trace": obs_trace.TRACER.capture_stats(),
            "journal": self._journal_doc(),
        }

    def _flight_doc(self, req: dict) -> dict:
        """The live flight-recorder view (``flight`` op): ring stats
        plus events — optionally filtered to one job (``job``), an
        idempotence-key family (``job_key``, matching the key and its
        derived shard/rebalance keys), an exact ``trace_id``, or the
        newest N (``last``); with ``job`` the bounded per-job trace
        slice rides along for timeline rendering."""
        try:
            job = req.get("job")
            job = int(job) if job is not None else None
            last = int(req.get("last", 0) or 0)
        except (TypeError, ValueError):
            return protocol.error_frame(
                "bad_request", "flight: job/last must be integers")
        job_key = req.get("job_key")
        trace_id = req.get("trace_id")
        if (job_key is not None and not isinstance(job_key, str)) or \
                (trace_id is not None
                 and not isinstance(trace_id, str)):
            return protocol.error_frame(
                "bad_request",
                "flight: job_key/trace_id must be strings")
        doc = {
            "ok": True,
            "pid": os.getpid(),
            "identity": self._identity(),
            "ring": obs_flight.FLIGHT.stats(),
            "events": obs_flight.FLIGHT.snapshot(
                job=job, last=last, job_key=job_key,
                trace_id=trace_id),
        }
        doc.update(self._clock_anchors())
        if job is not None:
            doc["job_trace"] = obs_trace.TRACER.job_slice(job)
        return doc

    def _journal_query_doc(self, req: dict) -> dict:
        """Bounded read-only journal slice (r23 ``journal_query``).
        The ask MUST carry a key filter (``job_key``, matching the
        key and its derived shard/rebalance family, or a raw
        ``job_key_prefix``) and ``max_records`` — an unbounded ask is
        a ``bad_request`` by contract — and the response is further
        capped at JOURNAL_QUERY_MAX_RECORDS /
        JOURNAL_QUERY_MAX_BYTES.  ``done`` records have their result
        body slimmed (the recorded FASTA stays on disk; only its size
        ships).  Scans the file with the torn-tail-tolerant reader —
        the live append handle is never touched, so the op is
        read-only by construction."""
        job_key = req.get("job_key")
        prefix = req.get("job_key_prefix")
        if not (isinstance(job_key, str) and job_key) and \
                not (isinstance(prefix, str) and prefix):
            return protocol.error_frame(
                "bad_request",
                "journal_query requires a job_key or "
                "job_key_prefix filter (unbounded reads are "
                "refused)")
        try:
            max_records = int(req.get("max_records"))
        except (TypeError, ValueError):
            max_records = 0
        if max_records <= 0:
            return protocol.error_frame(
                "bad_request",
                "journal_query requires max_records > 0 "
                "(unbounded reads are refused)")
        max_records = min(max_records, JOURNAL_QUERY_MAX_RECORDS)
        try:
            max_bytes = int(req.get("max_bytes",
                                    JOURNAL_QUERY_MAX_BYTES))
        except (TypeError, ValueError):
            max_bytes = JOURNAL_QUERY_MAX_BYTES
        max_bytes = min(max(1, max_bytes), JOURNAL_QUERY_MAX_BYTES)
        base = {"ok": True, "pid": os.getpid(),
                "identity": self._identity()}
        base.update(self._clock_anchors())
        if self._journal is None:
            return dict(base, enabled=False, records=[],
                        complete=True, matched=0)
        records, truncated = serve_journal.scan(self._journal.path)

        def _slim(rec: dict) -> dict:
            rec = dict(rec)
            res = rec.get("result")
            if isinstance(res, dict):
                slim = {k: res.get(k) for k in
                        ("ok", "job_id", "n_sequences", "wall_s",
                         "trace_id") if k in res}
                fb = res.get("fasta_b64")
                if isinstance(fb, str):
                    slim["fasta_bytes"] = \
                        len(fb) * 3 // 4 - fb[-2:].count("=")
                rec["result"] = slim
            return rec

        sel = [_slim(rec) for rec in records
               if _key_filter_match(rec.get("job_key"),
                                    job_key=(job_key or None),
                                    prefix=(prefix or None))]
        matched = len(sel)
        complete = matched <= max_records
        sel = sel[-max_records:]
        out, used = [], 0
        import json as _json
        for rec in sel:
            n = len(_json.dumps(rec, separators=(",", ":")))
            if out and used + n > max_bytes:
                complete = False
                break
            out.append(rec)
            used += n
        return dict(base, enabled=True, path=self._journal.path,
                    records=out, scan_truncated=truncated,
                    complete=complete, matched=matched)

    def _trace_query_doc(self, req: dict) -> dict:
        """Bounded per-job trace slice (r23 ``trace_query``): the
        same events ``submit --trace`` rides on the response frame,
        readable after the fact by a fleet assembler.  Requires
        ``job`` and ``max_events`` (capped at
        TRACE_QUERY_MAX_EVENTS); read-only against the tracer's
        bounded LRU index."""
        try:
            job = int(req.get("job"))
        except (TypeError, ValueError):
            return protocol.error_frame(
                "bad_request", "trace_query requires a job id")
        try:
            max_events = int(req.get("max_events"))
        except (TypeError, ValueError):
            max_events = 0
        if max_events <= 0:
            return protocol.error_frame(
                "bad_request",
                "trace_query requires max_events > 0 "
                "(unbounded reads are refused)")
        max_events = min(max_events, TRACE_QUERY_MAX_EVENTS)
        evs = obs_trace.TRACER.job_slice(job)
        doc = {"ok": True, "pid": os.getpid(),
               "identity": self._identity(), "job": job,
               "complete": len(evs) <= max_events,
               "events": evs[-max_events:],
               "capture": obs_trace.TRACER.capture_stats()}
        doc.update(self._clock_anchors())
        return doc

    def _explain_doc(self, req: dict) -> dict:
        """The decision-plane view (``explain`` op, r16):
        per-stage calibration health plus the decision-record ring —
        optionally filtered to one job (``job``) or the newest N
        events (``last``).  The client CLI renders the per-job cost
        waterfall from this one frame."""
        from racon_tpu.obs import export

        try:
            job = req.get("job")
            job = int(job) if job is not None else None
            last = int(req.get("last", 0) or 0)
        except (TypeError, ValueError):
            return protocol.error_frame(
                "bad_request", "explain: job/last must be integers")
        from racon_tpu import cache as rcache

        snap = REGISTRY.snapshot()
        return {
            "ok": True,
            "pid": os.getpid(),
            "identity": self._identity(),
            "calhealth": export.drift_summary(snap),
            "cache": rcache.stats(),
            "ring": obs_decision.DECISIONS.stats(),
            "counts": obs_decision.DECISIONS.counts(job=job),
            "events": obs_decision.DECISIONS.snapshot(job=job,
                                                      last=last),
        }

    def _health_doc(self) -> dict:
        """Liveness/readiness without a registry walk — cheap enough
        for a tight poll loop.  r15 adds the internal depths a fleet
        overseer triages with: the flight-ring fill, the device
        executor's fusion-queue backlog, and the in-flight job
        count."""
        from racon_tpu.tpu import executor as device_executor

        q = self.scheduler.snapshot()
        doc = {
            "ok": True,
            "status": "draining" if q["draining"] else "ok",
            "pid": os.getpid(),
            "identity": self._identity(),
            "uptime_s": round(obs_trace.now() - self._t_start, 3),
            "accepting": not q["draining"],
            "queue_depth": q["queue_depth"],
            "running": len(q["running"]),
            "in_flight_jobs": len(q["running"]),
            "paused": q["paused"],
            "flight_ring_depth": obs_flight.FLIGHT.stats()["size"],
            "fusion_queue_depth":
                device_executor.get_executor().pending_units(),
            "cache": self._cache_health(),
            "journal": self._journal_doc(),
            "recovered_jobs": self.recovered["requeued"],
            "recovery": dict(self.recovered),
            # r23 fleet forensics: capture depths + clock anchors, so
            # `inspect --fleet` estimates this daemon's clock offset
            # from the probe round trip and warns on rollover
            "capture": self._capture_doc(),
        }
        doc.update(self._clock_anchors())
        return doc

    def _cache_health(self) -> dict:
        """The result cache's cheap health block (r18): hit ratio +
        resident bytes, without the full stats walk.  r22 attaches
        the epoch-tagged digest sketch (racon_tpu/cache/sketch.py) —
        ~11 KiB base64 — which the fleet router scores content-keyed
        submits against for affinity placement."""
        from racon_tpu import cache as rcache

        st = rcache.stats()
        doc = {"enabled": st.get("enabled", False),
               "hit_ratio": st.get("hit_ratio", 0.0),
               "bytes": st.get("bytes", 0),
               "entries": st.get("entries", 0)}
        try:
            doc["sketch"] = rcache.sketch_doc()
        except Exception:
            # sketch export is advisory routing data; never let it
            # break a health probe
            doc["sketch"] = None
        return doc

    def _journal_doc(self) -> dict:
        """The write-ahead journal's health block (r17)."""
        if self._journal is not None:
            return self._journal.stats()
        return {"enabled": False}

    def _handle_watch(self, conn, req: dict) -> None:
        """Stream telemetry frames on this connection (the one
        multi-frame op).  Ends when ``count`` frames were sent, the
        client closes, or the server drains — sleeping on
        ``self._stop.wait`` so drain interrupts the stream
        promptly."""
        try:
            interval = float(req.get("interval_s", 1.0))
        except (TypeError, ValueError):
            interval = 1.0
        interval = min(max(interval, 0.05), 60.0)
        try:
            count = int(req.get("count", 0))
        except (TypeError, ValueError):
            count = 0
        REGISTRY.add("serve_watchers")
        sent = 0
        try:
            while True:
                doc = self.telemetry_doc(prometheus=False)
                doc["seq"] = sent
                protocol.send_frame(conn, doc)
                sent += 1
                if count and sent >= count:
                    return
                if self._stop.wait(interval):
                    return
        except OSError:
            return   # watcher went away; nothing to salvage

    def _sampler_loop(self, period: float) -> None:
        """Background gauge refresh (RACON_TPU_SERVE_SAMPLE_S): keeps
        queue depth / uptime / device utilization current in the
        registry between requests so an exposition scrape never reads
        stale gauges.  Pure read-side — it writes only gauges derived
        from state the events already maintain."""
        from racon_tpu.obs import devutil

        while not self._stop.wait(period):
            devutil.DEVICE_UTIL.publish(REGISTRY)
            q = self.scheduler.snapshot()
            REGISTRY.set("serve_queue_depth", q["queue_depth"])
            REGISTRY.set("serve_running", len(q["running"]))
            REGISTRY.set("serve_uptime_s",
                         round(obs_trace.now() - self._t_start, 3))

    def _serve_connection(self, conn) -> None:
        try:
            req = protocol.recv_frame(conn)
            if req is None:
                return
            op = req.get("op") if isinstance(req, dict) else None
            if op == "watch":
                # multi-frame: the handler owns the connection
                self._handle_watch(conn, req)
                return
            if op == "submit":
                resp = self._handle_submit(req)
            elif op == "status":
                resp = self._status_doc()
            elif op == "metrics":
                resp = self.telemetry_doc(prometheus=True)
            elif op == "health":
                resp = self._health_doc()
            elif op == "flight":
                resp = self._flight_doc(req)
            elif op == "journal_query":
                resp = self._journal_query_doc(req)
            elif op == "trace_query":
                resp = self._trace_query_doc(req)
            elif op == "explain":
                resp = self._explain_doc(req)
            elif op == "cancel":
                key = req.get("job_key")
                if not isinstance(key, str) or not key:
                    resp = protocol.error_frame(
                        "bad_request", "cancel carries no job_key")
                else:
                    resp = self.scheduler.cancel(key)
            elif op == "pause":
                self.scheduler.pause()
                resp = {"ok": True, "paused": True}
            elif op == "resume":
                self.scheduler.resume()
                resp = {"ok": True, "paused": False}
            elif op == "shutdown":
                resp = {"ok": True, "draining": True}
                self._stop.set()
            else:
                resp = protocol.error_frame("bad_request",
                                            f"unknown op {op!r}")
            protocol.send_frame(conn, resp)
        except protocol.ProtocolError as exc:
            REGISTRY.add("serve_bad_frames")
            try:
                protocol.send_frame(
                    conn, protocol.error_frame("bad_request", str(exc)))
            except OSError:
                pass
        except OSError:
            pass   # client went away mid-reply; nothing to salvage
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _touch(self) -> None:
        with self._lock:
            self._last_activity = obs_trace.now()

    def _idle_expired(self) -> bool:
        if self.idle_timeout <= 0:
            return False
        if not self.scheduler.idle():
            return False
        with self._lock:
            return (obs_trace.now() - self._last_activity
                    > self.idle_timeout)

    # -- durability (r17) ----------------------------------------------

    def _peer_alive(self):
        """Probe the socket's current owner with a real ``health``
        frame: ``True`` = answered (alive), ``False`` = connection
        refused (dead — the socket is stale), ``None`` = ambiguous
        (connected but no valid frame; the caller refuses takeover
        rather than orphan a wedged-but-alive daemon's queue)."""
        probe = socket.socket(socket.AF_UNIX)
        probe.settimeout(5.0)
        try:
            probe.connect(self.socket_path)
        except ConnectionRefusedError:
            return False
        except OSError:
            return None
        try:
            protocol.send_frame(probe, {"op": "health"})
            resp = protocol.recv_frame(probe)
            return True if isinstance(resp, dict) else None
        except (protocol.ProtocolError, OSError):
            return None
        finally:
            try:
                probe.close()
            except OSError:
                pass

    def _recover(self) -> None:
        """Open the write-ahead journal and replay any previous
        incarnation's record: terminal outcomes preload the
        scheduler's idempotence index (duplicate keyed submits answer
        from the record), interrupted jobs requeue through NORMAL
        admission carrying their megabatch checkpoints, and the
        replay summary is journaled + flight-recorded.  No-op with
        ``RACON_TPU_JOURNAL=0`` — the daemon then behaves exactly as
        before r17."""
        if not serve_journal.enabled():
            return
        path = serve_journal.journal_path(self.socket_path)
        records, truncated = serve_journal.scan(path)
        plan = recover.replay(records)
        self._journal = serve_journal.JobJournal(
            path, prior_records=len(records))
        self.scheduler.attach_journal(self._journal)
        self.scheduler.preload_completed(plan["completed"])
        out = recover.requeue(self.scheduler, plan,
                              journal=self._journal,
                              flight=obs_flight.FLIGHT)
        self.recovered = {
            "requeued": out["requeued"],
            "failed": plan["stats"]["failed"] + out["failed"],
            "completed": plan["stats"]["completed"],
        }
        REGISTRY.set("serve_recovered_jobs", out["requeued"])
        if records:
            self._journal.append(
                "recovery", stats=plan["stats"],
                requeued=out["requeued"],
                requeue_failed=out["failed"],
                truncated=truncated or None)
            obs_flight.FLIGHT.record(
                "recovery", records=len(records),
                completed=plan["stats"]["completed"],
                requeued=out["requeued"],
                failed=self.recovered["failed"])
            eprint(f"[racon_tpu::serve] journal replay ({path}): "
                   f"{len(records)} record(s) -> "
                   f"{plan['stats']['completed']} completed, "
                   f"{out['requeued']} requeued, "
                   f"{self.recovered['failed']} failed"
                   + (" (torn tail dropped)" if truncated else ""))

    # -- main loop -----------------------------------------------------

    def serve_forever(self) -> int:
        # a served job's split must be a pure function of the
        # server-start calibration state (see module docstring)
        os.environ["RACON_TPU_CALIB_FREEZE"] = "1"
        if os.path.exists(self.socket_path):
            # takeover decision (r17): unlink ONLY a provably dead
            # peer.  A bare connect() can succeed against a wedged
            # listener backlog, so the liveness proof is a real
            # health-frame round trip; anything short of a refused
            # connection or a valid answer refuses takeover rather
            # than orphan a live daemon's queue.
            alive = self._peer_alive()
            if alive:
                eprint(f"[racon_tpu::serve] error: a live server "
                       f"already owns {self.socket_path} "
                       f"(health-frame probe answered); refusing "
                       f"to take over")
                return 1
            if alive is None:
                eprint(f"[racon_tpu::serve] error: cannot prove the "
                       f"owner of {self.socket_path} dead (probe "
                       f"connected but no health frame answered); "
                       f"refusing to take over — remove the socket "
                       f"manually if the process is gone")
                return 1
            eprint(f"[racon_tpu::serve] stale socket "
                   f"{self.socket_path}: previous owner is dead, "
                   f"taking over")
            os.unlink(self.socket_path)
        # journal + crash recovery AFTER the takeover check (a
        # refused second daemon must never touch the live daemon's
        # journal) and BEFORE bind (requeued jobs re-admit before
        # any new submission can race them)
        self._recover()
        self._sock = socket.socket(socket.AF_UNIX)
        self._sock.bind(self.socket_path)
        self._sock.listen(16)
        self._sock.settimeout(0.25)
        eprint(f"[racon_tpu::serve] listening on {self.socket_path} "
               f"(queue {self.scheduler.max_queue}, "
               f"jobs {self.scheduler.max_jobs}, "
               f"idle_timeout {self.idle_timeout or 'off'})")
        try:
            sample_s = float(
                os.environ.get("RACON_TPU_SERVE_SAMPLE_S", "0"))
        except ValueError:
            sample_s = 0.0
        if sample_s > 0:
            threading.Thread(
                target=self._sampler_loop,
                args=(max(sample_s, 0.05),), daemon=True,
                name="racon-serve-sampler").start()
        self._touch()   # prewarm time must not count against idle
        try:
            while True:
                if self._stop.is_set():
                    # drain mode: keep ACCEPTING so new submissions
                    # get a structured "draining" reject (and status
                    # keeps answering) while admitted jobs finish;
                    # the loop ends once the last one has
                    if not self.scheduler.draining:
                        eprint("[racon_tpu::serve] draining: "
                               "finishing queued/running jobs, "
                               "rejecting new ones")
                        self.scheduler.start_drain()
                    if self.scheduler.idle():
                        break
                elif self._idle_expired():
                    eprint("[racon_tpu::serve] idle timeout reached, "
                           "shutting down")
                    self._exit_reason = "idle_timeout"
                    break
                try:
                    conn, _ = self._sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                self._touch()
                t = threading.Thread(target=self._serve_connection,
                                     args=(conn,), daemon=True,
                                     name="racon-serve-conn")
                self._handlers.append(t)
                t.start()
                self._handlers = [h for h in self._handlers
                                  if h.is_alive()]
        finally:
            self._shutdown()
        return 0

    def _shutdown(self) -> None:
        with obs_trace.span("serve.drain", cat="serve"):
            self.scheduler.drain()
            # let blocked submit handlers flush their replies before
            # the process goes away
            for h in list(self._handlers):
                h.join(timeout=10)
        try:
            self._sock.close()
        finally:
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass
        snap = self.scheduler.snapshot()
        if obs_flight.enabled():
            # the ring now holds the drain marker and every job's
            # final events — persist it so a post-mortem has the
            # same record the live `flight` op would have served
            try:
                path = obs_flight.FLIGHT.dump(
                    reason=self._exit_reason)
                eprint(f"[racon_tpu::serve] flight dump: {path}")
            except OSError as exc:
                eprint(f"[racon_tpu::serve] flight dump failed: "
                       f"{exc}")
        if self._journal is not None:
            self._journal.close()
        eprint(f"[racon_tpu::serve] drained "
               f"({snap['completed']} job(s) served); bye")

    def request_stop(self, *_sig) -> None:
        self._stop.set()


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="racon-tpu serve",
        description="Persistent polishing daemon: keeps compiled "
        "kernels, the AOT shelf and calibration warm across jobs "
        "submitted over a unix-domain socket (racon-tpu submit).")
    p.add_argument("--socket", required=True,
                   help="unix-domain socket path to listen on")
    p.add_argument("--queue", type=int, default=None,
                   help="max queued jobs before backpressure rejects "
                   "(default: RACON_TPU_SERVE_QUEUE or 8)")
    p.add_argument("--jobs", type=int, default=None,
                   help="max concurrently running jobs (default: "
                   "RACON_TPU_SERVE_JOBS or 2)")
    p.add_argument("--idle-timeout", type=float, default=None,
                   help="self-shutdown after this many idle seconds "
                   "(default: RACON_TPU_SERVE_IDLE_S or 0 = never)")
    # prewarm scoring config: the shelf variants are keyed by the
    # scoring triple + trim, so the daemon warms the config its jobs
    # will use (defaults match the one-shot CLI's)
    p.add_argument("-m", "--match", type=int, default=3)
    p.add_argument("-x", "--mismatch", type=int, default=-5)
    p.add_argument("-g", "--gap", type=int, default=-4)
    p.add_argument("--no-trimming", action="store_true")
    return p


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    server = PolishServer(args.socket, max_queue=args.queue,
                          max_jobs=args.jobs,
                          idle_timeout=args.idle_timeout)
    # graceful drain on SIGTERM/SIGINT (fleet managers send TERM)
    signal.signal(signal.SIGTERM, server.request_stop)
    signal.signal(signal.SIGINT, server.request_stop)
    server.prewarm(args.match, args.mismatch, args.gap,
                   not args.no_trimming)
    return server.serve_forever()


if __name__ == "__main__":
    sys.exit(main())
