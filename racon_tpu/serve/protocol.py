"""Wire protocol: length-prefixed JSON frames over a unix socket.

Every message in both directions is one frame::

    +----------------+----------------------+
    | 4 bytes, u32BE |  <length> JSON bytes |
    +----------------+----------------------+

The JSON document is always an object.  Client requests carry an
``op`` key (``submit`` / ``status`` / ``pause`` / ``resume`` /
``shutdown`` / ``metrics`` / ``health`` / ``watch`` / ``flight`` /
``explain``);
server responses carry ``ok`` (bool) and, when ``ok`` is false, a
machine-readable ``error`` object::

    {"ok": false,
     "error": {"code": "queue_full", "reason": "...", ...}}

Error codes in use: ``queue_full`` (backpressure — the bounded queue
is at capacity), ``draining`` (SIGTERM/shutdown received; running
jobs finish, new ones are rejected), ``job_too_large`` (admission
control: the priced wall exceeds ``RACON_TPU_SERVE_MAX_WALL_S``),
``input_not_found`` / ``bad_request`` (malformed submission), and
``job_failed`` (the polish itself raised; the queue and the warm
engines survive).

Polished FASTA rides inside the JSON response base64-encoded
(``fasta_b64``) so the framing stays single-format; the client
decodes back to the exact bytes the polisher emitted.

A submission's job spec may carry an optional ``tenant`` tag (string,
<= 64 chars, default ``"default"``; client flag ``--tenant``): the
tenant the job's device work is accounted to in the r13 cross-job
fused executor (racon_tpu/tpu/executor.py) — fusion stats surface
under ``fusion`` in the ``metrics``/``watch`` telemetry, per-tenant
queue-wait SLOs as ``serve_queue_wait_s.<tenant>`` /
``serve_tenant_wait_s.<tenant>`` histograms.  The tag never affects
output bytes, only fairness/accounting.

Telemetry ops (r12, racon_tpu/obs/export.py):

* ``metrics`` — one response frame with the process registry as both
  Prometheus text exposition (``prometheus``) and a JSON snapshot
  with per-histogram p50/p90/p99 (``snapshot``), plus per-engine
  device utilization (``device_util``) and the serving-SLO percentile
  table (``slo``).
* ``health`` — a cheap liveness/readiness document (no registry
  walk): uptime, queue depth, draining/paused state.
* ``watch`` — the one multi-frame op: the server streams one
  telemetry frame (same shape as ``metrics`` minus the Prometheus
  text) every ``interval_s`` seconds (clamped to 0.05..60, default
  1.0), ``seq``-numbered, until the optional ``count`` is reached,
  the client closes, or the server drains.  Every frame carries
  ``ok: true``; the stream ending is the only termination signal.

Forensics ops (r14, racon_tpu/obs/flight.py):

* ``flight`` — the live flight-recorder view: ring stats (``ring``)
  and the structured event list (``events``), optionally filtered
  with ``job: <id>`` (that job's events only, plus its bounded trace
  slice as ``job_trace``) and/or ``last: <n>`` (newest n events).
* ``submit`` with ``trace: true`` — the response frame additionally
  carries the finished job's trace slice (``trace_events``, Chrome
  trace events tagged ``{job, tenant, trace_id}``) and its flight
  events (``flight_events``) — the ``racon-tpu inspect`` /
  ``submit --trace`` source.

Fleet ops (r15, racon_tpu/serve/fleet.py):

* ``submit`` may carry ``trace_context`` (string, 1..128 chars of
  ``[A-Za-z0-9._:-]`` starting alphanumeric — traceparent-style):
  the daemon adopts it as the job's trace id, so spans, flight
  events and ``inspect`` timelines from DIFFERENT daemons handling
  parts of one logical request share a trace id end-to-end.  A
  malformed value is ``bad_request``; absent, the daemon mints its
  own deterministic ``<pid>-<job>`` id as before.
* ``metrics`` / ``health`` / ``watch`` / ``status`` responses carry
  an ``identity`` block (``daemon_id`` — stable 12-hex digest of
  host/socket/pid/start, plus ``host``/``pid``/``socket``/
  ``start_epoch``/``version``/``backend``) so a fleet scraper
  attributes every frame to a PROCESS, not a socket path that may
  be reused across restarts.

Decision-plane ops (r16, racon_tpu/obs/decision.py + calhealth.py):

* ``explain`` — the decision-record view: per-stage calibration
  health (``calhealth`` — predicted/actual drift EWMA + p50/p99 per
  stage with advisory recalibration flags), decision-ring stats
  (``ring``), per-kind event counts (``counts``) and the structured
  decision events themselves (``events``), optionally filtered with
  ``job: <id>`` and/or ``last: <n>`` exactly like ``flight``.  The
  ``racon-tpu explain`` CLI renders a per-job cost waterfall from
  this one frame.
* ``metrics`` / ``watch`` frames also carry the ``calhealth``
  summary, so the ``top`` drift column needs no extra round trip.

Durability (r17, racon_tpu/serve/journal.py + recover.py):

* ``submit`` may carry ``job_key`` (same charset/length rule as
  ``trace_context``; client flag ``--job-key``): the client's
  idempotence key.  A duplicate submit with the same key joins the
  live job (one run, every duplicate gets the same response), and a
  duplicate AFTER completion — including after a daemon crash and
  restart — is answered from the write-ahead journal's recorded
  result without re-running.  A malformed value is ``bad_request``.
* ``health`` / ``status`` responses carry a ``journal`` block (the
  write-ahead journal's ``enabled``/``path``/``depth``/``bytes``/
  ``fsync``/``last_fsync_t``) and the restart-recovery summary
  (``health``: ``recovered_jobs`` + ``recovery``; ``status``:
  ``recovered``) so an operator can verify durability is on and see
  what a restart replayed.
* The journal file itself (``<socket>.journal``) uses THIS module's
  length-prefixed JSON framing, one record per frame — see
  racon_tpu/serve/journal.py for the record schema
  (``racon-tpu-journal-v1``) and ``RACON_TPU_JOURNAL*`` knobs.

Result cache (r18, racon_tpu/cache/):

* ``metrics`` / ``watch`` / ``explain`` frames carry a ``cache``
  block — the content-addressed result cache's stats (``enabled``,
  ``entries``, ``bytes``, ``budget_bytes``, ``hits``, ``misses``,
  ``fills``, ``evicts``, ``disk_hits``, ``hit_ratio``, and, when the
  persistent tier is on, ``persist`` with its directory and indexed
  entry count).  ``health`` carries a cheaper ``cache`` summary
  (``enabled``/``hit_ratio``/``bytes``/``entries``).  The
  ``cache_hit``/``cache_miss``/``cache_fill``/``cache_evict``
  counters also ride the registry snapshot, so fleet merges
  (racon_tpu/obs/aggregate.py) sum them exactly and the merged
  hit ratio is the true fleet ratio.  Cache state is policy-only:
  a hit returns the same bytes the engines would recompute
  (pinned by tests/test_cache.py), so no protocol field changes
  meaning based on cache temperature.
* The persistent segment files (``seg-<pid>.rseg`` under the cache
  root) reuse this module's u32BE length-prefix framing with a
  binary body (32-byte key + crc32 + codec blob) — see
  racon_tpu/cache/store.py (``racon-tpu-rcache-v1``) and the
  ``RACON_TPU_CACHE*`` knobs.

Fleet routing (r19, racon_tpu/serve/router.py):

* The framing is transport-agnostic by construction (both helpers
  below take any connected socket object), and r19 uses that: a
  ``racon-tpu route`` router speaks the SAME frames on its unix
  socket and on an optional TCP listener (``--tcp HOST:PORT`` /
  ``RACON_TPU_ROUTE_TCP``), so clients address a router as
  ``host:port`` with no protocol change (racon_tpu/serve/client.py
  picks the address family from the address's shape).
* ``route_status`` — router-only op: per-backend circuit-breaker
  state (``CLOSED``/``OPEN``/``HALF-OPEN``), consecutive failures,
  probe staleness, draining flags, and the router's
  ``route_submit``/``route_spillover``/``route_failover``/
  ``route_dedup_joins`` counters.  A router's ``status`` answers the
  same document, flagged ``router: true`` so ``racon-tpu status``
  renders it as a router.  Routers also answer ``health`` /
  ``metrics`` / ``flight`` / ``shutdown`` in the daemon shapes
  (``metrics`` adds a ``route`` block), and proxy ``submit``
  verbatim — placement, spillover and crash failover are invisible
  in the response apart from an added ``routed_backend`` field.
* ``queue_full`` / ``draining`` reject objects now carry
  ``retry_after_s`` — the server's own estimate of when a retry can
  admit, priced from its observed exec walls and queue state.
  Clients (``submit_with_retry``) and the router's spillover loop
  prefer the hint over their blind exponential schedules; the
  jittered schedule remains the fallback.  A router that exhausts
  every backend answers the code ``no_backend``.

Scatter/gather mega-job sharding (r20, racon_tpu/serve/scatter.py):

* ``submit`` takes an optional ``shards`` field — an int (forced
  shard count; 0 forces unsharded), or ``"auto"`` (one shard per
  eligible backend).  Routers consume it; absent the field, a router
  auto-scatters only when the admission estimate exceeds
  ``RACON_TPU_SCATTER_MIN_WALL_S``.  Plain daemons instead accept a
  sub-job field ``spec["shard"] = [index, count]`` — the target
  shard the polisher owns (the ``target_slice`` contract) — which
  the router sets on each fanned-out sub-job; sub-jobs run under
  derived idempotence keys ``<job_key>-shard-<i>of<k>`` so the r17
  journal gives exactly-once per shard.
* A scattered submit's response is ONE merged frame: the FASTA is
  the shard outputs concatenated in shard order (byte-identical to
  the unsharded run by construction), ``report`` is a
  ``racon-tpu-scatter-v1`` doc with ``per_shard`` sub-blocks and
  the full shard reports, and a ``scatter`` block names the shard
  count and backends.  ``route_status`` shows live scatter progress
  (``scatter.active``: per-job done/shards counts) plus the
  ``route_scatter_jobs``/``route_scatter_shards``/
  ``route_cache_affinity`` counters; a router's ``health`` doc
  carries ``scatter: true`` as the capability flag wrappers key off.

Staged inputs + straggler rebalancing (r21, racon_tpu/io/staging.py
+ the router watchdog):

* Sub-job specs may carry ``spec["stage"]`` — the router's slice
  hint for the shard's overlaps file: ``{"ranges": [[start, end),
  ...], "sig": [size, newline-count], "shard": [i, k],
  "staged_bytes": N, "total_bytes": M}``.  The daemon validates the
  signature and shard coordinates against the file it opens and
  restricts the overlap scan to the byte ranges (the record stream
  for owned targets is byte-identical to the full parse); ANY
  mismatch, malformed hint, or ``RACON_TPU_STAGE=0`` falls back to
  the full parse — staging is policy, never bytes.  The job report's
  ``host.staged_bytes`` / ``host.parse_skipped_bytes`` gauges
  account for the skip.
* ``cancel`` op: ``{"op": "cancel", "job_key": K}`` — best-effort
  cancellation by idempotence key.  A queued job finishes as the
  error code ``job_canceled`` without running; a running one stops
  at its next between-units poll site (after its last committed
  checkpoint); unknown or finished keys are a safe no-op (the reply
  carries ``state`` saying which).  The router's rebalancer
  broadcasts this for superseded attempt keys.
* A straggling shard (elapsed beyond ``max(factor x p50 predicted
  shard wall, 4 probe periods)``, factor from
  ``RACON_TPU_SCATTER_REBALANCE``) gets a speculative replacement
  under the derived key ``<job_key>-shard-<i>of<k>-r<n>`` on the
  idlest untried backend; first success wins the shard, losers are
  canceled.  The merged response's ``scatter`` block adds
  ``staged_bytes`` and ``rebalanced`` (per-shard lineage strings,
  e.g. ``"0of2-r1 <- 0of2"``); ``route_status``'s
  ``scatter.active`` rows add per-shard ``staged_bytes`` /
  ``parse_skipped_bytes`` / ``rebalanced``, its ``scatter`` block
  reports ``rebalance_factor`` and ``staging``, and the
  ``route_stage_plans`` / ``route_rebalance`` / ``route_cancels``
  counters plus ``route_stage_plan`` / ``route_rebalance`` flight
  events make every plan and handoff auditable.

Closed control loop (r22, racon_tpu/cache/sketch.py +
racon_tpu/serve/affinity.py + scheduler deadline classes):

* A submission's job spec may carry an optional ``class`` field
  (``"interactive"`` | ``"batch"``, default ``"interactive"``;
  client flag ``--class``).  Validated at admission (any other
  value is ``bad_request``).  The class orders same-priority work
  (interactive before batch, with an aging bound so batch never
  starves), scales the job's device-executor DRR weight from the
  observed per-class queue-wait p99 vs ``RACON_TPU_CLASS_TARGET_
  P99_S``, and reserves queue headroom for interactive admissions
  (``RACON_TPU_CLASS_HEADROOM``, scaled up while the SLO is
  missed).  ``queue_full``/``draining`` rejects price their
  ``retry_after_s`` from the class's own exec-wall histogram.
  Scheduling policy only — the class never changes output bytes.
* A daemon's ``health`` and ``metrics``/``watch`` cache blocks
  carry ``sketch`` — a compact epoch-tagged digest-membership
  sketch of the result cache's contents
  (``{"schema": "racon-tpu-sketch-v1", "m": 65536, "k": 4,
  "n": ..., "epoch": <engine-epoch hex>, "bits": <base64
  bitmap>}``, ~11 KiB).  The fleet router scores each
  content-keyed submit's digest sample against every backend's
  sketch and folds the estimated hit fraction into placement
  pricing (``RACON_TPU_ROUTE_AFFINITY``).  Sketch staleness or
  false positives only mis-price placement — the content-addressed
  unit keys still decide every actual cache hit, so bytes never
  depend on the sketch.

Fleet forensics (r23, racon_tpu/obs/assemble.py +
``racon-tpu inspect --fleet``):

* ``journal_query`` op: ``{"op": "journal_query", "job_key": K |
  "job_key_prefix": P, "max_records": N [, "max_bytes": B]}`` — a
  bounded, READ-ONLY slice of the daemon's write-ahead journal.  A
  key filter (exact key matches its whole r20/r21 derived family:
  ``K``, ``K-shard-<i>of<k>``, ``...-r<n>``) AND a positive
  ``max_records`` are required; an unbounded ask is ``bad_request``.
  Caps: 1024 records / 8 MiB per response (asks above are clamped).
  Records are returned oldest-first (the newest ``max_records`` of
  the match); ``done`` records have their result frames slimmed —
  ``fasta_b64`` is replaced by a ``fasta_bytes`` length so a
  forensic read never hauls result payloads.  ``complete: false``
  flags a clipped response, ``scan_truncated`` a torn journal tail.
  Journal-off daemons and the router answer
  ``{"ok": true, "enabled": false, "records": []}``.
* ``trace_query`` op: ``{"op": "trace_query", "job": N,
  "max_events": M}`` — the bounded per-job captured trace slice
  (what ``submit --trace`` would have attached), readable after the
  fact.  ``max_events`` required (cap 4096); ``complete: false``
  when clipped.  The router answers from its own capture (r23
  router forensic parity: ``route.submit`` / ``route.attempt``
  spans).
* Clock anchors: ``health``, ``flight``, ``journal_query`` and
  ``trace_query`` responses carry ``wall_t`` (the daemon's wall
  clock at reply build) and ``trace_epoch_wall`` (the wall time of
  its monotonic trace epoch).  A collector estimates per-daemon
  clock offsets from health-probe send/recv pairs (midpoint
  estimator, min-RTT probe of three; confidence = half the round
  trip) and uses them to align flight/trace/journal timestamps onto
  one timeline.  RENDERING ONLY: offsets never steer control flow
  and never touch job bytes.
* ``health`` additionally reports ``capture`` — per-surface depth
  (``flight`` ring size/capacity/dropped, ``trace`` per-job index
  jobs/max_jobs/spans_per_job/evicted, ``journal`` enabled/path) —
  so a fleet assembler can warn when a ring rolled over mid-job
  instead of presenting a silently partial lineage.
* ``flight`` accepts ``job_key`` (matches the key's derived family)
  and ``trace_id`` (exact) filters alongside the existing ``job`` /
  ``last``.
* Trace-context adoption: a routed submit with no client
  ``trace_context`` now adopts its idempotence key as the wire
  trace id, and the router propagates it through every scatter /
  rebalance / failover sub-submit, so all fragments of one
  distributed job share one trace id.  Backend ``ok`` results carry
  ``trace_id`` (journaled, so dedup replays keep the ORIGINAL id);
  scatter reports carry per-shard ``trace_id``.  With ``trace``
  set, a router's submit response adds ``router_pid`` /
  ``router_flight_events`` / ``router_trace_events`` beside the
  winning backend's — forensic parity between the two halves of a
  routed job.

Internal overlap discovery + rounds (r24, racon_tpu/overlap/):

* ``submit`` specs no longer require an overlaps input.
  ``overlaps: null`` (or the key absent) plus an integer ``rounds``
  field (1..16; out-of-range or non-integer is ``bad_request``)
  opts the job into the in-process minimap-lite mapper: overlaps
  are discovered against the draft before polishing, and the job
  runs ``rounds`` polish→re-map→re-polish rounds.  The client
  builds this spec from ``submit reads.fq draft.fa --rounds N``
  (two positionals, no PAF).
* A spec with no overlaps and NO ``rounds`` field is answered with
  the structured ``missing_overlaps`` error code (machine-readable,
  distinct from ``input_not_found``) whose ``hint`` names the
  ``--rounds`` opt-in and the accepted external formats.
* The admission estimate prices the map stage from input bytes
  (``RACON_TPU_SERVE_MAP_MBPS``) — surfaced as ``map_s`` in the
  ``estimate`` block — and multiplies the wall terms by the round
  count (``rounds`` echoed in the estimate).
* The per-job report's ``details`` carry a ``rounds`` list (one
  entry per round: ``wall_s``, ``map_s``, ``overlaps``,
  ``cache_hit``, ``n_sequences``) so clients can observe the
  inter-round cache discount; scatter sub-jobs inherit the whole
  spec, so ``rounds`` rides shard plans unchanged.
"""

from __future__ import annotations

import json
import struct

#: refuse frames past this size (a corrupt length prefix must not
#: make the server try to allocate gigabytes)
FRAME_MAX = 1 << 30

_LEN = struct.Struct(">I")


class ProtocolError(RuntimeError):
    """Malformed frame (bad length prefix, truncated body, non-JSON
    payload).  The server answers one ``bad_request`` frame when it
    can and drops the connection; the warm state is untouched."""


def send_frame(sock, obj: dict) -> None:
    payload = json.dumps(obj, separators=(",", ":")).encode()
    if len(payload) > FRAME_MAX:
        raise ProtocolError(f"frame too large ({len(payload)} bytes)")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise ProtocolError(
                f"connection closed mid-frame ({len(buf)}/{n} bytes)")
        buf += chunk
    return bytes(buf)


def recv_frame(sock):
    """Read one frame; returns the decoded object, or ``None`` on a
    clean EOF at a frame boundary (peer closed)."""
    head = b""
    while len(head) < _LEN.size:
        chunk = sock.recv(_LEN.size - len(head))
        if not chunk:
            if head:
                raise ProtocolError("connection closed mid-prefix")
            return None
        head += chunk
    (n,) = _LEN.unpack(head)
    if n > FRAME_MAX:
        raise ProtocolError(f"frame length {n} exceeds FRAME_MAX")
    try:
        return json.loads(_recv_exact(sock, n))
    except ValueError as exc:
        raise ProtocolError(f"frame body is not JSON ({exc})") from exc


def error_frame(code: str, reason: str, **extra) -> dict:
    err = {"code": code, "reason": reason}
    err.update(extra)
    return {"ok": False, "error": err}
