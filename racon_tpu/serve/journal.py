"""Write-ahead job journal: the serve tier's crash-safe record (r17).

The flight recorder (racon_tpu/obs/flight.py) answers "what was the
daemon doing" after the fact; it cannot bring the work back — a
crashed daemon lost its queue and every in-flight job.  The journal
promotes the flight-event schema from forensics to a write-ahead
log: every job-state transition is appended to an fsync'd on-disk
record BEFORE the daemon acts on it, so a restarted daemon replays
the file (racon_tpu/serve/recover.py) and requeues what was
interrupted.

File format — append-only, length-prefixed JSON records, the same
framing the wire protocol uses (racon_tpu/serve/protocol.py)::

    +----------------+----------------------+
    | 4 bytes, u32BE |  <length> JSON bytes |
    +----------------+----------------------+ ...repeated

Each record is a flight-event-shaped object (``kind``/``t``/``job``/
``tenant`` + kind-specific fields) plus the journal envelope
(``seq``, ``pid`` — records from several daemon incarnations share
one file and are told apart by pid).  The first record of every
incarnation is ``journal_open`` carrying ``schema:
"racon-tpu-journal-v1"``.  Record kinds written by the serve tier:

* ``admit``      — full job spec + ``job_key`` + priority/tenant/
  trace id + the calibration-epoch snapshot the job is pinned to
  (racon_tpu/utils/calibrate.epoch_snapshot)
* ``start``      — a worker popped the job
* ``checkpoint`` — one committed POA megabatch demux: the completed
  window ordinals with their consensus bytes (b64) and polish flags,
  so resume skips recompute AND stays byte-identical (the windows
  adopt like speculative results — see TPUPolisher)
* ``done``       — terminal success, carrying the full result frame
  body (fasta_b64 + report) so a duplicate idempotent submit after
  restart is answered from the record instead of re-running
* ``error``      — terminal failure with the structured error
* ``recovery``   — a restarted daemon's replay summary

Every job carries a ``job_key`` — client-supplied (``submit
--job-key``, idempotence across client retries) or daemon-minted
(``auto-<pid>-<id>``) — and replay merges records ACROSS
incarnations by that key, so a job requeued after crash N and
crashed again at N+1 resumes at N+2 with the union of its
checkpoints.

Durability contract: ``append`` returns only after write+flush+
fsync (``RACON_TPU_JOURNAL_FSYNC=0`` trades the fsync away for
throughput).  ``scan`` tolerates a torn tail — a crash mid-append
loses at most the record being written, never the file.  Timestamps
are wall-clock (``obs.trace.wall_now``): journal records are cross-process
identifiers read by a LATER process, so the per-process trace epoch
the flight ring uses would not correlate.

Read surfaces: recovery replay (racon_tpu/serve/recover.py) and,
since r23, the bounded ``journal_query`` wire op — a key-filtered,
record/byte-capped slice served off :func:`scan` against the file
path (never the live append handle), with ``done`` result bodies
slimmed to sizes.  The fleet forensics assembler
(racon_tpu/obs/assemble.py) aligns the wall-clock ``t`` of these
records onto a collector timeline via per-daemon offset estimates.

Knobs (provenance.KNOWN_KNOBS): ``RACON_TPU_JOURNAL`` ("0"
disables — the daemon then behaves exactly as before r17),
``RACON_TPU_JOURNAL_DIR`` (default: the socket's directory),
``RACON_TPU_JOURNAL_FSYNC``.
"""

from __future__ import annotations

import json
import os
import struct
import threading

from racon_tpu.obs import faultinject
from racon_tpu.obs.trace import wall_now

SCHEMA = "racon-tpu-journal-v1"

_LEN = struct.Struct(">I")
#: refuse records past this size on scan (a torn length prefix must
#: not make replay try to allocate gigabytes)
RECORD_MAX = 1 << 30


def enabled() -> bool:
    return os.environ.get("RACON_TPU_JOURNAL", "1") != "0"


def journal_path(socket_path: str) -> str:
    """Where the journal for a daemon on ``socket_path`` lives:
    ``<socket>.journal`` beside the socket (or under
    ``RACON_TPU_JOURNAL_DIR``) — so a restart on the same socket
    finds the previous incarnation's record with zero config."""
    d = os.environ.get("RACON_TPU_JOURNAL_DIR") \
        or os.path.dirname(os.path.abspath(socket_path))
    return os.path.join(d, os.path.basename(socket_path) + ".journal")


def scan(path: str):
    """Read every intact record -> ``(records, truncated)``.

    A torn tail (partial prefix, short body, or non-JSON bytes — the
    shapes a SIGKILL mid-append leaves) ends the scan cleanly with
    ``truncated=True``; everything before it is returned."""
    records = []
    try:
        f = open(path, "rb")
    except OSError:
        return records, False
    with f:
        while True:
            head = f.read(_LEN.size)
            if not head:
                return records, False
            if len(head) < _LEN.size:
                return records, True
            (n,) = _LEN.unpack(head)
            if n > RECORD_MAX:
                return records, True
            body = f.read(n)
            if len(body) < n:
                return records, True
            try:
                rec = json.loads(body)
            except ValueError:
                return records, True
            if isinstance(rec, dict):
                records.append(rec)


class JobJournal:
    """One daemon incarnation's append handle.  All methods are
    thread-safe; :func:`append` is called from the admission path,
    the worker loop and the polisher's checkpoint callback
    concurrently."""

    def __init__(self, path: str, prior_records: int = 0):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._f = open(path, "ab")
        self._fsync = os.environ.get(
            "RACON_TPU_JOURNAL_FSYNC", "1") != "0"
        self._seq = 0
        self._prior = prior_records
        self._last_fsync_t = None
        self.append("journal_open", schema=SCHEMA,
                    fsync=self._fsync)

    def append(self, kind: str, job=None, **fields) -> None:
        """Durably append one record.  Returns only after the bytes
        are flushed (+fsync'd unless RACON_TPU_JOURNAL_FSYNC=0) —
        callers rely on write-AHEAD ordering: the record survives
        any crash that happens after this returns."""
        rec = {"kind": kind, "t": round(wall_now(), 6),
               "pid": os.getpid()}
        if job is not None:
            rec["job"] = int(job)
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        faultinject.hit("journal-write")
        with self._lock:
            self._seq += 1
            # seq assigned under the lock so file order and seq
            # order agree
            rec["seq"] = self._seq
            payload = json.dumps(
                rec, separators=(",", ":")).encode()
            self._f.write(_LEN.pack(len(payload)) + payload)
            self._f.flush()
            if self._fsync:
                os.fsync(self._f.fileno())
                self._last_fsync_t = wall_now()

    def stats(self) -> dict:
        """The ``health``/``status`` journal block: path, record
        depth (prior incarnations + this one) and fsync recency."""
        with self._lock:
            try:
                size = os.fstat(self._f.fileno()).st_size
            except OSError:
                size = None
            return {
                "enabled": True,
                "path": self.path,
                "depth": self._prior + self._seq,
                "appended": self._seq,
                "bytes": size,
                "fsync": self._fsync,
                "last_fsync_t": (round(self._last_fsync_t, 3)
                                 if self._last_fsync_t else None),
            }

    def close(self) -> None:
        with self._lock:
            try:
                self._f.flush()
                if self._fsync:
                    os.fsync(self._f.fileno())
            except OSError:
                pass
            try:
                self._f.close()
            except OSError:
                pass
