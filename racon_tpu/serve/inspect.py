"""``racon-tpu inspect``: one job's timeline, from a live daemon or
a flight dump.

The r14 forensics story has three storage forms — the flight ring
(live, via the ``flight`` op), the flight dump (post-mortem JSON)
and the per-job trace slice — and this subcommand is the single
reader for all of them: given a job id it renders the job's life as
a relative-time line per event::

    job 17 (tenantA) — 6 flight event(s)
      +0.000s  admit           priority=0 predicted_wall=4.1s queue_depth=1
      +0.012s  start           queue wait 0.012s
      +0.640s  fused_dispatch  poa units=2 items=96 occupancy=0.75 tenants=tenantA,tenantB
      ...
      +2.310s  done            ok exec_wall=2.298s

so "what happened to job 17" is answerable from a terminal whether
the daemon is still alive or already dead.  Without ``--job`` it
summarizes every job the source knows about.

Sources:

* ``--socket PATH`` — queries a running daemon's ``flight`` op (and,
  with ``--job``, the bounded per-job trace slice rides along).
* ``--dump FILE`` — reads a flight dump written on drain/idle/crash
  (racon_tpu/obs/flight.py) or by ``RACON_TPU_FLIGHT_DUMP``.
* ``--fleet ADDR --job-key K`` (r23) — fleet forensics: collects
  flight events, journal records and trace slices from the router
  and every backend it discloses, reconstructs the lineage DAG
  (racon_tpu/obs/assemble.py: scatter shards, rebalance attempts,
  failovers, dedup joins, gather winners) and renders a
  clock-offset-corrected cross-daemon timeline.  ``--trace-out``
  additionally writes the merged Perfetto-loadable trace doc;
  ``--json`` prints the ``racon-tpu-lineage-v1`` document.

Read-only: no op used here touches queue or job state.
"""

from __future__ import annotations

import argparse
import json
import sys


def job_events(events, job: int) -> list:
    """Events belonging to ``job`` — tagged directly or via a fused
    dispatch's ``jobs`` list — in (time, seq) order."""
    job = int(job)
    sel = [ev for ev in events
           if ev.get("job") == job or job in ev.get("jobs", ())]
    sel.sort(key=lambda ev: (ev.get("t", 0.0), ev.get("seq", 0)))
    return sel


def _detail(ev: dict) -> str:
    kind = ev.get("kind", "?")
    if kind == "submit":
        return f"tenant={ev.get('tenant', 'default')}"
    if kind == "admit":
        parts = [f"priority={ev.get('priority', 0)}"]
        if "predicted_wall_s" in ev:
            parts.append(f"predicted_wall={ev['predicted_wall_s']}s")
        if "shared_wall_s" in ev:
            parts.append(f"shared_wall={ev['shared_wall_s']}s")
        if "queue_depth" in ev:
            parts.append(f"queue_depth={ev['queue_depth']}")
        return " ".join(parts)
    if kind == "reject":
        return f"code={ev.get('code')}"
    if kind == "start":
        if "queue_wait_s" in ev:
            return f"queue wait {ev['queue_wait_s']}s"
        return ""
    if kind == "fused_dispatch":
        return (f"{ev.get('unit_kind', '?')} "
                f"units={ev.get('units', '?')} "
                f"items={ev.get('items', '?')} "
                f"occupancy={ev.get('occupancy', '?')} "
                f"tenants={','.join(ev.get('tenants', []))}")
    if kind == "cache_hit":
        return (f"{ev.get('unit_kind', '?')} "
                f"hits={ev.get('hits', '?')}/{ev.get('items', '?')} "
                f"misses={ev.get('misses', '?')}")
    if kind == "unit_retry":
        return (f"{ev.get('unit_kind', '?')} "
                f"tenant={ev.get('tenant', 'default')} "
                f"items={ev.get('items', '?')} "
                f"error={ev.get('error', '?')}")
    if kind in ("error", "crash"):
        err = str(ev.get("error", "")).splitlines()
        return err[0] if err else ""
    if kind == "done":
        ok = "ok" if ev.get("ok") else "FAILED"
        return f"{ok} exec_wall={ev.get('exec_wall_s', '?')}s"
    if kind == "drain":
        return (f"queued={ev.get('queued', 0)} "
                f"running={ev.get('running', 0)}")
    if kind == "checkpoint":
        return f"windows={ev.get('n_windows', '?')}"
    if kind == "dedup":
        return (f"job_key={ev.get('job_key', '?')} "
                + ("answered from record" if ev.get("recorded")
                   else "joined live job"))
    if kind == "recover":
        return (f"job_key={ev.get('job_key', '?')} "
                f"checkpoint_windows="
                f"{ev.get('checkpoint_windows', 0)} "
                f"from={ev.get('recovered_from', '?')}")
    if kind == "recovery":
        return (f"records={ev.get('records', 0)} "
                f"completed={ev.get('completed', 0)} "
                f"requeued={ev.get('requeued', 0)} "
                f"failed={ev.get('failed', 0)}")
    # r21 straggler rebalancing: the router's handoff, the daemon's
    # cancel acknowledgement, and the yielding job's terminal event
    if kind == "route_rebalance":
        return (f"shard={ev.get('shard', '?')} "
                f"r{ev.get('attempt', '?')} -> "
                f"{ev.get('backend', '?')} "
                f"elapsed={ev.get('elapsed_s', '?')}s "
                f"threshold={ev.get('threshold_s', '?')}s")
    if kind == "route_stage_plan":
        staged = ev.get("staged_bytes") or []
        return (f"shards={ev.get('shards', '?')} "
                f"staged_bytes={'/'.join(str(b) for b in staged)} "
                f"of {ev.get('total_bytes', '?')}")
    if kind == "cancel":
        return (f"job_key={ev.get('job_key', '?')} "
                f"state={ev.get('state', '?')}")
    if kind == "job_canceled":
        return "yielded to a rebalanced attempt"
    return ""


def render_timeline(events, job: int, trace_events=None) -> str:
    """Pure renderer (tests golden it): one relative-time line per
    flight event, then a short trace-slice appendix when present."""
    sel = job_events(events, job)
    if not sel:
        return (f"job {job}: no events in this source (evicted from "
                f"the ring, or never seen here)\n")
    tenant = next((ev["tenant"] for ev in sel if "tenant" in ev),
                  "default")
    # the job's trace id (r15: possibly wire-propagated by the
    # caller) rides the header so timelines from different daemons
    # correlate by eye
    trace = next((ev["trace_id"] for ev in sel
                  if ev.get("trace_id")), None)
    who = f"{tenant}, trace {trace}" if trace else tenant
    t0 = sel[0].get("t", 0.0)
    lines = [f"job {job} ({who}) — {len(sel)} flight event(s)"]
    for ev in sel:
        dt = ev.get("t", t0) - t0
        lines.append(f"  +{dt:9.3f}s  {ev.get('kind', '?'):<15s} "
                     f"{_detail(ev)}".rstrip())
    if trace_events:
        lines.append(f"trace slice — {len(trace_events)} event(s)")
        shown = 0
        for ev in trace_events:
            if ev.get("ph") not in ("X", "i"):
                continue
            ts = ev.get("ts", 0.0) / 1e6 - t0
            dur = ev.get("dur")
            tail = f" dur={dur / 1e6:.3f}s" if dur is not None else ""
            lines.append(f"  +{ts:9.3f}s  {ev.get('name')}{tail}")
            shown += 1
            if shown >= 40:
                lines.append(f"  ... ({len(trace_events) - shown} "
                             f"more)")
                break
    return "\n".join(lines) + "\n"


def render_summary(events, header: str = "") -> str:
    """No ``--job``: one row per job seen in the source, plus the
    non-job markers (drain/crash) that frame them."""
    jobs: dict = {}
    markers = []
    for ev in events:
        ids = [ev["job"]] if "job" in ev else list(ev.get("jobs", ()))
        if not ids and ev.get("kind") in ("drain", "crash", "run",
                                          "run_done"):
            markers.append(ev)
        for j in ids:
            row = jobs.setdefault(j, {"tenant": None, "kinds": [],
                                      "t0": ev.get("t", 0.0)})
            if row["tenant"] is None and ev.get("tenant"):
                row["tenant"] = ev["tenant"]
            row["kinds"].append(ev.get("kind", "?"))
    lines = [header] if header else []
    if not jobs and not markers:
        lines.append("no events recorded")
        return "\n".join(lines) + "\n"
    for j in sorted(jobs):
        row = jobs[j]
        kinds = ",".join(row["kinds"])
        lines.append(f"job {j:<5d} tenant={row['tenant'] or '-':<12s} "
                     f"events: {kinds}")
    for ev in markers:
        lines.append(f"[{ev.get('kind')}] {_detail(ev)}".rstrip())
    return "\n".join(lines) + "\n"


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="racon-tpu inspect",
        description="Render a served job's timeline (queue wait, "
        "exec, fused dispatches with occupancy) from a live daemon's "
        "flight recorder or a flight dump file.")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--socket",
                     help="unix-domain socket of a live daemon")
    src.add_argument("--dump",
                     help="flight dump JSON written on "
                     "drain/idle/crash")
    src.add_argument("--fleet", metavar="ADDR",
                     help="router (or daemon) address for fleet "
                     "forensics: lineage DAG + clock-aligned "
                     "cross-daemon timeline (needs --job-key or "
                     "--trace-id)")
    p.add_argument("--job", type=int, default=None,
                   help="job id to render (omit for a per-job "
                   "summary of the whole source)")
    p.add_argument("--job-key", default=None,
                   help="with --fleet: the job's idempotence key "
                   "(lineage covers its derived shard/rebalance "
                   "keys)")
    p.add_argument("--trace-id", default=None,
                   help="with --fleet: wire trace id to assemble "
                   "instead of a job key")
    p.add_argument("--trace-out", metavar="FILE", default=None,
                   help="with --fleet: also write the merged "
                   "Perfetto-loadable trace document here")
    p.add_argument("--last", type=int, default=0,
                   help="with --socket and no --job: only the newest "
                   "N events")
    p.add_argument("--timeout", type=float, default=None,
                   help="with --fleet: per-target timeout in seconds "
                   "(default RACON_TPU_FLEET_TIMEOUT_S)")
    p.add_argument("--json", action="store_true",
                   help="print the raw event document instead of the "
                   "rendered timeline (with --fleet: the "
                   "racon-tpu-lineage-v1 document)")
    return p


def main_fleet(args) -> int:
    """The ``--fleet`` path: collect, build the lineage DAG, render.
    Exit status reflects lineage completeness (0 complete, 1 not) so
    scripts can gate on it."""
    from racon_tpu.obs import assemble
    if not args.job_key and not args.trace_id:
        print("[racon_tpu::inspect] --fleet needs --job-key or "
              "--trace-id", file=sys.stderr)
        return 2
    try:
        collection, lineage = assemble.assemble(
            args.fleet, job_key=args.job_key,
            trace_id=args.trace_id, timeout=args.timeout)
    except Exception as exc:
        print(f"[racon_tpu::inspect] error: {exc}", file=sys.stderr)
        return 1
    if args.trace_out:
        doc = assemble.merged_trace_doc(lineage, collection)
        with open(args.trace_out, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
        print(f"[racon_tpu::inspect] merged trace -> "
              f"{args.trace_out} ({len(doc['traceEvents'])} "
              f"event(s))", file=sys.stderr)
    if args.json:
        json.dump(lineage, sys.stdout, indent=1)
        print()
    else:
        sys.stdout.write(
            assemble.render_fleet_timeline(lineage, collection))
    return 0 if lineage.get("complete") else 1


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    if args.fleet:
        return main_fleet(args)
    if args.socket:
        from racon_tpu.serve import client
        try:
            doc = client.flight(args.socket, job=args.job,
                                last=args.last,
                                job_key=args.job_key,
                                trace_id=args.trace_id)
        except client.ServeError as exc:
            print(f"[racon_tpu::inspect] error: {exc}",
                  file=sys.stderr)
            return 1
        if not doc.get("ok"):
            print(f"[racon_tpu::inspect] error: "
                  f"{doc.get('error')}", file=sys.stderr)
            return 1
        events = doc.get("events", [])
        trace_events = doc.get("job_trace")
        ring = doc.get("ring", {})
        header = (f"flight ring @ pid {doc.get('pid')}: "
                  f"{ring.get('size', 0)}/{ring.get('capacity', 0)} "
                  f"event(s), {ring.get('dropped', 0)} dropped")
    else:
        from racon_tpu.obs import flight as obs_flight
        try:
            doc = obs_flight.load_dump(args.dump)
        except (OSError, ValueError) as exc:
            print(f"[racon_tpu::inspect] error: {exc}",
                  file=sys.stderr)
            return 1
        events = doc.get("events", [])
        trace_events = None
        ring = doc.get("ring", {})
        header = (f"flight dump {args.dump} (pid {doc.get('pid')}, "
                  f"reason {doc.get('reason')!r}): "
                  f"{len(events)} event(s), "
                  f"{ring.get('dropped', 0)} dropped")
    if args.json:
        json.dump(doc, sys.stdout, indent=1)
        print()
        return 0
    print(header)
    if args.job is not None:
        sys.stdout.write(render_timeline(events, args.job,
                                         trace_events=trace_events))
    else:
        sys.stdout.write(render_summary(events))
    return 0


if __name__ == "__main__":
    sys.exit(main())
