"""One served job's execution: polisher + per-job observability.

A session is what the scheduler's worker runs for one admitted job:
it builds a fresh polisher from the job spec (warm state — jit
caches, shelved exports, calibration — is process-wide, so the fresh
instance pays no compile cost on a warm server), polishes, and
assembles the response: the exact FASTA bytes the one-shot CLI would
have written plus a ``--metrics-json``-style report from the job's
own child registry.

Per-job namespacing of process-wide counters: the AOT-shelf counters
(``aot_shelf_hit/miss/fallback``) and the server's prewarm counter
live in the GLOBAL registry (shelf state is per process — that is
the point of a warm server).  So a job-level report does not
accumulate every previous job's contacts, the session snapshots
those counters around the polish and records the DELTA into the
job's registry locally (no parent propagation): a second job on a
warm server reports ``aot_shelf_miss == 0`` even though the process
total keeps job 1's cold misses — the warm-start assertion
tests/test_serve.py pins.  With several jobs in flight the deltas
can attribute a concurrent job's contact to this job (counters are
process-wide); first-contact shelf semantics make that a one-time,
cold-window-only ambiguity.

Crash containment: any exception inside the polish is caught and
returned as a structured ``job_failed`` error; the polisher (and its
thread pool) is closed either way, and nothing the job touched can
poison the queue or the warm engines.
"""

from __future__ import annotations

import base64

from racon_tpu import obs
from racon_tpu.obs import REGISTRY
from racon_tpu.obs import decision as obs_decision
from racon_tpu.obs import flight as obs_flight
from racon_tpu.obs import trace as obs_trace

#: global counters the session re-reports per job as deltas
_PROCESS_COUNTERS = ("aot_shelf_hit", "aot_shelf_miss",
                     "aot_shelf_fallback", "serve_prewarm_runs")

#: job-spec option defaults — exactly the one-shot CLI's
#: (racon_tpu/cli.py parse_args), so an option the client omits
#: resolves the same way the CLI would
OPTION_DEFAULTS = {
    "type": "kC", "window_length": 500, "quality_threshold": 10.0,
    "error_threshold": 0.3, "trim": True, "match": 3, "mismatch": -5,
    "gap": -4, "threads": 1, "drop_unpolished": True,
    "tpu_poa_batches": 0, "tpu_banded_alignment": False,
    "tpu_aligner_batches": 0,
}


def _resolve_options(spec: dict) -> dict:
    opts = dict(OPTION_DEFAULTS)
    for key in OPTION_DEFAULTS:
        if key in spec:
            opts[key] = spec[key]
    return opts


def _shard_of(spec: dict):
    """r20 scatter: ``spec["shard"] = [index, count]`` marks this job
    as one target shard of a scattered mega-job — the polisher owns
    only ``target_slice(n_targets, count, index)`` and emits only
    those targets.  The scheduler validated the shape at admission
    (racon_tpu/serve/scheduler.py); a malformed value that slipped
    past (hand-rolled client) fails the job, not the server."""
    shard = spec.get("shard")
    if shard is None:
        return None
    index, count = int(shard[0]), int(shard[1])
    if not 0 <= index < count:
        raise ValueError(f"bad shard spec: {shard!r}")
    return (index, count)


def _wire_durability(polisher, job) -> None:
    """r17: connect the polisher's three durability hooks to the
    job's journal/recovery state (all no-ops when the journal is
    off):

    * calibration pin — split rates come from the job's ADMISSION
      epoch snapshot, not the live calibration file, so a resumed
      job computes the same device/CPU split the interrupted run
      did even if calibration moved on disk in between;
    * resume windows — megabatch checkpoints replayed from a dead
      incarnation's journal, adopted like speculative results
      (byte-for-byte what that incarnation committed);
    * checkpoint callback — each committed megabatch demux appends
      one checkpoint record.  Best-effort: a full disk degrades
      durability (counted in ``serve_journal_errors``), never fails
      the job that just committed.
    """
    calib = getattr(job, "calib", None)
    if isinstance(calib, dict) and isinstance(calib.get("data"),
                                              dict):
        polisher._calib_pin = calib["data"]
    resume = getattr(job, "resume", None)
    if isinstance(resume, dict):
        windows = {}
        for k, v in (resume.get("windows") or {}).items():
            try:
                cons = base64.b64decode(v[0]) if v[0] else None
                windows[int(k)] = (cons, bool(v[1]))
            except (ValueError, TypeError, IndexError):
                continue   # torn checkpoint entry: recompute it
        if windows:
            polisher._resume_windows = windows
    journal = getattr(job, "journal", None)
    if journal is None or not getattr(job, "job_key", None):
        return

    def _checkpoint(entries):
        enc = {
            str(i): [(base64.b64encode(cons).decode("ascii")
                      if cons is not None else None), bool(ok)]
            for i, cons, ok in entries}
        try:
            journal.append("checkpoint", job=job.id,
                           job_key=job.job_key, windows=enc)
        except OSError:
            REGISTRY.add("serve_journal_errors")
        obs_flight.FLIGHT.record(
            "checkpoint", job=job.id, tenant=job.tenant,
            trace_id=job.trace_id, n_windows=len(entries))

    polisher._checkpoint_cb = _checkpoint


def run_job(job) -> dict:
    """Execute one admitted job; returns the response frame body."""
    from racon_tpu.core.polisher import JobCanceledError, PolisherType
    from racon_tpu.obs import provenance

    spec = job.spec
    opts = _resolve_options(spec)
    base = {k: REGISTRY.value(k) for k in _PROCESS_COUNTERS}
    t0 = obs.now()
    polisher = None
    try:
        with obs.span("serve.job", cat="serve",
                      args={"job": job.id,
                            "priority": job.priority}):
            # r24: a spec may omit overlaps (internal mapping) and
            # carry a rounds count; both run through the multi-round
            # driver — rounds == 1 with an overlaps file is exactly
            # the classic single-round pipeline
            rounds = spec.get("rounds")
            rounds = (rounds if isinstance(rounds, int)
                      and not isinstance(rounds, bool)
                      and rounds >= 1 else 1)
            shard = _shard_of(spec)

            def _configure(p):
                # seam wiring, applied to EVERY round's polisher
                nonlocal polisher
                polisher = p
                # tag the polisher's device submissions with the
                # job's tenant so the process-wide executor can fuse
                # them with other tenants' batches and enforce
                # per-tenant fairness
                p._executor_tenant = getattr(job, "tenant", "default")
                if shard is not None:
                    p._target_shard = shard
                    # r21 staged inputs: the router's plan-time slice
                    # index rides the sub-job spec; the polisher
                    # validates it (path + file signature + shard)
                    # and self-builds or full-parses on any mismatch.
                    # Only meaningful with a parsed overlaps file.
                    if isinstance(spec.get("stage"), dict) \
                            and spec.get("overlaps") is not None:
                        p._stage_hint = spec["stage"]
                # r21 rebalancing: the scheduler's cancel flag (set
                # by the router's `cancel` op when a replacement
                # attempt superseded this shard) is polled between
                # committed units — cancel-after-checkpoint by
                # construction
                cancel = getattr(job, "cancel_requested", None)
                if cancel is not None:
                    p._cancel_check = cancel.is_set
                # r17 checkpoints key windows by id within ONE
                # pipeline pass; multi-round jobs would collide ids
                # across rounds, so durability wires single-round
                # jobs only
                if rounds == 1:
                    _wire_durability(p, job)

            from racon_tpu.overlap import rounds as overlap_rounds
            polished, polisher = overlap_rounds.polish_rounds(
                spec["sequences"], spec.get("overlaps"),
                spec["targets"], PolisherType[opts["type"]],
                opts["window_length"], opts["quality_threshold"],
                opts["error_threshold"], opts["trim"], opts["match"],
                opts["mismatch"], opts["gap"], opts["threads"],
                rounds=rounds,
                drop_unpolished=opts["drop_unpolished"],
                tpu_poa_batches=opts["tpu_poa_batches"],
                tpu_banded_alignment=opts["tpu_banded_alignment"],
                tpu_aligner_batches=opts["tpu_aligner_batches"],
                configure=_configure)
        fasta = b"".join(b">" + s.name.encode() + b"\n" + s.data
                         + b"\n" for s in polished)
    except JobCanceledError:
        # r21: a superseded straggler stopping at its poll site.
        # Distinct from job_failed so the router's gather can tell
        # "this shard yielded to its replacement" from a real error;
        # everything checkpointed before the stop stays journaled.
        if polisher is not None:
            polisher.close()
        REGISTRY.add("serve_jobs_canceled")
        obs_flight.FLIGHT.record("job_canceled", job=job.id,
                                 tenant=job.tenant,
                                 trace_id=job.trace_id)
        return {"ok": False,
                "error": {"code": "job_canceled",
                          "reason": "job canceled by the serve tier "
                                    "(superseded by a rebalanced "
                                    "attempt)"}}
    except Exception as exc:
        # containment boundary: InvalidInputError / parser errors are
        # the expected bad-job shapes, but ANY failure must release
        # the polisher and leave the server serving
        if polisher is not None:
            polisher.close()
        REGISTRY.add("serve_jobs_failed")
        obs_trace.TRACER.add_instant(
            "serve.job_failed", cat="serve",
            args={"job": job.id, "type": type(exc).__name__})
        # the traceback goes to the flight ring (bounded), not the
        # response frame — a post-mortem reads it from the dump or
        # the `flight` op
        obs_flight.FLIGHT.record_exception("error", exc, job=job.id)
        return {"ok": False,
                "error": {"code": "job_failed",
                          "type": type(exc).__name__,
                          "reason": str(exc)}}

    wall = obs.now() - t0
    m = polisher.metrics
    # decision-plane rollup (r16): one job-tagged event carrying the
    # job's stage walls so `racon-tpu explain --job N` can render the
    # cost waterfall straight from the decision ring (the worker runs
    # under the job context, so job/tenant/trace tags are automatic)
    obs_decision.DECISIONS.record(
        "job_stages", wall_s=round(wall, 6),
        stage_walls={k: round(v, 6) for k, v in
                     getattr(polisher, "stage_walls", {}).items()},
        split_mode=getattr(polisher, "poa_split_detail",
                           {}).get("mode"))
    # per-job namespaced process counters: local writes only, so the
    # process totals (and every other job's registry) stay untouched
    for name in _PROCESS_COUNTERS:
        m.set_local(name, REGISTRY.value(name) - base[name])
    m.set_local("job_wall_s", round(wall, 6))
    report = provenance.metrics_doc(
        run_registry=m,
        details={
            "stage_walls": {k: round(v, 6) for k, v in
                            getattr(polisher, "stage_walls",
                                    {}).items()},
            "poa_split_detail": getattr(polisher, "poa_split_detail",
                                        {}),
            "shard": list(shard) if shard is not None else None,
            "rounds": getattr(polisher, "rounds_report", []),
        },
        probe=False)
    polisher.close()
    REGISTRY.add("serve_jobs_completed")
    REGISTRY.add("serve_busy_s", wall)
    return {
        "ok": True,
        "job_id": job.id,
        "n_sequences": fasta.count(b">"),
        "wall_s": round(wall, 6),
        "estimate": job.estimate,
        "fasta_b64": base64.b64encode(fasta).decode("ascii"),
        "report": report,
    }
