"""``racon-tpu explain``: per-job cost waterfall + calibration
health, from a live daemon or a ``--metrics-json`` run report.

The r16 decision plane records WHY the admission/ladder machinery did
what it did (racon_tpu/obs/decision.py) and HOW far its predictions
drifted from measured walls (racon_tpu/obs/calhealth.py).  This
subcommand is the single reader: given a job id it renders the job's
cost waterfall — stage walls as the share of the job wall, the
headline predicted-vs-measured ratio, and the per-stage drift table
with advisory "recalibration recommended" flags::

    job 17 (tenantA) — predicted 4.10s vs measured 4.52s (ratio 1.10)
      stage             wall     share
      poa              2.21s  #################         49%
      align_band       1.13s  #########                 25%
      ...
    calibration health (band 0.50..2.00)
      stage          n     ewma    p50      p99
      poa           12     1.07    1.05     1.31
      align_wfa      4     2.41    2.38     2.60   DRIFT
      ! align_wfa: predicted/actual drift outside band —
        recalibration recommended (RACON_TPU_RECALIBRATE=1)

Sources:

* ``--socket PATH`` — queries a running daemon's ``explain`` op
  (calhealth summary + decision ring stats/counts/events in one
  frame).
* ``--metrics-json FILE`` — reads a run report written by
  ``--metrics-json`` (one-shot or submit); drift is recomputed from
  the report's ``run`` registry snapshot, the waterfall from its
  ``details.stage_walls``.

Read-only; decision records feed only this view, never control flow.
"""

from __future__ import annotations

import argparse
import json
import sys

#: waterfall bar width in characters at 100% share
_BAR = 34


def _fmt_s(v) -> str:
    v = float(v)
    if v >= 3600:
        return f"{v / 3600:.1f}h"
    if v >= 60:
        return f"{v / 60:.1f}m"
    if v >= 1:
        return f"{v:.2f}s"
    return f"{v * 1000:.0f}ms"


def job_events(events, job: int) -> list:
    """Decision events belonging to ``job``, in (time, seq) order."""
    job = int(job)
    sel = [ev for ev in events
           if ev.get("job") == job or job in ev.get("jobs", ())]
    sel.sort(key=lambda ev: (ev.get("t", 0.0), ev.get("seq", 0)))
    return sel


def render_waterfall(stage_walls: dict, total_s=None) -> str:
    """Stage walls -> the share-bar table (pure; tests golden it)."""
    walls = {k: float(v) for k, v in (stage_walls or {}).items()
             if float(v) > 0.0}
    if not walls:
        return "  (no stage walls recorded)\n"
    denom = float(total_s) if total_s else sum(walls.values())
    denom = max(denom, 1e-9)
    lines = ["  stage             wall      share"]
    for name, w in sorted(walls.items(), key=lambda kv: -kv[1]):
        share = w / denom
        bar = "#" * max(1, round(share * _BAR))
        lines.append(f"  {name:<16s} {_fmt_s(w):>7s}  "
                     f"{bar:<{_BAR}s} {share * 100:3.0f}%")
    other = denom - sum(walls.values())
    if total_s and other > 0.05 * denom:
        lines.append(f"  {'(other)':<16s} {_fmt_s(other):>7s}  "
                     f"{'':<{_BAR}s} {other / denom * 100:3.0f}%")
    return "\n".join(lines) + "\n"


def render_drift(cal: dict) -> str:
    """Calhealth summary -> the drift table + advisories (pure)."""
    cal = cal or {}
    stages = cal.get("stages") or {}
    lo, hi = (cal.get("band") or (0.5, 2.0))[:2]
    lines = [f"calibration health (predicted vs actual, band "
             f"{lo:.2f}..{hi:.2f})"]
    seen = False
    drifted = []
    lines.append("  stage              n     ewma      p50      p99")
    for name in sorted(stages):
        s = stages[name] or {}
        if not s.get("n"):
            continue
        seen = True
        ew = s.get("ewma")
        flag = "   DRIFT" if s.get("drift") else ""
        if s.get("drift") and ew is not None:
            drifted.append((name, ew))
        ew_txt = "-" if ew is None else f"{ew:.3f}"
        lines.append(
            f"  {name:<16s} {s['n']:>4d}  {ew_txt:>7s}  "
            f"{s.get('p50', 0.0):>7.3f}  {s.get('p99', 0.0):>7.3f}"
            f"{flag}")
    if not seen:
        return ("calibration health: no predicted-vs-actual samples "
                "recorded yet\n")
    for name, ew in drifted:
        direction = "slower" if ew is not None and ew > 1.0 \
            else "faster"
        lines.append(
            f"  ! {name}: measured walls {direction} than predicted "
            f"(ewma {ew:.2f} outside {lo:.2f}..{hi:.2f}) — "
            f"recalibration recommended (RACON_TPU_RECALIBRATE=1)")
    return "\n".join(lines) + "\n"


def render_counts(counts: dict) -> str:
    counts = counts or {}
    if not counts:
        return ""
    body = "  ".join(f"{k}={counts[k]}" for k in sorted(counts))
    return f"decision events: {body}\n"


def render_job(doc: dict, job: int) -> str:
    """One ``explain`` frame + a job id -> the per-job view (pure)."""
    events = doc.get("events", [])
    sel = job_events(events, job)
    lines = []
    # the rollups the session/scheduler record per job: job_stages
    # carries the stage walls, job_wall the admission-priced headline
    stages_ev = next((ev for ev in reversed(sel)
                      if ev.get("kind") == "job_stages"), None)
    wall_ev = next((ev for ev in reversed(sel)
                    if ev.get("kind") == "job_wall"), None)
    if stages_ev is None and wall_ev is None:
        lines.append(f"job {job}: no decision records in this source "
                     f"(evicted from the ring, or never seen here)")
        lines.append("")
        lines.append(render_drift(doc.get("calhealth")).rstrip("\n"))
        return "\n".join(lines) + "\n"
    tenant = next((ev["tenant"] for ev in sel if ev.get("tenant")),
                  "default")
    head = f"job {job} ({tenant})"
    if wall_ev is not None:
        head += (f" — predicted {_fmt_s(wall_ev.get('predicted_s', 0))}"
                 f" vs measured {_fmt_s(wall_ev.get('measured_s', 0))}"
                 f" (ratio {wall_ev.get('ratio', 0):.2f})")
    elif stages_ev is not None and "wall_s" in stages_ev:
        head += f" — wall {_fmt_s(stages_ev['wall_s'])}"
    lines.append(head)
    if stages_ev is not None:
        mode = stages_ev.get("split_mode")
        if mode:
            lines.append(f"  poa split mode: {mode}")
        lines.append(render_waterfall(
            stages_ev.get("stage_walls"),
            total_s=stages_ev.get("wall_s")).rstrip("\n"))
    kinds: dict = {}
    for ev in sel:
        k = ev.get("kind", "?")
        kinds[k] = kinds.get(k, 0) + 1
    c = render_counts(kinds)
    if c:
        lines.append(c.rstrip("\n"))
    lines.append("")
    lines.append(render_drift(doc.get("calhealth")).rstrip("\n"))
    return "\n".join(lines) + "\n"


def render_overview(doc: dict) -> str:
    """No ``--job``: ring stats, per-kind counts, drift table."""
    ring = doc.get("ring") or {}
    lines = [f"decision ring @ pid {doc.get('pid')}: "
             f"{ring.get('size', 0)}/{ring.get('capacity', 0)} "
             f"event(s), {ring.get('dropped', 0)} dropped"
             + ("" if ring.get("enabled", True)
                else "  [RECORDING OFF]")]
    c = render_counts(doc.get("counts"))
    if c:
        lines.append(c.rstrip("\n"))
    # r18: the result-cache block rides the explain frame — hit ratio
    # and bytes-resident explain why a warm daemon's measured walls
    # undercut the (discounted) admission predictions
    ca = doc.get("cache") or {}
    if ca.get("enabled"):
        total = ca.get("hits", 0) + ca.get("misses", 0)
        lines.append(
            f"result cache: hit {ca.get('hit_ratio', 0.0) * 100:.0f}% "
            f"({ca.get('hits', 0)}/{total})  "
            f"{ca.get('bytes', 0) / (1 << 20):.1f} MB resident  "
            f"{ca.get('entries', 0)} entries  "
            f"{ca.get('fills', 0)} fills  {ca.get('evicts', 0)} evicted")
    lines.append("")
    lines.append(render_drift(doc.get("calhealth")).rstrip("\n"))
    return "\n".join(lines) + "\n"


def _doc_from_report(path: str) -> dict:
    """A ``--metrics-json`` run report -> an explain-shaped doc: the
    drift summary is recomputed from the report's run registry
    snapshot, the waterfall rides as a synthetic ``job_stages``."""
    from racon_tpu.obs import calhealth

    with open(path) as f:
        report = json.load(f)
    snap = report.get("run") or report.get("process") or {}
    details = report.get("details") or {}
    doc = {"ok": True, "pid": None, "ring": {},
           "counts": {}, "events": [],
           "calhealth": calhealth.summary(snap)}
    walls = details.get("stage_walls")
    if walls:
        gauges = (snap.get("gauges") or {})
        wall = gauges.get("job_wall_s") or sum(
            float(v) for v in walls.values())
        doc["events"] = [{"kind": "job_stages", "job": 0,
                          "wall_s": wall, "stage_walls": walls,
                          "split_mode": (details.get(
                              "poa_split_detail") or {}).get("mode")}]
    return doc


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="racon-tpu explain",
        description="Render the decision plane: a served job's cost "
        "waterfall (stage walls, decision counts) and the per-stage "
        "predicted-vs-actual calibration-health table, from a live "
        "daemon's explain op or a --metrics-json run report.")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--socket",
                     help="unix-domain socket of a live daemon")
    src.add_argument("--metrics-json", metavar="FILE",
                     help="run report written by --metrics-json "
                     "(one-shot CLI or submit)")
    p.add_argument("--job", type=int, default=None,
                   help="job id to render (omit for the ring "
                   "overview + drift table; with --metrics-json the "
                   "report IS the job)")
    p.add_argument("--last", type=int, default=0,
                   help="with --socket and no --job: only the newest "
                   "N decision events")
    p.add_argument("--json", action="store_true",
                   help="print the raw explain document instead of "
                   "the rendered view")
    return p


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    if args.socket:
        from racon_tpu.serve import client
        try:
            doc = client.explain(args.socket, job=args.job,
                                 last=args.last)
        except client.ServeError as exc:
            print(f"[racon_tpu::explain] error: {exc}",
                  file=sys.stderr)
            return 1
        if not doc.get("ok"):
            print(f"[racon_tpu::explain] error: {doc.get('error')}",
                  file=sys.stderr)
            return 1
    else:
        try:
            doc = _doc_from_report(args.metrics_json)
        except (OSError, ValueError) as exc:
            print(f"[racon_tpu::explain] error: {exc}",
                  file=sys.stderr)
            return 1
    if args.json:
        json.dump(doc, sys.stdout, indent=1)
        print()
        return 0
    if args.metrics_json and args.job is None and doc["events"]:
        # a run report describes exactly one run: render it as the job
        sys.stdout.write(render_job(doc, 0))
    elif args.job is not None:
        sys.stdout.write(render_job(doc, args.job))
    else:
        sys.stdout.write(render_overview(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
