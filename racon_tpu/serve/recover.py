"""Journal replay: turn a dead daemon's record into live work (r17).

:func:`replay` folds a scanned journal (racon_tpu/serve/journal.py)
into a recovery plan; the restarting daemon
(racon_tpu/serve/server.py) then

* preloads every TERMINAL job's outcome into the scheduler's
  idempotence index, so a client that lost its connection in the
  crash and retries ``submit --job-key`` gets the recorded result
  (or the journaled error) instead of a re-run;
* requeues every INTERRUPTED job — admitted but neither ``done`` nor
  ``error`` — through the NORMAL admission path
  (``JobScheduler.submit``), carrying its original priority, tenant,
  trace id, calibration-epoch pin and the union of its journaled
  megabatch checkpoints, so the resumed run skips committed windows
  and still emits byte-identical FASTA;
* jobs whose requeue is rejected (inputs deleted since admission,
  queue shrunk below the backlog) are journaled ``error`` /
  ``job_failed`` so the failure is terminal and auditable rather
  than silently dropped.

Records merge ACROSS daemon incarnations by ``job_key`` (every
journaled job has one — client-supplied or daemon-minted), with
later records winning per window: a job that survived two crashes
resumes with everything any incarnation committed.
"""

from __future__ import annotations


def replay(records) -> dict:
    """Fold journal records into a recovery plan::

        {"completed":   {job_key: result_frame_body},
         "interrupted": [{"job_key", "spec", "priority", "tenant",
                          "trace_id", "calib",
                          "windows": {ordinal: [cons_b64|None, ok]},
                          "started": bool, "job", "pid"}, ...],
         "stats": {"records", "jobs", "completed", "failed",
                   "interrupted", "checkpoint_windows"}}

    ``completed`` holds terminal outcomes (success AND journaled
    errors — both answer a duplicate submit without a re-run).
    ``interrupted`` preserves journal admission order, so requeue
    order matches the dead daemon's queue order.
    """
    jobs: dict = {}        # job_key -> folded state
    order: list = []       # admission order of keys
    # journal records carry (pid, job) — unique per incarnation --
    # and admit maps that pair to the job_key every later record of
    # the same incarnation is folded under
    key_of: dict = {}      # (pid, job) -> job_key
    n_jobs = 0

    for rec in records:
        kind = rec.get("kind")
        pid, jid = rec.get("pid"), rec.get("job")
        if kind == "admit":
            key = rec.get("job_key") or f"auto-{pid}-{jid}"
            key_of[(pid, jid)] = key
            st = jobs.get(key)
            if st is None:
                n_jobs += 1
                st = {"job_key": key, "windows": {},
                      "started": False, "terminal": None,
                      "result": None}
                jobs[key] = st
                order.append(key)
            # latest admit wins for the job description (a requeued
            # job's spec is identical; its calib pin must be the
            # ORIGINAL epoch, which the requeue admit carries along)
            st.update({
                "spec": rec.get("spec"),
                "priority": rec.get("priority", 0),
                "tenant": rec.get("tenant"),
                "trace_id": rec.get("trace_id"),
                "calib": rec.get("calib"),
                "job": jid, "pid": pid,
            })
            continue
        key = rec.get("job_key") or key_of.get((pid, jid))
        st = jobs.get(key)
        if st is None:
            continue   # header/recovery markers, or a torn admit
        if kind == "start":
            st["started"] = True
        elif kind == "checkpoint":
            for ordinal, payload in (rec.get("windows")
                                     or {}).items():
                st["windows"][str(ordinal)] = payload
        elif kind == "done":
            st["terminal"] = "done"
            st["result"] = rec.get("result")
        elif kind == "error":
            st["terminal"] = "error"
            st["result"] = {"ok": False,
                            "error": rec.get("error")
                            or {"code": "job_failed",
                                "reason": "journaled failure"}}

    completed = {}
    interrupted = []
    n_ckpt = 0
    for key in order:
        st = jobs[key]
        if st["terminal"] is not None:
            if st["result"] is not None:
                completed[key] = st["result"]
            continue
        n_ckpt += len(st["windows"])
        interrupted.append({
            "job_key": key,
            "spec": st.get("spec"),
            "priority": st.get("priority", 0),
            "tenant": st.get("tenant"),
            "trace_id": st.get("trace_id"),
            "calib": st.get("calib"),
            "windows": st["windows"],
            "started": st["started"],
            "job": st.get("job"), "pid": st.get("pid"),
        })
    n_failed = sum(1 for key in order
                   if jobs[key]["terminal"] == "error")
    return {
        "completed": completed,
        "interrupted": interrupted,
        "stats": {
            "records": len(records),
            "jobs": n_jobs,
            "completed": len(completed) - n_failed,
            "failed": n_failed,
            "interrupted": len(interrupted),
            "checkpoint_windows": n_ckpt,
        },
    }


def requeue(scheduler, plan, journal=None, flight=None) -> dict:
    """Push a plan's interrupted jobs back through the scheduler's
    normal admission path.  Returns ``{"requeued": n, "failed": n}``.

    Rejected requeues (missing inputs, shrunken queue) become
    terminal: the error is journaled and preloaded into the
    idempotence index so a keyed retry sees ``job_failed`` with the
    reason instead of hanging on a job that will never run."""
    from racon_tpu.serve.scheduler import RejectError

    out = {"requeued": 0, "failed": 0}
    for item in plan["interrupted"]:
        spec = item.get("spec")
        if not isinstance(spec, dict):
            err = {"code": "job_failed",
                   "reason": "journal admit record carries no "
                             "job spec (torn write?)"}
            result = {"ok": False, "error": err}
            scheduler.preload_completed({item["job_key"]: result})
            if journal is not None:
                journal.append("error", job_key=item["job_key"],
                               error=err)
            out["failed"] += 1
            continue
        try:
            job = scheduler.submit(
                spec, priority=int(item.get("priority") or 0),
                trace_context=item.get("trace_id"),
                job_key=item["job_key"],
                resume={"windows": item["windows"],
                        "calib": item.get("calib")},
                recovered_from=f"{item.get('pid')}:{item.get('job')}")
        except RejectError as exc:
            result = {"ok": False, "error": exc.error}
            scheduler.preload_completed({item["job_key"]: result})
            if journal is not None:
                journal.append("error", job_key=item["job_key"],
                               error=exc.error)
            out["failed"] += 1
            continue
        if flight is not None:
            flight.record(
                "recover", job=job.id, tenant=job.tenant,
                trace_id=job.trace_id, job_key=item["job_key"],
                checkpoint_windows=len(item["windows"]),
                recovered_from=f"{item.get('pid')}:"
                               f"{item.get('job')}")
        out["requeued"] += 1
    return out
