"""Persistent polishing service (warm-kernel job server + client).

One-shot ``racon-tpu`` pays the full process setup on every run: the
jax import, the AOT-shelf loads, kernel tracing/compiles and the
calibration read all happen again, then ``os._exit`` throws the warm
state away.  The reference amortizes device setup across one run via
per-GPU batch queues (src/cuda/cudabatch.cpp); this package amortizes
it across RUNS — the warm-weights/request-queue shape of an inference
server, applied to polishing:

* :mod:`racon_tpu.serve.server` — a long-lived daemon on a
  unix-domain socket (``racon-tpu serve --socket PATH``).  It prewarms
  the AOT shelf once at startup and keeps the process-wide warm state
  (jit caches, shelved exports, calibration) resident, so job N>=2
  pays zero compile/prewarm cost.
* :mod:`racon_tpu.serve.scheduler` — a bounded priority queue with
  admission control priced by :func:`racon_tpu.utils.calibrate.
  predict_walls`, structured backpressure rejects, and a worker pool
  that runs up to ``RACON_TPU_SERVE_JOBS`` polishes concurrently;
  their megabatches interleave through the shared device FIFO.
* :mod:`racon_tpu.serve.session` — one job's execution: a fresh
  polisher wired to a per-job child metrics registry, per-job
  namespaced AOT-shelf counters, and a ``--metrics-json``-style
  report embedded in the response.
* :mod:`racon_tpu.serve.client` — the blocking client and the
  ``racon-tpu submit`` / ``racon-tpu status`` subcommands.
* :mod:`racon_tpu.serve.fleet` — the r15 fleet telemetry plane: a
  concurrent multi-daemon ``metrics`` scraper with per-target
  staleness, the exact cross-daemon registry merge
  (racon_tpu/obs/aggregate.py), multiplexed ``watch`` streams, and
  the ``racon-tpu metrics`` one-shot CLI; ``racon-tpu top --fleet``
  renders the merged view.
* :mod:`racon_tpu.serve.router` — the r19 fault-tolerance tier: a
  ``racon-tpu route`` daemon fronting N serve daemons with
  health-probed cost-ranked placement, spillover on backpressure,
  per-backend circuit breakers, draining-aware + crash failover
  (exactly-once via idempotent job keys and the r17 journal dedup),
  and an optional TCP listener speaking the same framed protocol.

Determinism contract: a served job's FASTA is byte-identical to a
standalone CLI run with the same inputs/flags/threads/devices — the
server freezes calibration stores at startup (``RACON_TPU_CALIB_
FREEZE``) so job N's measured rates can never steer job N+1's split,
and each job gets its own polisher whose engine assignment stays a
pure function of its input (pinned by tests/test_serve.py, including
with two jobs in flight concurrently).
"""

from racon_tpu.serve.protocol import (ProtocolError, recv_frame,  # noqa: F401
                                      send_frame)
